"""Autotune subsystem tests: pure policies, shared hysteresis gating,
the journal-tap signal fold, controller end-to-end through a real
journal, decision replay (including tamper detection), and torn-read
hammers on the locked live-config paths the controller actuates.

The end-to-end tests use the same deterministic recipe as CI: a fake
monotonic clock (a mutable list cell) drives the controller, so
cooldown windows advance exactly when the test says they do.
"""

from __future__ import annotations

import json
import threading

import pytest

from specpride_tpu.autotune.controller import (
    Controller,
    ControllerThread,
    evaluate,
)
from specpride_tpu.autotune.policy import (
    BatchWindowPolicy,
    ElasticRangePolicy,
    FleetSparesPolicy,
    WorkerPolicy,
    parse_clamp,
    policy_from_params,
)
from specpride_tpu.autotune.replay import replay_journal
from specpride_tpu.autotune.signals import SignalState
from specpride_tpu.observability.journal import Journal, read_events
from specpride_tpu.serve.scheduler import AdmissionQueue, Quota

TRACE = "ab" * 16  # any 32-hex id satisfies the v4 trace envelope


# -- policies: pure decisions over (signal, current, params) ------------


class TestBatchWindowPolicy:
    def setup_method(self):
        self.p = BatchWindowPolicy(lo_ms=5.0, hi_ms=25.0, queue_hi=3)

    def test_widen_from_floor_on_queue_depth(self):
        got = self.p.decide({"queue_depth": 4}, 0.0)
        assert got is not None
        new, reason = got
        assert new == 5.0
        assert "queue depth 4" in reason

    def test_widen_doubles_and_clamps(self):
        assert self.p.decide({"queue_depth": 3}, 5.0)[0] == 10.0
        assert self.p.decide({"queue_depth": 9}, 20.0)[0] == 25.0

    def test_no_decision_below_queue_hi(self):
        assert self.p.decide({"queue_depth": 2}, 0.0) is None

    def test_zero_floor_seeds_first_widen(self):
        # lo=0 must not make "widen from the floor" a no-op forever
        p = BatchWindowPolicy(lo_ms=0.0, hi_ms=50.0, queue_hi=3)
        assert p.decide({"queue_depth": 4}, 0.0)[0] == 1.0
        assert p.decide({"queue_depth": 4}, 1.0)[0] == 2.0

    def test_no_decision_at_ceiling(self):
        assert self.p.decide({"queue_depth": 9}, 25.0) is None

    def test_shrink_on_idle_solo_dispatches(self):
        signal = {
            "queue_depth": 0,
            "jobs": {"n": 4},
            "batch": {"jobs_mean": 1.0},
        }
        new, reason = self.p.decide(signal, 20.0)
        assert new == 10.0
        assert "shrink" in reason

    def test_no_shrink_while_coalescing(self):
        signal = {
            "queue_depth": 0,
            "jobs": {"n": 4},
            "batch": {"jobs_mean": 3.0},  # window is earning its keep
        }
        assert self.p.decide(signal, 20.0) is None

    def test_no_shrink_without_recent_jobs(self):
        # an idle daemon is not evidence the window is too long
        assert self.p.decide({"queue_depth": 0}, 20.0) is None


class TestWorkerPolicy:
    def setup_method(self):
        self.p = WorkerPolicy(lo=1, hi=4, burn_hi=0.1, busy_lo=0.25,
                              min_slo_jobs=3)

    def test_unpark_on_slo_burn(self):
        signal = {"jobs": {"slo_jobs": 10, "slo_breaches": 3}}
        new, reason = self.p.decide(signal, 2)
        assert new == 3
        assert "unpark" in reason

    def test_burn_needs_min_slo_jobs(self):
        signal = {"jobs": {"slo_jobs": 2, "slo_breaches": 2}}
        assert self.p.decide(signal, 2) is None

    def test_unpark_clamped_at_pool_size(self):
        signal = {"jobs": {"slo_jobs": 10, "slo_breaches": 9}}
        assert self.p.decide(signal, 4) is None

    def test_park_on_low_busy_fraction(self):
        signal = {
            "window_s": 30.0,
            "queue_depth": 0,
            "jobs": {"n": 3, "busy_s": 1.0, "slo_breaches": 0},
        }
        new, reason = self.p.decide(signal, 4)
        assert new == 3
        assert "park" in reason

    def test_never_parks_below_floor(self):
        signal = {
            "window_s": 30.0,
            "queue_depth": 0,
            "jobs": {"n": 3, "busy_s": 0.0, "slo_breaches": 0},
        }
        assert self.p.decide(signal, 1) is None


class TestElasticRangePolicy:
    def setup_method(self):
        self.p = ElasticRangePolicy(lo=8, hi=512, target_s=30.0,
                                    chunk_hint=8)

    def test_no_decision_on_stale_evidence(self):
        assert self.p.decide({}, 64) is None
        assert self.p.decide({"heartbeats": {"ranks": 2}}, 64) is None

    def test_sizes_split_to_target_chunk_aligned(self):
        # 8-cluster chunks take 4s -> 0.5s/cluster -> 60 clusters for
        # 30s, aligned down to 56 (a multiple of the chunk hint)
        signal = {"heartbeats": {"ranks": 2, "chunk_s_mean": 4.0}}
        new, reason = self.p.decide(signal, 64)
        assert new == 56
        assert new % 8 == 0
        assert "30.0s" in reason

    def test_clamps_to_bounds(self):
        fast = {"heartbeats": {"ranks": 1, "chunk_s_mean": 0.001}}
        assert self.p.decide(fast, 64)[0] == 512
        slow = {"heartbeats": {"ranks": 1, "chunk_s_mean": 400.0}}
        assert self.p.decide(slow, 64)[0] == 8

    def test_no_op_suppressed(self):
        signal = {"heartbeats": {"ranks": 2, "chunk_s_mean": 4.0}}
        assert self.p.decide(signal, 56) is None


class TestFleetSparesPolicy:
    def setup_method(self):
        self.p = FleetSparesPolicy(lo=0, hi=2, pressure_hi=1)

    def test_add_spare_on_steal_pressure(self):
        signal = {"store": {"steal_proposals": 2, "stale_ranks": 0}}
        new, reason = self.p.decide(signal, 0)
        assert new == 1
        assert "steal pressure" in reason

    def test_add_spare_on_stale_rank(self):
        signal = {"store": {"steal_proposals": 0, "stale_ranks": 1}}
        assert self.p.decide(signal, 1) == (
            2, "steal pressure (proposals=0, stale_ranks=1): "
               "add a warm spare")

    def test_clamped_at_hi(self):
        signal = {"store": {"steal_proposals": 5, "stale_ranks": 2}}
        assert self.p.decide(signal, 2) is None

    def test_retire_on_quiet_window(self):
        signal = {"store": {"steal_proposals": 0, "stale_ranks": 0}}
        assert self.p.decide(signal, 2)[0] == 1
        assert self.p.decide(signal, 0) is None  # already at floor


class TestPolicyPlumbing:
    def test_parse_clamp(self):
        assert parse_clamp("5:25") == (5.0, 25.0)
        assert parse_clamp("0:0") == (0.0, 0.0)
        for bad in ("5", "hi:25", "25:5", "-1:5"):
            with pytest.raises(ValueError):
                parse_clamp(bad)

    def test_policy_from_params_roundtrip(self):
        src = BatchWindowPolicy(lo_ms=2.0, hi_ms=9.0, queue_hi=7)
        rebuilt = policy_from_params("batch_window_ms", dict(src.params))
        assert rebuilt.params == src.params

    def test_policy_from_params_ignores_unknown_keys(self):
        p = policy_from_params("workers", {"hi": 8, "from_the_future": 1})
        assert p.params["hi"] == 8
        assert "from_the_future" not in p.params

    def test_policy_from_params_unknown_knob_raises(self):
        with pytest.raises(ValueError, match="unknown autotune knob"):
            policy_from_params("warp_factor", {})


# -- the shared gate live ticks and replay both run ---------------------


class TestEvaluateGating:
    def setup_method(self):
        self.p = BatchWindowPolicy(lo_ms=5.0, hi_ms=25.0, queue_hi=3,
                                   cooldown_s=2.0, deadband=0.2)
        self.busy = {"now": 100.0, "queue_depth": 4}

    def test_passes_policy_decision_through(self):
        assert evaluate(self.p, self.busy, 5.0, None) == (
            10.0, "queue depth 4 >= 3: widen window to coalesce "
                  "queued jobs")

    def test_cooldown_suppresses(self):
        assert evaluate(self.p, self.busy, 5.0, 99.0) is None
        # exactly at the cooldown boundary the knob is free again
        assert evaluate(self.p, self.busy, 5.0, 98.0) is not None

    def test_deadband_suppresses_small_relative_moves(self):
        p = FleetSparesPolicy(lo=0, hi=100)
        p.params["deadband"] = 0.2
        quiet = {"now": 0.0,
                 "store": {"steal_proposals": 0, "stale_ranks": 0}}
        # 50 -> 49 is a 2% move: inside the deadband, suppressed
        assert evaluate(p, quiet, 50, None) is None
        # 2 -> 1 is a 50% move: clears it
        assert evaluate(p, quiet, 2, None) == (
            1, "no steal pressure in window: retire a warm spare")

    def test_policy_none_is_none(self):
        assert evaluate(self.p, {"now": 0.0, "queue_depth": 0},
                        5.0, None) is None


# -- signal fold --------------------------------------------------------


class TestSignalFold:
    def test_queue_depth_is_a_counter_fold(self):
        s = SignalState(30.0)
        for _ in range(3):
            s.observe({"event": "job_queued", "mono": 1.0})
        s.observe({"event": "job_start", "mono": 2.0})
        assert s.snapshot(5.0)["queue_depth"] == 2
        s.observe({"event": "job_start", "mono": 3.0})
        s.observe({"event": "job_start", "mono": 4.0})
        s.observe({"event": "job_start", "mono": 5.0})  # never negative
        assert s.snapshot(6.0)["queue_depth"] == 0

    def test_job_window_sections_and_pruning(self):
        s = SignalState(10.0)
        s.observe({"event": "job_done", "mono": 1.0, "wall_s": 4.0,
                   "queue_wait_s": 1.0, "status": "done",
                   "slo_ok": False, "trace_id": "aa" * 16})
        s.observe({"event": "job_done", "mono": 8.0, "wall_s": 2.0,
                   "queue_wait_s": 0.0, "status": "done",
                   "slo_ok": True, "trace_id": "bb" * 16})
        snap = s.snapshot(9.0)
        jobs = snap["jobs"]
        assert jobs["n"] == 2 and jobs["done"] == 2
        assert jobs["wall_mean_s"] == 3.0 and jobs["busy_s"] == 6.0
        assert jobs["slo_jobs"] == 2 and jobs["slo_breaches"] == 1
        assert jobs["age_s"] == 1.0
        # the first job ages out of the window; the section re-derives
        snap = s.snapshot(12.0)
        assert snap["jobs"]["n"] == 1
        assert snap["jobs"]["slo_breaches"] == 0

    def test_batch_and_heartbeat_sections(self):
        s = SignalState(30.0)
        s.observe({"event": "batch_dispatch", "mono": 1.0, "n_jobs": 3,
                   "window_wait_s": 0.01, "bucket_occupancy_frac": 0.5,
                   "trace_ids": ["cc" * 16]})
        s.observe({"event": "batch_dispatch", "mono": 2.0, "n_jobs": 1,
                   "window_wait_s": 0.03, "bucket_occupancy_frac": 0.9})
        s.observe({"event": "heartbeat", "mono": 3.0, "rank": 0,
                   "chunk_s": 4.0})
        s.observe({"event": "heartbeat", "mono": 4.0, "rank": 1,
                   "chunk_s": 2.0})
        snap = s.snapshot(5.0)
        assert snap["batch"]["n"] == 2
        assert snap["batch"]["jobs_mean"] == 2.0
        assert snap["batch"]["solo"] == 1
        assert snap["batch"]["occupancy_mean"] == 0.7
        hb = snap["heartbeats"]
        assert hb["ranks"] == 2 and hb["stale_ranks"] == 0
        assert hb["chunk_s_mean"] == 3.0 and hb["chunk_s_max"] == 4.0
        # a rank whose beat falls out of the window goes stale, and its
        # wall stops feeding the mean
        snap = s.snapshot(33.5)
        assert snap["heartbeats"]["stale_ranks"] == 1
        assert snap["heartbeats"]["chunk_s_mean"] == 2.0

    def test_recent_traces_distinct_newest_first_order(self):
        s = SignalState(30.0)
        for i, tid in enumerate(["t1", "t2", "t1", "t3"]):
            s.observe({"event": "job_done", "mono": float(i),
                       "wall_s": 0.1, "status": "done",
                       "trace_id": tid})
        assert s.recent_traces() == ["t2", "t1", "t3"]
        assert s.recent_traces(n=2) == ["t1", "t3"]

    def test_unknown_and_autotune_events_ignored(self):
        s = SignalState(30.0)
        s.observe({"event": "autotune", "mono": 1.0, "knob": "workers"})
        s.observe({"event": "from_the_future", "mono": 1.0})
        s.observe("not a dict")
        s.observe({"event": "job_queued"})  # no mono: dropped
        assert s.snapshot(2.0)["queue_depth"] == 0


# -- controller end-to-end over a real journal --------------------------


def _drive(journal_path, mode):
    """The deterministic widen/widen/shrink scenario: returns the
    journal path, the final knob value, and the decisions list."""
    clock = [100.0]
    value = [0.0]
    j = Journal(journal_path)
    ctl = Controller(j, mode=mode, window_s=30.0,
                     clock=lambda: clock[0])
    ctl.register(
        BatchWindowPolicy(lo_ms=5.0, hi_ms=25.0, queue_hi=3,
                          cooldown_s=2.0),
        get=lambda: value[0],
        set=lambda v: value.__setitem__(0, v),
    )
    decisions = []
    for i in range(4):
        j.emit("job_queued", job_id=i, client="t", trace_id=TRACE)
    decisions += ctl.tick()             # widen 0 -> 5 (queue depth 4)
    clock[0] += 10.0                    # clear the cooldown
    decisions += ctl.tick()             # widen again (5 -> 10)
    for i in range(4):
        j.emit("job_start", job_id=i, trace_id=TRACE)
        j.emit("job_done", job_id=i, status="done", wall_s=0.01,
               queue_wait_s=0.0, trace_id=TRACE)
    j.emit("batch_dispatch", batch_id=1, jobs=[3], n_jobs=1,
           n_clusters=1, window_wait_s=0.0, status="shared",
           trace_ids=[TRACE])
    clock[0] += 10.0
    decisions += ctl.tick()             # shrink (queue idle, solo)
    clock[0] += 10.0
    decisions += ctl.tick()             # steady state: no decision
    ctl.close()
    j.close()
    return value[0], decisions


class TestControllerEndToEnd:
    def test_on_mode_acts_and_journals_evidence(self, tmp_path):
        path = str(tmp_path / "serve.jsonl")
        final, decisions = _drive(path, "on")
        assert [d["new"] for d in decisions] == [5.0, 10.0, 5.0]
        assert all(d["acted"] for d in decisions)
        assert final == 5.0  # the knob cell was actually moved
        events, violations = read_events(path)
        assert violations == []
        at = [e for e in events if e["event"] == "autotune"]
        assert len(at) == 3
        for e in at:
            # the evidence contract: every decision self-describes
            assert e["knob"] == "batch_window_ms"
            assert e["mode"] == "on"
            assert e["reason"]
            assert e["signal"]["now"] == e["clock"]
            assert e["params"]["lo_ms"] == 5.0
        # the shrink decision cites the window's traces as evidence
        # (the widen ticks ran before any job_done/batch_dispatch
        # carried a trace into the fold)
        assert at[2]["trace_ids"] == [TRACE]
        # decision lines land in fold order: the widen tick's evidence
        # shows the queue the worker events built BEFORE it
        assert at[0]["signal"]["queue_depth"] == 4

    def test_observe_mode_journals_but_never_acts(self, tmp_path):
        path = str(tmp_path / "serve.jsonl")
        final, decisions = _drive(path, "observe")
        assert final == 0.0  # knob cell untouched
        # no actuation means the knob never leaves the floor, so the
        # shrink branch can't fire: two would-be widens, nothing acted
        assert [(d["new"], d["acted"]) for d in decisions] == [
            (5.0, False), (5.0, False),
        ]

    def test_cooldown_blocks_back_to_back_ticks(self, tmp_path):
        clock = [100.0]
        value = [0.0]
        j = Journal(str(tmp_path / "j.jsonl"))
        ctl = Controller(j, mode="on", clock=lambda: clock[0])
        ctl.register(
            BatchWindowPolicy(lo_ms=5.0, hi_ms=25.0, cooldown_s=2.0),
            get=lambda: value[0],
            set=lambda v: value.__setitem__(0, v),
        )
        for i in range(4):
            j.emit("job_queued", job_id=i, client="t", trace_id=TRACE)
        assert len(ctl.tick()) == 1
        clock[0] += 0.5  # inside the cooldown
        assert ctl.tick() == []
        ctl.close()
        j.close()

    def test_raising_policy_degrades_to_no_tuning(self, tmp_path):
        j = Journal(str(tmp_path / "j.jsonl"))
        ctl = Controller(j, mode="on", clock=lambda: 1.0)

        class Exploding:
            knob = "workers"
            params = {}

            def decide(self, signal, current):
                raise RuntimeError("boom")

        ctl.register(Exploding(), get=lambda: 1, set=lambda v: None)
        assert ctl.tick() == []  # logged and skipped, never raised
        ctl.close()
        j.close()

    def test_controller_thread_ticks_and_stops(self, tmp_path):
        j = Journal(str(tmp_path / "j.jsonl"))
        ctl = Controller(j, mode="on")
        ctl.register(
            FleetSparesPolicy(lo=0, hi=2, cooldown_s=0.0),
            get=lambda: 0, set=lambda v: None,
        )
        ticked = threading.Event()
        orig = ctl.tick

        def _tick(extras=None):
            out = orig(extras)
            ticked.set()
            return out

        ctl.tick = _tick
        t = ControllerThread(ctl, interval=0.05).start()
        assert ticked.wait(timeout=10.0)
        t.stop()
        j.close()
        assert ctl.journal._taps == ()  # stop() detached the tap


# -- replay: the determinism audit --------------------------------------


class TestReplay:
    def test_replay_reproduces_every_decision(self, tmp_path):
        path = str(tmp_path / "serve.jsonl")
        _drive(path, "on")
        res = replay_journal(path)
        assert res["ok"], res
        assert res["decisions"] == 3
        assert res["reproduced"] == 3
        assert res["acted"] == 3
        assert res["streams"] == 1
        assert res["refold_mismatches"] == []

    def test_replay_detects_tampered_decision(self, tmp_path):
        path = str(tmp_path / "serve.jsonl")
        _drive(path, "on")
        lines = [json.loads(ln) for ln in open(path, encoding="utf-8")]
        # rewrite the FIRST decision's outcome: the policy no longer
        # derives it from the recorded signal
        for rec in lines:
            if rec.get("event") == "autotune":
                rec["new"] = 17.0
                break
        with open(path, "w", encoding="utf-8") as fh:
            for rec in lines:
                fh.write(json.dumps(rec) + "\n")
        res = replay_journal(path)
        assert not res["ok"]
        assert any("replay new=5.0" in m for m in res["mismatches"])

    def test_replay_detects_acted_mode_inconsistency(self, tmp_path):
        path = str(tmp_path / "serve.jsonl")
        _drive(path, "observe")
        lines = [json.loads(ln) for ln in open(path, encoding="utf-8")]
        for rec in lines:
            if rec.get("event") == "autotune":
                rec["acted"] = True  # observe mode must never act
                break
        with open(path, "w", encoding="utf-8") as fh:
            for rec in lines:
                fh.write(json.dumps(rec) + "\n")
        res = replay_journal(path)
        assert not res["ok"]
        assert any("inconsistent with mode" in m
                   for m in res["mismatches"])

    def test_replay_skips_refold_for_store_extras(self, tmp_path):
        # fleet snapshots carry a store-derived view replay cannot
        # re-derive from the journal: the decision check still runs
        path = str(tmp_path / "fleet.jsonl")
        j = Journal(path)
        ctl = Controller(j, mode="on", clock=lambda: 50.0)
        spares = [0]
        ctl.register(
            FleetSparesPolicy(lo=0, hi=2),
            get=lambda: spares[0],
            set=lambda v: spares.__setitem__(0, v),
        )
        out = ctl.tick(extras={"steal_proposals": 2, "stale_ranks": 0})
        assert len(out) == 1 and out[0]["signal"]["store"]
        ctl.close()
        j.close()
        res = replay_journal(path)
        assert res["ok"], res
        assert res["decisions"] == 1 and res["reproduced"] == 1


# -- torn-read hammers on the locked live-config paths ------------------


class TestLiveValueConcurrency:
    """The controller moves knobs while hot paths read them; the locked
    accessors must never expose a torn or out-of-set value (pattern:
    test_exporter.py TestRegistryConcurrency)."""

    N_ITER = 2000

    def test_daemon_live_knobs_under_mutation_hammer(self, tmp_path):
        from specpride_tpu.serve.daemon import ServeDaemon

        d = ServeDaemon(
            str(tmp_path / "s.sock"),
            compile_cache=str(tmp_path / "cache"),
            workers=4,
        )
        valid_windows = {0.005 * k for k in range(8)}
        stop = threading.Event()
        errors: list = []

        def _mutate():
            try:
                for i in range(self.N_ITER):
                    d.batch_window = 0.005 * (i % 8)
                    d.active_workers = (i % 4) + 1
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        def _read():
            try:
                while not stop.is_set():
                    w = d.batch_window
                    assert w in valid_windows, w
                    n = d.active_workers
                    assert 1 <= n <= 4, n
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        writers = [threading.Thread(target=_mutate) for _ in range(2)]
        readers = [threading.Thread(target=_read) for _ in range(2)]
        for t in readers:
            t.start()
        for t in writers:
            t.start()
        for t in writers:
            t.join(timeout=60)
        stop.set()
        for t in readers:
            t.join(timeout=60)
        assert not errors, errors
        assert d.batch_window in valid_windows
        assert 1 <= d.active_workers <= 4

    def test_set_quotas_live_under_offer_pop_hammer(self):
        """Quota-table swaps racing offer/pop must never tear: every
        popped job is released, accounting lands exact, and a final
        table applies to every client atomically."""
        q = AdmissionQueue(capacity=64)
        tables = [
            {"*": Quota(1.0, None)},
            {"a": Quota(3.0, 8), "*": Quota(1.0, 4)},
            {"b": Quota(2.0, 2)},
        ]
        stop = threading.Event()
        errors: list = []
        popped = []
        pop_lock = threading.Lock()

        def _swap():
            try:
                for i in range(self.N_ITER):
                    q.set_quotas(tables[i % len(tables)])
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        def _offer(client):
            try:
                for i in range(200):
                    try:
                        q.offer(client, (client, i))
                    except Exception as e:
                        if "quota" not in str(e):
                            raise
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        def _pop():
            try:
                while True:
                    job = q.pop(timeout=0.05)
                    if job is None:
                        if stop.is_set():
                            return
                        continue
                    with pop_lock:
                        popped.append(job)
                    q.release(job)
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        swapper = threading.Thread(target=_swap)
        offerers = [threading.Thread(target=_offer, args=(c,))
                    for c in ("a", "b", "c")]
        poppers = [threading.Thread(target=_pop) for _ in range(2)]
        for t in poppers:
            t.start()
        swapper.start()
        for t in offerers:
            t.start()
        for t in offerers:
            t.join(timeout=60)
        swapper.join(timeout=60)
        # drain the tail, then stop the poppers
        deadline = 200
        while len(q) and deadline:
            deadline -= 1
            stop.wait(0.05)
        stop.set()
        for t in poppers:
            t.join(timeout=60)
        assert not errors, errors
        assert len(q) == 0
        assert len(popped) == len(set(popped))  # no job served twice
        # the last table swap fully applied: no half-resolved state
        q.set_quotas({"*": Quota(5.0, 7)})
        for st in q._states.values():
            assert st.quota == Quota(5.0, 7)
