"""JAX-free tests for the native compute kernels (cosine + medoid).

Deliberately imports no jax: ``make -C native tsan`` runs this module with
the ThreadSanitizer builds preloaded, and an instrumented process that
loads jax drowns in false positives from its uninstrumented runtime
threads.  The oracle (``backends.numpy_backend``) is pure numpy, so the
same parity checks run clean under TSan.
"""

import numpy as np
import pytest

from specpride_tpu.backends import numpy_backend as nb
from specpride_tpu.config import CosineConfig, MedoidConfig
from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.ops import cosine_native, medoid_native


def _clusters(rng, n=24, max_members=9):
    """Enough clusters that the worker pool actually runs multi-threaded
    (when cores exist) — the point of the TSan pass."""
    out = []
    for i in range(n):
        n_peaks = int(rng.integers(5, 120))
        skel = np.sort(rng.uniform(120.0, 1800.0, n_peaks))
        members = [
            Spectrum(
                mz=np.sort(skel + rng.normal(0, 0.003, n_peaks)),
                intensity=rng.uniform(1.0, 1e4, n_peaks),
                precursor_mz=500.0,
                precursor_charge=2,
                title=f"cluster-{i};mzspec:PXD1:r:scan:{i * 100 + m}",
            )
            for m in range(int(rng.integers(1, max_members)))
        ]
        out.append(Cluster(f"cluster-{i}", members))
    return out


def _flat_layout(clusters):
    mz, inten, spec_offsets, cso = [], [], [0], [0]
    for c in clusters:
        for s in c.members:
            mz.append(np.asarray(s.mz, np.float64))
            inten.append(np.asarray(s.intensity, np.float64))
            spec_offsets.append(spec_offsets[-1] + s.n_peaks)
        cso.append(cso[-1] + c.n_members)
    cat = lambda xs: (
        np.concatenate(xs) if xs else np.zeros(0, np.float64)
    )
    return (
        cat(mz), cat(inten),
        np.array(spec_offsets, np.int64), np.array(cso, np.int64),
    )


@pytest.fixture
def rng():
    return np.random.default_rng(11)


class TestNativeCosineStandalone:
    @pytest.fixture(autouse=True)
    def _need(self):
        if not cosine_native.available():
            pytest.skip("native cosine not built")

    def test_pair_cosines_match_oracle(self, rng):
        clusters = _clusters(rng)
        reps = nb.run_bin_mean(clusters)
        mem_mz, mem_int, spec_offsets, cso = _flat_layout(clusters)
        rep_offsets = np.zeros(len(reps) + 1, np.int64)
        np.cumsum([r.n_peaks for r in reps], out=rep_offsets[1:])
        cos = cosine_native.pair_cosines(
            np.concatenate([r.mz for r in reps]),
            np.concatenate([r.intensity for r in reps]),
            rep_offsets, mem_mz, mem_int, spec_offsets, cso,
            CosineConfig().mz_space,
        )
        k = 0
        for rep, c in zip(reps, clusters):
            for s in c.members:
                assert cos[k] == pytest.approx(
                    nb.binned_cosine(rep, s), rel=1e-12, abs=1e-14
                )
                k += 1


class TestNativeMedoidStandalone:
    @pytest.fixture(autouse=True)
    def _need(self):
        if not medoid_native.available():
            pytest.skip("native medoid not built")

    def test_shared_counts_match_oracle(self, rng):
        clusters = _clusters(rng)
        mem_mz, _, spec_offsets, cso = _flat_layout(clusters)
        bin_size = MedoidConfig().bin_size
        shared_flat, out_offsets = medoid_native.shared_bin_counts(
            mem_mz, spec_offsets, cso, bin_size
        )
        for ci, c in enumerate(clusters):
            m = c.n_members
            shared = shared_flat[
                out_offsets[ci] : out_offsets[ci + 1]
            ].reshape(m, m)
            for i in range(m):
                bi = np.unique(
                    (c.members[i].mz / bin_size).astype(np.int64)
                )
                assert shared[i, i] == bi.size
                for j in range(i + 1, m):
                    bj = np.unique(
                        (c.members[j].mz / bin_size).astype(np.int64)
                    )
                    expect = np.intersect1d(
                        bi, bj, assume_unique=True
                    ).size
                    assert shared[i, j] == expect == shared[j, i]

    def test_boundary_values(self):
        """One-decimal m/z on exact 0.1 Da grid edges must bin by true
        division (trunc(mz / bin_size)), as numpy does."""
        s1 = Spectrum(
            mz=np.array([100.1, 250.7, 999.9]),
            intensity=np.ones(3), precursor_mz=500.0, precursor_charge=2,
            title="c;u1",
        )
        s2 = Spectrum(
            mz=np.array([100.14, 250.72, 999.95]),
            intensity=np.ones(3), precursor_mz=500.0, precursor_charge=2,
            title="c;u2",
        )
        mem_mz, _, spec_offsets, cso = _flat_layout(
            [Cluster("c", [s1, s2])]
        )
        shared_flat, _ = medoid_native.shared_bin_counts(
            mem_mz, spec_offsets, cso, 0.1
        )
        shared = shared_flat.reshape(2, 2)
        b1 = np.unique((s1.mz / 0.1).astype(np.int64))
        b2 = np.unique((s2.mz / 0.1).astype(np.int64))
        assert shared[0, 1] == np.intersect1d(b1, b2).size
