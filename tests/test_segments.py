"""Unit tests for ops.segments — the scatter-free sorted-run reductions
every device kernel is built on (see the module docstring for why
``segment_sum`` was abandoned)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from specpride_tpu.ops import segments as sg


def make_runs(rng, n_runs, max_len, pad=0, sent=2**30):
    lens = rng.integers(1, max_len + 1, n_runs)
    keys = np.repeat(np.arange(n_runs, dtype=np.int64), lens)
    keys = np.concatenate([keys, np.full(pad, sent, dtype=np.int64)])
    vals = rng.uniform(0.5, 1e4, keys.size).astype(np.float32)
    return keys, vals, lens


@functools.partial(jax.jit, static_argnames=("rcap", "lcap"))
def _sums(keys, vals, rcap, lcap):
    starts = sg.run_starts(keys)
    (tot, cnt), endpos = sg.run_sums(
        starts, (vals, jnp.ones_like(vals)), rcap, lcap
    )
    return tot, cnt, endpos


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("pad", [0, 7, 64])
def test_run_sums_match_reduceat(seed, pad):
    rng = np.random.default_rng(seed)
    keys, vals, lens = make_runs(rng, n_runs=rng.integers(1, 200), max_len=17, pad=pad)
    lcap = 32
    rcap = int(lens.size + 2)  # + sentinel run + slack
    tot, cnt, endpos = _sums(jnp.asarray(keys), jnp.asarray(vals), rcap, lcap)
    tot, cnt, endpos = map(np.asarray, (tot, cnt, endpos))

    starts = np.concatenate([[True], keys[1:] != keys[:-1]])
    want = np.add.reduceat(vals.astype(np.float64), np.flatnonzero(starts))
    genuine = keys[endpos] != 2**30
    n_real = lens.size
    assert genuine[:n_real].all()
    assert not genuine[n_real:].any() or pad == 0
    np.testing.assert_allclose(tot[:n_real], want[:n_real], rtol=1e-5)
    np.testing.assert_array_equal(cnt[:n_real].astype(int), lens)


def test_precision_small_run_after_large_prefix():
    """The reason diff-of-global-cumsum was rejected: a tiny run following
    millions of large values must keep its own relative precision."""
    rng = np.random.default_rng(0)
    big = rng.uniform(1e3, 1e4, 2**17).astype(np.float32)
    keys = np.concatenate([
        np.repeat(np.arange(big.size // 8), 8), [10**7, 10**7]
    ]).astype(np.int64)
    vals = np.concatenate([big, [0.125, 0.25]]).astype(np.float32)
    tot, cnt, endpos = _sums(jnp.asarray(keys), jnp.asarray(vals),
                             rcap=big.size // 8 + 2, lcap=8)
    got = float(np.asarray(tot)[big.size // 8])
    assert got == pytest.approx(0.375, rel=1e-6)


def test_run_ids_and_broadcast():
    rng = np.random.default_rng(3)
    keys, vals, lens = make_runs(rng, n_runs=50, max_len=9, pad=5)
    starts = sg.run_starts(jnp.asarray(keys))
    ids = np.asarray(sg.run_ids(starts))
    want = np.cumsum(np.concatenate([[True], keys[1:] != keys[:-1]])) - 1
    np.testing.assert_array_equal(ids, want)

    # broadcast pattern: totals gathered back per element
    (tot,), _ = sg.run_sums(starts, (jnp.asarray(vals),),
                            rcap=int(want[-1] + 2), lcap=16)
    per_elem = np.asarray(tot)[ids]
    ref = np.add.reduceat(vals.astype(np.float64), np.flatnonzero(
        np.concatenate([[True], keys[1:] != keys[:-1]])))
    np.testing.assert_allclose(per_elem, ref[ids], rtol=1e-5)


def _pallas_seg_means(keys, vals):
    """seg_mean_pallas (interpret) run totals/means gathered at run
    ends, or None when pallas is unavailable (skip cleanly, as
    ``pallas_kernels.has_pallas`` does for the real device path)."""
    from specpride_tpu.ops import pallas_kernels as pk

    if pk.pl is None:
        return None
    n = keys.size
    pad = pk.pad_to_block(n) - n
    sent = np.int64(2**30)
    w = (keys != sent).astype(np.float32)
    cnt, mean = pk.seg_mean_pallas(
        np.pad(keys, (0, pad), constant_values=sent).astype(np.int32),
        np.pad(w, (0, pad)),
        np.pad(vals, (0, pad)),
        interpret=True,
    )
    return np.asarray(cnt)[:n], np.asarray(mean)[:n]


def test_run_length_exactly_lcap():
    """A real run of length EXACTLY lcap is the scan window's boundary
    case: log2(lcap) shift steps must cover the whole run (a one-off
    would window it like a sentinel tail).  Both the XLA chain and the
    fused Pallas kernel must agree with reduceat."""
    lcap = 16
    lens = [lcap, 1, lcap, 3]
    keys = np.repeat(np.arange(len(lens), dtype=np.int64), lens)
    rng = np.random.default_rng(9)
    vals = rng.uniform(0.5, 100.0, keys.size).astype(np.float32)
    tot, cnt, endpos = _sums(
        jnp.asarray(keys), jnp.asarray(vals), rcap=8, lcap=lcap
    )
    want = np.add.reduceat(
        vals.astype(np.float64),
        np.flatnonzero(np.concatenate([[True], keys[1:] != keys[:-1]])),
    )
    np.testing.assert_allclose(
        np.asarray(tot)[: len(lens)], want, rtol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(cnt)[: len(lens)].astype(int), lens
    )
    got = _pallas_seg_means(keys, vals)
    if got is not None:
        pcnt, pmean = got
        ends = np.cumsum(lens) - 1
        np.testing.assert_array_equal(pcnt[ends].astype(int), lens)
        np.testing.assert_allclose(
            pmean[ends], want / np.asarray(lens), rtol=1e-5
        )


def test_all_sentinel_padding_tail():
    """An input that is NOTHING but sentinel padding: the scan must not
    crash, every run slot must read back as sentinel-keyed, and the
    Pallas kernel must report zero counts/means throughout."""
    sent = np.int64(2**30)
    keys = np.full(64, sent)
    vals = np.ones(64, dtype=np.float32)
    tot, cnt, endpos = _sums(
        jnp.asarray(keys), jnp.asarray(vals), rcap=4, lcap=4
    )
    assert (keys[np.asarray(endpos)] == sent).all()
    got = _pallas_seg_means(keys, vals)
    if got is not None:
        pcnt, pmean = got
        assert (pcnt == 0).all() and (pmean == 0).all()


def test_single_element_runs():
    """Every run length 1 (fully distinct keys): prefix == value, count
    == 1, means == values — on both implementations."""
    n = 100
    keys = np.arange(n, dtype=np.int64)
    rng = np.random.default_rng(4)
    vals = rng.uniform(1.0, 50.0, n).astype(np.float32)
    tot, cnt, endpos = _sums(
        jnp.asarray(keys), jnp.asarray(vals), rcap=n + 2, lcap=4
    )
    np.testing.assert_allclose(np.asarray(tot)[:n], vals, rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(cnt)[:n].astype(int), np.ones(n, int)
    )
    np.testing.assert_array_equal(np.asarray(endpos)[:n], np.arange(n))
    got = _pallas_seg_means(keys, vals)
    if got is not None:
        pcnt, pmean = got
        np.testing.assert_array_equal(pcnt, np.ones(n, np.float32))
        np.testing.assert_allclose(pmean, vals, rtol=1e-6)


def test_runs_longer_than_lcap_are_windowed_not_crashing():
    """Sentinel tail runs exceed lcap by contract; values are garbage but
    the call must not fail and genuine runs stay exact."""
    keys = np.concatenate([[0, 0, 1], np.full(100, 2**30)]).astype(np.int64)
    vals = np.ones(keys.size, dtype=np.float32)
    tot, cnt, endpos = _sums(jnp.asarray(keys), jnp.asarray(vals),
                             rcap=4, lcap=2)
    tot = np.asarray(tot)
    assert tot[0] == 2.0 and tot[1] == 1.0
    assert np.asarray(keys)[np.asarray(endpos)[2]] == 2**30  # sentinel run
