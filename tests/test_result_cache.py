"""Content-addressed consensus result cache: digest canonicalization,
local-tier LRU/atomic-commit/quarantine semantics, the singleton
lifecycle, and the machine-checked byte-parity matrix — 3 methods x
cache {off, cold, warm} for one-shot runs, plus served, batched, and
2-rank elastic shared-tier runs, plus a concurrency hammer."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from specpride_tpu.cache import digest as cd
from specpride_tpu.cache import result_cache as rc
from specpride_tpu.cli import build_parser, main as cli_main
from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.observability.journal import read_events
from specpride_tpu.serve import client as sc
from specpride_tpu.serve.daemon import ServeDaemon

from conftest import make_cluster

METHODS = [
    ("bin-mean", "consensus"),
    ("gap-average", "consensus"),
    ("medoid", "select"),
]


@pytest.fixture(autouse=True)
def _fresh_singleton():
    rc.reset()
    yield
    rc.reset()


def _respell(s: Spectrum, perm) -> Spectrum:
    return Spectrum(
        mz=np.asarray(s.mz)[perm],
        intensity=np.asarray(s.intensity)[perm],
        precursor_mz=s.precursor_mz,
        precursor_charge=s.precursor_charge,
        rt=s.rt,
        title=s.title,
        extra=dict(s.extra),
    )


# -- digest canonicalization ---------------------------------------------


class TestDigest:
    def test_peak_order_invariant(self, rng):
        c = make_cluster(rng, "c-1", n_members=3, n_peaks=20)
        base = cd.cluster_digest(c)
        shuffled = Cluster(c.cluster_id, [
            _respell(s, rng.permutation(len(s.mz))) for s in c.members
        ])
        assert cd.cluster_digest(shuffled) == base

    def test_member_order_is_content(self, rng):
        """Float reduction order shows in the output bits, so reordered
        members are a DIFFERENT input, not the same one respelled."""
        c = make_cluster(rng, "c-1", n_members=3, n_peaks=10)
        flipped = Cluster(c.cluster_id, list(reversed(c.members)))
        assert cd.cluster_digest(flipped) != cd.cluster_digest(c)

    def test_titles_and_values_are_content(self, rng):
        c = make_cluster(rng, "c-1", n_members=2, n_peaks=10)
        base = cd.cluster_digest(c)
        retitled = Cluster(c.cluster_id, [
            Spectrum(
                mz=c.members[0].mz, intensity=c.members[0].intensity,
                precursor_mz=c.members[0].precursor_mz,
                precursor_charge=c.members[0].precursor_charge,
                rt=c.members[0].rt, title="other-title",
            ),
            c.members[1],
        ])
        assert cd.cluster_digest(retitled) != base
        bumped = Cluster(c.cluster_id, [
            _respell(c.members[0], np.arange(len(c.members[0].mz))),
            Spectrum(
                mz=c.members[1].mz,
                intensity=np.asarray(c.members[1].intensity) * 2.0,
                precursor_mz=c.members[1].precursor_mz,
                precursor_charge=c.members[1].precursor_charge,
                rt=c.members[1].rt, title=c.members[1].title,
            ),
        ])
        assert cd.cluster_digest(bumped) != base

    def test_result_key_splits_every_axis(self):
        base = cd.result_key("c", "bin-mean", "cfg", "f32", "rc1")
        assert cd.result_key("d", "bin-mean", "cfg", "f32", "rc1") != base
        assert cd.result_key("c", "medoid", "cfg", "f32", "rc1") != base
        assert cd.result_key("c", "bin-mean", "cfg2", "f32", "rc1") != base
        assert cd.result_key("c", "bin-mean", "cfg", "bf16", "rc1") != base
        assert cd.result_key("c", "bin-mean", "cfg", "f32", "rc2") != base

    def test_file_digest_is_content_only(self, tmp_path):
        a = tmp_path / "a.bin"
        b = tmp_path / "sub" / "b.bin"
        b.parent.mkdir()
        a.write_bytes(b"same bytes")
        b.write_bytes(b"same bytes")
        assert cd.file_digest(str(a)) == cd.file_digest(str(b))
        assert cd.file_digest(str(tmp_path / "missing")) is None


# -- local tier ----------------------------------------------------------


def _entry_for(rng, key, cid="c-e"):
    c = make_cluster(rng, cid, n_members=2, n_peaks=10)
    return c, rc.make_entry(key, c.members[0], c, 0.99)


class TestLocalTier:
    def test_roundtrip_and_decode(self, tmp_path, rng):
        tier = rc.LocalTier(str(tmp_path))
        key = "a" * 64
        c, entry = _entry_for(rng, key)
        tier.put(key, entry)
        got = tier.get(key)
        assert got is not None and got is not rc.CORRUPT
        rep = rc.decode_rep(got["rep"])
        np.testing.assert_array_equal(rep.mz, c.members[0].mz)
        np.testing.assert_array_equal(rep.intensity,
                                      c.members[0].intensity)
        assert rep.title == c.members[0].title
        assert tier.info()["entries"] == 1

    def test_tmp_debris_never_parses_as_entry(self, tmp_path, rng):
        """Atomic-commit crash sim: a killed writer leaves only private
        tmp files, which neither serve nor count nor survive a cap
        sweep as entries."""
        tier = rc.LocalTier(str(tmp_path))
        key = "b" * 64
        _, entry = _entry_for(rng, key)
        # a torn half-write the way mkstemp+replace would leave it
        debris = tmp_path / ".tmp-dead1234.part"
        debris.write_text(json.dumps(entry)[: 40])
        assert tier.get(key) is None
        assert tier.info()["entries"] == 0
        tier.put(key, entry)
        assert tier.get(key) is not rc.CORRUPT
        assert tier.info()["entries"] == 1  # debris still not counted

    def test_corrupt_entry_quarantined_as_miss(self, tmp_path, rng):
        tier = rc.LocalTier(str(tmp_path))
        key = "c" * 64
        _, entry = _entry_for(rng, key)
        tier.put(key, entry)
        path = tmp_path / (key + ".json")
        body = path.read_text()
        path.write_text(body.replace('"cosine":0.99', '"cosine":0.5'))
        assert tier.get(key) is rc.CORRUPT
        assert not path.exists(), "failed entry must move aside"
        assert (tmp_path / (key + ".json.corrupt")).exists(), \
            "quarantine keeps the evidence"
        assert tier.get(key) is None  # now a plain miss
        # a ResultCache reports it as a corrupt-counted miss
        cache = rc.ResultCache(rc.LocalTier(str(tmp_path)))
        tier.put(key, entry)
        (tmp_path / (key + ".json")).write_text("{not json")
        doc, tiername = cache.lookup(key)
        assert doc is None and tiername == "corrupt"

    def test_wrong_key_entry_is_corrupt(self, tmp_path, rng):
        """An entry filed under the wrong key (or a digest collision in
        a copied tier) must never be served for that key."""
        tier = rc.LocalTier(str(tmp_path))
        key = "d" * 64
        _, entry = _entry_for(rng, key)
        tier.put(key, entry)
        other = "e" * 64
        os.replace(tmp_path / (key + ".json"),
                   tmp_path / (other + ".json"))
        assert tier.get(other) is rc.CORRUPT

    def test_lru_bound_and_eviction_accounting(self, tmp_path, rng):
        tier = rc.LocalTier(str(tmp_path))
        keys = [ch * 64 for ch in "fghi"]
        entries = {k: _entry_for(rng, k, cid=f"c-{k[0]}")[1]
                   for k in keys}
        tier.put(keys[0], entries[keys[0]])
        size = os.path.getsize(tmp_path / (keys[0] + ".json"))
        tier.max_bytes = int(size * 2.5)  # room for two entries
        # pin recency explicitly: mtime IS the LRU axis
        for i, k in enumerate(keys[1:], 1):
            tier.put(k, entries[k])
            os.utime(tmp_path / (k + ".json"), (i, i))
        os.utime(tmp_path / (keys[0] + ".json"), (0, 0))
        tier.put(keys[0], entries[keys[0]])  # re-put touches: newest
        info = tier.info()
        assert info["bytes"] <= tier.max_bytes
        assert info["entries"] == 2
        assert tier.evictions == 2 and tier.evicted_bytes > 0
        assert tier.get(keys[1]) is None, "oldest mtime evicts first"
        assert tier.get(keys[0]) not in (None, rc.CORRUPT)


# -- singleton lifecycle + runtime gating --------------------------------


class TestRuntime:
    def test_parse_spec(self):
        assert rc.parse_spec("/tmp/x") == ("/tmp/x", rc.DEFAULT_MAX_MB)
        assert rc.parse_spec("/tmp/x:64") == ("/tmp/x", 64)

    def test_configure_active_reset(self, tmp_path):
        assert rc.active() is None
        cache = rc.configure(str(tmp_path / "t"))
        assert rc.active() is cache
        rc.configure(None)
        assert rc.active() is None

    def test_runtime_for_gates(self, tmp_path):
        tier = str(tmp_path / "t")

        def _args(extra):
            return build_parser().parse_args(
                ["consensus", "in.mgf", "out.mgf"] + extra
            )

        cached = _args(["--method", "bin-mean", "--result-cache", tier])
        assert rc.runtime_for(cached, "evaluate") is None
        best = _args(["--method", "bin-mean", "--result-cache", tier])
        best.method = "best"  # per-job score table: never cacheable
        assert rc.runtime_for(best, "consensus") is None

        class BatchView:
            is_batch_view = True

        assert rc.runtime_for(cached, "consensus",
                              backend=BatchView()) is None
        bare = _args(["--method", "bin-mean"])
        assert rc.runtime_for(bare, "consensus") is None, \
            "no flag, no singleton: cache off"
        ctx = rc.runtime_for(cached, "consensus")
        assert ctx is not None and ctx.method == "bin-mean"

    def test_qc_config_splits_keys(self, tmp_path, rng):
        """QC-on and QC-off runs key differently, so an entry cached
        without a cosine can never satisfy a QC-on lookup."""
        tier = str(tmp_path / "t")
        base = ["consensus", "in.mgf", "out.mgf", "--method", "bin-mean",
                "--result-cache", tier]
        ctx_off = rc.runtime_for(
            build_parser().parse_args(base), "consensus"
        )
        ctx_on = rc.runtime_for(
            build_parser().parse_args(
                base + ["--qc-report", str(tmp_path / "qc.json")]
            ),
            "consensus",
        )
        c = make_cluster(rng, "c-1", n_members=2, n_peaks=10)
        assert ctx_off.key_of(c) != ctx_on.key_of(c)


# -- shared tier ---------------------------------------------------------


class TestSharedTier:
    def test_fs_store_roundtrip_and_backfill(self, tmp_path, rng):
        from specpride_tpu.parallel.store import FsStore

        shared = rc.SharedTier(FsStore(str(tmp_path / "store")))
        key = "a" * 64
        c, entry = _entry_for(rng, key)
        shared.put(key, entry)
        assert shared.get(key)["cluster_id"] == c.cluster_id
        # a fresh local tier backfills from shared on lookup
        cache = rc.ResultCache(rc.LocalTier(str(tmp_path / "l")), shared)
        doc, tier = cache.lookup(key)
        assert tier == "shared" and doc is not None
        doc2, tier2 = cache.lookup(key)
        assert tier2 == "local", "shared hit must backfill local"

    def test_shared_corrupt_is_miss(self, tmp_path, rng):
        from specpride_tpu.parallel.store import FsStore

        store = FsStore(str(tmp_path / "store"))
        shared = rc.SharedTier(store)
        key = "b" * 64
        _, entry = _entry_for(rng, key)
        entry = dict(entry, seal="0" * 64)  # bad seal
        store.put_new("rc-" + key, entry)
        assert shared.get(key) is rc.CORRUPT
        cache = rc.ResultCache(rc.LocalTier(str(tmp_path / "l")), shared)
        assert cache.lookup(key) == (None, "corrupt")


# -- one-shot CLI parity matrix ------------------------------------------


N_CLUSTERS = 6


@pytest.fixture(scope="module")
def workload(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("rc_wl")
    rng = np.random.default_rng(424)
    clusters = [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=20)
        for i in range(N_CLUSTERS)
    ]
    src = tmp / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], src)
    return str(src)


def _rc_event(journal_path):
    events, violations = read_events(journal_path)
    assert not violations, violations
    got = [e for e in events if e["event"] == "result_cache"]
    return events, (got[-1] if got else None)


class TestOneShotParity:
    @pytest.mark.parametrize("method,command", METHODS)
    def test_off_cold_warm_bytes_and_qc(
        self, tmp_path, workload, method, command
    ):
        tier = tmp_path / "tier"
        outs, qcs = {}, {}
        for mode in ("off", "cold", "warm"):
            out = tmp_path / f"{mode}.mgf"
            qc = tmp_path / f"{mode}.qc.json"
            jp = tmp_path / f"{mode}.jsonl"
            argv = [
                command, workload, str(out), "--method", method,
                "--qc-report", str(qc), "--journal", str(jp),
            ]
            if mode != "off":
                argv += ["--result-cache", str(tier)]
            assert cli_main(argv) == 0
            outs[mode], qcs[mode] = out.read_bytes(), qc.read_bytes()
        assert outs["cold"] == outs["off"], method
        assert outs["warm"] == outs["off"], method
        assert qcs["cold"] == qcs["off"] and qcs["warm"] == qcs["off"]
        # cache-off is parity by ABSENCE: no result_cache event at all
        off_events, off_rc = _rc_event(str(tmp_path / "off.jsonl"))
        assert off_rc is None
        _, cold = _rc_event(str(tmp_path / "cold.jsonl"))
        assert cold["misses"] == N_CLUSTERS and cold["hits"] == 0
        assert cold["populated"] == N_CLUSTERS
        events, warm = _rc_event(str(tmp_path / "warm.jsonl"))
        assert warm["hits"] == N_CLUSTERS and warm["misses"] == 0
        assert warm["bytes_saved"] > 0
        end = [e for e in events if e["event"] == "run_end"][-1]
        assert end["counters"]["result_cache_hits"] == N_CLUSTERS

    def test_corrupt_tier_recomputes_identical(self, tmp_path, workload):
        """Garbling every cached entry must turn the warm run into a
        cold one — counted corrupt, recomputed, byte-identical."""
        tier = tmp_path / "tier"
        base = tmp_path / "base.mgf"
        assert cli_main([
            "consensus", workload, str(base), "--method", "bin-mean",
            "--result-cache", str(tier),
        ]) == 0
        for name in os.listdir(tier):
            if name.endswith(".json"):
                path = tier / name
                path.write_text(path.read_text()[:-20] + "garbage")
        out = tmp_path / "after.mgf"
        jp = tmp_path / "after.jsonl"
        assert cli_main([
            "consensus", workload, str(out), "--method", "bin-mean",
            "--result-cache", str(tier), "--journal", str(jp),
        ]) == 0
        assert out.read_bytes() == base.read_bytes()
        _, ev = _rc_event(str(jp))
        assert ev["hits"] == 0 and ev["corrupt"] == N_CLUSTERS
        quarantined = [n for n in os.listdir(tier)
                       if n.endswith(".corrupt")]
        assert len(quarantined) == N_CLUSTERS

    def test_stats_renders_result_cache_line(
        self, tmp_path, workload, capsys
    ):
        tier = tmp_path / "tier"
        jp = tmp_path / "warm.jsonl"
        for p in ("one.mgf", "two.mgf"):
            assert cli_main([
                "consensus", workload, str(tmp_path / p),
                "--method", "bin-mean", "--result-cache", str(tier),
                "--journal", str(jp),
            ]) == 0
        capsys.readouterr()
        assert cli_main(["stats", str(jp)]) == 0
        text = capsys.readouterr().out
        assert "result-cache:" in text
        assert f"hits={N_CLUSTERS}" in text and "hit_rate=100.0%" in text
        agg = tmp_path / "agg.json"
        assert cli_main(["stats", str(jp), "--json", str(agg)]) == 0
        doc = json.loads(agg.read_text())
        rc_doc = doc["runs"][-1]["result_cache"]
        assert rc_doc["hits"] == N_CLUSTERS and rc_doc["hit_rate"] == 1.0


# -- served + batched ----------------------------------------------------


def _start(daemon):
    t = threading.Thread(target=daemon.run, daemon=True)
    t.start()
    assert sc.wait_for_socket(daemon.socket_path, timeout=120)
    return t


def _stop(daemon, thread):
    daemon.drain()
    thread.join(timeout=60)
    assert not thread.is_alive()


class TestServed:
    def test_repeat_served_job_hits_and_matches_cli(
        self, tmp_path, workload
    ):
        cli_out = tmp_path / "cli.mgf"
        assert cli_main([
            "consensus", workload, str(cli_out), "--method", "bin-mean",
        ]) == 0
        d = ServeDaemon(
            str(tmp_path / "serve.sock"),
            compile_cache=str(tmp_path / "cc"),
            journal_path=str(tmp_path / "serve.jsonl"),
            result_cache=str(tmp_path / "tier") + ":64",
        )
        t = _start(d)
        try:
            assert rc.active() is not None, "boot owns the singleton"
            terms = []
            for tag in ("first", "second"):
                out = tmp_path / f"{tag}.mgf"
                term = sc.submit_wait(d.socket_path, [
                    "consensus", workload, str(out), "--method",
                    "bin-mean", "--journal", str(tmp_path / f"{tag}.jsonl"),
                ])
                assert term["status"] == "done", term
                assert out.read_bytes() == cli_out.read_bytes()
                terms.append(term)
            # hit attribution on the daemon's job_done events
            events, violations = read_events(d.journal_path)
            assert not violations, violations
            done = [e for e in events if e["event"] == "job_done"]
            assert done[0].get("result_cache_hits", 0) == 0
            assert done[1].get("result_cache_hits") == N_CLUSTERS
            # live status carries tier occupancy + process totals
            status = d.status()
            assert status["result_cache"]["entries"] == N_CLUSTERS
            assert status["result_cache"]["hits"] >= N_CLUSTERS
        finally:
            _stop(d, t)
        assert rc.active() is None, "drain clears the singleton"

    def test_job_carrying_result_cache_flag_rejected(
        self, tmp_path, workload
    ):
        d = ServeDaemon(
            str(tmp_path / "serve.sock"),
            compile_cache=str(tmp_path / "cc"),
            journal_path=str(tmp_path / "serve.jsonl"),
        )
        t = _start(d)
        try:
            term = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(tmp_path / "o.mgf"),
                "--method", "bin-mean",
                "--result-cache", str(tmp_path / "job_tier"),
            ])
            assert term["status"] == "rejected", term
            assert "--result-cache" in term["reason"]
        finally:
            _stop(d, t)

    def test_batched_members_share_cache(self, tmp_path, workload):
        """Two concurrent tenants coalesced into one shared dispatch:
        outputs byte-identical to solo CLI, and a SECOND batched pair
        is served from the cache (leader-side consult)."""
        cli_out = tmp_path / "cli.mgf"
        assert cli_main([
            "consensus", workload, str(cli_out), "--method", "bin-mean",
        ]) == 0
        d = ServeDaemon(
            str(tmp_path / "serve.sock"),
            compile_cache=str(tmp_path / "cc"),
            journal_path=str(tmp_path / "serve.jsonl"),
            workers=1,
            batch_window=0.25,
            result_cache=str(tmp_path / "tier"),
        )
        d._gate.clear()  # admit both jobs before any executes
        t = _start(d)
        try:
            for round_no in range(2):
                terms = {}

                def _submit(tag):
                    out = tmp_path / f"r{round_no}_{tag}.mgf"
                    terms[tag] = (sc.submit_wait(d.socket_path, [
                        "consensus", workload, str(out), "--method",
                        "bin-mean",
                    ], client=f"tenant-{tag}"), out)

                threads = [
                    threading.Thread(target=_submit, args=(tag,))
                    for tag in ("a", "b")
                ]
                for th in threads:
                    th.start()
                deadline = time.time() + 30
                while len(d.queue) < 2 and time.time() < deadline:
                    time.sleep(0.01)
                d._gate.set()
                for th in threads:
                    th.join(timeout=120)
                for tag, (term, out) in terms.items():
                    assert term["status"] == "done", (tag, term)
                    assert out.read_bytes() == cli_out.read_bytes()
                d._gate.clear()
            totals = rc.totals()
            assert totals["hits"] >= N_CLUSTERS, totals
            assert totals["populated"] >= N_CLUSTERS, totals
            events, _ = read_events(d.journal_path)
            assert any(e["event"] == "batch_dispatch" and
                       e.get("status") == "shared" for e in events)
        finally:
            d._gate.set()
            _stop(d, t)


# -- elastic 2-rank shared tier ------------------------------------------


def _elastic_env():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_NUM_CPU_DEVICES", None)
    return env

def _elastic_rank_argv(src, out, coord, rank, tier, store, journal):
    return [
        sys.executable, "-m", "specpride_tpu",
        "consensus", str(src), str(out), "--method", "bin-mean",
        "--elastic", str(coord), "--process-id", str(rank),
        "--elastic-range", "2", "--checkpoint-every", "1",
        "--qc-report", f"{out}.qc.json", "--journal", str(journal),
        "--result-cache", str(tier), "--result-store", str(store),
    ]


@pytest.mark.slow
def test_elastic_two_ranks_share_store(tmp_path, workload):
    """Cold 2-rank elastic run populates the shared tier; a warm rerun
    with FRESH local tiers and a fresh coordinator serves every cluster
    from the store — merged bytes + QC identical to serial both times."""
    serial = tmp_path / "serial.mgf"
    assert cli_main([
        "consensus", workload, str(serial), "--method", "bin-mean",
        "--qc-report", str(tmp_path / "serial.qc.json"),
    ]) == 0
    store = tmp_path / "store"
    env = _elastic_env()

    def _run_pair(phase):
        out = tmp_path / f"{phase}.mgf"
        coord = tmp_path / f"coord_{phase}"
        journal = tmp_path / f"{phase}.jsonl"
        procs = [
            subprocess.Popen(
                _elastic_rank_argv(
                    workload, out, coord, rank,
                    tmp_path / f"tier_{phase}_{rank}", store, journal,
                ),
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
            )
            for rank in (0, 1)
        ]
        for p in procs:
            _, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()
        assert cli_main([
            "merge-parts", str(out), "--elastic", str(coord),
            "--qc-report", f"{out}.qc.json",
        ]) == 0
        snaps = []
        for rank in (0, 1):
            events, violations = read_events(
                f"{journal}.part{rank:05d}"
            )
            assert not violations, violations
            snaps += [e for e in events if e["event"] == "result_cache"]
        return out, snaps

    cold_out, cold = _run_pair("cold")
    assert cold_out.read_bytes() == serial.read_bytes()
    assert (tmp_path / "cold.mgf.qc.json").read_bytes() == \
        (tmp_path / "serial.qc.json").read_bytes()
    assert sum(e["populated"] for e in cold) >= N_CLUSTERS
    warm_out, warm = _run_pair("warm")
    assert warm_out.read_bytes() == serial.read_bytes()
    assert (tmp_path / "warm.mgf.qc.json").read_bytes() == \
        (tmp_path / "serial.qc.json").read_bytes()
    # fresh local tiers: every warm hit came over the shared store
    assert sum(e["hits"] for e in warm) == N_CLUSTERS
    assert sum(e.get("shared_hits", 0) for e in warm) == N_CLUSTERS


# -- ingest-cache content fallback ---------------------------------------


class TestIngestContentFallback:
    def test_copied_input_content_hits(self, tmp_path):
        from specpride_tpu.serve import ingest_cache as ic

        ic.clear()
        a = tmp_path / "a.mgf"
        a.write_text("BEGIN IONS\nTITLE=x\nEND IONS\n")
        ic.put(str(a), ["parsed"], n_spectra=1, n_peaks=2)
        entry, kind = ic.lookup(str(a))
        assert kind == "stat" and entry == (["parsed"], 1, 2)
        # the same bytes under a new path: content fallback serves the
        # resident parse and re-keys it
        b = tmp_path / "copy.mgf"
        b.write_bytes(a.read_bytes())
        entry, kind = ic.lookup(str(b))
        assert kind == "content" and entry == (["parsed"], 1, 2)
        assert ic.info()["content_hits"] == 1
        entry, kind = ic.lookup(str(b))
        assert kind == "stat", "content hit re-keys to a stat hit"
        # different bytes stay a miss
        c = tmp_path / "other.mgf"
        c.write_text("BEGIN IONS\nTITLE=y\nEND IONS\n")
        assert ic.lookup(str(c)) == (None, "miss")
        ic.clear()

    def test_eviction_drops_content_index(self, tmp_path):
        from specpride_tpu.serve import ingest_cache as ic

        ic.clear()
        paths = []
        for i in range(6):  # cap is 4 entries
            p = tmp_path / f"f{i}.mgf"
            p.write_text(f"content-{i}")
            ic.put(str(p), [i], n_spectra=1, n_peaks=1)
            paths.append(p)
        # f0/f1 evicted: a copy of f0's bytes must MISS, not resolve a
        # dangling index entry
        copy = tmp_path / "f0_copy.mgf"
        copy.write_bytes(paths[0].read_bytes())
        assert ic.lookup(str(copy)) == (None, "miss")
        copy5 = tmp_path / "f5_copy.mgf"
        copy5.write_bytes(paths[5].read_bytes())
        assert ic.lookup(str(copy5))[1] == "content"
        ic.clear()


# -- concurrency hammer --------------------------------------------------


def test_concurrency_hammer(tmp_path, rng):
    """Many threads putting/getting against one capped tier: no
    exceptions, every served entry verifies for its own key, and the
    cap holds once the dust settles."""
    tier = rc.LocalTier(str(tmp_path), max_mb=1)
    keys, entries = [], {}
    for i in range(12):
        key = f"{i:02d}" + "0" * 62
        keys.append(key)
        entries[key] = _entry_for(rng, key, cid=f"c-{i}")[1]
    tier.put(keys[0], entries[keys[0]])
    size = os.path.getsize(tmp_path / (keys[0] + ".json"))
    tier.max_bytes = size * 5  # constant eviction pressure
    errors = []

    def worker(seed):
        r = np.random.default_rng(seed)
        try:
            for _ in range(150):
                k = keys[int(r.integers(len(keys)))]
                if r.random() < 0.5:
                    tier.put(k, entries[k])
                else:
                    got = tier.get(k)
                    if got is not None and got is not rc.CORRUPT:
                        assert got["key"] == k
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert tier.info()["bytes"] <= tier.max_bytes
    assert not [n for n in os.listdir(tmp_path)
                if n.endswith(".corrupt")], \
        "atomic commits must never yield a torn entry"
