"""Cross-job micro-batching (``specpride serve --batch-window``):
compatibility-key eligibility, the scheduler's compatible-pop (quota /
conflict-guard policy unchanged), batched-vs-solo byte + QC parity
across methods x workers x window x tenants, drain-with-open-window
commit semantics, shared-dispatch attribution (batch_dispatch journal
event, batch metrics, per-job deltas), plan-cache cross-job sharing,
and the drain-snapshot 0-valued series fix."""

import json
import os
import threading
import time

import numpy as np
import pytest

from specpride_tpu.cli import build_parser, main as cli_main
from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.observability.journal import read_events
from specpride_tpu.serve import batcher, client as sc
from specpride_tpu.serve.daemon import ServeDaemon
from specpride_tpu.serve.scheduler import AdmissionQueue, Quota

from conftest import make_cluster

METHODS = [
    ("bin-mean", "consensus"),
    ("gap-average", "consensus"),
    ("medoid", "select"),
]


def _parse(argv):
    return build_parser().parse_args(argv)


def _start(daemon: ServeDaemon) -> threading.Thread:
    t = threading.Thread(target=daemon.run, daemon=True)
    t.start()
    assert sc.wait_for_socket(daemon.socket_path, timeout=120), \
        "daemon never answered ping"
    return t


def _stop(daemon: ServeDaemon, thread: threading.Thread) -> None:
    daemon.drain()
    thread.join(timeout=60)
    assert not thread.is_alive(), "daemon thread did not exit after drain"


@pytest.fixture(scope="module")
def workloads(tmp_path_factory):
    """Two DISTINCT tenant inputs (different cluster shapes), so a batch
    exercises the merged multi-source pack, not just same-input
    fan-out."""
    tmp = tmp_path_factory.mktemp("batch_wl")
    rng = np.random.default_rng(91)
    a = tmp / "tenant_a.mgf"
    b = tmp / "tenant_b.mgf"
    write_mgf(
        [s for c in (
            make_cluster(rng, f"a-{i}", n_members=3, n_peaks=25)
            for i in range(6)
        ) for s in c.members],
        a,
    )
    write_mgf(
        [s for c in (
            make_cluster(rng, f"b-{i}", n_members=4, n_peaks=30)
            for i in range(5)
        ) for s in c.members],
        b,
    )
    return str(a), str(b)


@pytest.fixture(scope="module")
def golden(workloads, tmp_path_factory):
    """Solo one-shot CLI bytes + QC for every (method, input) — the
    parity bar every batched cell must reproduce."""
    tmp = tmp_path_factory.mktemp("batch_golden")
    out = {}
    for method, command in METHODS:
        for tag, src in zip(("a", "b"), workloads):
            o = tmp / f"{method}_{tag}.mgf"
            qc = tmp / f"{method}_{tag}.qc.json"
            assert cli_main([
                command, src, str(o), "--method", method,
                "--qc-report", str(qc),
            ]) == 0
            out[(method, tag)] = (o.read_bytes(), qc.read_text())
    return out


class TestBatchKey:
    def test_eligible_and_spelling_invariant(self, workloads):
        src, _ = workloads
        k1 = batcher.batch_key(
            _parse(["consensus", src, "/tmp/o1.mgf", "--method",
                    "bin-mean", "--bin-size", "0.02"]),
            "consensus",
        )
        k2 = batcher.batch_key(
            _parse(["consensus", src, "/tmp/o2.mgf",
                    "--bin-size", "0.02", "--method", "bin-mean"]),
            "consensus",
        )
        assert k1 is not None and k1 == k2, \
            "flag order must not split compatible jobs"

    def test_config_differences_split_the_key(self, workloads):
        src, _ = workloads
        base = _parse(["consensus", src, "/tmp/o.mgf", "--method",
                       "bin-mean"])
        other = _parse(["consensus", src, "/tmp/o.mgf", "--method",
                        "bin-mean", "--bin-size", "0.05"])
        qc = _parse(["consensus", src, "/tmp/o.mgf", "--method",
                     "bin-mean", "--qc-report", "/tmp/q.json"])
        kb = batcher.batch_key(base, "consensus")
        assert kb != batcher.batch_key(other, "consensus")
        assert kb != batcher.batch_key(qc, "consensus"), \
            "QC and no-QC jobs must not share a dispatch"
        gap = _parse(["consensus", src, "/tmp/o.mgf", "--method",
                      "gap-average"])
        assert kb != batcher.batch_key(gap, "consensus")

    @pytest.mark.parametrize("argv_extra", [
        ["--backend", "numpy"],
        ["--mesh"],
        ["--elastic", "/tmp/el"],
        ["--inject-faults", "dispatch:error:1"],
        ["--single"],
        ["--on-error", "skip"],
        ["--stream-clusters", "64"],
    ])
    def test_solo_semantics_are_ineligible(self, workloads, argv_extra):
        src, _ = workloads
        args = _parse(
            ["consensus", src, "/tmp/o.mgf", "--method", "bin-mean"]
            + argv_extra
        )
        assert batcher.batch_key(args, "consensus") is None

    def test_best_spectrum_is_ineligible(self, workloads):
        src, _ = workloads
        args = _parse(["select", src, "/tmp/o.mgf", "--method", "best",
                       "--msms", "/tmp/msms.txt"])
        assert batcher.batch_key(args, "select") is None


class _KeyedJob:
    def __init__(self, name, key, paths=()):
        self.name = name
        self.batch_key = key
        self.paths = tuple(paths)

    def __repr__(self):
        return self.name


class TestPopCompatible:
    def test_pops_only_matching_heads_in_fair_order(self):
        q = AdmissionQueue(16)
        a1 = _KeyedJob("a1", ("k",))
        b1 = _KeyedJob("b1", ("other",))
        c1 = _KeyedJob("c1", ("k",))
        for client, job in (("A", a1), ("B", b1), ("C", c1)):
            assert q.offer(client, job)
        match = lambda j: j.batch_key == ("k",)  # noqa: E731
        assert q.pop_compatible(match) is a1
        assert q.pop_compatible(match) is c1
        assert q.pop_compatible(match) is None, \
            "non-matching heads must stay queued"
        assert q.pop(timeout=0.1) is b1

    def test_respects_inflight_quota(self):
        q = AdmissionQueue(16, quotas={"A": Quota(1.0, max_inflight=1)})
        a1, a2 = _KeyedJob("a1", ("k",)), _KeyedJob("a2", ("k",))
        assert q.offer("A", a1)
        assert q.pop(timeout=0.1) is a1  # A at its cap
        with q._cond:  # inject past the admission check
            q._states["A"].queue.append(a2)
            q._total += 1
        match = lambda j: True  # noqa: E731
        assert q.pop_compatible(match) is None, \
            "a capped client must not feed a batch"
        q.release(a1)
        assert q.pop_compatible(match) is a2

    def test_respects_conflict_guard(self):
        q = AdmissionQueue(
            16, conflict_key=lambda j: j.paths,
        )
        a1 = _KeyedJob("a1", ("k",), paths=("/out/x",))
        b1 = _KeyedJob("b1", ("k",), paths=("/out/x",))
        q.offer("A", a1)
        q.offer("B", b1)
        assert q.pop(timeout=0.1) is a1
        assert q.pop_compatible(lambda j: True) is None, \
            "a same-output job must not join a batch mid-write"
        q.release(a1)
        assert q.pop_compatible(lambda j: True) is b1


def _boot(tmp, *, workers, window_s, cache, **kw):
    d = ServeDaemon(
        str(tmp / "serve.sock"),
        compile_cache=cache,
        journal_path=str(tmp / "serve.jsonl"),
        workers=workers,
        batch_window=window_s,
        **kw,
    )
    d._gate.clear()
    return d, _start(d)


class TestBatchedParity:
    """The matrix: 3 methods x workers {1,2} x batch-window {0, 50ms}
    x 2 concurrent tenants with DISTINCT inputs — batched (and
    degenerate-solo) outputs byte-identical to solo CLI runs, QC
    reports equal."""

    @pytest.mark.parametrize("method,command", METHODS)
    @pytest.mark.parametrize("workers", [1, 2])
    @pytest.mark.parametrize("window_s", [0.0, 0.05])
    def test_matrix_cell(
        self, tmp_path, tmp_path_factory, workloads, golden,
        method, command, workers, window_s,
    ):
        cache = str(tmp_path_factory.getbasetemp() / "batch_cache")
        d, t = _boot(
            tmp_path, workers=workers, window_s=window_s, cache=cache,
        )
        terms = {}

        def _submit(tag, src):
            out = tmp_path / f"{tag}.mgf"
            qc = tmp_path / f"{tag}.qc.json"
            terms[tag] = (
                sc.submit_wait(
                    d.socket_path,
                    [command, src, str(out), "--method", method,
                     "--qc-report", str(qc)],
                    client=f"tenant-{tag}",
                ),
                out, qc,
            )

        threads = [
            threading.Thread(target=_submit, args=(tag, src))
            for tag, src in zip(("a", "b"), workloads)
        ]
        threads[0].start()
        # both jobs admitted before any executes (the gate holds the
        # popping worker), so the window>0 single-lane cells batch
        # deterministically
        deadline = time.time() + 30
        while not d._inflight_by and time.time() < deadline:
            time.sleep(0.01)
        threads[1].start()
        while len(d.queue) + len(d._inflight_by) < 2 and \
                time.time() < deadline:
            time.sleep(0.01)
        d._gate.set()
        for th in threads:
            th.join(timeout=180)
            assert not th.is_alive()
        _stop(d, t)
        for tag in ("a", "b"):
            term, out, qc = terms[tag]
            assert term["status"] == "done", (method, tag, term)
            want_bytes, want_qc = golden[(method, tag)]
            assert out.read_bytes() == want_bytes, (method, tag)
            assert json.loads(qc.read_text()) == json.loads(want_qc), \
                (method, tag)
        events, violations = read_events(d.journal_path)
        assert not violations, violations
        shared = [
            e for e in events
            if e["event"] == "batch_dispatch"
            and e.get("status") == "shared"
        ]
        if window_s > 0 and workers == 1:
            # single lane + held gate: both jobs were queued when the
            # collector ran — the shared dispatch MUST have coalesced
            assert shared and shared[0]["n_jobs"] == 2, shared
            assert shared[0]["n_clusters"] == 11  # 6 + 5 merged
            done = [e for e in events if e["event"] == "job_done"]
            assert all(
                e.get("batch_id") == shared[0]["batch_id"]
                for e in done
            ), done
            assert terms["a"][0].get("batch", {}).get("batch_jobs") == 2
        if window_s == 0:
            assert not shared, "batching off must never share dispatches"


class TestDrainWithOpenWindow:
    def test_drain_closes_the_window_and_commits(
        self, tmp_path, workloads, golden,
    ):
        """A leader sitting in a wide-open window (no companions) must
        commit its job promptly when drain fires — never wait out the
        window, never drop the job."""
        src, _ = workloads
        d = ServeDaemon(
            str(tmp_path / "serve.sock"),
            compile_cache=str(tmp_path / "cache"),
            journal_path=str(tmp_path / "serve.jsonl"),
            workers=1,
            batch_window=30.0,  # far beyond the test timeout
        )
        t = _start(d)
        out = tmp_path / "drained.mgf"
        term = {}

        def _submit():
            term["msg"] = sc.submit_wait(d.socket_path, [
                "consensus", src, str(out), "--method", "bin-mean",
                "--qc-report", str(tmp_path / "drained.qc.json"),
            ], client="lonely")

        th = threading.Thread(target=_submit)
        th.start()
        deadline = time.time() + 30
        while not d._inflight_by and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)  # let the leader enter the collection window
        t0 = time.time()
        _stop(d, t)
        assert time.time() - t0 < 20, \
            "drain must not wait out the 30s batch window"
        th.join(timeout=60)
        assert term["msg"]["status"] == "done", term["msg"]
        assert out.read_bytes() == golden[("bin-mean", "a")][0]
        events, violations = read_events(d.journal_path)
        assert not violations, violations
        done = [e for e in events if e["event"] == "job_done"]
        assert len(done) == 1 and done[0]["status"] == "done"


class TestQuotaAccountingUnderBatching:
    def test_max_inflight_unchanged(self, tmp_path, workloads):
        """A tenant at max_inflight=1 gets the same named retriable
        bounce with batching armed; the batch collector never pulls a
        capped tenant's second job."""
        from specpride_tpu.serve.scheduler import parse_quota_spec

        src, _ = workloads
        d = ServeDaemon(
            str(tmp_path / "serve.sock"),
            compile_cache=str(tmp_path / "cache"),
            journal_path=str(tmp_path / "serve.jsonl"),
            workers=1,
            batch_window=0.05,
            quotas=parse_quota_spec("capped=1:1"),
        )
        d._gate.clear()
        t = _start(d)
        terms = {}

        def _submit(tag):
            terms[tag] = sc.submit_wait(d.socket_path, [
                "consensus", src, str(tmp_path / f"{tag}.mgf"),
                "--method", "bin-mean",
            ], client="capped")

        try:
            t1 = threading.Thread(target=_submit, args=("first",))
            t1.start()
            deadline = time.time() + 30
            while d._inflight is None and time.time() < deadline:
                time.sleep(0.01)
            _submit("bounced")
            term = terms["bounced"]
            assert term["status"] == "rejected", term
            assert term["retriable"] is True
            assert "quota" in term["reason"]
        finally:
            d._gate.set()
            t1.join(timeout=120)
            _stop(d, t)
        assert terms["first"]["status"] == "done"


class TestPlanCacheCrossJobSharing:
    def test_second_job_hits_with_correct_per_job_deltas(
        self, tmp_path, workloads,
    ):
        """The bucket-plan cache is shared READ-ONLY across jobs: the
        first job's pack memoizes the plan (misses > 0), an identical
        second job reuses it (hits > 0, misses == 0), and each job's
        run_end reports ITS OWN traffic — the PlanCacheScope deltas."""
        from specpride_tpu.data.packed import clear_plan_cache

        src, _ = workloads
        clear_plan_cache()
        d = ServeDaemon(
            str(tmp_path / "serve.sock"),
            compile_cache=str(tmp_path / "cache"),
            journal_path=str(tmp_path / "serve.jsonl"),
            workers=1,
            layout="bucketized",  # the (B, K) packers use the plan cache
        )
        t = _start(d)
        try:
            deltas = []
            for tag in ("first", "second"):
                jp = tmp_path / f"{tag}.jsonl"
                term = sc.submit_wait(d.socket_path, [
                    "consensus", src, str(tmp_path / f"{tag}.mgf"),
                    "--method", "bin-mean", "--journal", str(jp),
                ])
                assert term["status"] == "done", term
                events, violations = read_events(str(jp))
                assert not violations, violations
                end = [e for e in events if e["event"] == "run_end"][-1]
                deltas.append(end["plan_cache"])
        finally:
            _stop(d, t)
        first, second = deltas
        assert first["misses"] > 0, first
        assert second["misses"] == 0, \
            f"identical shape profile must reuse the memoized plan: " \
            f"{second}"
        assert second["hits"] > 0, second
        assert (tmp_path / "first.mgf").read_bytes() == \
            (tmp_path / "second.mgf").read_bytes()


class TestDrainSnapshotSeries:
    def test_final_snapshot_keeps_client_and_batch_series(
        self, tmp_path, workloads,
    ):
        """The drain-time --metrics-out snapshot renders 0-valued
        series: per-client queue depth for every tenant ever admitted
        (clear-and-set alone dropped the rows), and the batch
        counters/gauge even when batching never coalesced."""
        from specpride_tpu.observability.exporter import (
            parse_exposition,
        )

        src, _ = workloads
        prom = tmp_path / "final.prom"
        d = ServeDaemon(
            str(tmp_path / "serve.sock"),
            compile_cache=str(tmp_path / "cache"),
            journal_path=str(tmp_path / "serve.jsonl"),
            workers=1,
            batch_window=0.01,
            metrics_out=str(prom),
        )
        t = _start(d)
        term = sc.submit_wait(d.socket_path, [
            "consensus", src, str(tmp_path / "o.mgf"),
            "--method", "bin-mean",
        ], client="tenant-gone")
        assert term["status"] == "done", term
        _stop(d, t)
        text = prom.read_text()
        samples, problems = parse_exposition(text)
        assert not problems, problems
        assert samples[(
            "specpride_serve_queue_depth_client",
            (("client", "tenant-gone"),),
        )] == 0.0, "departed client must render a 0 row at drain"
        for name in (
            "specpride_serve_batch_dispatches_total",
            "specpride_serve_batch_jobs_total",
            "specpride_serve_batch_clusters_total",
            "specpride_serve_batch_occupancy",
        ):
            assert (name, ()) in samples, f"missing 0-valued {name}"
            assert samples[(name, ())] == 0.0


class TestSharedBackendUnits:
    def test_run_shared_scatters_per_source(self):
        """``TpuBackend.run_shared`` over two distinct sources returns
        per-source slices identical to per-source solo runs."""
        from specpride_tpu.backends.tpu_backend import TpuBackend
        from specpride_tpu.config import BinMeanConfig, CosineConfig

        rng = np.random.default_rng(7)
        a = [make_cluster(rng, f"sa-{i}", n_members=3, n_peaks=20)
             for i in range(4)]
        b = [make_cluster(rng, f"sb-{i}", n_members=2, n_peaks=15)
             for i in range(3)]
        backend = TpuBackend()
        cfg, ccfg = BinMeanConfig(), CosineConfig()
        shared = backend.run_shared(
            "bin-mean", [a, b], cfg, cos_config=ccfg
        )
        assert len(shared) == 2
        solo_a, cos_a = backend.run_bin_mean_with_cosines(a, cfg, ccfg)
        solo_b, cos_b = backend.run_bin_mean_with_cosines(b, cfg, ccfg)
        for (reps, cos), solo, solo_cos in (
            (shared[0], solo_a, cos_a), (shared[1], solo_b, cos_b),
        ):
            assert len(reps) == len(solo)
            for r, s in zip(reps, solo):
                assert r.title == s.title
                np.testing.assert_array_equal(r.mz, s.mz)
                np.testing.assert_array_equal(r.intensity, s.intensity)
                assert r.precursor_mz == s.precursor_mz
            np.testing.assert_array_equal(
                np.asarray(cos), np.asarray(solo_cos)
            )

    def test_batch_result_backend_forwards_unknown_clusters(self):
        from specpride_tpu.backends.tpu_backend import TpuBackend
        from specpride_tpu.config import BinMeanConfig

        rng = np.random.default_rng(8)
        known = [make_cluster(rng, "known", n_members=2, n_peaks=10)]
        other = [make_cluster(rng, "other", n_members=2, n_peaks=10)]
        inner = TpuBackend()
        cfg = BinMeanConfig()
        [rep] = inner.run_bin_mean(known, cfg)
        shim = batcher.BatchResultBackend(
            inner, batcher.SharedResults({"known": rep}, None),
        )
        assert shim.supports_prepare("bin-mean") is False
        assert shim.run_bin_mean(known, cfg) == [rep]
        # unknown cluster: forwarded to the real backend, never wrong
        [fresh] = shim.run_bin_mean(other, cfg)
        [solo] = inner.run_bin_mean(other, cfg)
        np.testing.assert_array_equal(fresh.mz, solo.mz)
        # attribute traffic lands on the real backend
        shim.pack_accounting = True
        assert inner.pack_accounting is True
        inner.pack_accounting = False
