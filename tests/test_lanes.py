"""Multi-lane executor (--pack-workers / --async-write): byte parity
against the serial path across the lane matrix, kill/resume with the
committer lane active, forced out-of-order pack completion through the
reorder buffer, per-lane run_end telemetry, the CPU-only gap-average
device routing, and the unified traced MGF writer."""

import json
import os

import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.io.mgf import read_mgf, write_mgf

from conftest import make_cluster


def _workload(rng, n=9, **kw):
    return [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25, **kw)
        for i in range(n)
    ]


def _write(tmp_path, clusters):
    path = tmp_path / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], path)
    return path


class TestLaneParity:
    @pytest.mark.parametrize("method,command", [
        ("bin-mean", "consensus"),
        ("gap-average", "consensus"),
        ("medoid", "select"),
    ])
    def test_byte_identical_across_lane_matrix(
        self, tmp_path, rng, method, command
    ):
        """Every (pack-workers, async-write) combination must produce the
        serial run's exact MGF bytes AND checkpoint manifest: the lanes
        change scheduling, never results or resume state."""
        clustered = _write(tmp_path, _workload(rng))
        golden = golden_manifest = None
        combos = [("serial", ["--prefetch", "0"])] + [
            (
                f"pw{pw}_{aw}",
                ["--prefetch", "4", "--pack-workers", str(pw),
                 "--async-write", aw],
            )
            for pw in (0, 1, 4)
            for aw in ("on", "off")
        ]
        for tag, extra in combos:
            out = tmp_path / f"out_{tag}.mgf"
            ckpt = tmp_path / f"ck_{tag}.json"
            assert cli_main([
                command, str(clustered), str(out), "--method", method,
                "--checkpoint", str(ckpt), "--checkpoint-every", "2",
            ] + extra) == 0
            data = out.read_bytes()
            manifest = json.loads(ckpt.read_text())
            if golden is None:
                golden, golden_manifest = data, manifest
            else:
                assert data == golden, (method, tag)
                assert manifest == golden_manifest, (method, tag)

    def test_qc_report_identical_with_committer(self, tmp_path, rng):
        """QC rows finalize on the committer lane under --async-write;
        the report must still match the serial run byte for byte."""
        clustered = _write(tmp_path, _workload(rng))
        reports = {}
        for tag, extra in (
            ("serial", ["--prefetch", "0"]),
            ("lanes", ["--prefetch", "4", "--pack-workers", "4",
                       "--async-write", "on"]),
        ):
            out = tmp_path / f"o_{tag}.mgf"
            qc = tmp_path / f"qc_{tag}.json"
            assert cli_main([
                "consensus", str(clustered), str(out),
                "--checkpoint", str(tmp_path / f"c_{tag}.json"),
                "--checkpoint-every", "3", "--qc-report", str(qc),
            ] + extra) == 0
            reports[tag] = qc.read_bytes()
        assert reports["serial"] == reports["lanes"]

    def test_kill_resume_with_committer_lane(self, tmp_path, rng):
        """A mid-run kill (committed partial manifest + an orphaned torn
        append) resumed with the full lane stack active must converge to
        the serial golden bytes — the committer writes checkpoint i only
        after chunk i's MGF bytes are flushed, so every crash state it
        can leave is one the serial path could also leave."""
        clusters = _workload(rng, n=8)
        clustered = _write(tmp_path, clusters)

        golden = tmp_path / "golden.mgf"
        assert cli_main([
            "consensus", str(clustered), str(golden), "--prefetch", "0",
            "--checkpoint", str(tmp_path / "g.json"),
            "--checkpoint-every", "2",
        ]) == 0
        golden_bytes = golden.read_bytes()

        head_src = tmp_path / "head.mgf"
        write_mgf([s for c in clusters[:2] for s in c.members], head_src)
        out = tmp_path / "out.mgf"
        assert cli_main([
            "consensus", str(head_src), str(out), "--prefetch", "0",
        ]) == 0
        committed = out.stat().st_size
        assert golden_bytes.startswith(out.read_bytes())
        with open(out, "ab") as fh:
            fh.write(b"BEGIN IONS\nTITLE=torn-orphan\n")
        ckpt = tmp_path / "ckpt.json"
        ckpt.write_text(json.dumps({
            "done": ["cluster-0", "cluster-1"], "output_bytes": committed,
        }))
        assert cli_main([
            "consensus", str(clustered), str(out), "--prefetch", "4",
            "--pack-workers", "4", "--async-write", "on",
            "--checkpoint", str(ckpt), "--checkpoint-every", "2",
        ]) == 0
        assert out.read_bytes() == golden_bytes

    def test_on_error_skip_with_lanes(self, tmp_path, rng):
        """--on-error skip with a poisoned cluster under the full lane
        stack: the pack-pool failure must still route through the
        consumer's per-cluster serial retry and record exactly the bad
        cluster — same output and manifest as serial."""
        good = _workload(rng, n=5)
        bad = make_cluster(rng, "cluster-bad", n_members=2, n_peaks=15)
        bad.members[1].precursor_charge = bad.members[0].precursor_charge + 1
        clusters = good[:2] + [bad] + good[2:]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        outs = {}
        for tag, extra in (
            ("serial", ["--prefetch", "0"]),
            ("lanes", ["--prefetch", "2", "--pack-workers", "3",
                       "--async-write", "on"]),
        ):
            out = tmp_path / f"out_{tag}.mgf"
            ckpt = tmp_path / f"ck_{tag}.json"
            assert cli_main([
                "consensus", str(clustered), str(out), "--on-error", "skip",
                "--checkpoint", str(ckpt), "--checkpoint-every", "2",
            ] + extra) == 0
            outs[tag] = out.read_bytes()
            assert json.loads(ckpt.read_text())["failed"] == ["cluster-bad"]
        assert outs["serial"] == outs["lanes"]
        assert sorted(s.title for s in read_mgf(tmp_path / "out_lanes.mgf")) \
            == sorted(c.cluster_id for c in good)

    def test_abort_shuts_all_lanes_down(self, tmp_path, rng):
        """Default --on-error abort with the bad cluster in an EARLY
        chunk of a longer worklist: the pack-pool error propagates and
        neither pool workers nor the committer thread survive — the
        executor must close its lanes on the abort path, not rely on the
        worklist being exhausted before the failure."""
        bad = make_cluster(rng, "cluster-bad", n_members=2, n_peaks=15)
        bad.members[1].precursor_charge = bad.members[0].precursor_charge + 1
        clusters = [bad] + _workload(rng, n=12)
        clustered = _write(tmp_path, clusters)
        with pytest.raises(ValueError):
            cli_main([
                "consensus", str(clustered), str(tmp_path / "x.mgf"),
                "--prefetch", "2", "--pack-workers", "4",
                "--async-write", "on",
                "--checkpoint", str(tmp_path / "c.json"),
                "--checkpoint-every", "1",
            ])
        import threading

        assert not [
            t for t in threading.enumerate()
            if t.name.startswith(("specpride-packer", "specpride-committer"))
            and t.is_alive()
        ]


class TestReorderBuffer:
    def test_out_of_order_pack_completion_releases_fifo(self, tmp_path, rng):
        """Force chunk 0's pack to finish LAST: later chunks must wait in
        the reorder buffer (reorder_stall_s > 0), and the output must
        still be the serial bytes — FIFO release is the ordering
        contract, not pack completion order."""
        import time

        from specpride_tpu import cli as cli_mod
        from specpride_tpu.backends import numpy_backend as nb
        from specpride_tpu.observability import RunStats

        clusters = _workload(rng, n=8)

        class SlowHead(list):
            """Delays every materialization of clusters 0/1 (chunk 0)
            so pool workers complete chunks 1..3 first."""

            def __getitem__(self, i):
                if i in (0, 1):
                    time.sleep(0.15)
                return super().__getitem__(i)

        def run(source, extra):
            n = len(list(tmp_path.iterdir()))
            out = tmp_path / f"out_{n}.mgf"
            args = cli_mod.build_parser().parse_args([
                "consensus", "in.mgf", str(out), "--backend", "numpy",
                "--checkpoint", str(tmp_path / f"ck_{n}.json"),
                "--checkpoint-every", "2",
            ] + extra)
            stats = RunStats()
            cli_mod._checkpointed_run(args=args, backend=nb,
                                      method="bin-mean", clusters=source,
                                      stats=stats)
            return out.read_bytes(), stats.pipeline

        golden, _ = run(list(clusters), ["--prefetch", "0"])
        data, pipe = run(SlowHead(clusters), [
            "--prefetch", "4", "--pack-workers", "4", "--async-write", "on",
        ])
        assert data == golden
        assert pipe["pack_workers"] == 4 and pipe["async_write"] is True
        assert pipe["reorder_stall_s"] > 0.0
        assert len(pipe["pack_busy_s"]) == 4

    def test_run_end_pipeline_lane_fields(self, tmp_path, rng):
        """run_end.pipeline carries the per-lane summary and `specpride
        stats` renders it."""
        clustered = _write(tmp_path, _workload(rng))
        journal = tmp_path / "run.jsonl"
        agg = tmp_path / "agg.json"
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "o.mgf"),
            "--prefetch", "2", "--pack-workers", "2", "--async-write", "on",
            "--checkpoint", str(tmp_path / "c.json"),
            "--checkpoint-every", "2", "--journal", str(journal),
        ]) == 0
        events = [json.loads(l) for l in journal.read_text().splitlines()]
        end = [e for e in events if e["event"] == "run_end"][-1]
        pipe = end["pipeline"]
        assert pipe["pack_workers"] == 2 and pipe["async_write"] is True
        assert len(pipe["pack_busy_s"]) == 2
        assert pipe["write_busy_s"] >= 0.0
        assert pipe["reorder_stall_s"] >= 0.0
        # worker spans carry their lane index; the committer has its own
        span_names = {e["name"] for e in events if e["event"] == "span"}
        assert any(n.startswith("pipeline:pack[") for n in span_names)
        assert "pipeline:write" in span_names
        # commit protocol order is auditable from the journal: every
        # checkpoint_write follows its chunk's chunk_done, n_done grows
        order = [
            e for e in events
            if e["event"] in ("chunk_done", "checkpoint_write")
        ]
        n_done = 0
        for prev, cur in zip(order, order[1:]):
            if cur["event"] == "checkpoint_write":
                assert prev["event"] == "chunk_done"
                assert cur["n_done"] > n_done
                n_done = cur["n_done"]
        import subprocess
        import sys

        res = subprocess.run(
            [sys.executable, "-m", "specpride_tpu", "stats", str(journal),
             "--json", str(agg)],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert res.returncode == 0, res.stderr
        assert "reorder_stall_s" in res.stdout
        run = json.loads(agg.read_text())["runs"][0]
        assert run["pack_workers"] == 2
        assert "write_busy_s" in run and "pack_busy_s" in run


class TestGapAverageRouting:
    def _gap_clusters(self, rng):
        from test_tpu_parity import make_gap_safe_cluster

        return [
            make_gap_safe_cluster(rng, f"cluster-{i}", n_members=3)
            for i in range(5)
        ]

    def test_cpu_only_bucketized_routes_to_host(self, rng):
        """On a CPU-only host, --layout bucketized gap-average runs the
        vectorized host consensus (same results, ~3x faster here) and
        journals the decision exactly once."""
        from specpride_tpu.backends import numpy_backend as nb
        from specpride_tpu.backends.tpu_backend import TpuBackend

        events = []

        class Capture:
            enabled = True

            def emit(self, event, **fields):
                events.append({"event": event, **fields})
                return {}

        backend = TpuBackend(layout="bucketized")
        backend.journal = Capture()
        clusters = self._gap_clusters(rng)
        out = backend.run_gap_average(clusters)
        backend.run_gap_average(clusters)  # second call: no duplicate event
        oracle = nb.run_gap_average(clusters)
        for o, d in zip(oracle, out):
            assert o.n_peaks == d.n_peaks
        routing = [e for e in events if e["event"] == "routing"]
        assert routing == [{
            "event": "routing", "method": "gap-average",
            "path": "host-vectorized", "reason": "cpu-only-devices",
            "source": "static",
        }]
        # the host path dispatched no gap kernel
        assert not [e for e in events if e["event"] == "dispatch"]

    def test_force_device_keeps_kernel(self, rng):
        """--force-device pins the requested device path: the bucketized
        kernel dispatches and no routing event is emitted."""
        from specpride_tpu.backends.tpu_backend import TpuBackend

        events = []

        class Capture:
            enabled = True

            def emit(self, event, **fields):
                events.append({"event": event, **fields})
                return {}

        backend = TpuBackend(layout="bucketized", force_device=True)
        backend.journal = Capture()
        backend.run_gap_average(self._gap_clusters(rng))
        assert not [e for e in events if e["event"] == "routing"]
        assert [
            e for e in events
            if e["event"] == "dispatch"
            and e["kernel"] == "gap_average_compact"
        ]

    def test_cli_force_device_flag(self, tmp_path, rng):
        """The CLI flag reaches the backend, and the default CLI path
        journals the routing decision on CPU-only hosts."""
        clustered = _write(tmp_path, _workload(rng, n=4))
        journal = tmp_path / "run.jsonl"
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "o.mgf"),
            "--method", "gap-average", "--layout", "bucketized",
            "--journal", str(journal),
        ]) == 0
        events = [json.loads(l) for l in journal.read_text().splitlines()]
        assert [e for e in events if e["event"] == "routing"]
        journal2 = tmp_path / "run2.jsonl"
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "o2.mgf"),
            "--method", "gap-average", "--layout", "bucketized",
            "--force-device", "--journal", str(journal2),
        ]) == 0
        events2 = [json.loads(l) for l in journal2.read_text().splitlines()]
        assert not [e for e in events2 if e["event"] == "routing"]


class TestUnifiedMgfWriter:
    def test_all_three_branches_traced(self, tmp_path, rng):
        """File-path, file-object and string targets all open the same
        write:mgf span with an n_spectra note (previously only the path
        branch was traced)."""
        import io

        from specpride_tpu.observability import Tracer
        from specpride_tpu.observability import tracing

        spectra = [s for c in _workload(rng, n=2) for s in c.members]
        prev = tracing.set_current(Tracer(keep=True))
        try:
            write_mgf(spectra, tmp_path / "a.mgf")
            sink = io.StringIO()
            write_mgf(spectra, sink)
            text = write_mgf(spectra, None)
            tracer = tracing.current()
        finally:
            tracing.set_current(prev)
        spans = [s for s in tracer.spans if s["name"] == "write:mgf"]
        assert len(spans) == 3
        assert all(
            s["labels"]["n_spectra"] == len(spectra) for s in spans
        )
        # identical bytes out of every branch
        assert (tmp_path / "a.mgf").read_text() == sink.getvalue() == text
