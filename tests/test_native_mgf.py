"""C++ fast-parser parity tests: native/mgf_parser.cpp vs the pure-Python
oracle parser (``io.mgf.parse_mgf_stream``).

The native path must be BYTE-EXACT: identical titles, extras, and
bit-identical float64 m/z / intensity / precursor values (both sides are
correctly-rounded decimal→double conversions).  Skipped wholesale when no
toolchain is available to build the library.
"""

import gzip
import shutil

import numpy as np
import pytest

from specpride_tpu.data.peaks import Spectrum
from specpride_tpu.io import native
from specpride_tpu.io.mgf import read_mgf, write_mgf

pytestmark = pytest.mark.skipif(
    not native.ensure_built(), reason="native parser not built (no toolchain?)"
)


def make_spectra(rng, n=40):
    spectra = []
    for i in range(n):
        k = int(rng.integers(1, 300))
        spectra.append(
            Spectrum(
                mz=np.sort(rng.uniform(100, 2000, k)),
                intensity=rng.uniform(0, 1e6, k),
                precursor_mz=float(rng.uniform(300, 900)),
                precursor_charge=int(rng.integers(-3, 4)),
                rt=float(rng.uniform(0, 3600)) if i % 3 else 0.0,
                title=f"cluster-{i};mzspec:PXD004732:run a;b=c:scan:{i}",
                extra={"SEQUENCE": "PEPTIDE", "SCANS": str(i)} if i % 2 else {},
            )
        )
    return spectra


def assert_identical(py, nat):
    assert len(py) == len(nat)
    for a, b in zip(py, nat):
        assert a.title == b.title
        assert a.precursor_mz == b.precursor_mz
        assert a.precursor_charge == b.precursor_charge
        assert a.rt == b.rt
        assert a.extra == b.extra
        np.testing.assert_array_equal(a.mz, b.mz)
        np.testing.assert_array_equal(a.intensity, b.intensity)


def test_exact_parity(tmp_path):
    rng = np.random.default_rng(11)
    path = tmp_path / "t.mgf"
    write_mgf(make_spectra(rng), path)
    assert_identical(
        read_mgf(path, use_native=False), native.read_mgf_native(path)
    )


def test_gzip_parity(tmp_path):
    rng = np.random.default_rng(12)
    plain = tmp_path / "t.mgf"
    gz = tmp_path / "t.mgf.gz"
    write_mgf(make_spectra(rng, 10), plain)
    with open(plain, "rb") as fi, gzip.open(gz, "wb") as fo:
        shutil.copyfileobj(fi, fo)
    assert_identical(
        read_mgf(plain, use_native=False), native.read_mgf_native(gz)
    )


def test_dialect_oddities(tmp_path):
    """Hand-written MGF exercising parser edge cases: junk outside records,
    blank lines inside records, single-field peak lines, PEPMASS with
    intensity, charge forms, lowercase keys, missing RT."""
    text = """# a comment outside any record
random garbage
BEGIN IONS
TITLE=c1;mzspec:PXD1:r:scan:1

pepmass=445.12 1000.5
CHARGE=2+
rtinseconds=12.5
SEQUENCE=PEPTIDE
100.5 200.25
101.5
.5 7
+2.5 8

END IONS
stray line between records
BEGIN IONS
TITLE=c2;u2
PEPMASS=
CHARGE=3-
300.1 1.0
END IONS
"""
    path = tmp_path / "odd.mgf"
    path.write_text(text)
    py = read_mgf(path, use_native=False)
    nat = native.read_mgf_native(path)
    assert_identical(py, nat)
    assert py[0].precursor_mz == 445.12
    assert py[0].precursor_charge == 2
    assert py[0].rt == 12.5
    assert py[0].extra == {"SEQUENCE": "PEPTIDE"}
    np.testing.assert_array_equal(py[0].mz, [100.5, 101.5, 0.5, 2.5])
    np.testing.assert_array_equal(py[0].intensity, [200.25, 0.0, 7.0, 8.0])
    assert py[1].precursor_mz == 0.0
    assert py[1].precursor_charge == -3


def test_unterminated_record_dropped(tmp_path):
    """A record with no END IONS yields nothing — both parsers."""
    path = tmp_path / "u.mgf"
    path.write_text("BEGIN IONS\nTITLE=c1;u\n100.0 1.0\n")
    assert read_mgf(path, use_native=False) == []
    assert native.read_mgf_native(path) == []


@pytest.mark.parametrize(
    "bad_line",
    [
        "100.5 12,3",  # junk intensity field
        "1.5.5 7",  # junk m/z field
        "RTINSECONDS=12.5 min",  # trailing junk after RT
        "CHARGE=abc",  # non-numeric charge
        "PEPMASS=abc 100",  # non-numeric pepmass first field
    ],
)
def test_malformed_rejected_by_both(tmp_path, bad_line):
    """Malformed numeric fields raise in the Python parser (float()/int()
    semantics) — the native parser must reject them too, not silently
    coerce, or corrupt files would parse differently depending on whether
    the .so is built."""
    path = tmp_path / "bad.mgf"
    path.write_text(
        f"BEGIN IONS\nTITLE=c1;u\n{bad_line}\n100.0 1.0\nEND IONS\n"
    )
    with pytest.raises(ValueError):
        read_mgf(path, use_native=False)
    with pytest.raises(RuntimeError):
        native.read_mgf_native(path)


def test_charge_leading_plus(tmp_path):
    """CHARGE=+2 parses to 2 in both parsers (Python int() accepts '+')."""
    path = tmp_path / "p.mgf"
    path.write_text(
        "BEGIN IONS\nTITLE=c1;u\nCHARGE=+2\n100.0 1.0\nEND IONS\n"
    )
    py = read_mgf(path, use_native=False)
    nat = native.read_mgf_native(path)
    assert py[0].precursor_charge == nat[0].precursor_charge == 2


def test_read_mgf_dispatches_to_native(tmp_path):
    rng = np.random.default_rng(13)
    path = tmp_path / "d.mgf"
    write_mgf(make_spectra(rng, 5), path)
    assert_identical(read_mgf(path, use_native=False), read_mgf(path))


def test_parallel_chunk_split_ignores_begin_ions_prefix(tmp_path, monkeypatch):
    """Multithreaded parses (files >= 8 MB) split the buffer at lines that
    trim to exactly "BEGIN IONS".  A record-internal header line merely
    *starting* with those 10 bytes (e.g. "BEGIN IONSFAKE=1" — a legal
    KEY=VALUE extra for both parsers) must NOT be a split point: the old
    prefix-only memcmp silently dropped the enclosing record (advisor r2).
    Every record carries such headers, so any false boundary would show as
    a parity break against the serial Python result.

    Construction: one giant record spans the file midpoint (where the
    2-thread splitter places its guess) and carries the fake header just
    PAST the midpoint, so the old forward scan found the fake line before
    the next real record boundary and dropped the giant record."""
    monkeypatch.setenv("SPECPRIDE_MGF_THREADS", "2")  # containers report 1 core
    parts = ["BEGIN IONS\nTITLE=cluster-0;u0\nPEPMASS=500.25\nCHARGE=2+\n"]
    # ~5.5 MB of peaks, fake header, a few more peaks
    parts.append(
        "\n".join(f"{100.0 + i * 0.001:.3f} {i % 997}.5" for i in range(450000))
    )
    parts.append("\nBEGIN IONSFAKE=1\nBEGIN IONS EXTRA=x\n")
    parts.append("".join(f"{600.0 + i:.1f} 1.0\n" for i in range(5)))
    parts.append("END IONS\n")
    small = (
        "BEGIN IONS\nTITLE=cluster-{i};u{i}\nPEPMASS=400.5\nCHARGE=2+\n"
        + "".join(f"{200.0 + j * 0.5:.1f} {j + 1}.0\n" for j in range(400))
        + "END IONS\n"
    )
    for i in range(1, 600):
        parts.append(small.replace("{i}", str(i)))
    path = tmp_path / "big.mgf"
    path.write_text("".join(parts))
    assert path.stat().st_size >= 8 << 20, "fixture must trigger threading"
    py = read_mgf(path, use_native=False)
    assert len(py) == 600
    assert py[0].extra["BEGIN IONSFAKE"] == "1"
    assert_identical(py, native.read_mgf_native(path))
