"""Warm-start subsystem: shape manifests, the AOT warmup registry, the
persistent-compile-cache control/accounting, and the measured routing
table — plus the CLI loop (cold run seeds the manifest, `specpride
warmup` pre-compiles, the warmed run journals zero fresh compiles and
byte-identical output)."""

import json
import os

import numpy as np
import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.warmstart import (
    RoutingTable,
    ShapeEntry,
    entries_from_seen,
    load_manifest,
    merge_manifest,
)
from specpride_tpu.warmstart import registry


@pytest.fixture
def rng():
    return np.random.default_rng(5)


def _workload(rng, n=6):
    clusters = []
    for i in range(n):
        m = int(rng.integers(2, 5))
        base = np.sort(rng.uniform(150, 1500, 50))
        members = [
            Spectrum(
                mz=np.sort(base + rng.normal(0, 0.002, 50)),
                intensity=rng.uniform(1, 1e4, 50),
                precursor_mz=400.0, precursor_charge=2, rt=1.0,
                title=f"w{i};s{k}",
            )
            for k in range(m)
        ]
        clusters.append(Cluster(f"w{i}", members))
    return clusters


def _write(tmp_path, clusters, name="in.mgf"):
    path = tmp_path / name
    write_mgf([s for c in clusters for s in c.members], str(path))
    return path


class TestManifest:
    def test_round_trip_and_merge_idempotent(self, tmp_path):
        path = str(tmp_path / "m.json")
        entries = [
            ShapeEntry("bin_mean_flat_intensity", (1024, 1024, 1024, 4)),
            ShapeEntry(
                "gap_average_compact", (64, 2048, 1536),
                {"type": "GapAverageConfig", "mz_accuracy": 0.01,
                 "dyn_range": 1000.0, "min_fraction": 0.5,
                 "tail_mode": "reference", "pepmass": "lower_median",
                 "rt": "median"},
            ),
        ]
        assert merge_manifest(path, entries) == 2
        assert merge_manifest(path, entries) == 2  # union, not append
        got = load_manifest(path)
        assert {e.kernel for e in got} == {
            "bin_mean_flat_intensity", "gap_average_compact"
        }
        assert all(isinstance(e.shape_key, tuple) for e in got)

    def test_entries_from_seen_config_binding(self):
        from specpride_tpu.config import BinMeanConfig

        seen = {
            ("bin_mean_bucketized", 64, 2048, 1024, 8),
            ("bin_mean_flat_intensity", 1024, 1024, 1024, 4),
            ("cosine_flat", 1024, 256, 64, 64, 65536, 4, 256, 256, 4, 32),
        }
        entries = entries_from_seen(seen, BinMeanConfig())
        by_kernel = {e.kernel: e for e in entries}
        assert by_kernel["bin_mean_bucketized"].config["type"] == (
            "BinMeanConfig"
        )
        assert by_kernel["bin_mean_flat_intensity"].config is None
        assert by_kernel["cosine_flat"].config is None

    def test_config_keyed_kernel_without_config_is_skipped(self):
        # a gap kernel recorded while the run's config is bin-mean's
        # cannot be rebuilt — must be dropped, not mis-recorded
        from specpride_tpu.config import BinMeanConfig

        entries = entries_from_seen(
            {("gap_average_compact", 64, 2048, 1536)}, BinMeanConfig()
        )
        assert entries == []

    def test_bad_manifest_raises(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text('{"version": 99, "entries": []}')
        with pytest.raises(ValueError):
            load_manifest(str(p))


class TestRegistry:
    def test_every_registered_kernel_aot_compiles(self):
        """Each registry builder must produce a lowerable call on the
        test platform (the Pallas variants are exercised separately:
        they only lower on TPU)."""
        from specpride_tpu.config import BinMeanConfig, GapAverageConfig
        from specpride_tpu.warmstart.manifest import config_dict

        cases = [
            ShapeEntry("bin_mean_flat_intensity", (16384, 1024, 1024, 4)),
            ShapeEntry(
                "bin_mean_bucketized", (8, 256, 1024, 8),
                config_dict(BinMeanConfig()),
            ),
            ShapeEntry(
                "gap_average_compact", (8, 256, 1024),
                config_dict(GapAverageConfig()),
            ),
            ShapeEntry("medoid_select_packed", (8, 256, 32, 256)),
            ShapeEntry("shared_bins_packed", (8, 256, 32, 256)),
            ShapeEntry("cosine_packed", (8, 256, 256, 32)),
            ShapeEntry(
                "cosine_flat",
                (16384, 256, 64, 64, 65536, 4, 256, 256, 4, 32),
            ),
        ]
        for entry in cases:
            fn, avals, statics = registry.build(entry)
            fn.lower(*avals, **statics).compile()

    def test_unknown_kernel_returns_none(self):
        assert registry.build(ShapeEntry("no_such_kernel", (1,))) is None

    def test_warm_entries_skips_unknown_and_reports(self):
        from specpride_tpu.warmstart.warmup import warm_entries

        events = []

        class Capture:
            enabled = True

            def emit(self, event, **fields):
                events.append({"event": event, **fields})
                return {}

        results = warm_entries(
            [
                ShapeEntry("bin_mean_flat_intensity",
                           (16384, 1024, 1024, 4)),
                ShapeEntry("mystery_kernel", (4,)),
            ],
            journal=Capture(),
        )
        by = {r.entry.kernel: r for r in results}
        assert by["bin_mean_flat_intensity"].status in (
            "compiled", "cache_hit"
        )
        assert by["mystery_kernel"].status == "skipped"
        warm_events = [e for e in events if e["event"] == "warmup"]
        assert len(warm_events) == 2
        assert all(
            {"kernel", "cache_hit", "seconds"} <= set(e)
            for e in warm_events
        )


class TestRouting:
    def test_static_defaults(self):
        t = RoutingTable()
        d = t.decide("gap-average", "cpu")
        assert (d.path, d.source) == ("host-vectorized", "static")
        assert t.decide("gap-average", "tpu").path == "xla"
        assert t.decide("bin-mean", "tpu").path == "xla"
        assert t.decide("unknown-method", "tpu").path == "xla"

    def test_override_file(self, tmp_path):
        p = tmp_path / "routing.json"
        p.write_text(json.dumps({
            "version": 1,
            "entries": [{
                "method": "bin-mean", "platform": "tpu",
                "path": "pallas", "reason": "pallas_ab: 1.7x",
            }],
        }))
        t = RoutingTable.load(str(p))
        d = t.decide("bin-mean", "tpu")
        assert (d.path, d.source) == ("pallas", "override")
        # untouched decisions keep the static defaults
        assert t.decide("gap-average", "cpu").path == "host-vectorized"

    def test_bad_explicit_override_fails_loudly(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text('{"version": 1, "entries": [{"method": "x", '
                     '"platform": "cpu", "path": "warp-drive"}]}')
        with pytest.raises(SystemExit):
            RoutingTable.load(str(p))

    def test_backend_consults_table(self, rng):
        """An override that forces gap-average onto the device on CPU is
        honored (and journaled with source=override)."""
        from specpride_tpu.backends.tpu_backend import TpuBackend

        events = []

        class Capture:
            enabled = True

            def emit(self, event, **fields):
                events.append({"event": event, **fields})
                return {}

        table = RoutingTable(
            {("gap-average", "cpu"): ("xla", "test-override")}
        )
        backend = TpuBackend(layout="bucketized", routing=table)
        backend.journal = Capture()
        backend.run_gap_average(_workload(rng, n=3))
        # the device kernel dispatched (no host reroute)...
        assert [
            e for e in events
            if e["event"] == "dispatch"
            and e["kernel"] == "gap_average_compact"
        ]
        # ...and no host-vectorized routing event was emitted
        assert not [
            e for e in events
            if e["event"] == "routing"
            and e["path"] == "host-vectorized"
        ]

    def test_pallas_override_falls_back_off_tpu(self, rng):
        """path=pallas where Pallas cannot lower → the scan impl runs,
        and the fallback is journaled."""
        from specpride_tpu.backends.tpu_backend import TpuBackend
        from specpride_tpu.ops import pallas_kernels as pk

        if pk.has_pallas():
            pytest.skip("test expects a host without Pallas lowering")
        events = []

        class Capture:
            enabled = True

            def emit(self, event, **fields):
                events.append({"event": event, **fields})
                return {}

        table = RoutingTable({
            ("gap-average", "cpu"): ("pallas", "forced for test"),
        })
        backend = TpuBackend(
            layout="bucketized", force_device=True, routing=table
        )
        backend.journal = Capture()
        out = backend.run_gap_average(_workload(rng, n=3))
        assert len(out) == 3
        assert [
            e for e in events
            if e["event"] == "routing" and e["path"] == "xla"
            and e["reason"] == "pallas-unavailable"
        ]
        assert [
            e for e in events
            if e["event"] == "dispatch"
            and e["kernel"] == "gap_average_compact"
        ]


class TestCompileCacheControl:
    def test_off_and_explicit_dir(self, tmp_path):
        from specpride_tpu.warmstart import cache

        state = cache.configure_compile_cache("off")
        assert not state.enabled and state.source == "off"
        d = str(tmp_path / "cc")
        state = cache.configure_compile_cache(d)
        assert state.enabled and state.dir == d and state.source == "flag"
        import jax

        assert jax.config.jax_compilation_cache_dir == d
        # explicit dir caches EVERYTHING (the zero-fresh-compiles
        # guarantee needs fast compiles cached too)
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0

    def test_counters_delta_shape(self):
        from specpride_tpu.warmstart import cache

        snap = cache.counters_snapshot()
        delta = cache.counters_delta(snap)
        assert set(delta) == {"hits", "misses", "requests", "saved_s"}


class TestWarmStartCli:
    def test_cold_then_warm_zero_fresh_compiles(self, tmp_path, rng):
        """The acceptance loop, in-process: a cold run against a fresh
        --compile-cache seeds the manifest and journals fresh compiles;
        the warmed rerun journals warmup events, ZERO fresh compiles,
        and byte-identical output."""
        import jax

        clustered = _write(tmp_path, _workload(rng))
        cache = str(tmp_path / "cache")

        def run(tag):
            # drop the in-process jit cache: an earlier test in this
            # process may have compiled the same shape class, which
            # would silently absorb the cold run's compile request
            jax.clear_caches()
            journal = tmp_path / f"{tag}.jsonl"
            assert cli_main([
                "consensus", str(clustered), str(tmp_path / f"{tag}.mgf"),
                "--method", "bin-mean", "--layout", "flat",
                "--force-device", "--compile-cache", cache,
                "--journal", str(journal),
            ]) == 0
            return [
                json.loads(line)
                for line in journal.read_text().splitlines()
            ]

        cold = run("cold")
        end = [e for e in cold if e["event"] == "run_end"][-1]
        assert end["compile_cache"]["misses"] > 0
        cc = [e for e in cold if e["event"] == "compile_cache"]
        assert cc and cc[0]["enabled"] and cc[0]["dir"] == cache
        manifest = os.path.join(cache, "shape_manifest.json")
        assert os.path.exists(manifest)
        assert any(
            e.kernel == "bin_mean_flat_intensity"
            for e in load_manifest(manifest)
        )

        warm = run("warm")
        end = [e for e in warm if e["event"] == "run_end"][-1]
        assert end["compile_cache"]["misses"] == 0
        assert end["compile_cache"]["hits"] > 0
        warmed = [e for e in warm if e["event"] == "warmup"]
        assert warmed and all(e["cache_hit"] for e in warmed)
        assert (tmp_path / "cold.mgf").read_bytes() == (
            tmp_path / "warm.mgf"
        ).read_bytes()

    def test_warmup_command_smoke(self, tmp_path, rng):
        """`specpride warmup MANIFEST` pre-populates a FRESH cache so a
        first-ever workload run journals zero fresh compiles."""
        clustered = _write(tmp_path, _workload(rng))
        cache1 = str(tmp_path / "c1")
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "seed.mgf"),
            "--method", "bin-mean", "--layout", "flat", "--force-device",
            "--compile-cache", cache1,
        ]) == 0
        manifest = os.path.join(cache1, "shape_manifest.json")
        cache2 = str(tmp_path / "c2")
        wu_journal = tmp_path / "wu.jsonl"
        assert cli_main([
            "warmup", manifest, "--compile-cache", cache2,
            "--journal", str(wu_journal),
        ]) == 0
        events = [
            json.loads(line)
            for line in wu_journal.read_text().splitlines()
        ]
        assert [e for e in events if e["event"] == "warmup"]
        run_journal = tmp_path / "first.jsonl"
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "first.mgf"),
            "--method", "bin-mean", "--layout", "flat", "--force-device",
            "--compile-cache", cache2, "--warmup", "off",
            "--journal", str(run_journal),
        ]) == 0
        events = [
            json.loads(line)
            for line in run_journal.read_text().splitlines()
        ]
        end = [e for e in events if e["event"] == "run_end"][-1]
        assert end["compile_cache"]["misses"] == 0
        assert (tmp_path / "seed.mgf").read_bytes() == (
            tmp_path / "first.mgf"
        ).read_bytes()

    def test_warmup_manifest_mode_requires_manifest(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=2))
        with pytest.raises(SystemExit):
            cli_main([
                "consensus", str(clustered), str(tmp_path / "o.mgf"),
                "--method", "bin-mean",
                "--compile-cache", str(tmp_path / "empty-cache"),
                "--warmup", "manifest",
            ])

    def test_stats_renders_warmstart_line(self, tmp_path, rng, capsys):
        clustered = _write(tmp_path, _workload(rng, n=3))
        cache = str(tmp_path / "cache")
        for tag in ("a", "b"):
            assert cli_main([
                "consensus", str(clustered), str(tmp_path / f"{tag}.mgf"),
                "--method", "bin-mean", "--layout", "flat",
                "--force-device", "--compile-cache", cache,
                "--journal", str(tmp_path / f"{tag}.jsonl"),
            ]) == 0
        agg = tmp_path / "agg.json"
        assert cli_main([
            "stats", str(tmp_path / "b.jsonl"), "--json", str(agg),
        ]) == 0
        rendered = capsys.readouterr().out
        assert "warmstart:" in rendered
        assert "fresh_compiles=0" in rendered
        doc = json.loads(agg.read_text())
        ws = doc["runs"][0]["warmstart"]
        assert ws["fresh_compiles"] == 0 and ws["kernels_warmed"] >= 1
