"""End-to-end pipeline tests: mzML I/O, converter, metrics, viz, CLI."""

import dataclasses
import json
import os

import numpy as np
import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.convert import convert_mgf, convert_mzml
from specpride_tpu.data.peaks import Cluster, Spectrum, group_into_clusters
from specpride_tpu.io.mgf import read_mgf, write_mgf
from specpride_tpu.io.mzml import iter_mzml, read_mzml_scans, write_mzml
from specpride_tpu import metrics

from conftest import make_cluster, make_spectrum


@pytest.fixture
def raw_spectra(rng):
    """Raw (unclustered) spectra with scan-style titles."""
    out = []
    for scan in range(100, 110):
        s = make_spectrum(rng, n_peaks=30, scan=scan)
        s.title = f"run1.{scan}.{scan}.2 File:run1.raw scan={scan}"
        out.append((scan, s))
    return out


def write_inputs(tmp_path, raw_spectra):
    mgf = tmp_path / "raw.mgf"
    write_mgf([s for _, s in raw_spectra], mgf)
    # msms.txt: MaxQuant columns; col 1 = scan, col 7 = _PEPTIDE_
    msms = tmp_path / "msms.txt"
    header = [
        "Raw file", "Scan number", "c2", "c3", "c4", "c5", "c6",
        "Modified sequence", "Score",
    ]
    lines = ["\t".join(header)]
    for scan, _ in raw_spectra[:8]:  # last two scans have no ID
        lines.append(
            "\t".join(
                ["run1", str(scan), "x", "x", "x", "x", "x",
                 "_PEPTIDEK_", str(100.0 + scan)]
            )
        )
    msms.write_text("\n".join(lines) + "\n")
    # MaRaCluster TSV: two clusters of four scans each
    tsv = tmp_path / "clusters.tsv"
    rows = []
    for scan, _ in raw_spectra[:4]:
        rows.append(f"run1.raw\t{scan}\t0.9")
    rows.append("")
    for scan, _ in raw_spectra[4:8]:
        rows.append(f"run1.raw\t{scan}\t0.9")
    rows.append("")
    tsv.write_text("\n".join(rows))
    return mgf, msms, tsv


class TestMzml:
    def test_round_trip(self, tmp_path, rng):
        specs = [
            (100 + i, make_spectrum(rng, n_peaks=25, scan=100 + i), {})
            for i in range(5)
        ]
        path = tmp_path / "t.mzML"
        write_mzml(specs, path)
        back = read_mzml_scans(path)
        assert set(back) == {100, 101, 102, 103, 104}
        for scan, orig, _ in specs:
            got = back[scan]
            np.testing.assert_allclose(got.mz, orig.mz)
            np.testing.assert_allclose(got.intensity, orig.intensity)
            assert got.precursor_charge == orig.precursor_charge
            np.testing.assert_allclose(got.precursor_mz, orig.precursor_mz)
            np.testing.assert_allclose(got.rt, orig.rt)

    def test_scan_filter(self, tmp_path, rng):
        specs = [
            (200 + i, make_spectrum(rng, n_peaks=10, scan=200 + i), {})
            for i in range(4)
        ]
        path = tmp_path / "t.mzML"
        write_mzml(specs, path)
        got = read_mzml_scans(path, scans={201, 203})
        assert set(got) == {201, 203}

    def test_iter_yields_all(self, tmp_path, rng):
        specs = [(i, make_spectrum(rng, n_peaks=5, scan=i), {}) for i in (1, 2)]
        path = tmp_path / "t.mzML"
        write_mzml(specs, path)
        assert len(list(iter_mzml(path))) == 2

    def test_hostile_userparams_stay_valid_xml(self, tmp_path, rng):
        """Free text in userParams (peptide/cluster ids with &, <, quotes)
        must be escaped — the file stays well-formed and values round-trip
        exactly (advisor r1: unescaped interpolation)."""
        import xml.etree.ElementTree as ET

        hostile = {
            "Peptide sequence": 'PEP<T&IDE">K',
            'Cluster "accession"': "cluster-1;a&b<c>'d",
        }
        specs = [(7, make_spectrum(rng, n_peaks=5, scan=7), hostile)]
        path = tmp_path / "hostile.mzML"
        write_mzml(specs, path)
        tree = ET.parse(path)  # raises ParseError if escaping is broken
        ns = "{http://psi.hupo.org/ms/mzml}"
        got = {
            p.get("name"): p.get("value")
            for p in tree.iter(f"{ns}userParam")
        }
        assert got == hostile
        # the spectrum itself still reads back
        assert set(read_mzml_scans(path)) == {7}


class TestConvert:
    def test_convert_mgf(self, tmp_path, rng, raw_spectra):
        mgf, msms, tsv = write_inputs(tmp_path, raw_spectra)
        out = tmp_path / "clustered.mgf"
        n = convert_mgf(mgf, msms, tsv, out, "run1.raw")
        assert n == 8  # scans without peptide or cluster are dropped
        clusters = group_into_clusters(read_mgf(out))
        assert sorted(c.cluster_id for c in clusters) == ["cluster-1", "cluster-2"]
        assert all(c.n_members == 4 for c in clusters)
        # titles carry the USI with peptide interpretation
        s = clusters[0].members[0]
        assert s.usi.startswith("mzspec:PXD004732:run1.raw:scan:")
        assert s.usi.endswith("PEPTIDEK/2")

    def test_convert_mzml(self, tmp_path, rng, raw_spectra):
        _, msms, tsv = write_inputs(tmp_path, raw_spectra)
        mzml = tmp_path / "raw.mzML"
        write_mzml([(scan, s, {}) for scan, s in raw_spectra], mzml)
        out = tmp_path / "clustered.mgf"
        n = convert_mzml(mzml, msms, tsv, out, "run1.raw")
        assert n == 8
        clusters = group_into_clusters(read_mgf(out))
        assert len(clusters) == 2


class TestMetrics:
    def test_evaluate_and_report(self, tmp_path, rng):
        from specpride_tpu.backends import numpy_backend as nb

        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=40)
            for i in range(3)
        ]
        reps = nb.run_bin_mean(clusters)
        for backend in ("numpy", "tpu"):
            results = metrics.evaluate(reps, clusters, backend=backend)
            assert len(results) == 3
            assert all(0.0 <= r.avg_cosine <= 1.0 for r in results)
        summary = metrics.summarize(results)
        assert summary["n_clusters"] == 3
        report = tmp_path / "report.json"
        metrics.write_report(results, str(report))
        data = json.loads(report.read_text())
        assert len(data["clusters"]) == 3
        # CSV format, including a cluster id that needs quoting
        results[0] = dataclasses.replace(results[0], cluster_id='a,"b"')
        csv_path = tmp_path / "report.csv"
        metrics.write_report(results, str(csv_path), fmt="csv")
        import csv as _csv

        rows = list(_csv.reader(csv_path.open()))
        assert rows[0][0] == "cluster_id" and len(rows) == 4
        assert rows[1][0] == 'a,"b"'  # round-trips through quoting
        with pytest.raises(ValueError):
            metrics.write_report(results, str(csv_path), fmt="xml")

    def test_by_fraction_with_peptide(self, rng):
        c = make_cluster(rng, n_members=2, n_peaks=30)
        for s in c.members:
            s.title = s.title + ":PEPTIDEK/2"
        from specpride_tpu.backends import numpy_backend as nb

        reps = nb.run_medoid([c])
        results = metrics.evaluate(reps, [c], backend="numpy")
        assert results[0].by_fraction is not None
        assert 0.0 <= results[0].by_fraction <= 1.0


class TestViz:
    def test_mirror_plots(self, tmp_path, rng):
        from specpride_tpu.backends import numpy_backend as nb
        from specpride_tpu import viz

        c = make_cluster(rng, n_members=2, n_peaks=40)
        rep = nb.run_bin_mean([c])[0]
        paths = viz.plot_cluster_vs_consensus(
            c.members, rep, str(tmp_path / "mirror")
        )
        assert len(paths) == 2
        assert all(os.path.getsize(p) > 1000 for p in paths)
        paths = viz.plot_cluster_vs_theoretical(
            c.members[:1], "PEPTIDEK", 2, str(tmp_path / "theo")
        )
        assert os.path.getsize(paths[0]) > 1000

    def test_mirror_plot_labels_matched_ions(self):
        """Matched peaks carry b/y ion labels (the identity text the
        spectrum_utils plots the reference wraps show, ref
        src/plot_cluster.py:33-45)."""
        import numpy as np

        from specpride_tpu import viz
        from specpride_tpu.ops import fragments as fr

        peptide = "PEPTIDEK"
        theo = viz.theoretical_spectrum(peptide, 2)
        # a 'measured' spectrum sitting exactly on the fragment mzs
        spec = viz.Spectrum(
            mz=theo.mz, intensity=np.ones_like(theo.mz) * 50.0,
            precursor_mz=900.0, precursor_charge=2, title="m",
        )
        ax = viz.mirror_plot(spec, theo, annotate_peptide=peptide)
        labels = {t.get_text() for t in ax.texts}
        mzs, frag_labels = fr.fragment_annotations(peptide, "by", 1)
        assert labels  # annotations rendered
        assert labels & set(frag_labels)  # real ion names, e.g. b3/y5
        assert any(lab.startswith("b") for lab in labels)
        assert any(lab.startswith("y") for lab in labels)
        import matplotlib.pyplot as plt

        plt.close(ax.figure)

    def test_fragment_annotations_align_with_mzs(self):
        from specpride_tpu.ops import fragments as fr
        import numpy as np

        mzs, labels = fr.fragment_annotations("PEPTIDEK", "by", 2)
        np.testing.assert_allclose(
            mzs, fr.fragment_mzs("PEPTIDEK", "by", 2)
        )
        assert len(labels) == mzs.size
        # each label decodes back to the right mass
        residues, _ = fr.parse_peptide("PEPTIDEK")
        b3 = (
            sum(fr.RESIDUE_MASSES[r] for r in residues[:3]) + fr.PROTON_MASS
        )
        i = labels.index("b3")
        assert mzs[i] == pytest.approx(b3)


class TestCli:
    def test_full_pipeline(self, tmp_path, rng, raw_spectra):
        mgf, msms, tsv = write_inputs(tmp_path, raw_spectra)
        clustered = tmp_path / "clustered.mgf"
        assert cli_main([
            "convert", str(mgf), str(clustered),
            "--msms", str(msms), "--clusters", str(tsv), "--raw-name", "run1.raw",
        ]) == 0

        for method in ("bin-mean", "gap-average"):
            out = tmp_path / f"consensus_{method}.mgf"
            assert cli_main([
                "consensus", str(clustered), str(out), "--method", method,
                "--backend", "tpu",
            ]) == 0
            reps = read_mgf(out)
            assert len(reps) == 2

        out = tmp_path / "medoid.mgf"
        assert cli_main(["select", str(clustered), str(out),
                         "--method", "medoid"]) == 0
        assert len(read_mgf(out)) == 2

        out = tmp_path / "best.mgf"
        assert cli_main(["select", str(clustered), str(out), "--method", "best",
                         "--msms", str(msms)]) == 0
        assert len(read_mgf(out)) == 2

        report = tmp_path / "report.json"
        assert cli_main([
            "evaluate", str(tmp_path / "consensus_bin-mean.mgf"),
            str(clustered), "--report", str(report),
        ]) == 0
        assert json.loads(report.read_text())["summary"]["n_clusters"] == 2

        assert cli_main([
            "plot", str(clustered), "cluster-1", str(tmp_path / "p"),
            "--consensus", str(tmp_path / "consensus_bin-mean.mgf"),
        ]) == 0

    def test_single_mode(self, tmp_path, rng):
        """--single merges the whole file as ONE cluster, titled with the
        output name (ref average_spectrum_clustering.py:172-176,203-205)."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=20)
            for i in range(3)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out = tmp_path / "single.mgf"
        assert cli_main([
            "consensus", str(clustered), str(out),
            "--method", "gap-average", "--single", "--backend", "numpy",
        ]) == 0
        reps = read_mgf(out)
        assert len(reps) == 1
        assert reps[0].title == str(out)
        # matches merging all six spectra as one cluster directly
        from specpride_tpu.backends import numpy_backend as nb

        spectra = [s for c in clusters for s in c.members]
        oracle = nb.run_gap_average([Cluster(str(out), spectra)])[0]
        np.testing.assert_allclose(reps[0].mz, oracle.mz)
        np.testing.assert_allclose(reps[0].intensity, oracle.intensity)

    def test_append_flag(self, tmp_path, rng):
        cluster = make_cluster(rng, "cluster-0", n_members=2, n_peaks=15)
        clustered = tmp_path / "clustered.mgf"
        write_mgf(cluster.members, clustered)
        out = tmp_path / "out.mgf"
        for _ in range(2):
            assert cli_main([
                "consensus", str(clustered), str(out),
                "--append", "--backend", "numpy",
            ]) == 0
        assert len(read_mgf(out)) == 2  # appended, not replaced
        assert cli_main([
            "consensus", str(clustered), str(out), "--backend", "numpy",
        ]) == 0
        assert len(read_mgf(out)) == 1  # default mode replaces

    def test_select_best_percolator_scores(self, tmp_path, rng, raw_spectra):
        mgf, msms, tsv = write_inputs(tmp_path, raw_spectra)
        clustered = tmp_path / "clustered.mgf"
        assert cli_main([
            "convert", str(mgf), str(clustered),
            "--msms", str(msms), "--clusters", str(tsv),
            "--raw-name", "run1.raw",
        ]) == 0
        # percolator TSV: scans 100-107, scan 103 / 107 score highest
        psms = tmp_path / "perc.target.psms.txt"
        rows = ["file\tscan\tcharge\tpercolator score\tsequence"]
        for scan in range(100, 108):
            score = 9.0 if scan in (103, 107) else 1.0
            rows.append(f"data/run1.mzML\t{scan}\t2\t{score}\tPEPTIDEK")
        psms.write_text("\n".join(rows) + "\n")
        out = tmp_path / "best.mgf"
        assert cli_main([
            "select", str(clustered), str(out), "--method", "best",
            "--psms", str(psms),
        ]) == 0
        reps = read_mgf(out)
        assert sorted(s.usi.split(":scan:")[1].split(":")[0] for s in reps) \
            == ["103", "107"]
        # explicit --raw-name already carrying the extension joins the same
        out2 = tmp_path / "best2.mgf"
        assert cli_main([
            "select", str(clustered), str(out2), "--method", "best",
            "--psms", str(psms), "--raw-name", "run1.raw",
        ]) == 0
        assert [s.title for s in read_mgf(out2)] == [s.title for s in reps]

    def test_layout_bucketized_escape_hatch(self, tmp_path, rng):
        """--layout bucketized forces the (B, K) device paths mesh-less —
        the escape hatch if a flat path regresses (VERDICT r3 weak #6);
        output must match the default layout."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25)
            for i in range(4)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out_a = tmp_path / "flat.mgf"
        out_b = tmp_path / "bucketized.mgf"
        assert cli_main(["consensus", str(clustered), str(out_a)]) == 0
        assert cli_main([
            "consensus", str(clustered), str(out_b), "--layout", "bucketized",
        ]) == 0
        a, b = read_mgf(out_a), read_mgf(out_b)
        assert [s.title for s in a] == [s.title for s in b]
        for x, y in zip(a, b):
            np.testing.assert_allclose(x.mz, y.mz, rtol=1e-5, atol=1e-3)
            np.testing.assert_allclose(
                x.intensity, y.intensity, rtol=1e-4, atol=1e-2
            )

    def test_merge_parts(self, tmp_path, rng):
        """merge-parts concatenates block-sharded multi-host outputs in
        part order == cluster order."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=10)
            for i in range(5)
        ]
        from specpride_tpu.backends import numpy_backend as nb

        reps = nb.run_bin_mean(clusters)
        out = tmp_path / "out.mgf"
        write_mgf(reps[:2], f"{out}.part00000")
        write_mgf(reps[2:4], f"{out}.part00001")
        write_mgf(reps[4:], f"{out}.part00002")
        assert cli_main(["merge-parts", str(out), "--remove-parts"]) == 0
        assert [s.title for s in read_mgf(out)] == [
            c.cluster_id for c in clusters
        ]
        assert not list(tmp_path.glob("out.mgf.part*"))
        # nothing to merge -> error
        assert cli_main(["merge-parts", str(tmp_path / "none.mgf")]) == 1
        # a GAP in the rank sequence (a rank never finished) -> refuse
        out2 = tmp_path / "gapped.mgf"
        write_mgf(reps[:2], f"{out2}.part00000")
        write_mgf(reps[2:4], f"{out2}.part00002")
        assert cli_main(["merge-parts", str(out2)]) == 1
        assert not out2.exists() or out2.stat().st_size == 0
        # short-but-contiguous set caught via --num-processes
        out3 = tmp_path / "short.mgf"
        write_mgf(reps[:2], f"{out3}.part00000")
        assert cli_main([
            "merge-parts", str(out3), "--num-processes", "3",
        ]) == 1

    @pytest.mark.parametrize("method,backend", [
        ("bin-mean", "tpu"), ("bin-mean", "numpy"), ("gap-average", "tpu"),
    ])
    def test_consensus_qc_report(self, tmp_path, rng, method, backend):
        """--qc-report computes each representative's mean member cosine in
        the same run (fused with the consensus dispatch on the device
        bin-mean path) and must match `evaluate` on the written reps."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25)
            for i in range(5)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out = tmp_path / "reps.mgf"
        qc = tmp_path / "qc.json"
        assert cli_main([
            "consensus", str(clustered), str(out), "--method", method,
            "--backend", backend, "--qc-report", str(qc),
        ]) == 0
        report = json.loads(qc.read_text())
        assert [r["cluster_id"] for r in report["clusters"]] == [
            c.cluster_id for c in clusters
        ]
        # cross-check against the evaluate flow (numpy oracle cosines)
        from specpride_tpu.backends import numpy_backend as nb

        reps = read_mgf(out)
        want = [
            nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)
        ]
        got = [r["avg_cosine"] for r in report["clusters"]]
        np.testing.assert_allclose(got, want, rtol=5e-5, atol=1e-5)
        assert report["summary"]["n_clusters"] == 5
        assert 0 < report["summary"]["mean_cosine"] <= 1.0

    def test_empty_input_writes_empty_output(self, tmp_path):
        """Zero clusters still produce an (empty) output file, so
        downstream steps see a result instead of ENOENT."""
        clustered = tmp_path / "empty.mgf"
        clustered.write_text("")
        out = tmp_path / "out.mgf"
        assert cli_main([
            "consensus", str(clustered), str(out), "--backend", "numpy",
        ]) == 0
        assert out.exists() and out.stat().st_size == 0
        assert read_mgf(out) == []
        # --append on a fresh path also creates the file ('a' mode)
        out2 = tmp_path / "out2.mgf"
        assert cli_main([
            "consensus", str(clustered), str(out2), "--backend", "numpy",
            "--append",
        ]) == 0
        assert out2.exists() and out2.stat().st_size == 0

    def test_select_medoid_qc_report(self, tmp_path, rng):
        """select --qc-report: the medoid's mean member cosine per cluster
        (a medoid IS a member, so cosines are high for tight clusters)."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25,
                         jitter=0.001)
            for i in range(4)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out, qc = tmp_path / "med.mgf", tmp_path / "qc.json"
        assert cli_main([
            "select", str(clustered), str(out), "--method", "medoid",
            "--qc-report", str(qc),
        ]) == 0
        report = json.loads(qc.read_text())
        assert report["summary"]["n_clusters"] == 4
        assert all(0 < r["avg_cosine"] <= 1.0 for r in report["clusters"])

    def test_select_best_qc_report_skips_scoreless(self, tmp_path, rng,
                                                   raw_spectra):
        """select --method best --qc-report: scoreless clusters are DROPPED
        by the method (ref src/best_spectrum.py:170-174), so the QC report
        covers exactly the produced representatives — no phantom rows, no
        re-parse of the output hunting for them."""
        mgf, msms, tsv = write_inputs(tmp_path, raw_spectra)
        clustered = tmp_path / "clustered.mgf"
        assert cli_main([
            "convert", str(mgf), str(clustered),
            "--msms", str(msms), "--clusters", str(tsv),
            "--raw-name", "run1.raw",
        ]) == 0
        # msms scores cover only SOME scans: drop rows for cluster 2's
        # scans so that cluster is scoreless
        lines = msms.read_text().splitlines()
        kept = [lines[0]] + [
            ln for ln in lines[1:] if ln.split("\t")[1] in
            {"100", "101", "102", "103"}
        ]
        msms.write_text("\n".join(kept) + "\n")
        out, qc = tmp_path / "best.mgf", tmp_path / "qc.json"
        assert cli_main([
            "select", str(clustered), str(out), "--method", "best",
            "--backend", "numpy", "--msms", str(msms), "--qc-report", str(qc),
        ]) == 0
        reps = read_mgf(out)
        report = json.loads(qc.read_text())
        assert len(report["clusters"]) == len(reps) >= 1
        assert {r["cluster_id"] for r in report["clusters"]} == {
            s.cluster_id for s in reps
        }

    def test_qc_report_complete_after_resume(self, tmp_path, rng):
        """A resumed --qc-report run must still cover EVERY cluster: the
        manifest skips done clusters, so their cosines are recomputed from
        the reps already in the output (advisor r4: a silent half-report)."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=20)
            for i in range(6)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out, ckpt, qc = (
            tmp_path / "o.mgf", tmp_path / "ck.json", tmp_path / "qc.json"
        )
        # simulate a crash after 4 clusters: run them, keep the manifest
        from specpride_tpu.backends import numpy_backend as nb

        write_mgf(nb.run_bin_mean(clusters[:4]), out)
        ckpt.write_text(json.dumps({
            "done": [c.cluster_id for c in clusters[:4]],
            "output_bytes": out.stat().st_size,
        }))
        assert cli_main([
            "consensus", str(clustered), str(out), "--backend", "numpy",
            "--checkpoint", str(ckpt), "--qc-report", str(qc),
        ]) == 0
        report = json.loads(qc.read_text())
        assert report["summary"]["n_clusters"] == 6
        assert [r["cluster_id"] for r in report["clusters"]] == [
            c.cluster_id for c in clusters
        ]

    def test_on_error_skip_isolates_bad_clusters(self, tmp_path, rng):
        """--on-error skip retries a failing chunk cluster-by-cluster and
        drops only the offenders, logged and recorded in the manifest
        (survey §5 failure detection; default remains abort)."""
        good = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=15)
            for i in range(3)
        ]
        # mixed charge states make bin-mean raise for this cluster
        bad = make_cluster(rng, "cluster-bad", n_members=2, n_peaks=15)
        bad.members[1].precursor_charge = bad.members[0].precursor_charge + 1
        clusters = good[:2] + [bad] + good[2:]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out = tmp_path / "out.mgf"
        ckpt = tmp_path / "ckpt.json"
        # default: abort
        with pytest.raises(ValueError):
            cli_main([
                "consensus", str(clustered), str(tmp_path / "x.mgf"),
                "--backend", "numpy",
            ])
        # skip: the three good clusters come through, failure recorded
        assert cli_main([
            "consensus", str(clustered), str(out), "--backend", "numpy",
            "--on-error", "skip", "--checkpoint", str(ckpt),
            "--checkpoint-every", "2",
        ]) == 0
        assert sorted(s.title for s in read_mgf(out)) == sorted(
            c.cluster_id for c in good
        )
        assert json.loads(ckpt.read_text())["failed"] == ["cluster-bad"]
        # a resume must not erase the failure record (advisor r4)
        assert cli_main([
            "consensus", str(clustered), str(out), "--backend", "numpy",
            "--on-error", "skip", "--checkpoint", str(ckpt),
            "--checkpoint-every", "2",
        ]) == 0
        assert json.loads(ckpt.read_text())["failed"] == ["cluster-bad"]

    def test_on_error_skip_flags_failures_in_qc_report(self, tmp_path, rng):
        """Missing QC rows must be machine-readably attributed: the report
        summary distinguishes method-failed clusters from QC failures
        instead of just shrinking n_clusters (advisor r4)."""
        good = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=15)
            for i in range(2)
        ]
        bad = make_cluster(rng, "cluster-bad", n_members=2, n_peaks=15)
        bad.members[1].precursor_charge = bad.members[0].precursor_charge + 1
        clustered = tmp_path / "clustered.mgf"
        write_mgf(
            [s for c in good[:1] + [bad] + good[1:] for s in c.members],
            clustered,
        )
        report_path = tmp_path / "qc.json"
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "out.mgf"),
            "--backend", "numpy", "--on-error", "skip",
            "--qc-report", str(report_path),
        ]) == 0
        summary = json.loads(report_path.read_text())["summary"]
        assert summary["n_clusters"] == 2
        assert summary["n_input_clusters"] == 3
        assert summary["n_method_failed"] == 1
        assert summary["method_failed_cluster_ids"] == ["cluster-bad"]
        assert summary["n_qc_failed"] == 0

    def test_select_best_requires_score_source(self, tmp_path, rng):
        cluster = make_cluster(rng, "cluster-0", n_members=2, n_peaks=15)
        clustered = tmp_path / "clustered.mgf"
        write_mgf(cluster.members, clustered)
        with pytest.raises(SystemExit):
            cli_main([
                "select", str(clustered), str(tmp_path / "o.mgf"),
                "--method", "best",
            ])

    def test_checkpoint_resume(self, tmp_path, rng):
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=30)
            for i in range(6)
        ]
        spectra = [s for c in clusters for s in c.members]
        clustered = tmp_path / "clustered.mgf"
        write_mgf(spectra, clustered)
        out = tmp_path / "out.mgf"
        ckpt = tmp_path / "ckpt.json"
        assert cli_main([
            "consensus", str(clustered), str(out),
            "--checkpoint", str(ckpt), "--checkpoint-every", "2",
        ]) == 0
        assert len(read_mgf(out)) == 6
        done = json.loads(ckpt.read_text())["done"]
        assert len(done) == 6
        # resume: nothing new is appended
        assert cli_main([
            "consensus", str(clustered), str(out),
            "--checkpoint", str(ckpt), "--checkpoint-every", "2",
        ]) == 0
        assert len(read_mgf(out)) == 6

    def test_crash_between_write_and_manifest_no_duplicates(
        self, tmp_path, rng
    ):
        """A crash after a chunk's output append but before its manifest
        update must not duplicate the chunk on resume: the manifest's
        recorded output_bytes truncates the orphaned tail (advisor r1)."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=20)
            for i in range(4)
        ]
        spectra = [s for c in clusters for s in c.members]
        clustered = tmp_path / "clustered.mgf"
        write_mgf(spectra, clustered)

        # clean full run = the expected final output
        golden = tmp_path / "golden.mgf"
        assert cli_main([
            "consensus", str(clustered), str(golden),
            "--checkpoint", str(tmp_path / "g.json"), "--checkpoint-every", "2",
        ]) == 0
        golden_bytes = golden.read_bytes()

        # crashed state: chunk 1 (clusters 0-1) committed in the manifest,
        # chunk 2's bytes already appended to the output but NOT recorded
        from specpride_tpu.backends import numpy_backend as nb

        out = tmp_path / "out.mgf"
        write_mgf(nb.run_bin_mean(clusters[:2]), out)
        committed = out.stat().st_size
        write_mgf(nb.run_bin_mean(clusters[2:]), out, append=True)
        ckpt = tmp_path / "ckpt.json"
        ckpt.write_text(json.dumps(
            {"done": ["cluster-0", "cluster-1"], "output_bytes": committed}
        ))

        assert cli_main([
            "consensus", str(clustered), str(out),
            "--checkpoint", str(ckpt), "--checkpoint-every", "2",
        ]) == 0
        reps = read_mgf(out)
        assert [s.title for s in reps] == [c.cluster_id for c in clusters]
        assert out.read_bytes() == golden_bytes

    def test_checkpoint_output_shorter_than_manifest_restarts(
        self, tmp_path, rng
    ):
        """Power-cut ordering can persist the manifest but lose the
        un-fsynced output append; trusting the manifest would silently
        drop the done-listed clusters, so the run restarts."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=20)
            for i in range(2)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out = tmp_path / "out.mgf"
        out.write_text("BEGIN IONS\n")  # truncated remnant
        ckpt = tmp_path / "ckpt.json"
        ckpt.write_text(json.dumps(
            {"done": ["cluster-0"], "output_bytes": 10_000}
        ))
        assert cli_main([
            "consensus", str(clustered), str(out),
            "--checkpoint", str(ckpt), "--checkpoint-every", "2",
        ]) == 0
        assert [s.title for s in read_mgf(out)] == ["cluster-0", "cluster-1"]

    def test_corrupt_resume_with_append_refuses(self, tmp_path, rng):
        """--append + an unusable resume state must refuse rather than
        re-append on top of partial output (advisor r3: the redo would
        duplicate records because pre-existing appended content can't be
        told apart from this run's)."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=20)
            for i in range(2)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out = tmp_path / "out.mgf"
        out.write_text("BEGIN IONS\n")  # truncated remnant
        ckpt = tmp_path / "ckpt.json"
        ckpt.write_text(json.dumps(
            {"done": ["cluster-0"], "output_bytes": 10_000}
        ))
        with pytest.raises(SystemExit, match="append"):
            cli_main([
                "consensus", str(clustered), str(out), "--append",
                "--checkpoint", str(ckpt), "--checkpoint-every", "2",
            ])
        # the corrupt remnant was not appended to
        assert out.read_text() == "BEGIN IONS\n"

    def test_checkpoint_output_deleted_restarts(self, tmp_path, rng):
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=20)
            for i in range(2)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out = tmp_path / "out.mgf"
        ckpt = tmp_path / "ckpt.json"
        ckpt.write_text(json.dumps(
            {"done": ["cluster-0", "cluster-1"], "output_bytes": 123}
        ))
        # output is gone: the stale manifest must not mask the loss
        assert cli_main([
            "consensus", str(clustered), str(out),
            "--checkpoint", str(ckpt), "--checkpoint-every", "2",
        ]) == 0
        assert len(read_mgf(out)) == 2

    def test_partial_checkpoint_resumes(self, tmp_path, rng):
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=20)
            for i in range(4)
        ]
        spectra = [s for c in clusters for s in c.members]
        clustered = tmp_path / "clustered.mgf"
        write_mgf(spectra, clustered)
        out = tmp_path / "out.mgf"
        ckpt = tmp_path / "ckpt.json"
        # simulate an interrupted run: two clusters already done
        ckpt.write_text(json.dumps({"done": ["cluster-0", "cluster-1"]}))
        from specpride_tpu.backends import numpy_backend as nb

        write_mgf(nb.run_bin_mean(clusters[:2]), out)
        assert cli_main([
            "consensus", str(clustered), str(out),
            "--checkpoint", str(ckpt), "--checkpoint-every", "2",
        ]) == 0
        reps = read_mgf(out)
        assert [s.title for s in reps] == [c.cluster_id for c in clusters]


class TestStreamingIngest:
    def test_streamed_consensus_matches_eager(self, tmp_path, rng):
        """--stream-clusters N produces byte-identical output to eager
        ingest (same cluster order, same chunking semantics via the
        window), with bounded memory."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25)
            for i in range(11)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        eager_out = tmp_path / "eager.mgf"
        stream_out = tmp_path / "stream.mgf"
        assert cli_main([
            "consensus", str(clustered), str(eager_out),
            "--backend", "numpy", "--stream-clusters", "off",
        ]) == 0
        assert cli_main([
            "consensus", str(clustered), str(stream_out),
            "--backend", "numpy", "--stream-clusters", "4",
        ]) == 0
        assert eager_out.read_bytes() == stream_out.read_bytes()

    def test_streamed_resume_and_qc(self, tmp_path, rng):
        """Streaming composes with checkpoint/resume and the QC report:
        a resumed streamed run recomputes QC for done clusters without
        loading the file whole."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=2, n_peaks=20)
            for i in range(8)
        ]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        out = tmp_path / "out.mgf"
        ckpt = tmp_path / "ckpt.json"
        qc = tmp_path / "qc.json"
        assert cli_main([
            "consensus", str(clustered), str(out), "--backend", "numpy",
            "--stream-clusters", "3", "--checkpoint", str(ckpt),
            "--checkpoint-every", "3",
        ]) == 0
        # resume over a finished run: everything skipped, QC recomputed
        assert cli_main([
            "consensus", str(clustered), str(out), "--backend", "numpy",
            "--stream-clusters", "3", "--checkpoint", str(ckpt),
            "--checkpoint-every", "3", "--qc-report", str(qc),
        ]) == 0
        report = json.loads(qc.read_text())
        assert report["summary"]["n_clusters"] == 8
        assert [r["cluster_id"] for r in report["clusters"]] == [
            c.cluster_id for c in clusters
        ]


class TestDirectMzml:
    """Direct mzML + MaRaCluster workflows (ref src/binning.py:33-118 and
    src/plot_cluster.py:50-86 need no pre-conversion step)."""

    def test_consensus_direct_equals_convert_route(self, tmp_path, rng,
                                                   raw_spectra):
        _, msms, tsv = write_inputs(tmp_path, raw_spectra)
        mzml = tmp_path / "raw.mzML"
        write_mzml([(scan, s, {}) for scan, s in raw_spectra], mzml)
        # route A: convert -> consensus
        clustered = tmp_path / "clustered.mgf"
        assert cli_main([
            "convert", str(mzml), str(clustered), "--msms", str(msms),
            "--clusters", str(tsv), "--raw-name", "raw",
        ]) == 0
        out_a = tmp_path / "a.mgf"
        assert cli_main([
            "consensus", str(clustered), str(out_a), "--backend", "numpy",
        ]) == 0
        # route B: direct mzML
        out_b = tmp_path / "b.mgf"
        assert cli_main([
            "consensus", str(mzml), str(out_b), "--backend", "numpy",
            "--clusters", str(tsv), "--msms", str(msms),
            "--raw-name", "raw",
        ]) == 0
        assert out_a.read_bytes() == out_b.read_bytes()

    def test_consensus_direct_without_msms(self, tmp_path, rng, raw_spectra):
        """The reference's C1 needs no peptide IDs — neither do we."""
        _, _, tsv = write_inputs(tmp_path, raw_spectra)
        mzml = tmp_path / "raw.mzML"
        write_mzml([(scan, s, {}) for scan, s in raw_spectra], mzml)
        out = tmp_path / "o.mgf"
        assert cli_main([
            "consensus", str(mzml), str(out), "--backend", "numpy",
            "--clusters", str(tsv),
        ]) == 0
        reps = read_mgf(out)
        assert sorted(s.cluster_id for s in reps) == [
            "cluster-1", "cluster-2",
        ]

    def test_consensus_mzml_requires_clusters(self, tmp_path, rng,
                                              raw_spectra):
        mzml = tmp_path / "raw.mzML"
        write_mzml([(scan, s, {}) for scan, s in raw_spectra], mzml)
        with pytest.raises(SystemExit, match="clusters"):
            cli_main([
                "consensus", str(mzml), str(tmp_path / "o.mgf"),
                "--backend", "numpy",
            ])

    def test_select_direct_mzml(self, tmp_path, rng, raw_spectra):
        _, msms, tsv = write_inputs(tmp_path, raw_spectra)
        mzml = tmp_path / "raw.mzML"
        write_mzml([(scan, s, {}) for scan, s in raw_spectra], mzml)
        out = tmp_path / "sel.mgf"
        assert cli_main([
            "select", str(mzml), str(out), "--method", "medoid",
            "--backend", "numpy", "--clusters", str(tsv),
        ]) == 0
        assert len(read_mgf(out)) == 2

    def test_plot_direct_mzml(self, tmp_path, rng, raw_spectra):
        _, msms, tsv = write_inputs(tmp_path, raw_spectra)
        mzml = tmp_path / "raw.mzML"
        write_mzml([(scan, s, {}) for scan, s in raw_spectra], mzml)
        assert cli_main([
            "plot", str(mzml), "cluster-1", str(tmp_path / "m"),
            "--clusters", str(tsv), "--msms", str(msms),
        ]) == 0
        assert os.path.getsize(f"{tmp_path}/m_0.png") > 1000


def test_invalid_ppm_options_fail_fast(tmp_path, rng):
    """Bad grid options are a usage error before any cluster runs — not a
    deep ZeroDivisionError, and never misattributed to clusters under
    --on-error skip (advisor r5)."""
    c = make_cluster(rng, "cluster-0", n_members=2, n_peaks=10)
    clustered = tmp_path / "c.mgf"
    write_mgf(c.members, clustered)
    for extra in (["--tolerance-mode", "ppm", "--ppm", "0"],
                  ["--tolerance-mode", "ppm", "--min-mz", "0"],
                  ["--bin-size", "0"]):
        with pytest.raises(SystemExit, match="invalid bin-mean"):
            cli_main([
                "consensus", str(clustered), str(tmp_path / "o.mgf"),
                "--backend", "numpy", "--on-error", "skip", *extra,
            ])


def test_exploration_notebook_executes(tmp_path, monkeypatch):
    """The C9 exploratory notebook (notebooks/exploration.ipynb) must stay
    runnable: execute its code cells top to bottom in one namespace (the
    first cell's sys.path insert is replaced by the test environment)."""
    nb_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "notebooks", "exploration.ipynb",
    )
    with open(nb_path) as fh:
        nb = json.load(fh)
    monkeypatch.chdir(tmp_path)  # notebook writes scratch files to cwd
    ns: dict = {}
    for cell in nb["cells"]:
        if cell["cell_type"] != "code":
            continue
        exec("".join(cell["source"]), ns)  # noqa: S102 - our own notebook
    assert os.path.exists(tmp_path / "exploration_reps.mgf")
    assert os.path.exists(tmp_path / "exploration_mirror.png")
