"""Fragment theory tests (capability of spectrum_utils/pyteomics consumed at
ref src/benchmark.py:40-61 and src/plot_cluster.py:36-41)."""

import numpy as np
import pytest

from specpride_tpu.ops import fragments as fr


def test_proton_mass():
    # pyteomics nist_mass['H+'][0][0] (ref src/average_spectrum_clustering.py:6)
    assert fr.PROTON_MASS == pytest.approx(1.00727646677, abs=1e-9)


def test_peptide_mass_known_value():
    # glycine: residue + water = 75.032...
    assert fr.peptide_mass("G") == pytest.approx(75.03203, abs=1e-3)
    # angiotensin fragment DRVYIHPF monoisotopic mass ≈ 1045.534
    assert fr.peptide_mass("DRVYIHPF") == pytest.approx(1045.534, abs=5e-3)


def test_fragment_count():
    frags = fr.fragment_mzs("PEPTIDE", "by", max_charge=1)
    # 6 b-ions + 6 y-ions
    assert frags.size == 12
    frags2 = fr.fragment_mzs("PEPTIDE", "by", max_charge=2)
    assert frags2.size == 24


def test_by_complementarity():
    # b_k + y_{n-k} = peptide mass + 2 protons (singly charged ions)
    seq = "VLHPLEGAVVIIFK"
    residues, deltas = fr.parse_peptide(seq)
    masses = np.array([fr.RESIDUE_MASSES[r] + d for r, d in zip(residues, deltas)])
    b = np.cumsum(masses)[:-1] + fr.PROTON_MASS
    y = np.cumsum(masses[::-1])[:-1] + fr.WATER_MASS + fr.PROTON_MASS
    total = fr.peptide_mass(seq)
    np.testing.assert_allclose(b + y[::-1], total + 2 * fr.PROTON_MASS, rtol=1e-9)


def test_modified_peptide():
    plain = fr.peptide_mass("PEPTMIDE")
    ox = fr.peptide_mass("PEPTM(ox)IDE")
    assert ox - plain == pytest.approx(15.9949, abs=1e-3)


def test_parse_maxquant_flanks():
    residues, _ = fr.parse_peptide("_PEPTIDE_")
    assert "".join(residues) == "PEPTIDE"


def test_parse_maxquant_nested_mod():
    # modern MaxQuant dialect: _M(Oxidation (M))PEPTIDEK_
    residues, deltas = fr.parse_peptide("_M(Oxidation (M))PEPTIDEK_")
    assert "".join(residues) == "MPEPTIDEK"
    assert deltas[0] == pytest.approx(15.9949, abs=1e-3)


def test_parse_nterm_mod():
    residues, deltas = fr.parse_peptide("(ac)PEPTIDEK")
    assert "".join(residues) == "PEPTIDEK"
    assert deltas[0] == pytest.approx(42.0106, abs=1e-3)


def test_fraction_of_by_hostile_sequences_score_zero():
    mz, inten = np.array([200.0]), np.array([1.0])
    # unknown mod, unbalanced parens, single residue: score 0, never raise
    assert fr.fraction_of_by("P(weird)EP", 500.0, 2, mz, inten) == 0.0
    assert fr.fraction_of_by("P(EP", 500.0, 2, mz, inten) == 0.0
    assert fr.fraction_of_by("K", 500.0, 2, mz, inten) == 0.0
    assert fr.fraction_of_by("(ac)PEPTIDEK", 500.0, 2, mz, inten) >= 0.0


def test_is_valid():
    assert fr.is_valid_peptide("PEPTIDE")
    assert not fr.is_valid_peptide("PEPT1DE")
    assert not fr.is_valid_peptide("")


def test_match_fragments_window():
    frags = np.array([200.0, 500.0])
    mz = np.array([200.0 + 200.0 * 40e-6, 200.0 + 200.0 * 60e-6, 499.9])
    hit = fr.match_fragments(mz, frags, tol=50.0, tol_mode="ppm")
    assert hit.tolist() == [True, False, False]


def test_fraction_of_by_perfect_and_noise():
    seq = "VLHPLEGAVVIIFK"
    frags = fr.fragment_mzs(seq, "by", max_charge=1)
    frags = frags[(frags > 100) & (frags < 1400)]
    inten = np.ones_like(frags)
    f = fr.fraction_of_by(seq, 779.48, 2, frags, inten)
    assert f == pytest.approx(1.0)
    # peaks far from any fragment annotate nothing
    noise = frags + 5.0
    f0 = fr.fraction_of_by(seq, 779.48, 2, noise, np.ones_like(noise))
    assert f0 < 0.2


def test_fraction_of_by_invalid_sequence():
    assert fr.fraction_of_by("XX1", 500.0, 2, np.array([100.0]), np.array([1.0])) == 0.0


def test_fraction_of_by_precursor_removed():
    seq = "PEPTIDEK"
    pmz = (fr.peptide_mass(seq) + 2 * fr.PROTON_MASS) / 2
    mz = np.array([pmz])  # only the precursor peak, removed in preprocessing
    assert fr.fraction_of_by(seq, pmz, 2, mz, np.array([100.0])) == 0.0


def test_fraction_of_by_batch_matches_scalar():
    """The batched form must equal per-call fraction_of_by bit for bit
    (it shares the window-match body; only the fragment-table build is
    cached), with NaN marking absent peptides."""
    rng = np.random.default_rng(3)
    seqs = ["VLHPLEGAVVIIFK", "PEPTIDEK", None, "XX1", "PEPTIDEK"]
    pmz = np.array([779.48, 450.2, 300.0, 500.0, 451.0])
    pz = np.array([2, 2, 2, 2, 3])
    mzs = [np.sort(rng.uniform(100, 1300, 80)) for _ in seqs]
    ints = [rng.uniform(1, 100, 80) for _ in seqs]
    batch = fr.fraction_of_by_batch(seqs, pmz, pz, mzs, ints)
    for i, s in enumerate(seqs):
        if s is None:
            assert np.isnan(batch[i])
        else:
            assert batch[i] == fr.fraction_of_by(
                s, float(pmz[i]), int(pz[i]), mzs[i], ints[i]
            )
