"""Multi-device sharding tests on the virtual 8-device CPU mesh
(survey §4d — the standard JAX idiom for testing pod sharding without TPU)."""

import os

import numpy as np
import jax

from specpride_tpu.backends import numpy_backend as nb
from specpride_tpu.backends.tpu_backend import TpuBackend
from specpride_tpu.parallel import cluster_mesh, cluster_sharding

from conftest import make_cluster
from test_tpu_parity import assert_spectra_close, random_clusters


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_bin_mean_matches_oracle(rng):
    mesh = cluster_mesh()
    assert mesh.size == 8
    backend = TpuBackend(mesh=mesh)
    clusters = random_clusters(rng, n=13)  # deliberately not divisible by 8
    oracle = nb.run_bin_mean(clusters)
    device = backend.run_bin_mean(clusters)
    assert len(oracle) == len(device)
    for o, d in zip(oracle, device):
        assert_spectra_close(o, d)


def test_sharded_gap_average_matches_oracle(rng):
    # force_device: on this CPU-only test mesh the backend would
    # otherwise route gap-average to the host path (the kernel under
    # test would silently stop running)
    backend = TpuBackend(mesh=cluster_mesh(), force_device=True)
    from test_tpu_parity import make_gap_safe_cluster

    clusters = [
        make_gap_safe_cluster(rng, f"cluster-{i}", n_members=3) for i in range(5)
    ]
    oracle = nb.run_gap_average(clusters)
    device = backend.run_gap_average(clusters)
    for o, d in zip(oracle, device):
        assert o.n_peaks == d.n_peaks
        np.testing.assert_allclose(o.mz, d.mz, rtol=1e-5, atol=1e-3)


def test_sharded_medoid_matches_oracle(rng):
    backend = TpuBackend(mesh=cluster_mesh())
    clusters = random_clusters(rng, n=9)
    assert backend.medoid_indices(clusters) == [
        nb.medoid_index(c.members) for c in clusters
    ]


def test_sharded_cosines_match_oracle(rng):
    backend = TpuBackend(mesh=cluster_mesh())
    clusters = random_clusters(rng, n=6)
    reps = nb.run_bin_mean(clusters)
    oracle = [nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)]
    device = backend.average_cosines(reps, clusters)
    np.testing.assert_allclose(oracle, device, rtol=5e-5, atol=1e-5)


def test_input_sharding_is_applied(rng):
    """The dispatched arrays really live split over the cluster axis."""
    mesh = cluster_mesh()
    x = np.zeros((16, 4, 8), np.float32)
    from specpride_tpu.parallel.mesh import shard_batch_arrays

    (sx,) = shard_batch_arrays(mesh, x)
    assert sx.sharding == cluster_sharding(mesh, 3)
    # each device holds 16/8 = 2 clusters
    shard_shapes = {s.data.shape for s in sx.addressable_shards}
    assert shard_shapes == {(2, 4, 8)}


def test_initialize_distributed_guard(monkeypatch):
    """The already-initialized probe must go through
    jax.distributed.is_initialized — NOT jax.process_count(), which spins
    up the local backend and makes a subsequent real
    jax.distributed.initialize illegal (advisor r1)."""
    from specpride_tpu.parallel import mesh as pm

    calls = []
    # raising=False: some jax builds lack the probe entirely (the guard
    # then falls back to global_state) — the patch installs it either way
    monkeypatch.setattr(
        jax.distributed, "is_initialized", lambda: False, raising=False
    )
    monkeypatch.setattr(
        jax.distributed,
        "initialize",
        lambda **kw: calls.append(kw),
    )
    monkeypatch.setattr(
        jax, "process_count",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("guard must not initialize the backend")
        ),
    )
    # no coordinator: stays a no-op
    pm.initialize_distributed()
    assert calls == []
    # coordinator given: forwarded to jax.distributed.initialize
    pm.initialize_distributed("host0:1234", 4, 1)
    assert calls == [
        {
            "coordinator_address": "host0:1234",
            "num_processes": 4,
            "process_id": 1,
        }
    ]
    # already initialized: no second init
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True)
    pm.initialize_distributed("host0:1234", 4, 1)
    assert len(calls) == 1


def test_two_process_coordinator_end_to_end(tmp_path, rng):
    """REAL multi-host run (BASELINE config 5): two coordinated processes
    on CPU, block-sharded input, per-rank part files, merge-parts
    reconstruction matching a single-process run.  Each process runs its
    shard on a LOCAL mesh — clusters are independent, so no collective
    ever crosses hosts (a global mesh would require identical device_put
    inputs on every process, which sharded inputs violate by design)."""
    import socket
    import subprocess
    import sys as _sys

    from specpride_tpu.io.mgf import read_mgf, write_mgf

    clusters = [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=20)
        for i in range(6)
    ]
    clustered = tmp_path / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], clustered)
    out = tmp_path / "out.mgf"

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    env.pop("XLA_FLAGS", None)  # no forced device count in children
    env.pop("JAX_NUM_CPU_DEVICES", None)  # conftest's 8-device setting
    # a PJRT plugin inherited via PYTHONPATH (e.g. a tunneled-TPU site
    # dir) can override JAX_PLATFORMS and break CPU multi-process gloo —
    # the explicit PYTHONPATH above drops any such site path
    procs = [
        subprocess.Popen(
            [
                _sys.executable, "-m", "specpride_tpu", "consensus",
                str(clustered), str(out),
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(i),
            ],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        for i in range(2)
    ]
    try:
        for p in procs:
            _, err = p.communicate(timeout=180)
            assert p.returncode == 0, err.decode()[-2000:]
    finally:
        for p in procs:  # a failed rank must not leave its peer blocked
            if p.poll() is None:
                p.kill()
                p.wait()

    from specpride_tpu.cli import main as cli_main

    assert cli_main(["merge-parts", str(out), "--num-processes", "2"]) == 0
    merged = read_mgf(out)
    ref = nb.run_bin_mean(clusters)
    assert [s.title for s in merged] == [r.title for r in ref]
    for a, b in zip(merged, ref):
        np.testing.assert_allclose(a.mz, b.mz, rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(
            a.intensity, b.intensity, rtol=1e-4, atol=1e-2
        )
