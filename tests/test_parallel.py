"""Multi-device sharding tests on the virtual 8-device CPU mesh
(survey §4d — the standard JAX idiom for testing pod sharding without TPU)."""

import numpy as np
import jax

from specpride_tpu.backends import numpy_backend as nb
from specpride_tpu.backends.tpu_backend import TpuBackend
from specpride_tpu.parallel import cluster_mesh, cluster_sharding

from conftest import make_cluster
from test_tpu_parity import assert_spectra_close, random_clusters


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_sharded_bin_mean_matches_oracle(rng):
    mesh = cluster_mesh()
    assert mesh.size == 8
    backend = TpuBackend(mesh=mesh)
    clusters = random_clusters(rng, n=13)  # deliberately not divisible by 8
    oracle = nb.run_bin_mean(clusters)
    device = backend.run_bin_mean(clusters)
    assert len(oracle) == len(device)
    for o, d in zip(oracle, device):
        assert_spectra_close(o, d)


def test_sharded_gap_average_matches_oracle(rng):
    backend = TpuBackend(mesh=cluster_mesh())
    from test_tpu_parity import make_gap_safe_cluster

    clusters = [
        make_gap_safe_cluster(rng, f"cluster-{i}", n_members=3) for i in range(5)
    ]
    oracle = nb.run_gap_average(clusters)
    device = backend.run_gap_average(clusters)
    for o, d in zip(oracle, device):
        assert o.n_peaks == d.n_peaks
        np.testing.assert_allclose(o.mz, d.mz, rtol=1e-5, atol=1e-3)


def test_sharded_medoid_matches_oracle(rng):
    backend = TpuBackend(mesh=cluster_mesh())
    clusters = random_clusters(rng, n=9)
    assert backend.medoid_indices(clusters) == [
        nb.medoid_index(c.members) for c in clusters
    ]


def test_sharded_cosines_match_oracle(rng):
    backend = TpuBackend(mesh=cluster_mesh())
    clusters = random_clusters(rng, n=6)
    reps = nb.run_bin_mean(clusters)
    oracle = [nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)]
    device = backend.average_cosines(reps, clusters)
    np.testing.assert_allclose(oracle, device, rtol=5e-5, atol=1e-5)


def test_input_sharding_is_applied(rng):
    """The dispatched arrays really live split over the cluster axis."""
    mesh = cluster_mesh()
    x = np.zeros((16, 4, 8), np.float32)
    from specpride_tpu.parallel.mesh import shard_batch_arrays

    (sx,) = shard_batch_arrays(mesh, x)
    assert sx.sharding == cluster_sharding(mesh, 3)
    # each device holds 16/8 = 2 clusters
    shard_shapes = {s.data.shape for s in sx.addressable_shards}
    assert shard_shapes == {(2, 4, 8)}


def test_initialize_distributed_guard(monkeypatch):
    """The already-initialized probe must go through
    jax.distributed.is_initialized — NOT jax.process_count(), which spins
    up the local backend and makes a subsequent real
    jax.distributed.initialize illegal (advisor r1)."""
    from specpride_tpu.parallel import mesh as pm

    calls = []
    monkeypatch.setattr(
        jax.distributed, "is_initialized", lambda: False
    )
    monkeypatch.setattr(
        jax.distributed,
        "initialize",
        lambda **kw: calls.append(kw),
    )
    monkeypatch.setattr(
        jax, "process_count",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("guard must not initialize the backend")
        ),
    )
    # no coordinator: stays a no-op
    pm.initialize_distributed()
    assert calls == []
    # coordinator given: forwarded to jax.distributed.initialize
    pm.initialize_distributed("host0:1234", 4, 1)
    assert calls == [
        {
            "coordinator_address": "host0:1234",
            "num_processes": 4,
            "process_id": 1,
        }
    ]
    # already initialized: no second init
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True)
    pm.initialize_distributed("host0:1234", 4, 1)
    assert len(calls) == 1
