"""Memory-bandwidth campaign: reduced-precision packed paths
(--precision {f32,bf16,int8}), buffer donation on the chunk loop
(--no-donate), and the double-buffered H2D transfer lane
(--h2d-buffer) — plus the byte-accounting satellites (journal ratios,
stats bandwidth rendering, warm-start manifests with non-f32 dtypes).
"""

import json
import os

import numpy as np
import pytest

from specpride_tpu.backends import numpy_backend as nb
from specpride_tpu.backends.tpu_backend import TpuBackend
from specpride_tpu.cli import main as cli_main
from specpride_tpu.config import (
    BinMeanConfig,
    GapAverageConfig,
    MedoidConfig,
)
from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.ops import quantize


@pytest.fixture
def rng():
    return np.random.default_rng(17)


def _workload(rng, n=14, peaks=70):
    clusters = []
    for i in range(n):
        m = int(rng.integers(2, 6))
        base = np.sort(rng.uniform(150, 1500, peaks))
        members = [
            Spectrum(
                mz=np.sort(base + rng.normal(0, 0.002, peaks)),
                intensity=rng.uniform(1, 1e4, peaks),
                precursor_mz=420.0, precursor_charge=2, rt=1.0,
                title=f"p{i};s{k}",
            )
            for k in range(m)
        ]
        clusters.append(Cluster(f"p{i}", members))
    return clusters


def _write(tmp_path, clusters, name="in.mgf"):
    path = tmp_path / name
    write_mgf([s for c in clusters for s in c.members], str(path))
    return str(path)


def _events(path):
    return [json.loads(line) for line in open(path)]


def _run_end(path):
    return [e for e in _events(path) if e["event"] == "run_end"][-1]


# method -> (command, device-layout flags that actually ship bytes on a
# CPU host; reduced precision routes onto these same device paths)
_METHOD_FLAGS = {
    "bin-mean": ("consensus", ["--layout", "flat"]),
    "gap-average": ("consensus", ["--layout", "bucketized",
                                  "--force-device"]),
    "medoid": ("select", ["--layout", "bucketized"]),
}


class TestEncodeHelpers:
    def test_bf16_exact_probe(self):
        exact = np.array([1.0, 2.5, 0.125, 384.0], dtype=np.float32)
        assert quantize.bf16_exact(exact)
        noisy = np.array([1.0000001, 2.5], dtype=np.float32)
        assert not quantize.bf16_exact(noisy)

    def test_encode_mz_falls_back_to_f32(self):
        noisy = np.array([[123.456789, 1000.000123]], dtype=np.float32)
        enc, tok = quantize.encode_mz(noisy, "bf16")
        assert tok == "f32" and enc.dtype == np.float32

    def test_int8_rows_error_bound(self, rng):
        x = rng.uniform(0, 1e4, (8, 64)).astype(np.float32)
        codes, scale = quantize.encode_intensity_rows(x, "int8")
        assert codes.dtype == np.int8 and scale.shape == (8,)
        back = codes.astype(np.float32) * scale[:, None]
        # error <= scale/2 = rowmax/254 per element
        assert np.all(
            np.abs(back - x) <= x.max(axis=1)[:, None] / 253.9
        )

    def test_int8_flat_per_row_scales(self, rng):
        offs = np.array([0, 5, 5, 12], dtype=np.int64)  # empty middle row
        x = rng.uniform(0, 100, 12).astype(np.float32)
        codes, scale = quantize.encode_intensity_flat(x, offs, "int8")
        assert scale.shape == (3,)
        assert scale[1] == 1.0  # empty row forces the guard scale
        back = codes[:5].astype(np.float32) * scale[0]
        assert np.all(np.abs(back - x[:5]) <= x[:5].max() / 253.9)

    def test_narrow_i16(self):
        a = np.array([0, 5, 2**30], dtype=np.int32)
        got = quantize.narrow_i32_to_i16(a, max_valid=5)
        assert got.dtype == np.int16
        assert got.tolist() == [0, 5, 2**15 - 1]
        assert quantize.narrow_i32_to_i16(a, max_valid=2**15) is None

    def test_tolerance_table(self):
        assert quantize.precision_tolerance("bin-mean", "f32") == 1.0
        assert quantize.precision_tolerance("bin-mean", "bf16") >= 0.999
        assert quantize.precision_tolerance("gap-average", "int8") > 0.99


class TestPrecisionMatrix:
    """3 methods x {f32, bf16, int8}: f32 byte parity, reduced within
    the documented cosine tolerance vs the f32 oracle."""

    @pytest.mark.parametrize("method", list(_METHOD_FLAGS))
    def test_f32_flag_is_byte_parity(self, tmp_path, rng, method):
        src = _write(tmp_path, _workload(rng))
        command, flags = _METHOD_FLAGS[method]
        outs = []
        for tag, extra in (("bare", []), ("f32", ["--precision", "f32"])):
            out = str(tmp_path / f"{tag}.mgf")
            assert cli_main(
                [command, src, out, "--method", method] + flags + extra
            ) == 0
            outs.append(open(out, "rb").read())
        assert outs[0] == outs[1]

    @pytest.mark.parametrize("method", list(_METHOD_FLAGS))
    @pytest.mark.parametrize("precision", ["bf16", "int8"])
    def test_reduced_within_tolerance(self, rng, method, precision):
        clusters = _workload(rng)
        tol = quantize.precision_tolerance(method, precision)
        kw = (
            dict(layout="flat") if method == "bin-mean"
            else dict(layout="bucketized", force_device=True)
        )
        ref_b = TpuBackend(**kw)
        red_b = TpuBackend(precision=precision, **kw)
        if method == "bin-mean":
            ref = ref_b.run_bin_mean(clusters, BinMeanConfig())
            red = red_b.run_bin_mean(clusters, BinMeanConfig())
        elif method == "gap-average":
            ref = ref_b.run_gap_average(clusters, GapAverageConfig())
            red = red_b.run_gap_average(clusters, GapAverageConfig())
        else:
            iref = ref_b.medoid_indices(clusters, MedoidConfig())
            ired = red_b.medoid_indices(clusters, MedoidConfig())
            # integer narrowing is exact: identical winners
            assert iref == ired
            return
        cosines = [nb.binned_cosine(a, b) for a, b in zip(ref, red)]
        assert min(cosines) >= tol, (method, precision, min(cosines))

    def test_h2d_bytes_shrink_and_gate_journaled(self, tmp_path, rng):
        """The acceptance ratios on the flat bin-mean path: bf16 <=
        0.55x f32 H2D bytes, int8 <= 0.30x, QC gate green + journaled
        in run_end.precision."""
        src = _write(tmp_path, _workload(rng, n=20))
        bytes_by_prec = {}
        for prec in ("f32", "bf16", "int8"):
            out = str(tmp_path / f"{prec}.mgf")
            journal = str(tmp_path / f"{prec}.jsonl")
            assert cli_main([
                "consensus", src, out, "--method", "bin-mean",
                "--layout", "flat", "--precision", prec,
                "--journal", journal,
            ]) == 0
            end = _run_end(journal)
            bytes_by_prec[prec] = end["device"]["bytes_h2d"]
            if prec != "f32":
                p = end["precision"]
                assert p["ok"] and p["gated"]
                assert p["min_cosine"] >= p["tolerance"]
                assert [
                    e for e in _events(journal)
                    if e["event"] == "precision"
                    and e.get("intensity") == prec
                ]
        assert bytes_by_prec["bf16"] <= 0.55 * bytes_by_prec["f32"]
        assert bytes_by_prec["int8"] <= 0.30 * bytes_by_prec["f32"]

    def test_gate_failure_aborts(self, tmp_path, rng, monkeypatch):
        src = _write(tmp_path, _workload(rng))
        monkeypatch.setitem(
            quantize.PRECISION_MIN_COSINE, ("bin-mean", "bf16"), 1.1
        )
        with pytest.raises(SystemExit, match="precision gate FAILED"):
            cli_main([
                "consensus", src, str(tmp_path / "o.mgf"),
                "--method", "bin-mean", "--layout", "flat",
                "--precision", "bf16",
            ])


class TestGateEdgeCases:
    def test_gate_skips_wrapper_backends(self):
        """A batched member job runs against the batcher's read-only
        result view (not a dataclass); the gate must record and skip,
        never attempt to twin it."""
        import argparse

        from specpride_tpu import cli
        from specpride_tpu.observability import NullJournal, RunStats

        class Wrapper:  # forwards the resident backend's precision
            precision = "bf16"

        stats = RunStats()
        cli._precision_gate(
            argparse.Namespace(), Wrapper(), [], "bin-mean", stats,
            NullJournal(),
        )
        assert stats.precision["gated"] is False
        assert stats.precision["reason"] == "shared-batch-member"

    def test_elastic_runs_are_gated(self, tmp_path, rng, monkeypatch):
        """--elastic must not bypass the gate: a breach aborts before
        the rank claims any range."""
        src = _write(tmp_path, _workload(rng))
        monkeypatch.setitem(
            quantize.PRECISION_MIN_COSINE, ("bin-mean", "bf16"), 1.1
        )
        with pytest.raises(SystemExit, match="precision gate FAILED"):
            cli_main([
                "consensus", src, str(tmp_path / "e.mgf"),
                "--method", "bin-mean", "--layout", "flat",
                "--precision", "bf16",
                "--elastic", str(tmp_path / "coord"),
                "--elastic-range", "4", "--checkpoint-every", "2",
            ])


class TestH2dLaneErrors:
    def test_upstream_pack_failure_propagates(self):
        """An exception raised by the pack generator itself (e.g. the
        pool exiting without delivering a chunk) must abort the
        dispatch lane, not end the stream as a clean-looking truncated
        run."""
        from specpride_tpu import cli

        class NoStageBackend:
            def supports_h2d_stage(self, prepared):
                return False

        def items():
            it = cli._ChunkItem(0, [0])
            it.part = []
            yield it
            raise RuntimeError("pack worker pool exited")

        got = []
        with pytest.raises(RuntimeError, match="pool exited"):
            for item in cli._h2d_staged_chunks(
                items(), NoStageBackend(), 2, {}
            ):
                got.append(item)
        assert len(got) == 1  # the delivered chunk still flowed through


class TestDonation:
    def test_cpu_resolves_donation_off(self):
        """CPU-only jax maps host buffers zero-copy, so donation must
        resolve to a no-op there (the donated twin would alias output
        into memory the host reuses)."""
        assert TpuBackend()._donate_effective is False
        assert TpuBackend(donate=False)._donate_effective is False

    def test_donated_twin_numeric_parity(self, rng):
        """The donated jit twins compute the same values as the plain
        ones (inputs held alive across the call — the caller contract
        donation relies on)."""
        from specpride_tpu.ops import binning

        n = 700
        n_pad, rcap, cap = 1024, 1024, 1024
        inten = np.pad(
            rng.uniform(1, 1e4, n).astype(np.float32), (0, n_pad - n)
        )
        g = np.pad(
            np.sort(rng.integers(0, 400, n)).astype(np.int32),
            (0, n_pad - n), constant_values=2**31 - 1,
        )
        keep = np.zeros(rcap, bool)
        keep[:50] = True
        kw = dict(total_cap=cap, rcap=rcap, lcap=16, impl="scan")
        a = np.asarray(
            binning.bin_mean_flat_intensity(inten, g, keep, **kw)
        )
        b = np.asarray(
            binning.bin_mean_flat_intensity_donated(
                inten.copy(), g.copy(), keep.copy(), **kw
            )
        )
        np.testing.assert_array_equal(a, b)

    def test_no_donate_cli_byte_parity(self, tmp_path, rng):
        src = _write(tmp_path, _workload(rng))
        outs = []
        for tag, extra in (("on", []), ("off", ["--no-donate"])):
            out = str(tmp_path / f"d{tag}.mgf")
            assert cli_main([
                "consensus", src, out, "--method", "bin-mean",
                "--layout", "flat",
            ] + extra) == 0
            outs.append(open(out, "rb").read())
        assert outs[0] == outs[1]


class TestH2dBuffer:
    @pytest.mark.parametrize("precision", ["f32", "int8"])
    def test_double_buffer_byte_parity_and_overlap(
        self, tmp_path, rng, precision
    ):
        src = _write(tmp_path, _workload(rng, n=24))
        outs = {}
        for slots in (0, 2):
            out = str(tmp_path / f"h{slots}.mgf")
            journal = str(tmp_path / f"h{slots}.jsonl")
            assert cli_main([
                "consensus", src, out, "--method", "bin-mean",
                "--layout", "flat", "--precision", precision,
                "--h2d-buffer", str(slots),
                "--checkpoint", str(tmp_path / f"h{slots}.ck"),
                "--checkpoint-every", "6", "--journal", journal,
            ]) == 0
            outs[slots] = open(out, "rb").read()
            end = _run_end(journal)
            pipe = end.get("pipeline") or {}
            if slots:
                h2d = pipe["h2d"]
                assert h2d["slots"] == 2
                assert h2d["bytes"] > 0
                assert 0.0 <= h2d["overlap_efficiency"] <= 1.0
            else:
                assert "h2d" not in pipe
        assert outs[0] == outs[2]

    def test_staged_pipeline_spans_present(self, tmp_path, rng):
        src = _write(tmp_path, _workload(rng, n=24))
        journal = str(tmp_path / "spans.jsonl")
        assert cli_main([
            "consensus", src, str(tmp_path / "s.mgf"), "--method",
            "bin-mean", "--layout", "flat", "--h2d-buffer", "2",
            "--checkpoint", str(tmp_path / "s.ck"),
            "--checkpoint-every", "6", "--journal", journal,
        ]) == 0
        spans = [
            e for e in _events(journal)
            if e["event"] == "span" and e["name"] == "pipeline:h2d"
        ]
        assert spans, "h2d lane never traced"


class TestWarmstartRoundTrip:
    @pytest.mark.parametrize("precision", ["bf16", "int8"])
    def test_manifest_round_trip_no_spurious_recompiles(
        self, tmp_path, rng, precision
    ):
        """Non-f32 dtype tokens survive the shape-manifest round trip:
        the cold reduced run seeds the manifest, warmup rebuilds the
        exact reduced kernels, and the warm rerun journals ZERO fresh
        compiles."""
        import jax

        from specpride_tpu.warmstart.manifest import load_manifest
        from specpride_tpu.warmstart import registry

        src = _write(tmp_path, _workload(rng))
        cache = str(tmp_path / "cache")

        def run(tag):
            jax.clear_caches()
            journal = tmp_path / f"{tag}.jsonl"
            assert cli_main([
                "consensus", src, str(tmp_path / f"{tag}.mgf"),
                "--method", "bin-mean", "--layout", "flat",
                "--precision", precision, "--compile-cache", cache,
                "--journal", str(journal),
            ]) == 0
            return _run_end(str(journal))

        cold = run("cold")
        assert cold["compile_cache"]["misses"] > 0
        manifest = os.path.join(cache, "shape_manifest.json")
        entries = [
            e for e in load_manifest(manifest)
            if e.kernel == "bin_mean_flat_q"
        ]
        assert entries, "reduced kernel missing from manifest"
        assert all(precision in e.shape_key for e in entries)
        # the registry rebuilds the reduced variant dtype-exact
        for e in entries:
            built = registry.build(e, donate=False)
            assert built is not None
            fn, avals, statics = built
            assert str(avals[0].dtype) in ("bfloat16", "int8")
            fn.lower(*avals, **statics)  # traces without error

        warm = run("warm")
        assert warm["compile_cache"]["misses"] == 0
        assert warm["compile_cache"]["hits"] > 0
        assert (tmp_path / "cold.mgf").read_bytes() == (
            tmp_path / "warm.mgf"
        ).read_bytes()


class TestStatsRendering:
    def test_bandwidth_and_precision_lines(self, tmp_path, rng, capsys):
        from specpride_tpu.observability.stats_cli import run_stats

        src = _write(tmp_path, _workload(rng))
        journal = str(tmp_path / "r.jsonl")
        assert cli_main([
            "consensus", src, str(tmp_path / "r.mgf"), "--method",
            "bin-mean", "--layout", "flat", "--precision", "bf16",
            "--h2d-buffer", "2", "--journal", journal,
        ]) == 0
        json_out = str(tmp_path / "stats.json")
        assert run_stats([journal], json_out=json_out) == 0
        rendered = capsys.readouterr().out
        assert "bandwidth:" in rendered
        assert "MB/s" in rendered
        assert "precision=bf16" in rendered and "gate=ok" in rendered
        doc = json.loads(open(json_out).read())
        run = doc["runs"][0]
        assert run["bandwidth"]["h2d_mb"] > 0
        assert run["bandwidth"]["h2d_mb_per_s"] > 0
        assert run["precision"]["ok"] is True


class TestExporterBytes:
    def test_byte_counters_mirror_backend_registries(self):
        from specpride_tpu.observability import MetricsRegistry
        from specpride_tpu.observability.exporter import (
            ServeTelemetry,
            validate_exposition,
        )

        w0 = MetricsRegistry()
        w1 = MetricsRegistry()
        tele = ServeTelemetry(worker_registries={"0": w0, "1": w1})
        text = tele.exposition()
        assert "specpride_h2d_bytes_total 0" in text
        assert "specpride_d2h_bytes_total 0" in text
        w0.counter("specpride_bytes_h2d_total", "h").inc(1000)
        w1.counter("specpride_bytes_h2d_total", "h").inc(500)
        w1.counter("specpride_bytes_d2h_total", "h").inc(70)
        text = tele.exposition()
        assert "specpride_h2d_bytes_total 1500" in text
        assert "specpride_d2h_bytes_total 70" in text
        # monotone mirror: a second scrape with no new traffic holds
        text = tele.exposition()
        assert "specpride_h2d_bytes_total 1500" in text
        assert validate_exposition(text) == []
