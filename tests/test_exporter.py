"""Live telemetry plane: the in-process Prometheus ``/metrics``
endpoint under concurrent submissions (scrape-during-job gauges, post-
drain totals vs the journal), the strict exposition checker, SLO burn
accounting + ``stats --slo``, on-demand ``specpride profile`` against a
warm daemon, and the registry's thread-safety/snapshot-diff primitives
the plane is built on."""

import io
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.observability.exporter import (
    MetricsExporter,
    ServeTelemetry,
    parse_exposition,
    parse_slo_spec,
    slo_objective,
    validate_exposition,
)
from specpride_tpu.observability.journal import read_events
from specpride_tpu.observability.registry import (
    MetricsRegistry,
    device_counters_snapshot,
    device_summary,
)
from specpride_tpu.observability.stats_cli import run_stats
from specpride_tpu.serve import client as sc
from specpride_tpu.serve.daemon import ServeDaemon

from conftest import make_cluster


def _start(daemon: ServeDaemon) -> threading.Thread:
    t = threading.Thread(target=daemon.run, daemon=True)
    t.start()
    assert sc.wait_for_socket(daemon.socket_path, timeout=120), \
        "daemon never answered ping"
    return t


def _stop(daemon: ServeDaemon, thread: threading.Thread) -> None:
    daemon.drain()
    thread.join(timeout=60)
    assert not thread.is_alive(), "daemon thread did not exit after drain"


def _scrape(daemon: ServeDaemon) -> tuple[dict, str]:
    """GET /metrics; returns (samples, raw text) after a STRICT parse."""
    text = urllib.request.urlopen(
        daemon.exporter.url, timeout=10
    ).read().decode("utf-8")
    samples, problems = parse_exposition(text)
    assert not problems, problems
    return samples, text


def _get(samples: dict, name: str, **labels):
    return samples.get((name, tuple(sorted(labels.items()))))


@pytest.fixture(scope="module")
def workload(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("exporter_wl")
    rng = np.random.default_rng(7)
    # a DIFFERENT pack shape than test_serve's workload (4x30 vs 3x25):
    # the bucket-plan cache is process-wide and digest-keyed on pack
    # structure, and test_serve asserts its first job misses that cache
    clusters = [
        make_cluster(rng, f"cluster-{i}", n_members=4, n_peaks=30)
        for i in range(10)
    ]
    src = tmp / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], src)
    return str(src)


class TestSloSpec:
    def test_parse_and_precedence(self):
        slo = parse_slo_spec("bin-mean=2.5, medoid=1, *=10")
        assert slo == {"bin-mean": 2.5, "medoid": 1.0, "*": 10.0}
        assert slo_objective(slo, "bin-mean") == 2.5
        assert slo_objective(slo, "gap-average") == 10.0  # catch-all
        assert slo_objective({"bin-mean": 2.0}, "medoid") is None
        assert parse_slo_spec(None) == {}
        assert parse_slo_spec("") == {}

    @pytest.mark.parametrize("bad", [
        "bin-mean", "=2", "bin-mean=fast", "bin-mean=0", "bin-mean=-1",
    ])
    def test_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_slo_spec(bad)


class TestExpositionChecker:
    GOOD = (
        "# HELP jobs_total served jobs\n"
        "# TYPE jobs_total counter\n"
        'jobs_total{method="bin-mean"} 3\n'
        "# TYPE depth gauge\n"
        "depth 0\n"
        "# TYPE lat histogram\n"
        'lat_bucket{le="0.1"} 1\n'
        'lat_bucket{le="1"} 2\n'
        'lat_bucket{le="+Inf"} 2\n'
        "lat_sum 0.7\n"
        "lat_count 2\n"
    )

    def test_conforming_document(self):
        assert validate_exposition(self.GOOD) == []
        samples, _ = parse_exposition(self.GOOD)
        assert samples[("jobs_total", (("method", "bin-mean"),))] == 3.0
        assert samples[("lat_count", ())] == 2.0

    @pytest.mark.parametrize("mutate,needle", [
        (lambda t: t.rstrip("\n"), "newline"),
        (lambda t: t + "bad line here and more\n", "unparseable"),
        (lambda t: t + "jobs_total{method=\"bin-mean\"} 4\n",
         "duplicate series"),
        (lambda t: t + "# TYPE jobs_total counter\n", "duplicate TYPE"),
        (lambda t: t + "x nanops\n", "bad value"),
        (lambda t: t.replace('le="1"} 2', 'le="1"} 0'),
         "not cumulative"),
        (lambda t: t.replace('lat_bucket{le="+Inf"} 2\n', ""),
         "+Inf"),
        (lambda t: t.replace("lat_count 2", "lat_count 3"),
         "+Inf bucket != _count"),
        (lambda t: t.replace("lat_count 2\n", ""), "missing _count"),
        (lambda t: t + 'jobs_total{method=bin} 1\n', "malformed label"),
    ])
    def test_catches_violations(self, mutate, needle):
        problems = validate_exposition(mutate(self.GOOD))
        assert problems and any(needle in p for p in problems), problems


class TestRegistryConcurrency:
    def test_render_while_mutating(self):
        """A scraper rendering WHILE worker threads inc counters and
        observe histograms must never crash or read torn state; final
        totals are exact."""
        r = MetricsRegistry()
        n_threads, n_iter = 4, 2000
        stop = threading.Event()
        errors: list = []

        def _mutate(tid):
            try:
                c = r.counter("t_total", "x", labels=("tid",))
                h = r.histogram("t_seconds", "x", labels=("tid",))
                for i in range(n_iter):
                    c.inc(1, tid=str(tid))
                    h.observe(0.01 * (i % 7), tid=str(tid))
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        def _render():
            try:
                while not stop.is_set():
                    problems = validate_exposition(r.to_prometheus_text())
                    assert not problems, problems
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=_mutate, args=(t,))
            for t in range(n_threads)
        ]
        scraper = threading.Thread(target=_render)
        scraper.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        stop.set()
        scraper.join(timeout=60)
        assert not errors, errors
        assert r.sum_counter("t_total") == n_threads * n_iter
        samples, problems = parse_exposition(r.to_prometheus_text())
        assert not problems
        for tid in range(n_threads):
            assert _get(samples, "t_seconds_count", tid=str(tid)) == n_iter

    def test_device_summary_snapshot_diff(self):
        """Per-job attribution on a resident registry: the delta view
        reports only post-snapshot traffic, the absolute view stays
        cumulative (Prometheus-monotone)."""
        r = MetricsRegistry()
        r.counter(
            "specpride_dispatches_total", "d", labels=("kernel",)
        ).inc(5, kernel="k")
        r.counter("specpride_bytes_h2d_total", "b").inc(100)
        snap = device_counters_snapshot(r)
        r.counter(
            "specpride_dispatches_total", "d", labels=("kernel",)
        ).inc(2, kernel="k")
        r.counter("specpride_bytes_h2d_total", "b").inc(30)
        delta = device_summary(r, since=snap)
        assert delta["dispatches"] == 2 and delta["bytes_h2d"] == 30
        total = device_summary(r)
        assert total["dispatches"] == 7 and total["bytes_h2d"] == 130
        assert device_counters_snapshot(None) == {}


class TestServeTelemetryUnit:
    def test_job_done_slo_and_lanes(self):
        t = ServeTelemetry(slo={"bin-mean": 1.0, "*": 5.0})
        fields = t.job_done(
            command="consensus", method="bin-mean", status="done",
            wall_s=0.4, queue_wait_s=0.1,
            summary={
                "phases_s": {"compute": 0.3},
                "pipeline": {
                    "pack_busy_s": [0.1, 0.2], "write_busy_s": 0.05,
                    "async_write": True,
                },
            },
        )
        assert fields == {
            "slo_objective_s": 1.0, "slo_latency_s": 0.5, "slo_ok": True,
        }
        breach = t.job_done(
            command="consensus", method="bin-mean", status="done",
            wall_s=2.0, queue_wait_s=0.0,
        )
        assert breach["slo_ok"] is False
        t.job_done(
            command="select", method="medoid", status="error",
            wall_s=0.1, queue_wait_s=0.0,
        )  # covered by the catch-all
        assert t.jobs_done.value(command="consensus", method="bin-mean") == 2
        assert t.jobs_failed.value(command="select", method="medoid") == 1
        assert t.slo_breaches.value(method="bin-mean") == 1
        assert t.slo_jobs.value(method="bin-mean") == 2
        assert t.slo_jobs.value(method="medoid") == 1
        assert t.lane_busy.value(lane="pack") == pytest.approx(0.3)
        assert t.lane_busy.value(lane="write") == pytest.approx(0.05)
        assert t.lane_busy.value(lane="dispatch") == pytest.approx(0.3)
        problems = validate_exposition(t.exposition())
        assert not problems, problems

    def test_no_slo_configured_returns_no_fields(self):
        t = ServeTelemetry()
        assert t.job_done(
            command="consensus", method="bin-mean", status="done",
            wall_s=9.9, queue_wait_s=0.0,
        ) == {}

    def test_exporter_http_roundtrip_and_404(self):
        exp = MetricsExporter(lambda: "# TYPE up gauge\nup 1\n").start()
        try:
            body = urllib.request.urlopen(
                exp.url, timeout=10
            ).read().decode()
            assert body == "# TYPE up gauge\nup 1\n"
            health = urllib.request.urlopen(
                exp.url.replace("/metrics", "/healthz"), timeout=10
            ).read()
            assert health == b"ok\n"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    exp.url.replace("/metrics", "/nope"), timeout=10
                )
        finally:
            exp.stop()


class TestLiveExporter:
    def test_scrape_during_job_then_totals_match_journal(
        self, tmp_path_factory, workload
    ):
        """The acceptance bar: a scrape DURING an in-flight job shows
        live queue-depth/in-flight gauges; after drain the counter and
        histogram totals equal the journal-derived serving summary, and
        the --metrics-out drain snapshot carries the same exposition."""
        tmp = tmp_path_factory.mktemp("exporter_live")
        d = ServeDaemon(
            str(tmp / "s.sock"),
            compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            metrics_port=0,
            metrics_out=str(tmp / "final.prom"),
            slo={"*": 300.0},
            # single lane: this test pins "one gated in-flight, one
            # queued" (a pool would pop both; the multi-lane exporter
            # series are covered in test_workers.py)
            workers=1,
        )
        d._gate.clear()  # hold the worker so the scrape sees it in flight
        t = _start(d)
        terms = {}

        def _submit(tag, client):
            terms[tag] = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(tmp / f"{tag}.mgf"),
                "--method", "bin-mean",
            ], client=client)

        t1 = threading.Thread(target=_submit, args=("first", "tenant-a"))
        t1.start()
        deadline = time.time() + 30
        while d._inflight is None and time.time() < deadline:
            time.sleep(0.01)
        assert d._inflight is not None
        t2 = threading.Thread(target=_submit, args=("second", "tenant-b"))
        t2.start()
        while len(d.queue) < 1 and time.time() < deadline:
            time.sleep(0.01)

        # live mid-load scrape: one job gated in flight, one queued
        samples, text = _scrape(d)
        assert _get(
            samples, "specpride_serve_inflight_jobs",
            command="consensus", method="bin-mean", backend="tpu",
        ) == 1
        assert _get(samples, "specpride_serve_queue_depth") == 1
        assert _get(
            samples, "specpride_serve_queue_depth_client",
            client="tenant-b",
        ) == 1
        assert _get(samples, "specpride_serve_uptime_seconds") > 0
        # nothing finished yet
        assert _get(
            samples, "specpride_serve_jobs_done_total",
            command="consensus", method="bin-mean",
        ) is None

        d._gate.set()
        t1.join(timeout=120)
        t2.join(timeout=120)
        assert terms["first"]["status"] == "done"
        assert terms["second"]["status"] == "done"
        # a reply is written BEFORE the worker drops the job from its
        # in-flight map — wait for the accounting to settle, or this
        # scrape races the residue on slow 1-core hosts
        assert d.wait_idle(10.0)

        samples, _ = _scrape(d)
        assert _get(
            samples, "specpride_serve_jobs_done_total",
            command="consensus", method="bin-mean",
        ) == 2
        assert _get(
            samples, "specpride_serve_job_wall_seconds_count",
            method="bin-mean",
        ) == 2
        assert _get(
            samples, "specpride_serve_job_queue_wait_seconds_count",
            method="bin-mean",
        ) == 2
        # the in-flight series drops to 0 but stays visible
        assert _get(
            samples, "specpride_serve_inflight_jobs",
            command="consensus", method="bin-mean", backend="tpu",
        ) == 0
        assert _get(samples, "specpride_serve_queue_depth") == 0
        url = d.exporter.url

        _stop(d, t)
        # the endpoint is down after drain...
        with pytest.raises(OSError):
            urllib.request.urlopen(url, timeout=2)
        # ...but the drain snapshot carries the final exposition
        final_text = (tmp / "final.prom").read_text()
        final, problems = parse_exposition(final_text)
        assert not problems, problems
        events, violations = read_events(str(tmp / "serve.jsonl"))
        assert not violations, violations
        jobs_done = [
            e for e in events
            if e["event"] == "job_done" and e["status"] == "done"
        ]
        assert _get(
            final, "specpride_serve_jobs_done_total",
            command="consensus", method="bin-mean",
        ) == len(jobs_done) == 2
        assert _get(
            final, "specpride_serve_job_wall_seconds_count",
            method="bin-mean",
        ) == len(jobs_done)
        # histogram sums agree with the journal's walls (within rounding)
        assert _get(
            final, "specpride_serve_job_wall_seconds_sum",
            method="bin-mean",
        ) == pytest.approx(
            sum(e["wall_s"] for e in jobs_done), abs=0.05
        )
        # SLO: both jobs under the generous catch-all objective
        assert _get(
            final, "specpride_serve_slo_jobs_total", method="bin-mean"
        ) == 2
        assert _get(
            final, "specpride_serve_slo_breaches_total",
            method="bin-mean",
        ) is None  # never incremented — no breaches

    def test_rejections_counted_by_category(
        self, tmp_path_factory, workload
    ):
        tmp = tmp_path_factory.mktemp("exporter_rej")
        d = ServeDaemon(
            str(tmp / "s.sock"), compile_cache=str(tmp / "cache"),
            metrics_port=0,
        )
        t = _start(d)
        try:
            term = sc.submit_wait(
                d.socket_path, ["evaluate", "x", "y"]
            )
            assert term["status"] == "rejected"
            # --metrics-out is daemon-owned now: a per-job textfile off
            # the SHARED resident registry would report the daemon's
            # cumulative traffic as the job's
            term = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(tmp / "o.mgf"),
                "--metrics-out", str(tmp / "o.prom"),
            ])
            assert term["status"] == "rejected" and not term["retriable"]
            assert "--metrics-out" in term["reason"]
            samples, _ = _scrape(d)
            assert _get(
                samples, "specpride_serve_jobs_rejected_total",
                reason="invalid",
            ) == 2
        finally:
            _stop(d, t)


class TestProfileVerb:
    def test_profile_against_warm_daemon(self, tmp_path_factory, workload):
        """`specpride profile` on a live daemon: yields device-trace
        artifacts without a restart, slices the journal window, and the
        NEXT job still journals zero fresh compiles (the capture must
        not perturb the warm jit caches)."""
        tmp = tmp_path_factory.mktemp("exporter_prof")
        d = ServeDaemon(
            str(tmp / "s.sock"), compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            layout="bucketized", force_device=True,
        )
        t = _start(d)
        try:
            warm = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(tmp / "w.mgf"),
                "--method", "gap-average",
            ])
            assert warm["status"] == "done", warm
            rep = sc.profile(
                d.socket_path, seconds=0.3,
                trace_dir=str(tmp / "prof"),
                chrome_trace=str(tmp / "prof.json.gz"),
            )
            assert rep.get("status") == "profiled", rep
            assert rep["trace_dir"] == str(tmp / "prof")
            assert rep["artifacts"], "no device-trace artifacts captured"
            for rel in rep["artifacts"]:
                assert (tmp / "prof" / rel).is_file()
            # the journal window landed beside the trace and holds the
            # capture's own profile_start
            assert rep.get("journal_window")
            window = [
                json.loads(line)
                for line in open(rep["journal_window"])
            ]
            assert any(e["event"] == "profile_start" for e in window)
            assert rep["window_events"].get("profile_start") == 1
            # warm after profiling: zero fresh compiles on the next job
            after = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(tmp / "a.mgf"),
                "--method", "gap-average",
            ])
            assert after["status"] == "done", after
            assert after["compile_cache"]["misses"] == 0, after
        finally:
            _stop(d, t)
        events, violations = read_events(d.journal_path)
        assert not violations, violations
        names = [e["event"] for e in events]
        assert "profile_start" in names and "profile_done" in names

    def test_profile_validation_and_mutual_exclusion(
        self, tmp_path_factory
    ):
        tmp = tmp_path_factory.mktemp("exporter_prof_val")
        d = ServeDaemon(
            str(tmp / "s.sock"), compile_cache=str(tmp / "cache"),
        )
        t = _start(d)
        try:
            bad = sc.request(
                d.socket_path, {"op": "profile", "seconds": -1}
            )
            assert bad["status"] == "rejected" and not bad["retriable"]
            bad = sc.request(
                d.socket_path, {"op": "profile", "seconds": 1e9}
            )
            assert bad["status"] == "rejected" and not bad["retriable"]
            bad = sc.request(
                d.socket_path,
                {"op": "profile", "seconds": 1, "trace_dir": 7},
            )
            assert bad["status"] == "rejected" and not bad["retriable"]
            # one capture at a time: a held session rejects retriable
            assert d._profile_lock.acquire(blocking=False)
            try:
                busy = sc.profile(d.socket_path, seconds=0.1)
                assert busy["status"] == "rejected", busy
                assert busy["retriable"] is True
            finally:
                d._profile_lock.release()
        finally:
            _stop(d, t)


class TestSloStats:
    def test_breach_counters_and_stats_slo_rendering(
        self, tmp_path_factory, workload
    ):
        """An impossible objective burns on every job; `stats --slo`
        renders the per-method table from the journal, and the serving
        line carries the breach total."""
        tmp = tmp_path_factory.mktemp("exporter_slo")
        d = ServeDaemon(
            str(tmp / "s.sock"), compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            metrics_port=0,
            slo={"bin-mean": 1e-6, "gap-average": 300.0},
        )
        t = _start(d)
        try:
            for method in ("bin-mean", "gap-average"):
                term = sc.submit_wait(d.socket_path, [
                    "consensus", workload, str(tmp / f"{method}.mgf"),
                    "--method", method,
                ])
                assert term["status"] == "done", term
            samples, _ = _scrape(d)
            assert _get(
                samples, "specpride_serve_slo_breaches_total",
                method="bin-mean",
            ) == 1
            assert _get(
                samples, "specpride_serve_slo_objective_seconds",
                method="gap-average",
            ) == 300.0
        finally:
            _stop(d, t)
        events, violations = read_events(d.journal_path)
        assert not violations, violations
        jd = {
            e["method"]: e for e in events if e["event"] == "job_done"
        }
        assert jd["bin-mean"]["slo_ok"] is False
        assert jd["bin-mean"]["slo_objective_s"] == 1e-6
        assert jd["gap-average"]["slo_ok"] is True
        buf = io.StringIO()
        assert run_stats([d.journal_path], out=buf, slo=True) == 0
        rendered = buf.getvalue()
        assert "slo_breaches=1" in rendered
        assert "slo: method=bin-mean" in rendered and "burn=100.0%" in \
            rendered
        assert "slo: method=gap-average" in rendered and "burn=0.0%" in \
            rendered
