"""Test configuration: run JAX on a virtual 8-device CPU mesh.

The standard JAX idiom for testing pod sharding without TPU hardware
(survey §4d): force the host platform and split it into 8 virtual devices.
Must run before jax initialises, hence the env mutation at import time.
"""

import os

# force CPU even when the shell points JAX at a real accelerator
# (JAX_PLATFORMS=axon/tpu): unit tests must see 8 virtual devices, and
# per-shape TPU compiles would dominate suite runtime.  Real-hardware runs
# happen via bench.py.  A TPU plugin may already be registered by a
# sitecustomize hook before this file runs, so the env vars alone are not
# enough — the jax.config updates below override it.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_NUM_CPU_DEVICES"] = "8"
# jax < 0.4.x spells the virtual-device split as an XLA flag; newer jax
# reads the env var / config option.  Set both so either version sees 8.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: the XLA_FLAGS path above covers it
    pass

import numpy as np
import pytest

from specpride_tpu.data.peaks import Spectrum


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def make_spectrum(
    rng: np.random.Generator,
    n_peaks: int = 50,
    cluster_id: str = "cluster-1",
    scan: int = 1,
    precursor_mz: float = 500.0,
    charge: int = 2,
    rt: float = 100.0,
    peptide: str | None = None,
) -> Spectrum:
    mz = np.sort(rng.uniform(100.0, 1900.0, size=n_peaks))
    intensity = rng.uniform(1.0, 1e4, size=n_peaks)
    usi = f"mzspec:PXD000001:run1:scan:{scan}"
    if peptide:
        usi += f":{peptide}/{charge}"
    return Spectrum(
        mz=mz,
        intensity=intensity,
        precursor_mz=precursor_mz,
        precursor_charge=charge,
        rt=rt,
        title=f"{cluster_id};{usi}",
    )


def make_cluster(
    rng: np.random.Generator,
    cluster_id: str = "cluster-1",
    n_members: int = 4,
    n_peaks: int = 50,
    jitter: float = 0.004,
    base_scan: int = 1000,
    charge: int = 2,
):
    """Members share a peak skeleton with m/z jitter — a realistic cluster."""
    from specpride_tpu.data.peaks import Cluster

    skeleton = np.sort(rng.uniform(120.0, 1800.0, size=n_peaks))
    members = []
    for m in range(n_members):
        mz = np.sort(skeleton + rng.normal(0.0, jitter, size=n_peaks))
        intensity = rng.uniform(10.0, 1e4, size=n_peaks)
        usi = f"mzspec:PXD000001:run1:scan:{base_scan + m}"
        members.append(
            Spectrum(
                mz=mz,
                intensity=intensity,
                precursor_mz=500.0 + rng.normal(0, 0.01),
                precursor_charge=charge,
                rt=100.0 + m,
                title=f"{cluster_id};{usi}",
            )
        )
    return Cluster(cluster_id, members)
