"""Worker-pool serving (``specpride serve --workers N --quota ...``):
weighted-fair deficit scheduling, per-tenant inflight quotas (retriable
rejections, exit 75), the output-path conflict guard, device-aware
placement, 2-worker concurrent byte+QC parity vs one-shot CLI runs, and
per-worker journal/exporter attribution."""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.observability.journal import read_events
from specpride_tpu.serve import client as sc
from specpride_tpu.serve import placement
from specpride_tpu.serve.daemon import ServeDaemon
from specpride_tpu.serve.scheduler import (
    AdmissionQueue,
    Quota,
    QuotaExceeded,
    parse_quota_spec,
)

from conftest import make_cluster

METHODS = [
    ("bin-mean", "consensus"),
    ("gap-average", "consensus"),
    ("medoid", "select"),
]


def _start(daemon: ServeDaemon) -> threading.Thread:
    t = threading.Thread(target=daemon.run, daemon=True)
    t.start()
    assert sc.wait_for_socket(daemon.socket_path, timeout=120), \
        "daemon never answered ping"
    return t


def _stop(daemon: ServeDaemon, thread: threading.Thread) -> None:
    daemon.drain()
    thread.join(timeout=60)
    assert not thread.is_alive(), "daemon thread did not exit after drain"


@pytest.fixture(scope="module")
def workload(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("workers_wl")
    rng = np.random.default_rng(41)
    clusters = [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25)
        for i in range(8)
    ]
    src = tmp / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], src)
    return str(src)


class TestQuotaSpec:
    def test_parse(self):
        q = parse_quota_spec("teamA=3:2, teamB=1 ,*=1:1")
        assert q["teamA"] == Quota(3.0, 2)
        assert q["teamB"] == Quota(1.0, None)
        assert q["*"] == Quota(1.0, 1)
        assert parse_quota_spec(None) == {}
        assert parse_quota_spec("") == {}

    @pytest.mark.parametrize("bad", [
        "teamA",            # no '='
        "=2",               # no client
        "teamA=x",          # weight not a number
        "teamA=0",          # weight must be > 0
        "teamA=-1",         # weight must be > 0
        "teamA=1:0",        # max_inflight must be >= 1
        "teamA=1:x",        # max_inflight not an integer
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_quota_spec(bad)


class TestWeightedFair:
    def test_deficit_ordering_respects_weights(self):
        """Weight 2 vs weight 1 under continuous backlog: the deficit
        counters serve A twice per B's once, FIFO within each client."""
        q = AdmissionQueue(
            64, quotas={"A": Quota(2.0), "B": Quota(1.0)},
        )
        for j in range(1, 7):
            assert q.offer("A", f"a{j}")
        for j in range(1, 4):
            assert q.offer("B", f"b{j}")
        order = [q.pop(timeout=0.1) for _ in range(9)]
        assert order == [
            "a1", "b1", "a2", "a3", "b2", "a4", "a5", "b3", "a6",
        ]

    def test_default_weights_degenerate_to_round_robin(self):
        q = AdmissionQueue(16)
        for client, job in [
            ("A", "a1"), ("A", "a2"), ("B", "b1"), ("C", "c1"),
        ]:
            assert q.offer(client, job)
        assert [q.pop(timeout=0.1) for _ in range(4)] == [
            "a1", "b1", "c1", "a2",
        ]

    def test_idle_client_banks_no_credit(self):
        """A client that sat out rounds re-enters at the virtual-time
        frontier — it does NOT get a catch-up burst."""
        q = AdmissionQueue(16, quotas={"A": Quota(1.0), "B": Quota(1.0)})
        for j in range(1, 4):
            q.offer("A", f"a{j}")
        assert [q.pop(timeout=0.1) for _ in range(3)] == ["a1", "a2", "a3"]
        # B shows up late: it starts at the frontier, so the backlogged
        # A and fresh B alternate instead of B draining first
        for j in range(1, 3):
            q.offer("B", f"b{j}")
        q.offer("A", "a4")
        q.offer("A", "a5")
        order = [q.pop(timeout=0.1) for _ in range(4)]
        assert order[:2] in (["b1", "a4"], ["a4", "b1"])
        assert set(order) == {"b1", "b2", "a4", "a5"}

    def test_max_inflight_caps_admission(self):
        q = AdmissionQueue(16, quotas={"A": Quota(1.0, max_inflight=2)})
        assert q.offer("A", "a1")
        assert q.offer("A", "a2")
        with pytest.raises(QuotaExceeded) as ei:
            q.offer("A", "a3")
        assert ei.value.client == "A" and ei.value.max_inflight == 2
        # popping does not free quota (the job is now EXECUTING) ...
        popped = q.pop(timeout=0.1)
        assert popped == "a1"
        with pytest.raises(QuotaExceeded):
            q.offer("A", "a3")
        # ... release does
        q.release(popped)
        assert q.offer("A", "a3")
        # unquota'd clients are never capped
        for j in range(5):
            assert q.offer("B", f"b{j}")

    def test_max_inflight_enforced_at_pop(self):
        """Even with a job queued (white-box: bypassing the admission
        cap), a client at its inflight cap is skipped by pop until a
        lane releases."""
        q = AdmissionQueue(16, quotas={"A": Quota(1.0, max_inflight=1)})
        assert q.offer("A", "a1")
        popped = q.pop(timeout=0.1)
        assert popped == "a1"  # A now at cap
        with q._cond:  # inject past the admission check
            q._states["A"].queue.append("a2")
            q._total += 1
        assert q.pop(timeout=0.2) is None, "capped client must not pop"
        q.release(popped)
        assert q.pop(timeout=1.0) == "a2"

    def test_conflict_guard_serializes_same_output(self):
        key = lambda job: job["paths"]  # noqa: E731
        q = AdmissionQueue(16, conflict_key=key)
        j1 = {"id": 1, "paths": ("/out/x.mgf",)}
        j2 = {"id": 2, "paths": ("/out/x.mgf",)}
        j3 = {"id": 3, "paths": ("/out/y.mgf",)}
        q.offer("A", j1)
        q.offer("B", j2)
        q.offer("C", j3)
        assert q.pop(timeout=0.1) is j1
        # B's head conflicts with the in-flight j1: C flows past it
        assert q.pop(timeout=0.1) is j3
        assert q.pop(timeout=0.2) is None, "conflicting job must wait"
        q.release(j1)
        assert q.pop(timeout=1.0) is j2

    def test_drain_ignores_caps_and_conflicts(self):
        q = AdmissionQueue(
            16, quotas={"A": Quota(1.0, max_inflight=1)},
            conflict_key=lambda j: ("same-path",),
        )
        q.offer("A", "a1")
        assert q.pop(timeout=0.1) == "a1"  # A capped, path held
        with q._cond:
            q._states["A"].queue.append("a2")
            q._total += 1
        q.offer("B", "b1")
        # drain returns BOTH the capped client's job and the conflicted
        # one — rejection must not deadlock on execution-time limits
        assert sorted(q.drain()) == ["a2", "b1"]
        assert q.pop(timeout=0.05) is None


class TestPlacement:
    def test_default_workers_capped(self):
        # conftest pins 8 virtual CPU devices; the default caps at 4
        assert placement.default_workers() == 4

    def test_cpu_hosts_share_platform(self):
        slots = placement.plan_placement(3)
        assert [s.worker for s in slots] == [0, 1, 2]
        assert all(s.device is None for s in slots), \
            "CPU-only hosts must not pin (device-keyed compile caches)"

    def test_pin_cpu_round_robins_devices(self):
        slots = placement.plan_placement(3, pin_cpu=True)
        ids = [s.device_index for s in slots]
        assert len(set(ids)) == 3 and all(s.device is not None
                                          for s in slots)

    def test_device_scope_nullcontext_when_unpinned(self):
        with placement.device_scope(None):
            pass  # must be a no-op, not a jax call


@pytest.fixture(scope="module")
def pool_daemon(tmp_path_factory):
    """One long-lived 2-worker daemon shared by the parity and
    attribution tests — the concurrent multi-lane reuse the pool exists
    for."""
    tmp = tmp_path_factory.mktemp("workers_daemon")
    d = ServeDaemon(
        str(tmp / "serve.sock"),
        compile_cache=str(tmp / "cache"),
        journal_path=str(tmp / "serve.jsonl"),
        workers=2,
    )
    t = _start(d)
    yield d
    _stop(d, t)
    events, violations = read_events(d.journal_path)
    assert not violations, violations
    names = [e["event"] for e in events]
    assert names[0] == "run_start" and names[-1] == "run_end"


class TestTwoWorkerParity:
    def test_concurrent_matrix_byte_and_qc_parity(
        self, tmp_path, workload, pool_daemon
    ):
        """All three methods submitted CONCURRENTLY to the 2-worker
        daemon reproduce the one-shot CLI's exact bytes and QC report,
        and every job journal carries the worker lane that ran it."""
        golden = {}
        for method, command in METHODS:
            out = tmp_path / f"cli_{method}.mgf"
            qc = tmp_path / f"cli_{method}.qc.json"
            assert cli_main([
                command, workload, str(out), "--method", method,
                "--qc-report", str(qc),
            ]) == 0
            golden[method] = (out.read_bytes(), qc.read_text())

        results = {}

        def _client(method, command):
            out = tmp_path / f"served_{method}.mgf"
            qc = tmp_path / f"served_{method}.qc.json"
            jp = tmp_path / f"job_{method}.jsonl"
            results[method] = (
                sc.submit_wait(
                    pool_daemon.socket_path,
                    [command, workload, str(out), "--method", method,
                     "--qc-report", str(qc), "--journal", str(jp)],
                    client=f"tenant-{method}",
                ),
                out, qc, jp,
            )

        threads = [
            threading.Thread(target=_client, args=mc) for mc in METHODS
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
            assert not t.is_alive()
        for method, (term, out, qc, jp) in results.items():
            assert term["status"] == "done", (method, term)
            assert term.get("worker") in (0, 1), term
            assert out.read_bytes() == golden[method][0], method
            assert (
                json.loads(qc.read_text())
                == json.loads(golden[method][1])
            ), method
            events, violations = read_events(str(jp))
            assert not violations, violations
            end = [e for e in events if e["event"] == "run_end"][-1]
            assert end.get("worker") in (0, 1), \
                "job run_end must name its worker lane"

    def test_journal_attribution_and_stats_grouping(self, pool_daemon):
        """The daemon journal's job_start/job_done carry the worker
        lane, interleaved lines stay schema-valid, and the stats serving
        view groups jobs per worker."""
        events, violations = read_events(pool_daemon.journal_path)
        assert not violations, violations
        done = [e for e in events if e["event"] == "job_done"]
        starts = [e for e in events if e["event"] == "job_start"]
        assert done and starts
        assert all(e.get("worker") in (0, 1) for e in done + starts)
        serve_ev = next(e for e in events if e["event"] == "serve_start")
        assert serve_ev["workers"] == 2
        assert len(serve_ev["placement"]) == 2
        from specpride_tpu.observability.stats_cli import run_stats

        buf = io.StringIO()
        assert run_stats([pool_daemon.journal_path], out=buf) == 0
        text = buf.getvalue()
        assert "workers=2" in text
        assert "worker 0:" in text or "worker 1:" in text


class TestConcurrentLanes:
    def test_two_lanes_hold_jobs_concurrently_and_drain_commits_both(
        self, tmp_path_factory, workload
    ):
        """Deterministic two-lane occupancy via the worker gate: two
        jobs from distinct tenants are popped by BOTH workers, drain
        commits BOTH in-flight jobs (byte-identical outputs), and the
        journal shows each on its own lane."""
        tmp = tmp_path_factory.mktemp("workers_lanes")
        cli_out = tmp / "cli.mgf"
        assert cli_main([
            "consensus", workload, str(cli_out), "--method", "bin-mean",
        ]) == 0
        d = ServeDaemon(
            str(tmp / "s.sock"),
            compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            workers=2,
        )
        d._gate.clear()
        t = _start(d)
        terms = {}

        def _submit(tag):
            terms[tag] = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(tmp / f"{tag}.mgf"),
                "--method", "bin-mean",
            ], client=tag)

        threads = [
            threading.Thread(target=_submit, args=(tag,))
            for tag in ("tenant-a", "tenant-b")
        ]
        for th in threads:
            th.start()
        deadline = time.time() + 30
        while len(d._inflight_by) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert len(d._inflight_by) == 2, \
            "both worker lanes must hold an in-flight job"
        assert d._inflight is not None  # the single-lane view still works
        _stop(d, t)  # drain: opens the gate, joins BOTH workers
        for th in threads:
            th.join(timeout=120)
        for tag in ("tenant-a", "tenant-b"):
            assert terms[tag]["status"] == "done", terms[tag]
            assert (tmp / f"{tag}.mgf").read_bytes() == \
                cli_out.read_bytes()
        done = [
            e for e in read_events(d.journal_path)[0]
            if e["event"] == "job_done"
        ]
        assert sorted(e["worker"] for e in done) == [0, 1]

    def test_same_output_jobs_serialize(self, tmp_path_factory, workload):
        """The conflict guard: two jobs targeting the SAME output never
        run concurrently — the second waits for the first's lane."""
        tmp = tmp_path_factory.mktemp("workers_conflict")
        d = ServeDaemon(
            str(tmp / "s.sock"),
            compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            workers=2,
        )
        d._gate.clear()
        t = _start(d)
        terms = {}
        out = tmp / "shared.mgf"

        def _submit(tag):
            terms[tag] = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(out), "--method", "bin-mean",
            ], client=tag)

        threads = [
            threading.Thread(target=_submit, args=(tag,))
            for tag in ("first", "second")
        ]
        try:
            for th in threads:
                th.start()
            deadline = time.time() + 30
            while not d._inflight_by and time.time() < deadline:
                time.sleep(0.01)
            # give the scheduler every chance to (wrongly) pop job 2
            time.sleep(0.3)
            assert len(d._inflight_by) == 1, \
                "same-output jobs must not occupy two lanes"
            assert len(d.queue) == 1
        finally:
            d._gate.set()
            for th in threads:
                th.join(timeout=120)
            _stop(d, t)
        assert terms["first"]["status"] == "done"
        assert terms["second"]["status"] == "done"


class TestQuotaDaemon:
    def test_quota_rejection_retriable_exit75(
        self, tmp_path_factory, workload
    ):
        """A tenant at max_inflight=1 with a job on a lane gets its next
        submit rejected RETRIABLE with the quota named — the exit-75
        resubmit-later path — while other tenants keep flowing."""
        tmp = tmp_path_factory.mktemp("workers_quota")
        d = ServeDaemon(
            str(tmp / "s.sock"),
            compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            workers=1,
            quotas=parse_quota_spec("capped=2:1"),
        )
        d._gate.clear()  # hold the lane so the first job stays in flight
        t = _start(d)
        terms = {}

        def _submit(tag, client):
            terms[tag] = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(tmp / f"{tag}.mgf"),
                "--method", "bin-mean",
            ], client=client)

        try:
            t1 = threading.Thread(
                target=_submit, args=("first", "capped")
            )
            t1.start()
            deadline = time.time() + 30
            while d._inflight is None and time.time() < deadline:
                time.sleep(0.01)
            assert d._inflight is not None
            # same tenant, lane occupied, cap 1: named retriable bounce
            _submit("bounced", "capped")
            term = terms["bounced"]
            assert term["status"] == "rejected", term
            assert term["retriable"] is True
            assert "quota" in term["reason"] and "capped" in term["reason"]
            assert sc.exit_code(term) == 75
            # an uncapped tenant still gets in
            t2 = threading.Thread(
                target=_submit, args=("other", "free")
            )
            t2.start()
            while len(d.queue) < 1 and time.time() < deadline:
                time.sleep(0.01)
            assert len(d.queue) == 1
        finally:
            d._gate.set()
            t1.join(timeout=120)
            t2.join(timeout=120)
            _stop(d, t)
        assert terms["first"]["status"] == "done"
        assert terms["other"]["status"] == "done"
        # the journal named the quota on the rejection
        events, _ = read_events(d.journal_path)
        rej = [e for e in events if e["event"] == "job_rejected"]
        assert rej and "quota" in rej[0]["reason"]


class TestIngestCache:
    def test_unit_hit_miss_invalidate_evict(self, tmp_path):
        from specpride_tpu.serve import ingest_cache as ic

        ic.clear()
        p = tmp_path / "a.mgf"
        p.write_text("BEGIN IONS\nEND IONS\n")
        assert ic.get(str(p)) is None  # miss
        ic.put(str(p), ["clusters"], n_spectra=3, n_peaks=9)
        assert ic.get(str(p)) == (["clusters"], 3, 9)
        # rewriting the file invalidates (size/mtime key)
        time.sleep(0.01)
        p.write_text("BEGIN IONS\nPEPMASS=1\nEND IONS\n")
        assert ic.get(str(p)) is None
        # bounded: old entries evict
        for i in range(10):
            q = tmp_path / f"b{i}.mgf"
            q.write_text("x")
            ic.put(str(q), [i], n_spectra=1, n_peaks=1)
        assert ic.info()["size"] <= 4
        ic.clear()

    def test_served_repeat_job_hits_and_modified_input_misses(
        self, tmp_path, pool_daemon
    ):
        """Repeat served jobs skip the parse (run_end counters prove
        it) and still produce CLI-identical bytes; a MODIFIED input
        re-parses and serves the new content."""
        rng = np.random.default_rng(77)
        src = tmp_path / "in.mgf"
        write_mgf(
            [s for c in (
                make_cluster(rng, f"x-{i}", n_members=3, n_peaks=20)
                for i in range(6)
            ) for s in c.members],
            src,
        )
        cli_out = tmp_path / "cli.mgf"
        assert cli_main([
            "consensus", str(src), str(cli_out), "--method", "bin-mean",
        ]) == 0

        def served(tag):
            out = tmp_path / f"{tag}.mgf"
            jp = tmp_path / f"{tag}.jsonl"
            term = sc.submit_wait(pool_daemon.socket_path, [
                "consensus", str(src), str(out), "--method", "bin-mean",
                "--journal", str(jp),
            ])
            assert term["status"] == "done", term
            events, violations = read_events(str(jp))
            assert not violations, violations
            end = [e for e in events if e["event"] == "run_end"][-1]
            return out, end["counters"]

        out1, c1 = served("first")
        out2, c2 = served("second")
        assert c1.get("ingest_cache_hits", 0) == 0
        assert c1.get("ingest_cache_misses", 0) == 1
        assert c2.get("ingest_cache_hits", 0) == 1, c2
        assert out1.read_bytes() == cli_out.read_bytes()
        assert out2.read_bytes() == cli_out.read_bytes()
        # rewrite the input: the cache must miss and the job must serve
        # the NEW content
        time.sleep(0.01)
        write_mgf(
            [s for c in (
                make_cluster(rng, f"y-{i}", n_members=3, n_peaks=20)
                for i in range(4)
            ) for s in c.members],
            src,
        )
        cli_out2 = tmp_path / "cli2.mgf"
        assert cli_main([
            "consensus", str(src), str(cli_out2), "--method", "bin-mean",
        ]) == 0
        out3, c3 = served("third")
        assert c3.get("ingest_cache_hits", 0) == 0
        assert out3.read_bytes() == cli_out2.read_bytes()
        assert out3.read_bytes() != cli_out.read_bytes()

    def test_one_shot_cli_never_caches(self, tmp_path):
        from specpride_tpu.serve import ingest_cache as ic

        ic.clear()
        rng = np.random.default_rng(5)
        src = tmp_path / "cli_in.mgf"
        write_mgf(
            [s for c in (
                make_cluster(rng, f"z-{i}", n_members=2, n_peaks=10)
                for i in range(3)
            ) for s in c.members],
            src,
        )
        assert cli_main([
            "consensus", str(src), str(tmp_path / "o.mgf"),
            "--method", "bin-mean",
        ]) == 0
        assert ic.info()["size"] == 0, \
            "one-shot runs must not populate the serving ingest cache"


class TestWorkerTelemetry:
    def test_worker_registries_render_labeled_and_valid(self):
        from specpride_tpu.observability.exporter import (
            ServeTelemetry,
            validate_exposition,
        )
        from specpride_tpu.observability.registry import MetricsRegistry

        regs = {}
        for wid in ("0", "1"):
            r = MetricsRegistry()
            r.counter(
                "specpride_dispatches_total", "device kernel dispatches",
                labels=("kernel",),
            ).inc(3 + int(wid), kernel="bin_mean")
            r.histogram(
                "specpride_dispatch_seconds", "dispatch wall",
                labels=("kernel",),
            ).observe(0.01, kernel="bin_mean")
            regs[wid] = r
        t = ServeTelemetry(worker_registries=regs)
        t.workers.set(2)
        for wid in ("0", "1"):
            t.inflight_worker.set(0, worker=wid)
        t.job_done(
            command="consensus", method="bin-mean", status="done",
            wall_s=1.5, queue_wait_s=0.1, worker=1,
        )
        text = t.exposition()
        problems = validate_exposition(text)
        assert not problems, problems
        # one TYPE per metric even though both registries carry it
        assert text.count("# TYPE specpride_dispatches_total") == 1
        assert 'specpride_dispatches_total{worker="0",kernel="bin_mean"} 3' \
            in text
        assert 'specpride_dispatches_total{worker="1",kernel="bin_mean"} 4' \
            in text
        assert "specpride_serve_workers 2" in text
        assert 'specpride_serve_inflight_worker{worker="0"} 0' in text
        assert (
            'specpride_serve_worker_busy_seconds_total{worker="1"} 1.5'
            in text
        )

    def test_render_labeled_rejects_schema_drift(self):
        from specpride_tpu.observability.registry import (
            MetricsRegistry,
            render_labeled,
        )

        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("m_total", "x").inc(1)
        b.gauge("m_total", "x").set(1)
        with pytest.raises(ValueError):
            render_labeled({"0": a, "1": b})

    def test_live_scrape_carries_worker_series(
        self, tmp_path, workload, pool_daemon
    ):
        """The 2-worker daemon's own telemetry plane: after served jobs,
        the exposition validates strictly and carries the pool series."""
        from specpride_tpu.observability.exporter import (
            parse_exposition,
        )

        # --qc-report forces a real device dispatch (the cosine kernel)
        # even on CPU hosts where the bin-mean consensus itself computes
        # host-side — so the worker's backend registry has series
        term = sc.submit_wait(pool_daemon.socket_path, [
            "consensus", workload, str(tmp_path / "scrape.mgf"),
            "--method", "bin-mean",
            "--qc-report", str(tmp_path / "scrape.qc.json"),
        ])
        assert term["status"] == "done"
        text = pool_daemon.telemetry.exposition()
        samples, problems = parse_exposition(text)
        assert not problems, problems
        names = {name for name, _ in samples}
        assert "specpride_serve_workers" in names
        assert "specpride_serve_inflight_worker" in names
        assert "specpride_serve_worker_busy_seconds_total" in names
        # both lanes' inflight gauges are present (0 when idle)
        workers = {
            dict(labels).get("worker")
            for name, labels in samples
            if name == "specpride_serve_inflight_worker"
        }
        assert workers == {"0", "1"}
        # the resident backend registries ride along worker-labeled
        backend_workers = {
            dict(labels).get("worker")
            for name, labels in samples
            if name == "specpride_dispatches_total"
        }
        assert backend_workers <= {"0", "1"} and backend_workers
