"""Flight recorder + incident plane: detector units over synthetic
streams, the dedup cooldown's replayable accounting, ring-buffer
overwrite + torn-read hammer, atomic bundle dumps (and the ``.tmp-``
debris a mid-dump kill leaves), recorder end-to-end through a real
journal (catch-up included), ``incident-replay`` bit-parity on daemon
and merged 2-rank elastic journals, and the ``off`` kill switch
constructing no recorder at all."""

from __future__ import annotations

import json
import os
import threading

import pytest

from specpride_tpu.observability.detect import (
    DEFAULT_PARAMS,
    DETECTOR_NAMES,
    DetectorSet,
    derived_trace_id,
    incident_id,
)
from specpride_tpu.observability.flightrec import (
    FlightRecorder,
    RingBuffer,
    config_digest,
    find_bundle,
    list_bundles,
    replay_incidents,
)
from specpride_tpu.observability.journal import (
    Journal,
    read_events,
    validate_event,
)
from specpride_tpu.serve.daemon import ServeDaemon

TRACE = "ab" * 16  # any 32-hex id satisfies the v4 trace envelope


def _fold(recs):
    """One fresh DetectorSet over a synthetic record list; returns
    every firing in stream order."""
    det = DetectorSet()
    out = []
    for rec in recs:
        out.extend(det.observe(rec))
    return out


def _job_done(mono, *, ok=None, wall=0.01, job="j"):
    rec = {"event": "job_done", "mono": mono, "job_id": job,
           "status": "done", "wall_s": wall, "trace_id": TRACE}
    if ok is not None:
        rec["slo_ok"] = ok
    return rec


# -- detector units: each fires and clears on a synthetic stream --------


class TestDetectors:
    def test_slo_breach_fires_on_streak_and_clears_on_ok(self):
        streak = DEFAULT_PARAMS["slo_breach"]["streak"]
        recs = [_job_done(float(i), ok=False) for i in range(streak)]
        fired = _fold(recs)
        assert [f["detector"] for f in fired] == ["slo_breach"]
        assert fired[0]["evidence"]["streak"] == streak
        # an ok job resets the streak: the same breaches spread around
        # a success never fire
        recs = [_job_done(0.0, ok=False), _job_done(1.0, ok=False),
                _job_done(2.0, ok=True), _job_done(3.0, ok=False)]
        assert _fold(recs) == []

    def test_slo_breach_ignores_uncovered_jobs(self):
        # jobs with no objective (slo_ok absent) are not breaches
        assert _fold([_job_done(float(i)) for i in range(9)]) == []

    def test_latency_spike_after_seeding(self):
        p = DEFAULT_PARAMS["latency_spike"]
        recs = [_job_done(float(i), wall=0.1)
                for i in range(p["min_jobs"])]
        recs.append(_job_done(99.0, wall=0.1 * p["factor"] * 2))
        fired = _fold(recs)
        assert [f["detector"] for f in fired] == ["latency_spike"]
        assert fired[0]["evidence"]["ratio"] > p["factor"]

    def test_latency_spike_not_before_min_jobs(self):
        recs = [_job_done(0.0, wall=0.1), _job_done(1.0, wall=100.0)]
        assert _fold(recs) == []

    def test_queue_sat_needs_announced_capacity(self):
        queued = [{"event": "job_queued", "mono": float(i), "job_id": i,
                   "client": "t", "trace_id": TRACE} for i in range(10)]
        assert _fold(queued) == []  # no serve_start: bound unknown
        start = {"event": "serve_start", "mono": 0.0,
                 "socket": "s", "max_queue": 10}
        fired = _fold([start] + queued)
        assert fired and fired[0]["detector"] == "queue_sat"
        assert fired[0]["evidence"]["queue_depth"] == 9  # 0.9 * 10

    def test_queue_sat_drains_on_job_start(self):
        start = {"event": "serve_start", "mono": 0.0,
                 "socket": "s", "max_queue": 10}
        recs = [start]
        for i in range(20):  # every queued job starts promptly
            recs.append({"event": "job_queued", "mono": float(i),
                         "job_id": i, "client": "t", "trace_id": TRACE})
            recs.append({"event": "job_start", "mono": i + 0.5,
                         "job_id": i, "trace_id": TRACE})
        assert _fold(recs) == []

    def test_watchdog_fires_on_every_stall(self):
        rec = {"event": "watchdog_stall", "mono": 5.0, "lane": 1,
               "elapsed_s": 31.0, "timeout_s": 30.0}
        fired = _fold([rec])
        assert [f["detector"] for f in fired] == ["watchdog"]
        assert fired[0]["evidence"]["lane"] == 1

    def test_retry_exhaust_on_attempt_threshold(self):
        need = DEFAULT_PARAMS["retry_exhaust"]["attempts"]
        recs = [{"event": "retry", "mono": float(i), "site": "dispatch",
                 "attempt": i, "backoff_s": 0.1} for i in range(need)]
        fired = _fold(recs)
        assert [f["detector"] for f in fired] == ["retry_exhaust"]
        assert fired[0]["evidence"]["attempt"] == need - 1

    def test_solo_burst_counts_only_fallbacks_in_window(self):
        def dispatch(mono, status):
            return {"event": "batch_dispatch", "mono": mono,
                    "batch_id": 1, "jobs": [1], "n_jobs": 1,
                    "n_clusters": 4, "window_wait_s": 0.0,
                    "status": status, "trace_ids": [TRACE]}
        count = DEFAULT_PARAMS["solo_burst"]["count"]
        window = DEFAULT_PARAMS["solo_burst"]["window_s"]
        # shared dispatches never count
        assert _fold([dispatch(float(i), "shared")
                      for i in range(count * 2)]) == []
        # fallbacks spread wider than the window never reach the count
        spread = [dispatch(i * window, "fallback_solo")
                  for i in range(count * 2)]
        assert _fold(spread) == []
        burst = [dispatch(float(i), "fallback_solo")
                 for i in range(count)]
        fired = _fold(burst)
        assert [f["detector"] for f in fired] == ["solo_burst"]

    def test_lease_churn_over_the_lifecycle_events(self):
        count = DEFAULT_PARAMS["lease_churn"]["count"]
        recs = []
        for i in range(count):
            recs.append({"event": "lease_expire", "mono": float(i),
                         "rank": 0, "range": i})
        fired = _fold(recs)
        assert [f["detector"] for f in fired] == ["lease_churn"]
        assert fired[0]["evidence"]["churn"] == count

    def test_incident_events_never_feed_back(self):
        # the recorder's own output must not trigger detectors
        rec = {"event": "incident", "mono": 1.0, "detector": "watchdog",
               "reason": "x", "clock": 1.0, "mode": "observe",
               "bundled": False}
        det = DetectorSet()
        assert det.observe(rec) == []


# -- dedup: the cooldown window and its replayable accounting -----------


class TestDedup:
    def _stall(self, mono):
        return {"event": "watchdog_stall", "mono": mono, "lane": 0,
                "elapsed_s": 1.0, "timeout_s": 0.5}

    def test_cooldown_suppresses_and_rides_next_incident(self):
        cd = DEFAULT_PARAMS["cooldown_s"]
        det = DetectorSet()
        first = det.observe(self._stall(0.0))
        assert len(first) == 1 and first[0]["suppressed"] == 0
        # two firings inside the window are swallowed, accounted
        assert det.observe(self._stall(cd * 0.3)) == []
        assert det.observe(self._stall(cd * 0.6)) == []
        assert det.suppressed == 2
        after = det.observe(self._stall(cd + 1.0))
        assert len(after) == 1 and after[0]["suppressed"] == 2

    def test_cooldown_is_per_detector(self):
        det = DetectorSet()
        assert len(det.observe(self._stall(0.0))) == 1
        # a different detector inside the watchdog's window still fires
        need = DEFAULT_PARAMS["retry_exhaust"]["attempts"]
        fired = det.observe({"event": "retry", "mono": 1.0,
                             "site": "s", "attempt": need - 1,
                             "backoff_s": 0.1})
        assert [f["detector"] for f in fired] == ["retry_exhaust"]

    def test_identity_is_content_derived(self):
        # two folds of the same stream mint the same ids — the replay
        # bit-parity contract
        a = _fold([self._stall(7.5)])[0]
        b = _fold([self._stall(7.5)])[0]
        assert a["incident_id"] == b["incident_id"]
        assert a["incident_id"] == incident_id("watchdog", 7.5)
        assert a["trace_id"] == derived_trace_id("watchdog", 7.5)
        assert len(a["trace_id"]) == 32  # v4 envelope shape

    def test_trigger_trace_id_preferred(self):
        fired = _fold([_job_done(float(i), ok=False)
                       for i in range(3)])
        assert fired[0]["trace_id"] == TRACE


# -- ring buffer: overwrite + torn-read hammer --------------------------


class TestRingBuffer:
    def test_overwrite_keeps_newest(self):
        ring = RingBuffer(4)
        for i in range(10):
            ring.append({"i": i})
        assert len(ring) == 4
        assert ring.appended == 10
        assert [r["i"] for r in ring.snapshot()] == [6, 7, 8, 9]

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            RingBuffer(0)

    def test_snapshot_under_append_hammer(self):
        """Concurrent appends must never tear a snapshot: every copy is
        a contiguous, in-order window of the stream."""
        ring = RingBuffer(64)
        stop = threading.Event()
        errors: list = []

        def _write():
            i = 0
            while not stop.is_set():
                ring.append({"i": i})
                i += 1

        def _read():
            try:
                for _ in range(2000):
                    snap = ring.snapshot()
                    assert len(snap) <= 64
                    seq = [r["i"] for r in snap]
                    # contiguous window: strictly consecutive ints
                    assert seq == list(range(seq[0], seq[0] + len(seq))) \
                        if seq else True
            except Exception as e:  # noqa: BLE001 - report to main
                errors.append(e)

        w = threading.Thread(target=_write, daemon=True)
        readers = [threading.Thread(target=_read, daemon=True)
                   for _ in range(3)]
        w.start()
        for r in readers:
            r.start()
        for r in readers:
            r.join(timeout=60)
        stop.set()
        w.join(timeout=60)
        assert not errors, errors


# -- bundles: atomic dumps and the read side ----------------------------


class TestBundles:
    def _recorder(self, tmp_path, **kw):
        j = Journal(str(tmp_path / "j.jsonl"))
        rec = FlightRecorder(
            j, mode="on", incident_dir=str(tmp_path / "incidents"),
            **kw,
        ).start()
        return j, rec

    def _trigger(self, j):
        j.emit("watchdog_stall", lane=0, elapsed_s=2.0, timeout_s=1.0)

    def test_bundle_layout_and_manifest(self, tmp_path):
        cfg = {"host": "test", "workers": 2}
        j, rec = self._recorder(
            tmp_path,
            metrics_fn=lambda: "# HELP x\n",
            autotune_fn=lambda: {"knobs": {"workers": 2}},
            extra_fn=lambda: {"ranks": 1},
            config=cfg,
        )
        self._trigger(j)
        rec.stop()
        j.close()
        bundles, warnings = list_bundles(str(tmp_path / "incidents"))
        assert warnings == []
        assert len(bundles) == 1
        b = bundles[0]
        assert b["schema"] == 1
        assert b["incident"]["detector"] == "watchdog"
        assert b["incident"]["mode"] == "on"
        for fname in ("ring.jsonl", "stacks.txt", "journal_tail.jsonl",
                      "metrics.prom", "autotune.json", "host.json",
                      "config.json", "manifest.json"):
            path = os.path.join(b["dir"], fname)
            assert os.path.exists(path), fname
            assert fname == "manifest.json" or fname in b["files"]
        conf = json.loads(
            open(os.path.join(b["dir"], "config.json")).read()
        )
        assert conf["config"] == cfg
        assert conf["digest"] == config_digest(cfg)
        # the ring dump holds the trigger record
        ring = [json.loads(ln) for ln in
                open(os.path.join(b["dir"], "ring.jsonl"))]
        assert any(r["event"] == "watchdog_stall" for r in ring)
        stacks = open(os.path.join(b["dir"], "stacks.txt")).read()
        assert "--- thread" in stacks

    def test_failing_section_degrades_not_fails(self, tmp_path):
        def boom():
            raise RuntimeError("scrape died")
        j, rec = self._recorder(tmp_path, metrics_fn=boom)
        self._trigger(j)
        rec.stop()
        j.close()
        bundles, _ = list_bundles(str(tmp_path / "incidents"))
        assert len(bundles) == 1
        assert "metrics.error.txt" in bundles[0]["files"]
        assert "metrics.prom" not in bundles[0]["files"]
        # the incident still journaled as bundled
        events, violations = read_events(str(tmp_path / "j.jsonl"))
        assert violations == []
        inc = [e for e in events if e["event"] == "incident"]
        assert inc and inc[0]["bundled"] is True

    def test_tmp_debris_skipped_silently(self, tmp_path):
        """The atomicity contract: a kill mid-dump leaves only a
        ``.tmp-`` staging dir, which the read side ignores without
        even a warning."""
        inc_dir = tmp_path / "incidents"
        debris = inc_dir / "deadbeef00000000-watchdog.tmp-12345"
        debris.mkdir(parents=True)
        (debris / "ring.jsonl").write_text("{}\n")  # no manifest yet
        bundles, warnings = list_bundles(str(inc_dir))
        assert bundles == [] and warnings == []

    def test_manifestless_dir_is_a_warning(self, tmp_path):
        inc_dir = tmp_path / "incidents"
        (inc_dir / "odd-dir").mkdir(parents=True)
        bundles, warnings = list_bundles(str(inc_dir))
        assert bundles == []
        assert warnings and "unreadable manifest" in warnings[0]

    def test_future_schema_refused(self, tmp_path):
        inc_dir = tmp_path / "incidents"
        d = inc_dir / "aa00-watchdog"
        d.mkdir(parents=True)
        (d / "manifest.json").write_text(json.dumps({"schema": 99}))
        bundles, warnings = list_bundles(str(inc_dir))
        assert bundles == []
        assert warnings and "newer than this build" in warnings[0]

    def test_find_bundle_prefix_match(self, tmp_path):
        j, rec = self._recorder(tmp_path)
        self._trigger(j)
        rec.stop()
        j.close()
        bundles, _ = list_bundles(str(tmp_path / "incidents"))
        full = bundles[0]["incident"]["incident_id"]
        hit = find_bundle(str(tmp_path / "incidents"), full[:6])
        assert hit is not None
        assert hit["incident"]["incident_id"] == full
        assert find_bundle(str(tmp_path / "incidents"), "zzzz") is None


# -- recorder end-to-end over a real journal ----------------------------


class TestRecorderEndToEnd:
    def test_observe_journals_schema_valid_incidents(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        rec = FlightRecorder(j, mode="observe").start()
        j.emit("watchdog_stall", lane=0, elapsed_s=2.0, timeout_s=1.0)
        rec.stop()  # drains the queued firing before returning
        j.close()
        events, violations = read_events(path)
        assert violations == []
        inc = [e for e in events if e["event"] == "incident"]
        assert len(inc) == 1
        e = inc[0]
        assert validate_event(e) == []
        assert e["detector"] == "watchdog"
        assert e["mode"] == "observe"
        assert e["bundled"] is False
        assert "bundle_dir" not in e
        assert e["incident_id"] == incident_id("watchdog", e["clock"])
        assert rec.status()["fired"] == 1

    def test_catch_up_folds_pre_attach_records(self, tmp_path):
        """attach_tap catch-up: breaches journaled BEFORE the recorder
        started still fire — ring + detector state equal fold(file)
        from line one."""
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        for i in range(3):
            j.emit("job_done", job_id=i, status="done", wall_s=0.01,
                   slo_ok=False, trace_id=TRACE)
        rec = FlightRecorder(j, mode="observe").start()
        rec.stop()
        j.close()
        events, _ = read_events(path)
        inc = [e for e in events if e["event"] == "incident"]
        assert [e["detector"] for e in inc] == ["slo_breach"]
        assert rec.ring.appended >= 3

    def test_mode_on_requires_incident_dir(self, tmp_path):
        j = Journal(str(tmp_path / "j.jsonl"))
        with pytest.raises(ValueError):
            FlightRecorder(j, mode="on")
        with pytest.raises(ValueError):
            FlightRecorder(j, mode="bogus")
        j.close()

    def test_no_firings_means_no_extra_events(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        rec = FlightRecorder(j, mode="observe").start()
        for i in range(5):
            j.emit("job_done", job_id=i, status="done", wall_s=0.01,
                   trace_id=TRACE)
        rec.stop()
        j.close()
        events, _ = read_events(path)
        assert [e["event"] for e in events] == ["job_done"] * 5
        assert rec.status()["fired"] == 0


# -- the off kill switch: no recorder object at all ---------------------


class TestOffKillSwitch:
    def test_daemon_default_builds_no_recorder(self, tmp_path):
        d = ServeDaemon(str(tmp_path / "s.sock"))
        assert d.flightrec == "off"
        assert d.recorder is None
        d._boot_flightrec()  # off: a no-op, constructs nothing
        assert d.recorder is None
        assert "flightrec" not in d.status()

    def test_daemon_validates_mode(self, tmp_path):
        with pytest.raises(ValueError):
            ServeDaemon(str(tmp_path / "s.sock"), flightrec="bogus")

    def test_daemon_observe_requires_journal(self, tmp_path):
        d = ServeDaemon(str(tmp_path / "s.sock"), flightrec="observe")
        with pytest.raises(SystemExit):
            d._boot_flightrec()


# -- incident-replay: the determinism audit -----------------------------


def _daemon_style_journal(tmp_path, mode="observe"):
    """A serving-shaped journal with two incidents (slo_breach +
    watchdog) recorded live by a real recorder."""
    path = str(tmp_path / "serve.jsonl")
    j = Journal(path)
    kw = {}
    if mode == "on":
        kw["incident_dir"] = str(tmp_path / "incidents")
    rec = FlightRecorder(j, mode=mode, **kw).start()
    j.emit("serve_start", socket="s", max_queue=16)
    for i in range(3):
        j.emit("job_done", job_id=i, status="done", wall_s=0.01,
               slo_ok=False, trace_id=TRACE)
    j.emit("watchdog_stall", lane=0, elapsed_s=2.0, timeout_s=1.0)
    rec.stop()
    j.close()
    return path


class TestIncidentReplay:
    def test_daemon_journal_reproduces_bit_exact(self, tmp_path):
        path = _daemon_style_journal(tmp_path)
        res = replay_incidents(path)
        assert res["ok"], res
        assert res["incidents"] == 2
        assert res["reproduced"] == 2
        assert res["mismatches"] == []
        assert res["unjournaled"] == []
        assert res["by_detector"] == {"slo_breach": 1, "watchdog": 1}

    def test_bundled_mode_reproduces_too(self, tmp_path):
        path = _daemon_style_journal(tmp_path, mode="on")
        res = replay_incidents(path)
        assert res["ok"], res
        assert res["bundled"] == 2

    def test_flapping_dedup_accounting_replays(self, tmp_path):
        """A flapping detector journals ONE incident per cooldown
        window; the suppressed count is part of the bit-parity."""
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        rec = FlightRecorder(j, mode="observe").start()
        for _ in range(5):  # well inside one 30s cooldown window
            j.emit("watchdog_stall", lane=0, elapsed_s=2.0,
                   timeout_s=1.0)
        rec.stop()
        j.close()
        events, _ = read_events(path)
        inc = [e for e in events if e["event"] == "incident"]
        assert len(inc) == 1  # no bundle storm
        assert rec.status()["suppressed"] == 4
        res = replay_incidents(path)
        assert res["ok"], res
        assert res["incidents"] == 1

    def test_tampered_incident_fails_replay(self, tmp_path):
        path = _daemon_style_journal(tmp_path)
        lines = [json.loads(ln) for ln in open(path, encoding="utf-8")]
        for rec in lines:
            if rec.get("event") == "incident":
                rec["incident_id"] = "0" * 16  # forge the identity
                break
        with open(path, "w", encoding="utf-8") as fh:
            for rec in lines:
                fh.write(json.dumps(rec) + "\n")
        res = replay_incidents(path)
        assert not res["ok"]
        assert any("incident_id" in m for m in res["mismatches"])

    def test_observe_mode_claiming_bundled_fails(self, tmp_path):
        path = _daemon_style_journal(tmp_path)
        lines = [json.loads(ln) for ln in open(path, encoding="utf-8")]
        for rec in lines:
            if rec.get("event") == "incident":
                rec["bundled"] = True  # observe mode must never bundle
                break
        with open(path, "w", encoding="utf-8") as fh:
            for rec in lines:
                fh.write(json.dumps(rec) + "\n")
        res = replay_incidents(path)
        assert not res["ok"]
        assert any("bundled=true in observe mode" in m
                   for m in res["mismatches"])

    def test_dead_recorder_is_a_warning_not_a_failure(self, tmp_path):
        """Triggers with no incident events (a recorder killed before
        draining, or an off run) refold as `unjournaled` warnings —
        the stream holds MORE evidence than the recorder wrote."""
        path = str(tmp_path / "j.jsonl")
        j = Journal(path)
        j.emit("watchdog_stall", lane=0, elapsed_s=2.0, timeout_s=1.0)
        j.close()
        res = replay_incidents(path)
        assert res["ok"]
        assert res["incidents"] == 0
        assert len(res["unjournaled"]) == 1

    def test_two_rank_elastic_shards_replay_independently(self, tmp_path):
        """Merged ``.part<rank>`` journals: each rank's stream refolds
        through its own fresh DetectorSet — rank 0's churn must not
        leak into rank 1's fold."""
        base = str(tmp_path / "el.jsonl")
        count = DEFAULT_PARAMS["lease_churn"]["count"]
        for rank in range(2):
            j = Journal(f"{base}.part{rank:05d}")
            rec = FlightRecorder(j, mode="observe").start()
            j.emit("heartbeat", rank=rank, chunk_s=0.5)
            n = count if rank == 0 else count - 1  # rank 1: below bar
            for i in range(n):
                j.emit("lease_expire", rank=rank, range=i)
            rec.stop()
            j.close()
        res = replay_incidents(base)
        assert res["ok"], res
        assert res["streams"] == 2
        assert res["incidents"] == 1  # rank 0 only
        assert res["by_detector"] == {"lease_churn": 1}


# -- CLI surface ---------------------------------------------------------


class TestCli:
    def test_incident_replay_exit_codes(self, tmp_path, capsys):
        from specpride_tpu.cli import main as cli_main

        path = _daemon_style_journal(tmp_path)
        assert cli_main(["incident-replay", path]) == 0
        out = capsys.readouterr().out
        assert "reproduced: 2/2" in out and "ok" in out
        # tamper -> exit 1
        lines = [json.loads(ln) for ln in open(path, encoding="utf-8")]
        for rec in lines:
            if rec.get("event") == "incident":
                rec["reason"] = "forged"
                break
        with open(path, "w", encoding="utf-8") as fh:
            for rec in lines:
                fh.write(json.dumps(rec) + "\n")
        assert cli_main(["incident-replay", path]) == 1

    def test_incidents_list_show_export(self, tmp_path, capsys,
                                        monkeypatch):
        from specpride_tpu.cli import main as cli_main

        _daemon_style_journal(tmp_path, mode="on")
        inc_dir = str(tmp_path / "incidents")
        assert cli_main(["incidents", "list", inc_dir]) == 0
        out = capsys.readouterr().out
        assert "watchdog" in out and "slo_breach" in out
        iid = out.split()[0]
        assert cli_main(["incidents", "show", inc_dir, iid]) == 0
        shown = capsys.readouterr().out
        assert json.loads(shown)["incident"]["incident_id"] == iid
        monkeypatch.chdir(tmp_path)
        assert cli_main(["incidents", "export", inc_dir, iid]) == 0
        tarball = capsys.readouterr().out.strip()
        assert os.path.exists(tarball)

    def test_stats_renders_incidents(self, tmp_path, capsys):
        from specpride_tpu.cli import main as cli_main

        path = _daemon_style_journal(tmp_path)
        assert cli_main(["stats", path, "--incidents"]) == 0
        out = capsys.readouterr().out
        assert "incidents:" in out
        assert "watchdog" in out


# -- telemetry: the incident metric families ----------------------------


class TestIncidentMetrics:
    def test_counters_pre_registered_per_detector(self):
        from specpride_tpu.observability.exporter import ServeTelemetry

        t = ServeTelemetry()
        text = t.exposition()
        for det in DETECTOR_NAMES:
            assert (
                f'specpride_incidents_total{{detector="{det}"}} 0'
                in text
            ), det
        assert "specpride_incidents_suppressed_total" in text

    def test_recorder_bumps_the_counters(self, tmp_path):
        from specpride_tpu.observability.exporter import ServeTelemetry

        t = ServeTelemetry()
        j = Journal(str(tmp_path / "j.jsonl"))
        rec = FlightRecorder(j, mode="observe", telemetry=t).start()
        j.emit("watchdog_stall", lane=0, elapsed_s=2.0, timeout_s=1.0)
        rec.stop()
        j.close()
        text = t.exposition()
        assert 'specpride_incidents_total{detector="watchdog"} 1' in text
