"""Elastic tier 2: live work-stealing (split handshake, donor fence,
steal-half policy), the warm-spare fleet supervisor, the object-store
coordinator end to end, split-aware merging/stats, and the submit
retry satellite."""

import io
import json
import os
import subprocess
import sys
import threading
import time
from contextlib import redirect_stdout

import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.observability.journal import read_events
from specpride_tpu.parallel.coordinator import Coordinator
from specpride_tpu.parallel.elastic import (
    audit_elastic,
    elastic_range_table,
    summarize_ranks,
)
from specpride_tpu.parallel.fleet import FleetSupervisor, extract_flag
from specpride_tpu.parallel.store import CasServer
from specpride_tpu.robustness.errors import LeaseExpiredError

from conftest import make_cluster


class RecordingJournal:
    enabled = True

    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        rec = {"event": event, "ts": time.time(),
               "mono": time.perf_counter(), **fields}
        self.events.append(rec)
        return rec

    def close(self):
        pass


# -- the split handshake, unit level -------------------------------------


def _pair(tmp_path, n=12, chunk=2, **kw):
    ja, jb = RecordingJournal(), RecordingJournal()
    a = Coordinator(str(tmp_path), 0, n, n, ttl=5.0, journal=ja,
                    chunk_hint=chunk, **kw)
    b = Coordinator(str(tmp_path), 1, n, n, ttl=5.0, journal=jb,
                    chunk_hint=chunk, **kw)
    return a, b, ja, jb


def test_steal_handshake_moves_the_tail(tmp_path):
    """Propose -> ratify (steal-half, at a chunk boundary) -> claim:
    the donor journals lease_split, the stealer journals the paired
    chunk_reassign, and the overlay range covers exactly the ceded
    suffix."""
    a, b, ja, jb = _pair(tmp_path)
    try:
        assert a.claim_next().range.range_id == 0
        # donor progress: 2 chunks of 2 committed
        a.commit_fence(0, max_idx=1, n_clusters=2,
                       chunk_t0=time.perf_counter() - 0.1)
        a.commit_fence(0, max_idx=3, n_clusters=2,
                       chunk_t0=time.perf_counter() - 0.1)
        a._beat()
        assert b.claim_next() is None  # everything leased

        got = {}
        th = threading.Thread(
            target=lambda: got.update(c=b.try_steal(poll_timeout=3.0))
        )
        th.start()
        time.sleep(0.2)
        # donor's dispatch lane reaches the next chunk (local idx 4):
        # remaining 8 -> donor keeps 4, cedes [8, 12)
        clip = a.clip_or_ratify(0, next_min_idx=4)
        assert clip == 8
        th.join()
        tail = got["c"]
        assert tail is not None
        assert (tail.range.start, tail.range.stop) == (8, 12)
        assert tail.range.parent == 0 and tail.range.from_rank == 0
        splits = [e for e in ja.events if e["event"] == "lease_split"]
        assert splits and splits[0]["split_at"] == 8
        assert splits[0]["new_range"] == tail.range.range_id
        re = [e for e in jb.events if e["event"] == "chunk_reassign"]
        assert re and re[0]["range"] == tail.range.range_id
        assert re[0]["from_rank"] == 0 and re[0]["to_rank"] == 1
        assert not audit_elastic(ja.events + jb.events)
        assert a.lease_splits == 1 and b.steals == 1
        # the donor's effective range narrowed; commits below the cut
        # pass, the stolen suffix fences
        assert a.effective_range(0).stop == 8
    finally:
        a.stop()
        b.stop()


def test_donor_fences_on_commit_of_stolen_suffix(tmp_path):
    """A donor whose lease was split MUST get LeaseExpiredError on its
    next commit at/past the cut — the backstop that makes a zombie
    donor safe even if it never ran the dispatch-lane clip."""
    a, b, ja, jb = _pair(tmp_path)
    try:
        assert a.claim_next() is not None
        a._beat()
        got = {}
        th = threading.Thread(
            target=lambda: got.update(c=b.try_steal(poll_timeout=3.0))
        )
        th.start()
        time.sleep(0.2)
        assert a.clip_or_ratify(0, next_min_idx=4) == 8
        th.join()
        assert got["c"] is not None
        a.commit_fence(0, max_idx=7, n_clusters=2)  # below the cut: fine
        with pytest.raises(LeaseExpiredError):
            a.commit_fence(0, max_idx=8, n_clusters=2)
        # the lease itself is still the donor's (only the suffix moved)
        a.check_lease(0)
    finally:
        a.stop()
        b.stop()


def test_donor_keeps_first_chunk_and_declines_when_empty(tmp_path):
    a, b, ja, jb = _pair(tmp_path)
    try:
        assert a.claim_next() is not None
        nonce = a._held[0].nonce
        b.store.put_new(
            b._proposal_key(0, nonce),
            {"parent": 0, "stealer_rank": 1, "donor_nonce": nonce},
        )
        # nothing submitted yet: never cede the first chunk
        assert a.clip_or_ratify(0, next_min_idx=0) is None
        # on the LAST chunk: decline with a published cut so the
        # stealer's poll terminates instead of timing out
        assert a.clip_or_ratify(0, next_min_idx=10) is None
        cut = a.store.get(a._cut_key(0, nonce))
        assert cut is not None and cut[0]["new_range"] is None
        assert not [e for e in ja.events if e["event"] == "lease_split"]
    finally:
        a.stop()
        b.stop()


def test_donor_defers_its_own_split_tail(tmp_path):
    """The donor must not re-claim the tail it just ceded (it is the
    slow rank by construction); after a full expiry window unclaimed,
    it may."""
    a, b, ja, jb = _pair(tmp_path)
    try:
        assert a.claim_next() is not None
        a._beat()
        got = {}
        th = threading.Thread(
            target=lambda: got.update(c=b.try_steal(poll_timeout=3.0))
        )
        th.start()
        time.sleep(0.2)
        assert a.clip_or_ratify(0, next_min_idx=4) == 8
        th.join()
        tail_id = got["c"].range.range_id
        b.release(tail_id)  # stealer abandons (simulates its death)
        # donor finishes + releases its narrowed range; its scan must
        # NOT pick the tail back up inside the expiry window
        a.release(0)
        a.commit(0, {"output_bytes": 0, "sha256": "x"})
        claim = a.claim_next()
        assert claim is None
        # fake the window having passed: age the overlay record
        path = os.path.join(
            str(tmp_path), "overlay", f"range_{tail_id:05d}.json"
        )
        old = time.time() - 60
        os.utime(path, (old, old))
        claim = a.claim_next()
        assert claim is not None and claim.range.range_id == tail_id
    finally:
        a.stop()
        b.stop()


def test_audit_flags_unclaimed_split():
    events = [
        {"event": "lease_split", "range": 0, "new_range": 5, "rank": 0,
         "split_at": 8},
    ]
    assert len(audit_elastic(events)) == 1
    events.append({"event": "chunk_reassign", "range": 5, "from_rank": 0,
                   "to_rank": 1})
    assert not audit_elastic(events)


def test_elastic_range_table_rejects_gaps(tmp_path):
    coord = Coordinator(str(tmp_path), 0, 10, 5, ttl=5.0)
    coord.stop()
    table, problem = elastic_range_table(str(tmp_path))
    assert problem is None
    assert [(r["start"], r["stop"]) for r in table] == [(0, 5), (5, 10)]
    coord2 = Coordinator(str(tmp_path), 0, 10, 5, ttl=5.0)
    # an overlay ALLOCATION marker with no referencing cut record is
    # debris from a donor that died mid-handshake: invisible, the
    # parent stays whole and the table stays valid
    coord2.store.put_new(
        "overlay/range_00002.json",
        {"range_id": 2, "start": 3, "stop": 10, "parent": 1},
    )
    table, problem = elastic_range_table(str(tmp_path))
    assert problem is None
    assert [(r["start"], r["stop"]) for r in table] == [(0, 5), (5, 10)]
    # a tampered CUT whose tail overlaps the plan must refuse
    coord2.store.put_new(
        "split/range_00001.cut.deadbeef.json",
        {"cut": 3, "new_range": 2, "stop": 10, "parent": 1},
    )
    coord2.stop()
    table, problem = elastic_range_table(str(tmp_path))
    assert table is None and "tile" in problem


# -- stats: slow marker + split counters ---------------------------------


def test_stats_slow_marker_and_split_rollup(capsys):
    base = time.time()
    donor = [
        {"event": "heartbeat", "rank": 0, "holding": [0], "ttl": 1.0,
         "ts": base},
        {"event": "lease_claim", "rank": 0, "range": 0, "takeover": False,
         "ts": base},
        {"event": "lease_split", "range": 0, "new_range": 2, "rank": 0,
         "split_at": 8, "ts": base + 0.5},
    ]
    stealer = [
        {"event": "heartbeat", "rank": 1, "holding": [], "ttl": 1.0,
         "ts": base + 5.0},
        {"event": "chunk_reassign", "range": 2, "from_rank": 0,
         "to_rank": 1, "via": "lease_split", "ts": base + 1.0},
    ]
    view = summarize_ranks([donor, stealer])
    assert view["lease_splits"] == 1
    assert view["unpaired_lease_expiries"] == 0
    r0, r1 = view["ranks"]["0"], view["ranks"]["1"]
    # rank 0: silent for 5s with TTL 1 while holding a lease, never
    # expired -> stale-but-alive
    assert r0["slow"] is True and r0["lease_splits"] == 1
    assert r1["slow"] is False and r1["steals"] == 1

    from specpride_tpu.observability.stats_cli import _render_rank_view

    out = io.StringIO()
    _render_rank_view(view, out)
    text = out.getvalue()
    assert "slow: " in text and "1 split(s)" in text
    assert "lease_splits=1" in text and "steals=1" in text


# -- end to end ----------------------------------------------------------


def _write_input(tmp_path, rng, n):
    clusters = [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=20)
        for i in range(n)
    ]
    src = tmp_path / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], src)
    return src


def _serial_golden(tmp_path, src, backend="tpu"):
    out = tmp_path / "serial.mgf"
    qc = tmp_path / "serial_qc.json"
    assert cli_main([
        "consensus", str(src), str(out), "--method", "bin-mean",
        "--backend", backend, "--qc-report", str(qc),
    ]) == 0
    return out.read_bytes(), qc.read_bytes()


def test_forced_steal_two_ranks_byte_identical(tmp_path, rng):
    """The tier-2 acceptance scenario in miniature: a rank_slow-
    handicapped donor and a fast peer; the peer must steal a split of
    the donor's range (lease_split paired with chunk_reassign) and the
    merged output + QC report stay byte-identical to serial."""
    src = _write_input(tmp_path, rng, 24)
    golden, golden_qc = _serial_golden(tmp_path, src)
    out = tmp_path / "out.mgf"
    coord = tmp_path / "coord"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_NUM_CPU_DEVICES", None)
    slow_env = dict(
        env, SPECPRIDE_FAULTS="dispatch:rank_slow:1:0:9999",
        SPECPRIDE_SLOW_S="0.4",
    )

    def argv(rank):
        return [
            sys.executable, "-m", "specpride_tpu", "consensus", str(src),
            str(out), "--method", "bin-mean",
            "--elastic", str(coord), "--process-id", str(rank),
            "--elastic-range", "12", "--checkpoint-every", "2",
            "--elastic-ttl", "2",
            "--qc-report", f"{out}.qc.json",
            "--journal", str(tmp_path / "j.jsonl"),
        ]

    procs = [
        subprocess.Popen(argv(0), env=slow_env,
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.PIPE),
        subprocess.Popen(argv(1), env=env, stdout=subprocess.DEVNULL,
                         stderr=subprocess.PIPE),
    ]
    for p in procs:
        _, err = p.communicate(timeout=240)
        assert p.returncode == 0, err.decode()[-3000:]
    assert cli_main([
        "merge-parts", str(out), "--elastic", str(coord),
        "--qc-report", f"{out}.qc.json",
    ]) == 0
    assert out.read_bytes() == golden
    assert (tmp_path / "out.mgf.qc.json").read_bytes() == golden_qc
    events = []
    for r in (0, 1):
        ev, violations = read_events(str(tmp_path / f"j.jsonl.part0000{r}"))
        assert not violations, violations[:5]
        events += ev
    splits = [e for e in events if e["event"] == "lease_split"]
    assert splits, "the slow rank was never relieved"
    reassigns = [
        e for e in events
        if e["event"] == "chunk_reassign" and e.get("via") == "lease_split"
    ]
    assert any(e["to_rank"] == 1 for e in reassigns)
    assert not audit_elastic(events)
    ends = [e for e in events if e["event"] == "run_end"]
    assert sum(e["elastic"]["lease_splits"] for e in ends) == len(splits)
    assert sum(e["elastic"]["steals"] for e in ends) >= 1


def test_object_store_elastic_byte_identical(tmp_path, rng):
    """A full elastic run against the in-tree CAS object store — no
    coordinator directory at all — merges byte-identically, and the
    coordinator state (plan/lease/done) lives server-side."""
    src = _write_input(tmp_path, rng, 8)
    golden, golden_qc = _serial_golden(tmp_path, src)
    server = CasServer().start()
    try:
        out = tmp_path / "os.mgf"
        assert cli_main([
            "consensus", str(src), str(out), "--method", "bin-mean",
            "--elastic", server.url, "--process-id", "0",
            "--elastic-range", "3", "--checkpoint-every", "1",
            "--qc-report", f"{out}.qc.json",
            "--journal", str(tmp_path / "jos.jsonl"),
        ]) == 0
        assert cli_main([
            "merge-parts", str(out), "--elastic", server.url,
            "--qc-report", f"{out}.qc.json",
        ]) == 0
        assert out.read_bytes() == golden
        assert (tmp_path / "os.mgf.qc.json").read_bytes() == golden_qc
        # coordination state went through the store, not the filesystem
        assert server._data.get("plan.json") is not None
        assert [k for k in server._data if k.startswith("done/")]
        ev, violations = read_events(str(tmp_path / "jos.jsonl.part00000"))
        assert not violations
        end = [e for e in ev if e["event"] == "run_end"][-1]
        assert end["elastic"]["backend"].startswith("object-store:")
    finally:
        server.stop()


# -- fleet supervisor ----------------------------------------------------


def test_fleet_supervises_to_completion(tmp_path, rng):
    """`specpride fleet --ranks 2` drives an elastic run to exit 0 with
    journaled rank_spawn events and a byte-identical merge."""
    src = _write_input(tmp_path, rng, 8)
    golden, _ = _serial_golden(tmp_path, src, backend="numpy")
    out = tmp_path / "out.mgf"
    coord = tmp_path / "coord"
    fj = tmp_path / "fleet.jsonl"
    assert cli_main([
        "fleet", "--ranks", "2", "--timeout", "180",
        "--journal", str(fj), "--",
        "consensus", str(src), str(out), "--method", "bin-mean",
        "--backend", "numpy",
        "--elastic", str(coord), "--elastic-range", "3",
        "--checkpoint-every", "1", "--elastic-ttl", "2",
    ]) == 0
    assert cli_main([
        "merge-parts", str(out), "--elastic", str(coord),
    ]) == 0
    assert out.read_bytes() == golden
    events, violations = read_events(str(fj))
    assert not violations
    spawns = [e for e in events if e["event"] == "rank_spawn"]
    assert len(spawns) == 2
    assert all(e["reason"] == "boot" for e in spawns)


def test_fleet_requires_elastic_and_rejects_process_id(tmp_path):
    with pytest.raises(ValueError):
        FleetSupervisor(["consensus", "a", "b"], ranks=1)
    with pytest.raises(ValueError):
        FleetSupervisor(
            ["consensus", "a", "b", "--elastic", str(tmp_path),
             "--process-id", "0"],
            ranks=1,
        )
    assert extract_flag(["--elastic=x", "--elastic", "y"], "--elastic") == "y"


# -- submit --retry -------------------------------------------------------


def test_submit_retry_backs_off_on_retriable(tmp_path):
    """With no daemon listening, every attempt is retriable: --retry 2
    must make exactly 3 attempts with journaled backoff lines and still
    exit 75."""
    sock = str(tmp_path / "no-daemon.sock")
    buf = io.StringIO()
    t0 = time.perf_counter()
    with redirect_stdout(buf):
        rc = cli_main([
            "submit", "--socket", sock, "--retry", "2",
            "--retry-backoff", "0.05", "--timeout", "0.2",
            "--", "consensus", "in.mgf", "out.mgf",
        ])
    elapsed = time.perf_counter() - t0
    assert rc == 75
    lines = [json.loads(line) for line in buf.getvalue().splitlines()]
    errors = [m for m in lines if m.get("status") == "error"]
    retries = [m for m in lines if m.get("status") == "retrying"]
    assert len(errors) == 3 and len(retries) == 2
    assert retries[0]["attempt"] == 1 and retries[1]["attempt"] == 2
    # exponential: second wait ~2x the first, plus deterministic jitter
    assert retries[1]["backoff_s"] > retries[0]["backoff_s"]
    assert elapsed >= retries[0]["backoff_s"] + retries[1]["backoff_s"]


def test_submit_no_retry_by_default(tmp_path):
    sock = str(tmp_path / "no-daemon.sock")
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli_main([
            "submit", "--socket", sock, "--timeout", "0.2",
            "--", "consensus", "in.mgf", "out.mgf",
        ])
    assert rc == 75
    lines = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert not [m for m in lines if m.get("status") == "retrying"]
