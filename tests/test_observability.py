"""Observability subsystem: run journal, metrics registry / Prometheus
exporter, backend instrumentation, and the `specpride stats` command."""

import json
import os

import numpy as np
import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.io.mgf import read_mgf, write_mgf
from specpride_tpu.observability import (
    EVENT_FIELDS,
    SCHEMA_VERSION,
    Journal,
    MetricsRegistry,
    NullJournal,
    RunStats,
    device_summary,
    expand_parts,
    open_journal,
    read_events,
    validate_event,
)
from specpride_tpu.observability.stats_cli import run_stats

from conftest import make_cluster

GOLDEN = os.path.join(
    os.path.dirname(__file__), "data", "golden_clustered.mgf"
)


# ---------------------------------------------------------------------------
# RunStats
# ---------------------------------------------------------------------------

class TestRunStats:
    def test_throughput_uses_work_phases_not_wall_time(self):
        """A resumed run spends wall time on parse/skip; the rate must be
        clusters over compute+write, not clusters over elapsed."""
        stats = RunStats()
        stats.count("clusters", 100)
        # simulate 0.2 s of work inside a much longer wall clock
        stats.phases["compute"] = 0.15
        stats.phases["write"] = 0.05
        stats._start -= 100.0  # pretend the run has been up 100 s
        assert stats.throughput("clusters") == pytest.approx(500.0)

    def test_throughput_falls_back_to_wall_time(self):
        stats = RunStats()
        stats.count("clusters", 10)
        assert stats.throughput("clusters") > 0.0


# ---------------------------------------------------------------------------
# Journal
# ---------------------------------------------------------------------------

class TestJournal:
    def test_events_are_versioned_lines(self, tmp_path):
        path = tmp_path / "j.jsonl"
        with Journal(path) as j:
            j.emit("run_start", command="consensus", method="bin-mean",
                   backend="tpu", n_clusters=4)
            j.emit("chunk_start", chunk_index=0, n_clusters=4)
        events, violations = read_events(str(path))
        assert violations == []
        assert [e["event"] for e in events] == ["run_start", "chunk_start"]
        assert all(e["v"] == SCHEMA_VERSION for e in events)
        assert all(isinstance(e["ts"], float) for e in events)

    def test_numpy_scalars_serialize(self, tmp_path):
        path = tmp_path / "j.jsonl"
        with Journal(path) as j:
            j.emit("chunk_start", chunk_index=np.int64(1),
                   n_clusters=np.int32(7))
        events, violations = read_events(str(path))
        assert violations == []
        assert events[0]["n_clusters"] == 7

    def test_validate_rejects_unknown_and_missing(self):
        assert validate_event({"v": 1, "ts": 0.0, "event": "nope"})
        assert validate_event(
            {"v": 1, "ts": 0.0, "event": "chunk_start"}
        )  # missing required fields
        assert validate_event({"v": 2, "ts": 0.0, "event": "resume",
                               "n_done": 1})
        assert validate_event(
            {"v": 1, "ts": 0.0, "event": "resume", "n_done": 3}
        ) == []

    def test_null_journal_is_inert(self):
        j = NullJournal()
        assert j.emit("anything", x=1) == {}
        j.close()
        assert open_journal(None).enabled is False

    def test_reopen_heals_torn_final_line(self, tmp_path):
        """A kill mid-write leaves a partial line with no newline; the
        resumed run's first event must start on a fresh line, not fuse
        with the torn fragment."""
        path = tmp_path / "torn.jsonl"
        with Journal(path) as j:
            j.emit("chunk_start", chunk_index=0, n_clusters=4)
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('{"v": 1, "ts": 99.9, "event": "chunk_do')  # torn
        with Journal(path) as j:
            j.emit("resume", n_done=4)
        events, violations = read_events(str(path))
        assert [e["event"] for e in events] == ["chunk_start", "resume"]
        assert len(violations) == 1  # only the torn line itself

    def test_expand_parts_rank_order_and_gap(self, tmp_path):
        base = tmp_path / "j.jsonl"
        for rank in (0, 2, 10):
            (tmp_path / f"j.jsonl.part{rank:05d}").write_text("")
        paths, warnings = expand_parts(str(base))
        assert [p.rsplit(".part", 1)[1] for p in paths] == [
            "00000", "00002", "00010"
        ]
        assert any("missing" in w for w in warnings)


# ---------------------------------------------------------------------------
# Metrics registry + Prometheus exporter
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "help", labels=("k",))
        c.inc(2, k="a")
        c.inc(3, k="a")
        c.inc(1, k="b")
        assert c.value(k="a") == 5
        assert reg.sum_counter("t_total") == 6
        g = reg.gauge("g")
        g.set(1.5)
        assert g.value() == 1.5
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(99.0)
        text = reg.to_prometheus_text()
        assert 'h_seconds_bucket{le="0.1"} 1' in text
        assert 'h_seconds_bucket{le="1"} 2' in text
        assert 'h_seconds_bucket{le="+Inf"} 3' in text
        assert "h_seconds_count 3" in text

    def test_counters_refuse_negative_and_kind_conflicts(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.counter("c_total").inc(-1)
        reg.counter("x", labels=("a",))
        with pytest.raises(ValueError):
            reg.gauge("x", labels=("a",))
        with pytest.raises(ValueError):
            reg.counter("x", labels=("b",))

    def test_prometheus_format_help_type_and_escaping(self):
        reg = MetricsRegistry()
        reg.counter(
            'esc_total', 'help with \\ and\nnewline', labels=("lab",)
        ).inc(1, lab='va"l\\ue\nx')
        text = reg.to_prometheus_text()
        assert "# HELP esc_total help with \\\\ and\\nnewline\n" in text
        assert "# TYPE esc_total counter\n" in text
        assert 'esc_total{lab="va\\"l\\\\ue\\nx"} 1' in text
        assert text.endswith("\n")

    def test_textfile_rewrite_is_idempotent(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("a_total").inc(4)
        reg.gauge("b").set(2.0)
        out = tmp_path / "m.prom"
        reg.write_textfile(str(out))
        first = out.read_text()
        reg.write_textfile(str(out))
        assert out.read_text() == first  # replaced, never appended
        assert not os.path.exists(str(out) + ".tmp")

    def test_device_summary_fixed_schema(self):
        empty = device_summary(None)
        reg = MetricsRegistry()
        reg.counter("specpride_compiles_total", labels=("kernel",)).inc(
            2, kernel="k"
        )
        reg.counter("specpride_pack_real_elements_total",
                    labels=("kernel",)).inc(30, kernel="k")
        reg.counter("specpride_pack_padded_elements_total",
                    labels=("kernel",)).inc(40, kernel="k")
        full = device_summary(reg)
        assert set(full) == set(empty)  # numpy and device diff cleanly
        assert full["compiles"] == 2
        assert full["padding_waste_frac"] == pytest.approx(0.25)


# ---------------------------------------------------------------------------
# Backend instrumentation
# ---------------------------------------------------------------------------

class TestBackendInstrumentation:
    def test_device_dispatch_metrics_and_journal(self, tmp_path, rng):
        from specpride_tpu.backends.tpu_backend import TpuBackend

        clusters = [
            make_cluster(rng, f"c{i}", n_members=3, n_peaks=40)
            for i in range(4)
        ]
        jpath = tmp_path / "j.jsonl"
        backend = TpuBackend(layout="flat", journal=Journal(jpath))
        reps = backend.run_bin_mean(clusters)
        backend.journal.close()
        assert len(reps) == 4
        summary = device_summary(backend.metrics)
        assert summary["compiles"] >= 1
        assert summary["dispatches"] >= 1
        assert summary["bytes_h2d"] > 0
        assert summary["bytes_d2h"] > 0
        assert 0.0 <= summary["padding_waste_frac"] < 1.0
        events, violations = read_events(str(jpath))
        assert violations == []
        kinds = {e["event"] for e in events}
        assert {"compile", "dispatch"} <= kinds

    def test_pack_accounting_lazy_without_consumer(self, rng):
        """Bare library use (no journal, accounting off) must skip the
        O(rows*k) real-element reductions; attaching a journal turns
        them on."""
        from specpride_tpu.backends.tpu_backend import TpuBackend

        clusters = [
            make_cluster(rng, f"c{i}", n_members=3, n_peaks=40)
            for i in range(4)
        ]
        bare = TpuBackend(layout="bucketized")
        bare.run_bin_mean(clusters)
        assert device_summary(bare.metrics)["pack_real_elements"] == 0

        accounted = TpuBackend(layout="bucketized", pack_accounting=True)
        accounted.run_bin_mean(clusters)
        assert device_summary(accounted.metrics)["pack_real_elements"] > 0

    def test_second_run_reuses_compiled_shapes(self, rng):
        from specpride_tpu.backends.tpu_backend import TpuBackend

        clusters = [
            make_cluster(rng, f"c{i}", n_members=3, n_peaks=40)
            for i in range(4)
        ]
        backend = TpuBackend(layout="flat")
        backend.run_bin_mean(clusters)
        compiles_1 = device_summary(backend.metrics)["compiles"]
        backend.run_bin_mean(clusters)
        after = device_summary(backend.metrics)
        assert after["compiles"] == compiles_1  # same shapes: no new trace
        assert after["dispatches"] > compiles_1


# ---------------------------------------------------------------------------
# CLI end-to-end: --journal / --metrics-out / stats
# ---------------------------------------------------------------------------

class TestCliJournal:
    def run_consensus(self, tmp_path, *extra):
        out = tmp_path / "reps.mgf"
        jpath = tmp_path / "run.jsonl"
        rc = cli_main([
            "consensus", GOLDEN, str(out), "--method", "bin-mean",
            "--backend", "tpu", "--journal", str(jpath), *extra,
        ])
        assert rc == 0
        return out, jpath

    def test_journal_matches_output(self, tmp_path):
        out, jpath = self.run_consensus(tmp_path)
        events, violations = read_events(str(jpath))
        assert violations == []
        kinds = [e["event"] for e in events]
        assert kinds[0] == "run_start"
        assert kinds[-1] == "run_end"
        assert "chunk_start" in kinds and "chunk_done" in kinds
        end = events[-1]
        n_written = len(read_mgf(str(out)))
        assert end["counters"]["representatives"] == n_written
        assert end["representatives_written"] == n_written
        # the device dict is schema-stable across backends
        assert set(end["device"]) == set(device_summary(None))

    def test_resume_event_journaled(self, tmp_path):
        ck = tmp_path / "ck.json"
        out, jpath = self.run_consensus(tmp_path, "--checkpoint", str(ck))
        # second run with the same manifest resumes (everything done)
        jpath2 = tmp_path / "resume.jsonl"
        rc = cli_main([
            "consensus", GOLDEN, str(out), "--method", "bin-mean",
            "--backend", "tpu", "--checkpoint", str(ck),
            "--journal", str(jpath2),
        ])
        assert rc == 0
        events, violations = read_events(str(jpath2))
        assert violations == []
        resumes = [e for e in events if e["event"] == "resume"]
        assert len(resumes) == 1
        assert resumes[0]["n_done"] == len(read_mgf(str(out)))

    def test_interrupted_run_then_resume(self, tmp_path):
        """A journal from a killed run has heartbeats but no run_end; the
        resumed run journals `resume` and completes."""
        clustered = read_mgf(GOLDEN)
        out = tmp_path / "reps.mgf"
        ck = tmp_path / "ck.json"
        j1 = tmp_path / "dead.jsonl"
        # simulate the kill: run only the first cluster, checkpoint it
        ids = sorted({s.cluster_id for s in clustered})
        first = [s for s in clustered if s.cluster_id == ids[0]]
        partial_src = tmp_path / "first.mgf"
        write_mgf(first, str(partial_src))
        rc = cli_main([
            "consensus", str(partial_src), str(out), "--method", "bin-mean",
            "--backend", "tpu", "--checkpoint", str(ck),
            "--journal", str(j1),
        ])
        assert rc == 0
        dead_events, _ = read_events(str(j1))
        assert any(e["event"] == "chunk_done" for e in dead_events)
        # resume over the FULL input with the same manifest
        j2 = tmp_path / "resumed.jsonl"
        rc = cli_main([
            "consensus", GOLDEN, str(out), "--method", "bin-mean",
            "--backend", "tpu", "--checkpoint", str(ck),
            "--journal", str(j2),
        ])
        assert rc == 0
        events, violations = read_events(str(j2))
        assert violations == []
        assert any(e["event"] == "resume" for e in events)
        assert len(read_mgf(str(out))) == len(ids)

    def test_metrics_out_prometheus(self, tmp_path):
        mpath = tmp_path / "m.prom"
        self.run_consensus(tmp_path, "--metrics-out", str(mpath))
        text = mpath.read_text()
        assert "# TYPE specpride_run_representatives_total counter" in text
        assert "# TYPE specpride_padding_waste_frac gauge" in text
        assert "specpride_phase_seconds_total{phase=" in text

    def test_numpy_backend_same_schema(self, tmp_path):
        out = tmp_path / "reps.mgf"
        jpath = tmp_path / "np.jsonl"
        rc = cli_main([
            "consensus", GOLDEN, str(out), "--method", "bin-mean",
            "--backend", "numpy", "--journal", str(jpath),
        ])
        assert rc == 0
        events, violations = read_events(str(jpath))
        assert violations == []
        end = next(e for e in events if e["event"] == "run_end")
        assert set(end["device"]) == set(device_summary(None))

    def test_skipped_clusters_full_list_journaled(self, tmp_path, rng):
        """--on-error skip must journal EVERY skipped id (the log line
        truncates at 5)."""
        good = make_cluster(rng, "good", n_members=3, charge=2)
        bad = []
        for i in range(7):
            c = make_cluster(rng, f"bad{i}", n_members=2, charge=2)
            c.members[1].precursor_charge = 3  # mixed charge: bin-mean raises
            bad.append(c)
        src = tmp_path / "mixed.mgf"
        write_mgf([s for c in [good, *bad] for s in c.members], str(src))
        jpath = tmp_path / "skip.jsonl"
        rc = cli_main([
            "consensus", str(src), str(tmp_path / "o.mgf"),
            "--method", "bin-mean", "--backend", "numpy",
            "--on-error", "skip", "--journal", str(jpath),
        ])
        assert rc == 0
        events, violations = read_events(str(jpath))
        assert violations == []
        skipped = next(
            e for e in events if e["event"] == "skipped_clusters"
        )
        assert sorted(skipped["cluster_ids"]) == sorted(
            c.cluster_id for c in bad
        )

    def test_stats_command(self, tmp_path, capsys):
        out, jpath = self.run_consensus(tmp_path)
        agg = tmp_path / "agg.json"
        rc = cli_main(["stats", str(jpath), "--json", str(agg)])
        assert rc == 0
        text = capsys.readouterr().out
        assert "padding_waste_frac" in text
        assert "compile_count" in text
        data = json.loads(agg.read_text())
        assert data["v"] == 1
        run = data["runs"][0]
        assert run["complete"] is True
        assert run["representatives_written"] == len(read_mgf(str(out)))

    def test_stats_fails_on_schema_violation(self, tmp_path, capsys):
        bad = tmp_path / "bad.jsonl"
        bad.write_text(
            '{"v": 1, "ts": 1.0, "event": "made_up_event"}\n'
            "not json at all\n"
        )
        rc = run_stats([str(bad)])
        assert rc == 1

    def test_stats_survives_corrupt_lines(self, tmp_path, capsys):
        """Post-mortem inputs are exactly the corrupt ones: a record with
        no 'event', a truncated chunk_done — stats must report violations
        and exit 1, never traceback."""
        bad = tmp_path / "corrupt.jsonl"
        bad.write_text(
            '{"v": 1, "ts": 1}\n'
            '{"v": 1, "ts": 2.0, "event": "chunk_done", "chunk_index": 0}\n'
            '{"v": 1, "ts": 3.0, "event": "resume", "n_done": 2}\n'
        )
        rc = run_stats([str(bad)])
        assert rc == 1
        out = capsys.readouterr()
        assert "schema violation" in out.err
        # the valid resume event still made it into the summary
        assert "resumes=" in out.out or "INCOMPLETE" in out.out

    def test_stats_splits_appended_runs(self, tmp_path, capsys):
        """A crashed run resumed with the same --journal path appends a
        second run to the file; each must be summarized separately, not
        run 1's heartbeats paired with run 2's run_end."""
        j = tmp_path / "two.jsonl"
        with Journal(j) as jj:
            jj.emit("run_start", command="consensus", method="bin-mean",
                    backend="tpu", n_clusters=30)
            jj.emit("chunk_done", chunk_index=0, n_clusters=30,
                    n_representatives=30, elapsed_s=1.0,
                    clusters_per_sec=30.0)
            # crash: no run_end — then the resumed run appends
            jj.emit("run_start", command="consensus", method="bin-mean",
                    backend="tpu", n_clusters=10)
            jj.emit("resume", n_done=30)
            jj.emit("run_end", counters={"clusters": 10,
                                         "representatives": 10},
                    phases_s={}, elapsed_s=1.0,
                    representatives_written=10,
                    device=device_summary(None))
        agg = tmp_path / "agg.json"
        assert run_stats([str(j)], json_out=str(agg)) == 0
        data = json.loads(agg.read_text())
        assert len(data["runs"]) == 2
        assert data["runs"][0]["complete"] is False
        assert data["runs"][0]["chunks"] == 1
        assert data["runs"][1]["complete"] is True
        assert data["runs"][1]["chunks"] == 0
        assert data["runs"][1]["resumes"] == 1

    def test_stats_merges_rank_parts(self, tmp_path):
        base = tmp_path / "multi.jsonl"
        for rank in range(2):
            with Journal(f"{base}.part{rank:05d}") as j:
                j.emit("run_start", command="consensus", method="bin-mean",
                       backend="tpu", n_clusters=2)
                j.emit("run_end", counters={"clusters": 2,
                                            "representatives": 2},
                       phases_s={}, elapsed_s=1.0,
                       representatives_written=2,
                       device=device_summary(None))
        agg = tmp_path / "agg.json"
        rc = run_stats([str(base)], json_out=str(agg))
        assert rc == 0
        data = json.loads(agg.read_text())
        assert data["totals"]["n_journals"] == 2
        assert data["totals"]["representatives_written"] == 4

    def test_incomplete_journal_reported(self, tmp_path, capsys):
        dead = tmp_path / "dead.jsonl"
        with Journal(dead) as j:
            j.emit("run_start", command="consensus", method="bin-mean",
                   backend="tpu", n_clusters=10)
            j.emit("chunk_done", chunk_index=0, n_clusters=5,
                   n_representatives=5, elapsed_s=0.5,
                   clusters_per_sec=10.0)
        rc = run_stats([str(dead)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "INCOMPLETE" in out
        assert "chunk 0" in out


# ---------------------------------------------------------------------------
# Hierarchical span tracing (v2)
# ---------------------------------------------------------------------------

class TestTracer:
    def test_nested_spans_journal_valid_v2_events(self, tmp_path):
        from specpride_tpu.observability import Tracer

        jpath = tmp_path / "t.jsonl"
        with Journal(jpath) as j:
            tracer = Tracer(journal=j)
            with tracer.span("outer", chunk=0):
                with tracer.span("inner") as sp:
                    sp.note(rows=7)
        events, violations = read_events(str(jpath))
        assert violations == []
        # children close (and journal) before their parents
        assert [(e["name"], e["depth"]) for e in events] == [
            ("inner", 1), ("outer", 0)
        ]
        inner, outer = events
        assert inner["labels"] == {"rows": 7}
        assert outer["labels"] == {"chunk": 0}
        # envelope: monotonic end time present, duration sane, nested
        assert all(isinstance(e["mono"], float) for e in events)
        assert inner["dur_s"] <= outer["dur_s"]

    def test_complete_records_retroactive_span(self, tmp_path):
        import time

        from specpride_tpu.observability import Tracer

        jpath = tmp_path / "t.jsonl"
        with Journal(jpath) as j:
            tracer = Tracer(journal=j)
            t0 = time.perf_counter() - 0.25
            tracer.complete("kernel:k1", t0, 0.25, compile=True)
        events, violations = read_events(str(jpath))
        assert violations == []
        assert events[0]["name"] == "kernel:k1"
        assert events[0]["dur_s"] == pytest.approx(0.25)
        assert events[0]["labels"]["compile"] is True

    def test_module_helpers_noop_without_tracer(self):
        from specpride_tpu.observability import tracing

        assert tracing.current().enabled is False
        with tracing.span("anything", x=1) as sp:
            sp.note(y=2)  # must not raise
        tracing.current().complete("k", 0.0, 1.0)

        calls = []

        @tracing.traced("fn")
        def fn(a):
            calls.append(a)
            return a * 2

        assert fn(21) == 42 and calls == [21]

    def test_set_current_returns_previous(self):
        from specpride_tpu.observability import Tracer, tracing

        t1 = Tracer()
        prev = tracing.set_current(t1)
        try:
            assert prev.enabled is False
            assert tracing.current() is t1
            assert tracing.set_current(None) is t1
            assert tracing.current().enabled is False
        finally:
            tracing.set_current(None)

    def test_aggregate_spans_self_time_and_percentiles(self):
        from specpride_tpu.observability.tracing import aggregate_spans

        def span(name, start, dur):
            return {"v": 2, "ts": start + dur, "mono": start + dur,
                    "event": "span", "name": name,
                    "dur_s": dur, "depth": 0}

        # parent [0, 1.0] containing child [0.2, 0.5]: parent self time
        # must exclude the contained child
        events = [
            span("child", 0.2, 0.3),
            span("parent", 0.0, 1.0),
            span("child", 2.0, 0.1),
        ]
        rows = {r["name"]: r for r in aggregate_spans([events])}
        assert rows["parent"]["self_s"] == pytest.approx(0.7)
        assert rows["parent"]["total_s"] == pytest.approx(1.0)
        assert rows["child"]["count"] == 2
        assert rows["child"]["self_s"] == pytest.approx(0.4)
        assert rows["child"]["p50_s"] in (0.1, 0.3)
        assert rows["child"]["max_s"] == pytest.approx(0.3)

    def test_rank_of_path(self):
        from specpride_tpu.observability.tracing import rank_of_path

        assert rank_of_path("j.jsonl.part0") == 0
        assert rank_of_path("j.jsonl.part00003") == 3
        assert rank_of_path("j.jsonl", default=7) == 7


class TestChromeTrace:
    def run_traced_consensus(self, tmp_path):
        out = tmp_path / "reps.mgf"
        jpath = tmp_path / "run.jsonl"
        tpath = tmp_path / "trace.json"
        rc = cli_main([
            "consensus", GOLDEN, str(out), "--method", "bin-mean",
            "--backend", "tpu", "--journal", str(jpath),
            "--chrome-trace", str(tpath),
        ])
        assert rc == 0
        return jpath, tpath

    def test_chrome_trace_is_wellformed(self, tmp_path):
        _, tpath = self.run_traced_consensus(tmp_path)
        trace = json.loads(tpath.read_text())
        events = trace["traceEvents"]
        assert events
        for e in events:
            assert {"ph", "ts", "pid"} <= set(e)
        spans = [e for e in events if e["ph"] == "X"]
        assert all(e["dur"] >= 0 for e in spans)
        names = {e["name"] for e in spans}
        assert {"parse", "compute", "write", "chunk"} <= names

    def test_spans_cover_phase_timer_time(self, tmp_path):
        """Acceptance bar: the trace's phase-named spans account for
        >=95% of the summed phase-timer seconds (they are the same
        intervals by construction — RunStats.phase opens a span)."""
        jpath, tpath = self.run_traced_consensus(tmp_path)
        events, _ = read_events(str(jpath))
        end = next(e for e in events if e["event"] == "run_end")
        phase_total = sum(end["phases_s"].values())
        spans = [
            e for e in json.loads(tpath.read_text())["traceEvents"]
            if e["ph"] == "X" and e["name"] in end["phases_s"]
        ]
        span_total = sum(e["dur"] for e in spans) / 1e6
        assert span_total >= 0.95 * phase_total

    def test_journal_spans_match_kept_spans(self, tmp_path):
        """`specpride trace` over the journal reconstructs exactly the
        spans the in-process --chrome-trace export kept — including the
        parse spans, which finish before the journal opens and replay
        into it when it attaches (attach_journal)."""
        jpath, tpath = self.run_traced_consensus(tmp_path)
        recon = tmp_path / "recon.json"
        rc = cli_main(["trace", str(jpath), "-o", str(recon)])
        assert rc == 0
        direct = sorted(
            e["name"]
            for e in json.loads(tpath.read_text())["traceEvents"]
            if e["ph"] == "X"
        )
        rebuilt = sorted(
            e["name"]
            for e in json.loads(recon.read_text())["traceEvents"]
            if e["ph"] == "X"
        )
        assert any(n.startswith("parse") for n in rebuilt)
        assert rebuilt == direct

    def test_kernel_spans_nest_inside_dispatch_phase(self, tmp_path, rng):
        """Retroactive kernel:<name> spans must END no later than the
        dispatch phase span that contained the call — time-containment
        nesting (aggregate_spans self time, Perfetto) depends on it."""
        from specpride_tpu.backends.tpu_backend import TpuBackend
        from specpride_tpu.observability import RunStats, Tracer
        from specpride_tpu.observability import tracing

        clusters = [
            make_cluster(rng, f"c{i}", n_members=3, n_peaks=40)
            for i in range(4)
        ]
        jpath = tmp_path / "k.jsonl"
        backend = TpuBackend(layout="bucketized", journal=Journal(jpath))
        prev = tracing.set_current(Tracer(journal=backend.journal))
        try:
            backend.stats = RunStats()
            backend.run_bin_mean(clusters)
        finally:
            tracing.set_current(prev)
            backend.journal.close()
        events, violations = read_events(str(jpath))
        assert violations == []
        spans = [e for e in events if e["event"] == "span"]
        kernels = [s for s in spans if s["name"].startswith("kernel:")]
        dispatches = [s for s in spans if s["name"] == "dispatch"]
        assert kernels and dispatches
        tol = 1e-6  # dur_s is journaled at 1us precision
        for k in kernels:
            host = next(
                (d for d in dispatches
                 if d["mono"] - d["dur_s"] <= k["mono"] - k["dur_s"] + tol
                 and k["mono"] <= d["mono"] + tol),
                None,
            )
            assert host is not None, (
                f"kernel span {k['name']} not contained by any "
                f"dispatch phase span"
            )

    def test_trace_merges_rank_parts_onto_one_timeline(self, tmp_path):
        from specpride_tpu.observability import Tracer

        base = tmp_path / "multi.jsonl"
        for rank in range(2):
            with Journal(f"{base}.part{rank}") as j:
                j.emit("run_start", command="consensus", method="bin-mean",
                       backend="tpu", n_clusters=2)
                tracer = Tracer(journal=j)
                with tracer.span("compute"):
                    pass
        out = tmp_path / "merged.json"
        # explicit shard names, as in the acceptance example
        rc = cli_main([
            "trace", f"{base}.part0", f"{base}.part1", "-o", str(out),
        ])
        assert rc == 0
        events = json.loads(out.read_text())["traceEvents"]
        spans = [e for e in events if e["ph"] == "X"]
        assert sorted(e["pid"] for e in spans) == [0, 1]
        # base path expands to the same shard pair
        out2 = tmp_path / "merged2.json"
        assert cli_main(["trace", str(base), "-o", str(out2)]) == 0
        spans2 = [
            e for e in json.loads(out2.read_text())["traceEvents"]
            if e["ph"] == "X"
        ]
        assert sorted(e["pid"] for e in spans2) == [0, 1]

    def test_trace_exits_nonzero_without_journals(self, tmp_path, capsys):
        out = tmp_path / "none.json"
        rc = cli_main([
            "trace", str(tmp_path / "missing.jsonl"), "-o", str(out),
        ])
        assert rc == 1
        assert not out.exists()

    def test_trace_rejects_chrome_trace_input(self, tmp_path, capsys):
        """Feeding `specpride trace` a --chrome-trace output (instead of
        the journal it reads) must exit nonzero, not silently write a
        span-less trace."""
        _, tpath = self.run_traced_consensus(tmp_path)
        capsys.readouterr()
        out = tmp_path / "wrong.json"
        rc = cli_main(["trace", str(tpath), "-o", str(out)])
        assert rc == 1
        assert "not" in capsys.readouterr().err.lower()

    def test_torn_span_line_heals_and_drops_deterministically(
        self, tmp_path, capsys
    ):
        """A run killed mid-`span`-write leaves a torn final line.  The
        journal must reopen cleanly (resume appends on a fresh line) and
        `specpride trace` must drop exactly the torn record — same trace
        every time — while still rendering everything readable."""
        from specpride_tpu.observability import Tracer

        jpath = tmp_path / "killed.jsonl"
        with Journal(jpath) as j:
            j.emit("run_start", command="consensus", method="bin-mean",
                   backend="tpu", n_clusters=4)
            tracer = Tracer(journal=j)
            with tracer.span("compute"):
                pass
        with open(jpath, "a", encoding="utf-8") as fh:
            fh.write('{"v": 2, "ts": 9.9, "mono": 9.9, "event": "span", '
                     '"name": "kern')  # torn: killed mid-write
        # reopen heals the seam; the resumed run's events stay parseable
        with Journal(jpath) as j:
            j.emit("resume", n_done=4)
        outs = []
        for i in range(2):  # deterministic: identical trace both times
            out = tmp_path / f"trace{i}.json"
            rc = cli_main(["trace", str(jpath), "-o", str(out)])
            assert rc == 0
            outs.append(json.loads(out.read_text()))
        assert outs[0] == outs[1]
        err = capsys.readouterr().err
        assert "dropped" in err and "invalid JSON" in err
        spans = [
            e for e in outs[0]["traceEvents"] if e["ph"] == "X"
        ]
        assert [e["name"] for e in spans] == ["compute"]  # torn span gone


class TestTopSpans:
    def test_stats_top_spans_table(self, tmp_path, capsys):
        out = tmp_path / "reps.mgf"
        jpath = tmp_path / "run.jsonl"
        rc = cli_main([
            "consensus", GOLDEN, str(out), "--method", "bin-mean",
            "--backend", "tpu", "--journal", str(jpath),
        ])
        assert rc == 0
        capsys.readouterr()
        agg = tmp_path / "agg.json"
        rc = cli_main([
            "stats", str(jpath), "--top-spans", "10", "--json", str(agg),
        ])
        assert rc == 0
        text = capsys.readouterr().out
        assert "TOP" in text and "self_s" in text and "p99_ms" in text
        data = json.loads(agg.read_text())
        rows = data["top_spans"]
        assert rows and {"name", "count", "total_s", "self_s",
                         "p50_s", "p99_s", "max_s"} <= set(rows[0])
        # sorted by self time, descending
        selfs = [r["self_s"] for r in rows]
        assert selfs == sorted(selfs, reverse=True)

    def test_stats_top_spans_still_fails_on_violations(self, tmp_path):
        bad = tmp_path / "bad.jsonl"
        bad.write_text(
            '{"v": 2, "ts": 1.0, "mono": 1.0, "event": "span", '
            '"name": "x", "dur_s": 0.5, "depth": 0}\n'
            '{"v": 1, "ts": 1.0, "event": "made_up_event"}\n'
        )
        assert run_stats([str(bad)], top_spans=5) == 1

    def test_span_event_requires_fields(self):
        assert validate_event(
            {"v": 2, "ts": 1.0, "mono": 1.0, "event": "span",
             "name": "x", "dur_s": 0.1, "depth": 0}
        ) == []
        assert validate_event(
            {"v": 2, "ts": 1.0, "mono": 1.0, "event": "span", "name": "x"}
        )  # missing dur_s/depth
        assert validate_event(
            {"v": 2, "ts": 1.0, "event": "resume", "n_done": 1}
        )  # v2 requires mono


# ---------------------------------------------------------------------------
# Event spec hygiene
# ---------------------------------------------------------------------------

def test_event_spec_covers_all_emitters():
    """Every event name the codebase emits must be in EVENT_FIELDS (the
    docs page and validator both key off it)."""
    import re
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        ["grep", "-rhoE", r'emit\(\s*"[a-z_]+"', "--include=*.py",
         os.path.join(root, "specpride_tpu"), os.path.join(root, "bench.py")],
        capture_output=True, text=True,
    ).stdout
    emitted = set(re.findall(r'"([a-z_]+)"', out))
    assert emitted <= set(EVENT_FIELDS), (
        f"events emitted but not in EVENT_FIELDS: "
        f"{emitted - set(EVENT_FIELDS)}"
    )
