"""Fault-injection / recovery layer (specpride_tpu.robustness): plan
parsing and determinism, retry with backoff, graceful degradation
(OOM split + device reroute), the per-lane watchdog breaking injected
hangs, malformed-record quarantine, and resume-after-corruption repair
for all three methods — every recovery must leave output byte-identical
to a fault-free serial run (or be a loud, journaled restart)."""

import json
import os
import subprocess
import sys

import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.io.mgf import read_mgf, write_mgf
from specpride_tpu.robustness import errors as rb_errors
from specpride_tpu.robustness import faults as rb_faults
from specpride_tpu.robustness.faults import FaultPlan, audit_fault_recovery
from specpride_tpu.robustness.retry import RetryPolicy

from conftest import make_cluster


def _workload(rng, n=8, **kw):
    return [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25, **kw)
        for i in range(n)
    ]


def _write(tmp_path, clusters, name="clustered.mgf"):
    path = tmp_path / name
    write_mgf([s for c in clusters for s in c.members], path)
    return path


def _events(path):
    return [json.loads(line) for line in open(path)]


def _run(clustered, out, *extra, command="consensus", ck=None, journal=None):
    argv = [command, str(clustered), str(out)] + list(extra)
    if ck is not None:
        argv += ["--checkpoint", str(ck), "--checkpoint-every", "2"]
    if journal is not None:
        argv += ["--journal", str(journal)]
    return cli_main(argv)


class TestFaultPlan:
    def test_spec_parsing(self):
        plan = FaultPlan.parse(
            "dispatch:oom:0.5:2:3, write:io:1", seed=7
        )
        s0, s1 = plan.specs
        assert (s0.site, s0.kind, s0.rate, s0.after, s0.max_fires) == (
            "dispatch", "oom", 0.5, 2, 3
        )
        assert (s1.site, s1.kind, s1.rate, s1.after, s1.max_fires) == (
            "write", "io", 1.0, 0, 1
        )

    @pytest.mark.parametrize("bad", [
        "nope:io:1", "dispatch:nope:1", "dispatch:io:2", "dispatch:io",
        "", "dispatch:io:1:-1",
    ])
    def test_bad_specs_rejected(self, bad):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)

    def test_firing_is_deterministic_per_seed(self):
        def fired_visits(seed):
            plan = FaultPlan.parse("dispatch:io:0.3:0:1000", seed=seed)
            out = []
            for visit in range(50):
                try:
                    plan.check("dispatch")
                except OSError:
                    out.append(visit)
            return out

        a, b = fired_visits(11), fired_visits(11)
        assert a == b and a  # same seed -> same visits, and some fire
        assert fired_visits(12) != a  # a different seed reshuffles

    def test_after_and_max_fires(self):
        plan = FaultPlan.parse("write:io:1:3:2")
        outcomes = []
        for _ in range(8):
            try:
                plan.check("write")
                outcomes.append("ok")
            except OSError:
                outcomes.append("fault")
        # skips the first 3 visits, then fires exactly twice
        assert outcomes == ["ok"] * 3 + ["fault", "fault"] + ["ok"] * 3
        assert plan.fired_by_site == {"write": 2}

    def test_error_shapes_match_taxonomy(self):
        for kind, pred in (
            ("io", rb_errors.is_transient),
            ("oom", rb_errors.is_oom),
        ):
            plan = FaultPlan.parse(f"dispatch:{kind}:1")
            with pytest.raises(Exception) as exc_info:
                plan.check("dispatch")
            assert pred(exc_info.value)
        plan = FaultPlan.parse("dispatch:malformed:1")
        with pytest.raises(ValueError) as exc_info:
            plan.check("dispatch")
        assert rb_errors.classify(exc_info.value) == "permanent"

    def test_env_arming(self, monkeypatch):
        monkeypatch.setenv("SPECPRIDE_FAULTS", "qc:io:1:1")
        monkeypatch.setenv("SPECPRIDE_FAULT_SEED", "5")
        plan = FaultPlan.from_env()
        assert plan.seed == 5
        assert [(s.site, s.kind) for s in plan.specs] == [("qc", "io")]
        monkeypatch.delenv("SPECPRIDE_FAULTS")
        assert FaultPlan.from_env() is None


class TestRetryPolicy:
    def test_transient_retried_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "done"

        policy = RetryPolicy(retries=3, backoff=0.0)
        assert policy.call("write", flaky) == "done"
        assert len(calls) == 3
        assert policy.summary()["retries"] == 2
        assert policy.summary()["retries_by_site"] == {"write": 2}

    def test_permanent_never_retried(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("malformed")

        policy = RetryPolicy(retries=5, backoff=0.0)
        with pytest.raises(ValueError):
            policy.call("dispatch", bad)
        assert len(calls) == 1

    def test_budget_exhaustion_reraises(self):
        policy = RetryPolicy(retries=2, backoff=0.0)
        with pytest.raises(OSError):
            policy.call("write", lambda: (_ for _ in ()).throw(OSError("x")))
        assert policy.summary()["retries"] == 2

    def test_backoff_is_exponential_and_deterministic(self):
        policy = RetryPolicy(retries=3, backoff=0.1, seed=4)
        waits = [policy.backoff_s("dispatch", i) for i in range(3)]
        assert waits == [
            RetryPolicy(retries=3, backoff=0.1, seed=4).backoff_s(
                "dispatch", i
            )
            for i in range(3)
        ]
        assert 0.1 <= waits[0] < 0.125
        assert 0.2 <= waits[1] < 0.25
        assert 0.4 <= waits[2] < 0.5

    def test_before_retry_hook_runs(self):
        undone = []
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise OSError("partial write")
            return "ok"

        policy = RetryPolicy(retries=1, backoff=0.0)
        assert policy.call(
            "write", flaky, before_retry=lambda: undone.append(1)
        ) == "ok"
        assert undone == [1]


class TestInjectedRecovery:
    """End-to-end through the CLI: injected faults at every lane, output
    byte-identical to a fault-free serial run, fault/recovery pairs in
    the journal."""

    def test_retry_recovers_every_io_site(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng))
        golden = tmp_path / "golden.mgf"
        assert _run(clustered, golden, "--prefetch", "0",
                    ck=tmp_path / "g.ck.json") == 0
        out, jr = tmp_path / "chaos.mgf", tmp_path / "chaos.jsonl"
        assert _run(
            clustered, out, "--prefetch", "4", "--pack-workers", "2",
            "--async-write", "on", "--retries", "3",
            "--retry-backoff", "0.01", "--inject-faults",
            "parse:io:1,pack:io:1:1,prepare:io:1:1,dispatch:io:1:1,"
            "write:io:1:2,checkpoint_write:io:1:3",
            ck=tmp_path / "c.ck.json", journal=jr,
        ) == 0
        assert out.read_bytes() == golden.read_bytes()
        events = _events(jr)
        fired = {e["site"] for e in events if e["event"] == "fault"}
        assert fired == {
            "parse", "pack", "prepare", "dispatch", "write",
            "checkpoint_write",
        }
        assert audit_fault_recovery(events) == []
        rb = [e for e in events if e["event"] == "run_end"][-1]["robustness"]
        assert rb["retries"] >= len(fired)
        assert rb["faults"]["fired_total"] == len(fired)

    def test_oom_splits_chunk_and_preserves_bytes(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng))
        golden = tmp_path / "golden.mgf"
        assert _run(clustered, golden, "--prefetch", "0",
                    ck=tmp_path / "g.ck.json") == 0
        out, jr = tmp_path / "oom.mgf", tmp_path / "oom.jsonl"
        assert _run(
            clustered, out, "--prefetch", "2", "--retry-backoff", "0.01",
            "--inject-faults", "dispatch:oom:1:1",
            ck=tmp_path / "o.ck.json", journal=jr,
        ) == 0
        assert out.read_bytes() == golden.read_bytes()
        events = _events(jr)
        degrades = [e for e in events if e["event"] == "degrade"]
        assert [d["action"] for d in degrades] == ["split"]
        assert audit_fault_recovery(events) == []

    def test_repeated_device_failure_reroutes_to_numpy(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=4))
        out, jr = tmp_path / "reroute.mgf", tmp_path / "reroute.jsonl"
        # 9 fires at full rate with only 1 retry: the dispatch budget
        # exhausts while the error stays transient -> reroute to numpy
        assert _run(
            clustered, out, "--prefetch", "2", "--retries", "1",
            "--retry-backoff", "0.0",
            "--inject-faults", "dispatch:io:1:0:9",
            ck=tmp_path / "r.ck.json", journal=jr,
        ) == 0
        events = _events(jr)
        actions = [e["action"] for e in events if e["event"] == "degrade"]
        assert "reroute" in actions
        assert audit_fault_recovery(events) == []
        # every cluster still produced a representative
        assert sorted(s.cluster_id for s in read_mgf(out)) == [
            f"cluster-{i}" for i in range(4)
        ]

    def test_no_degrade_disables_split_and_reroute(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=4))
        out = tmp_path / "nd.mgf"
        with pytest.raises(RuntimeError):
            _run(
                clustered, out, "--prefetch", "2", "--no-degrade",
                "--retries", "1", "--retry-backoff", "0.0",
                "--inject-faults", "dispatch:oom:1:0:9",
                ck=tmp_path / "nd.ck.json",
            )

    def test_qc_fault_retries_and_report_matches(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=6))
        reports = {}
        for tag, extra in (
            ("clean", []),
            ("faulty", ["--retries", "2", "--retry-backoff", "0.01",
                        "--inject-faults", "qc:io:1:1"]),
        ):
            out = tmp_path / f"qc_{tag}.mgf"
            qc = tmp_path / f"qc_{tag}.json"
            jr = tmp_path / f"qc_{tag}.jsonl"
            assert _run(
                clustered, out, "--method", "medoid", "--prefetch", "2",
                "--qc-report", str(qc), *extra,
                command="select", ck=tmp_path / f"qc_{tag}.ck.json",
                journal=jr,
            ) == 0
            reports[tag] = qc.read_bytes()
        assert reports["clean"] == reports["faulty"]
        events = _events(tmp_path / "qc_faulty.jsonl")
        assert [e["site"] for e in events if e["event"] == "fault"] == ["qc"]
        assert audit_fault_recovery(events) == []

    def test_hang_broken_by_watchdog_and_retried(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=6))
        golden = tmp_path / "golden.mgf"
        assert _run(clustered, golden, "--prefetch", "0",
                    ck=tmp_path / "g.ck.json") == 0
        out, jr = tmp_path / "hang.mgf", tmp_path / "hang.jsonl"
        assert _run(
            clustered, out, "--prefetch", "2", "--retries", "2",
            "--retry-backoff", "0.01", "--watchdog-timeout", "0.2",
            "--inject-faults", "dispatch:hang:1:1",
            ck=tmp_path / "h.ck.json", journal=jr,
        ) == 0
        assert out.read_bytes() == golden.read_bytes()
        events = _events(jr)
        stalls = [e for e in events if e["event"] == "watchdog_stall"]
        assert stalls and stalls[0]["lane"] == "dispatch"
        assert stalls[0]["elapsed_s"] >= 0.2
        assert audit_fault_recovery(events) == []
        rb = [e for e in events if e["event"] == "run_end"][-1]["robustness"]
        assert rb["watchdog_stalls"] >= 1

    def test_env_var_arms_subprocess(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=4))
        jr = tmp_path / "env.jsonl"
        res = subprocess.run(
            [sys.executable, "-m", "specpride_tpu", "consensus",
             str(clustered), str(tmp_path / "env.mgf"),
             "--prefetch", "2", "--retries", "2", "--retry-backoff",
             "0.01", "--journal", str(jr)],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "SPECPRIDE_FAULTS": "write:io:1",
                 "SPECPRIDE_FAULT_SEED": "3"},
        )
        assert res.returncode == 0, res.stderr
        events = _events(jr)
        assert [e["site"] for e in events if e["event"] == "fault"] == [
            "write"
        ]
        assert audit_fault_recovery(events) == []

    def test_exhausted_io_fault_follows_on_error_skip(self, tmp_path, rng):
        """A persistent I/O failure that survives its (zero) retry budget
        must follow --on-error skip like any compute failure — the
        consumer's per-cluster serial retry recovers the chunk instead
        of the OSError aborting the run."""
        clustered = _write(tmp_path, _workload(rng, n=6))
        out, jr = tmp_path / "skip.mgf", tmp_path / "skip.jsonl"
        assert _run(
            clustered, out, "--on-error", "skip", "--prefetch", "2",
            "--retries", "0", "--no-degrade",
            "--inject-faults", "pack:io:1:0:99",
            ck=tmp_path / "s.ck.json", journal=jr,
        ) == 0
        # the serial retry materialized every cluster despite the pack
        # lane failing persistently
        assert sorted(s.cluster_id for s in read_mgf(out)) == [
            f"cluster-{i}" for i in range(6)
        ]

    def test_plan_never_leaks_across_runs(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=4))
        assert _run(
            clustered, tmp_path / "a.mgf", "--prefetch", "2",
            "--retries", "2", "--retry-backoff", "0.01",
            "--inject-faults", "write:io:1:1",
        ) == 0
        assert rb_faults.active_plan() is None
        jr = tmp_path / "clean.jsonl"
        assert _run(
            clustered, tmp_path / "b.mgf", "--prefetch", "2", journal=jr
        ) == 0
        events = _events(jr)
        assert not [e for e in events if e["event"] == "fault"]
        assert "robustness" not in [
            e for e in events if e["event"] == "run_end"
        ][-1]


class TestQuarantine:
    def _dirty_file(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=6))
        blocks = clustered.read_text().split("\n\n")
        trunc = (
            "BEGIN IONS\nTITLE=cluster-trunc;mzspec:PXD000001:run1:"
            "scan:9999\nPEPMASS=500.0\n123.4 10.0"
        )
        blocks.insert(4, trunc)  # mid-file BEGIN with no END IONS
        dirty = tmp_path / "dirty.mgf"
        dirty.write_text("\n\n".join(blocks))
        return dirty

    @pytest.mark.parametrize("stream", ["off", "2"])
    def test_truncated_block_quarantined(self, tmp_path, rng, stream):
        dirty = self._dirty_file(tmp_path, rng)
        out = tmp_path / f"q_{stream}.mgf"
        jr = tmp_path / f"q_{stream}.jsonl"
        assert _run(
            dirty, out, "--on-error", "skip", "--stream-clusters", stream,
            "--prefetch", "2", journal=jr,
        ) == 0
        qfile = tmp_path / f"q_{stream}.mgf.quarantine.mgf"
        assert "cluster-trunc" in qfile.read_text()
        events = _events(jr)
        qev = [e for e in events if e["event"] == "quarantine"]
        assert len(qev) == 1 and "truncated record" in qev[0]["reason"]
        # the 6 intact clusters all produced representatives
        assert sorted(s.cluster_id for s in read_mgf(out)) == [
            f"cluster-{i}" for i in range(6)
        ]
        rb = [e for e in events if e["event"] == "run_end"][-1]["robustness"]
        assert rb["quarantined"] == 1

    def test_quarantine_file_is_fresh_per_run(self, tmp_path, rng):
        """Re-running over the same output must not accumulate duplicate
        blocks (a resume re-parses the full input) or keep stale blocks
        from an unrelated earlier run."""
        dirty = self._dirty_file(tmp_path, rng)
        out = tmp_path / "q.mgf"
        qfile = tmp_path / "q.mgf.quarantine.mgf"
        qfile.write_text("BEGIN IONS\nTITLE=stale-from-last-run\nEND IONS\n")
        for _ in range(2):
            assert _run(dirty, out, "--on-error", "skip",
                        "--prefetch", "2") == 0
        text = qfile.read_text()
        assert "stale-from-last-run" not in text
        assert text.count("cluster-trunc") == 1

    def test_abort_policy_keeps_fail_fast(self, tmp_path, rng):
        """Under the default --on-error abort a damaged record must still
        raise (no quarantine file, no silent drop of the bad block)."""
        clustered = _write(tmp_path, _workload(rng, n=3))
        blocks = clustered.read_text().split("\n\n")
        blocks.insert(
            2,
            "BEGIN IONS\nTITLE=cluster-bad;mzspec:PXD000001:run1:scan:9\n"
            "PEPMASS=500.0\n123.4 banana\nEND IONS",
        )
        dirty = tmp_path / "dirty.mgf"
        dirty.write_text("\n\n".join(blocks))
        out = tmp_path / "abort.mgf"
        with pytest.raises(ValueError):
            _run(dirty, out, "--prefetch", "0")
        assert not (tmp_path / "abort.mgf.quarantine.mgf").exists()


class TestResumeIntegrity:
    """Truncate/bit-flip the manifest and the MGF tail between runs: all
    three methods must repair (or restart loudly) and converge to the
    fault-free bytes — never silently duplicate or drop spectra."""

    METHODS = [
        ("bin-mean", "consensus"),
        ("gap-average", "consensus"),
        ("medoid", "select"),
    ]

    def _golden_and_partial(self, tmp_path, rng, method, command):
        clusters = _workload(rng, n=6)
        clustered = _write(tmp_path, clusters)
        golden = tmp_path / "golden.mgf"
        assert _run(clustered, golden, "--method", method, "--prefetch",
                    "0", command=command, ck=tmp_path / "g.ck.json") == 0
        # a partial run over the head -> committed prefix + manifest
        head = _write(tmp_path, clusters[:3], name="head.mgf")
        out, ck = tmp_path / "out.mgf", tmp_path / "resume.ck.json"
        assert _run(head, out, "--method", method, "--prefetch", "0",
                    command=command, ck=ck) == 0
        assert golden.read_bytes().startswith(out.read_bytes())
        return clustered, golden, out, ck

    @pytest.mark.parametrize("method,command", METHODS)
    def test_torn_tail_truncated_and_resumed(
        self, tmp_path, rng, method, command
    ):
        clustered, golden, out, ck = self._golden_and_partial(
            tmp_path, rng, method, command
        )
        with open(out, "ab") as fh:
            fh.write(b"BEGIN IONS\nTITLE=torn\n123.4 5")
        jr = tmp_path / "r.jsonl"
        assert _run(clustered, out, "--method", method, "--prefetch", "4",
                    "--pack-workers", "2", "--async-write", "on",
                    command=command, ck=ck, journal=jr) == 0
        assert out.read_bytes() == golden.read_bytes()
        repairs = [
            (e["action"], e["reason"]) for e in _events(jr)
            if e["event"] == "resume_repair"
        ]
        assert ("truncate_tail", "torn_tail") in repairs

    @pytest.mark.parametrize("method,command", METHODS)
    def test_bit_flip_in_committed_region_restarts(
        self, tmp_path, rng, method, command
    ):
        clustered, golden, out, ck = self._golden_and_partial(
            tmp_path, rng, method, command
        )
        data = bytearray(out.read_bytes())
        data[len(data) // 2] ^= 0xFF
        out.write_bytes(bytes(data))
        jr = tmp_path / "r.jsonl"
        assert _run(clustered, out, "--method", method, "--prefetch", "2",
                    command=command, ck=ck, journal=jr) == 0
        assert out.read_bytes() == golden.read_bytes()
        repairs = [
            (e["action"], e["reason"]) for e in _events(jr)
            if e["event"] == "resume_repair"
        ]
        assert ("restart", "sha256_mismatch") in repairs

    @pytest.mark.parametrize("method,command", METHODS)
    def test_corrupt_manifest_restarts(self, tmp_path, rng, method, command):
        clustered, golden, out, ck = self._golden_and_partial(
            tmp_path, rng, method, command
        )
        ck.write_bytes(ck.read_bytes()[: ck.stat().st_size // 2])
        jr = tmp_path / "r.jsonl"
        assert _run(clustered, out, "--method", method, "--prefetch", "2",
                    command=command, ck=ck, journal=jr) == 0
        assert out.read_bytes() == golden.read_bytes()
        repairs = [
            (e["action"], e["reason"]) for e in _events(jr)
            if e["event"] == "resume_repair"
        ]
        assert ("restart", "manifest_unreadable") in repairs

    def test_manifest_carries_schema_and_hash(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=4))
        ck = tmp_path / "ck.json"
        out = tmp_path / "o.mgf"
        assert _run(clustered, out, ck=ck) == 0
        manifest = json.loads(ck.read_text())
        assert manifest["schema"] == 2
        import hashlib

        assert manifest["sha256"] == hashlib.sha256(
            out.read_bytes()[: manifest["output_bytes"]]
        ).hexdigest()

    def test_legacy_schemaless_manifest_still_resumes(self, tmp_path, rng):
        clusters = _workload(rng, n=6)
        clustered = _write(tmp_path, clusters)
        golden = tmp_path / "golden.mgf"
        assert _run(clustered, golden, "--prefetch", "0",
                    ck=tmp_path / "g.ck.json") == 0
        head = _write(tmp_path, clusters[:3], name="head.mgf")
        out, ck = tmp_path / "out.mgf", tmp_path / "ck.json"
        assert _run(head, out, "--prefetch", "0", ck=ck) == 0
        manifest = json.loads(ck.read_text())
        # strip the v2 fields: a PR4-era manifest
        ck.write_text(json.dumps({
            "done": manifest["done"],
            "output_bytes": manifest["output_bytes"],
        }))
        assert _run(clustered, out, "--prefetch", "2", ck=ck) == 0
        assert out.read_bytes() == golden.read_bytes()
        # and the resumed run upgraded the manifest in place
        assert json.loads(ck.read_text())["schema"] == 2


class TestStatsRendering:
    def test_stats_renders_robustness_summary(self, tmp_path, rng, capsys):
        from specpride_tpu.observability.stats_cli import run_stats

        clustered = _write(tmp_path, _workload(rng, n=4))
        jr = tmp_path / "run.jsonl"
        assert _run(
            clustered, tmp_path / "o.mgf", "--prefetch", "2",
            "--retries", "2", "--retry-backoff", "0.01",
            "--inject-faults", "write:io:1:1",
            ck=tmp_path / "ck.json", journal=jr,
        ) == 0
        agg = tmp_path / "agg.json"
        assert run_stats([str(jr)], json_out=str(agg)) == 0
        rendered = capsys.readouterr().out
        assert "robustness:" in rendered and "recovered" in rendered
        run = json.loads(agg.read_text())["runs"][0]
        assert run["robustness"]["fault"] == 1
        assert run["robustness"]["unrecovered_faults"] == 0
        assert run["robustness"]["run_end"]["retries"] >= 1
