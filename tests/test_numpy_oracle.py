"""Golden tests for the NumPy oracle backend — hand-computed expectations
pinning the reference semantics (survey §4 test plan item a)."""

import numpy as np
import pytest
import scipy.stats

from specpride_tpu.backends import numpy_backend as nb
from specpride_tpu.config import (
    BinMeanConfig,
    CosineConfig,
    GapAverageConfig,
    MedoidConfig,
)
from specpride_tpu.data.peaks import Cluster, Spectrum


def spec(mz, inten, pmz=500.0, z=2, rt=0.0, title="cluster-1;usi:1"):
    return Spectrum(
        mz=np.array(mz, dtype=float),
        intensity=np.array(inten, dtype=float),
        precursor_mz=pmz,
        precursor_charge=z,
        rt=rt,
        title=title,
    )


# ---------------------------------------------------------------------------
# bin-mean (ref src/binning.py:170-231)
# ---------------------------------------------------------------------------

class TestBinMean:
    def test_quorum_golden(self):
        members = [
            spec([100.005, 150.01], [10, 20]),
            spec([100.015, 200.0], [30, 40]),
            spec([100.009], [50]),
            spec([500.0], [60]),
        ]
        out = nb.bin_mean_consensus(members, BinMeanConfig(), "cluster-1")
        # quorum = int(4*0.25)+1 = 2: only bin 0 (3 contributors) survives
        assert out.n_peaks == 1
        assert out.intensity[0] == pytest.approx((10 + 30 + 50) / 3, rel=1e-6)
        assert out.mz[0] == pytest.approx((100.005 + 100.015 + 100.009) / 3, rel=1e-6)
        assert out.precursor_mz == pytest.approx(500.0)
        assert out.precursor_charge == 2
        assert out.title == "cluster-1"

    def test_duplicate_bin_last_wins(self):
        # numpy fancy += : within one member, the last peak in a bin wins
        # (ref src/binning.py:197-199)
        members = [
            spec([100.001, 100.002], [5, 7]),
            spec([100.005], [9]),
        ]
        out = nb.bin_mean_consensus(members, BinMeanConfig())
        assert out.n_peaks == 1
        assert out.intensity[0] == pytest.approx((7 + 9) / 2)
        assert out.mz[0] == pytest.approx((100.002 + 100.005) / 2)

    def test_range_mask(self):
        # peaks outside [min_mz, max_mz) are dropped (ref src/binning.py:191-192)
        members = [spec([50.0, 2000.0, 150.0], [1, 2, 3])]
        out = nb.bin_mean_consensus(members, BinMeanConfig(apply_peak_quorum=False))
        assert out.n_peaks == 1
        assert out.mz[0] == pytest.approx(150.0)

    def test_mixed_charges_raise(self):
        members = [spec([150.0], [1], z=2), spec([150.0], [1], z=3)]
        with pytest.raises(ValueError, match="charges"):
            nb.bin_mean_consensus(members)

    def test_quorum_disabled(self):
        members = [spec([150.0], [10]), spec([900.0], [20]), spec([901.0], [5]),
                   spec([902.0], [5])]
        out = nb.bin_mean_consensus(members, BinMeanConfig(apply_peak_quorum=False))
        assert out.n_peaks == 4


# ---------------------------------------------------------------------------
# gap-average (ref src/average_spectrum_clustering.py:26-103)
# ---------------------------------------------------------------------------

class TestGapAverage:
    def members(self):
        return [
            spec([100.0, 100.005, 200.0], [10, 20, 30]),
            spec([100.002, 300.0], [40, 50]),
        ]

    def test_reference_tail_merges_last_groups(self):
        out = nb.gap_average_consensus(self.members(), GapAverageConfig())
        # gaps at positions [3, 4]; reference mode drops the final gap:
        # groups [0,3) and [3,5)
        np.testing.assert_allclose(
            out.mz, [(100.0 + 100.002 + 100.005) / 3, (200.0 + 300.0) / 2]
        )
        np.testing.assert_allclose(out.intensity, [35.0, 40.0])

    def test_split_tail_honours_every_gap(self):
        out = nb.gap_average_consensus(
            self.members(), GapAverageConfig(tail_mode="split")
        )
        np.testing.assert_allclose(
            out.mz, [(100.0 + 100.002 + 100.005) / 3, 200.0, 300.0]
        )
        np.testing.assert_allclose(out.intensity, [35.0, 15.0, 25.0])

    def test_min_fraction_quorum(self):
        # min_fraction=1.0 → group must contain >= n_members peaks
        out = nb.gap_average_consensus(
            self.members(), GapAverageConfig(min_fraction=1.0, tail_mode="split")
        )
        # only the 3-peak group passes (3 >= 2); singleton groups fail
        np.testing.assert_allclose(out.intensity, [35.0])

    def test_dyn_range(self):
        members = [
            spec([100.0, 500.0], [10000.0, 1.0]),
            spec([100.004, 500.004], [10000.0, 1.0]),
        ]
        out = nb.gap_average_consensus(
            members, GapAverageConfig(dyn_range=1000.0, tail_mode="split")
        )
        # group intensities: 10000 and 1; floor = 10000/1000 = 10 → drop 1
        np.testing.assert_allclose(out.intensity, [10000.0])

    def test_singleton_passthrough(self):
        # ref src/average_spectrum_clustering.py:88-90
        s = spec([100.0, 200.0], [5.0, 6.0])
        out = nb.gap_average_consensus([s], GapAverageConfig())
        np.testing.assert_allclose(out.mz, s.mz)
        np.testing.assert_allclose(out.intensity, s.intensity)

    def test_no_gaps_single_group(self):
        # divergence: reference IndexErrors when no gap exists
        members = [spec([100.0], [10.0]), spec([100.004], [20.0])]
        out = nb.gap_average_consensus(members, GapAverageConfig())
        np.testing.assert_allclose(out.mz, [100.002])
        np.testing.assert_allclose(out.intensity, [15.0])


class TestEstimators:
    def members(self):
        return [
            spec([100.0], [1.0], pmz=500.0, z=2, rt=10.0),
            spec([100.0], [1.0], pmz=500.2, z=2, rt=20.0),
            spec([100.0], [1.0], pmz=334.0, z=3, rt=30.0),
        ]

    def test_naive_average_mixed_charge_raises(self):
        with pytest.raises(ValueError):
            nb.naive_average_mass_and_charge(self.members())

    def test_naive_average(self):
        m = self.members()[:2]
        mz, z = nb.naive_average_mass_and_charge(m)
        assert mz == pytest.approx(500.1)
        assert z == 2

    def test_neutral_average(self):
        m = self.members()
        masses, charges = nb._neutral_masses(m)
        expected_z = int(round(np.mean(charges)))
        expected = (np.mean(masses) + expected_z * nb.PROTON_MASS) / expected_z
        mz, z = nb.neutral_average_mass_and_charge(m)
        assert z == expected_z
        assert mz == pytest.approx(expected)

    def test_lower_median(self):
        m = self.members()
        masses, _ = nb._neutral_masses(m)
        # neutral masses: 2*500-2H≈998, 2*500.2-2H≈998.4, 3*334-3H≈999
        # sorted rank (3-1)//2 = 1 → the 998.4 member (z=2, rt=20)
        mz, z = nb.lower_median_mass_and_charge(m)
        assert z == 2
        assert mz == pytest.approx(500.2)
        assert nb.lower_median_mass_rt(m) == pytest.approx(20.0)

    def test_median_rt(self):
        assert nb.median_rt(self.members()) == pytest.approx(20.0)


# ---------------------------------------------------------------------------
# medoid (ref src/most_similar_representative.py)
# ---------------------------------------------------------------------------

class TestMedoid:
    def test_xcorr_identity(self):
        s = spec([100.01, 200.01], [1, 1])
        assert nb.xcorr_prescore(s, s) == pytest.approx(1.0)

    def test_xcorr_partial(self):
        s0 = spec([100.01, 200.01], [1, 1])
        s2 = spec([100.01, 300.0], [1, 1])
        assert nb.xcorr_prescore(s0, s2) == pytest.approx(0.5)

    def test_xcorr_empty(self):
        s0 = spec([100.01], [1])
        empty = spec([], [])
        assert nb.xcorr_prescore(s0, empty) == 0.0

    def test_xcorr_dedup_occupancy(self):
        # two peaks in one 0.1 Da bin occupy it once
        s1 = spec([100.01, 100.02], [1, 1])
        s2 = spec([100.03], [1])
        assert nb.xcorr_prescore(s1, s2) == pytest.approx(1.0)  # 1 shared / min(2,1)

    def test_medoid_golden(self):
        members = [
            spec([100.01, 200.01], [1, 1]),
            spec([100.02, 200.09], [1, 1]),
            spec([100.01, 300.0], [1, 1]),
        ]
        assert nb.medoid_index(members, MedoidConfig()) == 0

    def test_medoid_singleton(self):
        assert nb.medoid_index([spec([1.0], [1.0])]) == 0

    def test_medoid_tie_lowest_index(self):
        a = spec([100.01], [1])
        assert nb.medoid_index([a, a]) == 0


# ---------------------------------------------------------------------------
# best spectrum (ref src/best_spectrum.py)
# ---------------------------------------------------------------------------

class TestBestSpectrum:
    def members(self):
        return [
            spec([100.0], [1.0], title="cluster-1;usi:a"),
            spec([100.0], [1.0], title="cluster-1;usi:b"),
            spec([100.0], [1.0], title="cluster-1;usi:c"),
        ]

    def test_highest_score(self):
        scores = {"usi:a": 1.0, "usi:b": 9.0, "usi:c": 5.0}
        assert nb.best_spectrum_index(self.members(), scores) == 1

    def test_no_scores_raises(self):
        with pytest.raises(ValueError):
            nb.best_spectrum_index(self.members(), {"other": 1.0})

    def test_tie_lexicographic_usi(self):
        scores = {"usi:c": 9.0, "usi:b": 9.0}
        assert nb.best_spectrum_index(self.members(), scores) == 1

    def test_usi_normalization_join(self):
        # MaxQuant-side USIs carry '::scan:' (ref src/best_spectrum.py:61-62)
        # while converter titles use ':scan:' and may carry ':PEPTIDE/z';
        # the join must still match (reference latent bug, fixed here)
        members = [
            spec([1.0], [1.0], title="c;mzspec:PXD1:run1.raw:scan:10:PEP/2"),
            spec([1.0], [1.0], title="c;mzspec:PXD1:run1.raw:scan:11"),
        ]
        scores = {
            "mzspec:PXD1:run1.raw::scan:10": 5.0,
            "mzspec:PXD1:run1.raw::scan:11": 50.0,
        }
        assert nb.best_spectrum_index(members, scores) == 1

    def test_scoreless_cluster_dropped(self):
        clusters = [
            Cluster("cluster-1", self.members()),
            Cluster("cluster-2", [spec([1.0], [1.0], title="cluster-2;usi:x")]),
        ]
        out = nb.run_best_spectrum(clusters, {"usi:a": 1.0})
        assert len(out) == 1
        assert out[0].usi == "usi:a"


# ---------------------------------------------------------------------------
# cosine metric (ref src/benchmark.py:11-38)
# ---------------------------------------------------------------------------

class TestCosine:
    def test_self_similarity_is_one(self, rng):
        # the reference's only self-test invariant (ref src/benchmark.py:80)
        mz = np.sort(rng.uniform(100, 1500, size=80))
        s = spec(mz, rng.uniform(1, 100, size=80))
        assert nb.binned_cosine(s, s) == pytest.approx(1.0)

    def test_disjoint_is_zero(self):
        a = spec([100.0, 200.0], [1, 1])
        b = spec([500.0, 600.0], [1, 1])
        assert nb.binned_cosine(a, b) == pytest.approx(0.0)

    def test_matches_scipy_binned_statistic(self, rng):
        # cross-check our floor-binning against the reference's scipy grid
        cfg = CosineConfig()
        for _ in range(5):
            a = spec(np.sort(rng.uniform(100, 1400, 60)), rng.uniform(1, 100, 60))
            b = spec(np.sort(rng.uniform(100, 1400, 50)), rng.uniform(1, 100, 50))
            max_mz = max(a.mz[-1], b.mz[-1])
            edges = np.arange(-cfg.mz_space / 2.0, max_mz, cfg.mz_space)
            va, _, _ = scipy.stats.binned_statistic(
                a.mz, a.intensity, statistic="sum", bins=edges
            )
            vb, _, _ = scipy.stats.binned_statistic(
                b.mz, b.intensity, statistic="sum", bins=edges
            )
            va, vb = np.nan_to_num(va), np.nan_to_num(vb)
            expected = va @ vb / np.sqrt((va @ va) * (vb @ vb))
            assert nb.binned_cosine(a, b, cfg) == pytest.approx(expected, rel=1e-9)

    def test_average_cosine(self, rng):
        mz = np.sort(rng.uniform(100, 1000, 40))
        s = spec(mz, rng.uniform(1, 10, 40))
        assert nb.average_cosine(s, [s, s]) == pytest.approx(1.0)
        assert nb.average_cosine(s, []) == 0.0
