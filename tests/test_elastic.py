"""Elastic multi-host scale-out: coordinator leases/heartbeats,
dead-rank reassignment with byte-identical merge, merge-parts
hardening, the stats rank view, and the liveness exporter.

The reassignment matrix runs a real victim rank in a subprocess armed
with the ``rank_kill`` fault kind (SIGKILL at a write-site visit — the
chaos-CI idiom), then an in-process survivor that must observe the
lease expiry, reclaim only the uncommitted chunks, and reproduce the
single-host serial bytes for all three methods under both a clean crash
and a torn output tail."""

import io
import json
import os
import subprocess
import sys
import time

import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.observability.journal import NullJournal, read_events
from specpride_tpu.parallel.coordinator import Coordinator, plan_ranges
from specpride_tpu.parallel.elastic import (
    audit_elastic,
    merge_qc_reports,
    sha256_file,
    summarize_ranks,
    verify_part_manifest,
)
from specpride_tpu.robustness.errors import LeaseExpiredError
from specpride_tpu.robustness.faults import audit_fault_recovery

from conftest import make_cluster


class RecordingJournal(NullJournal):
    """Captures emitted events (schema-shaped) for assertions."""

    enabled = True

    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        rec = {"event": event, "ts": time.time(),
               "mono": time.perf_counter(), **fields}
        self.events.append(rec)
        return rec


# -- coordinator units ---------------------------------------------------


def test_plan_ranges_blocks_and_empty_input():
    ranges = plan_ranges(7, 3)
    assert [(r.range_id, r.start, r.stop) for r in ranges] == [
        (0, 0, 3), (1, 3, 6), (2, 6, 7),
    ]
    # empty input still plans one (empty) range so a claimer writes an
    # empty part and merge-parts finds a complete set
    empty = plan_ranges(0, 3)
    assert [(r.start, r.stop) for r in empty] == [(0, 0)]


def test_plan_mismatch_refuses(tmp_path):
    a = Coordinator(str(tmp_path), 0, 10, 5, ttl=5.0)
    a.stop()
    with pytest.raises(SystemExit, match="plan mismatch"):
        Coordinator(str(tmp_path), 1, 12, 5, ttl=5.0)


def test_lease_claim_renew_and_expiry(tmp_path):
    ja, jb = RecordingJournal(), RecordingJournal()
    a = Coordinator(str(tmp_path), 0, 4, 4, ttl=0.4, journal=ja)
    claim = a.claim_next()
    assert claim is not None and not claim.takeover
    assert claim.range.range_id == 0
    assert [e["event"] for e in ja.events if e["event"] == "lease_claim"]
    # renewal keeps the lease alive well past the raw TTL
    b = Coordinator(str(tmp_path), 1, 4, 4, ttl=0.4, journal=jb)
    time.sleep(1.0)
    assert b.claim_next() is None  # rank 0 heartbeats, lease stays live
    a.check_lease(0)  # still held
    # kill rank 0's heartbeats WITHOUT releasing (a crash): the lease
    # ages out and rank 1 steals it, journaling the expire/reassign pair
    a._stop.set()
    a._hb_thread.join()
    time.sleep(0.4 * 1.5 + 0.3)
    stolen = b.claim_next()
    assert stolen is not None and stolen.takeover
    assert stolen.from_rank == 0
    assert stolen.range.range_id == 0
    events = [e["event"] for e in jb.events]
    assert "lease_expire" in events and "chunk_reassign" in events
    assert not audit_elastic(jb.events)
    # the loser's fence must now refuse commits
    with pytest.raises(LeaseExpiredError):
        a.check_lease(0)
    b.stop()
    a.stop()


def test_double_commit_exactly_once(tmp_path):
    a = Coordinator(str(tmp_path), 0, 4, 4, ttl=5.0)
    b = Coordinator(str(tmp_path), 1, 4, 4, ttl=5.0)
    payload = {"output_bytes": 3, "sha256": "abc"}
    outcomes = [a.commit(0, payload), b.commit(0, payload)]
    assert sorted(outcomes) == [False, True]
    assert a.done_count() == 1
    a.stop()
    b.stop()


def test_assign_rank_is_unique(tmp_path):
    got = [Coordinator.assign_rank(str(tmp_path)) for _ in range(3)]
    assert got == [0, 1, 2]


def test_audit_elastic_pairs_by_range():
    expire = {"event": "lease_expire", "rank": 1, "range": 3}
    reassign = {"event": "chunk_reassign", "range": 3,
                "from_rank": 1, "to_rank": 0}
    assert audit_elastic([expire, reassign]) == []
    assert audit_elastic([expire]) == [expire]
    other = {"event": "chunk_reassign", "range": 4,
             "from_rank": 1, "to_rank": 0}
    assert audit_elastic([expire, other]) == [expire]


def test_verify_part_manifest(tmp_path):
    part = tmp_path / "out.part00000"
    part.write_bytes(b"BEGIN IONS\nEND IONS\n")
    good = {"output_bytes": part.stat().st_size,
            "sha256": sha256_file(str(part))}
    assert verify_part_manifest(str(part), good) is None
    assert "output_bytes" in verify_part_manifest(str(part), {})
    bad_size = dict(good, output_bytes=good["output_bytes"] + 1)
    assert "bytes" in verify_part_manifest(str(part), bad_size)
    bad_sha = dict(good, sha256="0" * 64)
    assert "sha256 mismatch" in verify_part_manifest(str(part), bad_sha)


# -- liveness exporter ---------------------------------------------------


def test_elastic_telemetry_exposition(tmp_path):
    from specpride_tpu.observability.exporter import (
        ElasticTelemetry,
        validate_exposition,
    )

    coord = Coordinator(str(tmp_path), 0, 8, 4, ttl=5.0)
    coord.commit(0, {"output_bytes": 0, "sha256": "x"})
    coord.lease_expires_observed = 2
    coord.reassignments = 1
    tel = ElasticTelemetry(coord)
    text = tel.exposition()
    assert validate_exposition(text) == []
    assert 'specpride_rank_heartbeat_age_seconds{rank="0"}' in text
    assert "specpride_elastic_ranges 2" in text
    assert "specpride_elastic_ranges_committed 1" in text
    assert "specpride_elastic_lease_expires_total 2" in text
    assert "specpride_elastic_reassignments_total 1" in text
    # counters mirror by delta: a second scrape must not double-count
    text2 = tel.exposition()
    assert "specpride_elastic_lease_expires_total 2" in text2
    coord.stop()


# -- CLI end-to-end ------------------------------------------------------


def _write_input(tmp_path, rng, n=6):
    clusters = [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=20)
        for i in range(n)
    ]
    src = tmp_path / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], src)
    return src


def _serial_golden(tmp_path, src, method, command):
    out = tmp_path / f"serial_{method}.mgf"
    qc = tmp_path / f"serial_{method}_qc.json"
    assert cli_main([
        command, str(src), str(out), "--method", method,
        "--qc-report", str(qc),
    ]) == 0
    return out.read_bytes(), qc.read_bytes()


def _elastic_argv(src, out, coord, rank, method, command, journal):
    return [
        command, str(src), str(out), "--method", method,
        "--elastic", str(coord), "--process-id", str(rank),
        "--elastic-range", "2", "--checkpoint-every", "1",
        "--elastic-ttl", "0.5",
        "--qc-report", f"{out}.qc.json",
        "--journal", str(journal),
    ]


def test_elastic_single_rank_byte_identical(tmp_path, rng):
    """A healthy 1-rank elastic run merges to the serial bytes and QC
    report, with manifest-verified merge-parts."""
    src = _write_input(tmp_path, rng)
    serial, serial_qc = _serial_golden(tmp_path, src, "bin-mean",
                                       "consensus")
    out = tmp_path / "out.mgf"
    coord = tmp_path / "coord"
    assert cli_main(_elastic_argv(
        src, out, coord, 0, "bin-mean", "consensus",
        tmp_path / "j.jsonl",
    )) == 0
    assert cli_main([
        "merge-parts", str(out), "--elastic", str(coord),
        "--qc-report", f"{out}.qc.json",
    ]) == 0
    assert out.read_bytes() == serial
    assert (tmp_path / "out.mgf.qc.json").read_bytes() == serial_qc
    # re-running over a finished coordinator is a no-op resume: every
    # range already carries a commit marker
    part0 = tmp_path / "out.mgf.part00000"
    before = part0.read_bytes()
    assert cli_main(_elastic_argv(
        src, out, coord, 2, "bin-mean", "consensus",
        tmp_path / "j2.jsonl",
    )) == 0
    assert part0.read_bytes() == before
    events, violations = read_events(
        str(tmp_path) + "/j2.jsonl.part00002"
    )
    assert not violations
    end = [e for e in events if e["event"] == "run_end"][-1]
    assert end["elastic"]["ranges_run"] == 0
    assert end["elastic"]["ranges_committed"] == 3


def _spawn_victim(src, out, coord, journal, method, command):
    """Run the victim rank in a subprocess armed with a rank_kill fault:
    SIGKILL at write-site visit 3 — after range A (2 chunks) and the
    first chunk of range B are committed, so range B is left half done
    under a live-looking lease."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
        SPECPRIDE_FAULTS="write:rank_kill:1:3",
    )
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_NUM_CPU_DEVICES", None)
    proc = subprocess.run(
        [sys.executable, "-m", "specpride_tpu"] + _elastic_argv(
            src, out, coord, 1, method, command, journal,
        ),
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        timeout=180,
    )
    assert proc.returncode in (-9, 137), proc.stderr.decode()[-2000:]


@pytest.mark.parametrize("method,command", [
    ("bin-mean", "consensus"),
    ("gap-average", "consensus"),
    ("medoid", "select"),
])
@pytest.mark.parametrize("damage", ["clean", "torn"])
def test_reassignment_after_rank_kill(tmp_path, rng, method, command,
                                      damage):
    """A SIGKILLed rank's uncommitted chunks are reassigned to a
    survivor and the merged output + QC report stay byte-identical to
    the single-host serial run — for a clean crash at a chunk boundary
    and for a torn tail past the last committed chunk."""
    src = _write_input(tmp_path, rng)
    serial, serial_qc = _serial_golden(tmp_path, src, method, command)
    out = tmp_path / "out.mgf"
    coord = tmp_path / "coord"
    _spawn_victim(src, out, coord, tmp_path / "j.jsonl", method, command)
    # the victim (rank 1, scan offset 1) committed range 1 whole and
    # exactly one chunk of range 2 before dying
    assert os.path.exists(coord / "done" / "range_00001.json")
    assert not os.path.exists(coord / "done" / "range_00002.json")
    assert os.path.exists(coord / "leases" / "range_00002.json")
    partial = f"{out}.part00002"
    manifest = json.load(open(coord / "ck" / "range_00002.json"))
    assert len(manifest["done"]) == 1
    assert os.path.getsize(partial) == manifest["output_bytes"]
    if damage == "torn":
        # a torn append past the committed prefix (un-fsynced bytes a
        # power cut shredded): the survivor's resume must truncate it
        with open(partial, "ab") as fh:
            fh.write(b"BEGIN IONS\nTITLE=torn-tail-garbage\n123 4")
    assert cli_main(_elastic_argv(
        src, out, coord, 0, method, command, tmp_path / "j.jsonl",
    )) == 0
    assert cli_main([
        "merge-parts", str(out), "--elastic", str(coord),
        "--qc-report", f"{out}.qc.json",
    ]) == 0
    assert out.read_bytes() == serial
    assert (tmp_path / "out.mgf.qc.json").read_bytes() == serial_qc
    # journal audit: the victim's rank_kill fault pairs with the
    # survivor's chunk_reassign, and every lease_expire is paired
    victim_events, _ = read_events(f"{tmp_path}/j.jsonl.part00001")
    survivor_events, _ = read_events(f"{tmp_path}/j.jsonl.part00000")
    kills = [e for e in victim_events if e["event"] == "fault"]
    assert kills and kills[-1]["kind"] == "rank_kill"
    assert [e for e in survivor_events if e["event"] == "lease_expire"]
    reassigns = [
        e for e in survivor_events if e["event"] == "chunk_reassign"
    ]
    assert reassigns and reassigns[0]["from_rank"] == 1
    merged = victim_events + survivor_events
    assert not audit_elastic(merged)
    assert not audit_fault_recovery(merged)
    # the survivor RESUMED range 2 (one chunk was trusted via the
    # manifest), never redid it from scratch
    resumes = [
        e for e in survivor_events
        if e["event"] == "resume" and e.get("n_done", 0) > 0
    ]
    assert resumes, "survivor restarted the partial range from scratch"
    if damage == "torn":
        repairs = [
            e for e in survivor_events
            if e["event"] == "resume_repair"
            and e.get("action") == "truncate_tail"
        ]
        assert repairs, "torn tail was not truncated on takeover"


def test_stats_rank_view_and_json(tmp_path, rng):
    """`specpride stats` renders the multi-host rank view from the
    merged .part<rank> journals and includes it in --json."""
    src = _write_input(tmp_path, rng, n=4)
    out = tmp_path / "out.mgf"
    coord = tmp_path / "coord"
    for rank in (0, 1):
        assert cli_main(_elastic_argv(
            src, out, coord, rank, "bin-mean", "consensus",
            tmp_path / "j.jsonl",
        )) == 0
    from specpride_tpu.observability.stats_cli import run_stats

    buf = io.StringIO()
    agg_path = tmp_path / "agg.json"
    assert run_stats(
        [str(tmp_path / "j.jsonl")], json_out=str(agg_path), out=buf,
    ) == 0
    text = buf.getvalue()
    assert "ranks: 2 seen" in text
    assert "rank 0:" in text and "rank 1:" in text
    assert "elastic: rank=0" in text
    agg = json.load(open(agg_path))
    assert set(agg["elastic"]["ranks"]) == {"0", "1"}
    assert agg["elastic"]["unpaired_lease_expiries"] == 0
    view = summarize_ranks([
        read_events(f"{tmp_path}/j.jsonl.part0000{r}")[0]
        for r in (0, 1)
    ])
    total_chunks = sum(
        r["chunks_committed"] for r in view["ranks"].values()
    )
    assert total_chunks == 4  # every cluster committed exactly once


# -- merge-parts hardening ----------------------------------------------


def _fake_parts(tmp_path, n=3):
    out = tmp_path / "m.mgf"
    manifests = []
    for i in range(n):
        part = f"{out}.part{i:05d}"
        body = f"BEGIN IONS\nTITLE=c{i};x\nEND IONS\n\n".encode()
        with open(part, "wb") as fh:
            fh.write(body)
        ck = f"{tmp_path}/ck.json.part{i:05d}"
        with open(ck, "w") as fh:
            json.dump({
                "schema": 2, "done": [f"c{i}"],
                "output_bytes": len(body),
                "sha256": sha256_file(part),
            }, fh)
        manifests.append(ck)
    return out


def test_merge_refuses_missing_middle_rank(tmp_path, capsys):
    out = _fake_parts(tmp_path)
    os.remove(f"{out}.part00001")
    assert cli_main(["merge-parts", str(out)]) == 1
    assert "missing [1]" in capsys.readouterr().err


def test_merge_refuses_missing_trailing_rank_with_count(tmp_path, capsys):
    out = _fake_parts(tmp_path)
    os.remove(f"{out}.part00002")
    # without a pinned count the trailing loss is invisible by
    # construction; --num-processes (or --elastic) pins it
    assert cli_main([
        "merge-parts", str(out), "--num-processes", "3",
    ]) == 1
    assert "missing [2]" in capsys.readouterr().err


def test_merge_verifies_checkpoint_manifests(tmp_path, capsys):
    out = _fake_parts(tmp_path)
    ck = f"{tmp_path}/ck.json"
    assert cli_main(["merge-parts", str(out), "--checkpoint", ck]) == 0
    # corrupt one byte inside a committed shard: the sha256 check must
    # refuse the merge and name the shard
    with open(f"{out}.part00001", "r+b") as fh:
        fh.seek(3)
        fh.write(b"X")
    assert cli_main(["merge-parts", str(out), "--checkpoint", ck]) == 1
    err = capsys.readouterr().err
    assert "rank/range 1" in err and "sha256 mismatch" in err


def test_merge_elastic_refuses_corrupt_part(tmp_path, rng):
    src = _write_input(tmp_path, rng, n=4)
    out = tmp_path / "out.mgf"
    coord = tmp_path / "coord"
    assert cli_main(_elastic_argv(
        src, out, coord, 0, "bin-mean", "consensus",
        tmp_path / "j.jsonl",
    )) == 0
    with open(f"{out}.part00000", "r+b") as fh:
        fh.seek(5)
        fh.write(b"Z")
    assert cli_main([
        "merge-parts", str(out), "--elastic", str(coord),
    ]) == 1


def test_merge_qc_reports_matches_serial_shape(tmp_path):
    shards = []
    for i, cos in enumerate(([0.5, 0.75], [1.0])):
        rows = [
            {"cluster_id": f"c{i}{j}", "n_members": 2, "avg_cosine": v}
            for j, v in enumerate(cos)
        ]
        path = tmp_path / f"qc.part0000{i}"
        with open(path, "w") as fh:
            json.dump({
                "summary": {
                    "n_clusters": len(rows),
                    "n_input_clusters": len(rows) + 1,
                    "n_method_failed": 0, "n_qc_failed": 0,
                },
                "clusters": rows,
            }, fh)
        shards.append(str(path))
    merged = tmp_path / "qc.json"
    assert merge_qc_reports(shards, str(merged)) == 3
    got = json.load(open(merged))
    assert got["summary"]["n_clusters"] == 3
    assert got["summary"]["n_input_clusters"] == 5
    assert got["summary"]["mean_cosine"] == pytest.approx(0.75)
    assert [r["cluster_id"] for r in got["clusters"]] == [
        "c00", "c01", "c10",
    ]
