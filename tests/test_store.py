"""Pluggable coordinator store: filesystem + object-store (CAS) backend
contract, the in-tree CAS server, clock-skew liveness judgment, and
injected compare-and-swap conflicts."""

import time

import pytest

from specpride_tpu.parallel.coordinator import Coordinator
from specpride_tpu.parallel.store import (
    CasServer,
    FsStore,
    HttpCasStore,
    is_remote_spec,
    store_from_spec,
)
from specpride_tpu.robustness import faults


@pytest.fixture()
def cas_server():
    server = CasServer().start()
    yield server
    server.stop()


def _contract(store):
    """The op contract both backends must satisfy identically."""
    # create-if-absent: exactly one winner
    assert store.put_new("leases/range_00000.json", {"nonce": "a"})
    assert not store.put_new("leases/range_00000.json", {"nonce": "b"})
    payload, etag = store.get("leases/range_00000.json")
    assert payload["nonce"] == "a"
    # touch refreshes freshness without changing content
    time.sleep(0.05)
    before = store.age_s("leases/range_00000.json")
    assert store.touch("leases/range_00000.json")
    after = store.age_s("leases/range_00000.json")
    assert after is not None and after <= before
    got = store.get("leases/range_00000.json")
    assert got[0]["nonce"] == "a"
    # compare-and-delete: stale token loses, current token wins
    assert not store.delete_if("leases/range_00000.json", "bogus-etag")
    current_etag = store.get("leases/range_00000.json")[1]
    assert store.delete_if("leases/range_00000.json", current_etag)
    assert store.get("leases/range_00000.json") is None
    assert not store.delete_if("leases/range_00000.json", current_etag)
    # unconditional put: last writer wins; listing sees only live keys
    store.put("hb/rank_00000.json", {"rank": 0})
    store.put("hb/rank_00000.json", {"rank": 0, "ts": 1})
    store.put("hb/rank_00001.json", {"rank": 1})
    assert store.list_keys("hb/") == [
        "hb/rank_00000.json", "hb/rank_00001.json",
    ]
    assert store.get("hb/rank_00000.json")[0]["ts"] == 1
    # absent keys
    assert store.get("nope.json") is None
    assert store.age_s("nope.json") is None
    assert not store.touch("nope.json")
    store.delete("hb/rank_00001.json")
    assert store.list_keys("hb/") == ["hb/rank_00000.json"]


def test_fs_store_contract(tmp_path):
    _contract(FsStore(str(tmp_path)))


def test_http_store_contract(cas_server):
    _contract(HttpCasStore(cas_server.url))


def test_fs_etag_stable_under_touch_distinct_per_content(tmp_path):
    """The filesystem token is content-derived: a renewal (utime) keeps
    it, a re-created lease (fresh nonce) changes it — so an expiry
    steal's compare-and-delete can never confuse the two."""
    store = FsStore(str(tmp_path))
    store.put_new("leases/r.json", {"nonce": "first"})
    etag = store.get("leases/r.json")[1]
    store.touch("leases/r.json")
    assert store.get("leases/r.json")[1] == etag
    store.delete("leases/r.json")
    store.put_new("leases/r.json", {"nonce": "second"})
    assert store.get("leases/r.json")[1] != etag


def test_http_etag_changes_per_revision(cas_server):
    """The object-store token is a server revision: even identical
    bytes re-written produce a fresh token (a stealer holding the old
    one loses, as it must)."""
    store = HttpCasStore(cas_server.url)
    store.put_new("k.json", {"x": 1})
    e1 = store.get("k.json")[1]
    assert store.touch("k.json")  # same body, new revision
    e2 = store.get("k.json")[1]
    assert e1 != e2
    assert not store.delete_if("k.json", e1)
    assert store.delete_if("k.json", e2)


def test_fs_tombstone_left_behind(tmp_path):
    """A filesystem compare-and-delete renames to a tombstone — steal
    debris stays on disk for post-mortems, and listings hide it."""
    store = FsStore(str(tmp_path))
    store.put_new("leases/r.json", {"nonce": "x"})
    etag = store.get("leases/r.json")[1]
    assert store.delete_if("leases/r.json", etag)
    leftovers = list((tmp_path / "leases").iterdir())
    assert leftovers and ".dead." in leftovers[0].name
    assert store.list_keys("leases/") == []


def test_store_from_spec_dispatch(tmp_path):
    assert isinstance(store_from_spec(str(tmp_path)), FsStore)
    assert isinstance(
        store_from_spec("http://127.0.0.1:1/x"), HttpCasStore
    )
    assert is_remote_spec("https://host/bucket")
    assert not is_remote_spec(str(tmp_path))


def test_http_age_is_server_clock(cas_server):
    """Liveness age comes from the SERVER's clock: a skewed client
    reads the same age any other observer would."""
    store = HttpCasStore(cas_server.url)
    store.put("hb/r.json", {"rank": 0})
    age = store.age_s("hb/r.json")
    assert age is not None and age < 1.0
    time.sleep(0.15)
    age2 = store.age_s("hb/r.json")
    assert age2 > age


# -- clock skew must not early-steal ------------------------------------


def test_skewed_observer_cannot_steal_inside_grace(tmp_path):
    """An observer whose clock runs ahead must NOT judge a live lease
    expired inside the TTL + grace window: with TTL=1s (grace 0.5s) and
    a +1.2s skew the lease looks 1.2s old — past the TTL but inside the
    grace — so the claim attempt yields nothing and the holder keeps
    its range.  Past TTL + grace the same observer may steal."""
    holder = Coordinator(str(tmp_path), 0, 4, 4, ttl=1.0)
    claim = holder.claim_next()
    assert claim is not None
    observer = Coordinator(str(tmp_path), 1, 4, 4, ttl=1.0)
    real_now = time.time
    try:
        # skew: past TTL, inside grace -> no steal
        observer.store._now = lambda: real_now() + 1.2
        assert observer.claim_next() is None
        holder.check_lease(0)  # holder is untouched
        # skew past TTL + grace -> the lease is fair game
        observer.store._now = lambda: real_now() + 2.0
        stolen = observer.claim_next()
        assert stolen is not None and stolen.takeover
    finally:
        holder.stop()
        observer.stop()


def test_renewal_resets_the_skewed_window(tmp_path):
    """A heartbeat renewal restarts the age even under observer skew —
    only a rank that STOPS renewing can be stolen from."""
    holder = Coordinator(str(tmp_path), 0, 4, 4, ttl=0.4,
                         heartbeat_interval=0.1)
    assert holder.claim_next() is not None
    observer = Coordinator(str(tmp_path), 1, 4, 4, ttl=0.4)
    real_now = time.time
    try:
        observer.store._now = lambda: real_now() + 0.5
        # drive the renewal synchronously via flush_progress (the same
        # _beat the heartbeat thread runs): on a loaded 1-core host the
        # background thread can be starved past TTL+grace, which would
        # test the scheduler, not the renewal semantics
        deadline = time.perf_counter() + 1.5
        while time.perf_counter() < deadline:
            holder.flush_progress()
            assert observer.claim_next() is None
            time.sleep(0.05)
        holder.check_lease(0)
    finally:
        holder.stop()
        observer.stop()


# -- injected CAS conflicts ---------------------------------------------


class RecordingJournal:
    enabled = True

    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        rec = {"event": event, **fields}
        self.events.append(rec)
        return rec

    def close(self):
        pass


def test_cas_conflict_injection_loses_gracefully(tmp_path):
    """An injected `cas` conflict makes the claim attempt lose like a
    real race: no lease lands, a `retry` event (site=cas) journals the
    recovery, and the next scan claims normally."""
    journal = RecordingJournal()
    plan = faults.FaultPlan.parse("cas:cas_conflict:1", seed=0)
    prev = faults.install(plan, journal=journal)
    try:
        coord = Coordinator(str(tmp_path), 0, 4, 4, ttl=5.0,
                            journal=journal)
        try:
            assert coord.claim_next() is None  # conflict injected
            assert coord.cas_conflicts == 1
            claim = coord.claim_next()  # plan MAX=1: second scan clean
            assert claim is not None
            retries = [
                e for e in journal.events
                if e["event"] == "retry" and e.get("site") == "cas"
            ]
            assert len(retries) == 1
            fired = [e for e in journal.events if e["event"] == "fault"]
            assert fired and fired[0]["kind"] == "cas_conflict"
            merged = journal.events
            assert not faults.audit_fault_recovery(merged)
        finally:
            coord.stop()
    finally:
        faults.install(prev)


def test_rank_slow_stalls_without_failing(monkeypatch):
    """`rank_slow` delays the visit and returns — no exception, and the
    recovery audit does not expect one."""
    monkeypatch.setenv("SPECPRIDE_SLOW_S", "0.05")
    plan = faults.FaultPlan.parse("dispatch:rank_slow:1:0:3", seed=0)
    t0 = time.perf_counter()
    for _ in range(3):
        plan.check("dispatch")  # must NOT raise
    assert time.perf_counter() - t0 >= 0.15
    assert plan.fired_by_site["dispatch"] == 3
    events = [
        {"event": "fault", "site": "dispatch", "kind": "rank_slow",
         "visit": i, "mono": float(i)}
        for i in range(3)
    ]
    assert not faults.audit_fault_recovery(events)
