"""``specpride serve``: served-vs-CLI byte parity for the three methods
(including two jobs submitted concurrently), bounded FIFO-fair
admission, graceful drain (in-flight commits, queued rejected with a
retriable status), resident-backend singleton-state deltas per job, and
``specpride stats --follow``."""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.io.mgf import write_mgf
from specpride_tpu.observability.journal import read_events
from specpride_tpu.serve import client as sc
from specpride_tpu.serve.daemon import ServeDaemon
from specpride_tpu.serve.scheduler import AdmissionQueue

from conftest import make_cluster

METHODS = [
    ("bin-mean", "consensus"),
    ("gap-average", "consensus"),
    ("medoid", "select"),
]


def _events(path):
    return [json.loads(line) for line in open(path)]


def _start(daemon: ServeDaemon) -> threading.Thread:
    t = threading.Thread(target=daemon.run, daemon=True)
    t.start()
    assert sc.wait_for_socket(daemon.socket_path, timeout=120), \
        "daemon never answered ping"
    return t


def _stop(daemon: ServeDaemon, thread: threading.Thread) -> None:
    daemon.drain()
    thread.join(timeout=60)
    assert not thread.is_alive(), "daemon thread did not exit after drain"


@pytest.fixture(scope="module")
def workload(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("serve_wl")
    rng = np.random.default_rng(99)
    clusters = [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25)
        for i in range(8)
    ]
    src = tmp / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], src)
    return str(src)


@pytest.fixture(scope="module")
def daemon(tmp_path_factory):
    """One long-lived daemon shared by the parity tests — exactly the
    multi-job reuse the serving subsystem exists for."""
    tmp = tmp_path_factory.mktemp("serve_daemon")
    d = ServeDaemon(
        str(tmp / "serve.sock"),
        compile_cache=str(tmp / "cache"),
        journal_path=str(tmp / "serve.jsonl"),
    )
    t = _start(d)
    yield d
    _stop(d, t)
    events, violations = read_events(d.journal_path)
    assert not violations, violations
    names = [e["event"] for e in events]
    assert names[0] == "run_start" and names[-1] == "run_end"
    assert "serve_start" in names and "serve_drain" in names


def _cli(src, out, method, command, qc=None, extra=()):
    argv = [command, src, out, "--method", method]
    if qc:
        argv += ["--qc-report", qc]
    assert cli_main(argv + list(extra)) == 0


class TestServedParity:
    @pytest.mark.parametrize("method,command", METHODS)
    def test_byte_identical_and_qc_equivalent(
        self, tmp_path, workload, daemon, method, command
    ):
        """A served job must reproduce the one-shot CLI's exact MGF
        bytes AND QC report for every method."""
        cli_out = tmp_path / "cli.mgf"
        cli_qc = tmp_path / "cli.qc.json"
        _cli(workload, str(cli_out), method, command, qc=str(cli_qc))
        served_out = tmp_path / "served.mgf"
        served_qc = tmp_path / "served.qc.json"
        term = sc.submit_wait(daemon.socket_path, [
            command, workload, str(served_out), "--method", method,
            "--qc-report", str(served_qc),
            "--journal", str(tmp_path / "job.jsonl"),
        ])
        assert term["status"] == "done" and term["rc"] == 0, term
        assert served_out.read_bytes() == cli_out.read_bytes(), method
        assert (
            json.loads(served_qc.read_text())
            == json.loads(cli_qc.read_text())
        ), method
        # the job journaled a complete run of its own
        job_events, violations = read_events(str(tmp_path / "job.jsonl"))
        assert not violations, violations
        assert [e for e in job_events if e["event"] == "run_end"]

    def test_two_concurrent_jobs_byte_identical(
        self, tmp_path, workload, daemon
    ):
        """Two clients submitting concurrently get the same bytes the
        CLI produces — admission is concurrent, execution serialized,
        and neither job sees the other's state."""
        golden = {}
        for method, command in METHODS[:2]:
            out = tmp_path / f"cli_{method}.mgf"
            _cli(workload, str(out), method, command)
            golden[method] = out.read_bytes()

        results = {}

        def _client(method, command):
            out = tmp_path / f"served_{method}.mgf"
            results[method] = (
                sc.submit_wait(daemon.socket_path, [
                    command, workload, str(out), "--method", method,
                ]),
                out,
            )

        threads = [
            threading.Thread(target=_client, args=mc) for mc in METHODS[:2]
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        for method, (term, out) in results.items():
            assert term["status"] == "done", (method, term)
            assert out.read_bytes() == golden[method], method

    def test_numpy_backend_job(self, tmp_path, workload, daemon):
        """--backend numpy jobs run through the oracle path (no resident
        backend involved) and still match the one-shot CLI."""
        cli_out = tmp_path / "cli_np.mgf"
        _cli(workload, str(cli_out), "bin-mean", "consensus",
             extra=["--backend", "numpy"])
        out = tmp_path / "served_np.mgf"
        term = sc.submit_wait(daemon.socket_path, [
            "consensus", workload, str(out), "--method", "bin-mean",
            "--backend", "numpy",
        ])
        assert term["status"] == "done", term
        assert out.read_bytes() == cli_out.read_bytes()


class TestResidentState:
    def test_warm_and_singleton_deltas_across_jobs(
        self, tmp_path_factory, workload
    ):
        """The multi-job singleton fix: job 2 of an identical workload
        on the resident backend reports ZERO fresh compiles, ZERO new
        shape classes and plan-cache hits — while job 1 reported the
        compiles and misses it actually paid.  Snapshot-and-diff, so
        neither job's numbers include the other's."""
        tmp = tmp_path_factory.mktemp("serve_warm")
        d = ServeDaemon(
            str(tmp / "s.sock"),
            compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            layout="bucketized",
            force_device=True,
            # one lane: both jobs must hit the SAME resident backend for
            # the job-2 zero-new-shape-classes assertion to hold (a pool
            # would route job 2 to a second backend's fresh seen-set)
            workers=1,
        )
        t = _start(d)
        try:
            journals = []
            for i in (1, 2):
                out = tmp / f"out{i}.mgf"
                jp = tmp / f"job{i}.jsonl"
                journals.append(str(jp))
                term = sc.submit_wait(d.socket_path, [
                    "consensus", workload, str(out), "--method",
                    "gap-average", "--journal", str(jp),
                ])
                assert term["status"] == "done", term
            ends = []
            for jp in journals:
                events, violations = read_events(jp)
                assert not violations, violations
                ends.append(
                    [e for e in events if e["event"] == "run_end"][-1]
                )
            first, second = ends
            # the daemon's backend is freshly constructed, so job 1
            # dispatches every shape class first; its unique workload
            # digest misses the process-wide plan cache.  (Absolute
            # compile-cache misses are NOT asserted for job 1: in-suite,
            # earlier tests may have jit-compiled the same kernels in
            # this process — exactly the warm behavior serving banks on.)
            assert first["shape_classes"]["new"] > 0
            assert first["shape_classes"]["total"] == \
                first["shape_classes"]["new"]
            assert first["plan_cache"]["misses"] > 0
            # job 2: fully warm, and its deltas are ITS OWN (zero), not
            # a cumulative process total
            assert second["compile_cache"]["misses"] == 0
            assert second["shape_classes"]["new"] == 0
            assert second["shape_classes"]["total"] == \
                first["shape_classes"]["total"]
            assert second["plan_cache"]["misses"] == 0
            assert second["plan_cache"]["hits"] > 0
            # the daemon journal agrees: the second job_done is warm
            dj = [
                e for e in _events(d.journal_path)
                if e["event"] == "job_done"
            ]
            assert dj[1]["fresh_compiles"] == 0
        finally:
            _stop(d, t)


class TestAdmission:
    def test_scheduler_round_robin_fair(self):
        q = AdmissionQueue(capacity=16)
        for client, job in [
            ("A", "a1"), ("A", "a2"), ("A", "a3"), ("B", "b1"), ("C", "c1"),
        ]:
            assert q.offer(client, job)
        order = [q.pop(timeout=0.1) for _ in range(5)]
        # one job per client per round (first-submission order), FIFO
        # within a client
        assert order == ["a1", "b1", "c1", "a2", "a3"]

    def test_scheduler_capacity_and_drain(self):
        q = AdmissionQueue(capacity=2)
        assert q.offer("A", 1) and q.offer("B", 2)
        assert not q.offer("C", 3), "offer above capacity must refuse"
        rejected = q.drain()
        assert rejected == [1, 2]
        assert not q.offer("A", 4), "a drained queue admits nothing"
        assert q.pop(timeout=0.05) is None

    def test_queue_full_rejected_retriable(
        self, tmp_path_factory, workload
    ):
        tmp = tmp_path_factory.mktemp("serve_full")
        d = ServeDaemon(
            str(tmp / "s.sock"), max_queue=1,
            compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            # single lane: the test fills the one queue slot behind a
            # held worker — a pool would pop the queued job into a
            # second gated lane and the queue would never reach capacity
            workers=1,
        )
        d._gate.clear()  # hold the worker so submissions stay queued
        t = _start(d)
        try:
            terms = {}

            def _submit(tag):
                terms[tag] = sc.submit_wait(d.socket_path, [
                    "consensus", workload, str(tmp / f"{tag}.mgf"),
                    "--method", "bin-mean",
                ])

            t1 = threading.Thread(target=_submit, args=("first",))
            t1.start()
            # wait for the first job to be POPPED (in flight, gated) so
            # the second occupies the single queue slot deterministically
            deadline = time.time() + 30
            while d._inflight is None and time.time() < deadline:
                time.sleep(0.01)
            assert d._inflight is not None
            t2 = threading.Thread(target=_submit, args=("second",))
            t2.start()
            while len(d.queue) < 1 and time.time() < deadline:
                time.sleep(0.01)
            # queue is now at capacity: the third submit must bounce
            _submit("third")
            assert terms["third"]["status"] == "rejected"
            assert terms["third"]["reason"] == "queue_full"
            assert terms["third"]["retriable"] is True
            d._gate.set()
            t1.join(timeout=120)
            t2.join(timeout=120)
            assert terms["first"]["status"] == "done"
            assert terms["second"]["status"] == "done"
        finally:
            _stop(d, t)

    def test_bad_jobs_rejected_permanent(self, tmp_path, workload, daemon):
        # unknown command
        term = sc.submit_wait(daemon.socket_path, ["evaluate", "x", "y"])
        assert term["status"] == "rejected" and not term["retriable"]
        # daemon-owned flag
        term = sc.submit_wait(daemon.socket_path, [
            "consensus", workload, str(tmp_path / "o.mgf"),
            "--compile-cache", "off",
        ])
        assert term["status"] == "rejected" and not term["retriable"]
        assert "--compile-cache" in term["reason"]
        # an ABBREVIATED daemon-owned flag (argparse accepts unambiguous
        # prefixes) must be caught too — via the parsed namespace
        term = sc.submit_wait(daemon.socket_path, [
            "consensus", workload, str(tmp_path / "o.mgf"),
            "--layou", "flat",
        ])
        assert term["status"] == "rejected" and not term["retriable"]
        assert "--layout" in term["reason"]
        # argv the CLI parser refuses — with the parser's own message
        term = sc.submit_wait(daemon.socket_path, [
            "consensus", workload, str(tmp_path / "o.mgf"),
            "--method", "no-such-method",
        ])
        assert term["status"] == "rejected" and not term["retriable"]
        assert "invalid choice" in term["reason"]
        # --help must reject, never print help into the daemon
        term = sc.submit_wait(daemon.socket_path, ["consensus", "--help"])
        assert term["status"] == "rejected" and not term["retriable"]
        # a non-string scheduling identity is a protocol violation, not
        # a TypeError inside the queue
        term = sc.request(daemon.socket_path, {
            "op": "submit",
            "argv": ["consensus", workload, str(tmp_path / "o.mgf")],
            "client": ["not", "a", "string"],
        })
        assert term["status"] == "rejected" and not term["retriable"]
        assert "client" in term["reason"]

    def test_job_error_reported_not_fatal(
        self, tmp_path, workload, daemon
    ):
        """A job whose input is missing errors to ITS client; the daemon
        keeps serving."""
        term = sc.submit_wait(daemon.socket_path, [
            "consensus", str(tmp_path / "missing.mgf"),
            str(tmp_path / "o.mgf"), "--method", "bin-mean",
        ])
        assert term["status"] == "error", term
        ok = tmp_path / "after_error.mgf"
        term = sc.submit_wait(daemon.socket_path, [
            "consensus", workload, str(ok), "--method", "bin-mean",
        ])
        assert term["status"] == "done" and ok.exists()


class TestDrain:
    def test_drain_commits_inflight_rejects_queued(
        self, tmp_path_factory, workload
    ):
        """The SIGTERM contract (drain() is the signal handler's body):
        the in-flight job commits through the ordered write lane and
        reports done; queued jobs are rejected with a retriable status;
        the drained output is byte-identical to the CLI's (no torn
        output, manifest complete)."""
        tmp = tmp_path_factory.mktemp("serve_drain")
        cli_out = tmp / "cli.mgf"
        _cli(workload, str(cli_out), "bin-mean", "consensus")
        d = ServeDaemon(
            str(tmp / "s.sock"),
            compile_cache=str(tmp / "cache"),
            journal_path=str(tmp / "serve.jsonl"),
            # single lane: "one in-flight, one queued" is the state the
            # drain contract is asserted against (the multi-worker drain
            # matrix lives in test_workers.py)
            workers=1,
        )
        d._gate.clear()
        t = _start(d)
        terms = {}

        def _submit(tag, extra=()):
            terms[tag] = sc.submit_wait(d.socket_path, [
                "consensus", workload, str(tmp / f"{tag}.mgf"),
                "--method", "bin-mean",
                "--checkpoint", str(tmp / f"{tag}.ck.json"),
                "--checkpoint-every", "2", *extra,
            ])

        t1 = threading.Thread(target=_submit, args=("inflight",))
        t1.start()
        deadline = time.time() + 30
        while d._inflight is None and time.time() < deadline:
            time.sleep(0.01)
        assert d._inflight is not None
        t2 = threading.Thread(target=_submit, args=("queued",))
        t2.start()
        while len(d.queue) < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert len(d.queue) == 1
        _stop(d, t)  # drain: sets the gate, joins the worker
        t1.join(timeout=120)
        t2.join(timeout=120)
        assert terms["inflight"]["status"] == "done", terms["inflight"]
        assert (tmp / "inflight.mgf").read_bytes() == cli_out.read_bytes()
        # resume integrity: the drained manifest records every cluster
        manifest = json.loads((tmp / "inflight.ck.json").read_text())
        assert len(manifest["done"]) == 8
        assert manifest["output_bytes"] == os.path.getsize(
            tmp / "inflight.mgf"
        )
        assert terms["queued"]["status"] == "rejected"
        assert terms["queued"]["reason"] == "draining"
        assert terms["queued"]["retriable"] is True
        # new connections are refused once drained (socket removed)
        with pytest.raises(OSError):
            sc.request(d.socket_path, {"op": "ping"}, timeout=2.0)
        events, violations = read_events(d.journal_path)
        assert not violations, violations
        drain_ev = [e for e in events if e["event"] == "serve_drain"]
        assert drain_ev and drain_ev[0]["n_rejected"] == 1


class TestFollow:
    def test_follow_rerenders_incrementally(self, tmp_path):
        """`stats --follow` re-renders as new complete events land and
        never consumes a torn trailing line."""
        from specpride_tpu.observability.journal import Journal
        from specpride_tpu.observability.stats_cli import follow_stats

        path = tmp_path / "live.jsonl"
        journal = Journal(path)
        journal.emit(
            "run_start", command="serve", method="serve", backend="tpu",
            n_clusters=0,
        )
        journal.emit(
            "serve_start", socket="s", max_queue=4, warmed_kernels=3,
        )

        buf = io.StringIO()
        stop = threading.Event()
        t = threading.Thread(
            target=follow_stats,
            args=(str(path),),
            kwargs={"out": buf, "interval": 0.05, "stop": stop},
            daemon=True,
        )
        t.start()

        def _wait_for(needle, timeout=20):
            deadline = time.time() + timeout
            while needle not in buf.getvalue():
                assert time.time() < deadline, (
                    needle, buf.getvalue()
                )
                time.sleep(0.02)

        _wait_for("update 1")
        assert "serving:" in buf.getvalue()
        # a torn line must NOT render until its newline lands
        with open(path, "a") as fh:
            fh.write('{"v": 2, "ts": 1.0, "mono": 1.0, "event": "job_')
            fh.flush()
            time.sleep(0.2)
            assert "update 2" not in buf.getvalue()
            fh.write(
                'done", "job_id": 1, "status": "done", "wall_s": 0.5, '
                '"fresh_compiles": 0}\n'
            )
        _wait_for("update 2")
        stop.set()
        t.join(timeout=10)
        assert not t.is_alive()
        out = buf.getvalue()
        assert "jobs_done=1" in out and "warm=1" in out
        journal.close()

    def test_follow_requires_single_journal(self, tmp_path):
        with pytest.raises(SystemExit):
            cli_main([
                "stats", str(tmp_path / "a"), str(tmp_path / "b"),
                "--follow",
            ])
