"""The v4 trace-context plane: clock anchoring, cross-process trace
reassembly (the merger's hard cases), journal rotation, exemplars, and
the /healthz readiness probe.

The merger cases are the ones the ISSUE names explicitly: multi-process
merge under deliberately skewed wall clocks (the anchors must bound the
skew), torn ``.part`` shards, a batch-leader trace spanning two
tenants' jobs, and v2/v3 journals read without trace fields.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
import urllib.request

import pytest

from specpride_tpu.observability.exporter import (
    MetricsExporter,
    ServeTelemetry,
    parse_exposition_full,
    validate_exposition,
)
from specpride_tpu.observability.journal import (
    SCHEMA_VERSION,
    Journal,
    emit_clock_anchor,
    expand_parts,
    expand_segments,
    open_journal,
    read_events,
    validate_event,
)
from specpride_tpu.observability.registry import MetricsRegistry
from specpride_tpu.observability.tracing import (
    TraceContext,
    Tracer,
    new_span_id,
    new_trace_id,
)
from specpride_tpu.observability import traceplane
from specpride_tpu.robustness.watchdog import Watchdog

T1 = "a" * 32
T2 = "b" * 32


def _line(fh, **rec):
    fh.write(json.dumps(rec) + "\n")


def _span_rec(name, mono, dur, trace, span, parent=None, tid=0,
              labels=None, v=SCHEMA_VERSION):
    rec = {
        "v": v, "ts": mono, "mono": mono, "event": "span",
        "name": name, "dur_s": dur, "depth": 0, "tid": tid,
        "trace_id": trace, "span_id": span,
    }
    if parent:
        rec["parent_span_id"] = parent
    if labels:
        rec["labels"] = labels
    return rec


def _anchor_rec(mono, wall, unc=1e-6, v=SCHEMA_VERSION):
    return {
        "v": v, "ts": wall, "mono": mono, "event": "clock_anchor",
        "wall": wall, "uncertainty_s": unc,
    }


# -- trace context ------------------------------------------------------


class TestTraceContext:
    def test_mint_shapes(self):
        ctx = TraceContext.mint()
        assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        int(ctx.trace_id, 16)  # hex

    def test_env_roundtrip(self):
        ctx = TraceContext.mint()
        back = TraceContext.from_env(ctx.to_env())
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id

    @pytest.mark.parametrize("bad", [
        "", "nope", "xyz:abc", "a" * 32, "a" * 32 + ":" + "g" * 16,
        "a" * 31 + ":" + "b" * 16,
    ])
    def test_env_malformed_degrades_to_none(self, bad):
        assert TraceContext.from_env(bad) is None

    def test_wire_roundtrip_and_rejects(self):
        ctx = TraceContext.mint()
        back = TraceContext.from_wire(ctx.to_wire())
        assert back.trace_id == ctx.trace_id
        assert TraceContext.from_wire(None) is None
        with pytest.raises(ValueError):
            TraceContext.from_wire({"trace_id": "zz"})
        with pytest.raises(ValueError):
            TraceContext.from_wire("not-an-object")
        with pytest.raises(ValueError):
            TraceContext.from_wire(
                {"trace_id": T1, "parent_span_id": "short"}
            )

    def test_tracer_assigns_causal_ids(self, tmp_path):
        path = tmp_path / "j.jsonl"
        ctx = TraceContext.mint()
        with Journal(path) as j:
            tracer = Tracer(journal=j, ctx=ctx)
            with tracer.span("outer"):
                with tracer.span("inner"):
                    pass
        events, bad = read_events(str(path))
        assert bad == []
        inner = next(e for e in events if e["name"] == "inner")
        outer = next(e for e in events if e["name"] == "outer")
        assert outer["parent_span_id"] == ctx.span_id
        assert inner["parent_span_id"] == outer["span_id"]
        assert len(outer["span_id"]) == 16

    def test_tracer_without_ctx_emits_no_ids(self, tmp_path):
        path = tmp_path / "j.jsonl"
        with Journal(path) as j:
            tracer = Tracer(journal=j)
            with tracer.span("plain"):
                pass
        events, _ = read_events(str(path))
        assert "span_id" not in events[0]


# -- v4 validation ------------------------------------------------------


class TestV4Validation:
    def test_v2_v3_job_events_read_without_trace_fields(self):
        for v in (2, 3):
            rec = {"v": v, "ts": 1.0, "mono": 1.0, "event": "job_done",
                   "job_id": 1, "status": "done", "wall_s": 0.5}
            assert validate_event(rec) == []

    def test_v4_job_events_require_trace_id(self):
        rec = {"v": 4, "ts": 1.0, "mono": 1.0, "event": "job_done",
               "job_id": 1, "status": "done", "wall_s": 0.5}
        assert any("trace fields" in p for p in validate_event(rec))
        rec["trace_id"] = T1
        assert validate_event(rec) == []

    def test_v4_batch_dispatch_requires_trace_ids(self):
        rec = {"v": 4, "ts": 1.0, "mono": 1.0, "event": "batch_dispatch",
               "batch_id": 1, "jobs": [1], "n_jobs": 1, "n_clusters": 3,
               "window_wait_s": 0.0, "status": "shared"}
        assert any("trace_ids" in p for p in validate_event(rec))
        rec["trace_ids"] = [T1]
        assert validate_event(rec) == []

    def test_malformed_ids_rejected(self):
        base = {"v": 4, "ts": 1.0, "mono": 1.0, "event": "resume",
                "n_done": 1}
        assert validate_event({**base, "trace_id": "nope"})
        assert validate_event({**base, "trace_id": T1}) == []
        assert validate_event(
            {**base, "trace_id": T1, "span_id": "xx"}
        )

    def test_bound_journal_stamps_every_event(self, tmp_path):
        path = tmp_path / "j.jsonl"
        with Journal(path) as j:
            j.bind_trace(T1)
            emit_clock_anchor(j)
            j.emit("resume", n_done=3)
            # an explicit trace_id wins over the binding
            j.emit("job_done", job_id=1, status="done", wall_s=0.1,
                   trace_id=T2)
        events, bad = read_events(str(path))
        assert bad == []
        assert [e["trace_id"] for e in events] == [T1, T1, T2]

    def test_clock_anchor_event_is_schema_valid(self, tmp_path):
        path = tmp_path / "j.jsonl"
        with Journal(path) as j:
            rec = emit_clock_anchor(j)
        assert validate_event(rec) == []
        events, bad = read_events(str(path))
        assert bad == []
        # the paired capture: mono is the midpoint, uncertainty bounds it
        assert events[0]["uncertainty_s"] < 0.1


# -- clock anchoring ----------------------------------------------------


class TestClockAnchorFit:
    def test_known_offset_recovered(self):
        events = [_anchor_rec(mono=100.0, wall=5100.0),
                  _anchor_rec(mono=200.0, wall=5200.0)]
        offset, bound = traceplane.clock_anchor_fit(events)
        assert offset == pytest.approx(5000.0)
        assert bound < 0.001

    def test_skewed_anchors_bound_the_skew(self):
        # one anchor drifted 0.5s (an NTP step mid-run): the median
        # offset tracks the majority and the bound reports the outlier
        events = [_anchor_rec(100.0, 5100.0),
                  _anchor_rec(200.0, 5200.0),
                  _anchor_rec(300.0, 5300.5)]
        offset, bound = traceplane.clock_anchor_fit(events)
        assert offset == pytest.approx(5000.0)
        assert bound >= 0.5

    def test_pre_v4_fallback_uses_envelope_pair(self):
        events = [{"v": 2, "ts": 5100.0, "mono": 100.0,
                   "event": "resume", "n_done": 1}]
        offset, bound = traceplane.clock_anchor_fit(events)
        assert offset == pytest.approx(5000.0)
        assert bound == pytest.approx(0.05)

    def test_no_usable_pair(self):
        assert traceplane.clock_anchor_fit([]) is None


# -- the merger's hard cases -------------------------------------------


class TestMergerHardCases:
    def _write(self, path, recs):
        with open(path, "w", encoding="utf-8") as fh:
            for rec in recs:
                _line(fh, **rec)

    def test_skewed_wall_clocks_align_on_one_axis(self, tmp_path):
        """Two processes whose WALL clocks disagree by 100s: each
        journal's anchors place its spans on its own wall axis — the
        merged view keeps the causal order because each process's
        offset comes from ITS anchors, and the skew bound reports the
        per-process capture quality (not the cross-host disagreement,
        which is unobservable without a common reference)."""
        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        # process A: wall = mono + 1000, span [10, 11]
        self._write(a, [
            _anchor_rec(5.0, 1005.0),
            _span_rec("client", 11.0, 1.0, T1, "1" * 16),
        ])
        # process B: wall = mono + 2000 BUT its wall clock runs 100s
        # ahead of A's; span [10.2, 10.8] nests inside A's on A's axis
        # only if B's own anchors are used — they are
        self._write(b, [
            _anchor_rec(5.0, 2105.0),
            _span_rec("server", 10.8, 0.6, T1, "2" * 16,
                      parent="1" * 16),
        ])
        view = traceplane.extract_trace([str(a), str(b)], T1)
        assert len(view.shards) == 2
        spans = {s["name"]: s for s in view.spans}
        assert spans["client"]["start"] == pytest.approx(1010.0)
        # B's span lands on B's anchored axis (2100 offset + skew)
        assert spans["server"]["start"] == pytest.approx(2110.2)
        assert view.skew_bound_s < 0.01

    def test_torn_part_shard_dropped_deterministically(self, tmp_path):
        base = tmp_path / "r.jsonl"
        p0 = tmp_path / "r.jsonl.part00000"
        p1 = tmp_path / "r.jsonl.part00001"
        self._write(p0, [
            _anchor_rec(1.0, 101.0),
            _span_rec("rank0", 2.0, 0.5, T1, "3" * 16),
        ])
        with open(p1, "w", encoding="utf-8") as fh:
            _line(fh, **_anchor_rec(1.0, 101.0))
            _line(fh, **_span_rec("rank1", 2.0, 0.5, T1, "4" * 16))
            fh.write('{"v": 4, "ts": 3.0, "mono": 3.0, "event": "spa')
        view = traceplane.extract_trace([str(base)], T1)
        assert {s["name"] for s in view.spans} == {"rank0", "rank1"}
        assert any("invalid JSON" in v for v in view.violations)
        # deterministic: a second read yields the identical view
        view2 = traceplane.extract_trace([str(base)], T1)
        assert [s["name"] for s in view2.spans] == \
            [s["name"] for s in view.spans]

    def test_batch_leader_trace_spans_two_tenants(self, tmp_path):
        """A shared dispatch serving tenants T1 (leader) and T2: the
        leader's trace pulls in the member's serve:job span via the
        batch_dispatch join (trace_ids + labels.job_id), marked
        linked=batch."""
        d = tmp_path / "serve.jsonl"
        leader_job = "5" * 16
        self._write(d, [
            _anchor_rec(1.0, 101.0),
            _span_rec("serve:job", 3.0, 1.0, T1, leader_job,
                      labels={"job_id": 1}),
            _span_rec("serve:job", 3.1, 1.0, T2, "6" * 16,
                      labels={"job_id": 2}),
            {"v": 4, "ts": 2.5, "mono": 2.5, "event": "batch_dispatch",
             "batch_id": 9, "jobs": [1, 2], "n_jobs": 2,
             "n_clusters": 8, "window_wait_s": 0.01,
             "status": "shared", "trace_ids": [T1, T2],
             "span_id": "7" * 16, "parent_span_id": leader_job},
            _span_rec("serve:batch", 2.9, 0.4, T1, "7" * 16,
                      parent=leader_job, labels={"batch_id": 9}),
        ])
        view = traceplane.extract_trace([str(d)], T1)
        names = {s["name"] for s in view.spans}
        assert names == {"serve:job", "serve:batch"}
        jobs = [s for s in view.spans if s["name"] == "serve:job"]
        assert len(jobs) == 2  # BOTH tenants' jobs in the leader trace
        linked = [s for s in jobs
                  if s["labels"].get("linked") == "batch"]
        assert len(linked) == 1
        assert linked[0]["labels"]["job_id"] == 2
        # the member's trace sees the batch too (trace_ids join) but
        # not the leader's solo spans
        view2 = traceplane.extract_trace([str(d)], T2)
        names2 = {(s["name"], s["labels"].get("job_id"))
                  for s in view2.spans}
        assert ("serve:job", 2) in names2
        assert ("serve:job", 1) in names2  # linked through the batch

    def test_old_journals_no_trace_fields_extract_nothing(self, tmp_path):
        old = tmp_path / "old.jsonl"
        self._write(old, [
            {"v": 2, "ts": 1.0, "mono": 1.0, "event": "run_start",
             "command": "consensus", "method": "bin-mean",
             "backend": "tpu", "n_clusters": 4},
            {"v": 2, "ts": 2.0, "mono": 2.0, "event": "span",
             "name": "chunk", "dur_s": 0.5, "depth": 0},
        ])
        events, bad = read_events(str(old))
        assert bad == []  # v2 still reads clean
        view = traceplane.extract_trace([str(old)], T1)
        assert view.spans == [] and view.shards == []

    def test_resolve_job_trace(self, tmp_path):
        d = tmp_path / "serve.jsonl"
        self._write(d, [
            {"v": 4, "ts": 1.0, "mono": 1.0, "event": "job_done",
             "job_id": 7, "status": "done", "wall_s": 0.5,
             "trace_id": T1},
        ])
        assert traceplane.resolve_job_trace([str(d)], 7) == T1
        assert traceplane.resolve_job_trace([str(d)], 8) is None

    def test_flow_events_cross_process_only(self, tmp_path):
        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        self._write(a, [
            _anchor_rec(0.0, 100.0),
            _span_rec("parent", 5.0, 4.0, T1, "1" * 16),
            _span_rec("samepid", 4.0, 1.0, T1, "9" * 16,
                      parent="1" * 16),
        ])
        self._write(b, [
            _anchor_rec(0.0, 100.0),
            _span_rec("child", 4.5, 2.0, T1, "2" * 16,
                      parent="1" * 16),
        ])
        out = tmp_path / "t.json"
        view = traceplane.build_trace_chrome(
            [str(a), str(b)], T1, str(out)
        )
        assert len(view.shards) == 2
        trace = json.loads(out.read_text())
        flows = [e for e in trace["traceEvents"]
                 if e.get("cat") == "flow"]
        # exactly one cross-process edge -> one s/f pair
        assert len(flows) == 2
        assert {f["ph"] for f in flows} == {"s", "f"}
        assert flows[0]["id"] == "2" * 16

    def test_critical_path_descends_latest_child(self):
        view = traceplane.TraceView(T1)
        view.spans = [
            {"name": "root", "start": 0.0, "end": 10.0, "dur": 10.0,
             "pid": 0, "tid": 0, "span_id": "1" * 16,
             "parent_span_id": None, "labels": {}},
            {"name": "early", "start": 1.0, "end": 3.0, "dur": 2.0,
             "pid": 0, "tid": 0, "span_id": "2" * 16,
             "parent_span_id": "1" * 16, "labels": {}},
            {"name": "late", "start": 4.0, "end": 9.0, "dur": 5.0,
             "pid": 1, "tid": 0, "span_id": "3" * 16,
             "parent_span_id": "1" * 16, "labels": {}},
        ]
        path = traceplane.critical_path(view)
        assert [h["name"] for h in path] == ["root", "late"]
        assert path[0]["self_s"] == pytest.approx(5.0)
        out = io.StringIO()
        traceplane.render_critical_path(view, out)
        assert "critical path" in out.getvalue()
        assert "late" in out.getvalue()


# -- journal rotation ---------------------------------------------------


class TestJournalRotation:
    def test_rotation_produces_numbered_segments(self, tmp_path):
        path = tmp_path / "serve.jsonl"
        j = Journal(path, rotate_mb=0.0005)  # ~512 bytes
        for i in range(40):
            j.emit("resume", n_done=i, pad="x" * 64)
        j.close()
        segs = expand_segments(str(path))
        assert len(segs) >= 3
        assert segs[-1] == str(path)
        assert segs[0].endswith(".1")
        # every segment is whole lines; the stream reassembles in order
        # (each fresh segment opens with its own clock_anchor so the
        # trace merger never degrades to the envelope fallback)
        seen = []
        for i, seg in enumerate(segs):
            events, bad = read_events(seg)
            assert bad == []
            if i > 0:
                assert events[0]["event"] == "clock_anchor"
            seen.extend(e["n_done"] for e in events
                        if e["event"] == "resume")
        assert seen == list(range(40))

    def test_expand_parts_walks_segments(self, tmp_path):
        path = tmp_path / "serve.jsonl"
        j = Journal(path, rotate_mb=0.0005)
        for i in range(40):
            j.emit("resume", n_done=i, pad="x" * 64)
        j.close()
        files, warnings = expand_parts(str(path))
        assert warnings == []
        assert files == expand_segments(str(path))

    def test_part_shards_with_segments(self, tmp_path):
        base = tmp_path / "r.jsonl"
        p0 = str(base) + ".part00000"
        j = Journal(p0, rotate_mb=0.0005)
        for i in range(40):
            j.emit("resume", n_done=i, pad="x" * 64)
        j.close()
        files, warnings = expand_parts(str(base))
        assert warnings == []  # rotated segments are not "unrecognized"
        assert files[-1] == p0
        assert len(files) >= 3

    def test_follow_reads_across_rotation(self, tmp_path):
        from specpride_tpu.observability.stats_cli import _poll_rotated

        path = tmp_path / "live.jsonl"

        def dones(events):
            return [e["n_done"] for e in events
                    if e["event"] == "resume"]

        j = Journal(path, rotate_mb=0.0005)
        j.emit("resume", n_done=0, pad="x" * 64)
        events, offset, segs = _poll_rotated(str(path), 0, 0)
        assert dones(events) == [0]
        # force several rotations between polls
        for i in range(1, 30):
            j.emit("resume", n_done=i, pad="x" * 64)
        events, offset, segs = _poll_rotated(str(path), offset, segs)
        assert dones(events) == list(range(1, 30))
        j.emit("resume", n_done=30, pad="x" * 64)
        events, offset, segs = _poll_rotated(str(path), offset, segs)
        assert dones(events) == [30]
        j.close()

    def test_no_rotation_by_default(self, tmp_path):
        path = tmp_path / "j.jsonl"
        j = open_journal(str(path))
        for i in range(100):
            j.emit("resume", n_done=i, pad="x" * 64)
        j.close()
        assert expand_segments(str(path)) == [str(path)]


# -- exemplars ----------------------------------------------------------


class TestExemplars:
    def test_histogram_renders_exemplar(self):
        r = MetricsRegistry()
        h = r.histogram("t_seconds", "test", buckets=(1.0, 5.0))
        h.observe(0.5, exemplar={"trace_id": T1})
        text = r.to_prometheus_text()
        assert f'# {{trace_id="{T1}"}} 0.5' in text
        assert validate_exposition(text) == []
        samples, exemplars, problems = parse_exposition_full(text)
        assert problems == []
        key = ("t_seconds_bucket", (("le", "1"),))
        assert exemplars[key] == {"trace_id": T1}

    def test_exemplar_on_inf_bucket(self):
        r = MetricsRegistry()
        h = r.histogram("t_seconds", "test", buckets=(1.0,))
        h.observe(99.0, exemplar={"trace_id": T2})
        _s, exemplars, problems = parse_exposition_full(
            r.to_prometheus_text()
        )
        assert problems == []
        assert (("t_seconds_bucket", (("le", "+Inf"),))) in exemplars

    def test_validator_rejects_exemplar_on_non_bucket(self):
        text = (
            "# TYPE x counter\n"
            'x_total 3 # {trace_id="' + T1 + '"} 3\n'
        )
        assert any("non-bucket" in p for p in validate_exposition(text))

    def test_validator_rejects_malformed_exemplar(self):
        text = (
            "# TYPE h histogram\n"
            'h_bucket{le="+Inf"} 1 # {trace_id=} 1\n'
            "h_sum 1\nh_count 1\n"
        )
        assert validate_exposition(text)

    def test_serve_telemetry_attaches_job_exemplar(self):
        t = ServeTelemetry()
        t.sampler = None
        t.job_done(command="consensus", method="bin-mean",
                   status="done", wall_s=0.2, queue_wait_s=0.01,
                   trace_id=T1)
        text = t.registry.to_prometheus_text()
        assert f'trace_id="{T1}"' in text
        assert validate_exposition(text) == []


class TestReviewRegressions:
    """Pins for the review-round fixes."""

    def test_exemplar_split_respects_quoted_label_values(self):
        # ' # ' inside a label VALUE (client ids are user-controlled)
        # is not an exemplar marker — the line must stay valid
        text = (
            "# TYPE specpride_serve_queue_depth_client gauge\n"
            'specpride_serve_queue_depth_client{client="team # 1"} 2\n'
        )
        samples, exemplars, problems = parse_exposition_full(text)
        assert problems == []
        assert exemplars == {}
        key = ("specpride_serve_queue_depth_client",
               (("client", "team # 1"),))
        assert samples[key] == 2.0

    def test_part_segment_not_swallowed_by_base(self, tmp_path):
        # x.jsonl.part00000.1 is a segment of the PART, never of the
        # base x.jsonl
        base = tmp_path / "x.jsonl"
        base.write_text(json.dumps(
            {"v": 4, "ts": 1.0, "mono": 1.0, "event": "resume",
             "n_done": 1}) + "\n")
        foreign = tmp_path / "x.jsonl.part00000.1"
        foreign.write_text(json.dumps(
            {"v": 4, "ts": 1.0, "mono": 1.0, "event": "resume",
             "n_done": 99}) + "\n")
        assert expand_segments(str(base)) == [str(base)]
        files, _ = expand_parts(str(base))
        assert files == [str(base)]

    def test_batch_join_spans_rotated_segments(self, tmp_path):
        """The batch_dispatch landing in segment .1 while the member
        spans land in the live file must still join — segments of one
        journal are ONE stream on ONE process track."""
        leader_job = "5" * 16
        seg1 = tmp_path / "serve.jsonl.1"
        live = tmp_path / "serve.jsonl"
        with open(seg1, "w", encoding="utf-8") as fh:
            _line(fh, **_anchor_rec(1.0, 101.0))
            _line(fh, **{
                "v": 4, "ts": 2.5, "mono": 2.5,
                "event": "batch_dispatch", "batch_id": 9,
                "jobs": [1, 2], "n_jobs": 2, "n_clusters": 8,
                "window_wait_s": 0.01, "status": "shared",
                "trace_ids": [T1, T2], "span_id": "7" * 16,
                "parent_span_id": leader_job,
            })
        with open(live, "w", encoding="utf-8") as fh:
            _line(fh, **_span_rec("serve:batch", 2.9, 0.4, T1, "7" * 16,
                                  parent=leader_job,
                                  labels={"batch_id": 9}))
            _line(fh, **_span_rec("serve:job", 3.0, 1.0, T1, leader_job,
                                  labels={"job_id": 1}))
            _line(fh, **_span_rec("serve:job", 3.1, 1.0, T2, "6" * 16,
                                  labels={"job_id": 2}))
        # the MEMBER's trace sees the shared span and both jobs
        view = traceplane.extract_trace([str(live)], T2)
        names = {s["name"] for s in view.spans}
        assert "serve:batch" in names
        assert len([s for s in view.spans
                    if s["name"] == "serve:job"]) == 2
        # one logical journal = one process track, segments included
        assert len(view.shards) == 1
        assert {s["pid"] for s in view.spans} == {0}

    def test_elastic_health_skips_cleanly_stopped_peers(self):
        from specpride_tpu.observability.exporter import ElasticTelemetry

        class FakeCoord:
            rank = 0
            ttl = 1.0
            grace = 0.5
            ranges = [1, 2, 3]

            def __init__(self, states):
                self._states = states

            def rank_heartbeat_states(self):
                return self._states

            def done_count(self):
                return 1

        # a retired peer (stopped=True, huge age) is NOT stale
        t = ElasticTelemetry(FakeCoord({0: (0.1, False),
                                        1: (99.0, True)}))
        ok, detail = t.health()
        assert ok, detail
        # a silent peer (no stopped marker) IS
        t = ElasticTelemetry(FakeCoord({0: (0.1, False),
                                        1: (99.0, False)}))
        ok, detail = t.health()
        assert not ok and "stale_ranks=1" in detail


# -- /healthz readiness -------------------------------------------------


class TestHealthz:
    def _get(self, url):
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_default_healthz_stays_unconditional(self):
        ex = MetricsExporter(lambda: "", port=0).start()
        try:
            code, body = self._get(
                f"http://127.0.0.1:{ex.port}/healthz"
            )
            assert code == 200 and body.strip() == "ok"
        finally:
            ex.stop()

    def test_health_callback_ok_and_degraded(self):
        state = {"ok": True}

        def health():
            if state["ok"]:
                return True, "workers=2"
            return False, "stalled=serve:job worst_stall_s=9.1"

        ex = MetricsExporter(lambda: "", port=0, health=health).start()
        try:
            url = f"http://127.0.0.1:{ex.port}/healthz"
            code, body = self._get(url)
            assert code == 200 and body == "ok workers=2\n"
            state["ok"] = False
            code, body = self._get(url)
            assert code == 503
            assert body.startswith("degraded stalled=serve:job")
        finally:
            ex.stop()

    def test_health_callback_crash_degrades(self):
        def health():
            raise RuntimeError("boom")

        ex = MetricsExporter(lambda: "", port=0, health=health).start()
        try:
            code, body = self._get(
                f"http://127.0.0.1:{ex.port}/healthz"
            )
            assert code == 503 and "boom" in body
        finally:
            ex.stop()

    def test_watchdog_stalled_view(self):
        wd = Watchdog(0.05)
        release = threading.Event()

        def wedge():
            with wd.section("serve:job"):
                release.wait(5.0)

        t = threading.Thread(target=wedge, daemon=True)
        t.start()
        deadline = time.time() + 2.0
        while time.time() < deadline and not wd.stalled():
            time.sleep(0.01)
        stalled = wd.stalled()
        assert stalled and stalled[0][0] == "serve:job"
        assert stalled[0][1] >= 0.05
        release.set()
        t.join()
        assert wd.stalled() == []  # recovery visible immediately
        wd.stop()

    def test_disabled_watchdog_reports_nothing(self):
        assert Watchdog(0.0).stalled() == []


# -- daemon healthz wiring (unit, no boot) ------------------------------


class TestDaemonHealth:
    def _daemon(self, **kw):
        from specpride_tpu.serve.daemon import ServeDaemon

        return ServeDaemon(socket_path="/tmp/nonexistent.sock", **kw)

    def test_ok_when_idle(self):
        d = self._daemon(watchdog_timeout=5.0)
        ok, detail = d._healthz()
        assert ok and "workers=" in detail

    def test_degraded_on_drain(self):
        d = self._daemon()
        d._draining = True
        ok, detail = d._healthz()
        assert not ok and detail.startswith("draining")

    def test_degraded_on_stall_names_lane(self):
        d = self._daemon(watchdog_timeout=0.05)
        release = threading.Event()

        def wedge():
            with d.watchdog.section("serve:job"):
                release.wait(5.0)

        t = threading.Thread(target=wedge, daemon=True)
        t.start()
        deadline = time.time() + 2.0
        while time.time() < deadline and d._healthz()[0]:
            time.sleep(0.01)
        ok, detail = d._healthz()
        release.set()
        t.join()
        d.watchdog.stop()
        assert not ok and "stalled=serve:job" in detail

    def test_watchdog_off_noted(self):
        d = self._daemon()
        ok, detail = d._healthz()
        assert ok and "watchdog=off" in detail
