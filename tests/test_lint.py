"""``specpride lint`` (specpride_tpu.analysis): one seeded violation
per checker must be caught, a clean fixture must report nothing, the
--json report round-trips, baseline/suppression semantics hold, and
the real repository lints clean (the CI gate's contract)."""

from __future__ import annotations

import json
import os
import textwrap

import pytest

from specpride_tpu.analysis import checker_ids, run_checks
from specpride_tpu.analysis.baseline import Baseline
from specpride_tpu.analysis.core import Finding, Project

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_tree(root, files: dict) -> str:
    for rel, text in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(textwrap.dedent(text))
    return str(root)


# -- fixture sources ----------------------------------------------------

LANE_BAD = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.hits = 0
            self._t = threading.Thread(
                target=self._run, name="fix-worker", daemon=True
            )

        def _run(self):
            while True:
                self.hits += 1  # unguarded, also written from main

        def bump(self):
            self.hits += 1

    def cmd_main():
        c = Counter()
        c.bump()
"""

LANE_GOOD = LANE_BAD.replace(
    """\
        def _run(self):
            while True:
                self.hits += 1  # unguarded, also written from main

        def bump(self):
            self.hits += 1
""",
    """\
        def _run(self):
            while True:
                with self._lock:
                    self.hits += 1

        def bump(self):
            with self._lock:
                self.hits += 1
""",
)

JIT_OPS = """
    from fix.ops.jit_util import jit_pair

    def _kernel(x, *, cap, impl):
        return x

    kernel_packed, kernel_packed_donated = jit_pair(
        _kernel, static_argnames=("cap", "impl"), donate_argnums=(0,)
    )
"""

JIT_UTIL = """
    import jax

    def jit_pair(fn, static_argnames, donate_argnums):
        plain = jax.jit(fn, static_argnames=static_argnames)
        donated = jax.jit(
            fn, static_argnames=static_argnames,
            donate_argnums=donate_argnums,
        )
        return plain, donated
"""

# builder statics drop "impl" -> the PR 6 bug class
JIT_REGISTRY_BAD = """
    from fix.ops import kernels

    def _kernel_packed(entry, donate):
        avals = ()
        statics = dict(cap=entry.shape_key[0])
        fn = (
            kernels.kernel_packed_donated if donate
            else kernels.kernel_packed
        )
        return fn, avals, statics

    _BUILDERS = {
        "kernel_packed": _kernel_packed,
    }
"""

JIT_REGISTRY_GOOD = JIT_REGISTRY_BAD.replace(
    "statics = dict(cap=entry.shape_key[0])",
    "statics = dict(cap=entry.shape_key[0], impl='scan')",
)

JOURNAL_MOD = """
    EVENT_FIELDS = {
        "run_start": frozenset({"command"}),
        "run_end": frozenset({"elapsed_s"}),
    }

    class Journal:
        def emit(self, event, **fields):
            return {}
"""

JOURNAL_EMIT_BAD = """
    def go(journal):
        journal.emit("run_start", command="x")
        journal.emit("run_stop")  # unknown event
        journal.emit("run_end")   # missing elapsed_s
"""

JOURNAL_EMIT_GOOD = """
    def go(journal):
        journal.emit("run_start", command="x")
        journal.emit("run_end", elapsed_s=1.0)

    def render(events):
        return [e for e in events if e["event"] == "run_end"]
"""

DOC_EVENTS_GOOD = """
    # Events

    | event | payload (required) | meaning |
    |---|---|---|
    | `run_start` | `command` | run began |
    | `run_end` | `elapsed_s` (plus `counters`) | run finished |
"""

DOC_EVENTS_BAD = """
    # Events

    | event | payload (required) | meaning |
    |---|---|---|
    | `run_start` | `command`, `n_clusters` | run began |
    | `run_finish` | `elapsed_s` | stale row |
"""

METRICS_BAD = """
    def build(r):
        r.counter("specpride_fix_jobs", "no _total suffix")
        r.gauge("specpride_fix_depth_total", "gauge with _total")
"""

METRICS_GOOD = """
    def build(r):
        r.counter("specpride_fix_jobs_total", "jobs")
        r.gauge("specpride_fix_depth", "depth")
"""

DOC_METRICS_GOOD = """
    # Metrics

    - `specpride_fix_jobs_total` — jobs
    - `specpride_fix_depth` — queue depth
"""

FLAGS_MOD_BAD = """
    DAEMON_ONLY_FLAGS = ("--layout", "--vanished")
    _DAEMON_OWNED_DESTS = ("layout", "stale_dest")
"""

FLAGS_MOD_GOOD = """
    DAEMON_ONLY_FLAGS = ("--layout",)
    _DAEMON_OWNED_DESTS = ("layout",)
"""

FLAGS_PARSER = """
    import argparse

    def build():
        ap = argparse.ArgumentParser()
        ap.add_argument("--layout", choices=["auto", "flat"])
        return ap
"""

DOC_FLAGS = """
    # Flags

    - `--layout` — device layout
"""

FAULTS_MOD = """
    EXECUTOR_FAULT_SITES = ("parse", "write")
    FAULT_SITES = EXECUTOR_FAULT_SITES + ("cas",)

    def check(site):
        pass
"""

FAULTS_VISITS_BAD = """
    from fix.robustness import faults

    def run():
        faults.check("parse")
        faults.check("wrong_site")
        # "write" and "cas" never visited
"""

FAULTS_VISITS_GOOD = """
    from fix.robustness import faults

    def run():
        faults.check("parse")
        faults.check("write")
        faults.check("cas")
"""


def base_fixture(good: bool) -> dict:
    """A miniature project exercising every checker's anchors; ``good``
    selects the violation-free variant of each artifact."""
    return {
        "fix/__init__.py": "",
        "fix/lanes.py": LANE_GOOD if good else LANE_BAD,
        "fix/ops/__init__.py": "",
        "fix/ops/jit_util.py": JIT_UTIL,
        "fix/ops/kernels.py": JIT_OPS,
        "fix/registry.py": (
            JIT_REGISTRY_GOOD if good else JIT_REGISTRY_BAD
        ),
        "fix/journal.py": JOURNAL_MOD,
        "fix/emitter.py": (
            JOURNAL_EMIT_GOOD if good else JOURNAL_EMIT_BAD
        ),
        "fix/metrics.py": METRICS_GOOD if good else METRICS_BAD,
        "fix/protocol.py": FLAGS_MOD_GOOD if good else FLAGS_MOD_BAD,
        "fix/parser.py": FLAGS_PARSER,
        "fix/robustness/__init__.py": "",
        "fix/robustness/faults.py": FAULTS_MOD,
        "fix/visits.py": (
            FAULTS_VISITS_GOOD if good else FAULTS_VISITS_BAD
        ),
        "docs/observability.md": (
            DOC_EVENTS_GOOD if good else DOC_EVENTS_BAD
        ) + DOC_METRICS_GOOD,
        "docs/cli.md": DOC_FLAGS,
    }


@pytest.fixture
def bad_root(tmp_path):
    return write_tree(tmp_path, base_fixture(good=False))


@pytest.fixture
def clean_root(tmp_path):
    return write_tree(tmp_path, base_fixture(good=True))


def by_check(findings):
    out = {}
    for f in findings:
        out.setdefault(f.check, []).append(f)
    return out


# -- every checker catches its seeded violation -------------------------


def test_lane_safety_catches_unlocked_multi_lane_write(bad_root):
    found = by_check(run_checks(bad_root, select=["lane-safety"]))
    hits = found.get("lane-safety", [])
    assert any(
        "Counter.hits" in f.symbol or f.symbol.endswith("hits")
        for f in hits
    ), hits
    assert all(f.path == "fix/lanes.py" for f in hits)


def test_jit_hygiene_catches_builder_statics_drift(bad_root):
    hits = run_checks(bad_root, select=["jit-hygiene"])
    assert any(
        "statics" in f.symbol and "impl" in f.message for f in hits
    ), hits


def test_jit_hygiene_catches_host_sync_and_missing_registry(tmp_path):
    files = base_fixture(good=True)
    files["fix/ops/kernels.py"] = textwrap.dedent(JIT_OPS) + (
        textwrap.dedent("""
        import numpy as np
        from fix.ops.jit_util import jit_pair

        def _orphan(x, *, cap):
            return float(np.asarray(x).sum())

        orphan_kernel, orphan_kernel_donated = jit_pair(
            _orphan, static_argnames=("cap",), donate_argnums=(0,)
        )
        """)
    )
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["jit-hygiene"])
    symbols = {f.symbol for f in hits}
    assert "orphan_kernel:registry" in symbols, hits
    assert any(s.startswith("_orphan:host-sync") for s in symbols), hits


def test_journal_schema_catches_all_directions(bad_root):
    hits = run_checks(bad_root, select=["journal-schema"])
    symbols = {f.symbol for f in hits}
    assert "emit:run_stop" in symbols  # unknown event emitted
    assert "emit:run_end:fields" in symbols  # missing required field
    assert "doc:run_start:fields" in symbols  # docs row drift
    assert "doc:run_finish:unknown" in symbols  # stale docs row
    assert "doc:run_end" in symbols  # schema event missing a row


def test_journal_schema_trace_envelope_both_directions(tmp_path):
    """The v4 extension: an emit of a TRACE_EVENT_FIELDS event missing
    its causal fields is a finding, and so is a docs row that never
    mentions them; the fixed variants are clean."""
    files = base_fixture(good=True)
    files["fix/journal.py"] = """
        EVENT_FIELDS = {
            "run_start": frozenset({"command"}),
            "run_end": frozenset({"elapsed_s"}),
            "job_done": frozenset({"job_id"}),
        }

        TRACE_EVENT_FIELDS = {
            "job_done": frozenset({"trace_id"}),
        }

        class Journal:
            def emit(self, event, **fields):
                return {}
    """
    files["fix/emitter.py"] = """
        def go(journal):
            journal.emit("run_start", command="x")
            journal.emit("run_end", elapsed_s=1.0)
            journal.emit("job_done", job_id=1)  # no trace_id
    """
    files["docs/observability.md"] = """
        # Events

        | event | payload (required) | meaning |
        |---|---|---|
        | `run_start` | `command` | run began |
        | `run_end` | `elapsed_s` (plus `counters`) | run finished |
        | `job_done` | `job_id` | done, trace field undocumented |
    """ + DOC_METRICS_GOOD
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["journal-schema"])
    symbols = {f.symbol for f in hits}
    assert "emit:job_done:trace" in symbols, hits
    assert "doc:job_done:trace" in symbols, hits
    # fixed: the emit carries trace_id, the row mentions it behind plus
    files["fix/emitter.py"] = """
        def go(journal, tid):
            journal.emit("run_start", command="x")
            journal.emit("run_end", elapsed_s=1.0)
            journal.emit("job_done", job_id=1, trace_id=tid)
    """
    files["docs/observability.md"] = """
        # Events

        | event | payload (required) | meaning |
        |---|---|---|
        | `run_start` | `command` | run began |
        | `run_end` | `elapsed_s` (plus `counters`) | run finished |
        | `job_done` | `job_id` (plus `trace_id`, required from v4) | done |
    """ + DOC_METRICS_GOOD
    root2 = write_tree(tmp_path / "fixed", files)
    assert run_checks(root2, select=["journal-schema"]) == []


def test_journal_schema_no_trace_table_is_vacuous(tmp_path):
    """A fixture tree without TRACE_EVENT_FIELDS (pre-v4) reports no
    trace findings — the anchor-absent convention every checker keeps."""
    root = write_tree(tmp_path, base_fixture(good=True))
    hits = run_checks(root, select=["journal-schema"])
    assert not any(":trace" in f.symbol for f in hits), hits
    assert not any(":v5" in f.symbol for f in hits), hits


def test_journal_schema_v5_fields_both_directions(tmp_path):
    """The v5 extension mirrors the v4 trace envelope: an emit of a
    V5_EVENT_FIELDS event missing its additive field is a finding, and
    so is a docs row that never mentions it; the fixed variants are
    clean."""
    files = base_fixture(good=True)
    files["fix/journal.py"] = """
        EVENT_FIELDS = {
            "run_start": frozenset({"command"}),
            "run_end": frozenset({"elapsed_s"}),
            "heartbeat": frozenset({"rank"}),
        }

        V5_EVENT_FIELDS = {
            "heartbeat": frozenset({"chunk_s"}),
        }

        class Journal:
            def emit(self, event, **fields):
                return {}
    """
    files["fix/emitter.py"] = """
        def go(journal):
            journal.emit("run_start", command="x")
            journal.emit("run_end", elapsed_s=1.0)
            journal.emit("heartbeat", rank=0)  # no chunk_s
    """
    files["docs/observability.md"] = """
        # Events

        | event | payload (required) | meaning |
        |---|---|---|
        | `run_start` | `command` | run began |
        | `run_end` | `elapsed_s` (plus `counters`) | run finished |
        | `heartbeat` | `rank` | liveness, v5 field undocumented |
    """ + DOC_METRICS_GOOD
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["journal-schema"])
    symbols = {f.symbol for f in hits}
    assert "emit:heartbeat:v5" in symbols, hits
    assert "doc:heartbeat:v5" in symbols, hits
    # fixed: the emit carries chunk_s, the row mentions it behind plus
    files["fix/emitter.py"] = """
        def go(journal, wall):
            journal.emit("run_start", command="x")
            journal.emit("run_end", elapsed_s=1.0)
            journal.emit("heartbeat", rank=0, chunk_s=wall)
    """
    files["docs/observability.md"] = """
        # Events

        | event | payload (required) | meaning |
        |---|---|---|
        | `run_start` | `command` | run began |
        | `run_end` | `elapsed_s` (plus `counters`) | run finished |
        | `heartbeat` | `rank` (plus `chunk_s`, required from v5) | beat |
    """ + DOC_METRICS_GOOD
    root2 = write_tree(tmp_path / "fixed", files)
    assert run_checks(root2, select=["journal-schema"]) == []


def test_journal_schema_catches_stale_renderer_literal(tmp_path):
    files = base_fixture(good=True)
    files["fix/emitter.py"] = textwrap.dedent(
        files["fix/emitter.py"]
    ) + textwrap.dedent("""
        def render_stale(events):
            return [e for e in events if e.get("event") == "gone"]
    """)
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["journal-schema"])
    assert any(f.symbol == "render:gone" for f in hits), hits


def test_metrics_conformance_catches_suffix_and_doc_drift(bad_root):
    hits = run_checks(bad_root, select=["metrics-conformance"])
    symbols = {f.symbol for f in hits}
    assert "specpride_fix_jobs:suffix" in symbols
    assert "specpride_fix_depth_total:suffix" in symbols
    # the good docs list the GOOD names; the bad code registers others
    assert any(s.endswith(":undocumented") for s in symbols)
    assert any(s.endswith(":stale-doc") for s in symbols)


def test_metrics_pre_register_contract(tmp_path):
    files = base_fixture(good=True)
    files["fix/exporter.py"] = textwrap.dedent("""
        PRE_REGISTERED_FAMILIES = ("specpride_fix_batch_*",)

        class Telemetry:
            def __init__(self, r):
                self.batch = r.counter(
                    "specpride_fix_batch_total", "batched work"
                )

            def sync_singletons(self):
                pass
    """)
    files["docs/observability.md"] += (
        "\n- `specpride_fix_batch_total` — batch counter\n"
    )
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["metrics-conformance"])
    assert any(
        f.symbol == "specpride_fix_batch_total:pre-register"
        for f in hits
    ), hits
    # zero-init satisfies the contract
    files["fix/exporter.py"] = textwrap.dedent("""
        PRE_REGISTERED_FAMILIES = ("specpride_fix_batch_*",)

        class Telemetry:
            def __init__(self, r):
                self.batch = r.counter(
                    "specpride_fix_batch_total", "batched work"
                )
                self.batch.inc(0)

            def sync_singletons(self):
                pass
    """)
    root2 = tmp_path / "ok"
    os.makedirs(root2, exist_ok=True)
    write_tree(root2, files)
    hits2 = run_checks(str(root2), select=["metrics-conformance"])
    assert not any("pre-register" in f.symbol for f in hits2), hits2


def test_cli_flags_catches_stale_daemon_flag_and_dest(bad_root):
    hits = run_checks(bad_root, select=["cli-flags"])
    symbols = {f.symbol for f in hits}
    assert "--vanished:unknown" in symbols
    assert "stale_dest:dest-stale" in symbols
    assert "vanished:dest-missing" in symbols


def test_cli_flags_catches_undocumented_flag(tmp_path):
    files = base_fixture(good=True)
    files["fix/parser.py"] = FLAGS_PARSER.replace(
        'ap.add_argument("--layout", choices=["auto", "flat"])',
        'ap.add_argument("--layout", choices=["auto", "flat"])\n'
        '        ap.add_argument("--mystery", type=int)',
    )
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["cli-flags"])
    assert any(
        f.symbol == "--mystery:undocumented" for f in hits
    ), hits


def test_fault_sites_both_directions(bad_root):
    hits = run_checks(bad_root, select=["fault-sites"])
    symbols = {f.symbol for f in hits}
    assert "wrong_site:undeclared" in symbols
    assert "write:unvisited" in symbols
    assert "cas:unvisited" in symbols


# -- clean fixture ------------------------------------------------------


def test_clean_fixture_has_zero_findings(clean_root):
    findings = run_checks(clean_root)
    assert findings == [], [f.to_json() for f in findings]


# -- report / baseline / suppression semantics --------------------------


def test_json_report_round_trip(bad_root, tmp_path):
    from specpride_tpu.cli import main as cli_main

    out = tmp_path / "report.json"
    rc = cli_main([
        "lint", str(bad_root), "--json", str(out),
    ])
    assert rc == 1  # seeded violations, no baseline
    report = json.loads(out.read_text())
    assert report["version"] == 1
    assert {c["id"] for c in report["checks"]} == set(checker_ids())
    assert report["summary"]["new"] == len(report["findings"]) > 0
    for rec in report["findings"]:
        f = Finding.from_json(rec)
        assert f.to_json() == rec
        assert f.check in set(checker_ids())


def test_baseline_suppresses_and_reports_stale(bad_root, tmp_path):
    findings = run_checks(bad_root)
    assert findings
    bl_path = str(tmp_path / "baseline.json")
    Baseline.write(bl_path, findings)
    payload = json.loads(open(bl_path).read())
    # an un-justified baseline entry is itself a failure
    bl = Baseline.load(bl_path)
    new, baselined, stale, bad = bl.split(findings)
    assert new == [] and len(baselined) == len(findings)
    assert len(bad) == len(payload["suppressions"])  # reasons empty
    # justify every entry -> green
    for e in payload["suppressions"]:
        e["reason"] = "legacy, tracked in ISSUE 14"
    with open(bl_path, "w") as fh:
        json.dump(payload, fh)
    new, baselined, stale, bad = Baseline.load(bl_path).split(findings)
    assert new == [] and bad == [] and stale == []
    # a paid-off finding leaves its entry stale (reported, not fatal)
    new, _baselined, stale, _bad = Baseline.load(bl_path).split(
        findings[1:]
    )
    assert new == [] and len(stale) == 1


def test_baseline_cli_gate(bad_root, tmp_path):
    from specpride_tpu.cli import main as cli_main

    bl = tmp_path / "bl.json"
    assert cli_main([
        "lint", str(bad_root), "--update-baseline",
        "--baseline", str(bl),
    ]) == 0
    payload = json.loads(bl.read_text())
    for e in payload["suppressions"]:
        e["reason"] = "seeded fixture violation"
    bl.write_text(json.dumps(payload))
    assert cli_main([
        "lint", str(bad_root), "--baseline", str(bl),
    ]) == 0
    assert cli_main([
        "lint", str(bad_root), "--baseline", str(bl), "--no-baseline",
    ]) == 1


def test_inline_suppression(tmp_path):
    files = base_fixture(good=False)
    files["fix/lanes.py"] = LANE_BAD.replace(
        "self.hits += 1  # unguarded, also written from main",
        "self.hits += 1  # lint: ok[lane-safety] fixture proves "
        "suppression",
    ).replace(
        "            self.hits += 1\n\n    def cmd_main",
        "            self.hits += 1  # lint: ok[lane-safety] fixture\n"
        "\n    def cmd_main",
    )
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["lane-safety"])
    assert hits == [], [f.to_json() for f in hits]


def test_select_unknown_checker_is_an_error(bad_root):
    from specpride_tpu.cli import main as cli_main

    assert cli_main([
        "lint", str(bad_root), "--select", "no-such-check",
    ]) == 2


def test_list_enumerates_all_checkers(capsys):
    from specpride_tpu.cli import main as cli_main

    assert cli_main(["lint", "--list"]) == 0
    out = capsys.readouterr().out
    for cid in checker_ids():
        assert cid in out
    assert len(checker_ids()) >= 6


# -- the real repository ------------------------------------------------


def test_repository_lints_clean():
    """The CI gate's contract: the tree as committed has no findings
    beyond the committed baseline (which must itself be justified)."""
    project = Project(REPO_ROOT)
    assert project.errors == []
    findings = run_checks(REPO_ROOT, project=project)
    bl_path = os.path.join(REPO_ROOT, "lint-baseline.json")
    bl = Baseline.load(bl_path)
    new, _baselined, stale, bad = bl.split(findings)
    assert new == [], [f.to_json() for f in new]
    assert bad == [], "baseline entries need a written reason"
    assert stale == [], "remove paid-off baseline entries"


def test_project_scans_package_data_subdir():
    """Root-level `data/`/`docs/` prune; a package's OWN data
    subpackage must still be analyzed (specpride_tpu/data holds the
    packed layouts — blinding the checkers to it defeats the point)."""
    project = Project(REPO_ROOT)
    rels = {m.rel for m in project.modules}
    assert "specpride_tpu/data/packed.py" in rels
    assert not any(r.startswith("tests/") for r in rels)


def test_update_baseline_with_select_preserves_other_checkers(
    bad_root, tmp_path
):
    findings = run_checks(bad_root)
    lane = [f for f in findings if f.check == "lane-safety"]
    other = [f for f in findings if f.check != "lane-safety"]
    assert lane and other
    bl_path = str(tmp_path / "bl.json")
    Baseline.write(bl_path, findings)
    payload = json.loads(open(bl_path).read())
    for e in payload["suppressions"]:
        e["reason"] = "justified"
    with open(bl_path, "w") as fh:
        json.dump(payload, fh)
    # a one-checker refresh must keep the other checkers' entries AND
    # carry forward the written reasons on re-emitted fingerprints
    Baseline.write(
        bl_path, lane, existing=Baseline.load(bl_path),
        select=["lane-safety"],
    )
    bl = Baseline.load(bl_path)
    assert len(bl.entries) == len({f.fingerprint for f in findings})
    assert all(e["reason"] == "justified" for e in bl.entries)
    new, _b, stale, bad = bl.split(findings)
    assert new == [] and stale == [] and bad == []


def test_pre_register_rejects_bare_inc(tmp_path):
    files = base_fixture(good=True)
    files["fix/exporter.py"] = """
        PRE_REGISTERED_FAMILIES = ("specpride_fix_batch_*",)

        class Telemetry:
            def __init__(self, r):
                self.batch = r.counter(
                    "specpride_fix_batch_total", "batched work"
                )
                self.batch.inc()  # increments by 1: NOT a zero-init

            def sync_singletons(self):
                pass
    """
    files["docs/observability.md"] += (
        "\\n- `specpride_fix_batch_total` — batch counter\\n"
    )
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["metrics-conformance"])
    assert any("pre-register" in f.symbol for f in hits), hits


def test_cli_flags_docs_match_is_token_not_substring(tmp_path):
    files = base_fixture(good=True)
    files["fix/parser.py"] = FLAGS_PARSER.replace(
        'ap.add_argument("--layout", choices=["auto", "flat"])',
        'ap.add_argument("--layout", choices=["auto", "flat"])\n'
        '        ap.add_argument("--poll", type=float)',
    )
    files["docs/cli.md"] = DOC_FLAGS + (
        "\n- `--poll-interval` — a LONGER flag must not count as"
        " documenting `--poll-interval`'s prefix\n"
    )
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["cli-flags"])
    assert any(
        f.symbol == "--poll:undocumented" for f in hits
    ), hits


def test_lane_safety_sees_nested_thread_bodies(tmp_path):
    """The dominant concurrency pattern here is a nested closure
    handed to Thread(target=...) — its body (and everything it calls)
    must be walked, or lane propagation dies at the entry point."""
    files = base_fixture(good=True)
    files["fix/lanes.py"] = """
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                self.count += 1

        def cmd_pipeline(shared):
            def _worker():
                while True:
                    shared.bump()

            t = threading.Thread(
                target=_worker, name="fix-nested-worker", daemon=True
            )
            t.start()
            shared.bump()
    """
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["lane-safety"])
    assert any(f.symbol.endswith("count") for f in hits), hits


def test_select_does_not_report_other_checkers_entries_stale(
    bad_root, tmp_path
):
    findings = run_checks(bad_root)
    bl_path = str(tmp_path / "bl.json")
    Baseline.write(bl_path, findings)
    payload = json.loads(open(bl_path).read())
    for e in payload["suppressions"]:
        e["reason"] = "justified"
    with open(bl_path, "w") as fh:
        json.dump(payload, fh)
    lane_only = run_checks(bad_root, select=["lane-safety"])
    bl = Baseline.load(bl_path)
    new, _b, stale, bad = bl.split(lane_only, select=["lane-safety"])
    assert new == [] and stale == [] and bad == []
    # without select the unmatched entries ARE stale (full-run truth)
    _n, _b2, stale_full, _bad2 = bl.split(lane_only)
    assert stale_full


def test_metrics_prefix_rule(tmp_path):
    files = base_fixture(good=True)
    files["fix/metrics.py"] = METRICS_GOOD.replace(
        'r.gauge("specpride_fix_depth", "depth")',
        'r.gauge("specpride_fix_depth", "depth")\n'
        '    r.counter("h2d_bytes_total", "missing project prefix")',
    )
    root = write_tree(tmp_path, files)
    hits = run_checks(root, select=["metrics-conformance"])
    assert any(
        f.symbol == "h2d_bytes_total:prefix" for f in hits
    ), hits


def test_repository_anchor_discovery():
    """The cross-artifact anchors must actually resolve on the real
    tree — a silently-skipped checker would pass vacuously."""
    project = Project(REPO_ROOT)
    assert project.one_constant("EVENT_FIELDS") is not None
    assert project.one_constant("FAULT_SITES") is not None
    assert project.one_constant("DAEMON_ONLY_FLAGS") is not None
    assert project.one_constant("_BUILDERS") is not None
    assert project.one_constant("PRE_REGISTERED_FAMILIES") is not None
    from specpride_tpu.analysis import jit_hygiene

    kernels = jit_hygiene._collect_jit_pairs(project)
    assert len(kernels) >= 8  # every packed device kernel
