"""Golden-file tests: a checked-in clustered MGF in the interchange format
(docs/file_formats.md; structure mirrors the example at ref
file_formats.md:5-50 — full USI titles with peptide interpretation,
PEPMASS/CHARGE/RTINSECONDS headers, SEQUENCE extras) plus frozen outputs
for all four methods.

The frozen outputs pin the numpy oracle BYTE-EXACTLY (any behavioral
drift in a kernel, the MGF writer, or float formatting fails here), and
the TPU backend must match them within fp32 tolerance.  Regenerate only
for intentional behavior changes (see git history for the generator).
"""

import os

import numpy as np
import pytest

from specpride_tpu.backends import numpy_backend as nb
from specpride_tpu.backends.tpu_backend import TpuBackend
from specpride_tpu.cli import main as cli_main
from specpride_tpu.data.peaks import group_into_clusters
from specpride_tpu.io.maxquant import read_msms_scores
from specpride_tpu.io.mgf import read_mgf, write_mgf

DATA = os.path.join(os.path.dirname(__file__), "data")


def golden(name: str) -> str:
    return os.path.join(DATA, name)


@pytest.fixture(scope="module")
def clusters():
    return group_into_clusters(
        read_mgf(golden("golden_clustered.mgf"), use_native=False)
    )


@pytest.fixture(scope="module")
def scores():
    return read_msms_scores(golden("golden_msms.txt"))


def test_golden_input_is_interchange_format(clusters):
    assert [c.cluster_id for c in clusters] == [
        "cluster-1", "cluster-2", "cluster-3"
    ]
    assert [c.n_members for c in clusters] == [1, 3, 4]
    s = clusters[0].members[0]
    assert s.usi.startswith("mzspec:PXD004732:01650b_BA5-TUM")
    assert ":scan:17551:VLHPLEGAVVIIFK/2" in s.usi
    assert s.precursor_charge == 2
    assert s.extra["SEQUENCE"] == "VLHPLEGAVVIIFK/2"


def run_numpy(method, clusters, scores):
    if method == "bin_mean":
        return nb.run_bin_mean(clusters)
    if method == "gap_average":
        return nb.run_gap_average(clusters)
    if method == "medoid":
        return nb.run_medoid(clusters)
    return nb.run_best_spectrum(clusters, scores)


def run_tpu(method, clusters, scores):
    backend = TpuBackend()
    if method == "bin_mean":
        return backend.run_bin_mean(clusters)
    if method == "gap_average":
        return backend.run_gap_average(clusters)
    if method == "medoid":
        return backend.run_medoid(clusters)
    return backend.run_best_spectrum(clusters, scores)


METHODS = ["bin_mean", "gap_average", "medoid", "best"]


@pytest.mark.parametrize("method", METHODS)
def test_numpy_backend_matches_golden_bytes(method, clusters, scores, tmp_path):
    reps = run_numpy(method, clusters, scores)
    out = tmp_path / "out.mgf"
    write_mgf(reps, out)
    assert out.read_bytes() == open(golden(f"golden_{method}.mgf"), "rb").read()


@pytest.mark.parametrize("method", METHODS)
def test_tpu_backend_matches_golden(method, clusters, scores):
    expected = read_mgf(golden(f"golden_{method}.mgf"), use_native=False)
    reps = run_tpu(method, clusters, scores)
    assert len(reps) == len(expected)
    for got, want in zip(reps, expected):
        assert got.title.split(";")[0] == want.title.split(";")[0]
        np.testing.assert_allclose(got.mz, want.mz, rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(
            got.intensity, want.intensity, rtol=1e-4, atol=1e-2
        )
        np.testing.assert_allclose(
            got.precursor_mz, want.precursor_mz, rtol=1e-6
        )


def test_cli_reproduces_golden_bin_mean(tmp_path):
    out = tmp_path / "out.mgf"
    assert cli_main([
        "consensus", golden("golden_clustered.mgf"), str(out),
        "--backend", "numpy",
    ]) == 0
    assert out.read_bytes() == open(golden("golden_bin_mean.mgf"), "rb").read()
