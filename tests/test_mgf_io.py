"""MGF reader/writer and TSV ingest tests.

Fixture records follow the clustered-MGF interchange contract of
ref file_formats.md:3-53.
"""

import numpy as np
import pytest

from specpride_tpu.data.peaks import (
    Spectrum,
    build_title,
    group_into_clusters,
    parse_title,
    peptide_from_usi,
    scan_from_usi,
)
from specpride_tpu.io.maracluster import read_maracluster_clusters, scan_to_cluster
from specpride_tpu.io.maxquant import read_msms_peptides, read_msms_scores
from specpride_tpu.io.mgf import IndexedMGF, read_mgf, write_mgf

MGF_TEXT = """\
BEGIN IONS
TITLE=cluster-1;mzspec:PXD004732:run1.raw:scan:17555:VLHPLEGAVVIIFK/2
PEPMASS=318.185
CHARGE=2+
RTINSECONDS=1234.5
1.5 8.84
97.999 1.1
132.017 445.98
END IONS

BEGIN IONS
TITLE=cluster-1;mzspec:PXD004732:run1.raw:scan:17556
PEPMASS=318.19
CHARGE=2+
132.02 400.0
169.955 4235.4
END IONS

BEGIN IONS
TITLE=cluster-2;mzspec:PXD004732:run1.raw:scan:99
PEPMASS=500.25
CHARGE=3+
100.5 1.0
END IONS
"""


@pytest.fixture
def mgf_file(tmp_path):
    p = tmp_path / "test.mgf"
    p.write_text(MGF_TEXT)
    return p


def test_read_mgf(mgf_file):
    spectra = read_mgf(mgf_file, use_native=False)
    assert len(spectra) == 3
    s = spectra[0]
    assert s.cluster_id == "cluster-1"
    assert s.usi == "mzspec:PXD004732:run1.raw:scan:17555:VLHPLEGAVVIIFK/2"
    assert s.precursor_mz == pytest.approx(318.185)
    assert s.precursor_charge == 2
    assert s.rt == pytest.approx(1234.5)
    np.testing.assert_allclose(s.mz, [1.5, 97.999, 132.017])
    np.testing.assert_allclose(s.intensity, [8.84, 1.1, 445.98])
    assert spectra[2].precursor_charge == 3


def test_roundtrip(mgf_file, tmp_path):
    spectra = read_mgf(mgf_file, use_native=False)
    out = tmp_path / "out.mgf"
    write_mgf(spectra, out)
    again = read_mgf(out, use_native=False)
    assert len(again) == 3
    for a, b in zip(spectra, again):
        np.testing.assert_allclose(a.mz, b.mz)
        np.testing.assert_allclose(a.intensity, b.intensity)
        assert a.title == b.title
        assert a.precursor_charge == b.precursor_charge


def test_append_mode(mgf_file, tmp_path):
    spectra = read_mgf(mgf_file, use_native=False)
    out = tmp_path / "out.mgf"
    write_mgf(spectra[:1], out)
    write_mgf(spectra[1:], out, append=True)
    assert len(read_mgf(out, use_native=False)) == 3


def test_nan_peaks_skipped(tmp_path):
    s = Spectrum(
        mz=[100.0, 200.0], intensity=[1.0, np.nan], title="c", precursor_mz=1.0,
        precursor_charge=2,
    )
    out = tmp_path / "nan.mgf"
    write_mgf([s], out)
    again = read_mgf(out, use_native=False)[0]
    assert again.n_peaks == 1


def test_indexed_mgf(mgf_file):
    idx = IndexedMGF(mgf_file)
    assert len(idx) == 3
    titles = idx.titles
    assert titles[0].startswith("cluster-1;")
    s = idx[titles[1]]
    np.testing.assert_allclose(s.mz, [132.02, 169.955])
    batch = idx[titles[:2]]
    assert len(batch) == 2


def test_group_into_clusters(mgf_file):
    clusters = group_into_clusters(read_mgf(mgf_file, use_native=False))
    assert [c.cluster_id for c in clusters] == ["cluster-1", "cluster-2"]
    assert clusters[0].n_members == 2


def test_title_helpers():
    t = build_title("cluster-7", "PXD1", "run.raw", 42, "PEPTIDE", 2)
    assert t == "cluster-7;mzspec:PXD1:run.raw:scan:42:PEPTIDE/2"
    cid, usi = parse_title(t)
    assert cid == "cluster-7"
    assert scan_from_usi(usi) == 42
    assert peptide_from_usi(usi) == ("PEPTIDE", 2)
    assert parse_title("cluster-1") == ("cluster-1", "")


def test_maracluster(tmp_path):
    p = tmp_path / "clusters.tsv"
    p.write_text(
        "run1\t10\t0.9\nrun1\t11\t0.8\n\nrun1\t20\t0.7\n\nrun1\t30\t0.5\n"
    )
    clusters = read_maracluster_clusters(p)
    assert clusters == [[10, 11], [20], [30]]
    mapping = scan_to_cluster(p)
    assert mapping == {10: "cluster-1", 11: "cluster-1", 20: "cluster-2", 30: "cluster-3"}


def test_maxquant(tmp_path):
    p = tmp_path / "msms.txt"
    header = "\t".join(
        ["Raw file", "Scan number", "a", "b", "c", "d", "e", "Modified sequence", "Score"]
    )
    rows = [
        "\t".join(["run1", "10", "", "", "", "", "", "_PEPTIDE_", "95.5"]),
        "\t".join(["run1", "11", "", "", "", "", "", "_AAAK_", "10.0"]),
        "\t".join(["run1", "11", "", "", "", "", "", "_AAAK_", "20.0"]),
    ]
    p.write_text(header + "\n" + "\n".join(rows) + "\n")
    scores = read_msms_scores(p, px_accession="PXD1")
    assert scores["mzspec:PXD1:run1.raw::scan:10"] == 95.5
    assert scores["mzspec:PXD1:run1.raw::scan:11"] == 20.0
    peptides = read_msms_peptides(p)
    assert peptides == {10: "PEPTIDE", 11: "AAAK"}


def test_percolator_unrecognized_header_raises(tmp_path):
    """A well-formed TSV whose headers match no known score column must
    raise (naming what's missing), not silently return zero scores
    (advisor r3: select --method best would then score nothing)."""
    from specpride_tpu.io.maxquant import read_percolator_scores

    p = tmp_path / "native_percolator.tsv"
    p.write_text(
        "PSMId\tscore\tq-value\n"  # no 'scan' column (native percolator)
        .replace("score", "svm_score")  # ...and no known score column
        + "target_0_100_2\t1.5\t0.01\n"
    )
    with pytest.raises(ValueError, match="scan"):
        read_percolator_scores(p)
    # an empty file (headers only) is fine — zero PSMs is a valid result
    empty = tmp_path / "empty.tsv"
    empty.write_text("file\tscan\tpercolator score\n")
    assert read_percolator_scores(empty) == {}


class TestStreamedClusters:
    """Bounded-memory windowed cluster access (the reference's IndexedMGF
    streaming, ref src/average_spectrum_clustering.py:151-160)."""

    def _write(self, tmp_path, rng, n_clusters=9, scatter=False):
        from specpride_tpu.data.peaks import Spectrum, build_title

        spectra = []
        for ci in range(n_clusters):
            for m in range(2 + ci % 3):
                mz = np.sort(rng.uniform(100, 1500, 25))
                spectra.append(Spectrum(
                    mz=mz, intensity=rng.uniform(1, 100, 25),
                    precursor_mz=400.0 + ci, precursor_charge=2,
                    rt=float(m),
                    title=build_title(f"cluster-{ci}", "PXD1", "r.raw",
                                      ci * 100 + m),
                ))
        if scatter:
            # interleave members of different clusters through the file
            order = rng.permutation(len(spectra))
            spectra = [spectra[i] for i in order]
        path = tmp_path / "clustered.mgf"
        write_mgf(spectra, path)
        return path, spectra

    def test_matches_eager_grouping(self, tmp_path, rng):
        from specpride_tpu.io.mgf import StreamedClusters

        path, spectra = self._write(tmp_path, rng)
        eager = group_into_clusters(read_mgf(path))
        streamed = StreamedClusters(path, window=3)
        assert len(streamed) == len(eager)
        assert streamed.cluster_ids == [c.cluster_id for c in eager]
        assert streamed.n_spectra == len(spectra)
        for a, b in zip(streamed, eager):
            assert a.cluster_id == b.cluster_id
            assert [s.title for s in a.members] == [
                s.title for s in b.members
            ]
            for sa, sb in zip(a.members, b.members):
                np.testing.assert_allclose(sa.mz, sb.mz)
                np.testing.assert_allclose(sa.intensity, sb.intensity)

    def test_scattered_members(self, tmp_path, rng):
        """Members of one cluster scattered through the file regroup in
        in-file order, exactly as eager grouping does."""
        from specpride_tpu.io.mgf import StreamedClusters

        path, _ = self._write(tmp_path, rng, scatter=True)
        eager = group_into_clusters(read_mgf(path))
        streamed = StreamedClusters(path, window=2)
        assert streamed.cluster_ids == [c.cluster_id for c in eager]
        for a, b in zip(streamed, eager):
            assert [s.title for s in a.members] == [
                s.title for s in b.members
            ]

    def test_window_cache_stays_bounded(self, tmp_path, rng):
        """Peak memory is at most TWO windows of parsed clusters (one per
        pipelined-executor lane), never the file."""
        from specpride_tpu.io.mgf import StreamedClusters

        path, _ = self._write(tmp_path, rng, n_clusters=12)
        streamed = StreamedClusters(path, window=4)
        for c in streamed:
            assert len(streamed._windows) <= 2
            assert all(len(w) <= 4 for w in streamed._windows.values())
        # jumping back re-materialises the earlier window
        first = streamed[0]
        assert 0 in streamed._windows
        assert first.cluster_id == "cluster-0"

    def test_slicing_returns_view(self, tmp_path, rng):
        from specpride_tpu.io.mgf import StreamedClusters

        path, _ = self._write(tmp_path, rng, n_clusters=10)
        streamed = StreamedClusters(path, window=4)
        view = streamed[3:7]
        assert len(view) == 4
        assert view.cluster_ids == streamed.cluster_ids[3:7]
        assert view[0].cluster_id == "cluster-3"


def test_format_spectrum_vectorized_matches_scalar_reprs():
    """The vectorized peak formatting must be byte-identical to per-peak
    f-strings (dragon4 shortest repr on both sides), including integral
    values, subnormal-ish smalls, infinities, and NaN skipping."""
    from specpride_tpu.data.peaks import Spectrum
    from specpride_tpu.io.mgf import format_spectrum

    mz = np.array([100.0, 123.456789012345, 1999.9999999999998,
                   0.0001, 5.0, np.inf, 150.5, 1e-7])
    inten = np.array([1.0, 2.5e-12, 9999.000000001, 3.0,
                      np.nan, 7.0, 1e15, 42.0])
    s = Spectrum(mz=mz, intensity=inten, precursor_mz=500.123,
                 precursor_charge=2, rt=12.5, title="c1;u1")
    got = format_spectrum(s)
    expect_lines = []
    for a, b in zip(mz, inten):
        if np.isnan(a) or np.isnan(b):
            continue
        expect_lines.append(f"{a} {b}")
    for line in expect_lines:
        assert line in got
    # record round-trips through the parser
    from specpride_tpu.io.mgf import parse_mgf_stream
    import io as _io

    back = next(parse_mgf_stream(_io.StringIO(got)))
    # the parser drops non-finite peaks on read (inf is written but not
    # read back), so the round trip covers the finite ones
    keep = np.isfinite(mz) & np.isfinite(inten)
    np.testing.assert_array_equal(back.mz, mz[keep])
    np.testing.assert_array_equal(back.intensity, inten[keep])
