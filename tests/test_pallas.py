"""Pallas segmented-scan kernel: interpreter-mode correctness vs the XLA
formulation and a f64 reference (the on-chip A/B perf numbers live in
BENCH_METHODS.json; CI has no TPU, so only semantics are checked here)."""

import numpy as np
import pytest

from specpride_tpu.ops import pallas_kernels as pk


def reference_seg_sums(keys, vals):
    starts = np.concatenate([[True], keys[1:] != keys[:-1]])
    out = np.zeros(vals.size)
    acc = 0.0
    for i in range(vals.size):
        acc = vals[i] if starts[i] else acc + vals[i]
        out[i] = acc
    return out


@pytest.mark.parametrize("seed", [0, 1])
def test_seg_scan_pallas_interpret(seed):
    if pk.pl is None:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(seed)
    n = 2 * pk.BLK  # two blocks: exercises the cross-block carry
    # runs of widely varying length, including one spanning the block edge
    lens = []
    while sum(lens) < n:
        lens.append(int(rng.integers(1, pk.BLK // 2)))
    keys = np.repeat(np.arange(len(lens)), lens)[:n].astype(np.int32)
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    x = rng.uniform(0.0, 1e4, n).astype(np.float32)
    y = rng.uniform(0.0, 1e4, n).astype(np.float32)

    ow, ox, oy = pk.seg_scan_pallas(keys, w, x, y, interpret=True)
    for got, vals in ((ow, w), (ox, x), (oy, y)):
        np.testing.assert_allclose(
            np.asarray(got), reference_seg_sums(keys, vals.astype(np.float64)),
            rtol=1e-5,
        )


def test_seg_scan_pallas_run_spanning_many_blocks():
    """A run longer than several blocks — the XLA path needs lcap >= run
    length; the Pallas carry is exact for any length."""
    if pk.pl is None:
        pytest.skip("pallas unavailable")
    n = 4 * pk.BLK
    keys = np.zeros(n, dtype=np.int32)  # ONE run covering everything
    keys[-pk.BLK // 2 :] = 7  # plus a tail run
    w = np.ones(n, dtype=np.float32)
    ow, _, _ = pk.seg_scan_pallas(keys, w, w, w, interpret=True)
    ow = np.asarray(ow)
    assert ow[n - pk.BLK // 2 - 1] == n - pk.BLK // 2  # long run's last
    assert ow[-1] == pk.BLK // 2  # tail run restarts
