"""Pallas kernels: interpreter-mode correctness vs the XLA formulation
and f64 references (the on-chip A/B perf numbers live in the BENCH
reports; CI has no TPU, so only semantics are checked here).  Covers
both ``seg_scan_pallas`` and the fused ``seg_mean_pallas`` — including
the full bin-mean/gap-average kernels running with ``impl=
"pallas_interpret"`` against their numpy oracles."""

import numpy as np
import pytest

from specpride_tpu.ops import pallas_kernels as pk


def reference_seg_sums(keys, vals):
    starts = np.concatenate([[True], keys[1:] != keys[:-1]])
    out = np.zeros(vals.size)
    acc = 0.0
    for i in range(vals.size):
        acc = vals[i] if starts[i] else acc + vals[i]
        out[i] = acc
    return out


@pytest.mark.parametrize("seed", [0, 1])
def test_seg_scan_pallas_interpret(seed):
    if pk.pl is None:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(seed)
    n = 2 * pk.BLK  # two blocks: exercises the cross-block carry
    # runs of widely varying length, including one spanning the block edge
    lens = []
    while sum(lens) < n:
        lens.append(int(rng.integers(1, pk.BLK // 2)))
    keys = np.repeat(np.arange(len(lens)), lens)[:n].astype(np.int32)
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    x = rng.uniform(0.0, 1e4, n).astype(np.float32)
    y = rng.uniform(0.0, 1e4, n).astype(np.float32)

    ow, ox, oy = pk.seg_scan_pallas(keys, w, x, y, interpret=True)
    for got, vals in ((ow, w), (ox, x), (oy, y)):
        np.testing.assert_allclose(
            np.asarray(got), reference_seg_sums(keys, vals.astype(np.float64)),
            rtol=1e-5,
        )


def test_seg_scan_pallas_run_spanning_many_blocks():
    """A run longer than several blocks — the XLA path needs lcap >= run
    length; the Pallas carry is exact for any length."""
    if pk.pl is None:
        pytest.skip("pallas unavailable")
    n = 4 * pk.BLK
    keys = np.zeros(n, dtype=np.int32)  # ONE run covering everything
    keys[-pk.BLK // 2 :] = 7  # plus a tail run
    w = np.ones(n, dtype=np.float32)
    ow, _, _ = pk.seg_scan_pallas(keys, w, w, w, interpret=True)
    ow = np.asarray(ow)
    assert ow[n - pk.BLK // 2 - 1] == n - pk.BLK // 2  # long run's last
    assert ow[-1] == pk.BLK // 2  # tail run restarts


@pytest.mark.parametrize("seed", [0, 3])
def test_seg_mean_pallas_interpret(seed):
    """The fused kernel's count/mean outputs at run-end positions match
    a sequential f64 reference, with zero-weight (masked) elements
    contributing nothing."""
    if pk.pl is None:
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(seed)
    n = 2 * pk.BLK
    lens = []
    while sum(lens) < n:
        lens.append(int(rng.integers(1, pk.BLK // 3)))
    keys = np.repeat(np.arange(len(lens)), lens)[:n].astype(np.int32)
    w = (rng.uniform(0, 1, n) < 0.8).astype(np.float32)  # masked slots
    x = rng.uniform(0.0, 1e4, n).astype(np.float32)
    y = rng.uniform(0.5, 2.0, n).astype(np.float32)

    cnt, mx, my = pk.seg_mean_pallas(keys, w, x, y, interpret=True)
    cnt, mx, my = map(np.asarray, (cnt, mx, my))

    ends = np.flatnonzero(
        np.concatenate([keys[1:] != keys[:-1], [True]])
    )
    for e in ends:
        run = keys == keys[e]
        c = w[run].sum()
        assert cnt[e] == pytest.approx(c, rel=1e-6)
        want_x = (x[run].astype(np.float64) * w[run]).sum() / max(c, 1)
        want_y = (y[run].astype(np.float64) * w[run]).sum() / max(c, 1)
        assert mx[e] == pytest.approx(want_x, rel=1e-5)
        assert my[e] == pytest.approx(want_y, rel=1e-5)


def test_seg_mean_pallas_single_channel_and_all_masked():
    """1-value-channel variant; a fully masked run reads count 0 and
    mean 0 (the padding/sentinel contract callers rely on)."""
    if pk.pl is None:
        pytest.skip("pallas unavailable")
    n = pk.BLK
    keys = np.zeros(n, dtype=np.int32)
    keys[n // 2 :] = 1  # second run fully masked
    w = np.ones(n, dtype=np.float32)
    w[n // 2 :] = 0.0
    x = np.full(n, 3.5, dtype=np.float32)
    (cnt, mx) = pk.seg_mean_pallas(keys, w, x, interpret=True)
    cnt, mx = np.asarray(cnt), np.asarray(mx)
    assert cnt[n // 2 - 1] == n // 2
    assert mx[n // 2 - 1] == pytest.approx(3.5, rel=1e-6)
    assert cnt[-1] == 0.0 and mx[-1] == 0.0


def _flat_bin_mean_parity(impl):
    """Full flat bin-mean kernel vs the numpy oracle, per impl."""
    import jax

    from specpride_tpu.backends import numpy_backend as nb
    from specpride_tpu.backends.tpu_backend import TpuBackend
    from specpride_tpu.data.peaks import Cluster, Spectrum
    from specpride_tpu.ops import binning

    rng = np.random.default_rng(11)
    clusters = []
    for i in range(12):
        m = int(rng.integers(2, 7))
        base = np.sort(rng.uniform(120, 1800, 80))
        members = [
            Spectrum(
                mz=np.sort(base + rng.normal(0, 0.003, 80)),
                intensity=rng.uniform(1, 1e4, 80),
                precursor_mz=500.0, precursor_charge=2, rt=1.0,
                title=f"c{i};s{k}",
            )
            for k in range(m)
        ]
        clusters.append(Cluster(f"c{i}", members))
    oracle = nb.run_bin_mean(clusters)

    orig = binning.bin_mean_flat_intensity
    calls = []

    def spy(*a, **kw):
        kw["impl"] = impl
        calls.append(impl)
        return orig(*a, **kw)

    backend = TpuBackend(layout="flat")
    try:
        binning.bin_mean_flat_intensity = spy
        got = backend.run_bin_mean(clusters)
    finally:
        binning.bin_mean_flat_intensity = orig
    assert calls, "flat kernel never dispatched"
    assert len(got) == len(oracle)
    # same tolerances as the existing flat-vs-oracle parity tests
    # (test_tpu_parity): f32 device accumulation vs f64 oracle
    for o, d in zip(oracle, got):
        assert o.n_peaks == d.n_peaks
        np.testing.assert_allclose(d.mz, o.mz, rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(
            d.intensity, o.intensity, rtol=1e-4, atol=1e-3
        )


def test_flat_bin_mean_pallas_oracle_parity():
    """The routing table's Pallas alternative for the flat bin-mean
    intensity kernel reproduces the numpy oracle (interpret mode — the
    same kernel body Mosaic lowers on TPU)."""
    if pk.pl is None:
        pytest.skip("pallas unavailable")
    _flat_bin_mean_parity("pallas_interpret")


def test_gap_average_pallas_oracle_parity():
    """The bucketized gap-average kernel with the fused Pallas core
    reproduces the numpy oracle on realistic clusters."""
    if pk.pl is None:
        pytest.skip("pallas unavailable")
    from specpride_tpu.backends import numpy_backend as nb
    from specpride_tpu.backends.tpu_backend import TpuBackend
    from specpride_tpu.data.peaks import Cluster, Spectrum
    from specpride_tpu.ops import gap_average as ga

    rng = np.random.default_rng(7)
    clusters = []
    for i in range(8):
        m = int(rng.integers(1, 6))  # incl. a singleton passthrough
        base = np.sort(rng.uniform(150, 1600, 60))
        members = [
            Spectrum(
                mz=np.sort(base + rng.normal(0, 0.002, 60)),
                intensity=rng.uniform(1, 1e4, 60),
                precursor_mz=450.0, precursor_charge=2, rt=1.0,
                title=f"g{i};s{k}",
            )
            for k in range(m)
        ]
        clusters.append(Cluster(f"g{i}", members))
    oracle = nb.run_gap_average(clusters)

    orig = ga.gap_average_compact
    calls = []

    def spy(*a, **kw):
        kw["impl"] = "pallas_interpret"
        calls.append(1)
        return orig(*a, **kw)

    backend = TpuBackend(layout="bucketized", force_device=True)
    try:
        ga.gap_average_compact = spy
        got = backend.run_gap_average(clusters)
    finally:
        ga.gap_average_compact = orig
    assert calls, "gap kernel never dispatched"
    for o, d in zip(oracle, got):
        assert o.n_peaks == d.n_peaks
        np.testing.assert_allclose(d.mz, o.mz, rtol=1e-5)
        np.testing.assert_allclose(d.intensity, o.intensity, rtol=1e-4)
