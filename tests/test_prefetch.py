"""Pipelined chunk executor (--prefetch): output parity with the serial
path, crash/resume and --on-error skip behavior under prefetch, pack-phase
attribution, pipeline telemetry, the bucket-plan cache, and the medoid
index-only device transfer."""

import json
import os

import numpy as np
import pytest

from specpride_tpu.cli import main as cli_main
from specpride_tpu.io.mgf import read_mgf, write_mgf

from conftest import make_cluster


def _workload(rng, n=9, **kw):
    return [
        make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=25, **kw)
        for i in range(n)
    ]


def _write(tmp_path, clusters):
    path = tmp_path / "clustered.mgf"
    write_mgf([s for c in clusters for s in c.members], path)
    return path


class TestPrefetchParity:
    @pytest.mark.parametrize("method,command", [
        ("bin-mean", "consensus"),
        ("gap-average", "consensus"),
        ("medoid", "select"),
    ])
    def test_byte_identical_output_and_checkpoint(
        self, tmp_path, rng, method, command
    ):
        """--prefetch 0/1/4 must produce byte-identical MGF output AND
        identical checkpoint manifests for every method (the executor
        changes scheduling, never results)."""
        clustered = _write(tmp_path, _workload(rng))
        outputs, manifests = {}, {}
        for p in (0, 1, 4):
            out = tmp_path / f"out_p{p}.mgf"
            ckpt = tmp_path / f"ckpt_p{p}.json"
            assert cli_main([
                command, str(clustered), str(out), "--method", method,
                "--prefetch", str(p),
                "--checkpoint", str(ckpt), "--checkpoint-every", "2",
            ]) == 0
            outputs[p] = out.read_bytes()
            manifests[p] = json.loads(ckpt.read_text())
        assert outputs[0] == outputs[1] == outputs[4]
        assert manifests[0] == manifests[1] == manifests[4]

    def test_qc_report_identical(self, tmp_path, rng):
        """The fused bin-mean + QC path rides prepare_chunk/run_prepared;
        the report must match the serial run exactly."""
        clustered = _write(tmp_path, _workload(rng))
        reports = {}
        for p in (0, 4):
            out = tmp_path / f"o{p}.mgf"
            qc = tmp_path / f"qc{p}.json"
            assert cli_main([
                "consensus", str(clustered), str(out), "--prefetch", str(p),
                "--checkpoint", str(tmp_path / f"c{p}.json"),
                "--checkpoint-every", "3", "--qc-report", str(qc),
            ]) == 0
            reports[p] = qc.read_bytes()
        assert reports[0] == reports[4]

    def test_kill_resume_under_prefetch(self, tmp_path, rng):
        """A mid-run kill (simulated as a committed partial manifest plus
        an orphaned appended chunk) resumed WITH prefetch must converge to
        the serial golden bytes — the crash-safety contract is scheduling-
        independent."""
        clusters = _workload(rng, n=8)
        clustered = _write(tmp_path, clusters)

        golden = tmp_path / "golden.mgf"
        assert cli_main([
            "consensus", str(clustered), str(golden), "--prefetch", "0",
            "--checkpoint", str(tmp_path / "g.json"),
            "--checkpoint-every", "2",
        ]) == 0
        golden_bytes = golden.read_bytes()

        # crashed state: chunk 1 committed (same backend as the golden run,
        # via the CLI on a 2-cluster input — per-cluster output makes its
        # bytes the golden prefix), then an orphaned partial append that
        # the manifest never recorded (the classic torn window)
        head_src = tmp_path / "head.mgf"
        write_mgf([s for c in clusters[:2] for s in c.members], head_src)
        out = tmp_path / "out.mgf"
        assert cli_main([
            "consensus", str(head_src), str(out), "--prefetch", "0",
        ]) == 0
        committed = out.stat().st_size
        assert golden_bytes.startswith(out.read_bytes())
        with open(out, "ab") as fh:
            fh.write(b"BEGIN IONS\nTITLE=torn-orphan\n")
        ckpt = tmp_path / "ckpt.json"
        ckpt.write_text(json.dumps({
            "done": ["cluster-0", "cluster-1"], "output_bytes": committed,
        }))
        assert cli_main([
            "consensus", str(clustered), str(out), "--prefetch", "4",
            "--checkpoint", str(ckpt), "--checkpoint-every", "2",
        ]) == 0
        assert out.read_bytes() == golden_bytes

    def test_on_error_skip_under_prefetch(self, tmp_path, rng):
        """--on-error skip with a poisoned cluster: the pipelined run must
        isolate exactly the bad cluster (serial per-cluster retry of the
        failing chunk) and keep every good one — same output and failure
        record as the serial run.  The failure surfaces on the PACKER
        thread (check_uniform_charge runs in prepare_chunk) and must
        still route through the consumer's skip path."""
        good = _workload(rng, n=5)
        bad = make_cluster(rng, "cluster-bad", n_members=2, n_peaks=15)
        bad.members[1].precursor_charge = bad.members[0].precursor_charge + 1
        clusters = good[:2] + [bad] + good[2:]
        clustered = tmp_path / "clustered.mgf"
        write_mgf([s for c in clusters for s in c.members], clustered)
        outs = {}
        for p in (0, 2):
            out = tmp_path / f"out_p{p}.mgf"
            ckpt = tmp_path / f"ck_p{p}.json"
            assert cli_main([
                "consensus", str(clustered), str(out), "--prefetch", str(p),
                "--on-error", "skip", "--checkpoint", str(ckpt),
                "--checkpoint-every", "2",
            ]) == 0
            outs[p] = out.read_bytes()
            assert json.loads(ckpt.read_text())["failed"] == ["cluster-bad"]
        assert outs[0] == outs[2]
        assert sorted(s.title for s in read_mgf(tmp_path / "out_p2.mgf")) \
            == sorted(c.cluster_id for c in good)

    def test_pack_materialization_failure_rebuilds_part(self, tmp_path, rng):
        """A packer-thread failure DURING chunk materialization delivers
        item.part = None; under --on-error skip the consumer must rebuild
        the chunk itself and run the per-cluster serial retry (the only
        path where the executor re-touches the input)."""
        from specpride_tpu import cli as cli_mod
        from specpride_tpu.backends import numpy_backend as nb
        from specpride_tpu.observability import RunStats

        clusters = _workload(rng, n=6)

        class FlakyList(list):
            """Fails the FIRST materialization of cluster 3 (that access
            happens on the packer thread); the consumer's rebuild and the
            retry then succeed."""

            tripped = False

            def __getitem__(self, i):
                if i == 3 and not self.tripped:
                    FlakyList.tripped = True
                    raise RuntimeError("flaky materialization")
                return super().__getitem__(i)

        out = tmp_path / "out.mgf"
        args = cli_mod.build_parser().parse_args([
            "consensus", "in.mgf", str(out),
            "--backend", "numpy", "--prefetch", "2",
            "--on-error", "skip",
            "--checkpoint", str(tmp_path / "ck.json"),
            "--checkpoint-every", "2",
        ])
        _, failed, qc_failed = cli_mod._checkpointed_run(
            nb, "bin-mean", FlakyList(clusters), args, RunStats()
        )
        assert failed == [] and qc_failed == []
        assert [s.title for s in read_mgf(out)] == [
            c.cluster_id for c in clusters
        ]

    def test_flat_layout_medoid_keeps_device_path(self, tmp_path, rng):
        """--layout flat forces the device medoid kernel; the pipelined
        executor must NOT silently reroute it to the host-native path
        (prepare_chunk returns None there), so prefetch 0 and 2 agree."""
        clustered = _write(tmp_path, _workload(rng))
        outs = {}
        for p in (0, 2):
            out = tmp_path / f"flat_p{p}.mgf"
            assert cli_main([
                "select", str(clustered), str(out), "--method", "medoid",
                "--layout", "flat", "--prefetch", str(p),
                "--checkpoint", str(tmp_path / f"fc{p}.json"),
                "--checkpoint-every", "3",
            ]) == 0
            outs[p] = out.read_bytes()
        assert outs[0] == outs[2]

    def test_abort_propagates_and_shuts_down(self, tmp_path, rng):
        """Default --on-error abort under prefetch: the pack-stage error
        propagates to the caller (and the packer thread is reaped, not
        left deadlocked on its queue)."""
        bad = make_cluster(rng, "cluster-bad", n_members=2, n_peaks=15)
        bad.members[1].precursor_charge = bad.members[0].precursor_charge + 1
        clusters = _workload(rng, n=4) + [bad]
        clustered = _write(tmp_path, clusters)
        with pytest.raises(ValueError):
            cli_main([
                "consensus", str(clustered), str(tmp_path / "x.mgf"),
                "--prefetch", "2", "--checkpoint", str(tmp_path / "c.json"),
                "--checkpoint-every", "1",
            ])
        import threading

        assert not [
            t for t in threading.enumerate()
            if t.name.startswith(("specpride-packer", "specpride-committer"))
            and t.is_alive()
        ]


class TestPipelineTelemetry:
    def test_journal_pipeline_summary_and_spans(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng))
        journal = tmp_path / "run.jsonl"
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "o.mgf"),
            "--prefetch", "2", "--checkpoint", str(tmp_path / "c.json"),
            "--checkpoint-every", "2", "--journal", str(journal),
        ]) == 0
        events = [json.loads(l) for l in journal.read_text().splitlines()]
        end = [e for e in events if e["event"] == "run_end"][-1]
        pipe = end.get("pipeline")
        assert pipe and pipe["prefetch"] == 2
        assert pipe["device_idle_s"] >= 0.0
        assert pipe["overlap_efficiency"] is None or (
            pipe["overlap_efficiency"] <= 1.0
        )
        span_names = {
            e["name"] for e in events if e["event"] == "span"
        }
        assert any(n.startswith("pipeline") for n in span_names)
        # satellite: packer time journaled as `pack`, not swallowed into
        # compute — and throughput still divides by compute+write only
        phases = end["phases_s"]
        assert phases.get("pack", 0.0) > 0.0
        want = end["counters"]["clusters"] / (
            phases.get("compute", 0.0) + phases.get("write", 0.0)
        )
        assert end["clusters_per_sec"] == pytest.approx(want, rel=0.05)

    def test_serial_run_has_no_pipeline_field(self, tmp_path, rng):
        clustered = _write(tmp_path, _workload(rng, n=4))
        journal = tmp_path / "run.jsonl"
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "o.mgf"),
            "--prefetch", "0", "--journal", str(journal),
        ]) == 0
        events = [json.loads(l) for l in journal.read_text().splitlines()]
        end = [e for e in events if e["event"] == "run_end"][-1]
        assert "pipeline" not in end

    def test_stats_cli_surfaces_device_idle(self, tmp_path, rng, capsys):
        clustered = _write(tmp_path, _workload(rng))
        journal = tmp_path / "run.jsonl"
        agg = tmp_path / "agg.json"
        assert cli_main([
            "consensus", str(clustered), str(tmp_path / "o.mgf"),
            "--prefetch", "2", "--checkpoint", str(tmp_path / "c.json"),
            "--checkpoint-every", "2", "--journal", str(journal),
        ]) == 0
        assert cli_main([
            "stats", str(journal), "--json", str(agg),
        ]) == 0
        run = json.loads(agg.read_text())["runs"][0]
        assert "device_idle_s" in run and "overlap_efficiency" in run
        assert "device_idle_s" in capsys.readouterr().out


class TestPlanCache:
    def test_repeated_pack_hits_cache(self, rng):
        from specpride_tpu.data import packed

        clusters = _workload(rng, n=6)
        packed.clear_plan_cache()
        a = packed.pack_bucketize(clusters)
        misses = packed.plan_cache_info()["misses"]
        b = packed.pack_bucketize(clusters)
        info = packed.plan_cache_info()
        assert info["misses"] == misses  # second pack re-planned nothing
        assert info["hits"] >= 1
        assert [x.cluster_ids for x in a] == [x.cluster_ids for x in b]
        np.testing.assert_array_equal(a[0].mz, b[0].mz)

    def test_different_inputs_miss(self, rng):
        from specpride_tpu.data import packed

        packed.clear_plan_cache()
        packed.pack_bucketize(_workload(rng, n=3))
        before = packed.plan_cache_info()["misses"]
        packed.pack_bucketize(_workload(rng, n=4))
        assert packed.plan_cache_info()["misses"] > before


class TestMedoidDeviceSelect:
    def test_index_only_matches_host_finalize(self, rng):
        """Device-side medoid selection (index-only D2H) must pick the
        same winners as the count-matrix fetch + host f64 finalize."""
        from specpride_tpu.backends.tpu_backend import TpuBackend
        from specpride_tpu.backends import numpy_backend as nb

        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=m, n_peaks=30)
            for i, m in enumerate([1, 2, 5, 3, 8, 2])
        ]
        dev = TpuBackend(layout="bucketized", medoid_device_select=True)
        host = TpuBackend(layout="bucketized", medoid_device_select=False)
        oracle = [nb.medoid_index(c.members) for c in clusters]
        assert dev.medoid_indices(clusters) == oracle
        assert host.medoid_indices(clusters) == oracle

    def test_d2h_bytes_drop(self, rng):
        """The whole point: the index transfer must be >= 10x smaller than
        the count-matrix transfer for the same workload."""
        from specpride_tpu.backends.tpu_backend import TpuBackend

        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=6, n_peaks=30)
            for i in range(8)
        ]

        def d2h_bytes(select: bool) -> int:
            backend = TpuBackend(
                layout="bucketized", medoid_device_select=select
            )
            backend.medoid_indices(clusters)
            counter = backend.metrics.counter(
                "specpride_bytes_d2h_total",
                "bytes fetched device->host",
            )
            return int(counter.value())

        assert d2h_bytes(False) >= 10 * d2h_bytes(True)
