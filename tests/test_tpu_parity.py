"""NumPy-oracle vs JAX-device parity tests (survey §4b).

The numpy backend is the behavioural oracle (direct reimplementation of the
reference algorithms); the TPU backend must reproduce it within float32
tolerance on randomized clusters.  Runs on the virtual 8-device CPU mesh set
up in conftest.py — the same jitted programs run unchanged on real TPU.
"""

import numpy as np
import pytest

from specpride_tpu.backends import numpy_backend as nb
from specpride_tpu.backends.tpu_backend import TpuBackend
from specpride_tpu.config import (
    BatchConfig,
    BinMeanConfig,
    CosineConfig,
    GapAverageConfig,
    MedoidConfig,
)
from specpride_tpu.data.peaks import Cluster, Spectrum

from conftest import make_cluster


def make_gap_safe_cluster(
    rng, cluster_id="cluster-1", n_members=4, n_skeleton=40, charge=2
):
    """Cluster with realistic group structure: skeleton spacing >= 0.05,
    member jitter <= 0.003.  (Historically these fixtures had to keep gaps
    away from the 0.01 Da threshold because the device kernel decided gaps
    in f32; grouping is now host-side float64 on both paths — see
    ``TestGapAverageParity.test_near_threshold_gaps`` for the adversarial
    case — so the margin is no longer load-bearing, just a realistic
    shape.)"""
    base = np.sort(rng.uniform(150.0, 1500.0, size=n_skeleton))
    keep = np.concatenate([[True], np.diff(base) >= 0.05])
    base = base[keep]
    members = []
    for m in range(n_members):
        mz = np.sort(base + rng.uniform(-0.003, 0.003, size=base.size))
        members.append(
            Spectrum(
                mz=mz,
                intensity=rng.uniform(10.0, 1e4, size=base.size),
                precursor_mz=500.0 + rng.normal(0, 0.01),
                precursor_charge=charge,
                rt=100.0 + m,
                title=f"{cluster_id};mzspec:PXD1:r:scan:{m}",
            )
        )
    return Cluster(cluster_id, members)


@pytest.fixture
def backend():
    return TpuBackend()


def random_clusters(rng, n=12):
    clusters = []
    for i in range(n):
        clusters.append(
            make_cluster(
                rng,
                cluster_id=f"cluster-{i}",
                n_members=int(rng.integers(1, 9)),
                n_peaks=int(rng.integers(5, 120)),
                jitter=float(rng.uniform(0.001, 0.02)),
                base_scan=1000 * i,
            )
        )
    return clusters


def assert_spectra_close(a: Spectrum, b: Spectrum, rtol=1e-5, atol=1e-4):
    assert a.n_peaks == b.n_peaks, f"{a.title}: {a.n_peaks} vs {b.n_peaks} peaks"
    np.testing.assert_allclose(a.mz, b.mz, rtol=rtol, atol=atol)
    np.testing.assert_allclose(a.intensity, b.intensity, rtol=rtol, atol=atol)
    np.testing.assert_allclose(
        a.precursor_mz, b.precursor_mz, rtol=1e-6, atol=1e-4
    )
    assert a.precursor_charge == b.precursor_charge


# ---------------------------------------------------------------------------
# K1: binned-mean consensus
# ---------------------------------------------------------------------------

class TestBinMeanParity:
    def test_random_clusters(self, rng, backend):
        clusters = random_clusters(rng)
        oracle = nb.run_bin_mean(clusters)
        device = backend.run_bin_mean(clusters)
        assert len(oracle) == len(device)
        for o, d in zip(oracle, device):
            assert_spectra_close(o, d)

    def test_duplicate_bin_last_occurrence(self, backend):
        """Several peaks of one member in the same 0.02 Da bin: only the last
        contributes (numpy buffered += semantics, ref src/binning.py:197-199)."""
        s1 = Spectrum(
            mz=[200.001, 200.002, 200.003, 500.0],
            intensity=[10.0, 20.0, 30.0, 40.0],
            precursor_mz=400.0,
            precursor_charge=2,
            title="c1;u1",
        )
        s2 = Spectrum(
            mz=[200.004, 500.001],
            intensity=[100.0, 50.0],
            precursor_mz=400.0,
            precursor_charge=2,
            title="c1;u2",
        )
        clusters = [Cluster("c1", [s1, s2])]
        oracle = nb.run_bin_mean(clusters)
        device = backend.run_bin_mean(clusters)
        assert_spectra_close(oracle[0], device[0])
        # bin at 200: member 1 contributes its LAST peak (30), member 2 its
        # only peak (100) → mean 65
        assert pytest.approx(65.0, rel=1e-5) == device[0].intensity[0]

    def test_quorum(self, rng, backend):
        cfg = BinMeanConfig(quorum_fraction=0.5)
        clusters = random_clusters(rng, n=6)
        oracle = nb.run_bin_mean(clusters, cfg)
        device = backend.run_bin_mean(clusters, cfg)
        for o, d in zip(oracle, device):
            assert_spectra_close(o, d)

    def test_no_quorum(self, rng, backend):
        cfg = BinMeanConfig(apply_peak_quorum=False)
        clusters = random_clusters(rng, n=6)
        oracle = nb.run_bin_mean(clusters, cfg)
        device = backend.run_bin_mean(clusters, cfg)
        for o, d in zip(oracle, device):
            assert_spectra_close(o, d)

    def test_mixed_charge_raises(self, rng, backend):
        c = make_cluster(rng, n_members=3)
        c.members[1].precursor_charge = 3
        with pytest.raises(ValueError, match="charges"):
            backend.run_bin_mean([c])

    def test_out_of_range_peaks_dropped(self, backend):
        s = Spectrum(
            mz=[50.0, 150.0, 2500.0],
            intensity=[1.0, 2.0, 3.0],
            precursor_mz=300.0,
            precursor_charge=2,
            title="c1;u1",
        )
        out = backend.run_bin_mean([Cluster("c1", [s, s])])
        assert out[0].n_peaks == 1
        assert 149.9 < out[0].mz[0] < 150.1


# ---------------------------------------------------------------------------
# K3: gap-average consensus
# ---------------------------------------------------------------------------

class TestGapAverageParity:
    @pytest.mark.parametrize("tail_mode", ["reference", "split"])
    def test_random_clusters(self, rng, backend, tail_mode):
        cfg = GapAverageConfig(tail_mode=tail_mode)
        clusters = [
            make_gap_safe_cluster(
                rng,
                f"cluster-{i}",
                n_members=int(rng.integers(1, 7)),
                n_skeleton=int(rng.integers(5, 80)),
            )
            for i in range(10)
        ]
        oracle = nb.run_gap_average(clusters, cfg)
        device = backend.run_gap_average(clusters, cfg)
        assert len(oracle) == len(device)
        for o, d in zip(oracle, device):
            assert o.n_peaks == d.n_peaks
            np.testing.assert_allclose(o.mz, d.mz, rtol=1e-5, atol=1e-3)
            np.testing.assert_allclose(
                o.intensity, d.intensity, rtol=1e-5, atol=1e-2
            )
            np.testing.assert_allclose(o.precursor_mz, d.precursor_mz)
            assert o.precursor_charge == d.precursor_charge
            np.testing.assert_allclose(o.rt, d.rt)

    def test_singleton_passthrough(self, rng, backend):
        c = make_gap_safe_cluster(rng, n_members=1)
        device = backend.run_gap_average([c])
        # singleton: peaks pass through untouched (ref :88-90) modulo
        # dyn-range floor; our test intensities are within dyn range
        np.testing.assert_allclose(
            device[0].mz, c.members[0].mz, rtol=1e-6, atol=1e-3
        )
        np.testing.assert_allclose(
            device[0].intensity, c.members[0].intensity, rtol=1e-6, atol=1e-2
        )

    def test_dyn_range_filter(self, backend):
        cfg = GapAverageConfig(dyn_range=10.0, min_fraction=0.4, tail_mode="split")
        s1 = Spectrum(
            mz=[100.0, 300.0, 600.0],
            intensity=[1.0, 500.0, 1000.0],
            precursor_mz=400.0,
            precursor_charge=2,
            title="c1;u1",
        )
        s2 = Spectrum(
            mz=[100.001, 300.001, 600.001],
            intensity=[1.0, 500.0, 1000.0],
            precursor_mz=400.0,
            precursor_charge=2,
            title="c1;u2",
        )
        clusters = [Cluster("c1", [s1, s2])]
        oracle = nb.run_gap_average(clusters, cfg)
        device = backend.run_gap_average(clusters, cfg)
        assert oracle[0].n_peaks == device[0].n_peaks == 2  # 1.0 < max/10
        np.testing.assert_allclose(
            oracle[0].intensity, device[0].intensity, rtol=1e-6
        )

    def test_near_threshold_gaps(self, backend):
        """Adversarial f64-parity case (VERDICT r1 weak #1): identical
        members with inter-peak gaps of 0.01 +/- 5e-5 Da at m/z ~1700-1900,
        where the f32 ulp (~1.2e-4) exceeds the whole band.  Deciding gaps
        in device f32 regrouped ~35/100 such clusters; the host-side f64
        segment precompute must match the oracle exactly (same peak counts,
        not just close values)."""
        rng = np.random.default_rng(7)
        cfg = GapAverageConfig()
        clusters = []
        for i in range(20):
            n = 60
            gaps = 0.01 + rng.uniform(-5e-5, 5e-5, size=n - 1)
            mz = 1700.0 + np.concatenate([[0.0], np.cumsum(gaps)])
            members = [
                Spectrum(
                    mz=mz.copy(),
                    intensity=rng.uniform(10.0, 1e4, size=n),
                    precursor_mz=900.0,
                    precursor_charge=2,
                    rt=float(k),
                    title=f"cluster-{i};mzspec:PXD1:r:scan:{i * 10 + k}",
                )
                for k in range(4)
            ]
            clusters.append(Cluster(f"cluster-{i}", members))
        oracle = nb.run_gap_average(clusters, cfg)
        device = backend.run_gap_average(clusters, cfg)
        for o, d in zip(oracle, device):
            assert o.n_peaks == d.n_peaks
            np.testing.assert_allclose(o.mz, d.mz, rtol=1e-5, atol=1e-3)
            np.testing.assert_allclose(
                o.intensity, d.intensity, rtol=1e-4, atol=1e-2
            )

    def test_many_groups_exact_output_bound(self, rng, backend):
        """A singleton cluster with thousands of peaks (every peak its own
        group) must come back complete — the host's exact group-count bound
        sizes the compacted output buffer (no truncation, no overflow
        path)."""
        n = 3000  # > max(512, bucket/4) for the 8192 total-peak bucket
        mz = np.sort(rng.uniform(100.0, 1900.0, size=n))
        keep = np.concatenate([[True], np.diff(mz) >= 0.02])
        mz = mz[keep]
        s = Spectrum(
            mz=mz,
            intensity=rng.uniform(10.0, 1e4, size=mz.size),
            precursor_mz=500.0,
            precursor_charge=2,
            title="c1;u1",
        )
        oracle = nb.run_gap_average([Cluster("c1", [s])])
        device = backend.run_gap_average([Cluster("c1", [s])])
        assert oracle[0].n_peaks == device[0].n_peaks == mz.size
        np.testing.assert_allclose(oracle[0].mz, device[0].mz, rtol=1e-6, atol=1e-3)

    @pytest.mark.parametrize(
        "pepmass", ["naive_average", "neutral_average", "lower_median"]
    )
    def test_pepmass_modes(self, rng, backend, pepmass):
        cfg = GapAverageConfig(pepmass=pepmass)
        clusters = [make_gap_safe_cluster(rng, n_members=5)]
        oracle = nb.run_gap_average(clusters, cfg)
        device = backend.run_gap_average(clusters, cfg)
        np.testing.assert_allclose(
            oracle[0].precursor_mz, device[0].precursor_mz
        )
        np.testing.assert_allclose(oracle[0].rt, device[0].rt)

    @pytest.mark.parametrize("tail_mode", ["reference", "split"])
    def test_numpy_fallback_host_path(self, rng, tail_mode, monkeypatch):
        """The vectorized numpy branch of _run_gap_average_host (used when
        the native lib is absent) must match the oracle too — CI builds
        the lib, so force the fallback explicitly."""
        from specpride_tpu.ops import gap_native

        monkeypatch.setattr(gap_native, "available", lambda: False)
        cfg = GapAverageConfig(tail_mode=tail_mode)
        clusters = [
            make_gap_safe_cluster(
                rng, f"c{i}", n_members=int(rng.integers(1, 6)),
                n_skeleton=int(rng.integers(4, 60)),
            )
            for i in range(8)
        ]
        clusters.append(Cluster("c-empty", [
            Spectrum(mz=[], intensity=[], precursor_mz=500.0,
                     precursor_charge=2, title="c-empty;u0"),
            Spectrum(mz=[200.0, 200.02], intensity=[5.0, 7.0],
                     precursor_mz=500.0, precursor_charge=2,
                     title="c-empty;u1"),
        ]))
        oracle = nb.run_gap_average(clusters, cfg)
        device = TpuBackend().run_gap_average(clusters, cfg)
        for o, d in zip(oracle, device):
            np.testing.assert_allclose(o.mz, d.mz, rtol=1e-12)
            np.testing.assert_allclose(o.intensity, d.intensity, rtol=1e-12)

    @pytest.mark.parametrize("tail_mode", ["reference", "split"])
    def test_native_host_path_is_bit_exact(self, rng, tail_mode):
        """The C++ multithreaded host path (ops.gap_native) must be
        BIT-identical to the oracle — same stable sort, same f64
        accumulation order — including near-threshold gaps, m/z ties
        (stability), peakless members, and the tail-mode merge."""
        from specpride_tpu.ops import gap_native

        if not gap_native.available():
            pytest.skip("native gap-average not built")
        cfg = GapAverageConfig(tail_mode=tail_mode)
        clusters = []
        for i in range(12):
            n = int(rng.integers(2, 120))
            gaps = 0.01 + rng.uniform(-5e-5, 5e-5, size=n - 1)
            base = 1500.0 + np.concatenate([[0.0], np.cumsum(gaps)])
            members = []
            for k in range(int(rng.integers(1, 6))):
                mz = base.copy()  # exact ties across members
                members.append(Spectrum(
                    mz=mz, intensity=rng.uniform(1.0, 1e4, n),
                    precursor_mz=700.0, precursor_charge=2, rt=float(k),
                    title=f"c{i};mzspec:PXD1:r:scan:{i * 10 + k}",
                ))
            clusters.append(Cluster(f"c{i}", members))
        # a cluster with a zero-peak member
        clusters.append(Cluster("c-empty", [
            Spectrum(mz=[], intensity=[], precursor_mz=500.0,
                     precursor_charge=2, title="c-empty;u0"),
            Spectrum(mz=[200.0, 200.02], intensity=[5.0, 7.0],
                     precursor_mz=500.0, precursor_charge=2,
                     title="c-empty;u1"),
        ]))
        oracle = nb.run_gap_average(clusters, cfg)
        device = TpuBackend().run_gap_average(clusters, cfg)
        for o, d in zip(oracle, device):
            np.testing.assert_array_equal(o.mz, d.mz)
            np.testing.assert_array_equal(o.intensity, d.intensity)


# ---------------------------------------------------------------------------
# K2: medoid representative
# ---------------------------------------------------------------------------

class TestMedoidParity:
    @pytest.mark.parametrize("layout", ["auto", "bucketized"])
    def test_random_clusters(self, rng, layout):
        """"auto" takes the native C++ counter when built; "bucketized"
        forces the device gram-matmul path — both must match the oracle
        index for index."""
        backend = TpuBackend(layout=layout)
        clusters = random_clusters(rng)
        oracle_idx = [nb.medoid_index(c.members) for c in clusters]
        device_idx = backend.medoid_indices(clusters)
        assert oracle_idx == device_idx

    def test_native_counts_match_device_semantics(self, rng):
        """The native counter's integer pair counts drive the SAME
        medoid_finalize as the device path: spot-check the counts against
        the oracle's xcorr numerators."""
        from specpride_tpu.ops import medoid_native

        if not medoid_native.available():
            pytest.skip("native medoid not built")
        clusters = random_clusters(rng, n=4)
        backend = TpuBackend()
        idx = backend._medoid_indices_native(clusters, MedoidConfig())
        assert idx == [nb.medoid_index(c.members) for c in clusters]

    def test_bin_boundary_mzs(self, rng):
        """One-decimal m/z values sit exactly on the default 0.1 Da grid
        edges — trunc(mz / bin_size) must match numpy's division bit for
        bit (advisor r5: a reciprocal-multiply formulation binned ~32% of
        such values differently, e.g. 100.1*10.0000..x -> 1000 instead of
        1001)."""
        members = []
        for k, base in enumerate(([100.1, 250.7, 999.9],
                                  [100.1, 250.7, 999.89],
                                  [100.14, 250.72, 999.9])):
            members.append(Spectrum(
                mz=np.array(base), intensity=np.array([5.0, 7.0, 9.0]),
                precursor_mz=500.0, precursor_charge=2,
                title=f"c1;mzspec:PXD1:r:scan:{k}",
            ))
        clusters = [Cluster("c1", members)]
        oracle = [nb.medoid_index(c.members) for c in clusters]
        for layout in ("auto", "bucketized"):
            assert TpuBackend(layout=layout).medoid_indices(
                clusters
            ) == oracle

    def test_mixed_member_counts_group_finalize(self, rng):
        """Clusters of very different sizes finalize in equal-M groups
        (no global quadratic padding): outputs stay in input order."""
        clusters = [
            make_cluster(rng, f"cluster-{i}", n_members=m, n_peaks=20)
            for i, m in enumerate([1, 7, 2, 7, 15, 1, 3])
        ]
        oracle = [nb.medoid_index(c.members) for c in clusters]
        assert TpuBackend().medoid_indices(clusters) == oracle

    def test_identical_members_lowest_index(self, rng, backend):
        s = make_cluster(rng, n_members=1).members[0]
        members = [
            Spectrum(
                mz=s.mz,
                intensity=s.intensity,
                precursor_mz=s.precursor_mz,
                precursor_charge=s.precursor_charge,
                title=f"c1;scan{i}",
            )
            for i in range(4)
        ]
        assert backend.medoid_indices([Cluster("c1", members)]) == [0]

    def test_singleton(self, rng, backend):
        c = make_cluster(rng, n_members=1)
        assert backend.medoid_indices([c]) == [0]

    def test_run_medoid_returns_member(self, rng, backend):
        clusters = random_clusters(rng, n=5)
        reps = backend.run_medoid(clusters)
        for rep, c in zip(reps, clusters):
            assert any(rep is m for m in c.members)


# ---------------------------------------------------------------------------
# K2b: binned cosine metric
# ---------------------------------------------------------------------------

class TestCosineParity:
    def test_rep_vs_members(self, rng, backend):
        clusters = random_clusters(rng, n=8)
        reps = nb.run_bin_mean(clusters)
        oracle = np.array(
            [nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)]
        )
        device = backend.average_cosines(reps, clusters)
        np.testing.assert_allclose(oracle, device, rtol=5e-5, atol=1e-5)

    def test_unsorted_spectrum_uses_last_peak_grid(self, backend):
        """The reference grid stops at the pair's LAST peak m/z, not the max
        (ref src/benchmark.py:20 assumes sorted spectra) — parity must hold
        even for unsorted inputs."""
        rep = Spectrum(
            mz=[200.0, 300.0], intensity=[10.0, 20.0],
            precursor_mz=400.0, precursor_charge=2, title="c1",
        )
        member = Spectrum(
            mz=[200.0, 900.0, 950.0, 300.0],  # unsorted: last peak 300 < max
            intensity=[10.0, 300.0, 1.0, 20.0],
            precursor_mz=400.0, precursor_charge=2, title="c1;u1",
        )
        oracle = nb.average_cosine(rep, [member])
        device = backend.average_cosines([rep], [Cluster("c1", [member])])
        np.testing.assert_allclose(device, [oracle], rtol=1e-5)

    def test_self_similarity_is_one(self, rng, backend):
        """average_cos_dist(s, [s]) == 1 (ref src/benchmark.py:80)."""
        c = make_cluster(rng, n_members=1)
        s = c.members[0]
        device = backend.average_cosines([s], [Cluster("c1", [s])])
        np.testing.assert_allclose(device, [1.0], rtol=1e-5)

    @pytest.mark.parametrize("layout", ["auto", "flat", "bucketized"])
    @pytest.mark.parametrize("ratio", [1e2, 1e3, 1e6])
    def test_mixed_intensity_scales(self, rng, layout, ratio):
        """Members (and clusters) whose intensity scales differ by orders
        of magnitude share device blocks; per-spectrum sums must not lose
        the small spectrum's bits to a large block-mate (the advisor's r4
        block-prefix cancellation repro: cosines off by up to 0.7)."""
        base = np.sort(rng.uniform(150.0, 1500.0, 50))
        clusters = []
        for i in range(6):
            members = []
            for m in range(4):
                scale = ratio if (m % 2 == 0) else 1.0
                members.append(Spectrum(
                    mz=np.sort(base + rng.normal(0, 0.001, base.size)),
                    intensity=rng.uniform(0.5, 1.0, base.size) * scale,
                    precursor_mz=500.0, precursor_charge=2, rt=float(m),
                    title=f"c{i};mzspec:PXD1:r:scan:{i * 10 + m}",
                ))
            clusters.append(Cluster(f"c{i}", members))
        reps = nb.run_bin_mean(clusters)
        oracle = np.array(
            [nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)]
        )
        device = TpuBackend(layout=layout).average_cosines(reps, clusters)
        np.testing.assert_allclose(oracle, device, rtol=5e-5, atol=5e-5)

    @pytest.mark.parametrize("layout", ["auto", "flat", "bucketized"])
    def test_zero_peak_reps_and_members(self, rng, layout):
        """Representatives or members with zero peaks (quorum can wipe a
        consensus; converters can emit empty spectra) must yield cosine 0
        for the affected pairs, matching the oracle, not crash."""
        full = make_cluster(rng, "c-full", n_members=3, n_peaks=20)
        empty_rep = Spectrum(
            mz=[], intensity=[], precursor_mz=500.0, precursor_charge=2,
            title="c-full",
        )
        mixed = Cluster("c-mixed", [
            Spectrum(mz=[], intensity=[], precursor_mz=500.0,
                     precursor_charge=2, title="c-mixed;u0"),
            full.members[0],
        ])
        clusters = [full, mixed, full]
        reps = [empty_rep, nb.run_bin_mean([mixed])[0], nb.run_bin_mean([full])[0]]
        oracle = np.array(
            [nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)]
        )
        device = TpuBackend(layout=layout).average_cosines(reps, clusters)
        np.testing.assert_allclose(device, oracle, rtol=5e-5, atol=1e-5)
        assert device[0] == 0.0  # empty rep -> no shared signal

    @pytest.mark.parametrize("layout", ["auto", "flat"])
    def test_fused_pipeline_matches_composition(self, rng, layout):
        """run_bin_mean_with_cosines (the overlapped consensus+QC pass —
        chunk-pipelined native cosine under "auto" when the C++ kernel is
        built, device cosine under "flat") must equal run_bin_mean followed
        by average_cosines."""
        backend = TpuBackend(layout=layout)
        clusters = random_clusters(rng, n=10)
        reps_f, cos_f = backend.run_bin_mean_with_cosines(clusters)
        reps = backend.run_bin_mean(clusters)
        cos = backend.average_cosines(reps, clusters)
        assert [s.title for s in reps_f] == [s.title for s in reps]
        for a, b in zip(reps_f, reps):
            np.testing.assert_array_equal(a.mz, b.mz)
            np.testing.assert_array_equal(a.intensity, b.intensity)
        np.testing.assert_allclose(cos_f, cos, rtol=1e-6, atol=1e-7)

    def test_multi_chunk_dispatch(self, rng):
        """Force >= 3 chunks through the flat cosine path so the
        chunk-offset rebasing (s0/p0/r0, fill spectra, per-chunk pos/npos)
        is exercised (advisor r4: the parity suite fit in one chunk)."""
        backend = TpuBackend(max_grid_elements=4096, layout="flat")
        clusters = random_clusters(rng, n=14)
        reps = nb.run_bin_mean(clusters)
        oracle = np.array(
            [nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)]
        )
        device = backend.average_cosines(reps, clusters)
        np.testing.assert_allclose(oracle, device, rtol=5e-5, atol=1e-5)

    def test_pipelined_native_multi_chunk(self, rng):
        """The chunk-pipelined native path (2-worker dispatch pool, per-
        chunk finalize + native cosine) must survive multi-chunk splits
        with outputs in input order."""
        from specpride_tpu.ops import cosine_native

        if not cosine_native.available():
            pytest.skip("native cosine not built")
        backend = TpuBackend(max_grid_elements=4096)
        clusters = random_clusters(rng, n=14)
        reps_f, cos_f = backend.run_bin_mean_with_cosines(clusters)
        assert [s.title for s in reps_f] == [c.cluster_id for c in clusters]
        reps = TpuBackend().run_bin_mean(clusters)
        cos = TpuBackend().average_cosines(reps, clusters)
        np.testing.assert_allclose(cos_f, cos, rtol=1e-6, atol=1e-7)


class TestNativeCosine:
    """The C++ threaded cosine (native/cosine.cpp) against the oracle —
    near-f64-exact (same accumulation order; only the final dot/norm
    reductions differ from BLAS pairwise summation)."""

    @pytest.fixture(autouse=True)
    def _need_native(self):
        from specpride_tpu.ops import cosine_native

        if not cosine_native.available():
            pytest.skip("native cosine not built (make -C native)")

    def test_exact_parity(self, rng, backend):
        clusters = random_clusters(rng, n=12)
        reps = nb.run_bin_mean(clusters)
        oracle = np.array(
            [nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)]
        )
        native = backend._average_cosines_native(
            reps, clusters, CosineConfig()
        )
        np.testing.assert_allclose(native, oracle, rtol=1e-12, atol=1e-14)

    def test_unsorted_member_matches_oracle(self, backend):
        """np.add.at accumulation order must survive the stable-sort
        fallback for unsorted spectra."""
        rep = Spectrum(
            mz=[200.0, 300.0], intensity=[10.0, 20.0],
            precursor_mz=400.0, precursor_charge=2, title="c1",
        )
        member = Spectrum(
            mz=[200.0, 900.0, 950.0, 300.0],
            intensity=[10.0, 300.0, 1.0, 20.0],
            precursor_mz=400.0, precursor_charge=2, title="c1;u1",
        )
        oracle = nb.average_cosine(rep, [member])
        native = backend._average_cosines_native(
            [rep], [Cluster("c1", [member])], CosineConfig()
        )
        np.testing.assert_allclose(native, [oracle], rtol=1e-12)

    def test_last_edge_fold(self, backend):
        """A peak exactly at the pair's last grid edge folds into the final
        bin (scipy binned_statistic's right-closed last bin), not out."""
        space = CosineConfig().mz_space
        # last edge of the grid ending at this spectrum's last peak
        n = int(np.ceil((500.0 + space / 2.0) / space))
        last_edge = -space / 2.0 + (n - 1) * space
        s = Spectrum(
            mz=[100.0, last_edge], intensity=[5.0, 7.0],
            precursor_mz=400.0, precursor_charge=2, title="c1",
        )
        oracle = nb.average_cosine(s, [s])
        native = backend._average_cosines_native(
            [s], [Cluster("c1", [s])], CosineConfig()
        )
        np.testing.assert_allclose(native, [oracle], rtol=1e-12)
        assert native[0] == pytest.approx(1.0)

    def test_empty_and_zero_norm(self, rng, backend):
        full = make_cluster(rng, "c-full", n_members=3, n_peaks=20)
        empty_rep = Spectrum(
            mz=[], intensity=[], precursor_mz=500.0, precursor_charge=2,
            title="c-full",
        )
        zero_int = Cluster("c-z", [Spectrum(
            mz=[100.0, 200.0], intensity=[0.0, 0.0], precursor_mz=500.0,
            precursor_charge=2, title="c-z;u0",
        )])
        clusters = [full, zero_int]
        reps = [empty_rep, nb.run_bin_mean([zero_int])[0]]
        oracle = np.array(
            [nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)]
        )
        native = backend._average_cosines_native(
            reps, clusters, CosineConfig()
        )
        np.testing.assert_allclose(native, oracle, rtol=1e-12, atol=0)


# ---------------------------------------------------------------------------
# bucketing / ordering invariants
# ---------------------------------------------------------------------------

class TestOrdering:
    def test_outputs_follow_input_order(self, rng, backend):
        """Bucketing shuffles compute order; outputs must not be shuffled."""
        clusters = random_clusters(rng, n=16)
        device = backend.run_bin_mean(clusters)
        assert [s.title for s in device] == [c.cluster_id for c in clusters]

    def test_small_batch_chunking(self, rng):
        backend = TpuBackend(
            batch_config=BatchConfig(clusters_per_batch=3),
            max_grid_elements=2 * BinMeanConfig().n_bins,  # forces chunk = 2
        )
        clusters = random_clusters(rng, n=9)
        oracle = nb.run_bin_mean(clusters)
        device = backend.run_bin_mean(clusters)
        for o, d in zip(oracle, device):
            assert_spectra_close(o, d)

    def test_flat_bin_mean_multi_chunk(self, rng):
        """Force the flat bin-mean path through >= 3 chunks (max_elements
        = max_grid_elements // 4 peaks per batch) so per-chunk run_offsets
        and n_runs bookkeeping is exercised (advisor r4)."""
        backend = TpuBackend(max_grid_elements=4096)
        clusters = random_clusters(rng, n=14)
        oracle = nb.run_bin_mean(clusters)
        device = backend.run_bin_mean(clusters)
        assert [s.title for s in device] == [c.cluster_id for c in clusters]
        for o, d in zip(oracle, device):
            assert_spectra_close(o, d)


class TestPpmAndNormalization:
    """BASELINE configs[3]: ppm-tolerance grid + sqrt/log intensity
    normalization — oracle and device share ops.quantize, so parity must
    hold on every layout."""

    @pytest.mark.parametrize("layout", ["auto", "flat", "bucketized"])
    @pytest.mark.parametrize("ppm", [5.0, 20.0, 50.0])
    def test_ppm_bin_mean_parity(self, rng, layout, ppm):
        clusters = random_clusters(rng, n=8)
        config = BinMeanConfig(tolerance_mode="ppm", ppm=ppm)
        oracle = nb.run_bin_mean(clusters, config)
        device = TpuBackend(layout=layout).run_bin_mean(clusters, config)
        assert len(oracle) == len(device)
        for o, d in zip(oracle, device):
            assert_spectra_close(d, o)

    def test_ppm_bin_width_scales_with_mz(self):
        from specpride_tpu.ops import quantize

        config = BinMeanConfig(tolerance_mode="ppm", ppm=20.0)
        # two peaks 10 ppm apart share a 20-ppm bin; 40 ppm apart do not
        for base in (150.0, 800.0, 1900.0):
            near = np.array([base, base * (1 + 10e-6)])
            far = np.array([base, base * (1 + 40e-6)])
            bn, _ = quantize.bin_mean_bins(near, config)
            bf, _ = quantize.bin_mean_bins(far, config)
            # width is proportional, so the far pair always splits
            assert bf[0] != bf[1]
            # near pair may straddle an edge at one base, but widths match
            # the geometric definition exactly
            width = np.log1p(20.0 * 1e-6)
            expect = np.floor(np.log(near / config.min_mz) / width)
            np.testing.assert_array_equal(bn, expect.astype(np.int64))
        assert config.n_bins > 0

    @pytest.mark.parametrize("layout", ["auto", "flat", "bucketized"])
    @pytest.mark.parametrize("norm", ["sqrt", "log"])
    def test_normalized_cosine_parity(self, rng, layout, norm):
        clusters = random_clusters(rng, n=8)
        config = CosineConfig(normalization=norm)
        reps = nb.run_bin_mean(clusters)
        oracle = np.array([
            nb.average_cosine(r, c.members, config)
            for r, c in zip(reps, clusters)
        ])
        device = TpuBackend(layout=layout).average_cosines(
            reps, clusters, config
        )
        np.testing.assert_allclose(oracle, device, rtol=5e-5, atol=1e-5)
        # the transform changes the metric (sanity that the knob is live)
        plain = np.array([
            nb.average_cosine(r, c.members) for r, c in zip(reps, clusters)
        ])
        assert not np.allclose(oracle, plain)

    def test_fused_pipeline_honors_normalization(self, rng):
        clusters = random_clusters(rng, n=6)
        backend = TpuBackend()
        config = CosineConfig(normalization="sqrt")
        reps, cos = backend.run_bin_mean_with_cosines(
            clusters, BinMeanConfig(), config
        )
        expect = backend.average_cosines(reps, clusters, config)
        np.testing.assert_allclose(cos, expect, rtol=1e-6, atol=1e-9)
