// Multithreaded segmented stable argsort (C ABI, loaded via ctypes).
//
// The pack-time host passes sort peaks by bin WITHIN independent segments
// (clusters for the flat bin-mean layout, spectra for the cosine layout).
// numpy's global lexsort over millions of composite keys costs ~0.5 s
// single-threaded and cannot exploit the segment structure; sorting each
// segment independently is cache-friendly and embarrassingly parallel.
// Stability matches np.argsort(kind="stable") / np.lexsort tie behavior
// (equal keys keep input order), which the dedup and parity semantics
// rely on.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <numeric>
#include <thread>
#include <vector>

extern "C" {

// order_out[i] receives GLOBAL indices: for each segment s,
// order_out[offsets[s]:offsets[s+1]] is offsets[s] + stable argsort of
// keys[offsets[s]:offsets[s+1]].
int seg_argsort_i64(
    const int64_t* keys,
    const int64_t* offsets,  // (n_segs + 1,)
    int64_t n_segs,
    int64_t* order_out,
    int n_threads) {
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? static_cast<int>(hc) : 4;
  }
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(n_segs, 1));

  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    for (;;) {
      int64_t s = next.fetch_add(1);
      if (s >= n_segs) return;
      const int64_t lo = offsets[s], hi = offsets[s + 1];
      std::iota(order_out + lo, order_out + hi, lo);
      std::stable_sort(order_out + lo, order_out + hi,
                       [&](int64_t a, int64_t b) { return keys[a] < keys[b]; });
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return 0;
}

// Threaded searchsorted (side='right'): out[i] = number of keys <= q[i].
// numpy's searchsorted is single-threaded; the cosine prep queries ~3M
// member keys against ~1M rep keys per batch, which is worth spreading
// across cores.
int searchsorted_right_i32(
    const int32_t* keys,
    int64_t n_keys,
    const int32_t* queries,
    int64_t n_queries,
    int64_t* out,
    int n_threads) {
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? static_cast<int>(hc) : 4;
  }
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(n_queries, 1));
  std::atomic<int64_t> next{0};
  const int64_t block = 1 << 16;
  auto worker = [&]() {
    for (;;) {
      int64_t lo = next.fetch_add(block);
      if (lo >= n_queries) return;
      int64_t hi = std::min(lo + block, n_queries);
      for (int64_t i = lo; i < hi; ++i) {
        out[i] = std::upper_bound(keys, keys + n_keys, queries[i]) - keys;
      }
    }
  };
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return 0;
}

}  // extern "C"
