// Multithreaded binned-cosine QC metric (C ABI, loaded via ctypes).
//
// Mean binned cosine of each cluster representative to the cluster's
// members (ref src/benchmark.py:11-38).  Like the gap-average
// (gap_average.cpp), this is memory-bound group-by work the measured
// single-chip reality favours on the host: the device kernel
// (ops/similarity.py:cosine_flat) must ship ~16 bytes per member peak over
// a ~90 MB/s tunneled H2D link to compute a handful of FLOPs per byte,
// while this path walks the same peaks in cache at memory speed — so the
// mesh-less backend calls this when built, keeping the device kernels for
// sharded mesh runs where the link economics differ.  Exact oracle
// semantics (backends/numpy_backend.py:binned_cosine), all float64:
//
//  * pair grid: edges = arange(-space/2, max(a.mz[-1], b.mz[-1]), space);
//    fewer than 2 edges -> cosine 0; either spectrum empty -> 0
//  * bin index floor((mz - edges[0]) / space); peaks outside
//    [edges[0], edges[-1]] are excluded; a peak exactly at the last edge
//    folds into the final bin (scipy binned_statistic's right-closed
//    last bin, idx == n_edges-1 -> n_edges-2)
//  * per-bin sums accumulate in input order (== ascending m/z for sorted
//    spectra; unsorted input is stable-sorted by bin, preserving the
//    oracle's np.add.at accumulation order within each bin)
//  * cosine = dot / sqrt(na * nb) over the dense grid vectors — computed
//    sparsely as a sorted-run merge (bins occupied by only one side
//    contribute zero to the dot); na == 0.0 or nb == 0.0 -> 0 (exact
//    float compare, as the oracle)
//
// Build: make -C native (produces libcosine.so).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <thread>
#include <utility>
#include <vector>

namespace {

// Compact per-bin runs of one spectrum on the pair's grid.  Returns runs in
// ascending-bin order; the accumulation order within a bin is input order
// (matches np.add.at).
void build_runs(const double* mz, const double* inten, int64_t n, double e0,
                double space, int64_t n_edges, double e_last,
                std::vector<int64_t>& bins, std::vector<double>& sums,
                std::vector<std::pair<int64_t, double>>& scratch) {
  bins.clear();
  sums.clear();
  bool sorted = true;
  for (int64_t i = 0; i < n; ++i) {
    const double m = mz[i];
    if (!(m >= e0 && m <= e_last)) continue;
    int64_t b = static_cast<int64_t>(std::floor((m - e0) / space));
    if (b == n_edges - 1) b = n_edges - 2;  // right-closed last bin
    if (!bins.empty() && b < bins.back()) {
      sorted = false;
      break;
    }
    if (!bins.empty() && bins.back() == b) {
      sums.back() += inten[i];
    } else {
      bins.push_back(b);
      sums.push_back(inten[i]);
    }
  }
  if (sorted) return;

  // unsorted spectrum (the oracle's scatter-add does not care): stable-sort
  // (bin, intensity) pairs by bin, then merge — input order survives within
  // each bin, so the per-bin accumulation order still matches np.add.at
  scratch.clear();
  for (int64_t i = 0; i < n; ++i) {
    const double m = mz[i];
    if (!(m >= e0 && m <= e_last)) continue;
    int64_t b = static_cast<int64_t>(std::floor((m - e0) / space));
    if (b == n_edges - 1) b = n_edges - 2;
    scratch.emplace_back(b, inten[i]);
  }
  std::stable_sort(scratch.begin(), scratch.end(),
                   [](const std::pair<int64_t, double>& a,
                      const std::pair<int64_t, double>& b) {
                     return a.first < b.first;
                   });
  bins.clear();
  sums.clear();
  for (const auto& p : scratch) {
    if (!bins.empty() && bins.back() == p.first) {
      sums.back() += p.second;
    } else {
      bins.push_back(p.first);
      sums.push_back(p.second);
    }
  }
}

}  // namespace

extern "C" {

// out_cos[s] = binned cosine of spectrum s to its cluster's representative.
// Spectra of cluster c are [cluster_spec_offsets[c], cluster_spec_offsets
// [c+1]); spectrum s's peaks are [spec_offsets[s], spec_offsets[s+1]);
// representative c's peaks are [rep_offsets[c], rep_offsets[c+1]).
int pair_cosines_run(
    const double* rep_mz,
    const double* rep_int,
    const int64_t* rep_offsets,           // (n_clusters + 1,)
    const double* mem_mz,
    const double* mem_int,
    const int64_t* spec_offsets,          // (n_spectra + 1,)
    const int64_t* cluster_spec_offsets,  // (n_clusters + 1,)
    int64_t n_clusters,
    double space,
    double* out_cos,  // (n_spectra,)
    int n_threads) {
  if (space <= 0.0) return 1;
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? static_cast<int>(hc) : 4;
  }
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(n_clusters, 1));
  const double e0 = -space / 2.0;

  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    std::vector<int64_t> rb, mb;
    std::vector<double> rs, ms;
    std::vector<std::pair<int64_t, double>> scratch;
    for (;;) {
      const int64_t c = next.fetch_add(1);
      if (c >= n_clusters) return;
      const int64_t r0 = rep_offsets[c], r1 = rep_offsets[c + 1];
      const int64_t nr = r1 - r0;
      const double rep_last = nr ? rep_mz[r1 - 1] : 0.0;
      for (int64_t s = cluster_spec_offsets[c]; s < cluster_spec_offsets[c + 1];
           ++s) {
        const int64_t p0 = spec_offsets[s], p1 = spec_offsets[s + 1];
        const int64_t np_ = p1 - p0;
        out_cos[s] = 0.0;
        if (nr == 0 || np_ == 0) continue;
        // pair grid from the LAST peak of each side (ref src/benchmark.py:20
        // assumes sorted spectra — the last element, not the max)
        const double max_mz = std::max(rep_last, mem_mz[p1 - 1]);
        const double len_d = std::ceil((max_mz - e0) / space);
        if (!(len_d >= 2.0)) continue;  // <2 edges (also rejects NaN)
        const int64_t n_edges = static_cast<int64_t>(len_d);
        // np.arange element i = start + i*step, both rounded once — same
        // double expression here, so the boundary tests match bitwise
        const double e_last =
            e0 + static_cast<double>(n_edges - 1) * space;

        build_runs(rep_mz + r0, rep_int + r0, nr, e0, space, n_edges, e_last,
                   rb, rs, scratch);
        build_runs(mem_mz + p0, mem_int + p0, np_, e0, space, n_edges, e_last,
                   mb, ms, scratch);

        double na = 0.0, nb = 0.0, dot = 0.0;
        for (double v : rs) na += v * v;  // ascending-bin order, as va @ va
        for (double v : ms) nb += v * v;
        if (na == 0.0 || nb == 0.0) continue;  // oracle's exact-zero test
        size_t i = 0, j = 0;
        while (i < rb.size() && j < mb.size()) {
          if (rb[i] == mb[j]) {
            dot += rs[i] * ms[j];
            ++i;
            ++j;
          } else if (rb[i] < mb[j]) {
            ++i;
          } else {
            ++j;
          }
        }
        out_cos[s] = dot / std::sqrt(na * nb);
      }
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return 0;
}

}  // extern "C"
