// Multithreaded pairwise shared-bin counts for medoid selection (C ABI,
// loaded via ctypes).
//
// The medoid distance (ref src/most_similar_representative.py:13-19) needs
// |unique_bins(a) ∩ unique_bins(b)| for every member pair of every cluster
// — exact INTEGER counts (bin = trunc(mz / bin_size), float64, matching
// numpy's `(mz / bin_size).astype(int64)`).  The device path computes the
// same counts as a bitmask-occupancy gram matmul on the MXU
// (ops/similarity.py:shared_bins_packed), which wins when the link is
// cheap; on the tunneled single-chip host the transfer dwarfs the FLOPs
// (round-4 bench: more time in dispatch round-trips than compute), so the
// mesh-less backend counts pairs here instead: per-member unique-bin lists
// built once, per-pair sorted-merge intersection, clusters partitioned
// across threads.  The float64 finalize (prescore / distance / argmin with
// the reference's double-counted diagonal) stays in
// ops/similarity.py:medoid_finalize — shared with the device path, so both
// paths' fp semantics are identical by construction.
//
// Build: make -C native (produces libmedoid.so).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

// unique, ascending bin ids of one spectrum (mz sorted -> trunc monotone;
// unsorted input falls back to an explicit sort, same result as np.unique).
// The bin MUST be a true division — mz * (1/bin_size) rounds differently
// at bin boundaries (e.g. 100.1/0.1 -> 1001 but 100.1*10.0000..x ->
// 1000.99..), and one-decimal m/z values, ubiquitous in MGF files, sit on
// those boundaries for the default 0.1 Da grid.
void build_bins(const double* mz, int64_t n, double bin_size,
                std::vector<int64_t>& bins) {
  bins.clear();
  bool sorted = true;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t b = static_cast<int64_t>(mz[i] / bin_size);
    if (!bins.empty() && b < bins.back()) {
      sorted = false;
      break;
    }
    if (bins.empty() || bins.back() != b) bins.push_back(b);
  }
  if (sorted) return;
  bins.clear();
  bins.reserve(n);
  for (int64_t i = 0; i < n; ++i) {
    bins.push_back(static_cast<int64_t>(mz[i] / bin_size));
  }
  std::sort(bins.begin(), bins.end());
  bins.erase(std::unique(bins.begin(), bins.end()), bins.end());
}

int64_t merge_count(const std::vector<int64_t>& a,
                    const std::vector<int64_t>& b) {
  int64_t count = 0;
  size_t i = 0, j = 0;
  while (i < a.size() && j < b.size()) {
    if (a[i] == b[j]) {
      ++count;
      ++i;
      ++j;
    } else if (a[i] < b[j]) {
      ++i;
    } else {
      ++j;
    }
  }
  return count;
}

}  // namespace

extern "C" {

// out_shared[out_offsets[c] + i*M + j] = shared unique-bin count of members
// i, j of cluster c (symmetric, diagonal = member's own unique-bin count),
// where M = cluster_spec_offsets[c+1] - cluster_spec_offsets[c] and
// out_offsets[c] accumulates M^2 (caller-computed).
int medoid_shared_run(
    const double* mz,
    const int64_t* spec_offsets,          // (n_spectra + 1,)
    const int64_t* cluster_spec_offsets,  // (n_clusters + 1,)
    const int64_t* out_offsets,           // (n_clusters + 1,)
    int64_t n_clusters,
    double bin_size,
    int32_t* out_shared,
    int n_threads) {
  if (bin_size <= 0.0) return 1;
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? static_cast<int>(hc) : 4;
  }
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(n_clusters, 1));

  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    std::vector<std::vector<int64_t>> bins;
    for (;;) {
      const int64_t c = next.fetch_add(1);
      if (c >= n_clusters) return;
      const int64_t s0 = cluster_spec_offsets[c];
      const int64_t m = cluster_spec_offsets[c + 1] - s0;
      int32_t* out = out_shared + out_offsets[c];
      bins.resize(m);
      for (int64_t i = 0; i < m; ++i) {
        const int64_t p0 = spec_offsets[s0 + i];
        build_bins(mz + p0, spec_offsets[s0 + i + 1] - p0, bin_size,
                   bins[i]);
      }
      for (int64_t i = 0; i < m; ++i) {
        out[i * m + i] = static_cast<int32_t>(bins[i].size());
        for (int64_t j = i + 1; j < m; ++j) {
          const int32_t s =
              static_cast<int32_t>(merge_count(bins[i], bins[j]));
          out[i * m + j] = s;
          out[j * m + i] = s;
        }
      }
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return 0;
}

}  // extern "C"
