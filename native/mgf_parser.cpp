// Fast MGF (Mascot Generic Format) parser — the native feed path.
//
// The reference's ingest is a pure-Python float()-per-line loop
// (ref src/binning.py:122-167); at device-kernel throughput the host parse
// becomes the end-to-end bottleneck (SURVEY.md §7 hard part d).  This
// library parses a clustered MGF into flat column arrays (all peaks
// concatenated + per-spectrum offsets) in one pass, exposed over a plain C
// ABI consumed from Python via ctypes (specpride_tpu/io/native.py) — no
// pybind11 dependency.
//
// Semantics mirror the Python oracle parser
// (specpride_tpu/io/mgf.py parse_mgf_stream) exactly:
//   * lines outside BEGIN IONS / END IONS are ignored; blank lines skipped
//   * a line starting with a digit or '+'/'-'/'.' inside a record is a peak
//     line: first field = m/z, second = intensity (missing -> 0.0)
//   * other record lines are KEY=VALUE headers; KEY is uppercased;
//     TITLE / PEPMASS (first field) / CHARGE (N+, N-, N) / RTINSECONDS are
//     extracted, everything else is kept verbatim as per-spectrum extras
//   * a record yields a spectrum only on END IONS
// Files ending in .gz are decompressed transparently (zlib), matching the
// gzip-transparent Python path.
//
// Build: make -C native  (g++ -O2 -shared -fPIC, links -lz)

#include <cctype>
#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

struct Columns {
  std::vector<double> mz;
  std::vector<double> intensity;
  std::vector<int64_t> peak_offsets;  // n_spectra + 1
  std::vector<double> precursor_mz;
  std::vector<int32_t> charge;
  std::vector<double> rt;
  std::string titles;                  // concatenated
  std::vector<int64_t> title_offsets;  // n_spectra + 1
  std::string extras;                  // "KEY=VALUE\n..." per spectrum
  std::vector<int64_t> extra_offsets;  // n_spectra + 1
};

struct MgfFile {
  Columns c;
  std::string error;
};

bool read_whole_file(const char* path, std::string& out, std::string& err) {
  size_t n = std::strlen(path);
  bool gz = n > 3 && std::strcmp(path + n - 3, ".gz") == 0;
  if (gz) {
    gzFile f = gzopen(path, "rb");
    if (!f) {
      err = std::string("cannot open ") + path;
      return false;
    }
    char buf[1 << 16];
    int got;
    while ((got = gzread(f, buf, sizeof buf)) > 0) out.append(buf, got);
    bool ok = got == 0;
    if (!ok) {
      int zerr = 0;
      err = std::string("gzread failed: ") + gzerror(f, &zerr);
    }
    gzclose(f);
    return ok;
  }
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    err = std::string("cannot open ") + path;
    return false;
  }
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size < 0) {
    std::fclose(f);
    err = "ftell failed";
    return false;
  }
  out.resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(out.data(), 1, out.size(), f) : 0;
  std::fclose(f);
  if (got != out.size()) {
    err = "short read";
    return false;
  }
  return true;
}

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline const char* trim_end(const char* p, const char* end) {
  while (end > p &&
         (end[-1] == ' ' || end[-1] == '\t' || end[-1] == '\r')) --end;
  return end;
}

inline bool is_field_ws(char c) { return c == ' ' || c == '\t'; }

// Parse ONE whole whitespace-delimited field as a double.  Python's
// float(field) raises on any trailing junk within the field and accepts a
// leading '+' (which std::from_chars does not) — mirror both: after the
// numeric parse the field must be exhausted (next char is whitespace or
// line end).  from_chars consumes the maximal valid prefix, so a single
// trailing-char check is equivalent to pre-scanning the field boundary —
// and one pass cheaper.  Returns pointer past the field, or nullptr.
inline const char* parse_double_field(const char* p, const char* end,
                                      double& out) {
  if (p < end && *p == '+') ++p;
  auto [ptr, ec] = std::from_chars(p, end, out);
  if (ec != std::errc()) return nullptr;
  if (ptr < end && !is_field_ws(*ptr)) return nullptr;  // junk inside field
  return ptr;
}

// CHARGE=2+ / 2- / 2 / +2  ->  signed int (mirror of mgf.py _parse_charge:
// strip ALL trailing '+' or ALL trailing '-', then int() the rest — which
// accepts a leading sign but no other junk).  Returns false on values where
// Python's int() would raise.
bool parse_charge(const char* p, const char* end, int32_t& out) {
  p = skip_ws(p, end);
  end = trim_end(p, end);
  int sign = 1;
  if (end > p && end[-1] == '+') {
    while (end > p && end[-1] == '+') --end;
  } else if (end > p && end[-1] == '-') {
    while (end > p && end[-1] == '-') --end;
    sign = -1;
  }
  if (end <= p) {
    out = 0;  // bare "+"/"-" strips to empty -> 0, as the Python parser
    return true;
  }
  if (*p == '+') ++p;  // from_chars<int> rejects the leading '+' int() allows
  int value = 0;
  auto [ptr, ec] = std::from_chars(p, end, value);
  if (ec != std::errc() || ptr != end) return false;
  out = sign * value;
  return true;
}

bool parse_range(const char* p, const char* file_end, int64_t line_base,
                 Columns& c, std::string& err) {
  // reserve from a size heuristic (~18 bytes per peak line) to avoid
  // vector regrowth memcpys on large files
  size_t approx_peaks = static_cast<size_t>(file_end - p) / 18 + 16;
  c.mz.reserve(approx_peaks);
  c.intensity.reserve(approx_peaks);

  bool in_ions = false;
  std::string title, extras_cur;
  double pepmass = 0.0, rtsec = 0.0;
  int32_t z = 0;
  int64_t peaks_start = 0;
  int64_t line_no = line_base;

  c.peak_offsets.push_back(0);
  c.title_offsets.push_back(0);
  c.extra_offsets.push_back(0);

  while (p < file_end) {
    ++line_no;
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(file_end - p)));
    const char* line_end = nl ? nl : file_end;
    const char* s = skip_ws(p, line_end);
    const char* e = trim_end(s, line_end);
    p = nl ? nl + 1 : file_end;
    if (s == e) continue;  // blank
    size_t len = static_cast<size_t>(e - s);

    if (len == 10 && std::memcmp(s, "BEGIN IONS", 10) == 0) {
      in_ions = true;
      title.clear();
      extras_cur.clear();
      pepmass = 0.0;
      rtsec = 0.0;
      z = 0;
      peaks_start = static_cast<int64_t>(c.mz.size());
      continue;
    }
    if (len == 8 && std::memcmp(s, "END IONS", 8) == 0) {
      if (in_ions) {
        c.peak_offsets.push_back(static_cast<int64_t>(c.mz.size()));
        c.precursor_mz.push_back(pepmass);
        c.charge.push_back(z);
        c.rt.push_back(rtsec);
        c.titles.append(title);
        c.title_offsets.push_back(static_cast<int64_t>(c.titles.size()));
        c.extras.append(extras_cur);
        c.extra_offsets.push_back(static_cast<int64_t>(c.extras.size()));
      }
      in_ions = false;
      continue;
    }
    if (!in_ions) continue;

    char first = *s;
    if ((first >= '0' && first <= '9') || first == '+' || first == '-' ||
        first == '.') {
      // Python: fields = line.split(); float(fields[0]), float(fields[1])
      // — first two fields must each be fully-valid floats (raise
      // otherwise); any further fields are ignored.
      double mz_val = 0.0;
      const char* q = parse_double_field(s, e, mz_val);
      if (!q) {
        char buf[96];
        std::snprintf(buf, sizeof buf, "line %lld: bad peak m/z",
                      static_cast<long long>(line_no));
        err = buf;
        return false;
      }
      double inten_val = 0.0;
      q = skip_ws(q, e);
      if (q < e) {
        if (!parse_double_field(q, e, inten_val)) {
          char buf[96];
          std::snprintf(buf, sizeof buf, "line %lld: bad peak intensity",
                        static_cast<long long>(line_no));
          err = buf;
          return false;
        }
      }
      c.mz.push_back(mz_val);
      c.intensity.push_back(inten_val);
      continue;
    }

    const char* eq = static_cast<const char*>(
        std::memchr(s, '=', static_cast<size_t>(e - s)));
    if (!eq) continue;  // mirror Python: non-KEY=VALUE line ignored
    const char* key_end = trim_end(s, eq);
    std::string key(s, static_cast<size_t>(key_end - s));
    for (char& ch : key)
      ch = static_cast<char>(std::toupper(static_cast<unsigned char>(ch)));
    const char* v = skip_ws(eq + 1, e);

    if (key == "TITLE") {
      title.assign(v, static_cast<size_t>(e - v));
    } else if (key == "PEPMASS") {
      // first whitespace-separated field only; empty value -> 0.0, junk ->
      // error (Python float(value.split()[0]) raises)
      if (v < e && !parse_double_field(v, e, pepmass)) {
        char buf[96];
        std::snprintf(buf, sizeof buf, "line %lld: bad PEPMASS",
                      static_cast<long long>(line_no));
        err = buf;
        return false;
      }
    } else if (key == "CHARGE") {
      if (!parse_charge(v, e, z)) {
        char buf[96];
        std::snprintf(buf, sizeof buf, "line %lld: bad CHARGE",
                      static_cast<long long>(line_no));
        err = buf;
        return false;
      }
    } else if (key == "RTINSECONDS") {
      // Python float(value or 0.0): whole (stripped) value must parse;
      // empty -> 0.0
      const char* fe = (v < e && *v == '+') ? v + 1 : v;
      double val = 0.0;
      if (v < e) {
        auto [ptr, ec] = std::from_chars(fe, e, val);
        if (ec != std::errc() || ptr != e) {
          char buf[96];
          std::snprintf(buf, sizeof buf, "line %lld: bad RTINSECONDS",
                        static_cast<long long>(line_no));
          err = buf;
          return false;
        }
        rtsec = val;
      }
    } else {
      extras_cur.append(key);
      extras_cur.push_back('=');
      extras_cur.append(v, static_cast<size_t>(e - v));
      extras_cur.push_back('\n');
    }
  }
  (void)peaks_start;
  return true;
}

void merge_columns(Columns& dst, Columns& src) {
  int64_t peak_base = static_cast<int64_t>(dst.mz.size());
  int64_t title_base = static_cast<int64_t>(dst.titles.size());
  int64_t extra_base = static_cast<int64_t>(dst.extras.size());
  dst.mz.insert(dst.mz.end(), src.mz.begin(), src.mz.end());
  dst.intensity.insert(dst.intensity.end(), src.intensity.begin(),
                       src.intensity.end());
  dst.precursor_mz.insert(dst.precursor_mz.end(), src.precursor_mz.begin(),
                          src.precursor_mz.end());
  dst.charge.insert(dst.charge.end(), src.charge.begin(), src.charge.end());
  dst.rt.insert(dst.rt.end(), src.rt.begin(), src.rt.end());
  dst.titles.append(src.titles);
  dst.extras.append(src.extras);
  // offset vectors all start with 0 — skip it and rebase
  for (size_t i = 1; i < src.peak_offsets.size(); ++i)
    dst.peak_offsets.push_back(src.peak_offsets[i] + peak_base);
  for (size_t i = 1; i < src.title_offsets.size(); ++i)
    dst.title_offsets.push_back(src.title_offsets[i] + title_base);
  for (size_t i = 1; i < src.extra_offsets.size(); ++i)
    dst.extra_offsets.push_back(src.extra_offsets[i] + extra_base);
}

// Split the buffer at record boundaries ("BEGIN IONS" at start of line) and
// parse the chunks in parallel.  Records are independent, so per-chunk
// Columns concatenate into exactly the single-thread result.
bool parse_buffer(const std::string& text, Columns& c, std::string& err) {
  const char* base = text.data();
  const char* end = base + text.size();

  unsigned hw = std::thread::hardware_concurrency();
  size_t want = hw ? hw : 1;
  // SPECPRIDE_MGF_THREADS overrides autodetection (containers often
  // report 1 core; tests use it to force the parallel split path)
  if (const char* env = std::getenv("SPECPRIDE_MGF_THREADS")) {
    long v = std::atol(env);
    if (v > 0) want = static_cast<size_t>(v);
  }
  if (want > 16) want = 16;
  const size_t min_chunk = 4 << 20;  // below ~4 MB threads don't pay
  if (text.size() / min_chunk < want) want = text.size() / min_chunk;
  if (want <= 1) return parse_range(base, end, 0, c, err);

  std::vector<const char*> starts{base};
  for (size_t t = 1; t < want; ++t) {
    const char* guess = base + text.size() * t / want;
    // advance to the next line that begins "BEGIN IONS"
    const char* q = guess;
    const char* found = nullptr;
    while (q < end) {
      const char* nl = static_cast<const char*>(
          std::memchr(q, '\n', static_cast<size_t>(end - q)));
      if (!nl) break;
      q = nl + 1;
      if (static_cast<size_t>(end - q) >= 10 &&
          std::memcmp(q, "BEGIN IONS", 10) == 0) {
        // the serial parser only treats a line *trimming to exactly*
        // "BEGIN IONS" as a record start; accepting e.g. "BEGIN IONSX"
        // or "BEGIN IONS extra" as a split point would silently drop the
        // enclosing record on multithreaded parses.  A missed split point
        // is harmless (the previous chunk parses through it), so be
        // strict: rest of the line must be whitespace only.
        const char* r = q + 10;
        while (r < end && (*r == ' ' || *r == '\t' || *r == '\r')) ++r;
        if (r == end || *r == '\n') {
          found = q;
          break;
        }
      }
    }
    if (found && found > starts.back()) starts.push_back(found);
  }
  starts.push_back(end);

  size_t n_chunks = starts.size() - 1;
  // absolute starting line number per chunk, so parse errors cite real
  // file lines regardless of which thread hits them
  std::vector<int64_t> line_bases(n_chunks, 0);
  for (size_t i = 1; i < n_chunks; ++i) {
    int64_t count = 0;
    for (const char* q = starts[i - 1]; q < starts[i]; ++q)
      if (*q == '\n') ++count;
    line_bases[i] = line_bases[i - 1] + count;
  }
  std::vector<Columns> cols(n_chunks);
  std::vector<std::string> errs(n_chunks);
  std::vector<char> oks(n_chunks, 0);
  std::vector<std::thread> threads;
  for (size_t i = 0; i < n_chunks; ++i) {
    threads.emplace_back([&, i] {
      try {
        oks[i] = parse_range(starts[i], starts[i + 1], line_bases[i], cols[i],
                             errs[i])
                     ? 1
                     : 0;
      } catch (const std::exception& e) {
        errs[i] = e.what();  // rethrowing would std::terminate the process
      } catch (...) {
        errs[i] = "unknown C++ exception";
      }
    });
  }
  for (auto& th : threads) th.join();
  for (size_t i = 0; i < n_chunks; ++i) {
    if (!oks[i]) {
      err = errs[i];
      return false;
    }
  }

  c.peak_offsets.push_back(0);
  c.title_offsets.push_back(0);
  c.extra_offsets.push_back(0);
  for (auto& chunk : cols) merge_columns(c, chunk);
  return true;
}

}  // namespace

extern "C" {

MgfFile* mgf_parse(const char* path, char* errbuf, int errlen) {
  // exceptions must not cross the C ABI into the ctypes frame
  // (std::terminate would abort the whole Python process) — catch
  // everything, including bad_alloc from slurping oversized files
  MgfFile* f = nullptr;
  try {
    f = new MgfFile();
    std::string text;
    if (read_whole_file(path, text, f->error) &&
        parse_buffer(text, f->c, f->error)) {
      return f;
    }
  } catch (const std::exception& e) {
    if (f)
      f->error = e.what();
    else if (errbuf && errlen > 0)
      std::snprintf(errbuf, static_cast<size_t>(errlen), "%s", e.what());
  } catch (...) {
    if (f) f->error = "unknown C++ exception";
  }
  if (f) {
    if (errbuf && errlen > 0) {
      std::snprintf(errbuf, static_cast<size_t>(errlen), "%s",
                    f->error.c_str());
    }
    delete f;
  }
  return nullptr;
}

int64_t mgf_n_spectra(const MgfFile* f) {
  return static_cast<int64_t>(f->c.precursor_mz.size());
}
int64_t mgf_n_peaks(const MgfFile* f) {
  return static_cast<int64_t>(f->c.mz.size());
}
const double* mgf_mz(const MgfFile* f) { return f->c.mz.data(); }
const double* mgf_intensity(const MgfFile* f) { return f->c.intensity.data(); }
const int64_t* mgf_peak_offsets(const MgfFile* f) {
  return f->c.peak_offsets.data();
}
const double* mgf_precursor_mz(const MgfFile* f) {
  return f->c.precursor_mz.data();
}
const int32_t* mgf_charge(const MgfFile* f) { return f->c.charge.data(); }
const double* mgf_rt(const MgfFile* f) { return f->c.rt.data(); }
const char* mgf_titles(const MgfFile* f) { return f->c.titles.data(); }
const int64_t* mgf_title_offsets(const MgfFile* f) {
  return f->c.title_offsets.data();
}
const char* mgf_extras(const MgfFile* f) { return f->c.extras.data(); }
const int64_t* mgf_extra_offsets(const MgfFile* f) {
  return f->c.extra_offsets.data();
}
void mgf_free(MgfFile* f) { delete f; }

}  // extern "C"
