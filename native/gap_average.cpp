// Multithreaded gap-average consensus (C ABI, loaded via ctypes).
//
// The gap-average method (ref src/average_spectrum_clustering.py:26-103) is
// a memory-bound per-cluster group-by: sort the cluster's concatenated
// peaks by m/z (float64 — the grouping threshold comparison must match
// numpy bit-for-bit), split at gaps >= mz_accuracy, average each group,
// apply the quorum and dynamic-range filters.  A TPU adds nothing here (the
// measured device path lost 14x to numpy over the host link), and a
// vectorized single-thread numpy pass only ties the per-cluster oracle —
// so the TPU backend's host path calls this instead: per-cluster work
// partitioned across threads, exact f64 semantics preserved:
//
//  * stable sort by m/z == np.argsort(kind="stable") (ties keep input
//    order); singleton clusters keep INPUT order, one group per peak
//    (ref :88-90)
//  * gap where diff >= mz_accuracy; tail_mode "reference" drops the final
//    gap when a multi-member cluster has >= 2 gaps (ref :79-87)
//  * group m/z = sum/size, group intensity = sum/n_members, accumulated
//    in ascending-m/z order (the same addition sequence as the oracle's
//    np.bincount weights) (ref :76-77,81-82,86-87)
//  * quorum: size >= min_fraction * n_members, float compare (ref :74);
//    skipped for singletons
//  * dynamic range: keep intensity >= max(kept)/dyn_range (ref :95-98);
//    all-fail -> empty output (documented oracle divergence from the
//    reference crash)
//
// Build: make -C native (produces libgap_average.so).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <limits>
#include <numeric>
#include <thread>
#include <vector>

extern "C" {

// Per-cluster outputs are written into caller-allocated flat buffers sized
// by the total input peak count (a group count never exceeds the peak
// count).  out_counts[c] = number of kept groups for cluster c; kept
// groups land at out offsets [peak_offsets[c], peak_offsets[c]+count).
int gap_average_run(
    const double* mz,
    const double* intensity,
    const int64_t* peak_offsets,  // (n_clusters + 1,)
    const int64_t* n_members,     // (n_clusters,)
    int64_t n_clusters,
    double mz_accuracy,
    int tail_mode_reference,
    double min_fraction,
    double dyn_range,
    double* out_mz,
    double* out_intensity,
    int64_t* out_counts,
    int n_threads) {
  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc ? static_cast<int>(hc) : 4;
  }
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(n_clusters, 1));

  std::atomic<int64_t> next{0};
  auto worker = [&]() {
    std::vector<int64_t> order;
    std::vector<int64_t> group_start;
    std::vector<double> gmz, gint;
    std::vector<int64_t> gsize;
    for (;;) {
      int64_t c = next.fetch_add(1);
      if (c >= n_clusters) return;
      const int64_t p0 = peak_offsets[c], p1 = peak_offsets[c + 1];
      const int64_t n = p1 - p0;
      const int64_t nm = n_members[c];
      out_counts[c] = 0;
      if (n == 0) continue;

      order.resize(n);
      std::iota(order.begin(), order.end(), p0);
      const bool singleton = nm == 1;
      if (!singleton) {
        std::stable_sort(order.begin(), order.end(),
                         [&](int64_t a, int64_t b) { return mz[a] < mz[b]; });
      }

      // group boundaries (positions i where a gap precedes peak i)
      group_start.clear();
      group_start.push_back(0);
      if (singleton) {
        for (int64_t i = 1; i < n; ++i) group_start.push_back(i);
      } else {
        for (int64_t i = 1; i < n; ++i) {
          if (mz[order[i]] - mz[order[i - 1]] >= mz_accuracy) {
            group_start.push_back(i);
          }
        }
        if (tail_mode_reference && group_start.size() >= 3) {
          // >= 2 gaps: the final gap is ignored -> last two groups merge
          group_start.pop_back();
        }
      }
      const int64_t ng = static_cast<int64_t>(group_start.size());

      gmz.assign(ng, 0.0);
      gint.assign(ng, 0.0);
      gsize.assign(ng, 0);
      for (int64_t g = 0; g < ng; ++g) {
        const int64_t lo = group_start[g];
        const int64_t hi = (g + 1 < ng) ? group_start[g + 1] : n;
        double ms = 0.0, is = 0.0;  // ascending-m/z accumulation order
        for (int64_t i = lo; i < hi; ++i) {
          ms += mz[order[i]];
          is += intensity[order[i]];
        }
        gsize[g] = hi - lo;
        gmz[g] = ms / static_cast<double>(gsize[g]);
        gint[g] = is / static_cast<double>(nm);
      }

      // quorum (float compare, skipped for singletons), then dyn range
      const double min_l = min_fraction * static_cast<double>(nm);
      double kept_max = -std::numeric_limits<double>::infinity();
      for (int64_t g = 0; g < ng; ++g) {
        const bool q = singleton || static_cast<double>(gsize[g]) >= min_l;
        gsize[g] = q ? gsize[g] : -1;  // mark dropped
        if (q && gint[g] > kept_max) kept_max = gint[g];
      }
      const double floor_v = kept_max / dyn_range;
      int64_t w = p0;
      for (int64_t g = 0; g < ng; ++g) {
        if (gsize[g] >= 0 && gint[g] >= floor_v) {
          out_mz[w] = gmz[g];
          out_intensity[w] = gint[g];
          ++w;
        }
      }
      out_counts[c] = w - p0;
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
  return 0;
}

}  // extern "C"
