#!/bin/sh
# External search + rescoring pipeline (the reference's search.sh:1-7):
# builds a peptide FASTA from MaxQuant peptides.txt, runs crux tide-index /
# tide-search against the benchmark mzML, and rescoring with percolator.
# The percolator PSM TSV it produces feeds straight into
#
#   specpride select clustered.mgf best.mgf --method best \
#       --psms crux/crux-output/percolator.target.psms.txt
#
# Requires `crux` (https://crux.ms) on PATH — deliberately NOT vendored:
# it is the reference's external ground-truth tool, not part of this
# framework.  awk replaces the reference's gawk (same one-liner).
#
#   sh scripts/search.sh [DATA_DIR]     # default: ./data (fetch_data.sh)
set -eu

DATA="${1:-data}"
MZML="$DATA/01650b_BA5-TUM_first_pool_75_01_01-3xHCD-1h-R2.mzML"
PEPTIDES="$DATA/peptides.txt"

command -v crux >/dev/null || {
    echo "crux not found on PATH (https://crux.ms)" >&2; exit 1; }
[ -f "$MZML" ] && [ -f "$PEPTIDES" ] || {
    echo "missing $MZML or $PEPTIDES — run scripts/fetch_data.sh first" >&2
    exit 1; }

MZML_ABS=$(cd "$(dirname "$MZML")" && pwd)/$(basename "$MZML")

mkdir -p crux
# peptide sequences -> one-entry-per-peptide FASTA (ref search.sh:3)
cut -f 1 "$PEPTIDES" | tail -n +2 \
    | awk '{print ">" $0; print $0}' > crux/pept.fa
cd crux
crux tide-index --overwrite T --mods-spec 3M+15.9949 pept.fa pept.idx
# absolute path: a relative "../$MZML" breaks for absolute DATA_DIRs
crux tide-search --overwrite T "$MZML_ABS" pept.idx
crux percolator --overwrite T \
    crux-output/tide-search.target.txt crux-output/tide-search.decoy.txt

cat <<EOF
done. rescored PSMs: crux/crux-output/percolator.target.psms.txt
next:
  specpride select clustered.mgf best.mgf --method best \\
      --psms crux/crux-output/percolator.target.psms.txt
EOF
