#!/bin/sh
# Minimal CI for specpride_tpu (survey §5: tests + native sanitizers).
#
#   sh scripts/ci.sh          # full: pytest + ASan/TSan parser suites
#   sh scripts/ci.sh --fast   # pytest only
#
# The Python suite pins JAX to a virtual 8-device CPU mesh via
# tests/conftest.py, so this runs anywhere (no TPU needed).
set -eu
cd "$(dirname "$0")/.."

echo "== static analysis: specpride lint =="
# the project-invariant analyzer (docs/static-analysis.md) must (a)
# still enumerate every checker — deleting one would silently drop its
# invariant from CI — and (b) report ZERO findings beyond the committed
# baseline (lint exits 1 on any new/unjustified finding)
lint_tmp=$(mktemp -d)
python -m specpride_tpu lint --list | tee "$lint_tmp/list.txt"
for check in lane-safety jit-hygiene journal-schema \
        metrics-conformance cli-flags fault-sites; do
    grep -q "^$check " "$lint_tmp/list.txt" || {
        echo "lint checker '$check' missing from --list"; exit 1; }
done
# human-readable pass first so a red build SHOWS its findings (the
# --json run suppresses the per-finding lines), then the JSON gate
python -m specpride_tpu lint
python -m specpride_tpu lint --json "$lint_tmp/lint.json"
python - "$lint_tmp/lint.json" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
assert len(report["checks"]) >= 6, report["checks"]
assert report["summary"]["new"] == 0, report["findings"]
assert report["summary"]["baseline_entries_missing_reason"] == 0
print(f"lint OK: {len(report['checks'])} checkers, "
      f"{report['summary']['baselined']} baselined finding(s)")
EOF
rm -rf "$lint_tmp"

echo "== hygiene: no committed or orphan __pycache__ =="
# bytecode dirs must never land in the index, and a __pycache__ whose
# parent package no longer holds any .py sources is debris from a
# moved/deleted module — stale .pyc files there can shadow imports
if git ls-files | grep -q "__pycache__"; then
    git ls-files | grep "__pycache__"
    echo "__pycache__ artifacts are committed; git rm them"
    exit 1
fi
find specpride_tpu tests -type d -name __pycache__ | while read -r d; do
    if ! ls "$(dirname "$d")"/*.py >/dev/null 2>&1; then
        echo "orphan __pycache__: $d (parent has no .py sources)"
        exit 1
    fi
done
echo "hygiene OK"

echo "== generic lint: ruff (pyflakes-equivalent) =="
# config lives in pyproject.toml ([tool.ruff]); the container may not
# ship ruff — skip with a notice rather than fail on the toolchain
if command -v ruff >/dev/null 2>&1; then
    ruff check specpride_tpu/ tests/
elif python -c "import ruff" >/dev/null 2>&1; then
    python -m ruff check specpride_tpu/ tests/
else
    echo "ruff not installed; skipping generic lint pass"
fi

echo "== pytest =="
python -m pytest tests/ -x -q

echo "== observability: journal + chrome-trace pipeline + specpride stats =="
# one real CLI run must produce a schema-valid journal, metrics file, and
# well-formed Chrome trace; `specpride stats` exits non-zero on any schema
# violation
obs_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$obs_tmp/reps.mgf" \
    --method bin-mean --backend tpu \
    --journal "$obs_tmp/run.jsonl" --metrics-out "$obs_tmp/run.prom" \
    --chrome-trace "$obs_tmp/run.trace.json"
test -s "$obs_tmp/run.prom"
python - "$obs_tmp/run.trace.json" <<'EOF'
import json, sys
trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty traceEvents"
for e in events:
    assert {"ph", "ts", "pid"} <= set(e), f"missing trace keys: {e}"
assert any(e["ph"] == "X" for e in events), "no span slices"
print(f"trace OK: {len(events)} events")
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$obs_tmp/run.jsonl" --json "$obs_tmp/agg.json" --top-spans 5
echo "== observability: specpride trace over a 2-shard .part journal pair =="
cp "$obs_tmp/run.jsonl" "$obs_tmp/multi.jsonl.part00000"
cp "$obs_tmp/run.jsonl" "$obs_tmp/multi.jsonl.part00001"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    trace "$obs_tmp/multi.jsonl" -o "$obs_tmp/multi.trace.json"
python - "$obs_tmp/multi.trace.json" <<'EOF'
import json, sys
events = json.load(open(sys.argv[1]))["traceEvents"]
pids = {e["pid"] for e in events if e["ph"] == "X"}
assert pids == {0, 1}, f"expected both ranks on the timeline, got {pids}"
print("multi-host trace merge OK")
EOF
rm -rf "$obs_tmp"

echo "== pipelined executor: --prefetch 2 parity + pipeline telemetry =="
# the pipelined chunk executor must produce byte-identical output to the
# serial path, and its journal must carry `pipeline` spans plus a
# device_idle_s summary in run_end (docs/performance.md)
pf_tmp=$(mktemp -d)
for P in 0 2; do
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        consensus tests/data/golden_clustered.mgf "$pf_tmp/reps_p$P.mgf" \
        --method bin-mean --backend tpu --prefetch "$P" \
        --checkpoint "$pf_tmp/ck_p$P.json" --checkpoint-every 1 \
        --journal "$pf_tmp/run_p$P.jsonl"
done
cmp "$pf_tmp/reps_p0.mgf" "$pf_tmp/reps_p2.mgf"
python - "$pf_tmp/run_p2.jsonl" <<'EOF'
import json, sys
events = [json.loads(l) for l in open(sys.argv[1])]
spans = [e for e in events if e["event"] == "span"
         and e["name"].startswith("pipeline")]
assert spans, "no pipeline spans in the prefetch journal"
end = [e for e in events if e["event"] == "run_end"][-1]
pipe = end.get("pipeline") or {}
assert "device_idle_s" in pipe, f"run_end missing pipeline.device_idle_s: {end}"
assert end["phases_s"].get("pack", 0) > 0, "packer time not journaled as pack"
print(f"pipeline OK: {len(spans)} pipeline spans, "
      f"device_idle_s={pipe['device_idle_s']}")
EOF
rm -rf "$pf_tmp"

echo "== multi-lane executor: pack-workers x async-write parity matrix =="
# every (pack-workers, async-write) combination must reproduce the serial
# output byte for byte; the journal must carry the per-lane run_end
# summary and prove the commit protocol's order (chunk_done — i.e. the
# MGF append — strictly before that chunk's checkpoint_write)
ln_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$ln_tmp/serial.mgf" \
    --method bin-mean --backend tpu --prefetch 0 \
    --checkpoint "$ln_tmp/serial.ck.json" --checkpoint-every 1
for PW in 0 4; do
    for AW in on off; do
        env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
            consensus tests/data/golden_clustered.mgf \
            "$ln_tmp/reps_pw${PW}_$AW.mgf" \
            --method bin-mean --backend tpu --prefetch 4 \
            --pack-workers "$PW" --async-write "$AW" \
            --checkpoint "$ln_tmp/ck_pw${PW}_$AW.json" --checkpoint-every 1 \
            --journal "$ln_tmp/run_pw${PW}_$AW.jsonl"
        cmp "$ln_tmp/serial.mgf" "$ln_tmp/reps_pw${PW}_$AW.mgf"
        cmp "$ln_tmp/serial.ck.json" "$ln_tmp/ck_pw${PW}_$AW.json"
    done
done
python - "$ln_tmp/run_pw4_on.jsonl" <<'EOF'
import json, sys
events = [json.loads(l) for l in open(sys.argv[1])]
end = [e for e in events if e["event"] == "run_end"][-1]
pipe = end.get("pipeline") or {}
for key in ("prefetch", "pack_workers", "async_write", "device_idle_s",
            "wall_s", "pack_busy_s", "write_busy_s", "reorder_stall_s"):
    assert key in pipe, f"run_end.pipeline missing {key}: {pipe}"
# pack_workers is the EFFECTIVE pool size (clamped to the chunk count)
# and must match the per-worker busy list
assert 1 <= pipe["pack_workers"] <= 4, pipe
assert len(pipe["pack_busy_s"]) == pipe["pack_workers"], pipe
assert pipe["async_write"] is True, pipe
names = {e["name"] for e in events if e["event"] == "span"}
assert any(n.startswith("pipeline:pack[") for n in names), names
assert "pipeline:write" in names, names
# commit protocol: chunk i's MGF append (chunk_done) precedes its
# checkpoint_write, and n_done/output_bytes only ever grow
order = [e for e in events if e["event"] in ("chunk_done", "checkpoint_write")]
n_done = out_bytes = 0
for prev, cur in zip([None] + order, order):
    if cur["event"] == "checkpoint_write":
        assert prev is not None and prev["event"] == "chunk_done", \
            "checkpoint_write without a preceding chunk_done"
        assert cur["n_done"] > n_done and cur["output_bytes"] >= out_bytes
        n_done, out_bytes = cur["n_done"], cur["output_bytes"]
print(f"lane matrix OK: {len(order)} commit events, "
      f"pack_busy_s={pipe['pack_busy_s']} write_busy_s={pipe['write_busy_s']}")
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$ln_tmp/run_pw4_on.jsonl" | grep -q reorder_stall_s
rm -rf "$ln_tmp"

echo "== robustness: chaos pass (one injected fault per site, seeded) =="
# the pack-workers x async-write matrix re-runs with one deterministic
# fault per lane site; every run must (a) exit 0, (b) reproduce the
# fault-free serial bytes AND manifest, (c) pair every journaled fault
# with a recovery event (retry/degrade/resume_repair/quarantine)
rb_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$rb_tmp/serial.mgf" \
    --method bin-mean --backend tpu --prefetch 0 \
    --checkpoint "$rb_tmp/serial.ck.json" --checkpoint-every 1
# golden_clustered.mgf holds 3 clusters -> 3 chunks at --checkpoint-every
# 1, so the AFTER offsets stagger the six faults across chunks 1..3
CHAOS="parse:io:1,pack:io:1:1,prepare:io:1:1,dispatch:oom:1:1,write:io:1:1,checkpoint_write:io:1:2"
for PW in 0 4; do
    for AW in on off; do
        env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
            consensus tests/data/golden_clustered.mgf \
            "$rb_tmp/chaos_pw${PW}_$AW.mgf" \
            --method bin-mean --backend tpu --prefetch 4 \
            --pack-workers "$PW" --async-write "$AW" \
            --retries 3 --retry-backoff 0.01 --fault-seed 0 \
            --inject-faults "$CHAOS" \
            --checkpoint "$rb_tmp/chaos_pw${PW}_$AW.ck.json" \
            --checkpoint-every 1 \
            --journal "$rb_tmp/chaos_pw${PW}_$AW.jsonl"
        cmp "$rb_tmp/serial.mgf" "$rb_tmp/chaos_pw${PW}_$AW.mgf"
        cmp "$rb_tmp/serial.ck.json" "$rb_tmp/chaos_pw${PW}_$AW.ck.json"
    done
done
# d2h fires only on a DEVICE layout (the auto bin-mean path is host-side),
# and qc only on a non-fused QC pass (select medoid + --qc-report); one
# run each so all 8 sites are exercised, parity-checked vs its own
# fault-free twin
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$rb_tmp/flat_clean.mgf" \
    --method bin-mean --backend tpu --layout flat --force-device \
    --prefetch 0 --checkpoint "$rb_tmp/flat_clean.ck.json" \
    --checkpoint-every 1
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$rb_tmp/flat_chaos.mgf" \
    --method bin-mean --backend tpu --layout flat --force-device \
    --prefetch 2 --retries 3 --retry-backoff 0.01 \
    --inject-faults "d2h:io:1:1" \
    --checkpoint "$rb_tmp/flat_chaos.ck.json" --checkpoint-every 1 \
    --journal "$rb_tmp/chaos_d2h.jsonl"
cmp "$rb_tmp/flat_clean.mgf" "$rb_tmp/flat_chaos.mgf"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    select tests/data/golden_clustered.mgf "$rb_tmp/qc_clean.mgf" \
    --method medoid --backend tpu --prefetch 2 \
    --qc-report "$rb_tmp/qc_clean.json" \
    --checkpoint "$rb_tmp/qc_clean.ck.json" --checkpoint-every 1
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    select tests/data/golden_clustered.mgf "$rb_tmp/qc_chaos.mgf" \
    --method medoid --backend tpu --prefetch 2 \
    --retries 3 --retry-backoff 0.01 --inject-faults "qc:io:1:1" \
    --qc-report "$rb_tmp/qc_chaos.json" \
    --checkpoint "$rb_tmp/qc_chaos.ck.json" --checkpoint-every 1 \
    --journal "$rb_tmp/chaos_qc.jsonl"
cmp "$rb_tmp/qc_clean.mgf" "$rb_tmp/qc_chaos.mgf"
cmp "$rb_tmp/qc_clean.json" "$rb_tmp/qc_chaos.json"
python - "$rb_tmp"/chaos_*.jsonl <<'EOF'
import json, sys
# the executor's lane sites only: `cas` fires exclusively in elastic
# runs and is exercised (and audited) by the preemption-storm pass
from specpride_tpu.robustness.faults import (
    EXECUTOR_FAULT_SITES as FAULT_SITES,
    audit_fault_recovery,
)
fired = set()
for path in sys.argv[1:]:
    events = [json.loads(l) for l in open(path)]
    faults = [e for e in events if e["event"] == "fault"]
    assert faults, f"{path}: no fault fired (is the plan armed?)"
    unmatched = audit_fault_recovery(events)
    assert not unmatched, f"{path}: unrecovered faults {unmatched}"
    end = [e for e in events if e["event"] == "run_end"][-1]
    rb = end.get("robustness") or {}
    assert rb.get("faults", {}).get("fired_total", 0) == len(faults), rb
    fired |= {e["site"] for e in faults}
missing = set(FAULT_SITES) - fired
assert not missing, f"sites never exercised: {sorted(missing)}"
print(f"chaos OK: all {len(FAULT_SITES)} sites fired and recovered, "
      "outputs byte-identical to fault-free runs")
EOF
# `specpride stats` must render the injection/recovery summary and exit 0
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$rb_tmp/chaos_pw4_on.jsonl" | grep -q "robustness:"
rm -rf "$rb_tmp"

echo "== robustness: elastic chaos pass (2 ranks, one SIGKILLed mid-run) =="
# the elastic scale-out acceptance bar: with 2 elastic ranks and one
# SIGKILLed mid-run (the rank_kill fault kind), the surviving rank must
# (a) observe the lease expiry and reassign the dead rank's uncommitted
# chunks (resuming — not redoing — its committed prefix via the sha256
# manifest), (b) exit 0, and (c) produce a manifest-verified merged
# output + QC report byte-identical to the single-host serial golden;
# every lease_expire must pair with a chunk_reassign in the journal audit
el_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$el_tmp/serial.mgf" \
    --method bin-mean --backend tpu --qc-report "$el_tmp/serial_qc.json"
# victim rank 1 (scan offset 1, ranges of 2 over 3 clusters): commits
# range 1 whole and ONE chunk of range 0, then rank_kill fires at write
# visit 2 — SIGKILL with the range-0 lease still held
el_elastic() { # $1 = rank; rest = extra env as KEY=VAL words
    _rank="$1"; shift
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" "$@" python -m specpride_tpu \
        consensus tests/data/golden_clustered.mgf "$el_tmp/out.mgf" \
        --method bin-mean --backend tpu \
        --elastic "$el_tmp/coord" --process-id "$_rank" \
        --elastic-range 2 --checkpoint-every 1 --elastic-ttl 1 \
        --qc-report "$el_tmp/qc.json" --journal "$el_tmp/j.jsonl"
}
EL_RC=0
el_elastic 1 SPECPRIDE_FAULTS="write:rank_kill:1:2" || EL_RC=$?
test "$EL_RC" -ne 0  # SIGKILL: the victim must NOT exit cleanly
test -f "$el_tmp/coord/done/range_00001.json"
test ! -f "$el_tmp/coord/done/range_00000.json"
# survivor rank 0: reassigns, completes, exits 0
el_elastic 0
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    merge-parts "$el_tmp/out.mgf" --elastic "$el_tmp/coord" \
    --qc-report "$el_tmp/qc.json"
cmp "$el_tmp/serial.mgf" "$el_tmp/out.mgf"
cmp "$el_tmp/serial_qc.json" "$el_tmp/qc.json"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$el_tmp" <<'EOF'
import json, os, sys
from specpride_tpu.parallel.elastic import audit_elastic
from specpride_tpu.robustness.faults import audit_fault_recovery
tmp = sys.argv[1]
victim = [json.loads(l)
          for l in open(os.path.join(tmp, "j.jsonl.part00001"))]
survivor = [json.loads(l)
            for l in open(os.path.join(tmp, "j.jsonl.part00000"))]
kills = [e for e in victim
         if e["event"] == "fault" and e["kind"] == "rank_kill"]
assert kills, "the rank_kill fault never fired (is the plan armed?)"
expires = [e for e in survivor if e["event"] == "lease_expire"]
reassigns = [e for e in survivor if e["event"] == "chunk_reassign"]
assert expires and reassigns, (expires, reassigns)
assert reassigns[0]["from_rank"] == 1 and reassigns[0]["to_rank"] == 0
merged = victim + survivor
assert not audit_elastic(merged), "unpaired lease expiries"
assert not audit_fault_recovery(merged), "unrecovered rank_kill"
# the survivor RESUMED the dead rank's partial range (manifest-trusted
# committed prefix), never redid it from scratch
resumes = [e for e in survivor
           if e["event"] == "resume" and e.get("n_done", 0) > 0]
assert resumes, "survivor restarted the partial range from scratch"
end = [e for e in survivor if e["event"] == "run_end"][-1]
assert end["elastic"]["reassignments"] == 1, end["elastic"]
print("elastic chaos OK: rank 1 SIGKILLed, rank 0 reassigned + resumed "
      "its chunks, merged output + QC byte-identical to serial")
EOF
# `specpride stats` renders the multi-host rank view off the .part shards
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$el_tmp/j.jsonl" | grep -q "ranks: 2 seen"
# merge-parts hardening: a missing middle shard refuses loudly
rm "$el_tmp/out.mgf.part00000"
MP_RC=0
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    merge-parts "$el_tmp/out.mgf" --elastic "$el_tmp/coord" \
    2>"$el_tmp/mp.err" || MP_RC=$?
test "$MP_RC" -ne 0
grep -q "missing \[0\]" "$el_tmp/mp.err"
rm -rf "$el_tmp"

echo "== robustness: elastic tier-2 preemption storm (both coordinator backends) =="
# the tier-2 acceptance bar, on the filesystem AND object-store
# coordinator backends: 2 ranks + 1 fleet-managed warm spare, one rank
# SIGKILLed mid-run (rank_kill), one rank handicapped per chunk
# (rank_slow) with an injected CAS conflict on its first claim.  The
# fleet must spawn the spare (journaled rank_spawn), the dead rank's
# range must be reassigned (lease_expire + chunk_reassign), the slow
# rank must be relieved by a live steal (lease_split + chunk_reassign
# via=lease_split), every fault must audit as recovered, and the
# merged output + QC report must be byte-identical to the single-host
# serial golden.
st_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=".:tests" python - "$st_tmp" <<'EOF'
import sys
import numpy as np
from conftest import make_cluster
from specpride_tpu.io.mgf import write_mgf
rng = np.random.default_rng(99)
cl = [make_cluster(rng, f"cluster-{i}", n_members=3, n_peaks=20)
      for i in range(48)]
write_mgf([s for c in cl for s in c.members], sys.argv[1] + "/in.mgf")
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$st_tmp/in.mgf" "$st_tmp/serial.mgf" \
    --method bin-mean --backend tpu --qc-report "$st_tmp/serial_qc.json"
st_storm() { # $1 = tag; $2 = coordinator spec (dir or URL)
    tag="$1"; spec="$2"; d="$st_tmp/$tag"; mkdir -p "$d"
    st_rank() { # $1 = rank id; rest = env KEY=VAL words
        _r="$1"; shift
        env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" "$@" \
            python -m specpride_tpu \
            consensus "$st_tmp/in.mgf" "$d/out.mgf" \
            --method bin-mean --backend tpu \
            --elastic "$spec" --process-id "$_r" \
            --elastic-range 24 --checkpoint-every 2 --elastic-ttl 1 \
            --elastic-local "$d/local" \
            --qc-report "$d/qc.json" --journal "$d/j.jsonl"
    }
    # rank 1: SIGKILLed at write visit 3; rank 0: 0.5s stall per chunk
    # dispatch plus one injected CAS conflict on its first lease claim
    st_rank 1 SPECPRIDE_FAULTS="write:rank_kill:1:3" & ST_V=$!
    st_rank 0 \
        SPECPRIDE_FAULTS="dispatch:rank_slow:1:0:9999,cas:cas_conflict:1:0" \
        SPECPRIDE_SLOW_S=0.5 & ST_S=$!
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        fleet --ranks 0 --spares 1 --timeout 240 \
        --journal "$d/fleet.jsonl" -- \
        consensus "$st_tmp/in.mgf" "$d/out.mgf" \
        --method bin-mean --backend tpu \
        --elastic "$spec" \
        --elastic-range 24 --checkpoint-every 2 --elastic-ttl 1 \
        --elastic-local "$d/local" \
        --qc-report "$d/qc.json" --journal "$d/j.jsonl" & ST_F=$!
    ST_RC=0; wait $ST_V || ST_RC=$?
    test "$ST_RC" -ne 0  # SIGKILL: the victim must NOT exit cleanly
    wait $ST_S           # the slow rank survives and exits 0
    wait $ST_F           # the fleet exits 0 once every range commits
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        merge-parts "$d/out.mgf" --elastic "$spec" \
        --qc-report "$d/qc.json"
    cmp "$st_tmp/serial.mgf" "$d/out.mgf"
    cmp "$st_tmp/serial_qc.json" "$d/qc.json"
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$d" <<'EOF'
import glob, json, sys
from specpride_tpu.parallel.elastic import audit_elastic
from specpride_tpu.robustness.faults import audit_fault_recovery
d = sys.argv[1]
ev = []
for p in sorted(glob.glob(d + "/j.jsonl.part*")):
    ev += [json.loads(line) for line in open(p)]
fleet = [json.loads(line) for line in open(d + "/fleet.jsonl")]
kinds = {e["kind"] for e in ev if e["event"] == "fault"}
assert {"rank_kill", "rank_slow", "cas_conflict"} <= kinds, kinds
expires = [e for e in ev if e["event"] == "lease_expire"]
splits = [e for e in ev if e["event"] == "lease_split"]
steals = [e for e in ev if e["event"] == "chunk_reassign"
          and e.get("via") == "lease_split"]
spawns = [e for e in fleet if e["event"] == "rank_spawn"]
assert expires, "the SIGKILLed rank's lease never expired"
assert splits and steals, "the slow rank was never relieved by a steal"
assert spawns, "the fleet never warmed its spare"
assert not audit_elastic(ev), "unpaired lease expiries/splits"
assert not audit_fault_recovery(ev), "unrecovered faults"
cas_retries = [e for e in ev if e["event"] == "retry"
               and e.get("site") == "cas"]
assert cas_retries, "the injected CAS conflict left no retry evidence"
print("storm OK: kill reassigned, slow rank split-stolen "
      f"({len(splits)} split(s)), spare spawned, all faults recovered")
EOF
    # the stats rank view renders splits + the pairing audit at exit 0
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        stats "$d/j.jsonl" | grep -q "split(s)"
}
st_storm fs "$st_tmp/fs/coord"
# object-store backend: the in-tree CAS server IS the coordinator — no
# shared directory, conditional-put/ETag all the way down
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    cas-server --url-file "$st_tmp/cas.url" & ST_CAS=$!
for _ in $(seq 50); do test -s "$st_tmp/cas.url" && break; sleep 0.1; done
st_storm objstore "$(cat "$st_tmp/cas.url")"
kill $ST_CAS 2>/dev/null || true
wait $ST_CAS 2>/dev/null || true
rm -rf "$st_tmp"

echo "== warm start: compile-cache + AOT warmup + zero fresh compiles =="
# each method runs twice against ONE fresh --compile-cache dir: the cold
# run pays (and journals) its XLA compiles and seeds the shape manifest;
# the warm rerun AOT-warms from the manifest and must journal ZERO fresh
# compiles (run_end.compile_cache.misses == 0) with byte-identical
# output.  Device layouts pinned so every method compiles real kernels
# on CPU-only hosts.
ws_tmp=$(mktemp -d)
WS_IN=tests/data/golden_clustered.mgf
ws_run() { # $1 = method; $2 = phase; $3 = command; rest = extra flags
    M="$1"; PHASE="$2"; CMD="$3"; shift 3
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        "$CMD" "$WS_IN" "$ws_tmp/${M}_${PHASE}.mgf" \
        --method "$M" --backend tpu \
        --compile-cache "$ws_tmp/cache" \
        --journal "$ws_tmp/${M}_${PHASE}.jsonl" "$@"
}
for PHASE in cold warm; do
    ws_run bin-mean "$PHASE" consensus --layout flat --force-device
    ws_run gap-average "$PHASE" consensus --layout bucketized --force-device
    ws_run medoid "$PHASE" select --layout bucketized
done
for M in bin-mean gap-average medoid; do
    # warmed vs unwarmed byte parity per method
    cmp "$ws_tmp/${M}_cold.mgf" "$ws_tmp/${M}_warm.mgf"
done
python - "$ws_tmp" <<'EOF'
import json, sys, glob, os
tmp = sys.argv[1]
for path in sorted(glob.glob(os.path.join(tmp, "*_cold.jsonl"))):
    events = [json.loads(l) for l in open(path)]
    cc = [e for e in events if e["event"] == "compile_cache"]
    assert cc and cc[0]["enabled"], f"{path}: cache not enabled"
    end = [e for e in events if e["event"] == "run_end"][-1]
    assert end["compile_cache"]["misses"] > 0, \
        f"{path}: cold run compiled nothing — the warm check is vacuous"
for path in sorted(glob.glob(os.path.join(tmp, "*_warm.jsonl"))):
    events = [json.loads(l) for l in open(path)]
    end = [e for e in events if e["event"] == "run_end"][-1]
    assert end["compile_cache"]["misses"] == 0, \
        f"{path}: warm rerun still compiled {end['compile_cache']}"
    warm = [e for e in events if e["event"] == "warmup"]
    assert warm and all(e["cache_hit"] for e in warm), \
        f"{path}: warmup did not hit the cache: {warm}"
print("warm start OK: 3 methods, warm reruns journal 0 fresh compiles")
EOF
# `specpride stats` renders the warmstart line
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$ws_tmp/bin-mean_warm.jsonl" | grep -q "warmstart:"
# `specpride warmup` smoke: pre-populate a FRESH cache from the saved
# manifest, then a first-ever run against it must also journal 0 misses
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    warmup "$ws_tmp/cache/shape_manifest.json" \
    --compile-cache "$ws_tmp/cache2" --journal "$ws_tmp/wu.jsonl"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$WS_IN" "$ws_tmp/first.mgf" \
    --method bin-mean --backend tpu --layout flat --force-device \
    --compile-cache "$ws_tmp/cache2" --warmup off \
    --journal "$ws_tmp/first.jsonl"
cmp "$ws_tmp/bin-mean_cold.mgf" "$ws_tmp/first.mgf"
python - "$ws_tmp" <<'EOF'
import json, sys, os
tmp = sys.argv[1]
wu = [json.loads(l) for l in open(os.path.join(tmp, "wu.jsonl"))]
assert [e for e in wu if e["event"] == "warmup"], "warmup journal empty"
events = [json.loads(l) for l in open(os.path.join(tmp, "first.jsonl"))]
end = [e for e in events if e["event"] == "run_end"][-1]
assert end["compile_cache"]["misses"] == 0, end["compile_cache"]
print("specpride warmup OK: first-ever run after standalone warmup "
      "journals 0 fresh compiles")
EOF
rm -rf "$ws_tmp"

echo "== result cache: off/cold/warm parity + shared tier + exposition =="
# the content-addressed consensus result cache (docs/performance.md):
# per method, a cache-off run is the byte bar; a cold run against a
# fresh tier must recompute (misses == populated, hits == 0) and a warm
# rerun must serve every cluster from the tier (hits > 0, misses == 0)
# — output bytes AND QC report cmp-identical across all three.  Then
# the shared tier: a rank populates the in-tree CAS server through one
# local tier, and a "different host" (fresh local tier, same store URL)
# serves everything as shared hits with the same bytes.
rc_tmp=$(mktemp -d)
RC_IN=tests/data/golden_clustered.mgf
rc_run() { # $1 method; $2 command; $3 phase; rest = cache flags
    M="$1"; CMD="$2"; PHASE="$3"; shift 3
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        "$CMD" "$RC_IN" "$rc_tmp/${M}_${PHASE}.mgf" --method "$M" \
        --qc-report "$rc_tmp/${M}_${PHASE}.qc.json" \
        --journal "$rc_tmp/${M}_${PHASE}.jsonl" "$@"
}
for spec in "bin-mean:consensus" "gap-average:consensus" "medoid:select"; do
    M=${spec%%:*}; CMD=${spec#*:}
    rc_run "$M" "$CMD" off
    rc_run "$M" "$CMD" cold --result-cache "$rc_tmp/tier:64"
    rc_run "$M" "$CMD" warm --result-cache "$rc_tmp/tier:64"
    cmp "$rc_tmp/${M}_off.mgf" "$rc_tmp/${M}_cold.mgf"
    cmp "$rc_tmp/${M}_off.mgf" "$rc_tmp/${M}_warm.mgf"
    cmp "$rc_tmp/${M}_off.qc.json" "$rc_tmp/${M}_cold.qc.json"
    cmp "$rc_tmp/${M}_off.qc.json" "$rc_tmp/${M}_warm.qc.json"
done
python - "$rc_tmp" <<'EOF'
import json, sys
tmp = sys.argv[1]
for m in ("bin-mean", "gap-average", "medoid"):
    def rc_of(phase):
        ev = [json.loads(l) for l in open(f"{tmp}/{m}_{phase}.jsonl")]
        got = [e for e in ev if e["event"] == "result_cache"]
        return got[-1] if got else None
    assert rc_of("off") is None, \
        f"{m}: cache-off journal must stay byte-identical by absence"
    cold, warm = rc_of("cold"), rc_of("warm")
    assert cold["hits"] == 0 and cold["misses"] > 0, (m, cold)
    assert cold["populated"] == cold["misses"], (m, cold)
    assert warm["misses"] == 0 and warm["hits"] == cold["misses"], \
        (m, warm)
print("result cache OK: 3 methods, cold populates, "
      "warm serves every cluster, bytes + QC identical to cache-off")
EOF
# shared tier against the in-tree CAS server
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    cas-server --url-file "$rc_tmp/cas.url" & RC_CAS=$!
for _ in $(seq 50); do test -s "$rc_tmp/cas.url" && break; sleep 0.1; done
RC_URL=$(cat "$rc_tmp/cas.url")
rc_run bin-mean consensus scold \
    --result-cache "$rc_tmp/tierA" --result-store "$RC_URL"
rc_run bin-mean consensus swarm \
    --result-cache "$rc_tmp/tierB" --result-store "$RC_URL"
kill $RC_CAS 2>/dev/null || true
wait $RC_CAS 2>/dev/null || true
cmp "$rc_tmp/bin-mean_off.mgf" "$rc_tmp/bin-mean_swarm.mgf"
cmp "$rc_tmp/bin-mean_off.qc.json" "$rc_tmp/bin-mean_swarm.qc.json"
python - "$rc_tmp" <<'EOF'
import json, sys
tmp = sys.argv[1]
ev = [json.loads(l) for l in open(f"{tmp}/bin-mean_swarm.jsonl")]
rc = [e for e in ev if e["event"] == "result_cache"][-1]
assert rc["misses"] == 0 and rc["hits"] > 0, rc
assert rc["shared_hits"] == rc["hits"], \
    f"fresh local tier: every hit must cross the store, got {rc}"
print(f"shared tier OK: {rc['hits']} hit(s), all via the CAS store")
EOF
# the specpride_result_cache_* families are pre-registered at 0 on a
# fresh telemetry plane and the exposition stays strictly valid once
# the process totals move
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'EOF'
from specpride_tpu.cache import result_cache as rc_mod
from specpride_tpu.observability.exporter import (
    ServeTelemetry, validate_exposition,
)
rc_mod.reset()
t = ServeTelemetry()
text = t.exposition()
assert not validate_exposition(text), validate_exposition(text)
families = (
    "specpride_result_cache_hits_total",
    "specpride_result_cache_misses_total",
    "specpride_result_cache_populated_total",
    "specpride_result_cache_evictions_total",
    "specpride_result_cache_bytes_saved_total",
    "specpride_result_cache_shared_hits_total",
    "specpride_result_cache_corrupt_total",
)
for name in families:
    assert f"{name} 0" in text, f"{name} not pre-registered at 0"
rc_mod._totals.add("hits", 3)
rc_mod._totals.add("bytes_saved", 4096)
text = t.exposition()
assert not validate_exposition(text), validate_exposition(text)
assert "specpride_result_cache_hits_total 3" in text
assert "specpride_result_cache_bytes_saved_total 4096" in text
rc_mod.reset()
print("result-cache exposition OK: 7 families, strict, delta-mirrored")
EOF
# `specpride stats` renders the result-cache line (captured to a file:
# `grep -q` would close the pipe before stats finishes rendering)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$rc_tmp/bin-mean_warm.jsonl" > "$rc_tmp/stats.txt"
grep -q "result-cache:" "$rc_tmp/stats.txt"
rm -rf "$rc_tmp"

echo "== serve: warm-kernel daemon (boot, parity, warm requests, drain) =="
# boot the daemon against a FRESH compile cache — with the live
# telemetry plane armed (/metrics endpoint, SLO objectives, drain-time
# textfile) — run the three methods through it twice (the warm pair of
# second submissions CONCURRENTLY), and assert: byte parity vs one-shot
# CLI runs, warm submissions journal ZERO fresh compiles, a mid-load
# /metrics scrape is strictly format-valid with queue/in-flight/latency
# series, `specpride profile` captures a device trace off the warm
# daemon, `stats` renders the serving summary + `stats --slo` the burn
# table, and SIGTERM drains cleanly (exit 0, schema-valid journal,
# final --metrics-out snapshot on disk)
sv_tmp=$(mktemp -d)
SV_IN=tests/data/golden_clustered.mgf
SOCK="$sv_tmp/serve.sock"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    serve --socket "$SOCK" --compile-cache "$sv_tmp/cache" \
    --journal "$sv_tmp/serve.jsonl" \
    --metrics-port 0 --metrics-out "$sv_tmp/serve.prom" \
    --slo "bin-mean=300,gap-average=300,medoid=0.000001" &
SV_PID=$!
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$SOCK" <<'EOF'
import sys
from specpride_tpu.serve.client import wait_for_socket
assert wait_for_socket(sys.argv[1], timeout=180), "daemon never came up"
EOF
sv_submit() { # $1 = method; $2 = command; $3 = phase
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        submit --socket "$SOCK" -- \
        "$2" "$SV_IN" "$sv_tmp/served_$1_$3.mgf" --method "$1" \
        --journal "$sv_tmp/job_$1_$3.jsonl" > /dev/null
}
# NOTE: no `set --` here — it would clobber the script's own "$1"
# (--fast) that the native section below still reads
for spec in "bin-mean:consensus" "gap-average:consensus" "medoid:select"; do
    M=${spec%%:*}; CMD=${spec#*:}
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        "$CMD" "$SV_IN" "$sv_tmp/cli_$M.mgf" --method "$M"
    sv_submit "$M" "$CMD" cold
    cmp "$sv_tmp/cli_$M.mgf" "$sv_tmp/served_${M}_cold.mgf"
done
# warm second submissions; bin-mean + gap-average submitted CONCURRENTLY
sv_submit bin-mean consensus warm &
SV_J1=$!
sv_submit gap-average consensus warm &
SV_J2=$!
# mid-load /metrics scrape while the warm pair runs: strictly
# format-valid exposition carrying queue / in-flight / latency series
# and live job counters
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$SOCK" <<'EOF'
import sys, urllib.request
from specpride_tpu.serve.client import request
from specpride_tpu.observability.exporter import parse_exposition
status = request(sys.argv[1], {"op": "status"})
url = status["metrics_url"]
text = urllib.request.urlopen(url, timeout=10).read().decode()
samples, problems = parse_exposition(text)
assert not problems, problems
names = {name for name, _ in samples}
for need in ("specpride_serve_queue_depth", "specpride_serve_inflight",
             "specpride_serve_uptime_seconds",
             "specpride_serve_job_wall_seconds_bucket",
             "specpride_serve_job_queue_wait_seconds_bucket",
             "specpride_serve_jobs_done_total",
             "specpride_serve_slo_objective_seconds"):
    assert need in names, f"missing series {need}; have {sorted(names)}"
done = sum(v for (n, _), v in samples.items()
           if n == "specpride_serve_jobs_done_total")
assert done >= 3, f"mid-load scrape saw only {done} done jobs"
print(f"mid-load scrape OK: {len(samples)} series samples, "
      f"{done:.0f} jobs done, exposition strictly valid")
EOF
wait $SV_J1
wait $SV_J2
sv_submit medoid select warm
for M in bin-mean gap-average medoid; do
    cmp "$sv_tmp/cli_$M.mgf" "$sv_tmp/served_${M}_warm.mgf"
done
# on-demand device profiling against the WARM daemon: a bounded
# jax.profiler window with artifacts, no restart — and the warm checks
# after drain prove the next jobs still compiled nothing fresh
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    profile --socket "$SOCK" --seconds 1 --trace-dir "$sv_tmp/prof" \
    > "$sv_tmp/profile.json"
python - "$sv_tmp" <<'EOF'
import json, os, sys
rep = json.load(open(os.path.join(sys.argv[1], "profile.json")))
assert rep["status"] == "profiled", rep
assert rep["artifacts"], "profile produced no device-trace artifacts"
for rel in rep["artifacts"]:
    assert os.path.isfile(os.path.join(rep["trace_dir"], rel)), rel
print(f"profile OK: {len(rep['artifacts'])} artifact(s) in "
      f"{rep['trace_dir']}")
EOF
# one more warm job AFTER the capture: profiling must not have
# disturbed the warm jit caches (asserted with the other warm jobs in
# the post-drain python block below)
sv_submit bin-mean consensus postprof
cmp "$sv_tmp/cli_bin-mean.mgf" "$sv_tmp/served_bin-mean_postprof.mgf"
# the daemon is still LIVE: stats must render the serving summary off
# the (run_end-less) journal, and --slo the per-method burn table
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$sv_tmp/serve.jsonl" | grep -q "serving:"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$sv_tmp/serve.jsonl" --slo | grep -q "slo: method=medoid"
kill -TERM $SV_PID
SV_RC=0; wait $SV_PID || SV_RC=$?
test "$SV_RC" -eq 0
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$sv_tmp" <<'EOF'
import glob, json, os, sys
tmp = sys.argv[1]
# warm submissions journal ZERO fresh compiles, per-job (the serving
# acceptance bar: the daemon's whole point is warm-request latency)
for path in sorted(glob.glob(os.path.join(tmp, "job_*_warm.jsonl"))):
    events = [json.loads(l) for l in open(path)]
    end = [e for e in events if e["event"] == "run_end"][-1]
    assert end["compile_cache"]["misses"] == 0, \
        f"{path}: warm served job still compiled {end['compile_cache']}"
serve = [json.loads(l) for l in open(os.path.join(tmp, "serve.jsonl"))]
jd = [e for e in serve if e["event"] == "job_done"]
assert len(jd) == 7 and all(e["status"] == "done" for e in jd), jd
warm = [e for e in jd[3:]]
assert all(e["fresh_compiles"] == 0 for e in warm), warm
# SLO evaluations rode every job_done (medoid's impossible objective
# burned; the 300s ones did not)
assert all("slo_ok" in e for e in jd), jd
assert all(e["slo_ok"] is False for e in jd if e["method"] == "medoid")
assert all(e["slo_ok"] is True for e in jd if e["method"] != "medoid")
# SIGTERM drained cleanly: journal complete and schema-valid, and the
# profile capture journaled its window
from specpride_tpu.observability.journal import read_events
events, violations = read_events(os.path.join(tmp, "serve.jsonl"))
assert not violations, violations
names = [e["event"] for e in events]
assert "serve_drain" in names and names[-1] == "run_end", names[-6:]
assert "profile_start" in names and "profile_done" in names
# the drain-time --metrics-out snapshot: strictly valid exposition whose
# totals equal the journal-derived serving summary
from specpride_tpu.observability.exporter import parse_exposition
final_text = open(os.path.join(tmp, "serve.prom")).read()
samples, problems = parse_exposition(final_text)
assert not problems, problems
done = sum(v for (n, _), v in samples.items()
           if n == "specpride_serve_jobs_done_total")
assert done == len(jd), (done, len(jd))
breaches = sum(v for (n, _), v in samples.items()
               if n == "specpride_serve_slo_breaches_total")
assert breaches == sum(1 for e in jd if not e["slo_ok"]), breaches
print("serve OK: 7 served jobs byte-identical to CLI, warm jobs 0 fresh "
      "compiles, live scrape + profile + SLO burn + drain snapshot, "
      "clean SIGTERM drain")
EOF
rm -rf "$sv_tmp"

echo "== serve: worker pool (--workers 2 --quota, two-tenant concurrent batch) =="
# boot a 2-lane daemon with per-tenant quotas (unequal weights), run a
# CONCURRENT batch from two tenants, then two more jobs right before
# SIGTERM, and assert: every served output is byte-identical to the
# one-shot CLI's, the interleaved daemon journal has no torn/invalid
# lines and attributes every job to a worker lane (both lanes served),
# and the drain commits all in-flight jobs from BOTH workers before
# exiting 0
wp_tmp=$(mktemp -d)
WP_IN=tests/data/golden_clustered.mgf
WPSOCK="$wp_tmp/serve.sock"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    serve --socket "$WPSOCK" --compile-cache "$wp_tmp/cache" \
    --journal "$wp_tmp/serve.jsonl" --workers 2 --max-queue 32 \
    --quota "tenantA=3:8,tenantB=1:8" &
WP_PID=$!
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$WPSOCK" <<'EOF'
import sys
from specpride_tpu.serve.client import wait_for_socket
assert wait_for_socket(sys.argv[1], timeout=180), "pool daemon never came up"
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$WP_IN" "$wp_tmp/cli.mgf" --method bin-mean
wp_submit() { # $1 = tenant; $2 = tag
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        submit --socket "$WPSOCK" --client "$1" -- \
        consensus "$WP_IN" "$wp_tmp/$2.mgf" --method bin-mean \
        > "$wp_tmp/$2.json"
}
# the concurrent two-tenant batch: 3 tenantA jobs vs 2 tenantB jobs
wp_submit tenantA a1 & WP_P1=$!
wp_submit tenantA a2 & WP_P2=$!
wp_submit tenantA a3 & WP_P3=$!
wp_submit tenantB b1 & WP_P4=$!
wp_submit tenantB b2 & WP_P5=$!
wait $WP_P1 && wait $WP_P2 && wait $WP_P3 && wait $WP_P4 && wait $WP_P5
for J in a1 a2 a3 b1 b2; do
    cmp "$wp_tmp/cli.mgf" "$wp_tmp/$J.mgf"
done
# two in-flight jobs, then SIGTERM: the drain must commit BOTH lanes'
# work before exit 0 (jobs either finished or were retriably rejected
# at admission — never torn output)
wp_submit tenantA d1 & WP_D1=$!
wp_submit tenantB d2 & WP_D2=$!
sleep 0.7
kill -TERM $WP_PID
WP_RC=0; wait $WP_PID || WP_RC=$?
test "$WP_RC" -eq 0
WP_D1_RC=0; wait $WP_D1 || WP_D1_RC=$?
WP_D2_RC=0; wait $WP_D2 || WP_D2_RC=$?
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - \
    "$wp_tmp" "$WP_D1_RC" "$WP_D2_RC" <<'EOF'
import json, os, sys
tmp, d1_rc, d2_rc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
from specpride_tpu.observability.journal import read_events
# interleaved concurrent-lane journal: every line whole and schema-valid
events, violations = read_events(os.path.join(tmp, "serve.jsonl"))
assert not violations, violations
names = [e["event"] for e in events]
assert "serve_drain" in names and names[-1] == "run_end", names[-6:]
serve_ev = next(e for e in events if e["event"] == "serve_start")
assert serve_ev["workers"] == 2, serve_ev
assert serve_ev.get("quota"), "quotas must be journaled at boot"
done = [e for e in events if e["event"] == "job_done"]
assert all(e["status"] == "done" for e in done), done
# every job is attributed to a lane, and BOTH lanes served the batch
workers = sorted({e["worker"] for e in done})
assert workers == [0, 1], f"expected both lanes to serve, got {workers}"
golden = open(os.path.join(tmp, "cli.mgf"), "rb").read()
# the drain-time pair: exit 0 => the job ran to commit (byte parity);
# exit 75 => rejected retriable at admission (daemon was draining)
for tag, rc in (("d1", d1_rc), ("d2", d2_rc)):
    if rc == 0:
        got = open(os.path.join(tmp, f"{tag}.mgf"), "rb").read()
        assert got == golden, f"{tag}: drained output diverged"
    else:
        assert rc == 75, f"{tag}: expected done(0) or retriable(75), got {rc}"
n_done = len(done)
n_rej = sum(1 for e in events if e["event"] == "job_rejected")
assert n_done + n_rej >= 6, (n_done, n_rej)
print(f"worker pool OK: {n_done} jobs byte-identical across 2 lanes "
      f"({n_rej} drain/quota rejections), clean SIGTERM drain")
EOF
rm -rf "$wp_tmp"

echo "== serve: cross-job micro-batching (--batch-window 25, shared dispatch) =="
# boot a 2-lane daemon with the 25ms batch window and the telemetry
# plane armed, fire a 6-job two-tenant burst of same-method small jobs
# (one python process, six threads — they arrive together, so the
# window coalesces them), and assert: >= 1 batch_dispatch journaled
# with jobs >= 2, EVERY job's output + QC byte-identical to the solo
# CLI run, the batch metrics on the drain snapshot pass the strict
# exposition check, and `stats` renders the batching: line
mb_tmp=$(mktemp -d)
MB_IN=tests/data/golden_clustered.mgf
MBSOCK="$mb_tmp/serve.sock"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    serve --socket "$MBSOCK" --compile-cache "$mb_tmp/cache" \
    --journal "$mb_tmp/serve.jsonl" --workers 2 --max-queue 32 \
    --batch-window 25 --metrics-port 0 --metrics-out "$mb_tmp/serve.prom" &
MB_PID=$!
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$MBSOCK" <<'EOF'
import sys
from specpride_tpu.serve.client import wait_for_socket
assert wait_for_socket(sys.argv[1], timeout=180), "batch daemon never came up"
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$MB_IN" "$mb_tmp/cli.mgf" --method bin-mean \
    --qc-report "$mb_tmp/cli.qc.json"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - \
    "$MBSOCK" "$MB_IN" "$mb_tmp" <<'EOF'
import sys, threading
from specpride_tpu.serve import client as sc
sock, src, tmp = sys.argv[1:4]
terms = {}
def submit(i):
    tenant = "tenantA" if i % 2 == 0 else "tenantB"
    terms[i] = sc.submit_wait(
        sock,
        ["consensus", src, f"{tmp}/burst_{i}.mgf", "--method", "bin-mean",
         "--qc-report", f"{tmp}/burst_{i}.qc.json"],
        client=tenant, timeout=600,
    )
threads = [threading.Thread(target=submit, args=(i,)) for i in range(6)]
for t in threads: t.start()
for t in threads: t.join()
bad = {i: t for i, t in terms.items() if t.get("status") != "done"}
assert not bad, bad
batched = [t for t in terms.values() if t.get("batch")]
print(f"burst OK: 6 jobs done, {len(batched)} rode a shared dispatch")
EOF
kill -TERM $MB_PID
MB_RC=0; wait $MB_PID || MB_RC=$?
test "$MB_RC" -eq 0
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$mb_tmp" <<'EOF'
import json, os, sys
tmp = sys.argv[1]
from specpride_tpu.observability.journal import read_events
events, violations = read_events(os.path.join(tmp, "serve.jsonl"))
assert not violations, violations
shared = [e for e in events if e["event"] == "batch_dispatch"
          and e.get("status") == "shared"]
assert shared, "the 6-job burst must coalesce at least one shared dispatch"
assert any(e["n_jobs"] >= 2 for e in shared), shared
done = [e for e in events if e["event"] == "job_done"]
assert len(done) == 6 and all(e["status"] == "done" for e in done), done
# byte + QC parity for EVERY burst job vs the solo CLI run
golden = open(os.path.join(tmp, "cli.mgf"), "rb").read()
golden_qc = json.load(open(os.path.join(tmp, "cli.qc.json")))
for i in range(6):
    got = open(os.path.join(tmp, f"burst_{i}.mgf"), "rb").read()
    assert got == golden, f"burst_{i}: batched output diverged from solo CLI"
    qc = json.load(open(os.path.join(tmp, f"burst_{i}.qc.json")))
    assert qc == golden_qc, f"burst_{i}: batched QC report diverged"
# strict exposition check on the drain snapshot, batch series included
from specpride_tpu.observability.exporter import parse_exposition
text = open(os.path.join(tmp, "serve.prom")).read()
samples, problems = parse_exposition(text)
assert not problems, problems
names = {name for name, _ in samples}
for need in ("specpride_serve_batch_dispatches_total",
             "specpride_serve_batch_jobs_total",
             "specpride_serve_batch_clusters_total",
             "specpride_serve_batch_occupancy",
             "specpride_serve_batch_jobs_per_dispatch_bucket",
             "specpride_serve_batch_window_wait_seconds_bucket"):
    assert need in names, f"missing batch series {need}"
n_disp = samples[("specpride_serve_batch_dispatches_total", ())]
n_batched = samples[("specpride_serve_batch_jobs_total", ())]
assert n_disp == len(shared), (n_disp, len(shared))
assert n_batched == sum(e["n_jobs"] for e in shared), n_batched
print(f"micro-batching OK: {len(shared)} shared dispatch(es) covering "
      f"{int(n_batched)} of 6 jobs, byte+QC parity for all, "
      "batch metrics strictly valid")
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$mb_tmp/serve.jsonl" | grep -q "batching:"
rm -rf "$mb_tmp"

echo "== distributed tracing: trace-context plane end to end =="
# the v4 acceptance bar: ONE `specpride trace --job` invocation over a
# batched served job AND a 2-rank elastic run (joined to the same
# trace via the SPECPRIDE_TRACE env handoff) yields a single
# schema-valid Perfetto trace whose spans cover client submit, daemon
# queue/dispatch, the shared batch dispatch, and rank-side chunk
# commits on one clock-anchored axis, with flow arrows across process
# tracks; every job_done's trace_id resolves; the latency histograms
# carry trace exemplars (strict validator); the rotating daemon
# journal reads across segments; /healthz answers ok; and tracing
# on/off outputs are byte-identical
dt_tmp=$(mktemp -d)
DT_IN=tests/data/golden_clustered.mgf
DTSOCK="$dt_tmp/serve.sock"
# tracing on vs off: byte-identical outputs (the causal envelope is
# observability-only)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$DT_IN" "$dt_tmp/plain.mgf" --method bin-mean
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$DT_IN" "$dt_tmp/traced.mgf" --method bin-mean \
    --journal "$dt_tmp/traced.jsonl"
cmp "$dt_tmp/plain.mgf" "$dt_tmp/traced.mgf"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    serve --socket "$DTSOCK" --compile-cache "$dt_tmp/cache" \
    --journal "$dt_tmp/serve.jsonl" --journal-rotate-mb 0.01 \
    --workers 2 --max-queue 32 --batch-window 25 \
    --watchdog-timeout 120 --metrics-port 0 \
    --metrics-out "$dt_tmp/serve.prom" &
DT_PID=$!
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$DTSOCK" "$dt_tmp" <<'EOF'
import json, sys, threading, urllib.request
from specpride_tpu.serve import client as sc
sock, tmp = sys.argv[1:3]
assert sc.wait_for_socket(sock, timeout=180), "trace daemon never came up"
# /healthz: a real readiness probe now (200 ok while lanes are healthy)
status = sc.request(sock, {"op": "status"})
url = status["metrics_url"].replace("/metrics", "/healthz")
with urllib.request.urlopen(url, timeout=10) as resp:
    body = resp.read().decode()
    assert resp.status == 200 and body.startswith("ok"), (resp.status, body)
# two-tenant 6-job burst: each submit writes its CLIENT journal shard
# and every job its own job journal — the trace merger's inputs
src = "tests/data/golden_clustered.mgf"
terms = {}
def submit(i):
    tenant = "tenantA" if i % 2 == 0 else "tenantB"
    terms[i] = sc.submit_wait(
        sock,
        ["consensus", src, f"{tmp}/burst_{i}.mgf", "--method",
         "bin-mean", "--journal", f"{tmp}/job_{i}.jsonl"],
        client=tenant, timeout=600, journal=f"{tmp}/client_{i}.jsonl",
    )
threads = [threading.Thread(target=submit, args=(i,)) for i in range(6)]
for t in threads: t.start()
for t in threads: t.join()
bad = {i: t for i, t in terms.items() if t.get("status") != "done"}
assert not bad, bad
assert all(t.get("trace_id") for t in terms.values()), terms
batched = {i: t for i, t in terms.items() if t.get("batch")}
assert batched, "the 6-job burst must coalesce at least one batch"
lead = min(batched)
json.dump({"job_id": terms[lead]["job_id"],
           "trace_id": terms[lead]["trace_id"]},
          open(f"{tmp}/lead.json", "w"))
print(f"burst OK: 6 traced jobs, {len(batched)} batched, "
      f"lead job {terms[lead]['job_id']}")
EOF
# a healthy 2-rank elastic run JOINED to the served job's trace via the
# SPECPRIDE_TRACE env handoff (the fleet-supervisor hop, exercised
# directly): both ranks' journals then carry the same trace_id
DT_TRACE=$(env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -c "
import json,sys; print(json.load(open(sys.argv[1]))['trace_id'])
" "$dt_tmp/lead.json")
dt_rank() {
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
        SPECPRIDE_TRACE="$DT_TRACE:ffffffffffffffff" \
        python -m specpride_tpu \
        consensus "$DT_IN" "$dt_tmp/el.mgf" --method bin-mean \
        --backend tpu --elastic "$dt_tmp/coord" --process-id "$1" \
        --elastic-range 2 --checkpoint-every 1 \
        --journal "$dt_tmp/el.jsonl"
}
dt_rank 0 & DT_R0=$!
dt_rank 1 & DT_R1=$!
wait $DT_R0; wait $DT_R1
kill -TERM $DT_PID
DT_RC=0; wait $DT_PID || DT_RC=$?
test "$DT_RC" -eq 0
# ONE trace --job invocation over daemon + client + job + rank shards
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$dt_tmp" <<'EOF'
import glob, json, os, subprocess, sys
tmp = sys.argv[1]
lead = json.load(open(os.path.join(tmp, "lead.json")))
shards = ([os.path.join(tmp, "serve.jsonl"),
           os.path.join(tmp, "el.jsonl")]
          + sorted(glob.glob(os.path.join(tmp, "client_*.jsonl")))
          + sorted(glob.glob(os.path.join(tmp, "job_*.jsonl"))))
out = os.path.join(tmp, "causal.json")
subprocess.run(
    [sys.executable, "-m", "specpride_tpu", "trace",
     *shards, "--job", str(lead["job_id"]), "-o", out],
    check=True,
)
trace = json.load(open(out))
evs = trace["traceEvents"]
# schema-valid Perfetto: every non-meta event has ph/ts/pid
for e in evs:
    assert "ph" in e and "pid" in e and ("ts" in e or e["ph"] == "M"), e
spans = [e for e in evs if e.get("ph") == "X"]
names = {e["name"] for e in spans}
pids = {e["pid"] for e in spans}
assert len(pids) >= 3, f"expected >=3 process tracks, got {pids}"
for need in ("submit", "serve:queue", "serve:job", "serve:batch",
             "chunk", "checkpoint_write"):
    assert need in names, f"span {need!r} missing from {sorted(names)}"
# flow arrows connect client -> worker -> batch across tracks
flows = [e for e in evs if e.get("cat") == "flow"]
assert flows and {f["ph"] for f in flows} >= {"s", "f"}, flows
by_name_pid = {}
for e in spans:
    by_name_pid.setdefault(e["name"], set()).add(e["pid"])
assert by_name_pid["submit"] != by_name_pid["serve:job"], \
    "client and daemon spans must live on different tracks"
# the elastic ranks joined the SAME trace (env handoff): their chunk
# commits render on their own tracks in this one file
assert by_name_pid["chunk"] - by_name_pid["serve:job"], \
    "rank-side chunk spans must appear on rank tracks"
meta_names = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
assert any(n.startswith("el.jsonl.part") for n in meta_names), \
    f"no elastic rank shard contributed to the trace: {meta_names}"
# every job_done's trace_id resolves through the merger
from specpride_tpu.observability import traceplane
from specpride_tpu.observability.journal import expand_parts, read_events
serve_files, _ = expand_parts(os.path.join(tmp, "serve.jsonl"))
assert len(serve_files) > 1, "the rotating daemon journal never rotated"
done = [e for f in serve_files for e in read_events(f)[0]
        if e["event"] == "job_done"]
assert len(done) == 6, [e.get("job_id") for e in done]
for e in done:
    tid = traceplane.resolve_job_trace(serve_files, e["job_id"])
    assert tid == e["trace_id"], (e["job_id"], tid)
# exemplars on the drain snapshot: strict validator + presence
from specpride_tpu.observability.exporter import parse_exposition_full
text = open(os.path.join(tmp, "serve.prom")).read()
samples, exemplars, problems = parse_exposition_full(text)
assert not problems, problems
ex_names = {name for name, _ in exemplars}
assert any(n.startswith("specpride_serve_job_wall_seconds_bucket")
           for n in ex_names), ex_names
assert all("trace_id" in ex for ex in exemplars.values()), exemplars
print(f"distributed trace OK: {len(spans)} spans on {len(pids)} "
      f"tracks, {len(flows)} flow events, 6/6 job traces resolvable, "
      f"{len(serve_files)} journal segments, exemplars strict-valid")
EOF
# the critical-path view renders off the same shards
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$dt_tmp/serve.jsonl" $dt_tmp/client_*.jsonl \
    --trace "$DT_TRACE" | grep -q "critical path"
# elastic byte parity under tracing: merged output == the plain run
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    merge-parts "$dt_tmp/el.mgf" --elastic "$dt_tmp/coord"
cmp "$dt_tmp/plain.mgf" "$dt_tmp/el.mgf"
rm -rf "$dt_tmp"

echo "== memory bandwidth: --precision byte ratios + QC gate + --no-donate parity =="
# per method: the bf16 run must exit 0 with the QC-cosine gate green
# (run_end.precision.ok) and journaled h2d_bytes <= 0.55x its f32 run's;
# int8 on the flat bin-mean path must reach <= 0.35x.  The workload's
# m/z is snapped to the bf16 grid so the pack-time exactness probe
# ships bf16 m/z (real noisy data falls back to f32 m/z, documented).
bw_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$bw_tmp/in.mgf" <<'EOF'
import sys

import ml_dtypes
import numpy as np

from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.io.mgf import write_mgf

rng = np.random.default_rng(29)
clusters = []
for i in range(48):
    m = int(rng.integers(3, 7))
    base = np.sort(rng.uniform(150, 1500, 90))
    members = []
    for k in range(m):
        mz = (base + rng.normal(0, 0.002, 90)).astype(np.float32)
        # bf16-exact m/z: the grid the pack-time probe verifies
        mz = np.sort(mz.astype(ml_dtypes.bfloat16).astype(np.float64))
        members.append(Spectrum(
            mz=mz, intensity=rng.uniform(1, 1e4, 90),
            precursor_mz=420.0, precursor_charge=2, rt=1.0,
            title=f"b{i:03d};s{k}",
        ))
    clusters.append(Cluster(f"b{i:03d}", members))
write_mgf([s for c in clusters for s in c.members], sys.argv[1])
EOF
bw_run() {  # bw_run TAG COMMAND METHOD PRECISION FLAGS...
    tag=$1; cmd=$2; method=$3; prec=$4; shift 4
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        "$cmd" "$bw_tmp/in.mgf" "$bw_tmp/$tag.mgf" --method "$method" \
        --precision "$prec" --journal "$bw_tmp/$tag.jsonl" "$@"
}
bw_run bin_f32  consensus bin-mean    f32  --layout flat
bw_run bin_bf16 consensus bin-mean    bf16 --layout flat
bw_run bin_int8 consensus bin-mean    int8 --layout flat
bw_run gap_f32  consensus gap-average f32  --layout bucketized --force-device
bw_run gap_bf16 consensus gap-average bf16 --layout bucketized --force-device
bw_run med_f32  select    medoid      f32  --layout bucketized
bw_run med_bf16 select    medoid      bf16 --layout bucketized
python - "$bw_tmp" <<'EOF'
import json, sys

tmp = sys.argv[1]

def end(tag):
    evs = [json.loads(l) for l in open(f"{tmp}/{tag}.jsonl")]
    return [e for e in evs if e["event"] == "run_end"][-1]

for pair, bound in (
    (("bin_f32", "bin_bf16"), 0.55),
    (("bin_f32", "bin_int8"), 0.35),
    (("gap_f32", "gap_bf16"), 0.55),
    (("med_f32", "med_bf16"), 0.55),
):
    f32, red = (end(t) for t in pair)
    a, b = f32["device"]["bytes_h2d"], red["device"]["bytes_h2d"]
    assert b <= bound * a, (pair, a, b, bound)
    p = red["precision"]
    assert p["ok"] and p["min_cosine"] >= p["tolerance"], (pair, p)
    print(f"{pair[1]}: h2d {b}B vs f32 {a}B = {b/a:.3f}x "
          f"(bound {bound}), gate min_cosine={p['min_cosine']}")
# medoid integer narrowing is exact: reduced output byte-identical
assert open(f"{tmp}/med_f32.mgf", "rb").read() == \
    open(f"{tmp}/med_bf16.mgf", "rb").read(), "medoid i16 not exact"
print("precision pass OK")
EOF
# stats renders the bandwidth + precision lines off the reduced journal
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$bw_tmp/bin_bf16.jsonl" | grep -q "bandwidth:"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$bw_tmp/bin_bf16.jsonl" | grep -q "precision=bf16"
# --no-donate parity pair (donation may never change bytes), with the
# double-buffered H2D lane armed on the donating side
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$bw_tmp/in.mgf" "$bw_tmp/don.mgf" --method bin-mean \
    --layout flat --h2d-buffer 2 \
    --checkpoint "$bw_tmp/don.ck" --checkpoint-every 12
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$bw_tmp/in.mgf" "$bw_tmp/nodon.mgf" --method bin-mean \
    --layout flat --no-donate \
    --checkpoint "$bw_tmp/nodon.ck" --checkpoint-every 12
cmp "$bw_tmp/don.mgf" "$bw_tmp/nodon.mgf"
echo "donation parity OK"
rm -rf "$bw_tmp"

echo "== autotune: closed-loop controller (serve on + elastic observe + replay) =="
# (a) serve --autotune on with a tight batch-window clamp: a 1-lane
# daemon under a 12-job concurrent burst must journal >=1 ACTED
# batch_window_ms decision carrying its full evidence payload (signal
# snapshot, params, clock, trace exemplars), every served output must
# stay byte-identical to the one-shot CLI, and `specpride
# autotune-replay` must reproduce every decision from the journal
# alone.  Two timing rules keep the deep-queue sample deterministic:
# the burst is one driver process with a thread per client (separate
# `specpride submit` processes would serialize on interpreter startup
# and trickle in), and it runs COLD — the first job's compile wall
# pins the single lane while the other 11 stack behind it, so the
# 0.1s controller ticks reliably observe depth >= queue_hi (a warm
# burst of these tiny jobs drains in ~20ms, between two ticks).
at_tmp=$(mktemp -d)
AT_IN=tests/data/golden_clustered.mgf
AT_SOCK="$at_tmp/serve.sock"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    serve --socket "$AT_SOCK" --compile-cache "$at_tmp/cache" \
    --journal "$at_tmp/serve.jsonl" --workers 1 --max-queue 32 \
    --autotune on --autotune-interval 0.1 \
    --autotune-batch-window 5:25 &
AT_PID=$!
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$AT_IN" "$at_tmp/cli.mgf" --method bin-mean
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - \
    "$AT_SOCK" "$AT_IN" "$at_tmp" <<'EOF'
import sys
import threading

from specpride_tpu.serve import client as sc

sock, src, tmp = sys.argv[1:4]
assert sc.wait_for_socket(sock, timeout=180), "daemon never came up"


def job(tag, client):
    term = sc.submit_wait(
        sock,
        ["consensus", src, f"{tmp}/served_{tag}.mgf",
         "--method", "bin-mean"],
        timeout=600, client=client,
    )
    assert term.get("status") == "done", term


errs = []


def run(i):
    try:
        job(str(i), f"burst-{i % 4}")
    except Exception as e:  # surfaced after join
        errs.append(repr(e))


threads = [threading.Thread(target=run, args=(i,)) for i in range(12)]
for t in threads:
    t.start()
for t in threads:
    t.join()
assert not errs, errs[:3]
EOF
for i in 0 1 2 3 4 5 6 7 8 9 10 11; do
    cmp "$at_tmp/cli.mgf" "$at_tmp/served_$i.mgf"
done
# stats renders the controller's state off the LIVE (run_end-less)
# journal: summary line plus the per-decision log under --autotune
AT_STATS=$(env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m \
    specpride_tpu stats "$at_tmp/serve.jsonl" --autotune)
printf '%s\n' "$AT_STATS" | grep -q "autotune: mode=on" || {
    printf '%s\n' "$AT_STATS"
    echo "FAIL: stats did not render the live autotune summary"
    exit 1
}
kill -TERM $AT_PID
AT_RC=0; wait $AT_PID || AT_RC=$?
test "$AT_RC" -eq 0
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$at_tmp" <<'EOF'
import os, sys
from specpride_tpu.observability.journal import read_events
tmp = sys.argv[1]
events, violations = read_events(os.path.join(tmp, "serve.jsonl"))
assert not violations, violations
at = [e for e in events if e["event"] == "autotune"]
assert at and all(e["knob"] == "batch_window_ms" for e in at), at
acted = [e for e in at if e["acted"]]
assert acted, "the burst never produced an acted decision"
widen = [e for e in acted if e["new"] > e["old"]]
assert widen, f"no widen decision under a depth-12 burst: {at}"
for e in at:  # the evidence contract: every decision self-describes
    assert e["mode"] == "on" and e["reason"], e
    assert e["signal"]["now"] == e["clock"], e
    assert (e["params"]["lo_ms"], e["params"]["hi_ms"]) == (5.0, 25.0)
    assert 5.0 <= e["new"] <= 25.0, e
    assert isinstance(e["trace_ids"], list), e
w = widen[0]
assert w["signal"]["queue_depth"] >= w["params"]["queue_hi"], w
print(f"serve autotune OK: {len(at)} decision(s), {len(acted)} acted, "
      f"first widen at queue depth {w['signal']['queue_depth']}, "
      "12 served outputs byte-identical to CLI")
EOF
# (b) elastic 2-rank observe run: the rank controllers must journal
# >=1 would-be elastic_range decision WITHOUT acting (observe never
# touches the split hint), and the merged output must stay
# byte-identical to the serial run of the same input
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$at_tmp/el_in.mgf" <<'EOF'
import sys

import numpy as np

from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.io.mgf import write_mgf

# enough clusters (checkpoint-every 1 => one heartbeat each) that the
# post-compile phase spans several 1s controller ticks even on a
# contended 1-core runner
rng = np.random.default_rng(18)
clusters = []
for i in range(48):
    members = []
    for k in range(int(rng.integers(4, 7))):
        mz = np.sort(rng.uniform(150, 1500, 150))
        members.append(Spectrum(
            mz=mz, intensity=rng.uniform(1, 1e4, 150),
            precursor_mz=420.0, precursor_charge=2, rt=1.0,
            title=f"e{i:03d};s{k}",
        ))
    clusters.append(Cluster(f"e{i:03d}", members))
write_mgf([s for c in clusters for s in c.members], sys.argv[1])
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$at_tmp/el_in.mgf" "$at_tmp/el_serial.mgf" \
    --method bin-mean --backend tpu
at_elastic() { # $1 = rank
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        consensus "$at_tmp/el_in.mgf" "$at_tmp/el.mgf" \
        --method bin-mean --backend tpu \
        --elastic "$at_tmp/coord" --process-id "$1" \
        --elastic-range 4 --checkpoint-every 1 --elastic-ttl 2 \
        --journal "$at_tmp/el.jsonl" --autotune observe
}
at_elastic 0 &
AT_R0=$!
at_elastic 1 &
AT_R1=$!
wait $AT_R0
wait $AT_R1
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    merge-parts "$at_tmp/el.mgf" --elastic "$at_tmp/coord"
cmp "$at_tmp/el_serial.mgf" "$at_tmp/el.mgf"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$at_tmp" <<'EOF'
import json, os, sys
tmp = sys.argv[1]
at = []
for rank in (0, 1):
    shard = os.path.join(tmp, f"el.jsonl.part{rank:05d}")
    events = [json.loads(l) for l in open(shard)]
    at += [e for e in events if e["event"] == "autotune"]
assert at, "no rank journaled a would-be elastic_range decision"
assert all(e["knob"] == "elastic_range" for e in at), at
assert all(e["mode"] == "observe" for e in at), at
assert all(e["acted"] is False for e in at), \
    f"observe mode must never act: {at}"
assert all("chunk_s_mean" in e["signal"]["heartbeats"] for e in at), at
print(f"elastic observe OK: {len(at)} would-be decision(s) journaled, "
      "none acted, merged output byte-identical to serial")
EOF
# (c) the determinism audit: replay must reproduce every decision in
# both journals exactly (exit 0)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    autotune-replay "$at_tmp/serve.jsonl"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    autotune-replay "$at_tmp/el.jsonl"
rm -rf "$at_tmp"

echo "== flight recorder: incident plane (armed daemon, bundles + replay) =="
# the v6 acceptance bar: a 2-lane daemon with the flight recorder ARMED
# (--flightrec on + --incident-dir), an impossible bin-mean SLO
# objective, and a 1.5s lane watchdog serves a 3-job breach streak
# (slo_breach fires at the third consecutive job_done breach) plus one
# job carrying an injected dispatch hang that wedges its serve:job lane
# past the daemon watchdog (watchdog fires).  Assert: exactly those two
# v6 `incident` events land in the journal, each with an atomic on-disk
# bundle (manifest schema 1, ring holds the trigger record, no .tmp-
# staging debris), every served output stays byte-identical to the
# one-shot CLI, `specpride incident-replay` re-derives both incidents
# bit-exact (exit 0), the incidents list/show/export read side works,
# `stats --incidents` renders the plane off the LIVE journal, and the
# drain metrics snapshot carries the per-detector incident counters.
# The compile cache is pre-seeded by the CLI run so warm serve:job
# sections never trip the daemon watchdog on their own.
fr_tmp=$(mktemp -d)
FR_IN=tests/data/golden_clustered.mgf
FRSOCK="$fr_tmp/serve.sock"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus "$FR_IN" "$fr_tmp/cli.mgf" --method bin-mean \
    --compile-cache "$fr_tmp/cache"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    serve --socket "$FRSOCK" --compile-cache "$fr_tmp/cache" \
    --journal "$fr_tmp/serve.jsonl" --workers 2 --max-queue 32 \
    --watchdog-timeout 1.5 --slo "bin-mean=0.000001" \
    --flightrec on --incident-dir "$fr_tmp/incidents" \
    --metrics-port 0 --metrics-out "$fr_tmp/serve.prom" &
FR_PID=$!
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$FRSOCK" <<'EOF'
import sys
from specpride_tpu.serve.client import wait_for_socket
assert wait_for_socket(sys.argv[1], timeout=180), \
    "flightrec daemon never came up"
EOF
fr_submit() { # $1 = tag; rest = extra job flags
    FR_TAG="$1"; shift
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        submit --socket "$FRSOCK" -- \
        consensus "$FR_IN" "$fr_tmp/served_$FR_TAG.mgf" \
        --method bin-mean "$@" > /dev/null
}
# (a) the breach streak: every bin-mean job_done breaks the 1us
# objective; the third consecutive breach fires slo_breach
fr_submit s1
fr_submit s2
fr_submit s3
# (b) the wedge: the injected dispatch hang stalls the serve:job lane
# past the daemon's 1.5s watchdog (-> watchdog_stall -> incident); the
# JOB's own 4s watchdog then cancels the hang so the retried job still
# commits byte-identical output.  Its fourth-in-a-row SLO breach stays
# inside slo_breach's 30s dedup cooldown — suppressed, never journaled
# twice.
fr_submit hang --prefetch 2 --retries 2 --retry-backoff 0.01 \
    --watchdog-timeout 4 --inject-faults "dispatch:hang:1:0"
for T in s1 s2 s3 hang; do
    cmp "$fr_tmp/cli.mgf" "$fr_tmp/served_$T.mgf"
done
# the daemon is still LIVE: the incident summary renders off the
# (run_end-less) journal
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$fr_tmp/serve.jsonl" --incidents | grep -q "incidents: mode=on"
kill -TERM $FR_PID
FR_RC=0; wait $FR_PID || FR_RC=$?
test "$FR_RC" -eq 0
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$fr_tmp" <<'EOF'
import json, os, sys
tmp = sys.argv[1]
from specpride_tpu.observability.journal import read_events
events, violations = read_events(os.path.join(tmp, "serve.jsonl"))
assert not violations, violations
names = [e["event"] for e in events]
assert "serve_drain" in names and names[-1] == "run_end", names[-6:]
inc = [e for e in events if e["event"] == "incident"]
assert sorted(e["detector"] for e in inc) == \
    ["slo_breach", "watchdog"], inc
for e in inc:
    assert e["mode"] == "on" and e["bundled"] is True, e
    assert e["incident_id"] and isinstance(e["evidence"], dict), e
    assert e["trace_id"], e
slo = next(e for e in inc if e["detector"] == "slo_breach")
assert slo["evidence"]["streak"] == 3, slo
wd = next(e for e in inc if e["detector"] == "watchdog")
assert wd["evidence"]["lane"] == "serve:job", wd
# on-disk bundles: atomic, schema-valid, complete, no staging debris
from specpride_tpu.observability.flightrec import list_bundles
inc_dir = os.path.join(tmp, "incidents")
assert not [p for p in os.listdir(inc_dir) if ".tmp-" in p], \
    "staging debris leaked into the incident dir"
bundles, warnings = list_bundles(inc_dir)
assert not warnings, warnings
by_id = {b["incident"]["incident_id"]: b for b in bundles}
assert set(by_id) == {e["incident_id"] for e in inc}, by_id
for e in inc:
    b = by_id[e["incident_id"]]
    assert b["schema"] == 1 and b["dir"] == e["bundle_dir"], b
    for fname in ("ring.jsonl", "stacks.txt", "journal_tail.jsonl",
                  "metrics.prom", "config.json"):
        assert fname in b["files"], (e["detector"], b["files"])
        assert os.path.getsize(os.path.join(b["dir"], fname)) > 0, fname
# each ring snapshot holds its own trigger record
slo_ring = [json.loads(l) for l in open(
    os.path.join(by_id[slo["incident_id"]]["dir"], "ring.jsonl"))]
assert any(r["event"] == "job_done" and r.get("slo_ok") is False
           for r in slo_ring), "trigger job_done missing from the ring"
wd_ring = [json.loads(l) for l in open(
    os.path.join(by_id[wd["incident_id"]]["dir"], "ring.jsonl"))]
assert any(r["event"] == "watchdog_stall" for r in wd_ring), \
    "trigger watchdog_stall missing from the ring"
# the config section carries the armed plane's boot knobs + digest
cfg = json.load(open(os.path.join(
    by_id[slo["incident_id"]]["dir"], "config.json")))
assert cfg["config"]["flightrec"] == "on" and cfg["digest"], cfg
# the drain metrics snapshot counts both detectors (strict exposition)
from specpride_tpu.observability.exporter import parse_exposition
samples, problems = parse_exposition(
    open(os.path.join(tmp, "serve.prom")).read())
assert not problems, problems
for det in ("slo_breach", "watchdog"):
    key = ("specpride_incidents_total", (("detector", det),))
    assert samples.get(key) == 1, (det, samples.get(key))
print(f"incident plane OK: slo_breach + watchdog fired once each, "
      f"{len(bundles)} atomic bundles, counters on the drain snapshot")
EOF
# read side: list renders both bundles; show resolves a git-style id
# prefix; export tars a complete bundle
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    incidents list "$fr_tmp/incidents" > "$fr_tmp/inc_list.txt"
grep -q "slo_breach" "$fr_tmp/inc_list.txt"
grep -q "watchdog" "$fr_tmp/inc_list.txt"
FR_ID=$(awk 'NR==1{print $1}' "$fr_tmp/inc_list.txt")
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    incidents show "$fr_tmp/incidents" "$(printf %.6s "$FR_ID")" \
    > "$fr_tmp/inc_show.json"
grep -q '"schema": 1' "$fr_tmp/inc_show.json"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    incidents export "$fr_tmp/incidents" "$FR_ID" \
    --output "$fr_tmp/inc.tar.gz"
tar -tzf "$fr_tmp/inc.tar.gz" | grep -q manifest.json
# the determinism audit: refold the journal through fresh detectors and
# require both incidents to re-derive bit-exact (exit 0)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    incident-replay "$fr_tmp/serve.jsonl"
rm -rf "$fr_tmp"

if [ "${1:-}" != "--fast" ]; then
    echo "== native: ASan parser suite =="
    make -C native asan
    echo "== native: TSan parser + threaded compute kernels =="
    make -C native tsan
fi
echo "CI OK"
