#!/bin/sh
# Minimal CI for specpride_tpu (survey §5: tests + native sanitizers).
#
#   sh scripts/ci.sh          # full: pytest + ASan/TSan parser suites
#   sh scripts/ci.sh --fast   # pytest only
#
# The Python suite pins JAX to a virtual 8-device CPU mesh via
# tests/conftest.py, so this runs anywhere (no TPU needed).
set -eu
cd "$(dirname "$0")/.."

echo "== pytest =="
python -m pytest tests/ -x -q

if [ "${1:-}" != "--fast" ]; then
    echo "== native: ASan parser suite =="
    make -C native asan
    echo "== native: TSan parser + threaded compute kernels =="
    make -C native tsan
fi
echo "CI OK"
