#!/bin/sh
# Minimal CI for specpride_tpu (survey §5: tests + native sanitizers).
#
#   sh scripts/ci.sh          # full: pytest + ASan/TSan parser suites
#   sh scripts/ci.sh --fast   # pytest only
#
# The Python suite pins JAX to a virtual 8-device CPU mesh via
# tests/conftest.py, so this runs anywhere (no TPU needed).
set -eu
cd "$(dirname "$0")/.."

echo "== pytest =="
python -m pytest tests/ -x -q

echo "== observability: journal-producing pipeline + specpride stats =="
# one real CLI run must produce a schema-valid journal and metrics file;
# `specpride stats` exits non-zero on any schema violation
obs_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$obs_tmp/reps.mgf" \
    --method bin-mean --backend tpu \
    --journal "$obs_tmp/run.jsonl" --metrics-out "$obs_tmp/run.prom"
test -s "$obs_tmp/run.prom"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$obs_tmp/run.jsonl" --json "$obs_tmp/agg.json"
rm -rf "$obs_tmp"

if [ "${1:-}" != "--fast" ]; then
    echo "== native: ASan parser suite =="
    make -C native asan
    echo "== native: TSan parser + threaded compute kernels =="
    make -C native tsan
fi
echo "CI OK"
