#!/bin/sh
# Minimal CI for specpride_tpu (survey §5: tests + native sanitizers).
#
#   sh scripts/ci.sh          # full: pytest + ASan/TSan parser suites
#   sh scripts/ci.sh --fast   # pytest only
#
# The Python suite pins JAX to a virtual 8-device CPU mesh via
# tests/conftest.py, so this runs anywhere (no TPU needed).
set -eu
cd "$(dirname "$0")/.."

echo "== pytest =="
python -m pytest tests/ -x -q

echo "== observability: journal + chrome-trace pipeline + specpride stats =="
# one real CLI run must produce a schema-valid journal, metrics file, and
# well-formed Chrome trace; `specpride stats` exits non-zero on any schema
# violation
obs_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$obs_tmp/reps.mgf" \
    --method bin-mean --backend tpu \
    --journal "$obs_tmp/run.jsonl" --metrics-out "$obs_tmp/run.prom" \
    --chrome-trace "$obs_tmp/run.trace.json"
test -s "$obs_tmp/run.prom"
python - "$obs_tmp/run.trace.json" <<'EOF'
import json, sys
trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty traceEvents"
for e in events:
    assert {"ph", "ts", "pid"} <= set(e), f"missing trace keys: {e}"
assert any(e["ph"] == "X" for e in events), "no span slices"
print(f"trace OK: {len(events)} events")
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$obs_tmp/run.jsonl" --json "$obs_tmp/agg.json" --top-spans 5
echo "== observability: specpride trace over a 2-shard .part journal pair =="
cp "$obs_tmp/run.jsonl" "$obs_tmp/multi.jsonl.part00000"
cp "$obs_tmp/run.jsonl" "$obs_tmp/multi.jsonl.part00001"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    trace "$obs_tmp/multi.jsonl" -o "$obs_tmp/multi.trace.json"
python - "$obs_tmp/multi.trace.json" <<'EOF'
import json, sys
events = json.load(open(sys.argv[1]))["traceEvents"]
pids = {e["pid"] for e in events if e["ph"] == "X"}
assert pids == {0, 1}, f"expected both ranks on the timeline, got {pids}"
print("multi-host trace merge OK")
EOF
rm -rf "$obs_tmp"

echo "== pipelined executor: --prefetch 2 parity + pipeline telemetry =="
# the pipelined chunk executor must produce byte-identical output to the
# serial path, and its journal must carry `pipeline` spans plus a
# device_idle_s summary in run_end (docs/performance.md)
pf_tmp=$(mktemp -d)
for P in 0 2; do
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        consensus tests/data/golden_clustered.mgf "$pf_tmp/reps_p$P.mgf" \
        --method bin-mean --backend tpu --prefetch "$P" \
        --checkpoint "$pf_tmp/ck_p$P.json" --checkpoint-every 1 \
        --journal "$pf_tmp/run_p$P.jsonl"
done
cmp "$pf_tmp/reps_p0.mgf" "$pf_tmp/reps_p2.mgf"
python - "$pf_tmp/run_p2.jsonl" <<'EOF'
import json, sys
events = [json.loads(l) for l in open(sys.argv[1])]
spans = [e for e in events if e["event"] == "span"
         and e["name"].startswith("pipeline")]
assert spans, "no pipeline spans in the prefetch journal"
end = [e for e in events if e["event"] == "run_end"][-1]
pipe = end.get("pipeline") or {}
assert "device_idle_s" in pipe, f"run_end missing pipeline.device_idle_s: {end}"
assert end["phases_s"].get("pack", 0) > 0, "packer time not journaled as pack"
print(f"pipeline OK: {len(spans)} pipeline spans, "
      f"device_idle_s={pipe['device_idle_s']}")
EOF
rm -rf "$pf_tmp"

echo "== multi-lane executor: pack-workers x async-write parity matrix =="
# every (pack-workers, async-write) combination must reproduce the serial
# output byte for byte; the journal must carry the per-lane run_end
# summary and prove the commit protocol's order (chunk_done — i.e. the
# MGF append — strictly before that chunk's checkpoint_write)
ln_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$ln_tmp/serial.mgf" \
    --method bin-mean --backend tpu --prefetch 0 \
    --checkpoint "$ln_tmp/serial.ck.json" --checkpoint-every 1
for PW in 0 4; do
    for AW in on off; do
        env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
            consensus tests/data/golden_clustered.mgf \
            "$ln_tmp/reps_pw${PW}_$AW.mgf" \
            --method bin-mean --backend tpu --prefetch 4 \
            --pack-workers "$PW" --async-write "$AW" \
            --checkpoint "$ln_tmp/ck_pw${PW}_$AW.json" --checkpoint-every 1 \
            --journal "$ln_tmp/run_pw${PW}_$AW.jsonl"
        cmp "$ln_tmp/serial.mgf" "$ln_tmp/reps_pw${PW}_$AW.mgf"
        cmp "$ln_tmp/serial.ck.json" "$ln_tmp/ck_pw${PW}_$AW.json"
    done
done
python - "$ln_tmp/run_pw4_on.jsonl" <<'EOF'
import json, sys
events = [json.loads(l) for l in open(sys.argv[1])]
end = [e for e in events if e["event"] == "run_end"][-1]
pipe = end.get("pipeline") or {}
for key in ("prefetch", "pack_workers", "async_write", "device_idle_s",
            "wall_s", "pack_busy_s", "write_busy_s", "reorder_stall_s"):
    assert key in pipe, f"run_end.pipeline missing {key}: {pipe}"
# pack_workers is the EFFECTIVE pool size (clamped to the chunk count)
# and must match the per-worker busy list
assert 1 <= pipe["pack_workers"] <= 4, pipe
assert len(pipe["pack_busy_s"]) == pipe["pack_workers"], pipe
assert pipe["async_write"] is True, pipe
names = {e["name"] for e in events if e["event"] == "span"}
assert any(n.startswith("pipeline:pack[") for n in names), names
assert "pipeline:write" in names, names
# commit protocol: chunk i's MGF append (chunk_done) precedes its
# checkpoint_write, and n_done/output_bytes only ever grow
order = [e for e in events if e["event"] in ("chunk_done", "checkpoint_write")]
n_done = out_bytes = 0
for prev, cur in zip([None] + order, order):
    if cur["event"] == "checkpoint_write":
        assert prev is not None and prev["event"] == "chunk_done", \
            "checkpoint_write without a preceding chunk_done"
        assert cur["n_done"] > n_done and cur["output_bytes"] >= out_bytes
        n_done, out_bytes = cur["n_done"], cur["output_bytes"]
print(f"lane matrix OK: {len(order)} commit events, "
      f"pack_busy_s={pipe['pack_busy_s']} write_busy_s={pipe['write_busy_s']}")
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$ln_tmp/run_pw4_on.jsonl" | grep -q reorder_stall_s
rm -rf "$ln_tmp"

echo "== robustness: chaos pass (one injected fault per site, seeded) =="
# the pack-workers x async-write matrix re-runs with one deterministic
# fault per lane site; every run must (a) exit 0, (b) reproduce the
# fault-free serial bytes AND manifest, (c) pair every journaled fault
# with a recovery event (retry/degrade/resume_repair/quarantine)
rb_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$rb_tmp/serial.mgf" \
    --method bin-mean --backend tpu --prefetch 0 \
    --checkpoint "$rb_tmp/serial.ck.json" --checkpoint-every 1
# golden_clustered.mgf holds 3 clusters -> 3 chunks at --checkpoint-every
# 1, so the AFTER offsets stagger the six faults across chunks 1..3
CHAOS="parse:io:1,pack:io:1:1,prepare:io:1:1,dispatch:oom:1:1,write:io:1:1,checkpoint_write:io:1:2"
for PW in 0 4; do
    for AW in on off; do
        env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
            consensus tests/data/golden_clustered.mgf \
            "$rb_tmp/chaos_pw${PW}_$AW.mgf" \
            --method bin-mean --backend tpu --prefetch 4 \
            --pack-workers "$PW" --async-write "$AW" \
            --retries 3 --retry-backoff 0.01 --fault-seed 0 \
            --inject-faults "$CHAOS" \
            --checkpoint "$rb_tmp/chaos_pw${PW}_$AW.ck.json" \
            --checkpoint-every 1 \
            --journal "$rb_tmp/chaos_pw${PW}_$AW.jsonl"
        cmp "$rb_tmp/serial.mgf" "$rb_tmp/chaos_pw${PW}_$AW.mgf"
        cmp "$rb_tmp/serial.ck.json" "$rb_tmp/chaos_pw${PW}_$AW.ck.json"
    done
done
# d2h fires only on a DEVICE layout (the auto bin-mean path is host-side),
# and qc only on a non-fused QC pass (select medoid + --qc-report); one
# run each so all 8 sites are exercised, parity-checked vs its own
# fault-free twin
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$rb_tmp/flat_clean.mgf" \
    --method bin-mean --backend tpu --layout flat --force-device \
    --prefetch 0 --checkpoint "$rb_tmp/flat_clean.ck.json" \
    --checkpoint-every 1
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$rb_tmp/flat_chaos.mgf" \
    --method bin-mean --backend tpu --layout flat --force-device \
    --prefetch 2 --retries 3 --retry-backoff 0.01 \
    --inject-faults "d2h:io:1:1" \
    --checkpoint "$rb_tmp/flat_chaos.ck.json" --checkpoint-every 1 \
    --journal "$rb_tmp/chaos_d2h.jsonl"
cmp "$rb_tmp/flat_clean.mgf" "$rb_tmp/flat_chaos.mgf"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    select tests/data/golden_clustered.mgf "$rb_tmp/qc_clean.mgf" \
    --method medoid --backend tpu --prefetch 2 \
    --qc-report "$rb_tmp/qc_clean.json" \
    --checkpoint "$rb_tmp/qc_clean.ck.json" --checkpoint-every 1
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    select tests/data/golden_clustered.mgf "$rb_tmp/qc_chaos.mgf" \
    --method medoid --backend tpu --prefetch 2 \
    --retries 3 --retry-backoff 0.01 --inject-faults "qc:io:1:1" \
    --qc-report "$rb_tmp/qc_chaos.json" \
    --checkpoint "$rb_tmp/qc_chaos.ck.json" --checkpoint-every 1 \
    --journal "$rb_tmp/chaos_qc.jsonl"
cmp "$rb_tmp/qc_clean.mgf" "$rb_tmp/qc_chaos.mgf"
cmp "$rb_tmp/qc_clean.json" "$rb_tmp/qc_chaos.json"
python - "$rb_tmp"/chaos_*.jsonl <<'EOF'
import json, sys
from specpride_tpu.robustness.faults import FAULT_SITES, audit_fault_recovery
fired = set()
for path in sys.argv[1:]:
    events = [json.loads(l) for l in open(path)]
    faults = [e for e in events if e["event"] == "fault"]
    assert faults, f"{path}: no fault fired (is the plan armed?)"
    unmatched = audit_fault_recovery(events)
    assert not unmatched, f"{path}: unrecovered faults {unmatched}"
    end = [e for e in events if e["event"] == "run_end"][-1]
    rb = end.get("robustness") or {}
    assert rb.get("faults", {}).get("fired_total", 0) == len(faults), rb
    fired |= {e["site"] for e in faults}
missing = set(FAULT_SITES) - fired
assert not missing, f"sites never exercised: {sorted(missing)}"
print(f"chaos OK: all {len(FAULT_SITES)} sites fired and recovered, "
      "outputs byte-identical to fault-free runs")
EOF
# `specpride stats` must render the injection/recovery summary and exit 0
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$rb_tmp/chaos_pw4_on.jsonl" | grep -q "robustness:"
rm -rf "$rb_tmp"

if [ "${1:-}" != "--fast" ]; then
    echo "== native: ASan parser suite =="
    make -C native asan
    echo "== native: TSan parser + threaded compute kernels =="
    make -C native tsan
fi
echo "CI OK"
