#!/bin/sh
# Minimal CI for specpride_tpu (survey §5: tests + native sanitizers).
#
#   sh scripts/ci.sh          # full: pytest + ASan/TSan parser suites
#   sh scripts/ci.sh --fast   # pytest only
#
# The Python suite pins JAX to a virtual 8-device CPU mesh via
# tests/conftest.py, so this runs anywhere (no TPU needed).
set -eu
cd "$(dirname "$0")/.."

echo "== pytest =="
python -m pytest tests/ -x -q

echo "== observability: journal + chrome-trace pipeline + specpride stats =="
# one real CLI run must produce a schema-valid journal, metrics file, and
# well-formed Chrome trace; `specpride stats` exits non-zero on any schema
# violation
obs_tmp=$(mktemp -d)
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    consensus tests/data/golden_clustered.mgf "$obs_tmp/reps.mgf" \
    --method bin-mean --backend tpu \
    --journal "$obs_tmp/run.jsonl" --metrics-out "$obs_tmp/run.prom" \
    --chrome-trace "$obs_tmp/run.trace.json"
test -s "$obs_tmp/run.prom"
python - "$obs_tmp/run.trace.json" <<'EOF'
import json, sys
trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
assert events, "empty traceEvents"
for e in events:
    assert {"ph", "ts", "pid"} <= set(e), f"missing trace keys: {e}"
assert any(e["ph"] == "X" for e in events), "no span slices"
print(f"trace OK: {len(events)} events")
EOF
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    stats "$obs_tmp/run.jsonl" --json "$obs_tmp/agg.json" --top-spans 5
echo "== observability: specpride trace over a 2-shard .part journal pair =="
cp "$obs_tmp/run.jsonl" "$obs_tmp/multi.jsonl.part00000"
cp "$obs_tmp/run.jsonl" "$obs_tmp/multi.jsonl.part00001"
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
    trace "$obs_tmp/multi.jsonl" -o "$obs_tmp/multi.trace.json"
python - "$obs_tmp/multi.trace.json" <<'EOF'
import json, sys
events = json.load(open(sys.argv[1]))["traceEvents"]
pids = {e["pid"] for e in events if e["ph"] == "X"}
assert pids == {0, 1}, f"expected both ranks on the timeline, got {pids}"
print("multi-host trace merge OK")
EOF
rm -rf "$obs_tmp"

echo "== pipelined executor: --prefetch 2 parity + pipeline telemetry =="
# the pipelined chunk executor must produce byte-identical output to the
# serial path, and its journal must carry `pipeline` spans plus a
# device_idle_s summary in run_end (docs/performance.md)
pf_tmp=$(mktemp -d)
for P in 0 2; do
    env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m specpride_tpu \
        consensus tests/data/golden_clustered.mgf "$pf_tmp/reps_p$P.mgf" \
        --method bin-mean --backend tpu --prefetch "$P" \
        --checkpoint "$pf_tmp/ck_p$P.json" --checkpoint-every 1 \
        --journal "$pf_tmp/run_p$P.jsonl"
done
cmp "$pf_tmp/reps_p0.mgf" "$pf_tmp/reps_p2.mgf"
python - "$pf_tmp/run_p2.jsonl" <<'EOF'
import json, sys
events = [json.loads(l) for l in open(sys.argv[1])]
spans = [e for e in events if e["event"] == "span"
         and e["name"].startswith("pipeline")]
assert spans, "no pipeline spans in the prefetch journal"
end = [e for e in events if e["event"] == "run_end"][-1]
pipe = end.get("pipeline") or {}
assert "device_idle_s" in pipe, f"run_end missing pipeline.device_idle_s: {end}"
assert end["phases_s"].get("pack", 0) > 0, "packer time not journaled as pack"
print(f"pipeline OK: {len(spans)} pipeline spans, "
      f"device_idle_s={pipe['device_idle_s']}")
EOF
rm -rf "$pf_tmp"

if [ "${1:-}" != "--fast" ]; then
    echo "== native: ASan parser suite =="
    make -C native asan
    echo "== native: TSan parser + threaded compute kernels =="
    make -C native tsan
fi
echo "CI OK"
