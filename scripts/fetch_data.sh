#!/bin/sh
# Fetch the PXD004732 benchmark inputs into data/ (the reference's
# install.sh:5-10 dataset step, done right: resumable, checksummed when a
# .sha256 manifest is present, and with curl told to actually save files —
# the reference's bare `curl <url>` writes the payload to stdout).
#
#   sh scripts/fetch_data.sh [DEST_DIR]     # default: ./data
#
# Needs network access to ftp.pride.ebi.ac.uk (EBI PRIDE archive).
set -eu

DEST="${1:-data}"
BASE="ftp://ftp.pride.ebi.ac.uk/pride/data/proteogenomics/projects/eubic-2020"
FILES="01650b_BA5-TUM_first_pool_75_01_01-3xHCD-1h-R2.mzML msms.txt peptides.txt"

mkdir -p "$DEST"
for f in $FILES; do
    echo "fetching $f ..."
    # Always run curl: -C - resumes a partial file and is a cheap no-op
    # when the file is already complete (a size-only "skip if non-empty"
    # guard would treat an interrupted download as done and pin its
    # truncated checksum below).  rc 33 = server refused the resume range
    # — which happens when the file is already complete, but ALSO when a
    # server simply doesn't honor ranges on a genuinely truncated partial
    # file, so verify the local size against the remote before trusting it
    # (otherwise a first fetch with no committed manifest would pin the
    # truncated file's checksum as ground truth below).
    curl --fail -C - -o "$DEST/$f" "$BASE/$f" || {
        rc=$?
        [ "$rc" -eq 33 ] || exit "$rc"
        remote_size=$(curl --fail -sI "$BASE/$f" | tr -d '\r' \
            | awk 'tolower($1)=="content-length:" {print $2}' | tail -n 1)
        local_size=$(wc -c < "$DEST/$f" | tr -d ' ')
        if [ -n "$remote_size" ] && [ "$remote_size" != "$local_size" ]; then
            echo "  ERROR: server refused resume but $f is incomplete" >&2
            echo "  ($local_size of $remote_size bytes) — delete it and retry" >&2
            exit 33
        fi
        if [ -z "$remote_size" ]; then
            echo "  WARNING: server refused resume and reports no size;" >&2
            echo "  $f may be partial — a recorded manifest could pin it" >&2
        else
            echo "  (resume refused; size matches remote: complete)"
        fi
    }
done

# Integrity: verify against a committed manifest when present, else record
# one so later fetches on other machines can be checked against it.
MANIFEST="$DEST/SHA256SUMS"
if [ -f "$MANIFEST" ]; then
    (cd "$DEST" && sha256sum -c SHA256SUMS)
else
    (cd "$DEST" && sha256sum $FILES > SHA256SUMS)
    echo "recorded $MANIFEST — commit it to pin the dataset"
fi

cat <<EOF
done. next steps (docs/datasets.md):
  specpride convert $DEST/01650b_BA5-TUM_first_pool_75_01_01-3xHCD-1h-R2.mzML clustered.mgf \\
      --msms $DEST/msms.txt --clusters MaRaCluster.clusters_p30.tsv
EOF
