#!/usr/bin/env python
"""Headline benchmark: consensus spectra/sec, device backend vs numpy oracle.

The reference publishes no numbers (BASELINE.md), so the baseline is our own
numpy oracle — a faithful behavioural port of ref src/binning.py:170-231 —
measured on the same synthetic PXD-like cluster workload.  Prints ONE JSON
line on stdout:

    {"metric": ..., "value": N, "unit": "clusters/sec", "vs_baseline": N}

``value`` is the device-backend end-to-end rate (pack + f64 quantize + H2D +
kernel + D2H + finalize); ``vs_baseline`` is the speedup over the numpy
oracle rate.  Runs on whatever JAX platform the environment provides (the
real TPU chip under the driver; CPU elsewhere).  Diagnostics go to stderr.

``--report FILE`` benches EVERY method (bin_mean / gap_average / medoid /
pipeline) with the backend's phase timers (pack / dispatch / d2h / finalize,
plus a synchronous device split) and the numpy oracle timed on the FULL
cluster set, plus a FILE-based end-to-end run (parse -> kernels -> write +
QC report, both backends), and writes the per-method JSON report (committed
as BENCH_METHODS.json).

Oracle protocol (pinned, round 5): the baseline is ALWAYS the full cluster
set timed in the same process immediately before the device runs — never a
sample.  Residual run-to-run variance (the r4 62.7 vs 132.5 cl/s pipeline
oracle discrepancy) is host noise: the bench host exposes ONE cpu core
behind a shared tunnel, so absolute rates move with machine load;
``vs_baseline`` stays meaningful because both sides are measured
back-to-back under the same conditions.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def eprint(*a):
    print(*a, file=sys.stderr, flush=True)


def make_workload(n_clusters: int, seed: int = 42):
    """Synthetic clustered MS/MS workload shaped like the PXD004732 benchmark
    set: cluster sizes skewed small (most clusters 2-8 members, tail to 20),
    100-400 peaks per spectrum, 0.003 Da m/z jitter within a cluster."""
    from specpride_tpu.data.peaks import Cluster, Spectrum

    rng = np.random.default_rng(seed)
    clusters = []
    for i in range(n_clusters):
        n_members = min(20, 1 + int(rng.gamma(2.0, 2.5)))
        n_peaks = int(rng.integers(100, 400))
        skeleton = np.sort(rng.uniform(120.0, 1900.0, size=n_peaks))
        charge = int(rng.integers(2, 4))
        members = []
        for k in range(n_members):
            mz = np.sort(skeleton + rng.normal(0.0, 0.003, size=n_peaks))
            members.append(
                Spectrum(
                    mz=mz,
                    intensity=rng.uniform(10.0, 1e4, size=n_peaks),
                    precursor_mz=float(rng.uniform(300.0, 900.0)),
                    precursor_charge=charge,
                    rt=float(i),
                    title=f"cluster-{i};mzspec:PXD1:r:scan:{i * 100 + k}",
                )
            )
        clusters.append(Cluster(f"cluster-{i}", members))
    return clusters


def _runners(backend, nb):
    def np_pipeline(cs):
        reps = nb.run_bin_mean(cs)
        return [nb.average_cosine(r, c.members) for r, c in zip(reps, cs)]

    def dev_pipeline(cs):
        # fused: overlaps cosine member prep with the bin-mean D2H stream
        reps, cos = backend.run_bin_mean_with_cosines(cs)
        assert len(reps) == len(cos) == len(cs)
        return cos

    run_np = {
        "pipeline": np_pipeline,
        "bin_mean": nb.run_bin_mean,
        "gap_average": nb.run_gap_average,
        "medoid": nb.run_medoid,
    }
    run_dev = {
        "pipeline": dev_pipeline,
        "bin_mean": backend.run_bin_mean,
        "gap_average": backend.run_gap_average,
        "medoid": backend.run_medoid,
    }
    return run_np, run_dev


METRIC_NAMES = {
    "pipeline": "consensus+QC pipeline (bin-mean + binned-cosine)",
    "bin_mean": "consensus spectra/sec (bin-mean)",
    "gap_average": "consensus spectra/sec (gap-average)",
    "medoid": "medoid representatives/sec",
}


def bench_method(
    method: str,
    clusters,
    backend,
    nb,
    numpy_sample: int,
    seed: int,
    steady_runs: int = 3,
    journal=None,
) -> dict:
    """Bench one method: numpy oracle rate (stratified sample or full set),
    device warm-up (compile) time, steady-state rate, and the backend's
    per-phase seconds for the best steady run.  With ``journal``, the
    per-run phase numbers stream out as ``bench_run`` events (the BENCH
    stdout JSON line is unchanged)."""
    from specpride_tpu.observability import NullJournal, RunStats

    journal = journal if journal is not None else NullJournal()

    run_np, run_dev = _runners(backend, nb)

    # numpy oracle: stratified random sample (NOT the first-N prefix — the
    # gamma-skewed workload makes early clusters unrepresentative), full set
    # when numpy_sample covers it
    if numpy_sample >= len(clusters):
        sample = clusters
    else:
        pick = np.random.default_rng(seed + 1).choice(
            len(clusters), size=numpy_sample, replace=False
        )
        sample = [clusters[i] for i in pick]
    t0 = time.perf_counter()
    run_np[method](sample)
    np_elapsed = time.perf_counter() - t0
    numpy_rate = len(sample) / np_elapsed
    eprint(
        f"[{method}] numpy oracle: {numpy_rate:.1f} clusters/sec "
        f"({len(sample)} clusters in {np_elapsed:.2f}s)"
    )

    # device: first run includes compile; report it separately
    t0 = time.perf_counter()
    run_dev[method](clusters)
    warmup_s = time.perf_counter() - t0
    eprint(f"[{method}] device warm-up (incl compile): {warmup_s:.1f}s")

    best_rate, best_phases = 0.0, {}
    for i in range(steady_runs):
        backend.stats = RunStats()
        t0 = time.perf_counter()
        out = run_dev[method](clusters)
        elapsed = time.perf_counter() - t0
        rate = len(clusters) / elapsed
        eprint(
            f"[{method}] device steady-state run {i}: {rate:.1f} clusters/sec "
            f"phases={ {k: round(v, 3) for k, v in backend.stats.phases.items()} }"
        )
        assert len(out) == len(clusters)
        if rate > best_rate:
            best_rate = rate
            best_phases = {
                k: round(v, 4) for k, v in backend.stats.phases.items()
            }

    entry = {
        "method": method,
        "metric": METRIC_NAMES[method],
        "numpy_clusters_per_sec": round(numpy_rate, 2),
        "numpy_sample_clusters": len(sample),
        "device_clusters_per_sec": round(best_rate, 2),
        "device_warmup_s": round(warmup_s, 2),
        "device_phases_s": best_phases,
        "speedup_vs_numpy": round(best_rate / numpy_rate, 3),
    }
    journal.emit(
        "bench_run", method=method, phases_s=best_phases,
        device_clusters_per_sec=entry["device_clusters_per_sec"],
        numpy_clusters_per_sec=entry["numpy_clusters_per_sec"],
        device_warmup_s=entry["device_warmup_s"],
        n_clusters=len(clusters),
    )
    return entry


def bench_end_to_end(clusters, workdir: str, runs: int = 2) -> dict:
    """FILE-based pipeline benchmark: write the workload as a clustered MGF
    once, then time the full CLI consensus run — native parse -> kernels ->
    MGF write + QC report — for both backends.  This is the number a user
    actually experiences; the in-memory method benches above deliberately
    exclude parse/write (VERDICT r4: the C++ parser's value and the true
    end-to-end rate were unmeasured)."""
    import os

    from specpride_tpu.cli import main as cli_main
    from specpride_tpu.io.mgf import write_mgf

    src = os.path.join(workdir, "bench_clustered.mgf")
    spectra = [s for c in clusters for s in c.members]
    t0 = time.perf_counter()
    write_mgf(spectra, src)
    eprint(
        f"[end_to_end] wrote {len(spectra)} spectra "
        f"({os.path.getsize(src) / 1e6:.0f} MB) in "
        f"{time.perf_counter() - t0:.1f}s"
    )

    def timed(backend: str, tag: str) -> float:
        best = float("inf")
        for i in range(runs):
            out = os.path.join(workdir, f"bench_out_{tag}_{i}.mgf")
            qc = os.path.join(workdir, f"bench_qc_{tag}_{i}.json")
            t0 = time.perf_counter()
            rc = cli_main([
                "consensus", src, out, "--backend", backend,
                "--qc-report", qc,
            ])
            elapsed = time.perf_counter() - t0
            assert rc == 0
            eprint(
                f"[end_to_end] {backend} run {i}: "
                f"{len(clusters) / elapsed:.1f} clusters/sec ({elapsed:.2f}s)"
            )
            best = min(best, elapsed)
        return best

    dev_s = timed("tpu", "tpu")
    np_s = timed("numpy", "numpy")
    return {
        "method": "end_to_end",
        "metric": "file-to-file consensus+QC (parse + bin-mean + cosine + "
        "write)",
        "n_clusters": len(clusters),
        "mgf_bytes": os.path.getsize(src),
        "numpy_clusters_per_sec": round(len(clusters) / np_s, 2),
        "device_clusters_per_sec": round(len(clusters) / dev_s, 2),
        "speedup_vs_numpy": round(np_s / dev_s, 3),
    }


def _sweep_source(clusters, workdir: str) -> str:
    """The clustered-MGF input shared by the executor sweeps (written
    once per workdir)."""
    import os

    from specpride_tpu.io.mgf import write_mgf

    src = os.path.join(workdir, "prefetch_clustered.mgf")
    if not os.path.exists(src):
        write_mgf([s for c in clusters for s in c.members], src)
    return src


def _sweep_run_full(command: str, method: str, src: str, workdir: str,
                    tag: str, flags: list):
    """One CLI run under the pinned executor-sweep protocol — identical
    chunking (``--checkpoint-every 256``) and a journal to read the
    ``run_end`` summary from.  THE one runner every sweep shares, so the
    measurement protocol cannot drift between them.  Returns
    ``(wall_s, executor_s, run_end, output_bytes)``; executor_s is the
    post-parse chunk loop the executor actually changed."""
    import os

    from specpride_tpu.cli import main as cli_main

    out = os.path.join(workdir, f"{tag}.mgf")
    journal = os.path.join(workdir, f"{tag}.jsonl")
    t0 = time.perf_counter()
    rc = cli_main([
        command, src, out, "--method", method,
        "--checkpoint", os.path.join(workdir, f"{tag}.ck.json"),
        "--checkpoint-every", "256",
        "--journal", journal,
    ] + flags)
    wall = time.perf_counter() - t0
    assert rc == 0
    with open(journal) as fh:
        events = [json.loads(line) for line in fh]
    end = [e for e in events if e["event"] == "run_end"][-1]
    executor_s = end["elapsed_s"] - end["phases_s"].get("parse", 0.0)
    with open(out, "rb") as fh:
        data = fh.read()
    return wall, executor_s, end, data


def _sweep_run(command: str, method: str, src: str, workdir: str,
               tag: str, flags: list):
    """``_sweep_run_full`` narrowed to the pipeline summary (the
    executor sweeps' historical signature)."""
    wall, executor_s, end, data = _sweep_run_full(
        command, method, src, workdir, tag, flags
    )
    return wall, executor_s, end.get("pipeline") or {}, data


_SWEEP_METHODS = (
    ("bin-mean", "consensus"),
    ("gap-average", "consensus"),
    ("medoid", "select"),
)


def bench_bandwidth(clusters, workdir: str) -> dict:
    """Memory-bandwidth campaign (``--precision`` x donation x
    double-buffered H2D), measured end to end through the CLI on the
    pinned sweep protocol.

    Workload note: m/z is snapped to the bf16 grid before writing the
    source, so the pack-time exactness probe ships bf16 m/z on the
    bucketized paths (real full-precision m/z falls back to f32 there —
    documented; the flat bin-mean path never ships m/z at all).  The
    QC-cosine tolerance gates still judge every reduced run against the
    f32 oracle on this same data.

    Primary sweep (flat bin-mean, the H2D-dominant packed path):
    precision {f32,bf16,int8} x donation {on,off} x h2d-buffer {0,2},
    reporting bytes moved, executor clusters/sec, overlap efficiency,
    and the per-cell QC gate.  Secondary: gap-average and medoid
    precision rows on their bucketized device paths.  Byte-parity
    audits: every f32 cell byte-identical to the flag-free baseline
    (donation/double-buffering may never change bytes), and each
    reduced precision's cells identical across the donation/h2d arms."""
    import os

    import ml_dtypes

    from specpride_tpu.data.peaks import Cluster, Spectrum
    from specpride_tpu.io.mgf import write_mgf

    bf16 = ml_dtypes.bfloat16
    snapped = [
        Cluster(c.cluster_id, [
            Spectrum(
                mz=np.sort(
                    np.asarray(s.mz, np.float32).astype(bf16)
                    .astype(np.float64)
                ),
                intensity=s.intensity,
                precursor_mz=s.precursor_mz,
                precursor_charge=s.precursor_charge,
                rt=s.rt, title=s.title,
            )
            for s in c.members
        ])
        for c in clusters
    ]
    src = os.path.join(workdir, "bandwidth.mgf")
    write_mgf([s for c in snapped for s in c.members], src)

    def run(tag, command, method, flags):
        wall, executor_s, end, data = _sweep_run_full(
            command, method, src, workdir, tag, flags
        )
        dev = end["device"]
        pipe = end.get("pipeline") or {}
        return {
            "wall_s": round(wall, 3),
            "executor_s": round(executor_s, 3),
            "clusters_per_sec_executor": round(
                len(clusters) / executor_s, 2
            ),
            "bytes_h2d": dev["bytes_h2d"],
            "bytes_d2h": dev["bytes_d2h"],
            "overlap_efficiency": pipe.get("overlap_efficiency"),
            "h2d_lane": pipe.get("h2d"),
            "gate": end.get("precision"),
        }, data

    report: dict = {"rows": []}
    # flag-free baselines: what a pre-campaign invocation runs per
    # method (the f32 cells must reproduce these bytes exactly)
    baselines = {}
    method_flags = {
        "bin-mean": ("consensus", ["--layout", "flat"]),
        "gap-average": (
            "consensus", ["--layout", "bucketized", "--force-device"]
        ),
        "medoid": ("select", ["--layout", "bucketized"]),
    }
    for method, (command, flags) in method_flags.items():
        m = method.replace("-", "_")
        _, baselines[method] = run(f"bw_{m}_base", command, method, flags)

    parity_ok = True
    f32_bytes = {}
    cells_by_prec: dict = {}
    for prec in ("f32", "bf16", "int8"):
        for donate in (True, False):
            for h2d in (0, 2):
                flags = [
                    "--layout", "flat", "--precision", prec,
                    "--prefetch", "4",
                ]
                if not donate:
                    flags.append("--no-donate")
                if h2d:
                    flags += ["--h2d-buffer", str(h2d)]
                tag = (
                    f"bw_bin_{prec}_{'don' if donate else 'nodon'}_h{h2d}"
                )
                row, data = run(tag, "consensus", "bin-mean", flags)
                row.update(
                    method="bin-mean", precision=prec, donate=donate,
                    h2d_buffer=h2d,
                )
                if prec == "f32":
                    row["identical_to_baseline"] = (
                        data == baselines["bin-mean"]
                    )
                    parity_ok &= row["identical_to_baseline"]
                cells_by_prec.setdefault(prec, []).append(data)
                if donate and h2d == 0:
                    f32_bytes[prec] = row["bytes_h2d"]
                report["rows"].append(row)
                eprint(
                    f"[bandwidth:bin-mean {prec} donate={donate} "
                    f"h2d={h2d}] h2d={row['bytes_h2d']}B executor "
                    f"{row['clusters_per_sec_executor']} cl/s "
                    f"overlap={row['overlap_efficiency']}"
                    + (
                        f" lane={row['h2d_lane']['overlap_efficiency']}"
                        if row["h2d_lane"] else ""
                    )
                )
    # donation/double-buffering may never change bytes WITHIN a precision
    for prec, datas in cells_by_prec.items():
        parity_ok &= all(d == datas[0] for d in datas)

    for method in ("gap-average", "medoid"):
        command, flags = method_flags[method]
        m = method.replace("-", "_")
        per_prec = {}
        for prec in ("f32", "bf16", "int8"):
            row, data = run(
                f"bw_{m}_{prec}", command, method,
                flags + ["--precision", prec],
            )
            row.update(method=method, precision=prec, donate=True,
                       h2d_buffer=0)
            if prec == "f32":
                row["identical_to_baseline"] = data == baselines[method]
                parity_ok &= row["identical_to_baseline"]
            per_prec[prec] = row["bytes_h2d"]
            report["rows"].append(row)
            eprint(
                f"[bandwidth:{method} {prec}] h2d={row['bytes_h2d']}B "
                f"executor {row['clusters_per_sec_executor']} cl/s"
            )
        report[f"{m}_h2d_reduction"] = {
            p: round(per_prec["f32"] / per_prec[p], 3)
            for p in ("bf16", "int8")
        }

    # headline: the flat bin-mean packed path's byte reduction
    report["bin_mean_h2d_reduction"] = {
        p: round(f32_bytes["f32"] / f32_bytes[p], 3)
        for p in ("bf16", "int8")
    }
    report["f32_byte_parity"] = parity_ok
    # wall-clock regression probe: the campaign's default arm (donation
    # on + double buffer) vs the flag-free baseline, f32
    base_wall = min(
        r["wall_s"] for r in report["rows"]
        if r["method"] == "bin-mean" and r["precision"] == "f32"
        and not r.get("h2d_buffer") and r["donate"]
    )
    armed_wall = min(
        r["wall_s"] for r in report["rows"]
        if r["method"] == "bin-mean" and r["precision"] == "f32"
        and r.get("h2d_buffer") == 2 and r["donate"]
    )
    report["f32_armed_vs_plain_wall"] = round(armed_wall / base_wall, 4)
    gates = [
        r["gate"] for r in report["rows"]
        if r["precision"] != "f32" and r.get("gate")
    ]
    report["all_gates_ok"] = bool(gates) and all(
        g.get("ok") for g in gates if g.get("gated")
    )
    return report


def bench_fault_overhead(clusters, workdir: str, repeats: int = 5) -> dict:
    """Zero-fault cost of the ARMED robustness harness (PR5 acceptance:
    < 1%).

    Same pinned protocol as the executor sweeps (``_sweep_run``), run
    ``repeats``x in alternation: disarmed (no fault plan) vs armed with
    a zero-rate fault spec at EVERY site — the plan is installed, every
    ``faults.check`` takes the full slow path (lock + visit counter +
    deterministic draw), retries wrap every lane, but nothing ever
    fires.  Reported as the median executor-seconds delta, so the
    number is the true per-run cost of *having* the harness, which is
    what a production deployment pays on every healthy run."""
    import statistics

    from specpride_tpu.robustness.faults import FAULT_SITES

    src = _sweep_source(clusters, workdir)
    armed_spec = ",".join(f"{site}:io:0" for site in FAULT_SITES)
    # one unmeasured warmup: the first CLI run of a process pays jit
    # compiles + page-cache fill that would otherwise land entirely on
    # whichever arm ran first
    _sweep_run(
        "consensus", "bin-mean", src, workdir, "fo_warmup",
        ["--prefetch", "4"],
    )
    walls: dict[str, list[float]] = {"disarmed": [], "armed": []}
    execs: dict[str, list[float]] = {"disarmed": [], "armed": []}
    for i in range(repeats):
        for tag, flags in (
            ("disarmed", []),
            ("armed", ["--inject-faults", armed_spec, "--fault-seed", "0"]),
        ):
            wall, executor_s, _, data = _sweep_run(
                "consensus", "bin-mean", src, workdir,
                f"fo_{tag}_{i}", ["--prefetch", "4"] + flags,
            )
            walls[tag].append(wall)
            execs[tag].append(executor_s)
    # min is the standard low-noise estimator here: scheduler/IO jitter
    # only ever ADDS time, and the harness cost we are measuring is a
    # constant per run, so the fastest observation of each arm is the
    # cleanest view of it (medians of few repeats still carry one noisy
    # run each)
    disarmed = min(execs["disarmed"])
    armed = min(execs["armed"])
    out = {
        "repeats": repeats,
        "armed_spec": armed_spec,
        "disarmed_executor_s": round(disarmed, 4),
        "armed_executor_s": round(armed, 4),
        "overhead_frac": round(armed / disarmed - 1.0, 4)
        if disarmed > 0 else None,
        "disarmed_executor_median_s": round(
            statistics.median(execs["disarmed"]), 4
        ),
        "armed_executor_median_s": round(
            statistics.median(execs["armed"]), 4
        ),
        "disarmed_wall_s": [round(w, 3) for w in walls["disarmed"]],
        "armed_wall_s": [round(w, 3) for w in walls["armed"]],
    }
    eprint(
        f"[fault_overhead] disarmed {disarmed:.3f}s armed {armed:.3f}s "
        f"-> overhead {out['overhead_frac']:+.2%}"
        if out["overhead_frac"] is not None else "[fault_overhead] n/a"
    )
    return out


def bench_elastic(clusters, workdir: str, repeats: int = 3) -> dict:
    """Elastic-mode overhead on a HEALTHY 2-rank run vs the static
    block partition (PR9 acceptance: within host noise).

    Both arms run the same 2-process fleet over the same input with the
    same chunking (``--checkpoint-every 256``): *static* shards once via
    ``--coordinator`` (jax.distributed over loopback, the
    ``_shard_for_process`` path), *elastic* claims 512-cluster ranges
    from the filesystem coordinator (leases + heartbeats + commit
    markers — the whole fault-tolerance tax, paid with zero faults).
    Wall is the slower rank's exit, min over ``repeats`` (the
    fault_overhead estimator); the merged elastic output must be
    byte-identical to the merged static output."""
    import os
    import shutil
    import socket
    import subprocess
    import sys as _sys

    src = _sweep_source(clusters, workdir)
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_NUM_CPU_DEVICES", None)

    def fleet(tag: str, mode: str, i: int) -> float:
        out = os.path.join(workdir, f"{tag}_{i}.mgf")
        # --mesh on BOTH arms: --coordinator implies the mesh kernel
        # path, so the elastic arm must run the same kernels or the
        # byte-parity check (and the timing) would compare different
        # compute, not different coordination
        common = [
            _sys.executable, "-m", "specpride_tpu", "consensus", src, out,
            "--method", "bin-mean", "--checkpoint-every", "256", "--mesh",
        ]
        if mode == "static":
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            argvs = [
                common + [
                    "--coordinator", f"localhost:{port}",
                    "--num-processes", "2", "--process-id", str(r),
                    "--checkpoint", f"{out}.ck.json",
                ]
                for r in range(2)
            ]
        else:
            coord = os.path.join(workdir, f"{tag}_{i}.coord")
            shutil.rmtree(coord, ignore_errors=True)
            argvs = [
                common + [
                    "--elastic", coord, "--process-id", str(r),
                    "--elastic-range", "512",
                ]
                for r in range(2)
            ]
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(
                argv, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
            )
            for argv in argvs
        ]
        for p in procs:
            _, err = p.communicate(timeout=600)
            assert p.returncode == 0, err.decode()[-2000:]
        wall = time.perf_counter() - t0
        merge = [
            _sys.executable, "-m", "specpride_tpu", "merge-parts", out,
        ]
        merge += (
            ["--num-processes", "2"] if mode == "static"
            else ["--elastic", os.path.join(workdir, f"{tag}_{i}.coord")]
        )
        subprocess.run(merge, env=env, check=True,
                       stdout=subprocess.DEVNULL)
        return wall

    # one unmeasured warmup pair per arm: first-fleet page-cache /
    # compile-cache fill must not land on whichever arm runs first
    fleet("el_warm_static", "static", 0)
    fleet("el_warm_elastic", "elastic", 0)
    walls: dict[str, list[float]] = {"static": [], "elastic": []}
    for i in range(1, repeats + 1):
        for mode in ("static", "elastic"):
            walls[mode].append(fleet(f"el_{mode}", mode, i))
    with open(os.path.join(workdir, f"el_static_{repeats}.mgf"), "rb") as fh:
        static_bytes = fh.read()
    with open(
        os.path.join(workdir, f"el_elastic_{repeats}.mgf"), "rb"
    ) as fh:
        elastic_bytes = fh.read()
    assert static_bytes == elastic_bytes, \
        "elastic merge diverged from the static merge"
    static = min(walls["static"])
    elastic = min(walls["elastic"])
    out = {
        "repeats": repeats,
        "ranks": 2,
        "static_wall_s": round(static, 3),
        "elastic_wall_s": round(elastic, 3),
        "overhead_frac": (
            round(elastic / static - 1.0, 4) if static > 0 else None
        ),
        "static_wall_all_s": [round(w, 3) for w in walls["static"]],
        "elastic_wall_all_s": [round(w, 3) for w in walls["elastic"]],
        "byte_identical": True,
    }
    eprint(
        f"[elastic] static {static:.3f}s elastic {elastic:.3f}s "
        f"-> overhead {out['overhead_frac']:+.2%}"
    )
    return out


def bench_elastic_steal(clusters, workdir: str) -> dict:
    """Elastic tier 2: (a) live work-stealing on a SKEWED fleet — one
    rank ``rank_slow``-handicapped per chunk — makespan with
    ``--elastic-steal on`` vs ``off`` (acceptance: stealing recovers
    >= 1.3x), with steal counts from the journals; (b) coordinator
    backend overhead on a HEALTHY 2-rank fleet — filesystem vs the
    in-tree CAS object store, identical flags, min-of-repeats
    (acceptance: within host noise).  Byte parity against the serial
    golden in every cell."""
    import os
    import shutil
    import subprocess
    import sys as _sys

    from specpride_tpu.io.mgf import write_mgf
    from specpride_tpu.parallel.store import CasServer

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_NUM_CPU_DEVICES", None)

    # the skewed cells use a compute-light subset so the injected
    # per-chunk stall (the slow HARDWARE being modeled) dominates the
    # wall — the quantity stealing can actually recover
    skew_clusters = clusters[:768]
    src = os.path.join(workdir, "steal_clustered.mgf")
    write_mgf([s for c in skew_clusters for s in c.members], src)
    golden = os.path.join(workdir, "steal_serial.mgf")
    subprocess.run(
        [_sys.executable, "-m", "specpride_tpu", "consensus", src, golden,
         "--method", "bin-mean"],
        env=env, check=True, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    with open(golden, "rb") as fh:
        golden_bytes = fh.read()

    def skew_fleet(tag: str, steal: str, i: int) -> tuple[float, int, int]:
        """One 2-rank skewed run: rank 0 stalls 0.75s per chunk.
        Returns (makespan, n_splits, n_steals)."""
        d = os.path.join(workdir, f"steal_{tag}_{i}")
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d)
        out = os.path.join(d, "out.mgf")
        coord = os.path.join(d, "coord")

        def argv(rank):
            return [
                _sys.executable, "-m", "specpride_tpu", "consensus",
                src, out, "--method", "bin-mean",
                "--elastic", coord, "--process-id", str(rank),
                "--elastic-range", "384", "--checkpoint-every", "32",
                "--elastic-ttl", "2", "--elastic-steal", steal,
                "--journal", os.path.join(d, "j.jsonl"),
            ]

        slow_env = dict(
            env, SPECPRIDE_FAULTS="dispatch:rank_slow:1:0:9999",
            SPECPRIDE_SLOW_S="1.0",
        )
        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(argv(0), env=slow_env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.PIPE),
            subprocess.Popen(argv(1), env=env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.PIPE),
        ]
        for p in procs:
            _, err = p.communicate(timeout=600)
            assert p.returncode == 0, err.decode()[-2000:]
        wall = time.perf_counter() - t0
        subprocess.run(
            [_sys.executable, "-m", "specpride_tpu", "merge-parts", out,
             "--elastic", coord],
            env=env, check=True, stdout=subprocess.DEVNULL,
        )
        with open(out, "rb") as fh:
            assert fh.read() == golden_bytes, f"{tag} diverged from serial"
        splits = steals = 0
        import glob as _glob

        for jp in _glob.glob(os.path.join(d, "j.jsonl.part*")):
            with open(jp) as fh:
                for line in fh:
                    e = json.loads(line)
                    if e.get("event") == "lease_split":
                        splits += 1
                    elif e.get("event") == "chunk_reassign" and (
                        e.get("via") == "lease_split"
                    ):
                        steals += 1
        return wall, splits, steals

    # unmeasured warmup pair (page cache, compile cache fill)
    skew_fleet("warm", "on", 0)
    skew: dict[str, list] = {"on": [], "off": []}
    counts = {"on": [0, 0], "off": [0, 0]}
    repeats = 2
    for i in range(1, repeats + 1):
        for steal in ("on", "off"):
            wall, splits, steals = skew_fleet(steal, steal, i)
            skew[steal].append(wall)
            counts[steal][0] += splits
            counts[steal][1] += steals
    assert counts["off"] == [0, 0], "steal off but splits journaled"
    on, off = min(skew["on"]), min(skew["off"])

    # healthy 2-rank coordinator-backend overhead: fs vs object store
    healthy_src = _sweep_source(clusters, workdir)

    def healthy_fleet(tag: str, spec: str, out: str) -> float:
        def argv(rank):
            return [
                _sys.executable, "-m", "specpride_tpu", "consensus",
                healthy_src, out, "--method", "bin-mean",
                "--elastic", spec, "--process-id", str(rank),
                "--elastic-range", "512", "--checkpoint-every", "256",
                "--elastic-local", f"{out}.elastic",
            ]

        t0 = time.perf_counter()
        procs = [
            subprocess.Popen(argv(r), env=env, stdout=subprocess.DEVNULL,
                             stderr=subprocess.PIPE)
            for r in range(2)
        ]
        for p in procs:
            _, err = p.communicate(timeout=600)
            assert p.returncode == 0, err.decode()[-2000:]
        wall = time.perf_counter() - t0
        subprocess.run(
            [_sys.executable, "-m", "specpride_tpu", "merge-parts", out,
             "--elastic", spec],
            env=env, check=True, stdout=subprocess.DEVNULL,
        )
        return wall

    walls: dict[str, list[float]] = {"fs": [], "objstore": []}
    outs: dict[str, str] = {}
    for i in range(repeats + 1):  # i == 0 is the unmeasured warmup
        for mode in ("fs", "objstore"):
            d = os.path.join(workdir, f"ov_{mode}_{i}")
            shutil.rmtree(d, ignore_errors=True)
            os.makedirs(d)
            out = os.path.join(d, "out.mgf")
            if mode == "fs":
                wall = healthy_fleet(mode, os.path.join(d, "coord"), out)
            else:
                server = CasServer().start()
                try:
                    wall = healthy_fleet(mode, server.url, out)
                finally:
                    server.stop()
            if i > 0:
                walls[mode].append(wall)
                outs[mode] = out
    with open(outs["fs"], "rb") as fh:
        fs_bytes = fh.read()
    with open(outs["objstore"], "rb") as fh:
        assert fh.read() == fs_bytes, "object-store merge diverged"
    fs_wall = min(walls["fs"])
    os_wall = min(walls["objstore"])

    out = {
        "skewed": {
            "ranks": 2,
            "n_clusters": len(skew_clusters),
            "slow_s_per_chunk": 1.0,
            "repeats": repeats,
            "steal_on_wall_s": round(on, 3),
            "steal_off_wall_s": round(off, 3),
            "makespan_recovery": round(off / on, 3) if on > 0 else None,
            "splits": counts["on"][0],
            "steals": counts["on"][1],
            "steal_on_wall_all_s": [round(w, 3) for w in skew["on"]],
            "steal_off_wall_all_s": [round(w, 3) for w in skew["off"]],
            "byte_identical": True,
        },
        "backend_overhead": {
            "ranks": 2,
            "n_clusters": len(clusters),
            "repeats": repeats,
            "fs_wall_s": round(fs_wall, 3),
            "objstore_wall_s": round(os_wall, 3),
            "overhead_frac": (
                round(os_wall / fs_wall - 1.0, 4) if fs_wall > 0 else None
            ),
            "fs_wall_all_s": [round(w, 3) for w in walls["fs"]],
            "objstore_wall_all_s": [round(w, 3) for w in walls["objstore"]],
            "byte_identical": True,
        },
    }
    eprint(
        f"[elastic_steal] skewed makespan on {on:.2f}s / off {off:.2f}s "
        f"-> {out['skewed']['makespan_recovery']}x recovery "
        f"({counts['on'][0]} splits); healthy fs {fs_wall:.2f}s vs "
        f"objstore {os_wall:.2f}s "
        f"({out['backend_overhead']['overhead_frac']:+.2%})"
    )
    return out


def bench_prefetch_sweep(
    clusters, workdir: str, prefetches=(0, 1, 2, 4)
) -> list[dict]:
    """Pipelined chunk executor (``cli._checkpointed_run`` + ``--prefetch``)
    measured end to end through the CLI, per method x prefetch depth.

    Every run chunks identically (``--checkpoint-every 256``) so serial
    and pipelined schedules process the same worklist; outputs are byte-
    compared against the prefetch-0 run.  Two rates per row: ``wall``
    includes the upfront eager parse (identical across depths, so it
    dilutes the speedup), ``executor`` is the post-parse chunk loop the
    pipeline actually changed.  ``overlap_efficiency`` = 1 −
    device_idle/wall from the run journal's pipeline summary."""
    src = _sweep_source(clusters, workdir)
    rows = []
    for method, command in _SWEEP_METHODS:
        base_bytes = base_exec = None
        for p in prefetches:
            tag = f"pf_{method.replace('-', '_')}_p{p}"
            wall, executor_s, pipe, data = _sweep_run(
                command, method, src, workdir, tag, ["--prefetch", str(p)]
            )
            if base_bytes is None:
                base_bytes, base_exec = data, executor_s
            row = {
                "method": method,
                "prefetch": p,
                "wall_s": round(wall, 3),
                "clusters_per_sec_wall": round(len(clusters) / wall, 2),
                "executor_s": round(executor_s, 3),
                "clusters_per_sec_executor": round(
                    len(clusters) / executor_s, 2
                ),
                "executor_speedup_vs_serial": round(base_exec / executor_s, 3),
                "device_idle_s": pipe.get("device_idle_s"),
                "overlap_efficiency": pipe.get("overlap_efficiency"),
                "identical_to_serial": data == base_bytes,
            }
            rows.append(row)
            eprint(
                f"[prefetch:{method} p={p}] wall "
                f"{row['clusters_per_sec_wall']:.0f} cl/s, executor "
                f"{row['clusters_per_sec_executor']:.0f} cl/s "
                f"({row['executor_speedup_vs_serial']}x vs serial), "
                f"idle={row['device_idle_s']} "
                f"overlap={row['overlap_efficiency']} "
                f"identical={row['identical_to_serial']}"
            )
    return rows


def bench_worker_sweep(
    clusters, workdir: str,
    combos=((0, "off"), (0, "on"), (1, "on"), (2, "on"), (4, "on")),
    prefetch: int = 4,
) -> list[dict]:
    """Multi-lane executor (``--pack-workers`` x ``--async-write``)
    measured end to end through the CLI against a serial (``--prefetch
    0``) baseline, per method.  Same protocol as ``bench_prefetch_sweep``
    (identical chunking via ``--checkpoint-every 256``, byte comparison
    against the serial output, one shared ``_sweep_run`` runner); each
    row additionally records the per-lane busy seconds and the
    reorder-buffer stall time from the run journal's
    ``run_end.pipeline`` summary, so the lane balance — not just the
    headline speedup — is pinned per round."""
    src = _sweep_source(clusters, workdir)
    rows = []
    for method, command in _SWEEP_METHODS:
        base_bytes = base_exec = None
        runs = [("serial", 0, 0, "off")] + [
            (f"pw{pw}_aw_{aw}", prefetch, pw, aw) for pw, aw in combos
        ]
        for label, p, pw, aw in runs:
            tag = f"ws_{method.replace('-', '_')}_{label}"
            wall, executor_s, pipe, data = _sweep_run(
                command, method, src, workdir, tag,
                ["--prefetch", str(p), "--pack-workers", str(pw),
                 "--async-write", aw],
            )
            if base_bytes is None:
                base_bytes, base_exec = data, executor_s
            pack_busy = pipe.get("pack_busy_s") or []
            wall_lane = pipe.get("wall_s") or 0.0
            row = {
                "method": method,
                "prefetch": p,
                "pack_workers": pw,
                "async_write": aw,
                "wall_s": round(wall, 3),
                "executor_s": round(executor_s, 3),
                "clusters_per_sec_executor": round(
                    len(clusters) / executor_s, 2
                ),
                "executor_speedup_vs_serial": round(
                    base_exec / executor_s, 3
                ),
                "device_idle_s": pipe.get("device_idle_s"),
                "overlap_efficiency": pipe.get("overlap_efficiency"),
                "pack_busy_s": pack_busy,
                "pack_busy_frac": round(
                    sum(pack_busy) / (wall_lane * len(pack_busy)), 3
                ) if wall_lane > 0 and pack_busy else None,
                "write_busy_s": pipe.get("write_busy_s"),
                "write_busy_frac": round(
                    pipe["write_busy_s"] / wall_lane, 3
                ) if wall_lane > 0 and pipe.get("write_busy_s") is not None
                else None,
                "reorder_stall_s": pipe.get("reorder_stall_s"),
                "identical_to_serial": data == base_bytes,
            }
            rows.append(row)
            eprint(
                f"[lanes:{method} pw={pw} aw={aw} p={p}] executor "
                f"{row['clusters_per_sec_executor']:.0f} cl/s "
                f"({row['executor_speedup_vs_serial']}x vs serial) "
                f"pack_busy={row['pack_busy_frac']} "
                f"write_busy={row['write_busy_frac']} "
                f"stall={row['reorder_stall_s']} "
                f"identical={row['identical_to_serial']}"
            )
    return rows


_WARM_START_METHODS = (
    # device layouts pinned so each method compiles real XLA kernels on
    # any host (the CPU default layouts route bin-mean/gap-average to
    # host paths that compile nothing — there would be no cold start to
    # measure)
    ("bin-mean", "consensus", ("--layout", "flat", "--force-device")),
    ("gap-average", "consensus",
     ("--layout", "bucketized", "--force-device")),
    ("medoid", "select", ("--layout", "bucketized",)),
)


def bench_warm_start(clusters, workdir: str) -> dict:
    """Cold-start vs warm-start wall time and compile counts per method
    (ROADMAP item 5a; the tentpole acceptance number for this round).

    Each run is a FRESH subprocess (the in-process jit cache would
    otherwise hide the cold start) against one shared ``--compile-cache``
    dir created fresh for this bench: the cold run pays every XLA
    compile and seeds the shape manifest; the warm rerun AOT-warms from
    the manifest and must journal ZERO fresh compiles
    (``run_end.compile_cache.misses == 0``).  Wall time includes process
    + jax startup — exactly what a CLI user experiences."""
    import os
    import subprocess
    import sys

    src = _sweep_source(clusters, workdir)
    cache = os.path.join(workdir, "warm_cache")
    rows = []
    for method, command, flags in _WARM_START_METHODS:
        row: dict = {"method": method, "flags": list(flags)}
        for phase in ("cold", "warm"):
            tag = f"wsb_{method.replace('-', '_')}_{phase}"
            journal = os.path.join(workdir, f"{tag}.jsonl")
            out = os.path.join(workdir, f"{tag}.mgf")
            argv = [
                sys.executable, "-m", "specpride_tpu", command, src, out,
                "--method", method, "--backend", "tpu",
                "--compile-cache", cache, "--journal", journal,
                *flags,
            ]
            t0 = time.perf_counter()
            proc = subprocess.run(
                argv, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE,
            )
            wall = time.perf_counter() - t0
            assert proc.returncode == 0, (
                method, phase, proc.stderr.decode(errors="replace")[-2000:]
            )
            with open(journal) as fh:
                events = [json.loads(line) for line in fh]
            end = [e for e in events if e["event"] == "run_end"][-1]
            cc = end.get("compile_cache") or {}
            warmups = [e for e in events if e["event"] == "warmup"]
            row[phase] = {
                "wall_s": round(wall, 3),
                "run_elapsed_s": end["elapsed_s"],
                # fresh XLA compiles (persistent-cache misses) vs loads
                "fresh_compiles": cc.get("misses"),
                "cache_hits": cc.get("hits"),
                # traced (kernel, shape-class) combos — the per-process
                # upper bound the compile-vs-cached tracing layer sees
                "compile_events": sum(
                    1 for e in events if e["event"] == "compile"
                ),
                "kernels_warmed": len(warmups),
                "warmup_s": round(
                    sum(e.get("seconds", 0.0) for e in warmups), 3
                ),
            }
        row["cold_minus_warm_wall_s"] = round(
            row["cold"]["wall_s"] - row["warm"]["wall_s"], 3
        )
        row["warm_speedup_wall"] = round(
            row["cold"]["wall_s"] / row["warm"]["wall_s"], 3
        )
        rows.append(row)
        eprint(
            f"[warm_start:{method}] cold {row['cold']['wall_s']}s "
            f"({row['cold']['fresh_compiles']} fresh compiles) -> warm "
            f"{row['warm']['wall_s']}s "
            f"({row['warm']['fresh_compiles']} fresh, "
            f"{row['warm']['kernels_warmed']} warmed) "
            f"= {row['warm_speedup_wall']}x wall"
        )
        assert row["warm"]["fresh_compiles"] == 0, (
            f"{method}: warm rerun still compiled "
            f"{row['warm']['fresh_compiles']} kernels"
        )
    return {"cache_dir": "fresh per bench invocation", "methods": rows}


def bench_serving(
    clusters, workdir: str, n_serving_clusters: int = 192,
    seq_runs: int = 4, load_total_jobs: int = 8,
) -> dict:
    """``specpride serve`` vs the one-shot CLI — the BENCH_r11
    acceptance numbers: first-request vs warm-request wall per method
    through a live daemon, and daemon jobs/sec under a 2- and 8-client
    closed-loop load generator vs sequential one-shot CLI subprocess
    runs of the same job.

    The serving workload is a SUBSET of the bench clusters: the
    daemon's scenario is repeated small/medium jobs, where per-job
    startup (process + jax import + trace + compile) is the bill being
    amortized — on one huge job the compute dominates and serving wins
    nothing by construction.  Device layouts are pinned (bucketized +
    --force-device, the _WARM_START_METHODS convention) so every method
    compiles real kernels on any host and the first-vs-warm delta
    measures the warm-kernel machinery, not a host-path accident."""
    import os
    import signal as _signal
    import subprocess
    import sys
    import threading

    from specpride_tpu.io.mgf import write_mgf
    from specpride_tpu.serve import client as sc

    sub = clusters[: min(n_serving_clusters, len(clusters))]
    src = os.path.join(workdir, "serving_clustered.mgf")
    write_mgf([s for c in sub for s in c.members], src)
    sock = os.path.join(workdir, "serve.sock")
    cache = os.path.join(workdir, "serve_cache")  # fresh per bench
    journal = os.path.join(workdir, "serve.jsonl")
    t_boot0 = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-m", "specpride_tpu", "serve",
         "--socket", sock, "--compile-cache", cache,
         "--layout", "bucketized", "--force-device",
         "--journal", journal, "--max-queue", "32"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        assert sc.wait_for_socket(sock, timeout=300), "daemon never booted"
        boot_s = time.perf_counter() - t_boot0

        def served(tag, method, command):
            out = os.path.join(workdir, f"sv_{tag}.mgf")
            t0 = time.perf_counter()
            term = sc.submit_wait(
                sock, [command, src, out, "--method", method], timeout=600
            )
            wall = time.perf_counter() - t0
            assert term["status"] == "done", (tag, term)
            return wall, term, out

        rows = []
        for method, command in _SWEEP_METHODS:
            tagm = method.replace("-", "_")
            first_wall, first, _ = served(f"{tagm}_first", method, command)
            warm_wall, warm, _ = served(f"{tagm}_warm", method, command)
            row = {
                "method": method,
                "first_request_wall_s": round(first_wall, 3),
                "warm_request_wall_s": round(warm_wall, 3),
                "warm_speedup": round(first_wall / warm_wall, 3),
                "first_fresh_compiles": first["compile_cache"]["misses"],
                "warm_fresh_compiles": warm["compile_cache"]["misses"],
            }
            assert row["warm_fresh_compiles"] == 0, row
            rows.append(row)
            eprint(
                f"[serving:{method}] first {first_wall:.2f}s "
                f"({row['first_fresh_compiles']} fresh compiles) -> warm "
                f"{warm_wall:.2f}s = {row['warm_speedup']}x"
            )

        # sequential one-shot CLI baseline: the SAME bin-mean job, a
        # fresh process per run, against the daemon's (now warm) compile
        # cache — the fairest baseline: it still pays process + jax
        # start + in-process trace per run, which is exactly the bill
        # serving deletes
        seq_out = os.path.join(workdir, "seq_out.mgf")
        argv = [
            sys.executable, "-m", "specpride_tpu", "consensus", src,
            seq_out, "--method", "bin-mean",
            "--layout", "bucketized", "--force-device",
            "--compile-cache", cache,
        ]
        seq_walls = []
        for _ in range(seq_runs):
            t0 = time.perf_counter()
            p = subprocess.run(
                argv, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE
            )
            assert p.returncode == 0, \
                p.stderr.decode(errors="replace")[-2000:]
            seq_walls.append(time.perf_counter() - t0)
        cli_jobs_per_sec = seq_runs / sum(seq_walls)
        eprint(
            f"[serving] sequential one-shot CLI: "
            f"{cli_jobs_per_sec:.3f} jobs/sec "
            f"(walls {[round(w, 2) for w in seq_walls]})"
        )

        load_rows = []
        for n_clients in (2, 8):
            jobs_per_client = max(1, load_total_jobs // n_clients)
            total = jobs_per_client * n_clients
            errors: list = []

            def _client(cid, jobs_per_client=jobs_per_client,
                        n_clients=n_clients):
                try:
                    for j in range(jobs_per_client):
                        out = os.path.join(
                            workdir, f"load_{n_clients}_{cid}_{j}.mgf"
                        )
                        term = sc.submit_wait(
                            sock,
                            ["consensus", src, out, "--method", "bin-mean"],
                            timeout=600,
                            # distinct scheduling identity per simulated
                            # client, so the load exercises the daemon's
                            # round-robin fairness, not one-client FIFO
                            client=f"loadgen-{n_clients}-{cid}",
                        )
                        if term.get("status") != "done":
                            errors.append(term)
                except Exception as e:  # noqa: BLE001 - surfaced below
                    errors.append(repr(e))

            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=_client, args=(c,))
                for c in range(n_clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            assert not errors, errors[:3]
            jobs_per_sec = total / wall
            load_rows.append({
                "clients": n_clients,
                "jobs": total,
                "wall_s": round(wall, 3),
                "jobs_per_sec": round(jobs_per_sec, 3),
                "speedup_vs_sequential_cli": round(
                    jobs_per_sec / cli_jobs_per_sec, 3
                ),
            })
            eprint(
                f"[serving] {n_clients}-client closed loop: {total} jobs "
                f"in {wall:.2f}s = {jobs_per_sec:.3f} jobs/sec "
                f"({load_rows[-1]['speedup_vs_sequential_cli']}x vs "
                "sequential CLI)"
            )
        # served-vs-CLI byte parity held under load too
        with open(seq_out, "rb") as fh:
            cli_bytes = fh.read()
        with open(os.path.join(workdir, "load_2_0_0.mgf"), "rb") as fh:
            assert fh.read() == cli_bytes, \
                "served load output diverged from the one-shot CLI's"
        proc.send_signal(_signal.SIGTERM)
        rc = proc.wait(timeout=300)
        assert rc == 0, f"daemon SIGTERM drain exited {rc}"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    return {
        "n_serving_clusters": len(sub),
        "boot_s": round(boot_s, 3),
        "methods": rows,
        "sequential_cli_wall_s": [round(w, 3) for w in seq_walls],
        "sequential_cli_jobs_per_sec": round(cli_jobs_per_sec, 3),
        "load": load_rows,
        "drain": "SIGTERM exit 0 after load",
    }


def bench_serving_concurrency(
    clusters, workdir: str, n_serving_clusters: int = 192,
    workers_list=(1, 2, 4), clients_list=(2, 8), load_total_jobs: int = 16,
) -> dict:
    """Concurrent execution lanes (``serve --workers N``) — the
    BENCH_r14 acceptance numbers: closed-loop daemon jobs/sec at
    workers x clients, every cell's served bytes compared against the
    one-shot CLI's, and the speedup each pool size buys over the
    single-lane daemon on THIS host.

    One persistent compile cache spans all three daemon boots, so the
    workers=1 arm's warmup pays the compiles once and every measured
    job runs warm (each cell's terminal messages are asserted to report
    zero fresh compiles — the per-worker warm bar).  Layouts are pinned
    exactly like the BENCH_r11 serving section (bucketized +
    --force-device) so the single-lane row is comparable to the r11/r12
    single-worker baselines recorded alongside."""
    import os
    import signal as _signal
    import subprocess
    import sys
    import threading

    from specpride_tpu.io.mgf import write_mgf
    from specpride_tpu.serve import client as sc

    sub = clusters[: min(n_serving_clusters, len(clusters))]
    src = os.path.join(workdir, "conc_clustered.mgf")
    write_mgf([s for c in sub for s in c.members], src)
    cache = os.path.join(workdir, "conc_cache")  # shared across boots
    # the one-shot CLI golden bytes every served cell must reproduce
    golden_path = os.path.join(workdir, "conc_cli.mgf")
    p = subprocess.run(
        [sys.executable, "-m", "specpride_tpu", "consensus", src,
         golden_path, "--method", "bin-mean",
         "--layout", "bucketized", "--force-device",
         "--compile-cache", cache],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    assert p.returncode == 0, p.stderr.decode(errors="replace")[-2000:]
    with open(golden_path, "rb") as fh:
        golden = fh.read()

    rows = []
    for n_workers in workers_list:
        sock = os.path.join(workdir, f"conc_{n_workers}.sock")
        journal = os.path.join(workdir, f"conc_{n_workers}.jsonl")
        t_boot0 = time.perf_counter()
        proc = subprocess.Popen(
            [sys.executable, "-m", "specpride_tpu", "serve",
             "--socket", sock, "--compile-cache", cache,
             "--layout", "bucketized", "--force-device",
             "--journal", journal, "--max-queue", "64",
             "--workers", str(n_workers)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            assert sc.wait_for_socket(sock, timeout=300), \
                f"--workers {n_workers} daemon never booted"
            boot_s = time.perf_counter() - t_boot0
            # warm every lane before measuring: 2x workers jobs
            # (sequential submits, concurrent lanes) through the shared
            # cache; the measured jobs below must then be fully warm
            for w in range(max(2, 2 * n_workers)):
                term = sc.submit_wait(
                    sock,
                    ["consensus", src,
                     os.path.join(workdir, f"warm_{n_workers}_{w}.mgf"),
                     "--method", "bin-mean"],
                    timeout=600, client=f"warmup-{w}",
                )
                assert term["status"] == "done", term
            row = {"workers": n_workers, "boot_s": round(boot_s, 3),
                   "load": []}
            for n_clients in clients_list:
                jobs_per_client = max(1, load_total_jobs // n_clients)
                total = jobs_per_client * n_clients
                errors: list = []
                fresh: list = []

                def _client(cid, jobs_per_client=jobs_per_client,
                            n_clients=n_clients, n_workers=n_workers):
                    try:
                        for j in range(jobs_per_client):
                            out = os.path.join(
                                workdir,
                                f"conc_{n_workers}_{n_clients}_{cid}_{j}"
                                ".mgf",
                            )
                            term = sc.submit_wait(
                                sock,
                                ["consensus", src, out, "--method",
                                 "bin-mean"],
                                timeout=600,
                                client=f"loadgen-{n_clients}-{cid}",
                            )
                            if term.get("status") != "done":
                                errors.append(term)
                            else:
                                fresh.append(
                                    term["compile_cache"].get("misses", 0)
                                )
                    except Exception as e:  # noqa: BLE001 - surfaced below
                        errors.append(repr(e))

                t0 = time.perf_counter()
                threads = [
                    threading.Thread(target=_client, args=(c,))
                    for c in range(n_clients)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                assert not errors, errors[:3]
                # per-worker warm bar: every measured job compiled
                # NOTHING fresh (lanes share the warm platform/cache)
                assert all(f == 0 for f in fresh), fresh
                # byte parity in EVERY cell: each served output must
                # equal the one-shot CLI bytes
                n_checked = 0
                for cid in range(n_clients):
                    for j in range(jobs_per_client):
                        path = os.path.join(
                            workdir,
                            f"conc_{n_workers}_{n_clients}_{cid}_{j}.mgf",
                        )
                        with open(path, "rb") as fh:
                            assert fh.read() == golden, path
                        n_checked += 1
                jobs_per_sec = total / wall
                row["load"].append({
                    "clients": n_clients,
                    "jobs": total,
                    "wall_s": round(wall, 3),
                    "jobs_per_sec": round(jobs_per_sec, 3),
                    "byte_parity_jobs": n_checked,
                })
                eprint(
                    f"[serving_concurrency] workers={n_workers} "
                    f"clients={n_clients}: {total} jobs in {wall:.2f}s "
                    f"= {jobs_per_sec:.3f} jobs/sec (all byte-identical, "
                    f"0 fresh compiles)"
                )
            proc.send_signal(_signal.SIGTERM)
            rc = proc.wait(timeout=300)
            assert rc == 0, f"--workers {n_workers} drain exited {rc}"
            rows.append(row)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    # speedups vs the single-lane row, per client count, and vs the
    # recorded PR 7/8 single-worker baselines (r11 serving load on the
    # same 192-cluster recipe; r12's telemetry-armed closed loop)
    r11 = {2: 1.406, 8: 1.434}
    r12_armed = 2.991
    base = {
        cell["clients"]: cell["jobs_per_sec"]
        for cell in rows[0]["load"]
    }
    for row in rows:
        for cell in row["load"]:
            cell["speedup_vs_workers1"] = round(
                cell["jobs_per_sec"] / base[cell["clients"]], 3
            )
            if cell["clients"] in r11:
                cell["speedup_vs_bench_r11"] = round(
                    cell["jobs_per_sec"] / r11[cell["clients"]], 3
                )
            cell["speedup_vs_bench_r12_armed"] = round(
                cell["jobs_per_sec"] / r12_armed, 3
            )
    return {
        "n_serving_clusters": len(sub),
        "load_total_jobs": load_total_jobs,
        "rows": rows,
        # the PR 7/8 single-worker context: BENCH_r11's serving load
        # (same workload size/layout recipe, jobs/sec 1.406 @ 2 clients
        # / 1.434 @ 8) and BENCH_r12's telemetry-armed closed loop
        # (2.991 jobs/sec on a smaller 128-cluster workload)
        "baselines": {
            "bench_r11_load_jobs_per_sec": {"2": 1.406, "8": 1.434},
            "bench_r12_telemetry_armed_jobs_per_sec": 2.991,
        },
    }


def bench_serving_batching(
    clusters, workdir: str, n_files: int = 4, clusters_per_file: int = 8,
    jobs_per_client: int = 6, workers_list=(1, 2),
    windows_ms=(0, 10, 50), slo_s: float = 30.0,
) -> dict:
    """Cross-job micro-batching (``serve --batch-window``) — the
    BENCH_r16 acceptance numbers: closed-loop SMALL-job daemon load at
    workers x batch-window, jobs/sec + shared-dispatch bucket occupancy
    + client-observed p50/p99 latency, byte parity per cell, and the
    batching-on vs batching-off speedup at each worker count.

    The workload is the regime BENCH_r14 plateaued on: each tenant job
    is a few-cluster input whose solo dispatch under-fills the 64-row
    bucket floor (occupancy ~12%) and pays the fixed dispatch overhead
    alone; the batch window lets concurrent tenants' jobs merge into
    one well-filled dispatch.  Four tenants submit from DISTINCT input
    files, so every shared dispatch exercises the multi-source merged
    pack, not same-input fan-out.  Layouts are pinned (bucketized +
    --force-device) exactly like the serving_concurrency section so
    the device-dispatch economics are the ones being measured; one
    compile cache spans every boot and each cell warms until a full
    closed-loop pass performs zero fresh compiles (solo AND shared
    shapes) before the measured pass."""
    import os
    import signal as _signal
    import statistics
    import subprocess
    import sys
    import threading

    from specpride_tpu.io.mgf import write_mgf
    from specpride_tpu.serve import client as sc

    # distinct small tenant inputs from distinct bench-cluster slices
    srcs, goldens = [], []
    cache = os.path.join(workdir, "batch_cache")  # shared across boots
    for i in range(n_files):
        part = clusters[
            i * clusters_per_file : (i + 1) * clusters_per_file
        ]
        assert part, "bench workload too small for the batching section"
        src = os.path.join(workdir, f"batch_in_{i}.mgf")
        write_mgf([s for c in part for s in c.members], src)
        srcs.append(src)
        golden_path = os.path.join(workdir, f"batch_cli_{i}.mgf")
        p = subprocess.run(
            [sys.executable, "-m", "specpride_tpu", "consensus", src,
             golden_path, "--method", "bin-mean",
             "--qc-report", golden_path + ".qc.json",
             "--layout", "bucketized", "--force-device",
             "--compile-cache", cache],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        assert p.returncode == 0, p.stderr.decode(errors="replace")[-2000:]
        with open(golden_path, "rb") as fh:
            goldens.append(fh.read())

    def _journal_events(path):
        import json as _json

        out = []
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    try:
                        out.append(_json.loads(line))
                    except ValueError:
                        pass  # torn in-progress tail
        except OSError:
            pass
        return out

    rows = []
    for n_workers in workers_list:
        for window_ms in windows_ms:
            tag = f"w{n_workers}_b{window_ms}"
            sock = os.path.join(workdir, f"batch_{tag}.sock")
            journal = os.path.join(workdir, f"batch_{tag}.jsonl")
            proc = subprocess.Popen(
                [sys.executable, "-m", "specpride_tpu", "serve",
                 "--socket", sock, "--compile-cache", cache,
                 "--layout", "bucketized", "--force-device",
                 "--journal", journal, "--max-queue", "64",
                 "--workers", str(n_workers),
                 "--batch-window", str(window_ms),
                 "--slo", f"*={slo_s:g}"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            try:
                assert sc.wait_for_socket(sock, timeout=300), \
                    f"{tag}: daemon never booted"

                def _loop(phase, tag=tag):
                    """One closed-loop pass: n_files clients, each
                    submitting jobs_per_client jobs over ITS OWN input.
                    Returns (wall, latencies, fresh, outputs)."""
                    errors: list = []
                    lat: list = []
                    fresh: list = []
                    outs: list = []
                    lock = threading.Lock()

                    def _client(cid):
                        try:
                            for j in range(jobs_per_client):
                                out = os.path.join(
                                    workdir,
                                    f"batch_{tag}_{phase}_{cid}_{j}.mgf",
                                )
                                t0 = time.perf_counter()
                                term = sc.submit_wait(
                                    sock,
                                    ["consensus", srcs[cid], out,
                                     "--method", "bin-mean",
                                     "--qc-report", out + ".qc.json"],
                                    timeout=600,
                                    client=f"tenant-{cid}",
                                )
                                dt = time.perf_counter() - t0
                                if term.get("status") != "done":
                                    errors.append(term)
                                    return
                                with lock:
                                    lat.append(dt)
                                    fresh.append(
                                        term["compile_cache"].get(
                                            "misses", 0)
                                    )
                                    outs.append((cid, out))
                        except Exception as e:  # noqa: BLE001
                            errors.append(repr(e))

                    t0 = time.perf_counter()
                    threads = [
                        threading.Thread(target=_client, args=(c,))
                        for c in range(n_files)
                    ]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                    wall = time.perf_counter() - t0
                    assert not errors, errors[:3]
                    return wall, lat, fresh, outs

                # warm until a full pass compiles nothing fresh —
                # neither per-job nor in shared batch dispatches (merged
                # row classes are new shapes on the first batched pass)
                for attempt in range(4):
                    n_ev = len(_journal_events(journal))
                    _, _, fresh, _ = _loop(f"warm{attempt}")
                    new_ev = _journal_events(journal)[n_ev:]
                    batch_fresh = sum(
                        e.get("fresh_compiles", 0) for e in new_ev
                        if e.get("event") == "batch_dispatch"
                    )
                    if all(f == 0 for f in fresh) and batch_fresh == 0:
                        break

                n_ev = len(_journal_events(journal))
                wall, lat, fresh, outs = _loop("measured")
                total = len(lat)
                assert total == n_files * jobs_per_client, total
                # warm bar: the measured pass compiled NOTHING fresh
                assert all(f == 0 for f in fresh), fresh
                new_ev = _journal_events(journal)[n_ev:]
                shared = [
                    e for e in new_ev
                    if e.get("event") == "batch_dispatch"
                    and e.get("status") == "shared"
                ]
                assert sum(
                    e.get("fresh_compiles", 0) for e in shared
                ) == 0, shared
                slo_breaches = sum(
                    1 for e in new_ev
                    if e.get("event") == "job_done"
                    and e.get("slo_ok") is False
                )
                # byte + QC parity in EVERY cell, for every job
                import json as _json

                for cid, out in outs:
                    with open(out, "rb") as fh:
                        assert fh.read() == goldens[cid], out
                    with open(out + ".qc.json") as fh:
                        got_qc = _json.load(fh)
                    with open(
                        os.path.join(
                            workdir, f"batch_cli_{cid}.mgf.qc.json"
                        )
                    ) as fh:
                        assert got_qc == _json.load(fh), out
                lat.sort()
                row = {
                    "workers": n_workers,
                    "batch_window_ms": window_ms,
                    "jobs": total,
                    "wall_s": round(wall, 3),
                    "jobs_per_sec": round(total / wall, 3),
                    "latency_p50_s": round(
                        lat[len(lat) // 2], 4),
                    "latency_p99_s": round(
                        lat[min(len(lat) - 1,
                                int(len(lat) * 0.99))], 4),
                    "latency_mean_s": round(
                        statistics.fmean(lat), 4),
                    "batch_dispatches": len(shared),
                    "batched_jobs": sum(
                        e.get("n_jobs", 0) for e in shared),
                    "mean_jobs_per_dispatch": round(
                        sum(e.get("n_jobs", 0) for e in shared)
                        / len(shared), 2) if shared else 0.0,
                    "mean_bucket_occupancy": round(
                        sum(e.get("bucket_occupancy_frac", 0.0)
                            for e in shared) / len(shared), 4,
                    ) if shared else None,
                    "slo_breaches": slo_breaches,
                    "byte_parity_jobs": total,
                }
                rows.append(row)
                eprint(
                    f"[serving_batching] workers={n_workers} "
                    f"window={window_ms}ms: {total} jobs in "
                    f"{wall:.2f}s = {row['jobs_per_sec']:.3f} jobs/sec, "
                    f"{len(shared)} shared dispatch(es) covering "
                    f"{row['batched_jobs']} jobs "
                    f"(occupancy {row['mean_bucket_occupancy']}), "
                    f"p99 {row['latency_p99_s']:.3f}s, all "
                    "byte-identical, 0 fresh compiles"
                )
                proc.send_signal(_signal.SIGTERM)
                rc = proc.wait(timeout=300)
                assert rc == 0, f"{tag}: drain exited {rc}"
            finally:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
    # the acceptance ratio: batching-on vs batching-off at the same
    # worker count (same closed-loop load, same host)
    for row in rows:
        base = next(
            r for r in rows
            if r["workers"] == row["workers"]
            and r["batch_window_ms"] == 0
        )
        row["speedup_vs_window0"] = round(
            row["jobs_per_sec"] / base["jobs_per_sec"], 3
        )
    return {
        "n_files": n_files,
        "clusters_per_file": clusters_per_file,
        "jobs_per_client": jobs_per_client,
        "slo_objective_s": slo_s,
        "rows": rows,
        "baseline": {
            "bench_r14_note": "BENCH_r14 serving_concurrency plateaued "
            "at 1.75x (2 workers, 8 clients) on small jobs — per-job "
            "dispatches under-fill the 64-row bucket floor; this "
            "section measures the shared-dispatch remedy",
        },
    }


def bench_autotune(
    clusters, workdir: str, n_files: int = 6, clusters_per_file: int = 8,
    burst_jobs_per_client: int = 6, lone_jobs: int = 10,
) -> dict:
    """Closed-loop controller A/B (BENCH_r18 acceptance): a SHIFTING
    two-phase workload — a concurrent small-job burst where batching
    wins, then a sequential lone-job phase where any collection window
    is pure added latency — served by three configs: ``static-0``
    (batching off), ``static-50`` (50ms window, the burst's friend),
    and ``autotune`` (``--autotune on`` over the full 0:50 clamp,
    booted at window 0).  No single static window is right for both
    phases; the controller must widen during the burst and shrink back
    for the lone phase, landing at-or-near the best static config in
    EACH phase without a human picking the number.  Byte parity holds
    for every job in every cell, and the controller's journal must
    replay bit-exact (`specpride autotune-replay` semantics, run
    in-process)."""
    import os
    import signal as _signal
    import statistics
    import subprocess
    import sys
    import threading

    from specpride_tpu.autotune.replay import replay_journal
    from specpride_tpu.io.mgf import write_mgf
    from specpride_tpu.serve import client as sc

    # distinct small tenant inputs (the batching section's regime:
    # each solo dispatch under-fills the 64-row bucket floor)
    srcs, goldens = [], []
    cache = os.path.join(workdir, "at_cache")  # shared across boots
    for i in range(n_files):
        part = clusters[
            i * clusters_per_file : (i + 1) * clusters_per_file
        ]
        assert part, "bench workload too small for the autotune section"
        src = os.path.join(workdir, f"at_in_{i}.mgf")
        write_mgf([s for c in part for s in c.members], src)
        srcs.append(src)
        golden_path = os.path.join(workdir, f"at_cli_{i}.mgf")
        p = subprocess.run(
            [sys.executable, "-m", "specpride_tpu", "consensus", src,
             golden_path, "--method", "bin-mean",
             "--layout", "bucketized", "--force-device",
             "--compile-cache", cache],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        assert p.returncode == 0, p.stderr.decode(errors="replace")[-2000:]
        with open(golden_path, "rb") as fh:
            goldens.append(fh.read())

    configs = (
        ("static-0", ["--batch-window", "0"]),
        ("static-50", ["--batch-window", "50"]),
        ("autotune", ["--batch-window", "0", "--autotune", "on",
                      "--autotune-interval", "0.2",
                      "--autotune-batch-window", "0:50"]),
    )
    rows = []
    for name, flags in configs:
        sock = os.path.join(workdir, f"at_{name}.sock")
        journal = os.path.join(workdir, f"at_{name}.jsonl")
        proc = subprocess.Popen(
            [sys.executable, "-m", "specpride_tpu", "serve",
             "--socket", sock, "--compile-cache", cache,
             "--layout", "bucketized", "--force-device",
             "--journal", journal, "--max-queue", "64",
             "--workers", "1"] + flags,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            assert sc.wait_for_socket(sock, timeout=300), \
                f"{name}: daemon never booted"

            def _submit(cid, out):
                t0 = time.perf_counter()
                term = sc.submit_wait(
                    sock,
                    ["consensus", srcs[cid], out, "--method",
                     "bin-mean"],
                    timeout=600, client=f"tenant-{cid}",
                )
                assert term.get("status") == "done", term
                return (time.perf_counter() - t0,
                        term["compile_cache"].get("misses", 0), out)

            def _burst(phase):
                """Phase A: n_files clients submit concurrently."""
                results: list = []
                errors: list = []
                lock = threading.Lock()

                def _client(cid):
                    try:
                        for j in range(burst_jobs_per_client):
                            out = os.path.join(
                                workdir,
                                f"at_{name}_{phase}_{cid}_{j}.mgf",
                            )
                            got = _submit(cid, out)
                            with lock:
                                results.append((cid,) + got)
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))

                t0 = time.perf_counter()
                threads = [
                    threading.Thread(target=_client, args=(c,))
                    for c in range(n_files)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                assert not errors, errors[:3]
                return wall, results

            # warm until a full burst pass compiles nothing fresh
            for attempt in range(4):
                _, warm = _burst(f"warm{attempt}")
                if all(f == 0 for _, _, f, _ in warm):
                    break

            # phase A: the concurrent burst
            burst_wall, burst = _burst("burst")
            assert all(f == 0 for _, _, f, _ in burst), burst
            # phase B: sequential lone jobs — an empty queue between
            # each, so any collection window is pure added latency
            lone: list = []
            lone_t0 = time.perf_counter()
            for j in range(lone_jobs):
                out = os.path.join(workdir, f"at_{name}_lone_{j}.mgf")
                lone.append((j % n_files,) + _submit(j % n_files, out))
            lone_wall = time.perf_counter() - lone_t0
            assert all(f == 0 for _, _, f, _ in lone), lone

            for cid, _, _, out in burst + lone:
                with open(out, "rb") as fh:
                    assert fh.read() == goldens[cid], out

            proc.send_signal(_signal.SIGTERM)
            rc = proc.wait(timeout=300)
            assert rc == 0, f"{name}: drain exited {rc}"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

        lone_lat = sorted(dt for _, dt, _, _ in lone)
        n_burst = len(burst)
        row = {
            "config": name,
            "burst_jobs": n_burst,
            "burst_wall_s": round(burst_wall, 3),
            "burst_jobs_per_sec": round(n_burst / burst_wall, 3),
            "lone_jobs": len(lone),
            "lone_wall_s": round(lone_wall, 3),
            "lone_latency_p50_s": round(
                lone_lat[len(lone_lat) // 2], 4),
            "lone_latency_mean_s": round(
                statistics.fmean(lone_lat), 4),
            "total_wall_s": round(burst_wall + lone_wall, 3),
            "byte_parity_jobs": n_burst + len(lone),
        }
        if name == "autotune":
            import json as _json

            events = [_json.loads(ln) for ln in open(journal)]
            at = [e for e in events if e.get("event") == "autotune"]
            acted = [e for e in at if e.get("acted")]
            assert acted, "the autotune config never acted on a knob"
            row["decisions"] = len(at)
            row["acted"] = len(acted)
            row["decision_log"] = [
                {"knob": e["knob"], "old": e["old"], "new": e["new"],
                 "reason": e["reason"]} for e in acted
            ]
            # the determinism audit over the bench's own journal
            rep = replay_journal(journal)
            assert rep["ok"], rep
            row["replay"] = {
                "decisions": rep["decisions"],
                "reproduced": rep["reproduced"],
                "ok": rep["ok"],
            }
        rows.append(row)
        eprint(
            f"[autotune] {name}: burst {n_burst} jobs in "
            f"{burst_wall:.2f}s = {row['burst_jobs_per_sec']:.2f} "
            f"jobs/sec; lone p50 {row['lone_latency_p50_s']:.3f}s; "
            f"total {row['total_wall_s']:.2f}s"
            + (f"; {row['acted']} acted decision(s), replay ok"
               if name == "autotune" else "")
        )
    by = {r["config"]: r for r in rows}
    return {
        "n_files": n_files,
        "clusters_per_file": clusters_per_file,
        "burst_jobs_per_client": burst_jobs_per_client,
        "lone_jobs": lone_jobs,
        "rows": rows,
        "verdict": {
            # the controller's bar: at-or-near the best static config
            # in EACH phase of the shifting workload
            "burst_vs_best_static": round(
                by["autotune"]["burst_wall_s"]
                / min(by["static-0"]["burst_wall_s"],
                      by["static-50"]["burst_wall_s"]), 3),
            "lone_vs_best_static": round(
                by["autotune"]["lone_wall_s"]
                / min(by["static-0"]["lone_wall_s"],
                      by["static-50"]["lone_wall_s"]), 3),
            "total_vs_best_single_static": round(
                by["autotune"]["total_wall_s"]
                / min(by["static-0"]["total_wall_s"],
                      by["static-50"]["total_wall_s"]), 3),
        },
    }


def bench_telemetry(
    clusters, workdir: str, n_serving_clusters: int = 128,
    repeats: int = 5, jobs_per_batch: int = 6, extra_scrapes: int = 100,
    scrape_interval_s: float = 0.25,
) -> dict:
    """Cost of the LIVE telemetry plane (BENCH_r12 acceptance): daemon
    jobs/sec with the /metrics exporter + SLO accounting armed (and a
    scraper polling the endpoint at 4 Hz throughout the load — an order
    of magnitude above Prometheus's usual 1/15 Hz) vs a disarmed daemon
    — target: below host noise, same min-estimator as the PR5
    fault_overhead section — plus /metrics scrape latency p50/p99.

    Both arms run against ONE shared compile cache and pay one
    unmeasured warmup job after boot, so every measured batch is fully
    warm; the min over per-arm batch walls is the low-noise view of the
    constant per-job cost being measured.  (Each scrape renders the
    exposition while holding the GIL; a pathological 100 Hz scraper
    measurably contends with job execution — the scrape-latency
    percentiles below bound that cost per scrape so an operator can
    budget their own cadence.)"""
    import os
    import signal as _signal
    import statistics
    import subprocess
    import sys
    import threading
    import urllib.request

    from specpride_tpu.io.mgf import write_mgf
    from specpride_tpu.serve import client as sc

    sub = clusters[: min(n_serving_clusters, len(clusters))]
    src = os.path.join(workdir, "telemetry_clustered.mgf")
    write_mgf([s for c in sub for s in c.members], src)
    cache = os.path.join(workdir, "telemetry_cache")  # shared: both warm

    def run_arm(tag: str, armed: bool):
        sock = os.path.join(workdir, f"tel_{tag}.sock")
        argv = [
            sys.executable, "-m", "specpride_tpu", "serve",
            "--socket", sock, "--compile-cache", cache,
            "--layout", "bucketized", "--force-device",
            "--max-queue", "32",
        ]
        if armed:
            argv += [
                "--metrics-port", "0",
                "--slo", "bin-mean=300,*=300",
            ]
        proc = subprocess.Popen(
            argv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
        )
        scrape_s: list[float] = []
        stop_scraper = threading.Event()
        try:
            assert sc.wait_for_socket(sock, timeout=300), \
                f"{tag} daemon never booted"
            url = None
            if armed:
                status = sc.request(sock, {"op": "status"})
                url = status["metrics_url"]

            def one_job(i: int) -> None:
                out = os.path.join(workdir, f"tel_{tag}_{i}.mgf")
                term = sc.submit_wait(
                    sock,
                    ["consensus", src, out, "--method", "bin-mean"],
                    timeout=600,
                )
                assert term["status"] == "done", (tag, term)

            one_job(-1)  # unmeasured warmup: first job pays any compiles

            def _scraper() -> None:
                # the armed arm is measured UNDER scrape pressure — the
                # whole point is the cost of being observed
                while not stop_scraper.is_set():
                    t0 = time.perf_counter()
                    try:
                        urllib.request.urlopen(url, timeout=10).read()
                        scrape_s.append(time.perf_counter() - t0)
                    except OSError:
                        pass
                    stop_scraper.wait(scrape_interval_s)

            scraper = None
            if armed:
                scraper = threading.Thread(target=_scraper, daemon=True)
                scraper.start()
            batch_walls = []
            job_seq = 0
            for _ in range(repeats):
                t0 = time.perf_counter()
                for _ in range(jobs_per_batch):
                    one_job(job_seq)
                    job_seq += 1
                batch_walls.append(time.perf_counter() - t0)
            if armed:
                # a deterministic scrape-latency sample on the still-
                # live (now idle) daemon tops up the under-load ones
                for _ in range(extra_scrapes):
                    t0 = time.perf_counter()
                    urllib.request.urlopen(url, timeout=10).read()
                    scrape_s.append(time.perf_counter() - t0)
                stop_scraper.set()
                scraper.join(timeout=10)
            proc.send_signal(_signal.SIGTERM)
            rc = proc.wait(timeout=300)
            assert rc == 0, f"{tag} daemon SIGTERM drain exited {rc}"
        finally:
            stop_scraper.set()
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        return batch_walls, scrape_s

    disarmed_walls, _ = run_arm("disarmed", armed=False)
    armed_walls, scrape_s = run_arm("armed", armed=True)
    best_dis, best_arm = min(disarmed_walls), min(armed_walls)
    lat_sorted = sorted(scrape_s)

    def pct(p: float) -> float:
        return lat_sorted[
            min(int(p * len(lat_sorted)), len(lat_sorted) - 1)
        ] if lat_sorted else 0.0

    out = {
        "n_serving_clusters": len(sub),
        "repeats": repeats,
        "jobs_per_batch": jobs_per_batch,
        "disarmed_batch_walls_s": [round(w, 3) for w in disarmed_walls],
        "armed_batch_walls_s": [round(w, 3) for w in armed_walls],
        "disarmed_jobs_per_sec": round(jobs_per_batch / best_dis, 3),
        "armed_jobs_per_sec": round(jobs_per_batch / best_arm, 3),
        "overhead_frac": round(best_arm / best_dis - 1.0, 4),
        "overhead_frac_median": round(
            statistics.median(armed_walls)
            / statistics.median(disarmed_walls) - 1.0, 4,
        ),
        # the host's own batch-to-batch spread per arm: the floor below
        # which an overhead delta is indistinguishable from noise
        "host_noise_frac": round(
            max(
                (max(w) - min(w)) / min(w)
                for w in (disarmed_walls, armed_walls)
            ), 4,
        ),
        "n_scrapes": len(scrape_s),
        "scrape_ms_p50": round(pct(0.50) * 1e3, 3),
        "scrape_ms_p99": round(pct(0.99) * 1e3, 3),
    }
    eprint(
        f"[telemetry] disarmed {best_dis:.3f}s armed {best_arm:.3f}s "
        f"per {jobs_per_batch}-job batch -> overhead "
        f"{out['overhead_frac']:+.2%}; {out['n_scrapes']} scrapes "
        f"p50 {out['scrape_ms_p50']}ms p99 {out['scrape_ms_p99']}ms"
    )
    return out


def bench_flightrec_overhead(
    clusters, workdir: str, n_serving_clusters: int = 128,
    repeats: int = 10, jobs_per_batch: int = 6,
) -> dict:
    """Armed-idle cost of the always-on flight recorder (PR17
    acceptance: < 1%): daemon jobs/sec with ``--flightrec observe``
    tapping the journal — ring capture plus every detector folding
    every record, zero firings — vs ``--flightrec off`` (no recorder
    object at all).  Both arms journal to disk against ONE shared
    compile cache and pay one unmeasured warmup job, so the measured
    delta is the recorder alone on the healthy path.  Same
    min-of-batch-walls estimator as the fault_overhead and telemetry
    sections.  The armed arm's journal is asserted incident-free (a
    firing would mean the delta included bundle work) and its detector
    fold is audited by the incident-replay contract afterwards."""
    import os
    import signal as _signal
    import statistics
    import subprocess
    import sys

    from specpride_tpu.io.mgf import write_mgf
    from specpride_tpu.serve import client as sc

    sub = clusters[: min(n_serving_clusters, len(clusters))]
    src = os.path.join(workdir, "flightrec_clustered.mgf")
    write_mgf([s for c in sub for s in c.members], src)
    cache = os.path.join(workdir, "flightrec_cache")  # shared: both warm

    # BOTH arms boot up front and the batches ALTERNATE between them —
    # sequential arms let slow host-load drift masquerade as (or mask)
    # the recorder cost; interleaving puts both arms under the same
    # drift.  Only one daemon is ever driven at a time; the idle one
    # blocks on an empty queue.
    arms = {"off": [], "observe": ["--flightrec", "observe"]}
    procs: dict[str, tuple] = {}
    walls: dict[str, list[float]] = {tag: [] for tag in arms}
    obs_journal = os.path.join(workdir, "fr_observe.jsonl")
    try:
        for tag, extra in arms.items():
            sock = os.path.join(workdir, f"fr_{tag}.sock")
            argv = [
                sys.executable, "-m", "specpride_tpu", "serve",
                "--socket", sock, "--compile-cache", cache,
                "--layout", "bucketized", "--force-device",
                "--max-queue", "32",
                "--journal", os.path.join(workdir, f"fr_{tag}.jsonl"),
            ] + extra
            procs[tag] = (
                subprocess.Popen(
                    argv, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                ),
                sock,
            )

        def one_job(tag: str, i: int) -> None:
            out = os.path.join(workdir, f"fr_{tag}_{i}.mgf")
            term = sc.submit_wait(
                procs[tag][1],
                ["consensus", src, out, "--method", "bin-mean"],
                timeout=600,
            )
            assert term["status"] == "done", (tag, term)

        for tag, (_, sock) in procs.items():
            assert sc.wait_for_socket(sock, timeout=300), \
                f"{tag} daemon never booted"
            one_job(tag, -1)  # unmeasured warmup: pays any compiles
        job_seq = 0
        for _ in range(repeats):
            for tag in procs:
                t0 = time.perf_counter()
                for _ in range(jobs_per_batch):
                    one_job(tag, job_seq)
                    job_seq += 1
                walls[tag].append(time.perf_counter() - t0)
        for tag, (proc, _) in procs.items():
            proc.send_signal(_signal.SIGTERM)
            rc = proc.wait(timeout=300)
            assert rc == 0, f"{tag} daemon SIGTERM drain exited {rc}"
    finally:
        for proc, _ in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    off_walls, obs_walls = walls["off"], walls["observe"]
    # zero firings on the healthy load: the measured delta is the pure
    # armed-idle cost, and the fold it paid for must replay bit-exact
    with open(obs_journal) as fh:
        events = [json.loads(line) for line in fh]
    incidents = [e for e in events if e.get("event") == "incident"]
    assert not incidents, incidents
    from specpride_tpu.observability.flightrec import replay_incidents

    replay = replay_incidents(obs_journal)
    assert replay["ok"], replay
    best_off, best_obs = min(off_walls), min(obs_walls)
    out = {
        "n_serving_clusters": len(sub),
        "repeats": repeats,
        "jobs_per_batch": jobs_per_batch,
        "off_batch_walls_s": [round(w, 3) for w in off_walls],
        "observe_batch_walls_s": [round(w, 3) for w in obs_walls],
        "off_jobs_per_sec": round(jobs_per_batch / best_off, 3),
        "observe_jobs_per_sec": round(jobs_per_batch / best_obs, 3),
        "overhead_frac": round(best_obs / best_off - 1.0, 4),
        "overhead_frac_median": round(
            statistics.median(obs_walls)
            / statistics.median(off_walls) - 1.0, 4,
        ),
        # the host's own batch-to-batch spread per arm: the floor below
        # which an overhead delta is indistinguishable from noise
        "host_noise_frac": round(
            max(
                (max(w) - min(w)) / min(w)
                for w in (off_walls, obs_walls)
            ), 4,
        ),
        "observe_journal_events": len(events),
        "incidents": len(incidents),
        "replay_ok": bool(replay["ok"]),
    }
    eprint(
        f"[flightrec_overhead] off {best_off:.3f}s observe "
        f"{best_obs:.3f}s per {jobs_per_batch}-job batch -> overhead "
        f"{out['overhead_frac']:+.2%} (noise floor "
        f"{out['host_noise_frac']:.2%}); 0 incidents, replay ok"
    )
    return out


def bench_result_cache(
    clusters, workdir: str, n_serving_clusters: int = 512,
    repeats: int = 4, jobs_per_batch: int = 3,
) -> dict:
    """Content-addressed result cache (docs/performance.md, PR 18
    acceptance): repeat-job throughput through a live daemon with
    ``--result-cache`` vs one without, per method, with QC armed — a
    warm cache hit skips BOTH the consensus compute and the QC cosine
    pass, so the measured delta is the compute the cache deletes.

    Both daemons boot up front against ONE shared compile cache and the
    measured batches ALTERNATE between arms (the flightrec idiom: slow
    host-load drift hits both equally).  Per method one unmeasured
    warmup job per arm pays the compiles — on the cached arm it is also
    the cold populate, so every measured cached job runs warm.  The
    acceptance bars asserted here: warm jobs/sec >= 2x cache-off per
    method, hit rate >= 0.9 across every cached-arm job (cold warmups
    included), warm p99 job wall no worse than cache-off, and BYTE
    PARITY for every output + QC report of every job in every cell."""
    import os
    import signal as _signal
    import subprocess
    import sys

    from specpride_tpu.io.mgf import write_mgf
    from specpride_tpu.serve import client as sc

    sub = clusters[: min(n_serving_clusters, len(clusters))]
    src = os.path.join(workdir, "rc_clustered.mgf")
    write_mgf([s for c in sub for s in c.members], src)
    cache = os.path.join(workdir, "rc_compile_cache")  # shared: both warm
    arms = {
        "off": [],
        "cached": ["--result-cache", os.path.join(workdir, "rc_tier")],
    }
    procs: dict[str, tuple] = {}
    batch_walls: dict = {}  # (method, tag) -> [batch wall, ...]
    job_walls: dict = {}    # (method, tag) -> [job wall, ...]
    cached_journal = os.path.join(workdir, "rc_cached.jsonl")
    try:
        for tag, extra in arms.items():
            sock = os.path.join(workdir, f"rc_{tag}.sock")
            argv = [
                sys.executable, "-m", "specpride_tpu", "serve",
                "--socket", sock, "--compile-cache", cache,
                "--layout", "bucketized", "--force-device",
                "--max-queue", "32",
                "--journal", os.path.join(workdir, f"rc_{tag}.jsonl"),
            ] + extra
            procs[tag] = (
                subprocess.Popen(
                    argv, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                ),
                sock,
            )
        for tag, (_, sock) in procs.items():
            assert sc.wait_for_socket(sock, timeout=300), \
                f"{tag} daemon never booted"

        def one_job(tag, method, command, name):
            out = os.path.join(workdir, f"rc_{tag}_{name}.mgf")
            qc = os.path.join(workdir, f"rc_{tag}_{name}.qc.json")
            t0 = time.perf_counter()
            term = sc.submit_wait(
                procs[tag][1],
                [command, src, out, "--method", method,
                 "--qc-report", qc],
                timeout=600,
            )
            wall = time.perf_counter() - t0
            assert term["status"] == "done", (tag, method, term)
            return wall, out, qc

        golden: dict = {}  # method -> (output bytes, qc bytes)
        for method, command in _SWEEP_METHODS:
            tagm = method.replace("-", "_")
            for tag in procs:
                # unmeasured: pays the compiles; cold-populates the tier
                _, out, qc = one_job(tag, method, command,
                                     f"{tagm}_warmup")
                with open(out, "rb") as fh:
                    body = fh.read()
                with open(qc, "rb") as fh:
                    qc_body = fh.read()
                if method not in golden:
                    golden[method] = (body, qc_body)
                assert (body, qc_body) == golden[method], \
                    f"{tag} warmup diverged for {method}"
            for key in ((method, "off"), (method, "cached")):
                batch_walls[key] = []
                job_walls[key] = []
            seq = 0
            for _ in range(repeats):
                for tag in procs:
                    t0 = time.perf_counter()
                    for _ in range(jobs_per_batch):
                        w, out, qc = one_job(
                            tag, method, command, f"{tagm}_{seq}"
                        )
                        seq += 1
                        job_walls[(method, tag)].append(w)
                        # byte parity EVERY cell: output + QC both arms
                        with open(out, "rb") as fh:
                            assert fh.read() == golden[method][0], \
                                (tag, method, out)
                        with open(qc, "rb") as fh:
                            assert fh.read() == golden[method][1], \
                                (tag, method, qc)
                    batch_walls[(method, tag)].append(
                        time.perf_counter() - t0
                    )
        for tag, (proc, _) in procs.items():
            proc.send_signal(_signal.SIGTERM)
            rc = proc.wait(timeout=300)
            assert rc == 0, f"{tag} daemon SIGTERM drain exited {rc}"
    finally:
        for proc, _ in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    # hit attribution from the cached daemon's own journal: every job
    # after the per-method cold warmup must have served every cluster
    # from the tier
    with open(cached_journal) as fh:
        events = [json.loads(line) for line in fh]
    done = [e for e in events if e.get("event") == "job_done"]
    hits = sum(e.get("result_cache_hits", 0) for e in done)
    hit_rate = hits / (len(done) * len(sub))
    assert hit_rate >= 0.9, \
        f"hit rate {hit_rate:.3f} < 0.9 over {len(done)} cached-arm jobs"

    def p99(ws):
        s = sorted(ws)
        return s[min(len(s) - 1, int(0.99 * (len(s) - 1)))]

    rows = []
    for method, _ in _SWEEP_METHODS:
        off_best = min(batch_walls[(method, "off")])
        cached_best = min(batch_walls[(method, "cached")])
        row = {
            "method": method,
            "off_batch_walls_s": [
                round(w, 3) for w in batch_walls[(method, "off")]
            ],
            "cached_batch_walls_s": [
                round(w, 3) for w in batch_walls[(method, "cached")]
            ],
            "off_jobs_per_sec": round(jobs_per_batch / off_best, 3),
            "cached_jobs_per_sec": round(
                jobs_per_batch / cached_best, 3
            ),
            "warm_speedup": round(off_best / cached_best, 3),
            "off_p99_job_wall_s": round(
                p99(job_walls[(method, "off")]), 3
            ),
            "cached_p99_job_wall_s": round(
                p99(job_walls[(method, "cached")]), 3
            ),
        }
        assert row["warm_speedup"] >= 2.0, \
            f"{method}: warm cache only {row['warm_speedup']}x"
        assert row["cached_p99_job_wall_s"] <= \
            row["off_p99_job_wall_s"], \
            f"{method}: cached p99 regressed: {row}"
        rows.append(row)
        eprint(
            f"[result_cache:{method}] off "
            f"{row['off_jobs_per_sec']} jobs/s -> cached "
            f"{row['cached_jobs_per_sec']} jobs/s = "
            f"{row['warm_speedup']}x, p99 "
            f"{row['off_p99_job_wall_s']}s -> "
            f"{row['cached_p99_job_wall_s']}s"
        )
    eprint(
        f"[result_cache] hit rate {hit_rate:.3f} over {len(done)} "
        f"cached-arm jobs x {len(sub)} clusters; parity held every cell"
    )
    return {
        "n_serving_clusters": len(sub),
        "repeats": repeats,
        "jobs_per_batch": jobs_per_batch,
        "methods": rows,
        "cached_arm_jobs": len(done),
        "hit_rate": round(hit_rate, 4),
        "parity": "output + QC byte-identical, every job, both arms",
    }


def bench_medoid_d2h(clusters) -> dict:
    """Medoid device path D2H bytes: index-only selection
    (``medoid_device_select``, the default) vs the count-matrix fetch it
    replaced — the acceptance bar is a >= 10x byte drop."""
    from specpride_tpu.backends.tpu_backend import TpuBackend
    from specpride_tpu.config import BatchConfig

    out: dict = {}
    for select, key in ((True, "index_only"), (False, "count_matrix")):
        backend = TpuBackend(
            batch_config=BatchConfig(clusters_per_batch=4096),
            layout="bucketized",
            medoid_device_select=select,
        )
        t0 = time.perf_counter()
        reps = backend.run_medoid(clusters)
        assert len(reps) == len(clusters)
        out[key] = {
            "d2h_bytes": int(
                backend.metrics.counter(
                    "specpride_bytes_d2h_total",
                    "bytes fetched device->host",
                ).value()
            ),
            "wall_s": round(time.perf_counter() - t0, 3),
        }
    out["d2h_reduction_x"] = round(
        out["count_matrix"]["d2h_bytes"]
        / max(out["index_only"]["d2h_bytes"], 1),
        1,
    )
    eprint(
        f"[medoid d2h] index-only {out['index_only']['d2h_bytes']} B vs "
        f"counts {out['count_matrix']['d2h_bytes']} B "
        f"({out['d2h_reduction_x']}x fewer)"
    )
    return out


def bench_sweep(clusters, backend, nb) -> dict:
    """BASELINE configs[3]: the ppm-tolerance grid sweep and the sqrt/log
    intensity-normalization sweep.  Grid rows time the bin-mean method on
    both backends per tolerance config (full-set oracle, one steady device
    run); normalization rows time the fused pipeline per transform and
    record the mean QC cosine so the knob's effect is visible."""
    from specpride_tpu.config import BinMeanConfig, CosineConfig
    from specpride_tpu.observability import RunStats

    grid_rows = []
    for label, cfg in [
        ("da-0.02", BinMeanConfig()),
        ("ppm-5", BinMeanConfig(tolerance_mode="ppm", ppm=5.0)),
        ("ppm-20", BinMeanConfig(tolerance_mode="ppm", ppm=20.0)),
        ("ppm-50", BinMeanConfig(tolerance_mode="ppm", ppm=50.0)),
    ]:
        t0 = time.perf_counter()
        nb.run_bin_mean(clusters, cfg)
        np_s = time.perf_counter() - t0
        backend.run_bin_mean(clusters, cfg)  # warm-up / compile
        backend.stats = RunStats()
        t0 = time.perf_counter()
        out = backend.run_bin_mean(clusters, cfg)
        dev_s = time.perf_counter() - t0
        assert len(out) == len(clusters)
        eprint(
            f"[sweep:{label}] n_bins={cfg.n_bins} numpy "
            f"{len(clusters) / np_s:.0f} cl/s device "
            f"{len(clusters) / dev_s:.0f} cl/s"
        )
        grid_rows.append({
            "grid": label,
            "n_bins": cfg.n_bins,
            "numpy_clusters_per_sec": round(len(clusters) / np_s, 2),
            "device_clusters_per_sec": round(len(clusters) / dev_s, 2),
            "speedup_vs_numpy": round(np_s / dev_s, 3),
        })

    norm_rows = []
    for norm in ("none", "sqrt", "log"):
        ccfg = CosineConfig(normalization=norm)
        backend.run_bin_mean_with_cosines(
            clusters, BinMeanConfig(), ccfg
        )  # warm-up
        backend.stats = RunStats()
        t0 = time.perf_counter()
        _, cos = backend.run_bin_mean_with_cosines(
            clusters, BinMeanConfig(), ccfg
        )
        dev_s = time.perf_counter() - t0
        eprint(
            f"[sweep:norm-{norm}] {len(clusters) / dev_s:.0f} cl/s "
            f"mean_cosine={float(np.mean(cos)):.4f}"
        )
        norm_rows.append({
            "normalization": norm,
            "device_clusters_per_sec": round(len(clusters) / dev_s, 2),
            "mean_cosine": round(float(np.mean(cos)), 5),
        })
    return {"tolerance_grid": grid_rows, "normalization": norm_rows}


def pallas_ab(clusters, report_path: str | None = None) -> dict | None:
    """On-chip A/B of the segmented-reduction cores on this workload's
    real flat bin-mean arrays: the XLA shift/select formulation
    (ops.segments) vs the Pallas kernels — the original 3-channel scan
    (seg_scan_pallas) AND the fused segment-mean single pass
    (seg_mean_pallas) the routing table can promote.  When the fused
    kernel beats the XLA chain by >= 10%, a routing-override file
    (<report>.routing.json, loadable via --routing-table /
    SPECPRIDE_ROUTING) is emitted so the promotion is a measured
    artifact, not an edit.  Returns None off-TPU."""
    import functools

    import jax

    from specpride_tpu.backends.tpu_backend import _pow2
    from specpride_tpu.config import BinMeanConfig
    from specpride_tpu.data.packed import pack_flat_bin_mean
    from specpride_tpu.ops import pallas_kernels as pk
    from specpride_tpu.ops import segments as sg

    if not pk.has_pallas() or pk.pl is None:
        return None
    cfg = BinMeanConfig()
    batch = pack_flat_bin_mean(clusters, cfg, max_elements=1 << 24)[0]
    n = batch.gbin.size
    n_pad = -(-n // pk.BLK) * pk.BLK
    sent = np.int32(2**31 - 1)
    gbin = jax.device_put(np.pad(batch.gbin, (0, n_pad - n),
                                 constant_values=sent))
    mz = jax.device_put(np.pad(batch.mz, (0, n_pad - n)))
    inten = jax.device_put(np.pad(batch.intensity, (0, n_pad - n)))
    w = jax.device_put(np.ones(n_pad, np.float32))
    jax.block_until_ready([gbin, mz, inten, w])
    lcap = _pow2(int(batch.n_members.max(initial=1)))

    @functools.partial(jax.jit, static_argnames=("lcap",))
    def xla(g, w, x, y, lcap):
        return sg.seg_scan(sg.run_starts(g), (w, x, y), lcap)

    pal = jax.jit(lambda g, w, x, y: pk.seg_scan_pallas(g, w, x, y))

    def best(fn, *a, runs=5, **kw):
        r = fn(*a, **kw)
        jax.block_until_ready(r)
        times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            r = fn(*a, **kw)
            jax.block_until_ready(r)
            times.append(time.perf_counter() - t0)
        return min(times)

    t_xla = best(xla, gbin, w, mz, inten, lcap=lcap)
    t_pal = best(pal, gbin, w, mz, inten)
    a = np.asarray(xla(gbin, w, mz, inten, lcap=lcap)[2])
    b = np.asarray(pal(gbin, w, mz, inten)[2])
    real = np.asarray(batch.gbin) != sent
    denom = np.maximum(np.abs(a[:n][real]), 1.0)
    rel = float(np.abs((a[:n][real] - b[:n][real]) / denom).max())
    eprint(
        f"[pallas A/B] {n} peaks: XLA seg_scan {t_xla*1e3:.2f}ms, "
        f"Pallas {t_pal*1e3:.2f}ms, max rel diff {rel:.1e}"
    )

    # the FUSED segment-mean pass (what the routing table promotes) vs
    # the full XLA equivalent: run_sums + the separate division
    import jax.numpy as jnp

    rcap = _pow2(int(batch.run_starts.size + 2))

    @functools.partial(jax.jit, static_argnames=("rcap", "lcap"))
    def xla_mean(g, w, x, rcap, lcap):
        starts = sg.run_starts(g)
        (counts, xs), _ = sg.run_sums(starts, (w, x * w), rcap, lcap)
        return xs / jnp.maximum(counts, 1.0)

    pal_mean = jax.jit(lambda g, w, x: pk.seg_mean_pallas(g, w, x)[1])
    t_xla_mean = best(xla_mean, gbin, w, inten, rcap=rcap, lcap=lcap)
    t_pal_mean = best(pal_mean, gbin, w, inten)
    seg_mean_speedup = round(t_xla_mean / t_pal_mean, 3)
    eprint(
        f"[pallas A/B] fused seg_mean: XLA {t_xla_mean*1e3:.2f}ms, "
        f"Pallas {t_pal_mean*1e3:.2f}ms -> {seg_mean_speedup}x"
    )

    out = {
        "n_peaks": n,
        "xla_seg_scan_ms": round(t_xla * 1e3, 3),
        "pallas_seg_scan_ms": round(t_pal * 1e3, 3),
        "max_rel_diff": rel,
        "xla_seg_mean_ms": round(t_xla_mean * 1e3, 3),
        "pallas_seg_mean_ms": round(t_pal_mean * 1e3, 3),
        "seg_mean_speedup": seg_mean_speedup,
    }
    if report_path and seg_mean_speedup >= 1.1:
        from specpride_tpu.warmstart.routing import write_overrides

        plat = jax.default_backend()
        override = report_path + ".routing.json"
        # promote ONLY what this A/B measured: the flat bin-mean
        # arrays.  gap-average's (row, seg) composite-key workload
        # needs its own measurement before a routing promotion — an
        # override's reason string must never claim a measurement that
        # did not happen.
        write_overrides(override, [
            {
                "method": "bin-mean", "platform": plat, "path": "pallas",
                "reason": f"pallas_ab: fused seg_mean "
                f"{seg_mean_speedup}x over XLA seg_scan on {plat} "
                "(flat bin-mean arrays)",
            }
        ])
        out["routing_override"] = override
        eprint(f"[pallas A/B] routing override -> {override}")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-clusters", type=int, default=2000)
    ap.add_argument("--numpy-sample", type=int, default=1 << 30,
                    help="clusters timed on the numpy oracle (stratified "
                    "random sample; >= n-clusters means the full set — the "
                    "default: sampled baselines swung 2x run-to-run on the "
                    "gamma-skewed workload)")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--method", default="pipeline",
        choices=["pipeline", "bin_mean", "gap_average", "medoid"],
    )
    ap.add_argument(
        "--report", metavar="FILE", default=None,
        help="bench ALL methods with phase breakdown + full-set numpy "
        "baselines and write the JSON report here (BENCH_METHODS.json)",
    )
    ap.add_argument(
        "--sections", default=None, metavar="LIST",
        help="with --report: comma list of report sections to run "
        "(default all): methods,flat,sweep,medoid_d2h,end_to_end,"
        "prefetch_sweep,worker_sweep,fault_overhead,warm_start,serving,"
        "serving_concurrency,serving_batching,autotune,telemetry,"
        "flightrec_overhead,result_cache,elastic,elastic_steal,pallas,"
        "bandwidth",
    )
    ap.add_argument(
        "--sync-timing", action="store_true",
        help="block after dispatch so the 'device' (H2D+kernel) and 'd2h' "
        "(pure transfer) phases time apart",
    )
    ap.add_argument(
        "--journal", metavar="FILE", default=None,
        help="stream per-run phase telemetry as JSONL bench_run events "
        "(default with --report: <report>.journal.jsonl)",
    )
    ap.add_argument(
        "--trace-dir", metavar="DIR", default=None,
        help="capture a jax.profiler device trace of the bench compute "
        "into this directory (view with TensorBoard / Perfetto)",
    )
    args = ap.parse_args()

    # validate --sections BEFORE the workload is paid for: a typo'd
    # section name must fail instantly, not after seconds of setup (and
    # never produce a silently empty report)
    all_sections = (
        "methods,flat,sweep,medoid_d2h,end_to_end,prefetch_sweep,"
        "worker_sweep,fault_overhead,warm_start,serving,"
        "serving_concurrency,serving_batching,autotune,telemetry,"
        "flightrec_overhead,result_cache,elastic,elastic_steal,pallas,"
        "bandwidth"
    )
    secs = set((args.sections or all_sections).split(","))
    unknown = secs - set(all_sections.split(","))
    if unknown:
        raise SystemExit(
            f"unknown --sections {sorted(unknown)}; "
            f"choose from: {all_sections}"
        )

    import jax

    from specpride_tpu.backends import numpy_backend as nb
    from specpride_tpu.backends.tpu_backend import TpuBackend
    from specpride_tpu.config import BatchConfig

    eprint(f"devices: {jax.devices()}")
    t0 = time.perf_counter()
    clusters = make_workload(args.n_clusters, args.seed)
    n_spectra = sum(c.n_members for c in clusters)
    eprint(
        f"workload: {len(clusters)} clusters, {n_spectra} spectra, "
        f"built in {time.perf_counter() - t0:.1f}s"
    )

    from specpride_tpu.observability import (
        Tracer,
        device_summary,
        device_trace,
        open_journal,
    )
    from specpride_tpu.observability import tracing

    journal_path = args.journal or (
        args.report + ".journal.jsonl" if args.report else None
    )
    journal = open_journal(journal_path)
    if journal.enabled:
        # span events ride the bench journal too, so BENCH_*.json rounds
        # carry per-kernel dispatch timelines (`specpride trace`-able)
        tracing.set_current(Tracer(journal=journal))
    journal.emit(
        "run_start", command="bench", method=args.method,
        backend="tpu", n_clusters=len(clusters),
    )

    # large batches: on tunneled hosts every extra dispatch costs a full
    # round-trip, so amortize over as many clusters as memory allows
    backend = TpuBackend(
        batch_config=BatchConfig(clusters_per_batch=4096),
        sync_timing=args.sync_timing,
        journal=journal,
    )

    with device_trace(args.trace_dir):
        if args.report:
            import os

            report = {
                "workload": {
                    "n_clusters": len(clusters),
                    "n_spectra": n_spectra,
                    "seed": args.seed,
                },
                "jax_devices": [str(d) for d in jax.devices()],
                # the host core count bounds every threaded native path: on
                # a 1-core bench host the C++ kernels win by cache locality
                # and allocation avoidance only, never by parallelism
                "host_cpu_cores": len(os.sched_getaffinity(0)),
                "methods": [],
            }
            import gc

            if "methods" in secs:
                for method in (
                    "bin_mean", "gap_average", "medoid", "pipeline"
                ):
                    report["methods"].append(
                        bench_method(
                            method, clusters, backend, nb,
                            numpy_sample=len(clusters), seed=args.seed,
                            journal=journal,
                        )
                    )
                    # back-to-back methods in one process measurably
                    # degrade on tunneled hosts (leftover device buffers +
                    # queue state); a collection pass between methods keeps
                    # runs comparable to standalone --method invocations
                    gc.collect()
            if "flat" in secs:
                # the measured-choice default ("auto") runs K1/K2b on the
                # host mesh-less; keep the DEVICE flat paths measured too,
                # so the device-vs-host decision stays pinned to current
                # numbers
                dev_backend = TpuBackend(
                    batch_config=BatchConfig(clusters_per_batch=4096),
                    layout="flat",
                    sync_timing=args.sync_timing,
                    journal=journal,
                    # one registry across both backends: run_end.device
                    # must cover the flat-layout benches too, not just the
                    # default backend's
                    metrics=backend.metrics,
                )
                for method in ("bin_mean", "pipeline"):
                    entry = bench_method(
                        method, clusters, dev_backend, nb,
                        numpy_sample=len(clusters), seed=args.seed,
                        journal=journal,
                    )
                    entry["method"] += "_device_flat"
                    entry["metric"] += " [device flat layout]"
                    report["methods"].append(entry)
                    gc.collect()
            if "sweep" in secs:
                report["sweep"] = bench_sweep(clusters, backend, nb)
            if "medoid_d2h" in secs:
                report["medoid_d2h"] = bench_medoid_d2h(clusters)
            import tempfile

            with tempfile.TemporaryDirectory() as workdir:
                if "end_to_end" in secs:
                    report["end_to_end"] = bench_end_to_end(
                        clusters, workdir
                    )
                if "bandwidth" in secs:
                    report["bandwidth"] = bench_bandwidth(
                        clusters, workdir
                    )
                if "prefetch_sweep" in secs:
                    report["prefetch_sweep"] = bench_prefetch_sweep(
                        clusters, workdir
                    )
                if "worker_sweep" in secs:
                    report["worker_sweep"] = bench_worker_sweep(
                        clusters, workdir
                    )
                if "fault_overhead" in secs:
                    report["fault_overhead"] = bench_fault_overhead(
                        clusters, workdir
                    )
                if "warm_start" in secs:
                    report["warm_start"] = bench_warm_start(
                        clusters, workdir
                    )
                if "serving" in secs:
                    report["serving"] = bench_serving(clusters, workdir)
                if "serving_concurrency" in secs:
                    report["serving_concurrency"] = \
                        bench_serving_concurrency(clusters, workdir)
                if "serving_batching" in secs:
                    report["serving_batching"] = \
                        bench_serving_batching(clusters, workdir)
                if "autotune" in secs:
                    report["autotune"] = bench_autotune(
                        clusters, workdir
                    )
                if "telemetry" in secs:
                    report["telemetry"] = bench_telemetry(
                        clusters, workdir
                    )
                if "flightrec_overhead" in secs:
                    report["flightrec_overhead"] = \
                        bench_flightrec_overhead(clusters, workdir)
                if "result_cache" in secs:
                    report["result_cache"] = bench_result_cache(
                        clusters, workdir
                    )
                if "elastic" in secs:
                    report["elastic"] = bench_elastic(clusters, workdir)
                if "elastic_steal" in secs:
                    report["elastic_steal"] = bench_elastic_steal(
                        clusters, workdir
                    )
            if "pallas" in secs:
                ab = pallas_ab(clusters, report_path=args.report)
                if ab is not None:
                    report["pallas_ab"] = ab
            with open(args.report, "w") as f:
                json.dump(report, f, indent=2)
                f.write("\n")
            eprint(f"wrote {args.report}")
            head = next(
                (r for r in report["methods"] if r["method"] == "pipeline"),
                report["methods"][0] if report["methods"] else {
                    "metric": "partial report (see --sections)",
                    "device_clusters_per_sec": 0.0,
                    "speedup_vs_numpy": 0.0,
                    "device_phases_s": {},
                },
            )
        else:
            head = bench_method(
                args.method, clusters, backend, nb,
                numpy_sample=args.numpy_sample, seed=args.seed,
                journal=journal,
            )

    tracing.set_current(None)
    journal.emit(
        "run_end",
        counters={"clusters": len(clusters), "spectra": n_spectra},
        phases_s=head["device_phases_s"],
        elapsed_s=round(time.perf_counter() - t0, 2),
        device=device_summary(backend.metrics),
    )
    journal.close()

    print(
        json.dumps(
            {
                "metric": head["metric"],
                "value": head["device_clusters_per_sec"],
                "unit": "clusters/sec",
                "vs_baseline": head["speedup_vs_numpy"],
            }
        )
    )


if __name__ == "__main__":
    main()
