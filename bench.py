#!/usr/bin/env python
"""Headline benchmark: consensus spectra/sec, device backend vs numpy oracle.

The reference publishes no numbers (BASELINE.md), so the baseline is our own
numpy oracle — a faithful behavioural port of ref src/binning.py:170-231 —
measured on the same synthetic PXD-like cluster workload.  Prints ONE JSON
line on stdout:

    {"metric": ..., "value": N, "unit": "clusters/sec", "vs_baseline": N}

``value`` is the device-backend end-to-end rate (bucketize + f64 quantize +
H2D + kernel + D2H + unpad); ``vs_baseline`` is the speedup over the numpy
oracle rate.  Runs on whatever JAX platform the environment provides (the
real TPU chip under the driver; CPU elsewhere).  Diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def eprint(*a):
    print(*a, file=sys.stderr, flush=True)


def make_workload(n_clusters: int, seed: int = 42):
    """Synthetic clustered MS/MS workload shaped like the PXD004732 benchmark
    set: cluster sizes skewed small (most clusters 2-8 members, tail to 20),
    100-400 peaks per spectrum, 0.003 Da m/z jitter within a cluster."""
    from specpride_tpu.data.peaks import Cluster, Spectrum

    rng = np.random.default_rng(seed)
    clusters = []
    for i in range(n_clusters):
        n_members = min(20, 1 + int(rng.gamma(2.0, 2.5)))
        n_peaks = int(rng.integers(100, 400))
        skeleton = np.sort(rng.uniform(120.0, 1900.0, size=n_peaks))
        charge = int(rng.integers(2, 4))
        members = []
        for k in range(n_members):
            mz = np.sort(skeleton + rng.normal(0.0, 0.003, size=n_peaks))
            members.append(
                Spectrum(
                    mz=mz,
                    intensity=rng.uniform(10.0, 1e4, size=n_peaks),
                    precursor_mz=float(rng.uniform(300.0, 900.0)),
                    precursor_charge=charge,
                    rt=float(i),
                    title=f"cluster-{i};mzspec:PXD1:r:scan:{i * 100 + k}",
                )
            )
        clusters.append(Cluster(f"cluster-{i}", members))
    return clusters


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-clusters", type=int, default=2000)
    ap.add_argument("--numpy-sample", type=int, default=100,
                    help="clusters timed on the numpy oracle (rate-based)")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--method", default="pipeline",
        choices=["pipeline", "bin_mean", "gap_average", "medoid"],
    )
    args = ap.parse_args()

    import jax

    from specpride_tpu.backends import numpy_backend as nb
    from specpride_tpu.backends.tpu_backend import TpuBackend
    from specpride_tpu.config import BatchConfig

    eprint(f"devices: {jax.devices()}")
    t0 = time.perf_counter()
    clusters = make_workload(args.n_clusters, args.seed)
    n_spectra = sum(c.n_members for c in clusters)
    eprint(
        f"workload: {len(clusters)} clusters, {n_spectra} spectra, "
        f"built in {time.perf_counter() - t0:.1f}s"
    )

    # large batches: on tunneled hosts every extra dispatch costs a full
    # round-trip, so amortize over as many clusters as memory allows
    backend = TpuBackend(
        batch_config=BatchConfig(clusters_per_batch=4096)
    )
    def np_pipeline(cs):
        reps = nb.run_bin_mean(cs)
        return [nb.average_cosine(r, c.members) for r, c in zip(reps, cs)]

    def dev_pipeline(cs):
        reps = backend.run_bin_mean(cs)
        cos = backend.average_cosines(reps, cs)
        assert len(reps) == len(cos) == len(cs)
        return cos

    run_np = {
        "pipeline": np_pipeline,
        "bin_mean": nb.run_bin_mean,
        "gap_average": nb.run_gap_average,
        "medoid": nb.run_medoid,
    }[args.method]
    run_dev = {
        "pipeline": dev_pipeline,
        "bin_mean": backend.run_bin_mean,
        "gap_average": backend.run_gap_average,
        "medoid": backend.run_medoid,
    }[args.method]

    # numpy oracle rate on a sample
    sample = clusters[: args.numpy_sample]
    t0 = time.perf_counter()
    run_np(sample)
    numpy_rate = len(sample) / (time.perf_counter() - t0)
    eprint(f"numpy oracle: {numpy_rate:.1f} clusters/sec")

    # device: first run includes compile; report the steady-state second run
    t0 = time.perf_counter()
    run_dev(clusters)
    eprint(f"device warm-up (incl compile): {time.perf_counter() - t0:.1f}s")
    best = 0.0
    for i in range(3):
        t0 = time.perf_counter()
        out = run_dev(clusters)
        rate = len(clusters) / (time.perf_counter() - t0)
        eprint(f"device steady-state run {i}: {rate:.1f} clusters/sec")
        best = max(best, rate)
        assert len(out) == len(clusters)
    device_rate = best

    metric = {
        "pipeline": "consensus+QC pipeline (bin-mean + binned-cosine)",
        "bin_mean": "consensus spectra/sec (bin-mean)",
        "gap_average": "consensus spectra/sec (gap-average)",
        "medoid": "medoid representatives/sec",
    }[args.method]
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(device_rate, 2),
                "unit": "clusters/sec",
                "vs_baseline": round(device_rate / numpy_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
