"""JSON-lines wire protocol for the ``specpride serve`` daemon.

Transport: a local **unix-domain stream socket**, one connection per
job (concurrent clients = concurrent connections).  Every message is
one JSON object per line, newline-terminated — the same framing as the
run journal, so both ends stay greppable and a protocol trace reads
like any other JSONL stream.

Client -> server (one request per connection)::

    {"op": "submit", "argv": ["consensus", IN, OUT, "--method", ...],
     "trace": {"trace_id": HEX32, "parent_span_id": HEX16}}
    {"op": "ping"}
    {"op": "status"}
    {"op": "profile", "seconds": 3.0, "trace_dir": DIR,
     "chrome_trace": FILE}

``trace`` (optional) is the v4 causal envelope: the client minted a
``trace_id`` and opened a submit span — the daemon adopts the trace,
parents its serve:queue/serve:job spans under ``parent_span_id``, and
stamps the id on the job's journal events, so ``specpride trace
--trace-id`` reassembles client + daemon + job (+ shared batch) onto
one timeline.  Absent, the daemon mints a fresh trace at admission
(every served job is traceable either way); present-but-malformed
rejects permanently.  The admission and terminal replies echo
``trace_id`` back so shell callers can harvest it.

``profile`` (``specpride profile``) captures a bounded ``jax.profiler``
device trace on the RUNNING warm daemon — no restart, no cold
recompile on the next job — plus the slice of the daemon journal that
landed inside the window (``<trace_dir>/journal_window.jsonl``).  The
reply names the artifacts::

    {"ok": true, "status": "profiled", "seconds": 3.0,
     "trace_dir": DIR, "artifacts": [...], "chrome_trace": FILE|null,
     "journal_window": PATH, "window_events": {"job_done": 2, ...}}

One capture runs at a time (jax has a single profiler session); a
concurrent request is rejected with ``retriable: true``.

Server -> client, for ``submit``: an admission line first, then —
unless the job was rejected — exactly one terminal line when the job
leaves the execution lane::

    {"ok": true,  "status": "accepted", "job_id": 3, "queue_depth": 1}
    {"ok": true,  "status": "done", "job_id": 3, "rc": 0,
     "wall_s": 1.23, "queue_wait_s": 0.0, "stats": {...},
     "compile_cache": {"hits": 0, "misses": 0, ...}, "worker": 1}
    {"ok": false, "status": "rejected", "reason": "queue_full",
     "retriable": true}
    {"ok": false, "status": "rejected",
     "reason": "quota client=teamA max_inflight=2: ...",
     "retriable": true}
    {"ok": false, "status": "error", "job_id": 3,
     "error": "ValueError: ...", "retriable": false}

``worker`` on the terminal line is the execution lane that ran the job
(``serve --workers N``).  ``retriable`` follows the robustness error
taxonomy (``robustness.errors``): admission rejections (``queue_full``,
``draining``, and per-tenant ``quota ...`` bounces — the quota is named
in the reason) are always retriable — resubmit after backoff — while
execution errors are retriable only when the taxonomy classifies them
transient.  ``specpride submit`` maps a retriable non-success to exit
code 75 (BSD ``EX_TEMPFAIL``), so shell callers can retry on ``$? ==
75`` without parsing JSON.

A job's ``argv`` is the exact one-shot CLI argv (``consensus``/
``select`` only) — the daemon parses it with the CLI's own parser, so a
served job can never accept flags the CLI would reject.  The flags in
``DAEMON_ONLY_FLAGS`` configure the daemon's resident backend at boot
and are refused on jobs: silently accepting a per-job ``--layout`` that
cannot apply to the already-constructed backend would be a lie.
"""

from __future__ import annotations

import json
import os

PROTOCOL_VERSION = 1

# commands a job may run: the chunked pipeline commands that benefit
# from (and are safe under) the resident warm backend
SERVABLE_COMMANDS = ("consensus", "select")

# flags the DAEMON owns (boot-time backend/cache construction, and the
# process-wide telemetry surface): a job carrying one is rejected,
# never silently ignored.  --metrics-out is daemon-owned because the
# resident backend registry is shared across jobs — a per-job textfile
# dumped from it would report the daemon's cumulative traffic as the
# job's (scrape /metrics, or read the drain snapshot, instead)
DAEMON_ONLY_FLAGS = (
    "--compile-cache",
    "--routing-table",
    "--layout",
    "--force-device",
    # packed-channel precision and buffer donation configure the
    # resident backends' kernel variants at boot — a per-job value
    # could not apply to the already-constructed lanes
    "--precision",
    "--no-donate",
    "--mesh",
    "--coordinator",
    "--num-processes",
    "--process-id",
    "--metrics-out",
    # elastic multi-host coordination and its liveness exporter are
    # fleet-process concerns: a served job is one tenant of ONE warm
    # daemon, not a rank (an in-job coordinator would lease ranges and
    # bind ports inside the daemon process)
    "--elastic",
    "--elastic-steal",
    "--elastic-local",
    "--metrics-port",
    # jax has ONE global profiler session per process: a per-job device
    # trace would race concurrent worker lanes (and any `specpride
    # profile` capture).  Profile the daemon itself instead.
    "--trace-dir",
    # the closed-loop controller is a process-wide plane (the daemon
    # boots its own via serve --autotune); a job cannot carry one
    "--autotune",
    # the result cache's tiers are boot-owned process-wide state every
    # worker lane shares — a job building its own tiers inside the
    # daemon would fork the cache the fleet is warming
    "--result-cache",
    "--result-store",
)

# `specpride submit` exit code for a retriable non-success (BSD
# EX_TEMPFAIL — the sysexits convention for "try again later")
EX_TEMPFAIL = 75

# ceiling on one `specpride profile` capture window: a profiler session
# pins a reader thread and buffers device events in memory — "bounded"
# is part of the verb's contract
PROFILE_MAX_SECONDS = 300.0


def default_socket_path() -> str:
    """Where daemon and client meet when ``--socket`` is not given:
    ``SPECPRIDE_SOCKET``, else a per-user path under ``~/.cache``."""
    env = os.environ.get("SPECPRIDE_SOCKET")
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "specpride_tpu", "serve.sock"
    )


def write_msg(fh, **payload) -> None:
    """One protocol message -> one flushed JSON line."""
    fh.write(json.dumps(payload) + "\n")
    fh.flush()


def read_msg(fh) -> dict | None:
    """The next message, ``None`` on EOF.  Raises ``ValueError`` on a
    line that is not a JSON object — a protocol violation the caller
    turns into a rejection (server) or ``ServeError`` (client)."""
    line = fh.readline()
    if not line:
        return None
    msg = json.loads(line)
    if not isinstance(msg, dict):
        raise ValueError(f"protocol message is not an object: {msg!r}")
    return msg


def forbidden_flags(argv: list[str]) -> list[str]:
    """Daemon-only flags present in a job argv (``--flag`` and
    ``--flag=value`` spellings both count)."""
    return sorted({
        tok.split("=", 1)[0]
        for tok in argv
        if tok.split("=", 1)[0] in DAEMON_ONLY_FLAGS
    })


# parser dests of the daemon-owned flags: a PARSED job namespace whose
# value differs from the CLI default was set by the argv, whatever
# spelling reached the parser (argparse accepts unambiguous prefixes
# like --layou, which the token scan above cannot see)
_DAEMON_OWNED_DESTS = (
    "compile_cache", "routing_table", "layout", "force_device",
    "precision", "no_donate",
    "mesh", "coordinator", "num_processes", "process_id", "metrics_out",
    "elastic", "elastic_steal", "elastic_local", "metrics_port",
    "trace_dir", "autotune", "result_cache", "result_store",
)

_daemon_owned_defaults: dict | None = None


def _owned_defaults() -> dict:
    """The CLI parser's OWN defaults for the daemon-owned dests, read
    once from a bare parse — never a hardcoded copy, which would drift
    the moment a CLI default changes (rejecting every job, or letting
    the old default through).  consensus and select share these flags
    via one ``_add_backend``, so either subcommand's baseline works."""
    global _daemon_owned_defaults
    if _daemon_owned_defaults is None:
        from specpride_tpu.cli import build_parser

        base = build_parser().parse_args(["consensus", "", ""])
        _daemon_owned_defaults = {
            dest: getattr(base, dest) for dest in _DAEMON_OWNED_DESTS
        }
    return _daemon_owned_defaults


def overridden_daemon_flags(args) -> list[str]:
    """Daemon-owned flags a PARSED job namespace overrides from their
    CLI defaults — the abbreviation-proof second line of defence behind
    :func:`forbidden_flags`."""
    return sorted(
        "--" + dest.replace("_", "-")
        for dest, default in _owned_defaults().items()
        if getattr(args, dest, default) != default
    )
