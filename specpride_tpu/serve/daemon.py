"""``specpride serve``: the warm-kernel consensus daemon.

Lifecycle (documented in docs/serving.md):

* **boot** — resolve the persistent compile cache ONCE
  (``--compile-cache``), load the routing table, construct the resident
  ``TpuBackend``, and AOT-warm the shape manifest beside the cache
  (reusing ``warmstart.warmup`` — the same pass ``specpride warmup``
  runs), so the first request already hits compiled kernels.  Then bind
  the unix socket and start accepting.
* **serve** — each connection is one job: the reader thread validates
  the argv with the CLI's own parser and offers it to the bounded
  weighted-fair :class:`~specpride_tpu.serve.scheduler.AdmissionQueue`
  (``--quota client=weight[:max_inflight]``); the **worker pool**
  (``--workers N``, default ``min(#local jax devices, 4)``; 1 = the
  PR 7 single lane) pops jobs and runs them through the exact CLI
  execution body (``cli._run_pipeline_command``) — the three-lane
  executor, per-job journal, per-job ``run_end`` stats and the
  robustness harness all behave exactly as one-shot runs, so served
  output is byte-identical to the CLI's.  Each worker owns its own
  resident ``TpuBackend`` placed by ``serve.placement`` (pinned to a
  distinct local device on accelerator hosts; shared platform on
  CPU-only hosts), so jobs writing distinct outputs execute
  CONCURRENTLY; the scheduler's output-path conflict guard serializes
  jobs that target the same file.
* **drain** — SIGTERM (or SIGINT): stop accepting, reject every
  *queued* job with a retriable status, let every worker's *in-flight*
  job commit through its ordered write lane, journal ``serve_drain`` +
  ``run_end``, remove the socket, exit 0.

Per-job resident-backend hygiene: jobs serialize PER WORKER, and
between jobs each worker resets exactly the per-run state on ITS OWN
backend — run stats, journal hook, routing-note memo — while the warm
state (jit caches, ``_seen_shapes``, plan cache, persistent compile
cache) stays resident.  Per-job deltas of the process-wide singletons
are snapshot-and-diffed by ``cli._open_run_journal`` / ``_finish_run``
per-worker-safely (thread-scoped compile-cache counters, a per-job
plan-cache scope, the worker's own device registry — never a process
total), so every job's ``run_end`` reports its own compile/plan-cache
traffic even with other jobs in flight concurrently.

Cross-job micro-batching (``--batch-window MS`` + ``serve.batcher``):
a worker that pops a batch-ELIGIBLE job (same-method, config-digest-
compatible, solo-semantics jobs — admission stamps the key) pulls
further compatible jobs from the queue — same weighted-fair order,
same quota/conflict eligibility — for up to the window, merges their
parsed clusters into ONE shared packed-bucket dispatch on its resident
backend (``TpuBackend.run_shared``), and then runs each job's ordinary
pipeline against the precomputed per-cluster results, so every job's
output bytes, QC report and checkpoint manifest stay byte-identical to
its solo CLI run.  The shared dispatch's compile/plan/device deltas
ride the journal's ``batch_dispatch`` event and the
``specpride_serve_batch_*`` exposition; a window that closes empty (or
a failed shared pass) degenerates to the solo path untouched.

Robustness: the request loop is guarded by the shared error taxonomy —
transient socket errors on accept retry with a short backoff instead of
killing the daemon, execution errors are classified
retriable-vs-permanent in the terminal response, and
``--watchdog-timeout`` arms the per-lane watchdog over the execution
lane (a wedged job journals ``watchdog_stall`` with the lane name).

Live telemetry plane (``observability.exporter``): ``--metrics-port``
serves a Prometheus ``/metrics`` endpoint sampled at scrape time (queue
depth total and per client, in-flight gauge, job counters/latency
histograms, per-lane busy seconds, compile/plan-cache counters, the
resident backend's dispatch-latency histogram and device peak-memory
watermark), ``--slo method=seconds`` arms per-job latency objectives
(journaled on ``job_done``, burn counters on ``/metrics``), the
``profile`` op captures an on-demand ``jax.profiler`` device trace on
the RUNNING warm daemon, and ``--metrics-out`` flushes a final textfile
snapshot at drain — a drained daemon leaves its numbers behind.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

from specpride_tpu.observability import (
    RunStats,
    device_summary,
    logger,
    open_journal,
)
from specpride_tpu.observability.journal import emit_clock_anchor
from specpride_tpu.observability.tracing import TraceContext, new_span_id
from specpride_tpu.robustness import errors as rb_errors
from specpride_tpu.robustness.watchdog import Watchdog
from specpride_tpu.serve import placement, protocol
from specpride_tpu.serve.scheduler import AdmissionQueue, QuotaExceeded

# how often a long-lived daemon re-journals its wall<->mono clock
# anchor (piggybacked on job completions): frequent enough that the
# trace merger's skew bound stays tight across NTP slews, cheap enough
# to be noise in the journal
CLOCK_ANCHOR_INTERVAL_S = 60.0


class Job:
    """One admitted request: parsed args + the connection awaiting the
    terminal response."""

    __slots__ = (
        "job_id", "client", "argv", "args", "command", "conn", "fh",
        "t_enqueued", "ack", "batch_key", "trace_id", "span_id",
        "parent_span_id",
    )

    def __init__(self, job_id, client, argv, args, command, conn, fh,
                 trace: TraceContext | None = None):
        self.job_id = job_id
        self.client = client
        self.argv = argv
        self.args = args
        self.command = command
        self.conn = conn
        self.fh = fh
        self.t_enqueued = time.perf_counter()
        # the v4 causal envelope: adopt the client's trace (the submit
        # span becomes this job's parent) or mint a fresh root at
        # admission; `span_id` is the job's own serve:job span, the
        # parent every pipeline span inside the job nests under
        ctx = trace if trace is not None else TraceContext.mint()
        self.trace_id = ctx.trace_id
        self.parent_span_id = ctx.span_id if trace is not None else None
        self.span_id = new_span_id()
        # set once the reader has WRITTEN the "accepted" line: the
        # worker (or drain) waits on it before the terminal line, so
        # the two threads can never interleave bytes on one connection
        self.ack = threading.Event()
        # cross-job micro-batching compatibility key (serve.batcher),
        # stamped at admission when the daemon batches; None = solo
        self.batch_key = None


def _job_claimed_paths(job: "Job") -> list[str]:
    """The filesystem paths a job WRITES — the conflict-guard tokens the
    scheduler holds while the job executes.  Two jobs sharing any of
    them (output, QC report, checkpoint manifest, journal) serialize;
    everything else runs concurrently."""
    paths = []
    for attr in ("output", "qc_report", "checkpoint", "journal",
                 "chrome_trace"):
        p = getattr(job.args, attr, None)
        if p:
            paths.append(os.path.abspath(p))
    return paths


class ServeDaemon:
    def __init__(
        self,
        socket_path: str | None = None,
        *,
        max_queue: int = 16,
        workers: int = 0,
        quotas: dict | None = None,
        compile_cache: str | None = None,
        routing_table: str | None = None,
        layout: str = "auto",
        force_device: bool = False,
        precision: str = "f32",
        donate: bool = True,
        warmup: str = "auto",
        warmup_manifest: str | None = None,
        warmup_jobs: int = 0,
        watchdog_timeout: float = 0.0,
        journal_path: str | None = None,
        journal_rotate_mb: float = 0.0,
        metrics_port: int | None = None,
        metrics_host: str = "127.0.0.1",
        metrics_out: str | None = None,
        slo: dict | None = None,
        batch_window: float = 0.0,
        batch_max_clusters: int = 4096,
        autotune: str = "off",
        autotune_interval: float = 1.0,
        autotune_batch_window: tuple | None = None,
        flightrec: str = "off",
        incident_dir: str | None = None,
        result_cache: str | None = None,
        result_store: str | None = None,
    ):
        self.socket_path = socket_path or protocol.default_socket_path()
        # content-addressed result cache (specpride_tpu.cache): boot
        # configures the process-wide tiers once; every worker lane's
        # jobs consult and populate them under per-run counters
        self.result_cache = result_cache
        self.result_store = result_store
        self.compile_cache = compile_cache
        self.routing_table = routing_table
        self.layout = layout
        self.force_device = force_device
        self.precision = precision
        self.donate = donate
        self.warmup = warmup
        self.warmup_manifest = warmup_manifest
        self.warmup_jobs = warmup_jobs
        self.quotas = dict(quotas or {})
        self.queue = AdmissionQueue(
            max_queue, quotas=self.quotas,
            conflict_key=_job_claimed_paths,
        )
        self.journal_path = journal_path
        self.journal_rotate_mb = max(float(journal_rotate_mb), 0.0)
        self.journal = None
        # cross-process clock anchoring: re-emit a clock_anchor on a
        # heartbeat cadence so days-long daemon journals stay alignable
        # even across wall-clock steps (NTP slews); worker lanes share
        # the throttle state under its own lock
        self._anchor_lock = threading.Lock()
        self._last_anchor_mono = 0.0
        self.backend = None  # worker 0's backend (back-compat alias)
        # execution lanes: 0 = auto (min(#local jax devices, 4)); the
        # placement plan and per-worker backends are built at boot
        self.workers_requested = int(workers)
        self.slots: list = []
        self.worker_backends: list = []
        self.metrics_port = metrics_port
        self.metrics_host = metrics_host
        self.metrics_out = metrics_out
        self.slo = dict(slo or {})
        self.telemetry = None  # ServeTelemetry, built at boot
        self.exporter = None  # MetricsExporter when --metrics-port given
        self._profile_lock = threading.Lock()  # one capture at a time
        self.watchdog = Watchdog(watchdog_timeout)
        self.warmed_kernels = 0
        self.jobs_done = 0
        self.jobs_failed = 0
        self.jobs_rejected = 0
        # live-config plane: knobs the autotune controller may move
        # while workers run read their CURRENT value under this lock
        # (one lock, leaf-level — never held while calling out), so a
        # reader can never observe a torn write.  With --autotune off
        # nothing ever writes after boot and the locked read returns
        # the boot value forever — byte-identical behavior.
        self._live_lock = threading.Lock()
        # cross-job micro-batching (serve.batcher): a worker that pops a
        # batch-eligible job pulls further COMPATIBLE queued jobs for up
        # to batch_window seconds (0 = off) and runs their cluster work
        # as one shared packed-bucket dispatch, bounded by
        # batch_max_clusters merged clusters; per-job outputs stay
        # byte-identical to solo runs (see serve.batcher)
        self.batch_window = max(float(batch_window), 0.0)
        self.batch_max_clusters = max(int(batch_max_clusters), 1)
        # closed-loop autotune (specpride_tpu.autotune): off = no
        # controller object exists at all; observe = decisions are
        # journaled, nothing actuates; on = batch-window and active-lane
        # knobs move live through the _live_lock paths above
        if autotune not in ("off", "observe", "on"):
            raise ValueError(
                f"autotune mode {autotune!r} must be off, observe or on"
            )
        self.autotune = autotune
        self._active_workers_v: int | None = None  # None = all lanes
        self.autotune_interval = max(float(autotune_interval), 0.05)
        self.autotune_batch_window = (
            tuple(autotune_batch_window)
            if autotune_batch_window is not None else (0.0, 50.0)
        )
        self.controller = None  # autotune.Controller, built at boot
        self._controller_thread = None
        # flight recorder (observability.flightrec): off = no recorder
        # object exists at all (byte-identical to a recorder-free
        # build); observe = detector firings journal as `incident`
        # events; on = firings also dump atomic bundles under
        # --incident-dir
        if flightrec not in ("off", "observe", "on"):
            raise ValueError(
                f"flightrec mode {flightrec!r} must be off, observe "
                "or on"
            )
        self.flightrec = flightrec
        self.incident_dir = incident_dir
        self.recorder = None  # flightrec.FlightRecorder, built at boot
        # worker parking (autotune workers knob) needs lanes to poll the
        # pop so a parked lane can re-check; every other mode keeps the
        # blocking pop — the exact pre-autotune behavior
        self._pop_timeout = 0.2 if autotune == "on" else None
        self.batches_dispatched = 0
        self.jobs_batched = 0
        self._batch_ids = iter(range(1, 1 << 62)).__next__
        # wid -> jobs collected into its current batch but not yet
        # executing (the sampler folds them into the in-flight view)
        self._batch_backlog: dict[int, list] = {}
        # every client that ever had a job admitted: the drain-time
        # metrics snapshot renders their queue-depth series at 0 instead
        # of dropping the rows (live scrapes keep clear-and-set so
        # departed clients don't linger as stale series forever)
        self._clients_seen: set[str] = set()
        # done/failed increment on CONCURRENT worker threads now, and
        # jobs_rejected on reader threads (and drain): every
        # read-modify-write needs its lock or bursts undercount
        self._rejected_lock = threading.Lock()
        self._counts_lock = threading.Lock()
        self._job_ids = iter(range(1, 1 << 62)).__next__
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._draining = False
        self._drain_lock = threading.Lock()
        self._t_boot = 0.0
        self._worker_threads: list[threading.Thread] = []
        # test seam: every worker waits on this gate between popping a
        # job and executing it, so drain-with-in-flight-work (and
        # concurrent-lane occupancy) is testable deterministically (set
        # by default — production never waits); _inflight_by maps worker
        # id -> its popped-but-not-yet-replied job, observable by the
        # same tests (the _inflight property keeps the single-lane view)
        self._gate = threading.Event()
        self._gate.set()
        self._inflight_by: dict[int, Job] = {}

    @property
    def _inflight(self) -> Job | None:
        """Any in-flight job (the PR 7 single-lane observable; tests and
        the sampler that need the full per-worker map read
        ``_inflight_by``)."""
        return next(iter(self._inflight_by.values()), None)

    # -- live-config knobs (the autotune actuation plane) ----------------

    @property
    def batch_window(self) -> float:
        """The micro-batch collection window in SECONDS, read under the
        live-config lock at every use site (admission's eligibility
        stamp, the collector's deadline) — so the controller can move
        it between jobs without a worker ever seeing a torn value."""
        with self._live_lock:
            return self._batch_window_v

    @batch_window.setter
    def batch_window(self, value) -> None:
        with self._live_lock:
            self._batch_window_v = max(float(value), 0.0)

    @property
    def active_workers(self) -> int:
        """Execution lanes currently picking up work: the worker-count
        knob parks lanes ``wid >= active_workers`` (they finish their
        current job, then idle) instead of destroying their warm
        backends — unparking is instant."""
        with self._live_lock:
            n = self._active_workers_v
        return n if n is not None else max(len(self.slots), 1)

    @active_workers.setter
    def active_workers(self, value) -> None:
        with self._live_lock:
            self._active_workers_v = min(
                max(int(value), 1), max(len(self.slots), 1)
            )

    # -- boot -----------------------------------------------------------

    def boot(self) -> "ServeDaemon":
        """Pay every cold-start cost once, before the socket exists."""
        from specpride_tpu.backends.tpu_backend import TpuBackend
        from specpride_tpu.warmstart import cache as ws_cache
        from specpride_tpu.warmstart.routing import RoutingTable

        self._t_boot = time.perf_counter()
        self.journal = open_journal(
            self.journal_path, rotate_mb=self.journal_rotate_mb,
        )
        self.journal.emit(
            "run_start", command="serve", method="serve", backend="tpu",
            n_clusters=0, socket=self.socket_path,
        )
        # the daemon journal holds MANY concurrent traces, so it never
        # binds one — per-job events name theirs explicitly; the clock
        # anchor still ties this process's mono axis to the wall clock
        emit_clock_anchor(self.journal)
        with self._anchor_lock:
            self._last_anchor_mono = time.perf_counter()
        ws_cache.configure_compile_cache(self.compile_cache)
        state = ws_cache.cache_state()
        self.journal.emit(
            "compile_cache", enabled=state.enabled, dir=state.dir,
            reason=state.reason, source=state.source,
        )
        self.watchdog.journal = self.journal
        if self.result_cache:
            from specpride_tpu.cache import result_cache as rc_mod

            cache = rc_mod.configure(self.result_cache, self.result_store)
            logger.info(
                "result cache: local %s (cap %d MB)%s",
                cache.local.root,
                cache.local.max_bytes // (1024 * 1024),
                ", shared " + cache.shared.describe()
                if cache.shared is not None else "",
            )
        routing = RoutingTable.load(self.routing_table)
        # the worker pool: one resident backend per execution lane,
        # placed by serve.placement (distinct local devices on
        # accelerator hosts; shared platform, independent per-lane
        # state, on CPU-only hosts).  Worker 0's backend doubles as
        # `self.backend` for the single-lane call sites.
        n_workers = (
            self.workers_requested
            if self.workers_requested >= 1
            else placement.default_workers()
        )
        self.slots = placement.plan_placement(n_workers)
        self.worker_backends = [
            TpuBackend(
                layout=self.layout, force_device=self.force_device,
                routing=routing, device=slot.device,
                precision=self.precision, donate=self.donate,
            )
            for slot in self.slots
        ]
        self.backend = self.worker_backends[0]
        # the live telemetry plane: always built (it feeds the drain-time
        # --metrics-out snapshot too), HTTP-exposed only with
        # --metrics-port.  The resident backend's registry rides along so
        # dispatch-latency histograms and the device memory watermark are
        # scrapeable live — which is WHY that registry stays resident
        # across jobs (run_end attribution diffs it per job instead).
        from specpride_tpu.observability.exporter import (
            MetricsExporter,
            ServeTelemetry,
        )

        # probe for a LIVE incumbent BEFORE the exporter binds and the
        # AOT warmup runs: losing the socket race after minutes of XLA
        # compiles would waste the whole boot (the bind below re-checks
        # — the race window stays closed, this is just the fast exit)
        if os.path.exists(self.socket_path) and self._socket_alive():
            raise SystemExit(
                f"another daemon is serving on {self.socket_path} "
                "(pass a different --socket, or stop it first)"
            )
        if len(self.worker_backends) == 1:
            # single lane: the resident registry rides the exposition
            # unlabeled, exactly the PR 8 series names
            self.telemetry = ServeTelemetry(
                slo=self.slo, extra_registries=(self.backend.metrics,),
            )
        else:
            # worker pool: each lane's registry carries the same metric
            # names, so they ride the exposition under one TYPE line
            # with a worker label per series (registry.render_labeled)
            self.telemetry = ServeTelemetry(
                slo=self.slo,
                worker_registries={
                    str(slot.worker): backend.metrics
                    for slot, backend in zip(
                        self.slots, self.worker_backends
                    )
                },
            )
        self.telemetry.sampler = self._sample_live
        if self.metrics_port is not None:
            self.exporter = MetricsExporter(
                self.telemetry.exposition, host=self.metrics_host,
                port=self.metrics_port, health=self._healthz,
            ).start()
        self._boot_warmup(state)
        self._boot_autotune()
        self._boot_flightrec()
        sock_dir = os.path.dirname(self.socket_path)
        if sock_dir:
            os.makedirs(sock_dir, exist_ok=True)
        if os.path.exists(self.socket_path):
            # a stale socket from a dead daemon blocks bind(); a LIVE
            # daemon must not be evicted silently
            if self._socket_alive():
                raise SystemExit(
                    f"another daemon is serving on {self.socket_path} "
                    "(pass a different --socket, or stop it first)"
                )
            os.unlink(self.socket_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(64)
        # a blocked accept() is NOT reliably interrupted by close() from
        # another thread (drain(), the in-process test path) — poll on a
        # short timeout so the stop flag is always observed promptly
        self._listener.settimeout(0.5)
        boot_s = time.perf_counter() - self._t_boot
        self.journal.emit(
            "serve_start", socket=self.socket_path,
            max_queue=self.queue.capacity,
            warmed_kernels=self.warmed_kernels,
            boot_s=round(boot_s, 4),
            workers=len(self.slots),
            placement=[slot.describe() for slot in self.slots],
            **({"batch_window_s": self.batch_window,
                "batch_max_clusters": self.batch_max_clusters}
               if self.batch_window > 0 else {}),
            **({"metrics_port": self.exporter.port}
               if self.exporter is not None else {}),
            **({"slo": self.slo} if self.slo else {}),
            **({"quota": {c: repr(q) for c, q in self.quotas.items()}}
               if self.quotas else {}),
            **({"autotune": self.autotune,
                "autotune_interval_s": self.autotune_interval,
                "autotune_batch_window_ms": list(
                    self.autotune_batch_window)}
               if self.autotune != "off" else {}),
            **({"flightrec": self.flightrec,
                **({"incident_dir": self.incident_dir}
                   if self.incident_dir else {})}
               if self.flightrec != "off" else {}),
        )
        logger.info(
            "serving on %s (boot %.2fs, %d kernel variants warmed, "
            "queue depth %d, %d worker lane(s): %s)", self.socket_path,
            boot_s, self.warmed_kernels, self.queue.capacity,
            len(self.slots),
            " ".join(slot.describe() for slot in self.slots),
        )
        if self.exporter is not None:
            logger.info("live metrics on %s", self.exporter.url)
        return self

    def _boot_autotune(self) -> None:
        """Construct the closed-loop controller (``--autotune
        observe|on``): one :class:`~specpride_tpu.autotune.Controller`
        tapping the daemon journal, with the batch-window and
        active-lane policies bound to the locked live-config knobs.
        ``off`` builds nothing — the kill switch is the absence of the
        controller, so an off daemon is byte-identical to pre-autotune
        behavior."""
        if self.autotune == "off":
            return
        if not self.journal.enabled:
            raise SystemExit(
                "serve --autotune observe|on requires --journal: every "
                "decision must be journaled as evidence"
            )
        from specpride_tpu.autotune import (
            BatchWindowPolicy,
            Controller,
            ControllerThread,
            WorkerPolicy,
        )

        lo_ms, hi_ms = self.autotune_batch_window
        ctl = Controller(
            self.journal, mode=self.autotune, telemetry=self.telemetry,
        )
        ctl.register(
            BatchWindowPolicy(lo_ms=lo_ms, hi_ms=hi_ms),
            get=lambda: round(self.batch_window * 1000.0, 3),
            set=lambda ms: setattr(
                self, "batch_window", float(ms) / 1000.0
            ),
        )
        ctl.register(
            WorkerPolicy(lo=1, hi=len(self.slots)),
            get=lambda: self.active_workers,
            set=lambda n: setattr(self, "active_workers", int(n)),
        )
        self.controller = ctl
        self._controller_thread = ControllerThread(
            ctl, interval=self.autotune_interval,
        ).start()
        logger.info(
            "autotune %s: knobs %s, tick %.2fs, batch-window clamp "
            "[%g, %g] ms", self.autotune,
            ",".join(ctl.status()["knobs"]), self.autotune_interval,
            lo_ms, hi_ms,
        )

    def _boot_flightrec(self) -> None:
        """Construct the flight recorder (``--flightrec observe|on``):
        an always-on ring of recent journal records plus the health
        detector set, tapping the daemon journal next to the autotune
        controller.  ``off`` builds nothing — the kill switch is the
        absence of the recorder, so an off daemon is byte-identical to
        a recorder-free build."""
        if self.flightrec == "off":
            return
        if self.journal is None or not self.journal.enabled:
            raise SystemExit(
                "serve --flightrec observe|on requires --journal: the "
                "detectors fold the journal stream"
            )
        from specpride_tpu.observability.flightrec import FlightRecorder

        ctl = self.controller
        self.recorder = FlightRecorder(
            self.journal,
            mode=self.flightrec,
            incident_dir=self.incident_dir,
            metrics_fn=self.telemetry.exposition,
            autotune_fn=(
                (lambda: {"status": ctl.status(),
                          "knobs": ctl.knob_values()})
                if ctl is not None else None
            ),
            config={
                "host": "serve",
                "socket": self.socket_path,
                "workers": len(self.slots),
                "max_queue": self.queue.capacity,
                "batch_window_s": self.batch_window,
                "batch_max_clusters": self.batch_max_clusters,
                "precision": self.precision,
                "layout": self.layout,
                "donate": self.donate,
                "warmup": self.warmup,
                "watchdog_timeout_s": self.watchdog.timeout_s,
                "slo": self.slo,
                "autotune": self.autotune,
                "flightrec": self.flightrec,
            },
            telemetry=self.telemetry,
        ).start()
        logger.info(
            "flightrec %s: %d detectors, ring %d%s", self.flightrec,
            len(self.recorder.detect.detectors),
            self.recorder.ring.capacity,
            f", bundles under {self.incident_dir}"
            if self.incident_dir else "",
        )

    def _sample_live(self, telemetry) -> None:
        """Scrape-time gauge refresh — every ``/metrics`` GET (and the
        drain-time textfile flush) sees CURRENT queue/in-flight state,
        not the state at the last job boundary."""
        telemetry.queue_depth.set(len(self.queue))
        # per-client depths are an ephemeral label set: clear-and-set so
        # departed clients don't linger as stale series forever — EXCEPT
        # at drain, where the final --metrics-out snapshot renders every
        # client ever admitted at 0 (clear-and-set alone would silently
        # drop the rows from the one exposition a drained daemon leaves
        # behind, hiding which tenants it served)
        telemetry.queue_depth_client.clear()
        if self._draining:
            # sorted() snapshots the set in one C-level pass — admission
            # threads may still be adding concurrently at drain onset
            for client in sorted(self._clients_seen):
                telemetry.queue_depth_client.set(0, client=client)
        for client, n in self.queue.depths().items():
            telemetry.queue_depth_client.set(n, client=str(client))
        # in-flight zeroes (not clears): once a (command, method) pair
        # has run, its series stays visible at 0 — scrapers see the drop
        telemetry.inflight.zero_all()
        inflight = dict(self._inflight_by)  # point-in-time lane view
        # list() snapshots the values in one C-level pass: workers
        # insert/pop backlog entries while scrapes render
        backlog = sum(len(v) for v in list(self._batch_backlog.values()))
        telemetry.inflight_total.set(len(inflight) + backlog)
        counts: dict[tuple, int] = {}
        for job in inflight.values():
            key = (
                job.command,
                str(getattr(job.args, "method", None) or "-"),
                getattr(job.args, "backend", "tpu"),
            )
            counts[key] = counts.get(key, 0) + 1
        for (command, method, backend), n in counts.items():
            telemetry.inflight.set(
                n, command=command, method=method, backend=backend,
            )
        # per-worker occupancy: clear-and-set over the FIXED worker set
        # (idle lanes read 0, busy lanes 1 — the lane-utilization view)
        telemetry.inflight_worker.clear()
        telemetry.workers.set(len(self.slots))
        for slot in self.slots:
            telemetry.inflight_worker.set(
                1 if slot.worker in inflight else 0,
                worker=str(slot.worker),
            )
        telemetry.uptime.set(
            round(time.perf_counter() - self._t_boot, 3)
        )

    def _healthz(self) -> tuple[bool, str]:
        """Per-lane readiness for ``GET /healthz``: ``ok`` while no
        execution lane is stalled, ``degraded`` (HTTP 503) naming the
        stalled lanes once the watchdog flags one — so fleet
        supervisors and load balancers see a wedged lane, not an
        unconditional 200 from a daemon that can no longer serve.
        Draining reports degraded too: a drain is not ready for new
        work.  Without ``--watchdog-timeout`` the stall signal is
        unavailable and the probe degrades only on drain (noted in the
        body so operators know what they armed)."""
        bits = [f"workers={len(self.slots)}",
                f"inflight={len(self._inflight_by)}"]
        if self._draining or self._stop.is_set():
            return False, "draining " + " ".join(bits)
        stalled = self.watchdog.stalled()
        if stalled:
            lanes = ",".join(sorted({lane for lane, _ in stalled}))
            worst = max(e for _, e in stalled)
            return False, (
                f"stalled={lanes} worst_stall_s={worst} "
                + " ".join(bits)
            )
        if not self.watchdog.enabled:
            bits.append("watchdog=off")
        return True, " ".join(bits)

    def _maybe_anchor(self) -> None:
        """Re-emit the journal's clock anchor on heartbeat cadence
        (cheap throttle — at most one pair per interval across lanes)."""
        now = time.perf_counter()
        with self._anchor_lock:
            if now - self._last_anchor_mono < CLOCK_ANCHOR_INTERVAL_S:
                return
            self._last_anchor_mono = now
        emit_clock_anchor(self.journal)

    def _socket_alive(self) -> bool:
        probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        probe.settimeout(1.0)
        try:
            probe.connect(self.socket_path)
            return True
        except OSError:
            return False
        finally:
            probe.close()

    def _boot_warmup(self, state) -> None:
        """AOT-warm the shape manifest once, at boot — the per-request
        path never compiles what a previous process already recorded."""
        if self.warmup == "off":
            return
        from specpride_tpu.warmstart.manifest import (
            DEFAULT_BASENAME,
            load_manifest,
        )
        from specpride_tpu.warmstart.warmup import warm_entries

        path = self.warmup_manifest
        if path is None and state.enabled and state.dir:
            path = os.path.join(state.dir, DEFAULT_BASENAME)
        if path is None or not os.path.exists(path):
            if self.warmup == "manifest":
                raise SystemExit(
                    "serve --warmup manifest: no shape manifest at "
                    f"{path or '<no --warmup-manifest and no compile cache>'}"
                )
            logger.info(
                "serve: no shape manifest yet (%s); first requests will "
                "seed it", path,
            )
            return
        try:
            entries = load_manifest(path)
        except (OSError, ValueError) as e:
            if self.warmup == "manifest":
                raise SystemExit(f"unreadable shape manifest {path}: {e}")
            logger.warning("ignoring shape manifest %s (%s)", path, e)
            return
        results = warm_entries(
            entries, journal=self.journal, jobs=self.warmup_jobs,
            # warm the jit twin the lanes will actually dispatch: the
            # resident backends resolve donation (off on cpu-only
            # hosts / --no-donate), and the aliasing spec is part of
            # the compiled executable — warming the wrong twin would
            # populate the wrong persistent-cache entry
            donate=getattr(
                self.worker_backends[0], "_donate_effective", False
            ),
        )
        self.warmed_kernels = len(results)

    # -- request loop ---------------------------------------------------

    def run(self) -> int:
        """Boot, then serve until SIGTERM/SIGINT (or :meth:`drain` from
        another thread, the in-process test path)."""
        self.boot()
        if threading.current_thread() is threading.main_thread():
            import signal

            signal.signal(signal.SIGTERM, self._on_signal)
            signal.signal(signal.SIGINT, self._on_signal)
        self._worker_threads = [
            threading.Thread(
                target=self._worker_loop, args=(slot.worker,),
                name=f"specpride-serve-worker-{slot.worker}", daemon=True,
            )
            for slot in self.slots
        ]
        for t in self._worker_threads:
            t.start()
        try:
            self._accept_loop()
        finally:
            self.drain()
        return 0

    def _on_signal(self, signum, frame) -> None:
        logger.info("signal %d: draining", signum)
        self._stop.set()
        # closing the listener pops the accept loop out of accept();
        # the run() finally performs the actual drain
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except TimeoutError:
                continue  # poll tick: re-check the stop flag
            except OSError as e:
                if self._stop.is_set():
                    return
                # the retry taxonomy guards the request loop: a
                # transient accept failure (EMFILE burst, interrupted
                # call) backs off instead of killing the daemon
                if rb_errors.is_transient(e):
                    logger.warning("accept failed transiently (%s)", e)
                    time.sleep(0.1)
                    continue
                raise
            t = threading.Thread(
                target=self._handle_conn, args=(conn,),
                name="specpride-serve-reader", daemon=True,
            )
            t.start()

    def _handle_conn(self, conn: socket.socket) -> None:
        # bound the ADMISSION read: a client that connects and goes
        # silent must not pin a reader thread forever.  Execution-side
        # waits are unaffected (the worker only writes).
        conn.settimeout(60.0)
        fh = conn.makefile("rw", encoding="utf-8", newline="\n")
        keep_open = False
        try:
            try:
                msg = protocol.read_msg(fh)
            except ValueError as e:
                protocol.write_msg(
                    fh, ok=False, status="rejected",
                    reason=f"bad message: {e}", retriable=False,
                )
                return
            if msg is None:
                return
            op = msg.get("op")
            if op == "ping":
                protocol.write_msg(
                    fh, ok=True, status="pong", v=protocol.PROTOCOL_VERSION,
                )
            elif op == "status":
                protocol.write_msg(fh, ok=True, **self.status())
            elif op == "profile":
                # runs on THIS reader thread (each connection has its
                # own), so a capture window never blocks admission or
                # the execution lane — profiling a daemon under load is
                # the whole point
                self._profile(msg, fh)
            elif op == "submit":
                keep_open = self._admit(msg, conn, fh)
            else:
                protocol.write_msg(
                    fh, ok=False, status="rejected",
                    reason=f"unknown op {op!r}", retriable=False,
                )
        except OSError as e:
            logger.warning("connection died during admission: %s", e)
        finally:
            if not keep_open:
                self._close(conn, fh)

    def _admit(self, msg: dict, conn, fh) -> bool:
        """Validate + enqueue one submit.  Returns True when the worker
        now owns the connection (it sends the terminal response)."""
        argv = msg.get("argv")
        job_id = self._job_ids()

        def reject(reason: str, retriable: bool) -> bool:
            with self._rejected_lock:
                self.jobs_rejected += 1
            # bounded label cardinality: free-text parser messages all
            # count as "invalid"; the retriable categories keep their
            # name, and per-tenant quota bounces roll up under "quota"
            self.telemetry.job_rejected(
                reason if reason in ("draining", "queue_full")
                else "quota" if reason.startswith("quota ")
                else "invalid"
            )
            self.journal.emit(
                "job_rejected", job_id=job_id, reason=reason,
                retriable=retriable,
            )
            protocol.write_msg(
                fh, ok=False, status="rejected", job_id=job_id,
                reason=reason, retriable=retriable,
            )
            return False

        if not isinstance(argv, list) or not all(
            isinstance(a, str) for a in argv
        ):
            return reject("argv must be a list of strings", False)
        client = msg.get("client")
        if client is not None and not isinstance(client, str):
            # the scheduling key must be hashable and sane; an array/
            # object here would TypeError inside the queue otherwise
            return reject("client must be a string", False)
        if self._draining or self._stop.is_set():
            return reject("draining", True)
        if not argv or argv[0] not in protocol.SERVABLE_COMMANDS:
            return reject(
                f"command must be one of {list(protocol.SERVABLE_COMMANDS)}",
                False,
            )
        forbidden = protocol.forbidden_flags(argv)
        if forbidden:
            return reject(
                f"daemon-owned flags on a job: {forbidden} (set them on "
                "`specpride serve` at boot)", False,
            )
        try:
            args = _parse_job_argv(argv)
        except ValueError as e:
            return reject(str(e), False)
        overridden = protocol.overridden_daemon_flags(args)
        if overridden:
            # abbreviation-proof: argparse accepts unambiguous prefixes
            # (--layou), which the token scan above cannot see — the
            # parsed namespace is the truth
            return reject(
                f"daemon-owned flags on a job: {overridden} (set them on "
                "`specpride serve` at boot)", False,
            )
        try:
            # the client's causal envelope: adopt its trace so the
            # daemon-side spans parent under the submit span; a
            # PRESENT-but-malformed trace rejects (a half-broken join
            # is worse than none), absent mints a fresh root in Job
            trace = TraceContext.from_wire(msg.get("trace"))
        except ValueError as e:
            return reject(str(e), False)
        job = Job(job_id, client or id(conn), argv, args,
                  argv[0], conn, fh, trace=trace)
        if self.batch_window > 0:
            # admission marks batch-eligible jobs: the compatibility key
            # is computed ONCE here (reader thread) so the worker-side
            # collector only compares tuples
            from specpride_tpu.serve import batcher

            job.batch_key = batcher.batch_key(args, job.command)
        try:
            admitted = self.queue.offer(job.client, job)
        except QuotaExceeded as e:
            # the tenant's max_inflight quota already covers its queued
            # + executing jobs: backpressure with the quota NAMED, and
            # retriable — `specpride submit` exits 75 (EX_TEMPFAIL)
            return reject(str(e), True)
        if not admitted:
            return reject(
                "draining" if self._draining else "queue_full", True
            )
        self._clients_seen.add(str(job.client))
        self.journal.emit(
            "job_queued", job_id=job_id, client=str(job.client),
            command=job.command, method=getattr(args, "method", None),
            trace_id=job.trace_id,
            **({"batch_eligible": job.batch_key is not None}
               if self.batch_window > 0 else {}),
        )
        try:
            protocol.write_msg(
                fh, ok=True, status="accepted", job_id=job_id,
                queue_depth=len(self.queue), trace_id=job.trace_id,
            )
        finally:
            job.ack.set()  # even on a dead client the worker must not wait
        return True

    # -- on-demand device profiling -------------------------------------

    def _profile(self, msg: dict, fh) -> None:
        """``specpride profile``: one bounded ``jax.profiler`` capture
        window on the RUNNING warm daemon — no restart, no cold
        recompile on the next job (start/stop trace does not touch the
        jit caches).  Also slices the daemon journal's events that
        landed inside the window into ``<trace_dir>/journal_window.jsonl``
        so the device trace and the serving timeline line up.  One
        capture at a time (jax has a single global profiler session);
        a concurrent request is rejected retriable."""
        seconds = msg.get("seconds", 3.0)
        if not isinstance(seconds, (int, float)) or not (
            0 < seconds <= protocol.PROFILE_MAX_SECONDS
        ):
            protocol.write_msg(
                fh, ok=False, status="rejected",
                reason=f"seconds must be in (0, "
                f"{protocol.PROFILE_MAX_SECONDS}]", retriable=False,
            )
            return
        trace_dir = msg.get("trace_dir")
        chrome_trace = msg.get("chrome_trace")
        for name, val in (("trace_dir", trace_dir),
                          ("chrome_trace", chrome_trace)):
            if val is not None and not isinstance(val, str):
                protocol.write_msg(
                    fh, ok=False, status="rejected",
                    reason=f"{name} must be a string path", retriable=False,
                )
                return
        if not self._profile_lock.acquire(blocking=False):
            protocol.write_msg(
                fh, ok=False, status="rejected",
                reason="a profile capture is already running",
                retriable=True,
            )
            return
        started = False
        try:
            import glob as _glob
            import shutil
            import tempfile

            import jax

            if trace_dir is None:
                trace_dir = tempfile.mkdtemp(prefix="specpride_profile_")
            else:
                os.makedirs(trace_dir, exist_ok=True)
            mono0 = time.perf_counter()
            self.journal.emit(
                "profile_start", seconds=seconds, trace_dir=trace_dir,
            )
            try:
                # perfetto trace only when the caller wants the
                # chrome-loadable artifact (it costs an extra export)
                jax.profiler.start_trace(
                    trace_dir, create_perfetto_trace=bool(chrome_trace)
                )
            except TypeError:  # older jax without the kwarg
                jax.profiler.start_trace(trace_dir)
            started = True
            deadline = mono0 + float(seconds)
            while not self._stop.is_set():
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                time.sleep(min(0.2, remaining))
            jax.profiler.stop_trace()
            started = False
            mono1 = time.perf_counter()
            artifacts = sorted(
                p for p in _glob.glob(
                    os.path.join(trace_dir, "**"), recursive=True
                )
                if os.path.isfile(p)
            )
            perfetto = next(
                (p for p in artifacts
                 if os.path.basename(p).startswith("perfetto_trace")),
                None,
            )
            if chrome_trace and perfetto:
                shutil.copyfile(perfetto, chrome_trace)
            window = self._journal_window(trace_dir, mono0, mono1)
            self.journal.emit(
                "profile_done", seconds=round(mono1 - mono0, 4),
                trace_dir=trace_dir, n_artifacts=len(artifacts),
            )
            logger.info(
                "profile: %.2fs window, %d artifact(s) -> %s",
                mono1 - mono0, len(artifacts), trace_dir,
            )
            protocol.write_msg(
                fh, ok=True, status="profiled",
                seconds=round(mono1 - mono0, 4), trace_dir=trace_dir,
                artifacts=[os.path.relpath(p, trace_dir)
                           for p in artifacts],
                chrome_trace=(
                    chrome_trace if chrome_trace and perfetto else None
                ),
                **window,
            )
        except Exception as e:  # noqa: BLE001 - reported to the client
            if started:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001 - best-effort cleanup
                    pass
            logger.warning("profile capture failed: %s", e)
            try:
                protocol.write_msg(
                    fh, ok=False, status="error",
                    error=f"{type(e).__name__}: {e}", retriable=False,
                )
            except OSError:
                pass
        finally:
            self._profile_lock.release()

    def _journal_window(
        self, trace_dir: str, mono0: float, mono1: float
    ) -> dict:
        """The daemon-journal events whose ``mono`` landed inside the
        capture window, written beside the device trace plus summarized
        inline — so "what was the daemon doing during this profile?"
        needs no manual timestamp math.  Empty dict without a journal."""
        path = getattr(self.journal, "path", None)
        if not path:
            return {"window_events": {}}
        counts: dict[str, int] = {}
        out_path = os.path.join(trace_dir, "journal_window.jsonl")
        try:
            with open(path, encoding="utf-8") as src, \
                    open(out_path, "w", encoding="utf-8") as dst:
                for line in src:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # the torn in-progress tail
                    mono = rec.get("mono")
                    if isinstance(mono, (int, float)) and \
                            mono0 <= mono <= mono1:
                        dst.write(line)
                        ev = rec.get("event", "?")
                        counts[ev] = counts.get(ev, 0) + 1
        except OSError as e:
            logger.warning("journal window slice failed: %s", e)
            return {"window_events": {}}
        return {"journal_window": out_path, "window_events": counts}

    # -- execution lane -------------------------------------------------

    def _worker_loop(self, wid: int) -> None:
        while True:
            if self._pop_timeout is not None and \
                    wid >= self.active_workers:
                # parked lane (autotune workers knob, mode on): the
                # warm backend idles — finish nothing new until the
                # controller unparks this lane or the daemon drains
                if self._stop.wait(self._pop_timeout):
                    return
                continue
            job = self.queue.pop(timeout=self._pop_timeout)
            if job is None:
                if self._pop_timeout is not None and \
                        not self._stop.is_set():
                    continue  # poll tick: re-check parking, not a drain
                return
            self._inflight_by[wid] = job
            self._gate.wait()
            batch, parsed, window_wait = self._collect_batch(job, wid)
            if parsed is None:
                # solo: exactly the PR 10 path (batching off, the job
                # ineligible, or the window closed empty)
                self._run_job(job, wid)
            else:
                self._batch_backlog[wid] = list(batch[1:])
                shared, batch_info = self._shared_dispatch(
                    batch, parsed, wid, window_wait
                )
                for j in batch:
                    self._inflight_by[wid] = j
                    backlog = self._batch_backlog.get(wid)
                    if backlog and j in backlog:
                        backlog.remove(j)
                    # only members actually served from the shared
                    # results carry batch fields: a failed shared pass
                    # (or a member whose parse failed) runs solo and
                    # must not report itself as batched — the
                    # batch_dispatch event still records the attempt
                    s = (shared or {}).get(j.job_id)
                    self._run_job(
                        j, wid, shared=s,
                        batch_info=batch_info if s is not None else None,
                    )
                self._batch_backlog.pop(wid, None)
            self._inflight_by.pop(wid, None)

    def _collect_batch(self, leader: Job, wid: int):
        """Micro-batch collection (the leader lane's window): pull
        further COMPATIBLE queued jobs — same weighted-fair order, same
        quota/conflict eligibility as a normal pop — and parse each
        member's input through the ingest-cache residency, until the
        merged cluster budget is met or the window closes.  The window
        bounds the wait for the FIRST companion; once companions are on
        board an empty queue dispatches immediately (idling a lane past
        that point only adds latency).  Drain closes the window early:
        jobs already collected commit, jobs still queued are rejected
        retriable by the drain as always.

        Returns ``(batch, parsed, window_wait_s)``; ``parsed`` is None
        for the solo path (batching off / ineligible leader / window
        closed empty), else ``{job_id: clusters-or-None}`` (None marks
        a member whose parse failed — it runs solo inside the batch so
        the error surfaces through its own lane)."""
        key = leader.batch_key
        if key is None or self.batch_window <= 0:
            return [leader], None, 0.0
        from specpride_tpu.serve import batcher

        t0 = time.perf_counter()
        parsed: dict[int, list | None] = {}
        try:
            parsed[leader.job_id] = batcher.parse_batch_input(
                leader.args, wid
            )
        except BaseException:  # noqa: BLE001 - solo run surfaces it
            return [leader], None, 0.0
        batch = [leader]
        total = len(parsed[leader.job_id])
        # the companion-wait deadline anchors AFTER the leader's parse:
        # anchored at t0, a parse >= the window would expire it before
        # the wait loop ever ran, silently degrading batching to
        # already-queued jobs in exactly the small-job regime it targets
        deadline = time.perf_counter() + self.batch_window
        while total < self.batch_max_clusters:
            nxt = self.queue.pop_compatible(
                lambda j: j.batch_key == key
            )
            if nxt is not None:
                batch.append(nxt)
                try:
                    clusters = batcher.parse_batch_input(nxt.args, wid)
                except BaseException:  # noqa: BLE001 - member runs solo
                    parsed[nxt.job_id] = None
                else:
                    parsed[nxt.job_id] = clusters
                    total += len(clusters)
                continue
            if len(batch) > 1:
                break  # companions on board: dispatch, don't idle
            if self._stop.is_set() or self._draining:
                break  # drain: commit what we hold
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            time.sleep(min(0.002, remaining))
        window_wait = time.perf_counter() - t0
        if len(batch) == 1:
            # degenerate path: the window closed empty — run solo (the
            # leader's parse stays resident in the ingest cache, so
            # nothing was wasted)
            return [leader], None, window_wait
        return batch, parsed, window_wait

    def _shared_dispatch(self, batch, parsed, wid: int, window_wait):
        """Run the batch's ONE shared prepare + dispatch group on this
        lane's resident backend and journal the ``batch_dispatch``
        attribution (jobs, merged clusters, bucket occupancy, window
        wait, fresh compiles, plan-cache traffic — the deltas no single
        job's run_end can claim).  Returns ``(shared, batch_info)``;
        ``shared`` is None when the shared pass failed — every member
        then runs solo, so a poisoned batch degrades to exactly the
        unbatched behavior."""
        from specpride_tpu.data.packed import (
            PlanCacheScope,
            set_plan_scope,
        )
        from specpride_tpu.observability import device_counters_snapshot
        from specpride_tpu.serve import batcher
        from specpride_tpu.warmstart import cache as ws_cache

        leader = batch[0]
        backend = self.worker_backends[wid]
        slot = self.slots[wid]
        bid = self._batch_ids()
        entries = [
            (j, parsed[j.job_id]) for j in batch
            if parsed.get(j.job_id) is not None
        ]
        n_clusters = sum(len(c) for _, c in entries)
        batch_info = {
            "batch_id": bid,
            "n_jobs": len(batch),
            "n_clusters": n_clusters,
            "window_wait_s": round(window_wait, 4),
        }
        # per-batch state reset on the REAL backend, mirroring
        # _execute's per-job reset (warm state stays resident)
        backend.stats = RunStats()
        backend.pack_accounting = False
        backend._routing_noted.clear()
        cc0 = ws_cache.thread_counters_snapshot()
        dev0 = device_counters_snapshot(backend.metrics)
        scope = PlanCacheScope()
        prev_scope = set_plan_scope(scope)
        t0 = time.perf_counter()
        shared, err = None, None
        try:
            with self.watchdog.section("serve:batch"), \
                    placement.device_scope(slot.device):
                shared = batcher.compute_shared(
                    backend, leader.args, entries
                )
        except BaseException as e:  # noqa: BLE001 - members run solo
            err = f"{type(e).__name__}: {e}"
            logger.warning(
                "batch %d: shared dispatch failed (%s); %d job(s) run "
                "solo", bid, e, len(batch),
            )
        finally:
            set_plan_scope(prev_scope)
        wall = time.perf_counter() - t0
        cc = ws_cache.thread_counters_delta(cc0)
        dev = device_summary(backend.metrics, since=dev0)
        status = "shared" if shared is not None else "fallback_solo"
        if shared is not None:
            with self._counts_lock:
                self.batches_dispatched += 1
                self.jobs_batched += len(shared)
        # the shared dispatch is ONE leader span in the leader's trace,
        # linked to every member: `trace_ids` names each member's trace
        # (the merger includes the batch in all of them) and the span
        # parents under the leader's serve:job span
        batch_span = new_span_id()
        self.journal.emit(
            "batch_dispatch", batch_id=bid,
            jobs=[j.job_id for j in batch],
            clients=sorted({str(j.client) for j in batch}),
            n_jobs=len(batch), n_clusters=n_clusters,
            method=getattr(leader.args, "method", None),
            key=list(leader.batch_key or ()),
            window_wait_s=round(window_wait, 4),
            wall_s=round(wall, 4), worker=wid, status=status,
            fresh_compiles=cc.get("misses", 0),
            plan_cache=scope.delta(),
            dispatches=dev["dispatches"],
            bucket_occupancy_frac=dev["bucket_occupancy_frac"],
            padding_waste_frac=dev["padding_waste_frac"],
            trace_ids=[j.trace_id for j in batch],
            span_id=batch_span,
            parent_span_id=leader.span_id,
            **({"error": err} if err else {}),
        )
        self.journal.emit(
            "span", name="serve:batch", mono=t0 + wall,
            dur_s=round(wall, 6), depth=1, tid=wid,
            trace_id=leader.trace_id, span_id=batch_span,
            parent_span_id=leader.span_id,
            labels={
                "batch_id": bid, "n_jobs": len(batch),
                "n_clusters": n_clusters, "status": status,
                "worker": wid,
            },
        )
        if shared is not None:
            # jobs SERVED from the share (a member whose parse failed
            # runs solo and is excluded), matching the status
            # snapshot's jobs_batched and the metric's help text
            self.telemetry.batch_dispatch(
                n_jobs=len(shared), n_clusters=n_clusters,
                window_wait_s=window_wait,
                occupancy_frac=dev["bucket_occupancy_frac"],
            )
        return shared, batch_info

    def _run_job(
        self, job: Job, wid: int, shared=None, batch_info=None,
    ) -> None:
        from specpride_tpu.warmstart import cache as ws_cache

        batch_fields = (
            {"batch_id": batch_info["batch_id"],
             "batch_jobs": batch_info["n_jobs"]}
            if batch_info is not None else {}
        )
        self._maybe_anchor()
        wait_s = time.perf_counter() - job.t_enqueued
        self.journal.emit(
            "job_start", job_id=job.job_id, command=job.command,
            method=getattr(job.args, "method", None),
            queue_wait_s=round(wait_s, 4), worker=wid,
            trace_id=job.trace_id,
            **batch_fields,
        )
        t0 = time.perf_counter()
        # the admission->execution wait as a REAL span in the job's
        # causal tree (sibling of serve:job, parented under the
        # client's submit span when one arrived on the wire)
        span_kwargs = (
            {"parent_span_id": job.parent_span_id}
            if job.parent_span_id else {}
        )
        self.journal.emit(
            "span", name="serve:queue", mono=t0,
            dur_s=round(wait_s, 6), depth=0, tid=wid,
            trace_id=job.trace_id, span_id=new_span_id(),
            labels={"job_id": job.job_id, "worker": wid},
            **span_kwargs,
        )
        # THREAD-scoped compile counters: every compile a job causes
        # fires on the worker thread that dispatched it, so this
        # delta is the job's own even with other lanes compiling
        # concurrently (the process-wide snapshot would cross-
        # attribute between in-flight jobs).  A batched job's shared
        # compiles fired BEFORE this snapshot and ride the
        # batch_dispatch event instead — per-job deltas stay the work
        # its own lane performed.
        cc0 = ws_cache.thread_counters_snapshot()
        status, rc, err, retriable, summary = "done", 0, None, False, None
        try:
            with self.watchdog.section("serve:job"):
                summary = self._execute(job, wid, shared=shared)
        except SystemExit as e:
            # CLI-style usage/abort error (bad input file, refused
            # resume): permanent from the daemon's point of view
            status, rc = "error", 1
            err = str(e.code) if not isinstance(e.code, int) else \
                f"exit {e.code}"
        except BaseException as e:  # noqa: BLE001 - reported to client
            status, rc = "error", 1
            err = f"{type(e).__name__}: {e}"
            retriable = rb_errors.is_transient(e)
        wall = time.perf_counter() - t0
        cc = ws_cache.thread_counters_delta(cc0)
        with self._counts_lock:
            if status == "done":
                self.jobs_done += 1
            else:
                self.jobs_failed += 1
        # the job's execution interval as the serve:job span — ITS
        # span_id is what every pipeline span inside the job (and a
        # shared batch dispatch it led) parents under
        self.journal.emit(
            "span", name="serve:job", mono=time.perf_counter(),
            dur_s=round(wall, 6), depth=0, tid=wid,
            trace_id=job.trace_id, span_id=job.span_id,
            labels={
                "job_id": job.job_id, "worker": wid,
                "command": job.command, "status": status,
                **({"method": getattr(job.args, "method")}
                   if getattr(job.args, "method", None) else {}),
            },
            **span_kwargs,
        )
        # fold the finished job into the live metric plane; the SLO
        # evaluation (objective, measured latency, ok/breach) rides
        # the journal's job_done so `stats --slo` and /metrics agree —
        # and the trace_id rides the latency histograms as an exemplar
        slo_fields = self.telemetry.job_done(
            command=job.command,
            method=getattr(job.args, "method", None),
            status=status, wall_s=wall, queue_wait_s=wait_s,
            summary=summary if isinstance(summary, dict) else None,
            worker=wid, trace_id=job.trace_id,
        )
        self.journal.emit(
            "job_done", job_id=job.job_id, status=status,
            wall_s=round(wall, 4), queue_wait_s=round(wait_s, 4),
            command=job.command,
            method=getattr(job.args, "method", None),
            fresh_compiles=cc.get("misses", 0),
            worker=wid,
            trace_id=job.trace_id,
            **batch_fields,
            **slo_fields,
            # result-cache hit attribution: ride the terminal event so
            # `stats` and operators see which jobs were served warm
            # without opening the job's own journal
            **(
                {"result_cache_hits":
                 summary["counters"]["result_cache_hits"]}
                if isinstance(summary, dict)
                and "result_cache_hits" in summary.get("counters", {})
                else {}
            ),
            **({"error": err} if err else {}),
        )
        job.ack.wait(timeout=10.0)  # admission line strictly first
        try:
            if status == "done":
                protocol.write_msg(
                    job.fh, ok=True, status="done", job_id=job.job_id,
                    rc=rc, wall_s=round(wall, 4),
                    queue_wait_s=round(wait_s, 4), stats=summary,
                    compile_cache=cc, worker=wid,
                    trace_id=job.trace_id,
                    **({"batch": batch_fields} if batch_fields else {}),
                )
            else:
                protocol.write_msg(
                    job.fh, ok=False, status="error", job_id=job.job_id,
                    error=err, retriable=retriable,
                )
        except (OSError, ValueError):
            # the client went away while its job ran (ValueError:
            # the admission path already closed the fh after a
            # failed accepted-write); the output is on disk
            # regardless — log, never crash the lane
            logger.warning(
                "job %d: client disconnected before the terminal "
                "response", job.job_id,
            )
        self._close(job.conn, job.fh)
        self._inflight_by.pop(wid, None)
        # free the client's inflight-quota slot and the job's
        # conflict-guard paths only AFTER the terminal write and
        # close: a same-output successor popping earlier could start
        # rewriting the file a reader still attributes to this job
        self.queue.release(job)

    def _execute(self, job: Job, wid: int, shared=None) -> dict:
        """Run one job through THE CLI execution body with worker
        ``wid``'s resident backend, pinned to its placement slot,
        resetting exactly the per-run backend state first.  ``shared``
        (a ``batcher.SharedResults``) wraps the backend in the batch's
        read-only result view — the pipeline body, write lanes and
        accounting run unchanged."""
        from specpride_tpu import cli

        slot = self.slots[wid]
        # the CLI stamps the worker into the job's run_end and scopes
        # its tracer + singleton snapshots to this thread (numpy-backend
        # jobs too: their journal spans must not leak across lanes)
        job.args._serve_worker = wid
        # the job's pipeline runs under ITS causal context: every span
        # in the job's own --journal parents under the serve:job span,
        # and the job journal stamps the trace_id on every event
        job.args._trace_ctx = TraceContext(job.trace_id, job.span_id)
        backend = None
        if getattr(job.args, "backend", "tpu") == "tpu":
            backend = self.worker_backends[wid]
            # per-job telemetry state on the worker's OWN backend: run
            # stats are per-run by contract; the journal hook and pack
            # accounting are (re)set by _open_run_journal, and the
            # routing-note memo clears so EVERY job's journal carries
            # the routing events that applied to it.  Warm state
            # (_seen_shapes, jit caches) deliberately survives — and so
            # does the METRICS registry: /metrics serves it live, so its
            # counters must stay Prometheus-monotone across jobs (each
            # job's run_end diffs a device_counters_snapshot instead;
            # per-worker registries make that diff concurrency-safe).
            backend.stats = RunStats()
            backend.pack_accounting = False
            backend._routing_noted.clear()
            # boot warmed the manifest once and the jit caches stay
            # resident: per-job AOT re-warming is pure request latency
            # (manifest saving still runs so jobs seed future boots)
            job.args._resident_warm = True
            if shared is not None:
                from specpride_tpu.serve.batcher import (
                    BatchResultBackend,
                )

                backend = BatchResultBackend(backend, shared)
        with placement.device_scope(slot.device):
            return cli._run_pipeline_command(job.args, job.command,
                                             backend=backend)

    # -- shutdown -------------------------------------------------------

    def drain(self) -> None:
        """Graceful shutdown: reject queued jobs (retriable), commit
        EVERY worker's in-flight job through its ordered write lane,
        close everything.  Idempotent and callable from any thread
        (signal path and in-process tests share it)."""
        with self._drain_lock:
            if self._draining:
                return
            self._draining = True
        self._stop.set()
        if self.journal is None:
            return  # boot never completed; nothing to flush or reject
        # the controller stops FIRST: a tick racing the final
        # serve_drain/run_end emits (or the journal close below) would
        # interleave a decision into the drain epilogue
        if self._controller_thread is not None:
            self._controller_thread.stop()
            self._controller_thread = None
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        rejected = self.queue.drain()
        for job in rejected:
            with self._rejected_lock:
                self.jobs_rejected += 1
            if self.telemetry is not None:
                self.telemetry.job_rejected("draining")
            self.journal.emit(
                "job_rejected", job_id=job.job_id, reason="draining",
                retriable=True,
            )
            job.ack.wait(timeout=10.0)  # admission line strictly first
            try:
                protocol.write_msg(
                    job.fh, ok=False, status="rejected", job_id=job.job_id,
                    reason="draining", retriable=True,
                )
            except (OSError, ValueError):
                pass  # client already gone / fh closed by its reader
            self._close(job.conn, job.fh)
        self._gate.set()  # a held test gate must not deadlock the drain
        # every lane finishes its in-flight job (the queue is closed and
        # empty, so each worker commits what it holds, then exits)
        for t in self._worker_threads:
            if t.is_alive():
                t.join()
        # wait out an in-flight profile capture (its window breaks on
        # _stop within one sleep quantum, but stop_trace's export + the
        # journal-window scan take real time): its profile_done must
        # land BEFORE run_end, never after journal close.  Bounded — a
        # wedged profiler must not hang the drain forever.
        if self._profile_lock.acquire(timeout=60):
            self._profile_lock.release()
        else:
            logger.warning(
                "drain: a profile capture did not finish within 60s; "
                "its journal events may be dropped"
            )
        self.watchdog.stop()
        # the flight recorder stops after the workers joined (their
        # final job/watchdog events still fold and can journal
        # incidents) and BEFORE the metrics flush + journal close:
        # stop() drains every queued firing, so no incident evidence
        # is swallowed by the drain
        if self.recorder is not None:
            self.recorder.stop()
        # final telemetry: the exporter stops AFTER the worker joined so
        # the last snapshot carries every job, and --metrics-out flushes
        # the same exposition a scraper would have read — a drained
        # daemon leaves its numbers behind, not just its journal
        if self.exporter is not None:
            self.exporter.stop()
        if self.metrics_out and self.telemetry is not None:
            try:
                self.telemetry.write_textfile(self.metrics_out)
                logger.info("final metrics -> %s", self.metrics_out)
            except OSError as e:
                logger.warning(
                    "final metrics flush to %s failed: %s",
                    self.metrics_out, e,
                )
        uptime = time.perf_counter() - self._t_boot
        self.journal.emit(
            "serve_drain", n_rejected=len(rejected),
            jobs_done=self.jobs_done, jobs_failed=self.jobs_failed,
        )
        self.journal.emit(
            "run_end",
            counters={
                "jobs_done": self.jobs_done,
                "jobs_failed": self.jobs_failed,
                "jobs_rejected": self.jobs_rejected,
            },
            phases_s={"serve": round(uptime, 4)},
            elapsed_s=round(uptime, 4),
            device=device_summary(None),
        )
        self.journal.close()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        if self.result_cache:
            # release the boot-owned tiers: in-flight RunContexts hold
            # their own reference, future in-process daemons (tests)
            # configure their own
            from specpride_tpu.cache import result_cache as rc_mod

            rc_mod.configure(None)
        logger.info(
            "drained: %d done, %d failed, %d rejected",
            self.jobs_done, self.jobs_failed, self.jobs_rejected,
        )

    def status(self) -> dict:
        return {
            "status": "serving" if not self._draining else "draining",
            "socket": self.socket_path,
            "queue_depth": len(self.queue),
            "max_queue": self.queue.capacity,
            "jobs_done": self.jobs_done,
            "jobs_failed": self.jobs_failed,
            "jobs_rejected": self.jobs_rejected,
            "warmed_kernels": self.warmed_kernels,
            "workers": len(self.slots),
            "placement": [slot.describe() for slot in self.slots],
            "inflight": len(self._inflight_by),
            "uptime_s": round(time.perf_counter() - self._t_boot, 2),
            **(
                {"batching": {
                    "window_s": self.batch_window,
                    "max_clusters": self.batch_max_clusters,
                    "batches_dispatched": self.batches_dispatched,
                    "jobs_batched": self.jobs_batched,
                }}
                if self.batch_window > 0 else {}
            ),
            **({"quota": {c: repr(q) for c, q in self.quotas.items()}}
               if self.quotas else {}),
            **(
                {"metrics_port": self.exporter.port,
                 "metrics_url": self.exporter.url}
                if self.exporter is not None else {}
            ),
            **({"slo": self.slo} if self.slo else {}),
            **(
                {"autotune": {
                    **self.controller.status(),
                    "batch_window_ms": round(
                        self.batch_window * 1000.0, 3
                    ),
                    "active_workers": self.active_workers,
                }}
                if self.controller is not None else {}
            ),
            **(
                {"flightrec": self.recorder.status()}
                if self.recorder is not None else {}
            ),
            **self._result_cache_status(),
        }

    @staticmethod
    def _result_cache_status() -> dict:
        from specpride_tpu.cache import result_cache as rc_mod

        cache = rc_mod.active()
        if cache is None:
            return {}
        return {"result_cache": {**cache.info(), **rc_mod.totals()}}

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no job is admitted, queued, batched or in
        flight — the deterministic seam tests (and scripted probes)
        use between 'the client got its reply' and 'the daemon's
        internal accounting settled': a reply is written BEFORE the
        worker drops the job from ``_inflight_by``, so a scrape right
        after a reply can otherwise race the residue.  Returns False
        on timeout."""
        deadline = time.perf_counter() + max(float(timeout), 0.0)
        while time.perf_counter() < deadline:
            if (
                not self._inflight_by
                and len(self.queue) == 0
                and not any(self._batch_backlog.values())
            ):
                return True
            time.sleep(0.002)
        return False

    @staticmethod
    def _close(conn, fh) -> None:
        for closer in (fh, conn):
            try:
                closer.close()
            except OSError:
                pass


_parser_lock = threading.Lock()
_job_parser = None


def _build_job_parser():
    """The CLI's OWN parser with every error() (top level AND each
    subparser) rebound to raise ValueError in place of argparse's
    print-to-stderr + SystemExit.  Rebinding — not
    ``contextlib.redirect_stderr`` — because admission runs on
    concurrent reader threads and redirecting the PROCESS-global
    ``sys.stderr`` there cross-attributes error text between clients
    and can leave stderr pointing at a dead buffer."""
    from specpride_tpu.cli import build_parser

    ap = build_parser()

    def _raise(message: str):
        raise ValueError(f"argv rejected by the CLI parser: {message}")

    ap.error = _raise
    if ap._subparsers is not None:
        for action in ap._subparsers._group_actions:
            for sub in (getattr(action, "choices", None) or {}).values():
                sub.error = _raise
    return ap


def _parse_job_argv(argv: list[str]):
    """Parse a job argv with the (cached) CLI parser, so served jobs
    accept exactly what one-shot runs accept.  Raises ValueError with
    the parser's own message on rejection; ``--help``-style exits are
    rejections too (a job must never print help into the daemon)."""
    global _job_parser
    with _parser_lock:
        # one parser for the daemon's lifetime (admission is the hot
        # path; rebuilding the full subcommand tree per request is
        # waste), serialized — parse_args builds a fresh Namespace but
        # argparse makes no thread-safety promises
        if _job_parser is None:
            _job_parser = _build_job_parser()
        try:
            return _job_parser.parse_args(argv)
        except SystemExit:
            # e.g. --help / --version actions exit without error()
            raise ValueError(
                f"argv rejected by the CLI parser: {json.dumps(argv)}"
            ) from None
