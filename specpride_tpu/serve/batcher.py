"""Cross-job micro-batching for the serving daemon (ROADMAP item 3(b)).

The paper's workloads are MANY SMALL per-cluster consensus computations,
and through PR 10 the worker pool still dispatched each tenant job's
packed buckets to the device alone — BENCH_r14 plateaued at 1.75x on
small jobs because per-job dispatches under-fill buckets and pay the
fixed dispatch overhead per job.  This module coalesces cluster work
from multiple queued jobs into SHARED packed-bucket device dispatches:

* **Compatibility key** (:func:`batch_key`) — jobs may share a dispatch
  only when one device program can serve them all: same command +
  method, byte-identical method/QC config (a digest of the constructed
  config objects, so argparse spelling differences cannot split
  compatible jobs or merge incompatible ones), same backend, and the
  daemon's one platform.  Anything with job-scoped execution semantics
  (elastic/mesh/multi-host flags, fault injection, ``--on-error skip``
  quarantine, streamed/mzML inputs, best-spectrum's per-job score
  source) is ineligible and runs solo exactly as before.

* **Collection** — the worker that pops a batch-eligible job becomes
  the batch LEADER: it pulls further compatible jobs from the admission
  queue (``AdmissionQueue.pop_compatible`` — same weighted-fair order,
  same inflight-quota and output-conflict eligibility as a normal pop,
  so scheduling policy is unchanged by batching), bounded by
  ``--batch-window`` (max wait for the first companion) and
  ``--batch-max-clusters`` (merged size).  A window that closes empty
  degenerates to the solo path untouched.

* **Shared dispatch** (:func:`compute_shared`) — each job's input is
  parsed once (through the ingest-cache residency), identical inputs
  are computed ONCE and fanned out, and distinct inputs are merged by
  ``data.packed.merge_cluster_sources`` into one
  ``TpuBackend.run_shared`` pack + dispatch group with provenance
  spans for the scatter.

* **Scatter with byte parity** — every job still runs the exact CLI
  execution body (``cli._run_pipeline_command``) through its own
  QC/write/checkpoint lanes; only its backend is wrapped in
  :class:`BatchResultBackend`, a read-only view serving the batch's
  precomputed per-cluster results (and QC cosines) by cluster id.
  Because every batchable method is per-cluster, the precomputed
  results are bit-identical to a solo run's, so each job's output
  bytes, QC report and checkpoint manifest match its solo CLI run —
  the same parity bar every other serving feature is held to.  Any
  cluster the shared pass did not cover (or a shared-dispatch failure)
  falls back to the real backend / a solo run, never to a wrong
  answer.

Attribution: the shared dispatch's compile-cache, bucket-plan and
device-counter deltas cannot be charged to any single job — they ride
the daemon journal's ``batch_dispatch`` event (jobs, clusters, bucket
occupancy, window wait, fresh compiles, plan traffic) and the
``specpride_serve_batch_*`` exposition instead, while each job's own
``run_end`` snapshot-and-diff accounting keeps reporting only the work
performed on its own lane (near zero when served from the batch).
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from specpride_tpu.observability import RunStats

# methods whose results are a pure per-cluster function of the input +
# config — the precondition for sharing a dispatch across jobs.
# best-spectrum is excluded: its result depends on a per-job score
# source (--msms/--psms), which is not part of the cluster data.
BATCHABLE_METHODS = ("bin-mean", "gap-average", "medoid")


def _eager_input(args) -> bool:
    """True when the job will parse its input EAGERLY (a materialized
    cluster list the batch can merge) — mirrors ``cli._load_clusters``'s
    streaming decision so eligibility never diverges from execution."""
    import os

    from specpride_tpu.cli import _STREAM_AUTO_BYTES

    if args.input.endswith(".gz"):
        # the ingest cache refuses .gz, so the batch parse could not be
        # reused by the job's own pipeline — it would parse twice
        return False
    mode = (getattr(args, "stream_clusters", "off") or "off").lower()
    if mode == "off":
        return True
    if mode != "auto":
        return False  # explicit stream window
    try:
        return os.path.getsize(args.input) < _STREAM_AUTO_BYTES
    except OSError:
        return False  # unreadable input: solo run surfaces the error


def config_digest(args, command: str) -> str | None:
    """Digest of the CONSTRUCTED method (+ QC cosine) config — the
    portion of a job's argv that must be byte-identical for two jobs to
    share one device program.  Hashing the built config objects, not the
    argv, makes the key immune to flag spelling/ordering.  None when the
    config does not build (the solo run will report the usage error)."""
    from specpride_tpu import cli

    try:
        cfg = cli._method_config(args.method, args)
    except (ValueError, SystemExit):
        return None
    parts = [command, args.method, repr(cfg)]
    if getattr(args, "qc_report", None):
        parts.append(repr(cli._cosine_config(args)))
    else:
        parts.append("noqc")
    h = hashlib.blake2b("\x00".join(parts).encode(), digest_size=8)
    return h.hexdigest()


def batch_key(args, command: str) -> tuple | None:
    """The (method, config-digest, backend) compatibility key admission
    stamps on a batch-eligible job, or None when the job must run solo.

    Conservative by design: everything that carries job-scoped execution
    semantics beyond the per-cluster compute — multi-host/elastic modes,
    fault injection, quarantine parsing, streamed or mzML inputs, the
    whole-file ``--single`` collapse — is ineligible, and stays on the
    PR 7/10 solo path byte-for-byte."""
    from specpride_tpu.cli import _is_mzml

    if command not in ("consensus", "select"):
        return None
    if getattr(args, "method", None) not in BATCHABLE_METHODS:
        return None
    if getattr(args, "backend", "tpu") != "tpu":
        return None
    if (
        getattr(args, "elastic", None)
        or getattr(args, "coordinator", None)
        or getattr(args, "mesh", False)
        or getattr(args, "inject_faults", None)
        or getattr(args, "single", False)
        or getattr(args, "on_error", "abort") == "skip"
    ):
        return None
    if _is_mzml(args.input) or not _eager_input(args):
        return None
    digest = config_digest(args, command)
    if digest is None:
        return None
    return (command, args.method, digest)


def parse_batch_input(args, worker: int):
    """Parse one batch member's input through the serving ingest-cache
    residency (the job's own pipeline re-parse then hits the cache, so
    the batch pays each distinct input's parse once).  Returns the
    eagerly parsed cluster list; raises whatever the parser raises —
    the caller then lets the job run solo so the error surfaces through
    its own lane exactly as without batching."""
    from specpride_tpu import cli

    args._serve_worker = worker  # the daemon's _execute sets it too
    clusters = cli._load_clusters_served(args, RunStats(), None)
    if not isinstance(clusters, list):  # streamed despite eligibility
        raise TypeError("batch members must parse to an eager list")
    return clusters


@dataclasses.dataclass
class SharedResults:
    """One job's slice of a shared dispatch: representatives (and QC
    cosines when the batch carries QC jobs) keyed by cluster id."""

    reps_by_id: dict
    cos_by_id: dict | None


def compute_shared(backend, args0, entries) -> dict:
    """Run the batch's ONE shared prepare + dispatch group.

    ``entries`` is ``[(job, clusters), ...]``; jobs whose parsed input
    is the SAME object (the ingest cache returns one resident list per
    unchanged file) share a single compute, and distinct inputs merge
    into one ``run_shared`` pack.  Returns ``{job_id: SharedResults}``.
    Raises on any failure — the daemon then runs every member solo, so
    a poisoned batch degrades to exactly the unbatched behavior."""
    from specpride_tpu import cli
    from specpride_tpu.cache import result_cache as rc_mod

    method = args0.method
    config = cli._method_config(method, args0)
    cos_config = (
        cli._cosine_config(args0)
        if getattr(args0, "qc_report", None) else None
    )
    parts: list = []
    part_of: dict[int, int] = {}
    for _, clusters in entries:
        key = id(clusters)
        if key not in part_of:
            part_of[key] = len(parts)
            parts.append(clusters)
    # result cache: every member's clusters are checked BEFORE joining
    # the shared dispatch — only the misses ride run_shared, and the
    # freshly computed results populate the tiers for the next batch.
    # The consult happens once, on the leader's lane, against the REAL
    # resident backend (member pipelines see the BatchResultBackend
    # view and skip their own consult).
    rc = rc_mod.runtime_for(
        args0, getattr(entries[0][0], "command", "consensus"),
        backend=backend,
    ) if entries else None
    consulted = [
        rc.consult(p) if rc is not None else None for p in parts
    ]
    miss_parts: list = []
    miss_of: list = []  # per part: its index into miss_parts, or None
    for p, con in zip(parts, consulted):
        if con is None:
            miss = p
        else:
            hit = rc.hit_ids(con)
            miss = [c for c in p if c.cluster_id not in hit]
        if miss:
            miss_of.append(len(miss_parts))
            miss_parts.append(miss)
        else:
            miss_of.append(None)  # every cluster was a cache hit
    results = (
        backend.run_shared(
            method, miss_parts, config, cos_config=cos_config
        )
        if miss_parts else []
    )
    full: list = []
    for p, con, mi in zip(parts, consulted, miss_of):
        if con is None:
            full.append(results[mi])
            continue
        reps_m, cos_m = results[mi] if mi is not None else ([], None)
        if mi is not None:
            rc.populate(
                (con[c.cluster_id][2], reps_m[j], c,
                 None if cos_m is None else float(cos_m[j]))
                for j, c in enumerate(miss_parts[mi])
            )
        got = (
            {c.cluster_id: j for j, c in enumerate(miss_parts[mi])}
            if mi is not None else {}
        )
        reps, cos = [], []
        for c in p:
            hit = con.get(c.cluster_id)
            if hit is not None and hit[0] is not None:
                reps.append(hit[0])
                cos.append(hit[1])
            else:
                j = got[c.cluster_id]
                reps.append(reps_m[j])
                cos.append(
                    None if cos_m is None else float(cos_m[j])
                )
        full.append((reps, cos if cos_config is not None else None))
    out: dict = {}
    for job, clusters in entries:
        reps, cosines = full[part_of[id(clusters)]]
        out[job.job_id] = SharedResults(
            reps_by_id={
                c.cluster_id: r for c, r in zip(clusters, reps)
            },
            cos_by_id=(
                None if cosines is None else {
                    c.cluster_id: float(v)
                    for c, v in zip(clusters, cosines)
                }
            ),
        )
    return out


class BatchResultBackend:
    """Per-job read-only view over the worker's resident backend,
    serving the batch's precomputed per-cluster results.

    The job's ``cli._run_pipeline_command`` runs UNCHANGED — journal,
    QC finalize, ordered writes, checkpoint manifests, run_end
    accounting — against this wrapper: the ``run_*`` entry points
    return the shared dispatch's results for the requested clusters
    (bit-identical to a solo run by per-cluster independence), and
    everything else (attributes, state resets, any cluster the shared
    pass did not cover) forwards to the real resident backend, so a
    partial or failed share can only cost work, never correctness.
    ``supports_prepare`` is False: with results precomputed there is
    nothing for the pack lane to run ahead of, and output stays
    byte-identical because it is chunk-invariant by contract."""

    # class-level marker (found before __getattr__ forwards): the
    # result cache skips member-pipeline consults behind this view —
    # the leader consulted for the whole batch in compute_shared
    is_batch_view = True

    def __init__(self, inner, shared: SharedResults):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_reps", shared.reps_by_id)
        object.__setattr__(self, "_cos", shared.cos_by_id)

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_inner"), name)

    def __setattr__(self, name, value):
        # per-job state resets (stats, journal hook, pack accounting)
        # must land on the REAL backend the telemetry reads
        setattr(object.__getattribute__(self, "_inner"), name, value)

    # -- precomputed lookups --------------------------------------------

    def _lookup(self, clusters):
        reps = object.__getattribute__(self, "_reps")
        out = []
        for c in clusters:
            r = reps.get(c.cluster_id)
            if r is None:
                return None
            out.append(r)
        return out

    def _cos_lookup(self, clusters):
        cos = object.__getattribute__(self, "_cos")
        if cos is None:
            return None
        out = np.zeros(len(clusters), dtype=np.float64)
        for i, c in enumerate(clusters):
            v = cos.get(c.cluster_id)
            if v is None:
                return None
            out[i] = v
        return out

    # -- the execution surface cli._run_method / QC consume --------------

    def supports_prepare(self, method: str) -> bool:
        return False

    def prepare_chunk(self, *args, **kwargs):
        return None

    def run_prepared(self, prepared):
        return self._inner.run_prepared(prepared)

    def run_bin_mean(self, clusters, config):
        got = self._lookup(clusters)
        if got is not None:
            return got
        return self._inner.run_bin_mean(clusters, config)

    def run_bin_mean_with_cosines(self, clusters, config, cos_config):
        got = self._lookup(clusters)
        cos = self._cos_lookup(clusters)
        if got is not None and cos is not None:
            return got, cos
        return self._inner.run_bin_mean_with_cosines(
            clusters, config, cos_config
        )

    def run_gap_average(self, clusters, config):
        got = self._lookup(clusters)
        if got is not None:
            return got
        return self._inner.run_gap_average(clusters, config)

    def run_medoid(self, clusters, config):
        got = self._lookup(clusters)
        if got is not None:
            return got
        return self._inner.run_medoid(clusters, config)

    def run_best_spectrum(self, clusters, scores, config):
        return self._inner.run_best_spectrum(clusters, scores, config)

    def average_cosines(self, representatives, clusters, config):
        cos = self._cos_lookup(clusters)
        if cos is not None:
            return cos
        return self._inner.average_cosines(
            representatives, clusters, config
        )
