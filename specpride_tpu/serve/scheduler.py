"""Bounded admission queue with FIFO-fair scheduling across clients.

The daemon's execution lane is singular (jobs multiplex the device
through one three-lane executor at a time), so *admission* is where
fairness lives: each client (connection origin) gets its own FIFO, the
worker pops **round-robin across clients**, and the total queued count
is bounded — a burst from one chatty client can neither starve a
neighbour (round-robin) nor queue unboundedly (``offer`` refuses at
capacity and the daemon replies ``queue_full``, retriable).

Fairness semantics: within one client, jobs run in submission order
(FIFO); across clients, the pop order interleaves one job per client
per round, clients served in first-submission order.  A client with an
empty queue leaves the rotation and re-enters at the tail on its next
submission — exactly the behaviour of a round-robin packet scheduler.

Thread contract: ``offer`` runs on connection reader threads, ``pop``
on the single worker thread, ``drain`` on whichever thread initiates
shutdown; everything synchronizes on one condition variable.
"""

from __future__ import annotations

import collections
import threading


class AdmissionQueue:
    def __init__(self, capacity: int):
        self.capacity = max(int(capacity), 1)
        self._cond = threading.Condition()
        # client id -> FIFO of jobs; dict order IS the round-robin
        # rotation (clients rotate by delete + re-insert on pop)
        self._queues: "collections.OrderedDict[object, collections.deque]" \
            = collections.OrderedDict()
        self._total = 0
        self._closed = False

    def __len__(self) -> int:
        with self._cond:
            return self._total

    def depths(self) -> dict:
        """Queued-job count per client — the live exporter's scrape-time
        view of queue pressure (who is waiting, and how much)."""
        with self._cond:
            return {client: len(q) for client, q in self._queues.items()}

    def offer(self, client, job) -> bool:
        """Enqueue ``job`` for ``client``; ``False`` when the queue is at
        capacity or closed (the caller rejects with a retriable
        status)."""
        with self._cond:
            if self._closed or self._total >= self.capacity:
                return False
            self._queues.setdefault(client, collections.deque()).append(job)
            self._total += 1
            self._cond.notify_all()
            return True

    def pop(self, timeout: float | None = None):
        """The next job in round-robin-fair order; blocks while empty.
        Returns ``None`` once the queue is closed and empty (worker
        shutdown), or on ``timeout``."""
        with self._cond:
            while self._total == 0:
                if self._closed:
                    return None
                if not self._cond.wait(timeout=timeout):
                    return None
            client, q = next(iter(self._queues.items()))
            job = q.popleft()
            self._total -= 1
            # rotate: the served client moves to the tail if it still
            # has queued jobs, else leaves the rotation entirely
            del self._queues[client]
            if q:
                self._queues[client] = q
            return job

    def close(self) -> None:
        """Stop admitting; ``pop`` drains what is queued then returns
        ``None``."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self) -> list:
        """Close AND empty the queue, returning every still-queued job
        (submission order per client, round-robin across clients — the
        order they would have run) so the daemon can reject each with a
        retriable status."""
        with self._cond:
            self._closed = True
            out = []
            while self._total:
                client, q = next(iter(self._queues.items()))
                out.append(q.popleft())
                self._total -= 1
                del self._queues[client]
                if q:
                    self._queues[client] = q
            self._cond.notify_all()
            return out
