"""Bounded admission queue with weighted-fair, quota-aware scheduling.

PR 7's daemon had ONE execution lane, so plain FIFO-fair round-robin
across clients was enough.  With the worker pool the queue feeds
several concurrent lanes, and admission grows three policies on top of
the capacity bound:

* **Weighted fairness** — each client carries a ``weight`` (``--quota
  client=weight[:max_inflight]``, default 1).  Scheduling is
  deficit-style (stride scheduling): every client keeps a virtual-time
  counter that advances by ``1/weight`` per served job, and ``pop``
  always serves the eligible client with the LEAST virtual time — i.e.
  the one with the largest accumulated service deficit relative to its
  weight.  Weight 3 gets three jobs per weight-1 job under contention;
  with no quotas every weight is 1 and the order degenerates to exactly
  the old FIFO-fair round-robin (one job per client per round, clients
  in first-submission order, FIFO within a client).  A client that goes
  idle re-enters at the current virtual-time frontier, so idling never
  banks credit and a burst can never starve incumbents.

* **Inflight quotas** — ``max_inflight`` caps a client's CONCURRENT
  execution lanes.  ``pop`` never selects a client at its cap (its jobs
  wait, other clients' jobs flow past), and ``offer`` refuses outright
  — :class:`QuotaExceeded`, which the daemon rejects retriable with the
  quota named — once the client already has ``max_inflight`` jobs in
  the system (queued + executing), so a capped tenant gets backpressure
  instead of unbounded queueing.

* **Output-path conflict guard** — two jobs writing the same output
  must not run concurrently (interleaved appends would tear the file;
  serialized, the second job simply rewrites the same bytes and served
  output stays byte-identical to one-shot CLI runs).  ``conflict_key``
  maps a job to its claimed path tokens; ``pop`` skips any client whose
  HEAD job touches a path some in-flight job holds (skipping only the
  head preserves per-client FIFO), and ``release`` frees the paths.

Thread contract: ``offer`` runs on connection reader threads, ``pop``
and ``release`` on the worker-pool threads, ``drain`` on whichever
thread initiates shutdown; everything synchronizes on one condition
variable.  ``release(job)`` MUST be called for every job ``pop``
returned once its lane is done with it — it frees the client's inflight
slot and the job's conflict paths and wakes blocked poppers.
"""

from __future__ import annotations

import collections
import itertools
import threading


class Quota:
    """One client's scheduling quota: relative ``weight`` (> 0) and an
    optional ``max_inflight`` concurrent-lane cap (>= 1, None = no cap)."""

    __slots__ = ("weight", "max_inflight")

    def __init__(self, weight: float = 1.0, max_inflight: int | None = None):
        self.weight = float(weight)
        self.max_inflight = max_inflight

    def __repr__(self) -> str:  # readable in rejection messages/tests
        cap = "" if self.max_inflight is None else f":{self.max_inflight}"
        return f"{self.weight:g}{cap}"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Quota)
            and self.weight == other.weight
            and self.max_inflight == other.max_inflight
        )


class QuotaExceeded(Exception):
    """A client at its ``max_inflight`` quota submitted another job.
    Retriable by contract: the tenant resubmits once a lane frees."""

    def __init__(self, client, max_inflight: int):
        self.client = client
        self.max_inflight = max_inflight
        super().__init__(
            f"quota client={client} max_inflight={max_inflight}: already "
            f"{max_inflight} job(s) queued or executing (retry after one "
            "completes)"
        )


def parse_quota_spec(spec: str | None) -> dict[str, Quota]:
    """``--quota client=weight[:max_inflight],...`` ->
    ``{client: Quota}``.  ``*`` is the default quota for clients not
    named explicitly.  Parsed at boot (the CLI turns ``ValueError`` into
    a usage error, never mid-serve) — same convention as ``--slo``."""
    out: dict[str, Quota] = {}
    if not spec:
        return out
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        client, sep, value = item.partition("=")
        client = client.strip()
        if not sep or not client:
            raise ValueError(
                f"--quota entry {item!r} is not client=weight[:max_inflight]"
            )
        weight_s, sep2, cap_s = value.partition(":")
        try:
            weight = float(weight_s)
        except ValueError:
            raise ValueError(
                f"--quota {client}: weight {weight_s!r} is not a number"
            ) from None
        if not weight > 0:
            raise ValueError(
                f"--quota {client}: weight must be > 0 (got {weight})"
            )
        cap: int | None = None
        if sep2:
            try:
                cap = int(cap_s)
            except ValueError:
                raise ValueError(
                    f"--quota {client}: max_inflight {cap_s!r} is not an "
                    "integer"
                ) from None
            if cap < 1:
                raise ValueError(
                    f"--quota {client}: max_inflight must be >= 1 "
                    f"(got {cap})"
                )
        out[client] = Quota(weight, cap)
    return out


_NO_QUOTA = Quota()


class _ClientState:
    """Persistent per-client scheduling state (survives empty queues so
    the deficit counter and inflight accounting stay correct)."""

    __slots__ = ("queue", "quota", "inflight", "vtime", "entry")

    def __init__(self, quota: Quota):
        self.queue: collections.deque = collections.deque()
        self.quota = quota
        self.inflight = 0  # jobs popped but not yet released
        self.vtime = 0.0  # deficit counter: advances 1/weight per job
        self.entry = 0  # rotation tie-break: when the client re-entered


class AdmissionQueue:
    def __init__(
        self,
        capacity: int,
        quotas: dict[str, Quota] | None = None,
        conflict_key=None,
    ):
        self.capacity = max(int(capacity), 1)
        self.quotas = dict(quotas or {})
        # job -> iterable of hashable path tokens it claims for the
        # duration of its execution (None = no conflict tracking)
        self._conflict_key = conflict_key
        self._cond = threading.Condition()
        self._states: dict[object, _ClientState] = {}
        self._total = 0
        self._closed = False
        self._vclock = 0.0  # virtual-time frontier (max served vtime)
        self._seq = itertools.count()
        self._held: set = set()  # path tokens claimed by in-flight jobs
        # id(job) -> (client, claimed tokens) for release()
        self._popped: dict[int, tuple[object, tuple]] = {}

    def _state(self, client) -> _ClientState:
        st = self._states.get(client)
        if st is None:
            quota = self.quotas.get(client) or self.quotas.get("*") \
                or _NO_QUOTA
            st = self._states[client] = _ClientState(quota)
        return st

    def set_quotas(self, quotas: dict[str, Quota] | None) -> None:
        """Replace the quota table LIVE, under the queue's one condition
        variable — the locked live-config path the autotune plane (and
        operators via future reload verbs) actuates through.  Every
        existing client state is re-resolved against the new table in
        the same critical section, so no pop/offer can ever observe a
        half-applied table (old map, new per-client quota, or vice
        versa); inflight counts and deficit clocks carry over untouched.
        Waiters are woken: a raised cap can make a parked client
        eligible right now."""
        with self._cond:
            self.quotas = dict(quotas or {})
            for client, st in self._states.items():
                st.quota = self.quotas.get(client) \
                    or self.quotas.get("*") or _NO_QUOTA
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return self._total

    def depths(self) -> dict:
        """Queued-job count per client — the live exporter's scrape-time
        view of queue pressure (who is waiting, and how much)."""
        with self._cond:
            return {
                client: len(st.queue)
                for client, st in self._states.items()
                if st.queue
            }

    def inflight_counts(self) -> dict:
        """Executing-job count per client (popped, not yet released)."""
        with self._cond:
            return {
                client: st.inflight
                for client, st in self._states.items()
                if st.inflight
            }

    def offer(self, client, job) -> bool:
        """Enqueue ``job`` for ``client``; ``False`` when the queue is at
        capacity or closed (the caller rejects with a retriable
        status).  Raises :class:`QuotaExceeded` when the client's
        ``max_inflight`` quota already covers its queued + executing
        jobs — also retriable, but with the quota named."""
        with self._cond:
            if self._closed or self._total >= self.capacity:
                return False
            st = self._state(client)
            cap = st.quota.max_inflight
            if cap is not None and st.inflight + len(st.queue) >= cap:
                raise QuotaExceeded(client, cap)
            if not st.queue:
                # (re-)entering the rotation: start at the virtual-time
                # frontier (idling banks no credit), behind incumbents
                # already at the frontier (entry order breaks ties)
                st.vtime = max(st.vtime, self._vclock)
                st.entry = next(self._seq)
            st.queue.append(job)
            self._total += 1
            self._cond.notify_all()
            return True

    # -- selection ------------------------------------------------------

    def _eligible(self, st: _ClientState) -> bool:
        if not st.queue:
            return False
        cap = st.quota.max_inflight
        if cap is not None and st.inflight >= cap:
            return False
        if self._conflict_key is not None and self._held:
            tokens = self._claim_tokens(st.queue[0])
            # only the HEAD job can run (per-client FIFO); a held path on
            # it parks the whole client until the holder releases
            if any(t in self._held for t in tokens):
                return False
        return True

    def _claim_tokens(self, job) -> tuple:
        if self._conflict_key is None:
            return ()
        return tuple(self._conflict_key(job))

    def _select_locked(self, ignore_limits: bool = False):
        """The next (client, state) in weighted-fair order, or None."""
        best = None
        for client, st in self._states.items():
            if ignore_limits:
                if not st.queue:
                    continue
            elif not self._eligible(st):
                continue
            rank = (st.vtime, st.entry)
            if best is None or rank < best[0]:
                best = (rank, client, st)
        if best is None:
            return None
        return best[1], best[2]

    def _pop_locked(self, client, st):
        """Dequeue ``client``'s head job with the full pop bookkeeping
        (deficit advance, inflight count, conflict-path claim).  Caller
        holds the lock and has already checked eligibility."""
        job = st.queue.popleft()
        self._total -= 1
        st.inflight += 1
        # deficit bookkeeping: serving one job costs 1/weight of
        # virtual time; the frontier follows
        st.vtime += 1.0 / st.quota.weight
        self._vclock = max(self._vclock, st.vtime)
        tokens = self._claim_tokens(job)
        self._held.update(tokens)
        self._popped[id(job)] = (client, tokens)
        return job

    def pop(self, timeout: float | None = None):
        """The next job in weighted-fair order; blocks while nothing is
        runnable (empty, every queued client at its inflight cap, or
        every head job path-conflicted with an in-flight job).  Returns
        ``None`` once the queue is closed and empty (worker shutdown),
        or on ``timeout``.  The caller MUST :meth:`release` the job when
        its lane is done with it."""
        with self._cond:
            while True:
                picked = self._select_locked() if self._total else None
                if picked is not None:
                    client, st = picked
                    return self._pop_locked(client, st)
                if self._closed and self._total == 0:
                    return None
                if not self._cond.wait(timeout=timeout):
                    return None

    def pop_compatible(self, match):
        """Non-blocking pop for the micro-batch collector: the next job
        in weighted-fair order whose client is ELIGIBLE (inflight cap,
        output-conflict guard — exactly :meth:`pop`'s criteria, so
        batching changes no scheduling policy) and whose HEAD job
        satisfies ``match(job)``.  Only heads are considered — per-
        client FIFO is preserved.  Returns None when no such job is
        queued right now; the caller MUST :meth:`release` any job
        returned, like a normal pop."""
        with self._cond:
            if self._total == 0:
                return None
            best = None
            for client, st in self._states.items():
                if not self._eligible(st) or not match(st.queue[0]):
                    continue
                rank = (st.vtime, st.entry)
                if best is None or rank < best[0]:
                    best = (rank, client, st)
            if best is None:
                return None
            return self._pop_locked(best[1], best[2])

    def release(self, job) -> None:
        """Mark a popped job's lane free: drop its client's inflight
        count and its claimed output paths, and wake blocked poppers.
        Idempotent for unknown jobs (drain-rejected jobs were never
        popped)."""
        with self._cond:
            client, tokens = self._popped.pop(id(job), (None, ()))
            if client is None:
                return
            self._held.difference_update(tokens)
            st = self._states.get(client)
            if st is not None:
                if st.inflight > 0:
                    st.inflight -= 1
                if not st.queue and st.inflight == 0:
                    # prune idle state: the vtime frontier (vclock)
                    # already equals a just-served client's vtime, so
                    # re-entry reconstructs the same schedule — and a
                    # long-lived daemon must not grow per-client state
                    # (and per-pop scan cost) with every tenant process
                    # it has ever served
                    del self._states[client]
            self._cond.notify_all()

    def close(self) -> None:
        """Stop admitting; ``pop`` drains what is queued then returns
        ``None``."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self) -> list:
        """Close AND empty the queue, returning every still-queued job
        in the weighted-fair order they would have run (inflight caps
        and path conflicts ignored — these jobs are being rejected, not
        run) so the daemon can reject each with a retriable status."""
        with self._cond:
            self._closed = True
            out = []
            while self._total:
                client, st = self._select_locked(ignore_limits=True)
                out.append(st.queue.popleft())
                self._total -= 1
                st.vtime += 1.0 / st.quota.weight
                self._vclock = max(self._vclock, st.vtime)
            self._cond.notify_all()
            return out
