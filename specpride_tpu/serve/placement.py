"""Device-aware worker placement for the serving daemon's lane pool.

``specpride serve --workers N`` runs N concurrent execution lanes, each
owning its own resident ``TpuBackend``.  This module decides where each
lane's dispatches land:

* **Accelerator hosts** (any non-CPU jax device visible): workers are
  pinned round-robin across the local devices via
  ``jax.default_device`` — N workers on N chips keep every chip busy
  with independent jobs, the scale-out the pool exists for.  Pinning
  commits each lane's jit executions to its device, so two lanes never
  contend for one chip's queue while another sits idle.

* **CPU-only hosts** (including the test suite's virtual 8-device CPU
  split): workers share the default device/platform unpinned.  XLA's
  CPU "devices" are one physical socket — pinning buys no parallelism
  (the thread pool is shared) but would fork the in-process jit caches
  AND the persistent compile cache per device ordinal (the cache key
  includes the device assignment; measured: a kernel cached for cpu:0
  recompiles for cpu:1), costing every lane a cold first job for
  nothing.  Lane concurrency still wins on CPU because a served job is
  mostly host-side work (parse, pack, QC finalize, write) that the
  lanes overlap.

Either way each worker keeps INDEPENDENT per-lane state — its own
backend, metrics registry, run stats, seen-shape set — so per-job
snapshot-and-diff attribution stays correct with jobs in flight
concurrently (see ``docs/serving.md``).
"""

from __future__ import annotations

import contextlib
import dataclasses

DEFAULT_MAX_WORKERS = 4


@dataclasses.dataclass(frozen=True)
class WorkerSlot:
    """One execution lane's placement: ``device`` is a jax Device to pin
    dispatches to, or None to share the process default."""

    worker: int
    device: object | None
    device_index: int | None
    platform: str

    def describe(self) -> str:
        if self.device is None:
            return f"{self.platform}:shared"
        return f"{self.platform}:{self.device_index}"


def local_devices() -> list:
    """The host's visible jax devices ([] when jax cannot initialize —
    placement then degrades to one unpinned worker)."""
    try:
        import jax

        return list(jax.local_devices())
    except Exception:  # noqa: BLE001 - bring-up failure: decide nothing
        return []


def default_workers() -> int:
    """``--workers`` default: ``min(#local jax devices, 4)``, floored at
    1 — one lane per accelerator up to a host-friendly cap (more lanes
    than devices just contend; 4 bounds the thread fan-out on big CPU
    hosts where "devices" are virtual)."""
    return max(1, min(DEFAULT_MAX_WORKERS, len(local_devices()) or 1))


def plan_placement(
    n_workers: int, *, pin_cpu: bool = False
) -> list[WorkerSlot]:
    """Placement for ``n_workers`` lanes: round-robin over the local
    devices on accelerator hosts, shared/unpinned on CPU-only hosts
    (``pin_cpu=True`` forces CPU pinning — tests exercising the pinning
    path use it; production never should, see the module docstring)."""
    n_workers = max(1, int(n_workers))
    devs = local_devices()
    if not devs:
        return [
            WorkerSlot(w, None, None, "unknown") for w in range(n_workers)
        ]
    cpu_only = all(
        getattr(d, "platform", "cpu") == "cpu" for d in devs
    )
    if cpu_only and not pin_cpu:
        plat = getattr(devs[0], "platform", "cpu")
        return [
            WorkerSlot(w, None, None, plat) for w in range(n_workers)
        ]
    return [
        WorkerSlot(
            w,
            devs[w % len(devs)],
            int(getattr(devs[w % len(devs)], "id", w % len(devs))),
            getattr(devs[w % len(devs)], "platform", "unknown"),
        )
        for w in range(n_workers)
    ]


def device_scope(device):
    """Context manager pinning the current thread's jax dispatches to
    ``device`` (``jax.default_device`` is thread-scoped, so concurrent
    lanes pin independently); a no-op for unpinned slots."""
    if device is None:
        return contextlib.nullcontext()
    import jax

    return jax.default_device(device)
