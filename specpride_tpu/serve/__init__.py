"""Consensus-as-a-service: the ``specpride serve`` daemon (ROADMAP
item 1).

Every one-shot CLI run pays parse + trace + XLA compile + lane spin-up
from cold.  This package turns the pipeline into a long-lived process
that pays those costs ONCE — at boot it resolves the persistent compile
cache and AOT-warms the shape manifest (reusing ``warmstart``), then
holds the backend, routing table, bucket-plan cache and jit caches
resident — and serves consensus/select jobs over a local unix socket at
warm-request latency:

* ``protocol`` — the JSON-lines request/response wire format and the
  job-validation rules (which flags the daemon owns vs the job);
* ``scheduler`` — the bounded admission queue with weighted-fair
  deficit scheduling, per-tenant ``--quota`` inflight caps, and the
  output-path conflict guard (defaults degenerate to the original
  FIFO-fair round-robin);
* ``placement`` — device-aware lane placement for the worker pool
  (``--workers N``: pinned per local device on accelerator hosts,
  shared platform on CPU);
* ``ingest_cache`` — parsed-input residency: repeat jobs over an
  unchanged input skip the parse (keyed by path + size + mtime);
* ``daemon`` — boot / accept / execute / drain lifecycle (SIGTERM
  drains: every lane's in-flight job commits through its ordered write
  lane, queued jobs are rejected with a retriable status);
* ``client`` — the thin ``specpride submit`` client.

Jobs run through the exact CLI execution body
(``cli._run_pipeline_command``) with a worker lane's resident backend,
so served output is byte-identical to the one-shot CLI's — the parity
the test suite and CI enforce, including concurrent and same-output
submissions.
"""

from specpride_tpu.serve.protocol import (  # noqa: F401
    DAEMON_ONLY_FLAGS,
    PROTOCOL_VERSION,
    SERVABLE_COMMANDS,
    default_socket_path,
)
from specpride_tpu.serve.scheduler import AdmissionQueue  # noqa: F401
