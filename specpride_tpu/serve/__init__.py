"""Consensus-as-a-service: the ``specpride serve`` daemon (ROADMAP
item 1).

Every one-shot CLI run pays parse + trace + XLA compile + lane spin-up
from cold.  This package turns the pipeline into a long-lived process
that pays those costs ONCE — at boot it resolves the persistent compile
cache and AOT-warms the shape manifest (reusing ``warmstart``), then
holds the backend, routing table, bucket-plan cache and jit caches
resident — and serves consensus/select jobs over a local unix socket at
warm-request latency:

* ``protocol`` — the JSON-lines request/response wire format and the
  job-validation rules (which flags the daemon owns vs the job);
* ``scheduler`` — the bounded admission queue with FIFO-fair
  round-robin scheduling across concurrent clients;
* ``daemon`` — boot / accept / execute / drain lifecycle (SIGTERM
  drains: in-flight jobs commit through the ordered write lane, queued
  jobs are rejected with a retriable status);
* ``client`` — the thin ``specpride submit`` client.

Jobs run through the exact CLI execution body
(``cli._run_pipeline_command``) with the daemon's resident backend, so
served output is byte-identical to the one-shot CLI's — the parity the
test suite and CI enforce.
"""

from specpride_tpu.serve.protocol import (  # noqa: F401
    DAEMON_ONLY_FLAGS,
    PROTOCOL_VERSION,
    SERVABLE_COMMANDS,
    default_socket_path,
)
from specpride_tpu.serve.scheduler import AdmissionQueue  # noqa: F401
