"""Thin client for the serve daemon: ``specpride submit`` and the
helpers tests/bench drive directly.

``submit`` is a generator so callers can stream the admission line
("accepted", with the queue depth) before the job finishes — an
operator watching a loaded daemon sees immediately whether the job
queued or was rejected, then waits only for the terminal line.

Every submit mints (or adopts) a **trace context** and sends it on the
wire, so the daemon's spans parent under this client's submit span;
with ``journal=`` the client writes its OWN journal shard — a clock
anchor plus ``submit``/``submit:admit``/``submit:wait`` spans — which
``specpride trace --job`` merges with the daemon and job journals into
one causal timeline (the client track).
"""

from __future__ import annotations

import json
import socket
import time

from specpride_tpu.observability.journal import (
    emit_clock_anchor,
    open_journal,
)
from specpride_tpu.observability.tracing import TraceContext, new_span_id
from specpride_tpu.serve import protocol


class ServeError(RuntimeError):
    """The daemon broke the protocol (connection torn mid-job, non-JSON
    line).  Transient from the client's point of view: the job may well
    have completed server-side — resubmitting is safe only because
    served jobs are idempotent (same argv -> same bytes)."""


def _connect(socket_path: str | None, timeout: float | None):
    path = socket_path or protocol.default_socket_path()
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect(path)
    return sock


def request(
    socket_path: str | None, payload: dict, timeout: float | None = 30.0
) -> dict:
    """One-shot ops (``ping`` / ``status``): send, read one reply."""
    sock = _connect(socket_path, timeout)
    try:
        fh = sock.makefile("rw", encoding="utf-8", newline="\n")
        protocol.write_msg(fh, **payload)
        msg = protocol.read_msg(fh)
        if msg is None:
            raise ServeError("daemon closed the connection without a reply")
        return msg
    finally:
        sock.close()


def _default_client_id() -> str:
    """One submitting PROCESS = one scheduling client: the daemon's
    FIFO-fair round-robin keys on this, so a process bursting jobs
    interleaves with its neighbours instead of monopolizing the queue
    (each job is its own connection, so without an explicit identity
    every job would look like a distinct one-job client and fairness
    would degenerate to global FIFO)."""
    import os

    return f"{os.getuid()}.{os.getpid()}"


def submit(
    socket_path: str | None, argv: list[str], timeout: float | None = 30.0,
    client: str | None = None, journal: str | None = None,
    trace: TraceContext | None = None,
):
    """Submit one job; yield every server message (admission line first,
    terminal line last).  ``timeout`` bounds connect + admission only —
    once the job is accepted the wait is unbounded (it may legitimately
    sit behind other clients' jobs).  ``client`` overrides the
    per-process scheduling identity (load generators simulating
    distinct tenants).

    ``trace`` overrides the minted trace context (resubmit loops keep
    ONE trace across attempts, each attempt a child submit span);
    ``journal`` writes the client-side journal shard (clock anchor +
    submit spans) for the trace merger."""
    ctx = trace if trace is not None else TraceContext.mint()
    # self-minted context: the submit span IS the trace root; a caller-
    # provided one makes this attempt a child (resubmit loops emit one
    # sibling submit span per attempt under the shared request id)
    submit_span = ctx.span_id if trace is None else new_span_id()
    submit_parent = None if trace is None else ctx.span_id
    # the daemon's serve:queue/serve:job spans parent under the WAIT
    # span (minted up front, emitted at close): the server does its
    # work while the client waits — that is the causal chain a
    # critical-path walk must descend through
    wait_span = new_span_id()
    jr = open_journal(journal)
    jr.bind_trace(ctx.trace_id)
    if jr.enabled:
        emit_clock_anchor(jr)
    t_start = time.perf_counter()
    t_admit = None

    def _span(name, t0, t1, span_id=None, parent=None, **labels):
        if not jr.enabled:
            return
        jr.emit(
            "span", name=name, mono=t1, dur_s=round(t1 - t0, 6),
            depth=0 if parent is None else 1, tid=0,
            span_id=span_id or new_span_id(),
            **({"parent_span_id": parent} if parent else {}),
            **({"labels": labels} if labels else {}),
        )

    sock = None
    last_status = "error"
    job_id = None
    try:
        sock = _connect(socket_path, timeout)
        fh = sock.makefile("rw", encoding="utf-8", newline="\n")
        protocol.write_msg(
            fh, op="submit", argv=list(argv),
            client=client or _default_client_id(),
            trace={"trace_id": ctx.trace_id,
                   "parent_span_id": wait_span},
        )
        while True:
            try:
                msg = protocol.read_msg(fh)
            except (ValueError, json.JSONDecodeError) as e:
                raise ServeError(f"bad protocol line from daemon: {e}")
            if msg is None:
                raise ServeError("connection closed before a terminal "
                                 "response (daemon killed mid-job?)")
            yield msg
            status = msg.get("status")
            job_id = msg.get("job_id", job_id)
            if status == "accepted":
                t_admit = time.perf_counter()
                _span("submit:admit", t_start, t_admit,
                      parent=submit_span)
                sock.settimeout(None)  # the job may queue; wait it out
            if status in ("done", "error", "rejected"):
                last_status = status
                return
    finally:
        if sock is not None:
            sock.close()
        t_end = time.perf_counter()
        if t_admit is not None:
            _span("submit:wait", t_admit, t_end, span_id=wait_span,
                  parent=submit_span)
        _span(
            "submit", t_start, t_end, span_id=submit_span,
            parent=submit_parent, status=last_status,
            **({"job_id": job_id} if job_id is not None else {}),
        )
        jr.close()


def submit_wait(
    socket_path: str | None, argv: list[str], timeout: float | None = 30.0,
    client: str | None = None, journal: str | None = None,
    trace: TraceContext | None = None,
) -> dict:
    """Submit and return only the terminal message."""
    last: dict = {}
    for last in submit(socket_path, argv, timeout=timeout, client=client,
                       journal=journal, trace=trace):
        pass
    return last


def exit_code(msg: dict | None) -> int:
    """Map a terminal message to a shell exit code: done -> the job's
    rc; retriable rejection/error -> 75 (``EX_TEMPFAIL``, resubmit
    later); permanent rejection -> 2 (usage); permanent error -> 1."""
    if not msg:
        return 1
    status = msg.get("status")
    if status == "done":
        return int(msg.get("rc", 0))
    if msg.get("retriable"):
        return protocol.EX_TEMPFAIL
    return 2 if status == "rejected" else 1


def profile(
    socket_path: str | None, seconds: float = 3.0,
    trace_dir: str | None = None, chrome_trace: str | None = None,
    timeout: float | None = 30.0,
) -> dict:
    """``specpride profile``: one bounded ``jax.profiler`` capture on a
    live daemon.  Blocks for roughly ``seconds`` (the daemon replies
    when the window closes); ``timeout`` covers connect + the margin
    past the window."""
    payload: dict = {"op": "profile", "seconds": float(seconds)}
    if trace_dir is not None:
        payload["trace_dir"] = trace_dir
    if chrome_trace is not None:
        payload["chrome_trace"] = chrome_trace
    return request(
        socket_path, payload,
        timeout=None if timeout is None else timeout + float(seconds),
    )


def wait_for_socket(
    socket_path: str | None, timeout: float = 60.0, interval: float = 0.1
) -> bool:
    """Poll until the daemon answers a ``ping`` (boot can take a while:
    jax import + AOT warmup).  False on timeout."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            if request(socket_path, {"op": "ping"}, timeout=2.0).get("ok"):
                return True
        except (OSError, ServeError):
            pass
        time.sleep(interval)
    return False
