"""Thin client for the serve daemon: ``specpride submit`` and the
helpers tests/bench drive directly.

``submit`` is a generator so callers can stream the admission line
("accepted", with the queue depth) before the job finishes — an
operator watching a loaded daemon sees immediately whether the job
queued or was rejected, then waits only for the terminal line.
"""

from __future__ import annotations

import json
import socket
import time

from specpride_tpu.serve import protocol


class ServeError(RuntimeError):
    """The daemon broke the protocol (connection torn mid-job, non-JSON
    line).  Transient from the client's point of view: the job may well
    have completed server-side — resubmitting is safe only because
    served jobs are idempotent (same argv -> same bytes)."""


def _connect(socket_path: str | None, timeout: float | None):
    path = socket_path or protocol.default_socket_path()
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect(path)
    return sock


def request(
    socket_path: str | None, payload: dict, timeout: float | None = 30.0
) -> dict:
    """One-shot ops (``ping`` / ``status``): send, read one reply."""
    sock = _connect(socket_path, timeout)
    try:
        fh = sock.makefile("rw", encoding="utf-8", newline="\n")
        protocol.write_msg(fh, **payload)
        msg = protocol.read_msg(fh)
        if msg is None:
            raise ServeError("daemon closed the connection without a reply")
        return msg
    finally:
        sock.close()


def _default_client_id() -> str:
    """One submitting PROCESS = one scheduling client: the daemon's
    FIFO-fair round-robin keys on this, so a process bursting jobs
    interleaves with its neighbours instead of monopolizing the queue
    (each job is its own connection, so without an explicit identity
    every job would look like a distinct one-job client and fairness
    would degenerate to global FIFO)."""
    import os

    return f"{os.getuid()}.{os.getpid()}"


def submit(
    socket_path: str | None, argv: list[str], timeout: float | None = 30.0,
    client: str | None = None,
):
    """Submit one job; yield every server message (admission line first,
    terminal line last).  ``timeout`` bounds connect + admission only —
    once the job is accepted the wait is unbounded (it may legitimately
    sit behind other clients' jobs).  ``client`` overrides the
    per-process scheduling identity (load generators simulating
    distinct tenants)."""
    sock = _connect(socket_path, timeout)
    try:
        fh = sock.makefile("rw", encoding="utf-8", newline="\n")
        protocol.write_msg(
            fh, op="submit", argv=list(argv),
            client=client or _default_client_id(),
        )
        while True:
            try:
                msg = protocol.read_msg(fh)
            except (ValueError, json.JSONDecodeError) as e:
                raise ServeError(f"bad protocol line from daemon: {e}")
            if msg is None:
                raise ServeError("connection closed before a terminal "
                                 "response (daemon killed mid-job?)")
            yield msg
            status = msg.get("status")
            if status == "accepted":
                sock.settimeout(None)  # the job may queue; wait it out
            if status in ("done", "error", "rejected"):
                return
    finally:
        sock.close()


def submit_wait(
    socket_path: str | None, argv: list[str], timeout: float | None = 30.0,
    client: str | None = None,
) -> dict:
    """Submit and return only the terminal message."""
    last: dict = {}
    for last in submit(socket_path, argv, timeout=timeout, client=client):
        pass
    return last


def exit_code(msg: dict | None) -> int:
    """Map a terminal message to a shell exit code: done -> the job's
    rc; retriable rejection/error -> 75 (``EX_TEMPFAIL``, resubmit
    later); permanent rejection -> 2 (usage); permanent error -> 1."""
    if not msg:
        return 1
    status = msg.get("status")
    if status == "done":
        return int(msg.get("rc", 0))
    if msg.get("retriable"):
        return protocol.EX_TEMPFAIL
    return 2 if status == "rejected" else 1


def profile(
    socket_path: str | None, seconds: float = 3.0,
    trace_dir: str | None = None, chrome_trace: str | None = None,
    timeout: float | None = 30.0,
) -> dict:
    """``specpride profile``: one bounded ``jax.profiler`` capture on a
    live daemon.  Blocks for roughly ``seconds`` (the daemon replies
    when the window closes); ``timeout`` covers connect + the margin
    past the window."""
    payload: dict = {"op": "profile", "seconds": float(seconds)}
    if trace_dir is not None:
        payload["trace_dir"] = trace_dir
    if chrome_trace is not None:
        payload["chrome_trace"] = chrome_trace
    return request(
        socket_path, payload,
        timeout=None if timeout is None else timeout + float(seconds),
    )


def wait_for_socket(
    socket_path: str | None, timeout: float = 60.0, interval: float = 0.1
) -> bool:
    """Poll until the daemon answers a ``ping`` (boot can take a while:
    jax import + AOT warmup).  False on timeout."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            if request(socket_path, {"op": "ping"}, timeout=2.0).get("ok"):
                return True
        except (OSError, ServeError):
            pass
        time.sleep(interval)
    return False
