"""Parsed-input residency for the serving daemon's worker pool.

The daemon's scenario is repeated small/medium jobs over the same
clustered MGF inputs — and profiling warm served jobs shows the parse
phase dominating them once kernels are warm (on hosts without the C++
fast parser it is a GIL-bound Python loop, which also caps what
concurrent lanes can overlap).  The compile cache, plan cache and jit
caches already stay resident across jobs; this module extends the same
residency to the PARSED INPUT: a bounded process-wide LRU of eagerly
parsed cluster lists keyed by ``(abspath, size, mtime_ns)``, so a
repeat job skips the parse entirely and a modified input misses by
construction.

Safety contract:

* Cached cluster lists are shared READ-ONLY across jobs (and across
  concurrent lanes).  Every consumer treats clusters/spectra as
  immutable — the bench harness has always re-run the same in-memory
  cluster lists through every backend with byte-identical outputs, and
  the served byte-parity tests cover the cached path the same way.
* Only EAGER parses cache: streamed inputs (``StreamedClusters``) are
  a bounded-memory view, not a materialized list, and quarantine runs
  (``--on-error skip``) must re-see malformed blocks — both bypass.
* Keyed on size + mtime_ns: rewriting the input invalidates; same
  bytes re-written in place (same mtime resolution caveat as make).
* Content-digest fallback: a stat-key miss against a non-empty cache
  hashes the file's bytes (``cache.digest.file_digest`` — the same
  helper the result cache keys clusters with) and matches them against
  resident entries, so a copied/touched/re-uploaded identical input
  still skips its parse.  The entry is re-keyed under the new stat
  identity, making the next lookup a plain stat hit.

Hit/miss counters ride each job's ``run_end.counters``
(``ingest_cache_hits`` / ``ingest_cache_misses``, with content-digest
matches attributed separately as ``ingest_cache_content_hits``) and
the daemon's ``/metrics`` exposition
(``specpride_serve_ingest_cache_*_total``).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

# entries, not bytes: serving workloads are "repeated small/medium
# jobs" by design — a handful of distinct inputs covers them, and an
# operator serving many huge distinct files should raise/disable this
DEFAULT_MAX_ENTRIES = 4

_lock = threading.Lock()
_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
_counts = {"hits": 0, "misses": 0, "content_hits": 0}
# content-digest fallback index: file sha256 -> the stat key holding
# those bytes' parse (and the reverse map, so LRU eviction cleans both)
_by_content: dict = {}
_content_of: dict = {}


def _max_entries() -> int:
    try:
        return int(os.environ.get("SPECPRIDE_INGEST_CACHE",
                                  DEFAULT_MAX_ENTRIES))
    except ValueError:
        return DEFAULT_MAX_ENTRIES


def _key(path: str) -> tuple | None:
    try:
        st = os.stat(path)
    except OSError:
        return None  # let the parser produce the real error
    return (os.path.abspath(path), st.st_size, st.st_mtime_ns)


def get(path: str) -> "tuple | None":
    """``(clusters, n_spectra, n_peaks)`` for an unchanged ``path``, or
    None (miss / disabled / unstattable)."""
    return lookup(path)[0]


def lookup(path: str) -> "tuple[tuple | None, str]":
    """``(entry, kind)``: kind is ``"stat"`` (unchanged path), or
    ``"content"`` (stat identity changed but the bytes matched a
    resident entry — re-keyed so the next lookup is a stat hit), or
    ``"miss"``."""
    if _max_entries() <= 0:
        return None, "miss"
    key = _key(path)
    if key is None:
        return None, "miss"
    with _lock:
        entry = _cache.get(key)
        if entry is not None:
            _counts["hits"] += 1
            _cache.move_to_end(key)
            return entry, "stat"
        populated = bool(_by_content)
    if not populated:
        # the miss is counted at put() time: a lookup whose parse
        # then FAILS never populates, and the exported miss total
        # must match its help text ("parses that populated") and
        # the per-job run_end counter
        return None, "miss"
    # stat-key miss with resident entries: one sequential read of the
    # candidate file (far cheaper than its parse) decides whether it is
    # the SAME BYTES under a new identity — a copy, a touch, a re-upload
    from specpride_tpu.cache.digest import file_digest

    digest = file_digest(path)
    if digest is None:
        return None, "miss"
    with _lock:
        old_key = _by_content.get(digest)
        entry = _cache.get(old_key) if old_key is not None else None
        if entry is None:
            return None, "miss"
        del _cache[old_key]
        _content_of.pop(old_key, None)
        _cache[key] = entry
        _by_content[digest] = key
        _content_of[key] = digest
        _counts["hits"] += 1
        _counts["content_hits"] += 1
        return entry, "content"


def put(path: str, clusters: list, n_spectra: int, n_peaks: int) -> None:
    """Cache one eagerly parsed input (no-op when disabled or the file
    cannot be stat'd — it may have been replaced mid-parse, in which
    case caching under the NEW stat would poison a future hit)."""
    limit = _max_entries()
    if limit <= 0:
        return
    key = _key(path)
    if key is None:
        return
    from specpride_tpu.cache.digest import file_digest

    digest = file_digest(path)  # outside the lock: one sequential read
    with _lock:
        _counts["misses"] += 1
        _cache[key] = (clusters, int(n_spectra), int(n_peaks))
        _cache.move_to_end(key)
        if digest is not None:
            _by_content[digest] = key
            _content_of[key] = digest
        while len(_cache) > limit:
            old, _ = _cache.popitem(last=False)
            d = _content_of.pop(old, None)
            if d is not None and _by_content.get(d) == old:
                del _by_content[d]


def info() -> dict:
    """{"hits", "misses", "size"} — exporter mirror + tests."""
    with _lock:
        return dict(_counts, size=len(_cache))


def clear() -> None:
    with _lock:
        _cache.clear()
        _by_content.clear()
        _content_of.clear()
        _counts.update(hits=0, misses=0, content_hits=0)
