"""Execution backends.

``numpy_backend`` is the behavioural oracle: an exact (and documented-where-
divergent) reimplementation of the reference algorithms on the host.  It is
the ground truth for parity tests and the ``--backend=numpy`` CLI path.

``tpu_backend`` is the production path: bucketed cluster batches executed by
the JAX/XLA (and Pallas) kernels in ``specpride_tpu.ops``, vmapped over the
cluster axis and shardable over a device mesh.
"""
