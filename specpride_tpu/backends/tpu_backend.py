"""TPU execution backend: drives the device kernels over bucketed batches.

Mirrors the numpy-oracle driver API (``backends.numpy_backend.run_*``) with
the same semantics, but executes each padded ``ClusterBatch`` as one jitted
XLA program on the default JAX backend (TPU on real hardware; CPU — incl. a
forced multi-device CPU mesh — in tests).  Host responsibilities: float64
m/z quantization (``ops.quantize``), precursor/RT estimators, unpadding, and
reassembly into the caller's original cluster order.

Memory is bounded by chunking each batch along the cluster axis so that the
largest on-device intermediate (the (B, n_bins) consensus grids or the
(B, M, grid) occupancy tensors) stays under ``max_grid_elements``; the final
chunk is zero-padded to the chunk shape so every chunk of a batch reuses one
compiled program.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from specpride_tpu.config import (
    BatchConfig,
    BestSpectrumConfig,
    BinMeanConfig,
    CosineConfig,
    GapAverageConfig,
    MedoidConfig,
)
from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.data.ragged import ClusterBatch, bucketize_clusters
from specpride_tpu.ops import quantize
from specpride_tpu.backends import numpy_backend


def _chunk_ranges(b: int, chunk: int):
    for start in range(0, b, chunk):
        yield start, min(start + chunk, b)


def _check_no_empty(clusters: list[Cluster]) -> None:
    """Zero-member clusters are rejected up front on every device driver so
    bucket-skipping can never silently misalign outputs against inputs (the
    numpy oracle raises for gap-average and medoid; for bin-mean it returns a
    degenerate NaN-precursor spectrum — we raise there too, documented
    divergence)."""
    for c in clusters:
        if c.n_members == 0:
            raise ValueError(f"empty cluster {c.cluster_id!r}")


def _pad_axis0(arr: np.ndarray, size: int) -> np.ndarray:
    if arr.shape[0] == size:
        return arr
    pad = [(0, size - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


@dataclasses.dataclass
class TpuBackend:
    """Device-execution backend (``--backend=tpu``).

    ``batch_config`` controls bucketing; ``max_grid_elements`` bounds the
    largest device intermediate per dispatch (default ~64M f32 = 256 MB).
    """

    batch_config: BatchConfig = dataclasses.field(default_factory=BatchConfig)
    max_grid_elements: int = 64 * 1024 * 1024

    # -- binned-mean consensus (K1) -------------------------------------

    def run_bin_mean(
        self, clusters: list[Cluster], config: BinMeanConfig = BinMeanConfig()
    ) -> list[Spectrum]:
        """Batched equivalent of ref src/binning.py:291-297."""
        from specpride_tpu.ops.binning import bin_mean_batch

        _check_no_empty(clusters)
        for c in clusters:
            numpy_backend.check_uniform_charge(c.members)

        out: list[Spectrum | None] = [None] * len(clusters)
        for batch in bucketize_clusters(clusters, self.batch_config):
            bins = quantize.bin_mean_bins(batch, config)
            b, m, p = batch.shape
            out_size = min(m * p, config.n_bins)
            # largest per-cluster intermediate: the (n_bins,) grids or the
            # flattened (m*p,) sort/mask arrays, whichever is bigger
            chunk = max(
                1, self.max_grid_elements // max(config.n_bins, m * p, 1)
            )
            for lo, hi in _chunk_ranges(b, chunk):
                size = min(chunk, b)
                mzs, intens, n_out, prec = bin_mean_batch(
                    _pad_axis0(batch.mz[lo:hi], size),
                    _pad_axis0(batch.intensity[lo:hi], size),
                    _pad_axis0(bins[lo:hi], size),
                    _pad_axis0(batch.member_mask[lo:hi], size),
                    _pad_axis0(batch.n_members[lo:hi], size),
                    _pad_axis0(batch.precursor_mz[lo:hi], size),
                    config,
                    out_size,
                )
                mzs = np.asarray(mzs)
                intens = np.asarray(intens)
                n_out = np.asarray(n_out)
                prec = np.asarray(prec)
                for ci in range(hi - lo):
                    k = int(n_out[ci])
                    gi = batch.source_indices[lo + ci]
                    charge = int(
                        batch.precursor_charge[lo + ci][
                            batch.member_mask[lo + ci]
                        ][0]
                    )
                    out[gi] = Spectrum(
                        mz=mzs[ci, :k].astype(np.float64),
                        intensity=intens[ci, :k].astype(np.float64),
                        precursor_mz=float(prec[ci]),
                        precursor_charge=charge,
                        title=batch.cluster_ids[lo + ci],
                    )
        return [s for s in out if s is not None]

    # -- gap-average consensus (K3) -------------------------------------

    def run_gap_average(
        self,
        clusters: list[Cluster],
        config: GapAverageConfig = GapAverageConfig(),
    ) -> list[Spectrum]:
        """Batched equivalent of ref src/average_spectrum_clustering.py:158-164;
        precursor/RT estimators run host-side (tiny, O(members))."""
        from specpride_tpu.ops.gap_average import gap_average_batch

        _check_no_empty(clusters)
        get_pepmass, get_rt = numpy_backend.resolve_gap_estimators(config)

        out: list[Spectrum | None] = [None] * len(clusters)
        for batch in bucketize_clusters(clusters, self.batch_config):
            b, m, p = batch.shape
            chunk = max(1, self.max_grid_elements // max(m * p * 4, 1))
            for lo, hi in _chunk_ranges(b, chunk):
                size = min(chunk, b)
                mzs, intens, n_out = gap_average_batch(
                    _pad_axis0(batch.mz[lo:hi], size),
                    _pad_axis0(batch.intensity[lo:hi], size),
                    _pad_axis0(batch.peak_mask[lo:hi], size),
                    _pad_axis0(batch.member_mask[lo:hi], size),
                    _pad_axis0(batch.n_members[lo:hi], size),
                    config,
                )
                mzs = np.asarray(mzs)
                intens = np.asarray(intens)
                n_out = np.asarray(n_out)
                for ci in range(hi - lo):
                    k = int(n_out[ci])
                    gi = batch.source_indices[lo + ci]
                    members = clusters[gi].members
                    pep_mz, pep_z = get_pepmass(members)
                    out[gi] = Spectrum(
                        mz=mzs[ci, :k].astype(np.float64),
                        intensity=intens[ci, :k].astype(np.float64),
                        precursor_mz=pep_mz,
                        precursor_charge=pep_z,
                        rt=get_rt(members),
                        title=batch.cluster_ids[lo + ci],
                    )
        return [s for s in out if s is not None]

    # -- medoid representative (K2) -------------------------------------

    def medoid_indices(
        self, clusters: list[Cluster], config: MedoidConfig = MedoidConfig()
    ) -> list[int]:
        """Per-cluster medoid member index (ref
        src/most_similar_representative.py:87-110 semantics)."""
        from specpride_tpu.ops.similarity import medoid_finalize, shared_bins_batch

        _check_no_empty(clusters)
        out: list[int] = [0] * len(clusters)
        for batch in bucketize_clusters(clusters, self.batch_config):
            bins, grid = quantize.medoid_bins(batch, config)
            b, m, p = batch.shape
            chunk = max(1, self.max_grid_elements // max(m * grid, 1))
            for lo, hi in _chunk_ranges(b, chunk):
                size = min(chunk, b)
                shared = np.asarray(
                    shared_bins_batch(_pad_axis0(bins[lo:hi], size), grid)
                )[: hi - lo]
                idx = medoid_finalize(
                    shared,
                    batch.n_peaks[lo:hi],
                    batch.member_mask[lo:hi],
                    batch.n_members[lo:hi],
                )
                for ci in range(hi - lo):
                    out[batch.source_indices[lo + ci]] = int(idx[ci])
        return out

    def run_medoid(
        self, clusters: list[Cluster], config: MedoidConfig = MedoidConfig()
    ) -> list[Spectrum]:
        indices = self.medoid_indices(clusters, config)
        return [c.members[i] for c, i in zip(clusters, indices)]

    # -- best-spectrum representative (host-only; ref src/best_spectrum.py) --

    def run_best_spectrum(
        self,
        clusters: list[Cluster],
        scores: dict[str, float],
        config: BestSpectrumConfig = BestSpectrumConfig(),
    ) -> list[Spectrum]:
        """Pure join/argmax — negligible compute, host-side by design
        (survey §3.4)."""
        return numpy_backend.run_best_spectrum(clusters, scores, config)

    # -- quality metrics (K2 cosine) ------------------------------------

    def average_cosines(
        self,
        representatives: list[Spectrum],
        clusters: list[Cluster],
        config: CosineConfig = CosineConfig(),
    ) -> np.ndarray:
        """Mean binned cosine of each representative to its cluster's members
        (ref src/benchmark.py:31-38), one device pass per bucket shape."""
        from specpride_tpu.ops.similarity import cosine_rep_vs_members

        if len(representatives) != len(clusters):
            raise ValueError("representatives and clusters must align")
        _check_no_empty(clusters)
        out = np.zeros((len(clusters),), dtype=np.float64)
        for batch in bucketize_clusters(clusters, self.batch_config):
            idxs = batch.source_indices
            b, m, p = batch.shape
            pr_raw = max(
                max((representatives[i].n_peaks for i in idxs), default=1), 1
            )
            # bucket the rep-peak axis (multiple of 128) so the jitted pair
            # kernel compiles once per bucket shape, not once per batch
            pr = ((pr_raw + 127) // 128) * 128
            rep_mz = np.zeros((b, pr), np.float64)
            rep_int = np.zeros((b, pr), np.float32)
            rep_valid = np.zeros((b, pr), bool)
            for ci, gi in enumerate(idxs):
                r = representatives[gi]
                k = r.n_peaks
                rep_mz[ci, :k] = r.mz
                rep_int[ci, :k] = r.intensity
                rep_valid[ci, :k] = True
            rep_bins, rep_edges = quantize.cosine_bins(rep_mz, rep_valid, config)
            mem_valid = batch.peak_mask & batch.member_mask[:, :, None]
            mem_bins, mem_edges = quantize.cosine_bins(
                batch.mz64, mem_valid, config
            )
            mem_int = batch.intensity  # already float32

            # per-cluster pair workspace: ~m concatenated (pr+p) key/value
            # arrays plus sort scratch
            per_cluster = m * (pr + p) * 8
            chunk = max(1, self.max_grid_elements // max(per_cluster, 1))
            for lo, hi in _chunk_ranges(b, chunk):
                size = min(chunk, b)
                mean, _ = cosine_rep_vs_members(
                    _pad_axis0(rep_bins[lo:hi], size),
                    _pad_axis0(rep_int[lo:hi], size),
                    _pad_axis0(rep_edges[lo:hi], size),
                    _pad_axis0(mem_bins[lo:hi], size),
                    _pad_axis0(mem_int[lo:hi], size),
                    _pad_axis0(mem_edges[lo:hi], size),
                    _pad_axis0(batch.member_mask[lo:hi], size),
                    _pad_axis0(batch.n_members[lo:hi], size),
                )
                mean = np.asarray(mean)
                for ci in range(hi - lo):
                    out[idxs[lo + ci]] = float(mean[ci])
        return out
