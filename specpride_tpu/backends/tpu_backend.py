"""TPU execution backend: drives the device kernels over packed batches.

Mirrors the numpy-oracle driver API (``backends.numpy_backend.run_*``) with
the same semantics, but executes each packed batch (``data.packed``) as one
jitted XLA program on the default JAX backend (TPU on real hardware; CPU —
incl. a forced multi-device CPU mesh — in tests).  Host responsibilities:
float64 m/z quantization (``ops.quantize`` / pack-time dedup), precursor/RT
estimators and medoid finalize (tiny, f64-exact), unpadding, and reassembly
into the caller's original cluster order.

Dispatch discipline (host link is latency- and bandwidth-bound): all chunks
are dispatched asynchronously before any result is collected, each kernel
returns ONE fused array per dispatch, and output buffers are sized by exact
host-computed bounds so the device→host transfer carries only real bytes.
Memory is bounded by chunking each batch along the cluster axis under
``max_grid_elements``; phantom rows from chunk padding are masked out and
never read back.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from specpride_tpu.config import (
    BatchConfig,
    BestSpectrumConfig,
    BinMeanConfig,
    CosineConfig,
    GapAverageConfig,
    MedoidConfig,
)
from specpride_tpu.data.peaks import Cluster, Spectrum
from specpride_tpu.ops import quantize
from specpride_tpu.backends import numpy_backend


def _chunk_ranges(b: int, chunk: int):
    for start in range(0, b, chunk):
        yield start, min(start + chunk, b)


def _pow2(n: int, floor: int = 1) -> int:
    """Round up to a power of two (>= floor).  Every value that feeds a
    static jit argument or a padded array shape goes through this: distinct
    shapes cost one XLA compile each, so bounding them to powers of two
    keeps the compile count logarithmic instead of per-batch (the round-1
    bench spent 47 s compiling one-off shapes)."""
    return max(floor, 1 << (max(n, 1) - 1).bit_length())


def _check_no_empty(clusters: list[Cluster]) -> None:
    """Zero-member clusters are rejected up front on every device driver so
    bucket-skipping can never silently misalign outputs against inputs (the
    numpy oracle raises for gap-average and medoid; for bin-mean it returns a
    degenerate NaN-precursor spectrum — we raise there too, documented
    divergence)."""
    for c in clusters:
        if c.n_members == 0:
            raise ValueError(f"empty cluster {c.cluster_id!r}")


def _iter_compacted(fused, cap: int, n_rows: int):
    """Split a fused ``[flat_mz (cap) | flat_intensity (cap) | n_out (B)]``
    device buffer (the globally-compacted layout of
    ``ops.binning.bin_mean_deduped_compact`` /
    ``ops.gap_average.gap_average_compact``) into per-row f64 (mz, intensity)
    slices.  Rows are row-major in dispatch order; padded phantom rows emit
    ``n_out == 0`` and sit past ``n_rows``, so they are never yielded."""
    fused = np.asarray(fused)
    flat_mz = fused[:cap]
    flat_int = fused[cap : 2 * cap]
    n_out = fused[2 * cap :].astype(np.int64)
    offsets = np.concatenate([[0], np.cumsum(n_out)])
    for ci in range(n_rows):
        o0, o1 = int(offsets[ci]), int(offsets[ci + 1])
        yield ci, flat_mz[o0:o1].astype(np.float64), flat_int[o0:o1].astype(
            np.float64
        )


def _pad_axis0(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    if arr.shape[0] == size:
        return arr
    pad = [(0, size - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad, constant_values=fill)


@dataclasses.dataclass
class TpuBackend:
    """Device-execution backend (``--backend=tpu``).

    ``batch_config`` controls bucketing; ``max_grid_elements`` bounds the
    largest device intermediate per dispatch (default ~64M f32 = 256 MB).
    ``mesh`` (optional): a 1-D ``jax.sharding.Mesh`` (``parallel.cluster_mesh``)
    — every dispatch is then padded to a multiple of the mesh size and its
    inputs sharded along the cluster axis, so XLA SPMD-partitions the kernels
    across all devices with no hot-loop collectives.
    """

    batch_config: BatchConfig = dataclasses.field(default_factory=BatchConfig)
    max_grid_elements: int = 64 * 1024 * 1024
    mesh: object | None = None  # jax.sharding.Mesh

    def _dispatch_size(self, chunk: int, b: int) -> int:
        """Dispatch (padded) cluster count: the chunk size rounded up to a
        power of two (so odd-sized tail batches reuse compiled shapes), then
        to a multiple of the mesh size when sharding.

        The 64-row floor amortizes compile shapes, but it must never
        overshoot the memory-derived ``chunk``: with very wide rows (e.g.
        medoid k*m ~ 2^24) chunk can be 1-4, and a hard floor of 64 would
        exceed the ``max_grid_elements`` budget up to 64x (device OOM
        risk).  Clamping the floor to pow2(chunk) bounds padding at 2x the
        budget."""
        size = _pow2(min(chunk, b), floor=min(64, _pow2(chunk)))
        if self.mesh is not None:
            n = self.mesh.size
            size = ((size + n - 1) // n) * n
        return size

    def _ship(self, *arrays: np.ndarray):
        """Shard inputs over the mesh (if any) along the cluster axis."""
        if self.mesh is None:
            return arrays
        from specpride_tpu.parallel.mesh import shard_batch_arrays

        return shard_batch_arrays(self.mesh, *arrays)

    # -- binned-mean consensus (K1) -------------------------------------

    def run_bin_mean(
        self, clusters: list[Cluster], config: BinMeanConfig = BinMeanConfig()
    ) -> list[Spectrum]:
        """Batched equivalent of ref src/binning.py:291-297 on the packed
        ragged layout; dispatches all chunks asynchronously, then collects
        (overlapping H2D/compute/D2H)."""
        from specpride_tpu.data.packed import pack_bucketize_bin_mean
        from specpride_tpu.ops.binning import bin_mean_deduped_compact

        _check_no_empty(clusters)
        for c in clusters:
            numpy_backend.check_uniform_charge(c.members)

        out: list[Spectrum | None] = [None] * len(clusters)
        pending = []
        for batch in pack_bucketize_bin_mean(
            clusters,
            config.min_mz,
            config.max_mz,
            config.bin_size,
            config.n_bins,
            self.batch_config,
        ):
            b, k = batch.mz.shape
            chunk = max(1, self.max_grid_elements // max(k * 4, 1))
            size = self._dispatch_size(chunk, b)
            for lo, hi in _chunk_ranges(b, chunk):
                # exact total surviving-bin bound for this chunk -> the
                # compacted D2H buffer carries only real output bytes
                dist = quantize.distinct_bins_per_row(
                    batch.bins[lo:hi], config.n_bins
                )
                # pow2: cap is a static jit arg — see _pow2
                cap = _pow2(int(dist.sum()), floor=1024)
                fused = bin_mean_deduped_compact(
                    *self._ship(
                        _pad_axis0(batch.mz[lo:hi], size),
                        _pad_axis0(batch.intensity[lo:hi], size),
                        # pad phantom rows with the sentinel so they emit
                        # no output bins
                        _pad_axis0(batch.bins[lo:hi], size, fill=config.n_bins),
                        _pad_axis0(batch.n_members[lo:hi], size),
                    ),
                    config=config,
                    total_cap=cap,
                )
                pending.append((batch, lo, hi, cap, fused))

        for batch, lo, hi, cap, fused in pending:
            for ci, r_mz, r_int in _iter_compacted(fused, cap, hi - lo):
                gi = batch.source_indices[lo + ci]
                members = clusters[gi].members
                out[gi] = Spectrum(
                    mz=r_mz,
                    intensity=r_int,
                    # exact f64 mean, as the oracle (ref src/binning.py:224)
                    precursor_mz=float(
                        np.mean([s.precursor_mz for s in members])
                    ),
                    precursor_charge=members[0].precursor_charge,
                    title=batch.cluster_ids[lo + ci],
                )
        return [s for s in out if s is not None]

    # -- gap-average consensus (K3) -------------------------------------

    def run_gap_average(
        self,
        clusters: list[Cluster],
        config: GapAverageConfig = GapAverageConfig(),
    ) -> list[Spectrum]:
        """Batched equivalent of ref src/average_spectrum_clustering.py:158-164
        on the packed layout.  Grouping (sort + f64 gap detection) happens at
        pack time on the host (``data.packed.pack_bucketize_gap`` — the same
        f64-parity split K1 uses, see ``ops.gap_average``); the device runs
        segment reductions + global compaction sized by the host's exact
        group-count bound, so there is no overflow/redispatch.  Precursor/RT
        estimators run host-side (tiny, O(members)) while the device works."""
        from specpride_tpu.data.packed import pack_bucketize_gap
        from specpride_tpu.ops.gap_average import gap_average_compact

        _check_no_empty(clusters)
        get_pepmass, get_rt = numpy_backend.resolve_gap_estimators(config)

        out: list[Spectrum | None] = [None] * len(clusters)
        pending = []
        for batch in pack_bucketize_gap(clusters, config, self.batch_config):
            b, k = batch.mz.shape
            chunk = max(1, self.max_grid_elements // max(k * 4, 1))
            size = self._dispatch_size(chunk, b)
            for lo, hi in _chunk_ranges(b, chunk):
                # exact total group-count bound for this chunk -> the
                # compacted D2H buffer carries only real output bytes
                # pow2: cap is a static jit arg — see _pow2
                cap = _pow2(int(batch.n_groups[lo:hi].sum()), floor=1024)
                fused = gap_average_compact(
                    *self._ship(
                        _pad_axis0(batch.mz[lo:hi], size),
                        _pad_axis0(batch.intensity[lo:hi], size),
                        _pad_axis0(batch.seg[lo:hi], size),
                        _pad_axis0(batch.n_valid[lo:hi], size),
                        _pad_axis0(batch.quorum[lo:hi], size),
                        _pad_axis0(batch.n_members[lo:hi], size),
                    ),
                    config=config,
                    total_cap=cap,
                )
                pending.append((batch, lo, hi, cap, fused))

        for batch, lo, hi, cap, fused in pending:
            for ci, r_mz, r_int in _iter_compacted(fused, cap, hi - lo):
                gi = batch.source_indices[lo + ci]
                members = clusters[gi].members
                pep_mz, pep_z = get_pepmass(members)
                out[gi] = Spectrum(
                    mz=r_mz,
                    intensity=r_int,
                    precursor_mz=pep_mz,
                    precursor_charge=pep_z,
                    rt=get_rt(members),
                    title=batch.cluster_ids[lo + ci],
                )
        return [s for s in out if s is not None]

    # -- medoid representative (K2) -------------------------------------

    def medoid_indices(
        self, clusters: list[Cluster], config: MedoidConfig = MedoidConfig()
    ) -> list[int]:
        """Per-cluster medoid member index (ref
        src/most_similar_representative.py:87-110 semantics): packed
        occupancy scatter + batched gram matmul on device, exact float64
        finalize on host."""
        from specpride_tpu.data.packed import pack_bucketize
        from specpride_tpu.ops.similarity import medoid_finalize, shared_bins_packed

        _check_no_empty(clusters)
        out: list[int] = [0] * len(clusters)
        pending = []
        for batch in pack_bucketize(
            clusters, self.batch_config, bucket_members=True
        ):
            # shared-bin counts travel as uint16 (D2H is the bottleneck)
            if int(batch.n_peaks.max(initial=0)) >= 1 << 16:
                raise ValueError(
                    "medoid kernel: a member has >= 2**16 peaks; uint16 "
                    "shared-bin counts would overflow"
                )
            bins = quantize.medoid_bins_packed(batch, config)
            b, k = batch.mz.shape
            m = batch.m
            # largest device intermediate is the (K*M,) run×member occupancy
            chunk = max(1, self.max_grid_elements // max(k * m, 1))
            size = self._dispatch_size(chunk, b)
            for lo, hi in _chunk_ranges(b, chunk):
                res = shared_bins_packed(
                    *self._ship(
                        _pad_axis0(bins[lo:hi], size, fill=2**30),
                        _pad_axis0(batch.member_id[lo:hi], size, fill=-1),
                    ),
                    m=m,
                )
                pending.append((batch, lo, hi, res))

        for batch, lo, hi, res in pending:
            # slice on device first: D2H carries only real rows (12 MB/s on
            # tunneled hosts), then widen uint16 counts for the f64 finalize
            shared = np.asarray(res[: hi - lo]).astype(np.int64)
            idx = medoid_finalize(
                shared,
                batch.n_peaks[lo:hi],
                batch.member_mask[lo:hi],
                batch.n_members[lo:hi],
            )
            for ci in range(hi - lo):
                out[batch.source_indices[lo + ci]] = int(idx[ci])
        return out

    def run_medoid(
        self, clusters: list[Cluster], config: MedoidConfig = MedoidConfig()
    ) -> list[Spectrum]:
        indices = self.medoid_indices(clusters, config)
        return [c.members[i] for c, i in zip(clusters, indices)]

    # -- best-spectrum representative (host-only; ref src/best_spectrum.py) --

    def run_best_spectrum(
        self,
        clusters: list[Cluster],
        scores: dict[str, float],
        config: BestSpectrumConfig = BestSpectrumConfig(),
    ) -> list[Spectrum]:
        """Pure join/argmax — negligible compute, host-side by design
        (survey §3.4)."""
        return numpy_backend.run_best_spectrum(clusters, scores, config)

    # -- quality metrics (K2 cosine) ------------------------------------

    def average_cosines(
        self,
        representatives: list[Spectrum],
        clusters: list[Cluster],
        config: CosineConfig = CosineConfig(),
    ) -> np.ndarray:
        """Mean binned cosine of each representative to its cluster's members
        (ref src/benchmark.py:31-38) on the packed layout: device receives
        packed peaks + f64-quantized grid bins, returns only the per-member
        cosines (``ops.similarity.cosine_packed``)."""
        from specpride_tpu.data.packed import pack_bucketize
        from specpride_tpu.ops.similarity import cosine_packed

        if len(representatives) != len(clusters):
            raise ValueError("representatives and clusters must align")
        _check_no_empty(clusters)
        space = config.mz_space
        out = np.zeros((len(clusters),), dtype=np.float64)
        pending = []
        for batch in pack_bucketize(clusters, self.batch_config):
            idxs = batch.source_indices
            b, k = batch.mz.shape
            m = batch.m
            pr_raw = max(
                max((representatives[i].n_peaks for i in idxs), default=1), 1
            )
            pr = _pow2(pr_raw, floor=256)  # shape-stable (one compile per value)
            rep_mz = np.zeros((b, pr), np.float64)
            rep_int = np.zeros((b, pr), np.float32)
            rep_valid = np.zeros((b, pr), bool)
            mem_edges = np.zeros((b, m), np.int32)
            for ci, gi in enumerate(idxs):
                r = representatives[gi]
                rep_mz[ci, : r.n_peaks] = r.mz
                rep_int[ci, : r.n_peaks] = r.intensity
                rep_valid[ci, : r.n_peaks] = True
                for mi, mem in enumerate(clusters[gi].members):
                    if mem.n_peaks:
                        # per-member edge count off the LAST peak
                        # (ref src/benchmark.py:20, assumes sorted)
                        mem_edges[ci, mi] = quantize.cosine_edge_count(
                            mem.mz[-1], space
                        )
            rep_bins, rep_edges = quantize.cosine_bins(rep_mz, rep_valid, config)
            mem_bins, _ = quantize.cosine_bins(
                batch.mz64, batch.member_id >= 0, config
            )

            chunk = max(1, self.max_grid_elements // max((k + pr) * 6, 1))
            size = self._dispatch_size(chunk, b)
            for lo, hi in _chunk_ranges(b, chunk):
                mean, _ = cosine_packed(
                    *self._ship(
                        _pad_axis0(rep_bins[lo:hi], size, fill=2**30),
                        _pad_axis0(rep_int[lo:hi], size),
                        _pad_axis0(rep_edges[lo:hi], size),
                        _pad_axis0(mem_bins[lo:hi], size, fill=2**30),
                        _pad_axis0(batch.intensity[lo:hi], size),
                        _pad_axis0(batch.member_id[lo:hi], size, fill=-1),
                        _pad_axis0(mem_edges[lo:hi], size),
                        _pad_axis0(batch.member_mask[lo:hi], size),
                        _pad_axis0(batch.n_members[lo:hi], size),
                    ),
                    m=m,
                )
                pending.append((idxs, lo, hi, mean))

        for idxs, lo, hi, mean in pending:
            mean = np.asarray(mean)
            for ci in range(hi - lo):
                out[idxs[lo + ci]] = float(mean[ci])
        return out
